"""Disaggregated prefill/decode deployment, end to end (reference analogue:
examples/llm graphs/disagg.py — decode worker + prefill worker + shared
queue + conditional disagg router + OpenAI frontend).

    python examples/llm/disagg.py
    curl localhost:8080/v1/chat/completions -H 'Content-Type: application/json' \
      -d '{"model":"tiny-test","messages":[{"role":"user","content":"hi"}]}'

Long prompts route to the prefill engine through the durable queue; KV
blocks move over the same-process device channel (HBM→HBM on real chips).
Short prompts stay local to the decode engine.
"""

import asyncio
import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)

from dynamo_tpu.disagg import (  # noqa: E402
    DecodeOperator,
    DisaggConfig,
    DisaggRouter,
    PrefillQueue,
    PrefillWorker,
)
from dynamo_tpu.engine.config import EngineConfig  # noqa: E402
from dynamo_tpu.engine.engine import TpuEngine  # noqa: E402
from dynamo_tpu.llm.discovery import (  # noqa: E402
    ModelManager,
    ModelWatcher,
    register_llm,
)
from dynamo_tpu.llm.http_service import HttpService  # noqa: E402
from dynamo_tpu.llm.local_model import LocalModel  # noqa: E402
from dynamo_tpu.runtime.distributed import DistributedRuntime  # noqa: E402

MODEL = os.environ.get("MODEL", "preset:tiny-test")
PORT = int(os.environ.get("PORT", "8080"))


async def main() -> None:
    drt = await DistributedRuntime.in_process()
    local = LocalModel.prepare(MODEL, context_length=256)
    params = local.load_params()

    def ecfg() -> EngineConfig:
        return EngineConfig(
            model=local.config, num_blocks=128, max_num_seqs=8,
            max_model_len=256,
        )

    decode = TpuEngine(ecfg(), params=params)
    await decode.start()
    prefill = TpuEngine(ecfg(), params=params)
    await prefill.start()

    router = await DisaggRouter(drt, "demo").start()
    await router.publish_config(
        DisaggConfig(max_local_prefill_length=32, max_prefill_queue_size=16)
    )
    queue = PrefillQueue(drt, "demo")
    operator = await DecodeOperator(decode, queue, router).start()
    worker = PrefillWorker(prefill, queue).start()

    ep = drt.namespace("demo").component("tpu").endpoint("generate")
    await ep.serve(operator)
    await register_llm(drt, ep, local.card)

    manager = ModelManager()
    await ModelWatcher(drt, manager).start()
    service = HttpService(manager, host="127.0.0.1", port=PORT)
    await service.start()
    print(
        f"disagg serving {local.name!r} on http://127.0.0.1:{service.port} "
        f"(prompts >32 tokens prefill remotely; transport={operator.transport})",
        flush=True,
    )
    try:
        await asyncio.Event().wait()
    finally:
        await worker.stop()
        await operator.stop()
        await service.stop()
        await prefill.stop()
        await decode.stop()
        await drt.shutdown()


if __name__ == "__main__":
    asyncio.run(main())

#!/usr/bin/env bash
# Multi-process deployment: an HTTP frontend hosting the control plane and
# a separate worker process joining it (reference analogue:
# `dynamo run in=http out=dyn` + a worker `in=dyn://... out=...`).
set -euo pipefail
cd "$(dirname "$0")/../.."

PORT="${PORT:-8080}"
CP_PORT="${CP_PORT:-6380}"
MODEL="${MODEL:-preset:tiny-test}"

python -m dynamo_tpu run --in http --out dyn \
  --spawn-control-plane "$CP_PORT" --http-port "$PORT" &
FRONT=$!
python -m dynamo_tpu run --in dyn://dynamo.tpu.generate --out tpu \
  --model-path "$MODEL" --control-plane "127.0.0.1:$CP_PORT" \
  --max-model-len 256 --num-blocks 128 --max-num-seqs 8 &
WORKER=$!
trap 'kill $FRONT $WORKER 2>/dev/null || true' EXIT

for _ in $(seq 90); do
  MODELS=$(curl -sf "http://127.0.0.1:$PORT/v1/models" 2>/dev/null || true)
  [[ "$MODELS" == *'"id"'* ]] && break
  sleep 1
done
echo "models: $MODELS"

curl -s "http://127.0.0.1:$PORT/v1/chat/completions" \
  -H 'Content-Type: application/json' \
  -d '{"model": "tiny-test",
       "messages": [{"role": "user", "content": "hello"}],
       "max_tokens": 16, "stream": false}'
echo

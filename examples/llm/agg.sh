#!/usr/bin/env bash
# Aggregated serving: one command, one engine, OpenAI API
# (reference analogue: `dynamo run in=http out=mistralrs <model>`).
set -euo pipefail
cd "$(dirname "$0")/../.."

MODEL="${MODEL:-preset:tiny-test}"   # or a HF dir / hf://org/name / *.gguf
PORT="${PORT:-8080}"

python -m dynamo_tpu run --in http --out tpu \
  --model-path "$MODEL" --http-port "$PORT" \
  --max-model-len 256 --num-blocks 128 --max-num-seqs 8 &
SERVER=$!
trap 'kill $SERVER 2>/dev/null || true' EXIT

for _ in $(seq 60); do
  curl -sf "http://127.0.0.1:$PORT/health" >/dev/null 2>&1 && break
  sleep 1
done

curl -s "http://127.0.0.1:$PORT/v1/chat/completions" \
  -H 'Content-Type: application/json' \
  -d '{"model": "'"$(basename "${MODEL#preset:}")"'",
       "messages": [{"role": "user", "content": "hello"}],
       "max_tokens": 16, "stream": false}'
echo

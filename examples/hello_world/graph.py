"""SDK hello world: a 3-stage service graph (reference analogue:
examples/hello_world — Frontend → Middle → Backend over the runtime).

    python examples/hello_world/graph.py
"""

import asyncio
import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)

from dynamo_tpu.runtime.distributed import DistributedRuntime  # noqa: E402
from dynamo_tpu.sdk import depends, endpoint, serve_graph, service  # noqa: E402


@service(namespace="hello")
class Backend:
    @endpoint
    async def generate(self, request):
        for word in request["text"].split():
            yield {"word": word.upper()}


@service(namespace="hello")
class Middle:
    backend = depends(Backend)

    @endpoint
    async def generate(self, request):
        async for item in self.backend.generate(request):
            yield {"word": f"*{item['word']}*"}


@service(namespace="hello")
class Frontend:
    middle = depends(Middle)

    @endpoint
    async def generate(self, request):
        async for item in self.middle.generate(request):
            yield item


async def main() -> None:
    drt = await DistributedRuntime.in_process()
    graph = await serve_graph(Frontend, drt)
    handle = graph.instance(Frontend)
    async for item in handle.middle.generate({"text": "hello tpu world"}):
        print(item["word"])
    await graph.stop()
    await drt.shutdown()


if __name__ == "__main__":
    asyncio.run(main())

"""Multimodal deployment: encode worker + TPU decode worker + OpenAI HTTP.

The reference's multimodal example shape (reference: examples/multimodal
README.md:18-30 — an encode_worker runs the vision encoder ahead of the
decode worker; the processor routes image content through it). Here both
workers join one in-process runtime; images ride OpenAI `image_url`
content parts as data: URLs, the vision encoder turns them into
soft-prompt embeddings, and the engine splices them into prefill in
place of placeholder tokens.

Run (CPU works):
  JAX_PLATFORMS=cpu python examples/multimodal/serve.py

Then query:
  python examples/multimodal/client.py http://127.0.0.1:8080
"""

import asyncio
import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)

from dynamo_tpu.engine.config import EngineConfig  # noqa: E402
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher, register_llm
from dynamo_tpu.llm.http_service import HttpService
from dynamo_tpu.llm.model_card import ModelDeploymentCard
from dynamo_tpu.llm.multimodal import VisionEncodeEngine
from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.models.vision import VisionConfig
from dynamo_tpu.runtime.distributed import DistributedRuntime


async def main() -> None:
    mcfg = ModelConfig.tiny_test()
    vcfg = VisionConfig.tiny_test(out_dim=mcfg.hidden_size)

    # Both model builds happen BEFORE the runtime exists: device dispatch /
    # XLA compile on the event loop would starve the lease keepalive past
    # its TTL and deregister everything (10s TTL; a tunneled-TPU init takes
    # longer than that).
    engine = TpuEngine(
        EngineConfig(
            model=mcfg, num_blocks=256, max_num_seqs=4, max_model_len=512,
            multimodal=True,
        )
    )
    await engine.start()
    encoder = await asyncio.to_thread(VisionEncodeEngine, vcfg)

    drt = await DistributedRuntime.in_process()
    # Encode worker (scales independently of decode workers in a real
    # deployment — here same process for a one-file example).
    await drt.namespace("mm").component("encoder").endpoint("encode").serve(
        encoder
    )
    gen_ep = drt.namespace("mm").component("tpu").endpoint("generate")
    await gen_ep.serve(engine)
    await register_llm(
        drt,
        gen_ep,
        ModelDeploymentCard(
            name="tiny-mm",
            model_path="toy",
            extra={
                "encode_endpoint": "mm.encoder.encode",
                "placeholder_token": 1,
            },
        ),
        model_type="multimodal",
    )

    manager = ModelManager()
    await ModelWatcher(drt, manager).start()
    while not manager.models():
        await asyncio.sleep(0.05)
    service = HttpService(manager, host="127.0.0.1", port=8080)
    await service.start()
    print(f"multimodal OpenAI server on http://127.0.0.1:{service.port}")
    try:
        await asyncio.Event().wait()
    finally:
        await service.stop()
        await engine.stop()
        await drt.shutdown()


if __name__ == "__main__":
    asyncio.run(main())

"""Send an image chat request to the multimodal example server."""

import base64
import io
import json
import sys
import urllib.request

import numpy as np


def main() -> None:
    base = sys.argv[1] if len(sys.argv) > 1 else "http://127.0.0.1:8080"
    # A random "image" as a data: URL carrying a .npy array — the
    # zero-egress-friendly source the server accepts (PIL formats work too
    # when PIL is installed).
    rng = np.random.default_rng(0)
    buf = io.BytesIO()
    np.save(buf, rng.random((32, 32, 3), np.float32))
    url = "data:application/x-npy;base64," + base64.b64encode(
        buf.getvalue()
    ).decode()

    body = {
        "model": "tiny-mm",
        "messages": [
            {
                "role": "user",
                "content": [
                    {"type": "text", "text": "What is in this image? "},
                    {"type": "image_url", "image_url": {"url": url}},
                ],
            }
        ],
        "stream": False,
        "max_tokens": 16,
    }
    req = urllib.request.Request(
        f"{base}/v1/chat/completions",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req) as resp:
        print(json.dumps(json.load(resp), indent=2))


if __name__ == "__main__":
    main()

"""Driver benchmark: offline continuous-batching decode throughput.

Runs the full TpuEngine (scheduler → paged KV cache → jitted steps) on a
Llama-3.2-1B-shaped model with random weights: 32 requests, ISL 128 /
OSL 64, greedy. Reports generated tokens/sec/chip.

``vs_baseline`` is measured against the only absolute rate the reference
checks in — its echo test engine at 100 tok/s (reference:
lib/llm/src/engines.rs:66-78; see BASELINE.md, which notes all other
published numbers are relative). The north-star comparisons (8B/70B disagg
vs vLLM-on-H100) need real checkpoints + multi-chip hardware not present
in this harness.
"""

from __future__ import annotations

import asyncio
import json
import os
import time

import numpy as np

SMOKE = bool(os.environ.get("BENCH_SMOKE"))  # tiny config for CI smoke runs


async def _main() -> dict:
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.runtime.engine import Context

    NUM_REQ, ISL, OSL = (4, 32, 8) if SMOKE else (32, 128, 64)
    cfg = EngineConfig(
        model=ModelConfig.tiny_test() if SMOKE else ModelConfig.llama32_1b(),
        num_blocks=256 if SMOKE else 1024,
        block_size=16,
        max_num_seqs=8,
        max_model_len=256 if SMOKE else 512,
        enable_prefix_caching=True,
    )
    engine = TpuEngine(cfg)
    await engine.start()

    rng = np.random.default_rng(0)
    reqs = [
        PreprocessedRequest(
            token_ids=rng.integers(0, cfg.model.vocab_size, ISL).tolist(),
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=OSL, ignore_eos=True),
        )
        for _ in range(NUM_REQ)
    ]

    async def run_one(req):
        n = 0
        first = None
        async for out in engine.generate(Context(req.to_wire())):
            if out["token_ids"] and first is None:
                first = time.monotonic()
            n += len(out["token_ids"])
        return n, first

    # Warmup: compile single + batched prefill and every power-of-two decode
    # chunk off the clock (max_tokens = 2*chunk-1 walks the ladder 8→4→2→1).
    def _warm_req(max_tokens):
        return PreprocessedRequest(
            token_ids=rng.integers(0, cfg.model.vocab_size, ISL).tolist(),
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=max_tokens, ignore_eos=True),
        )

    await run_one(_warm_req(2 * cfg.decode_chunk - 1))
    await asyncio.gather(*[run_one(_warm_req(2)) for _ in range(5)])

    t0 = time.monotonic()
    results = await asyncio.gather(*[run_one(r) for r in reqs])
    elapsed = time.monotonic() - t0
    await engine.stop()

    total_tokens = sum(n for n, _ in results)
    ttfts = [f - t0 for _, f in results if f is not None]
    return {
        "metric": "decode_throughput_tiny_smoke"
        if SMOKE
        else "decode_throughput_1b_isl128_osl64",
        "value": round(total_tokens / elapsed, 2),
        "unit": "tok/s/chip",
        "vs_baseline": round(total_tokens / elapsed / 100.0, 3),
        "extras": {
            "total_tokens": total_tokens,
            "elapsed_s": round(elapsed, 2),
            "p50_ttft_ms": round(1000 * float(np.median(ttfts)), 1),
            "max_ttft_ms": round(1000 * float(np.max(ttfts)), 1),
            "num_requests": NUM_REQ,
            "isl": ISL,
            "osl": OSL,
        },
    }


if __name__ == "__main__":
    print(json.dumps(asyncio.run(_main())))

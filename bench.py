"""Driver benchmark: offline continuous-batching decode throughput.

Runs the full TpuEngine (scheduler → paged KV cache → jitted steps) on a
Llama-3.2-1B-shaped model with random weights: 32 requests, ISL 128 /
OSL 64, greedy. Reports generated tokens/sec/chip plus a steady-state
decode microbench (per-step ms and effective HBM bandwidth).

``vs_baseline`` is measured against the only absolute rate the reference
checks in — its echo test engine at 100 tok/s (reference:
lib/llm/src/engines.rs:66-78; see BASELINE.md, which notes all other
published numbers are relative). The north-star comparisons (8B/70B disagg
vs vLLM-on-H100) need real checkpoints + multi-chip hardware not present
in this harness.

Modes:
- default: the engine's default attention path (Pallas kernels on TPU —
  the r03 A/B winner; see BENCHMARKS.md).
- BENCH_AB=1: run the E2E scenario twice (DYNAMO_TPU_PALLAS on/off child
  processes) and report both, so the attention-path choice stays an
  evidence-backed default rather than a belief.
- BENCH_SMOKE=1: tiny config for CI smoke runs.
"""

from __future__ import annotations

import asyncio
import json
import os
import subprocess
import sys
import time

import numpy as np

# Persistent XLA compile cache: multi-engine scenarios (router/offload/
# disagg) and A/B child processes re-instantiate runners with identical
# shapes — without this every instance pays 10-40 s/shape through the
# tunneled chip. The env shim covers the raw-runner bench legs (kvsp/8b);
# the e2e engine path goes through EngineConfig.compile_cache_dir, which
# adds the fingerprint namespace + warmed-shape ledger
# (engine/compile_cache.py). Opt out with DYNAMO_TPU_COMPILE_CACHE=0.
_CACHE_BASE = None
if os.environ.get("DYNAMO_TPU_COMPILE_CACHE", "1") != "0":
    _CACHE_BASE = (
        os.environ.get("DYNAMO_TPU_COMPILE_CACHE_DIR")
        or "/tmp/dynamo_tpu_jax_cache"
    )
    if _CACHE_BASE.lower() in ("none", "0", "off"):
        _CACHE_BASE = None
    else:
        os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", _CACHE_BASE)
if _CACHE_BASE is None:
    # Opting out must actually measure cold compiles: the runner falls
    # back to $DYNAMO_TPU_COMPILE_CACHE_DIR when the config is None (the
    # shipped container exports it), so override it with the disable
    # sentinel for this process and its A/B children.
    os.environ["DYNAMO_TPU_COMPILE_CACHE_DIR"] = "none"

SMOKE = bool(os.environ.get("BENCH_SMOKE"))  # tiny config for CI smoke runs
# BENCH_MOCKER=1: run the E2E scenario on the device-free MockerEngine
# (real scheduler/KV/streaming stack, simulated runner) — the CI smoke
# mode ci.sh uses: exercises the full serving path in seconds with no
# XLA compiles, and doubles as the disarmed-faults behavior check
# (tests/test_chaos.py compares its output against a faults-armed run).
MOCKER = bool(os.environ.get("BENCH_MOCKER"))
# The unified single-dispatch path (one ragged mixed prefill+decode
# batch per step; ROADMAP item #2) is the ONLY engine path now.
# BENCH_UNIFIED=1 additionally gates on the unified contract: warmup
# must stay within the budget ladder (≤ 8 programs vs the old
# lane×bucket grid's dozens) and the measured window must stay at zero
# mid-traffic compiles. BENCH_SPEC=1 (the spec A/B leg) implies the
# same gate with speculative decoding enabled.
UNIFIED = bool(
    os.environ.get("BENCH_UNIFIED") or os.environ.get("BENCH_SPEC")
)
UNIFIED_MAX_WARMUP_PROGRAMS = 8
# BENCH_TRACE=1: the observability leg (ci.sh "mocker trace smoke").
# The span capture itself is driven by DYNTPU_TRACE (utils/tracing.py);
# this flag asserts the leg's contract — refusing to run without a
# capture path and echoing it in extras — so the gate can't silently
# measure a run with tracing off.
TRACE = bool(os.environ.get("BENCH_TRACE"))
if TRACE and not os.environ.get("DYNTPU_TRACE"):
    raise SystemExit(
        "BENCH_TRACE=1 requires DYNTPU_TRACE=<capture path> — the trace "
        "leg exists to feed trace_merge.py --assert-complete"
    )
# BENCH_ROUTE_AUDIT=1: the KV-observatory leg (ci.sh "mocker route
# audit"). A multi-worker mocker deployment behind the KV-aware router
# with the trace capture on — route-audit records (predicted) and
# engine-side kv_actual records (actual) land in the same capture, and
# ci.sh closes the loop with benchmarks/route_audit.py --assert.
ROUTE_AUDIT = bool(os.environ.get("BENCH_ROUTE_AUDIT"))
if ROUTE_AUDIT and not os.environ.get("DYNTPU_TRACE"):
    raise SystemExit(
        "BENCH_ROUTE_AUDIT=1 requires DYNTPU_TRACE=<capture path> — the "
        "leg exists to feed route_audit.py --assert"
    )


def _env_int(name: str, default: int) -> int:
    return int(os.environ.get(name, default))


# Scenario knobs (env-overridable for on-chip experiments; the committed
# defaults are what the driver measures). 64 requests / 64 decode lanes:
# the r03 batch-width study (BENCHMARKS.md) measured decode cost nearly
# flat from B=32→64, so doubling the lanes took E2E 719→1061 tok/s/chip
# (+48%) on the same chip.
NUM_REQ = _env_int("BENCH_REQS", 4 if SMOKE else 64)
# BENCH_ISL=3000 BENCH_OSL=150 reproduces the reference harness shape
# (reference: examples/llm/benchmarks/perf.sh).
ISL, OSL = (32, 8) if SMOKE else (
    _env_int("BENCH_ISL", 128), _env_int("BENCH_OSL", 64)
)


def _engine_config():
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.models.config import ModelConfig

    # max_num_seqs=32: decode compute is latency-bound at these shapes
    # (B=32 costs ~same per step as B=8 — see BENCHMARKS.md microbench),
    # so wide batches are nearly free throughput and kill the admission
    # queueing that dominated r01/r02 TTFT. decode_chunk=16 amortizes the
    # host→device dispatch (dominant through the tunneled chip).
    # prefill_batch=16: wider fused prefill absorbs the arrival burst —
    # r03 A/B on the chip: 8→16→32 lanes moved E2E 690→1260→1562 tok/s/chip
    # and p50 TTFT 661→282→191 ms in one session (tunnel variance is large;
    # 16 is the balanced default — 32 makes each fused call a bigger single
    # dispatch, so a slow tunnel moment lands on every lane's TTFT at once).
    # It is a cap, not a quota: online latency never waits for stragglers.
    # BENCH_MODEL=llama31_8b (+ DYNAMO_TPU_QUANT=int8 to fit 16 GB HBM)
    # runs the 8B-class scenario (BASELINE.md progression step 2).
    model = (
        ModelConfig.tiny_test()
        if SMOKE
        else getattr(ModelConfig, os.environ.get("BENCH_MODEL", "llama32_1b"))()
    )
    return EngineConfig(
        model=model,
        num_blocks=256 if SMOKE else _env_int("BENCH_BLOCKS", 2048),
        block_size=16,
        max_num_seqs=8 if SMOKE else _env_int("BENCH_SEQS", 64),
        max_model_len=256 if SMOKE else _env_int(
            "BENCH_MAXLEN",
            max(512, 1 << (ISL + OSL - 1).bit_length()),
        ),
        decode_chunk=8 if SMOKE else _env_int("BENCH_CHUNK", 16),
        prefill_batch=4 if SMOKE else _env_int("BENCH_PREFILL_BATCH", 16),
        enable_prefix_caching=True,
        # DYNAMO_TPU_QUANT=int8 serves int8 weights (ops/quant.py) — halves
        # decode's weight-streaming bytes; BENCH_QUANT_AB=1 A/Bs it.
        quant=os.environ.get("DYNAMO_TPU_QUANT") or None,
        # BENCH_SPEC_K=N enables prompt-lookup speculative decoding (the
        # random-prompt scenario accepts ~nothing — real value shows on
        # repetitive text; see tests/test_speculative.py).
        speculative_k=_env_int("BENCH_SPEC_K", 0),
        unified=True,
        unified_token_budget=_env_int(
            "BENCH_UNIFIED_BUDGET", 64 if SMOKE else 256
        ),
        unified_prefill_quantum=_env_int(
            "BENCH_UNIFIED_QUANTUM", 16 if SMOKE else 64
        ),
        # The bench never requests penalties/logprobs; skipping the
        # extras variant keeps the warmed set at the bare budget ladder
        # (the unified_full top-rung program would be one extra).
        sampling_extras=False,
        compile_cache_dir=_CACHE_BASE,
    )


def _make_engine(cfg):
    if MOCKER:
        from dynamo_tpu.mocker import MockerConfig, MockerEngine

        return MockerEngine(
            cfg, MockerConfig(vocab_size=cfg.model.vocab_size)
        )
    from dynamo_tpu.engine.engine import TpuEngine

    return TpuEngine(cfg)


async def _run_e2e() -> dict:
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.runtime.engine import Context

    cfg = _engine_config()
    engine = _make_engine(cfg)
    await engine.start()

    rng = np.random.default_rng(0)
    reqs = [
        PreprocessedRequest(
            token_ids=rng.integers(0, cfg.model.vocab_size, ISL).tolist(),
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=OSL, ignore_eos=True),
        )
        for _ in range(NUM_REQ)
    ]

    async def run_one(req):
        n = 0
        first = None
        async for out in engine.generate(Context(req.to_wire())):
            if out["token_ids"] and first is None:
                first = time.monotonic()
            n += len(out["token_ids"])
        return n, first

    # Warmup: compile the serving shape set off the clock — every first
    # compile through a tunneled chip costs 10s+ and would otherwise land
    # inside the measured window (the r03 "regression" root cause). The
    # FULL pruned grid, not a hand-picked bucket subset: the r05 collapse
    # (BENCHMARKS.md) was the sweep's variable-length prompts landing in
    # buckets a [ISL//2, ISL] warmup never compiled, 10-14 s each, under
    # load. The persistent compile cache makes the wider grid a one-time
    # cost — relaunches replay it from disk.
    t_warm = time.monotonic()
    warmup_programs = await engine.warmup()
    warmup_s = round(time.monotonic() - t_warm, 1)
    await asyncio.gather(
        *[
            run_one(
                PreprocessedRequest(
                    token_ids=rng.integers(0, cfg.model.vocab_size, ISL).tolist(),
                    sampling=SamplingOptions(temperature=0.0),
                    stop=StopConditions(max_tokens=2, ignore_eos=True),
                )
            )
            for _ in range(3)
        ]
    )

    # DYNTPU_PROFILE=/dir captures an XLA/TPU profile of the measured
    # window (view with tensorboard / xprof) — the profiler-hook surface
    # for digging into dispatch vs device time.
    profile_dir = os.environ.get("DYNTPU_PROFILE")
    if profile_dir:
        import jax

        jax.profiler.start_trace(profile_dir)
    t0 = time.monotonic()
    results = await asyncio.gather(*[run_one(r) for r in reqs])
    elapsed = time.monotonic() - t0
    if profile_dir:
        import jax

        jax.profiler.stop_trace()

    total_tokens = sum(n for n, _ in results)
    ttfts = [f - t0 for _, f in results if f is not None]
    attn = getattr(engine.runner, "attn", None)  # SimRunner has none
    pallas = attn is not None and attn.use_pallas
    spec = {}
    if cfg.speculative_k:
        spec = {
            "spec_k": cfg.speculative_k,
            "spec_tokens_per_step": round(engine.spec_tokens_per_step, 3),
            "spec_active_at_end": engine.spec_active,
            "spec_gate_reprobes": engine.spec_probe_count,
        }
    micro = (
        {}
        if MOCKER  # no device: per-step HBM numbers would be fiction
        else await asyncio.to_thread(_decode_microbench, engine, cfg)
    )
    # BENCH_SWEEP=0 skips the concurrency sweep (the heavyweight 8B /
    # long-context scenarios time out sweeping through a tunneled chip).
    sweep_levels = (
        await _sweep(engine) if _env_int("BENCH_SWEEP", 1) else []
    )
    compile_extras = _compile_lifecycle_report(
        engine, warmup_programs, warmup_s, sweep_levels
    )
    await engine.stop()
    return {
        "tok_per_s": round(total_tokens / elapsed, 2),
        "total_tokens": total_tokens,
        "elapsed_s": round(elapsed, 2),
        "p50_ttft_ms": round(1000 * float(np.median(ttfts)), 1),
        "max_ttft_ms": round(1000 * float(np.max(ttfts)), 1),
        "attention_path": "sim" if MOCKER else ("pallas" if pallas else "jnp"),
        "quant": cfg.quant or "none",
        **spec,
        **compile_extras,
        **micro,
        "sweep": sweep_levels,
    }


def _compile_lifecycle_report(
    engine, warmup_programs: int, warmup_s: float, sweep_levels: list[dict]
) -> dict:
    """Warmup cost + the two regression tripwires from the r05 collapse:
    the headline/sweep window must see ZERO mid-traffic compiles, and no
    sweep leg may show the compile-stall TTFT signature (p95 > 10x p50).
    Hard failures by default — a silently-regressed number is worse than
    a red bench (BENCH_COMPILE_GUARD=0 to downgrade while debugging)."""
    cs = engine.runner.compile_stats
    ratios, bad = [], []
    for leg in sweep_levels:
        p50, p95 = leg.get("p50_ttft_ms"), leg.get("p95_ttft_ms")
        if not p50 or not p95:
            continue
        r = round(p95 / p50, 2)
        ratios.append(r)
        if r > 10.0:
            bad.append(leg["concurrency"])
    out = {
        "warmup_programs": warmup_programs,
        "warmup_s": warmup_s,
        "warmup_replayed_from_cache": cs.replayed_programs,
        "mid_traffic_compiles": cs.mid_traffic_compiles,
        "compile_stall_ms": round(cs.compile_stall_ms_total, 1),
        "ttft_p95_over_p50_max": max(ratios) if ratios else None,
    }
    guard = os.environ.get("BENCH_COMPILE_GUARD", "1") != "0"
    if cs.mid_traffic_compiles and guard:
        raise RuntimeError(
            f"{cs.mid_traffic_compiles} mid-traffic compile(s) in the "
            f"measured window (shapes: {cs.mid_traffic_keys}) — warmup "
            "no longer covers the serving shape set"
        )
    if UNIFIED and guard and warmup_programs > UNIFIED_MAX_WARMUP_PROGRAMS:
        # The unified path's whole point: the warmed shape set is the
        # budget ladder, not a grid. A creeping program count means a
        # phase-split shape leaked back into the unified warmup plan.
        raise RuntimeError(
            f"unified warmup compiled {warmup_programs} programs "
            f"(> {UNIFIED_MAX_WARMUP_PROGRAMS}) — the budget ladder "
            "contract is broken (compile_cache.default_shape_grid)"
        )
    if UNIFIED:
        out["unified"] = True
        out["unified_max_warmup_programs"] = UNIFIED_MAX_WARMUP_PROGRAMS
    if bad and guard:
        raise RuntimeError(
            f"sweep legs at concurrency {bad} show p95 TTFT > 10x p50 — "
            "the r05 compile-stall signature"
        )
    return out


def _decode_microbench(engine, cfg) -> dict:
    """Steady-state fused-decode timing on the live runner: per-step ms and
    effective HBM GB/s (weights + KV read per step / time). The E2E number
    above includes prefill + scheduling; this isolates the decode hot loop
    the ITL target cares about (reference bar: planner.md:86 ITL 4.83 ms)."""
    import jax

    r = engine.runner
    B = cfg.max_num_seqs
    ctx_len = ISL + OSL
    # Tables must cover position + steps - 1 (decode_multi precondition) so
    # the fused steps write real blocks, not aliased trash-block traffic.
    blocks_per = (
        ctx_len + cfg.decode_chunk + cfg.block_size - 1
    ) // cfg.block_size
    tables = np.zeros((B, cfg.max_blocks_per_seq), np.int32)
    assert 1 + B * blocks_per <= cfg.num_blocks, (
        f"microbench tables need {1 + B * blocks_per} blocks but the arena "
        f"has {cfg.num_blocks} — raise BENCH_BLOCKS or lower "
        f"BENCH_SEQS/ISL/OSL (out-of-range pages read garbage, not fail)"
    )
    nb = 1
    for b in range(B):
        tables[b, :blocks_per] = range(nb, nb + blocks_per)
        nb += blocks_per
    ctx = np.full(B, ctx_len, np.int32)
    toks = np.ones(B, np.int32)
    zeros_f = np.zeros(B, np.float32)
    zeros_i = np.zeros(B, np.int32)
    ones_f = np.ones(B, np.float32)
    steps = cfg.decode_chunk

    out = r.decode_multi(toks, ctx - 1, tables, ctx, zeros_f, zeros_i, ones_f, steps)
    _ = np.asarray(out)  # compile + sync
    t0 = time.monotonic()
    N = 4
    for _i in range(N):
        out = r.decode_multi(
            toks, ctx - 1, tables, ctx, zeros_f, zeros_i, ones_f, steps
        )
    _ = np.asarray(out)  # tokens forced = the ITL-visible sync point
    per_step = (time.monotonic() - t0) / (N * steps)
    # KV-write readiness is NOT awaited inside the window — serving never
    # blocks on it (the next chunk queues behind the writes on device);
    # through a tunneled chip that final confirmation alone costs an RTT.
    jax.block_until_ready(r.kv_caches[0][0])

    m = cfg.model
    dtype_bytes = np.dtype(cfg.dtype).itemsize
    # Per-leaf dtype sizes: under quant="int8" the matmul weights are 1
    # byte/param (+ f32 scales), which is exactly the point.
    weight_bytes = sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(r.params)
    )
    kv_read = (
        2 * m.num_layers * B * ctx_len * m.num_kv_heads
        * r.cache_head_dim * dtype_bytes
    )
    out = {
        "decode_step_ms": round(per_step * 1000, 2),
        "decode_tok_per_s": round(B / per_step, 1),
        "effective_hbm_gbps": round(
            (weight_bytes + kv_read) / per_step / 1e9, 1
        ),
    }
    gate_shape = B == 32 and cfg.decode_chunk == 16 and ctx_len == 192
    if not SMOKE and not gate_shape:
        out.update(_decode_microbench_b32(engine, cfg, weight_bytes))
    return out


def _decode_microbench_b32(engine, cfg, weight_bytes) -> dict:
    """The VERDICT r03 #2 gate shape: B=32, decode_chunk=16, ctx 192 —
    measured on a second runner SHARING the serving runner's params (no
    extra weight HBM; its own small KV arena)."""
    import dataclasses

    import jax

    from dynamo_tpu.engine.runner import ModelRunner

    cfg32 = dataclasses.replace(
        cfg, max_num_seqs=32, num_blocks=512, decode_chunk=16,
        sampling_extras=False,
        # Params arrive ALREADY quantized from the serving runner — a
        # quant mode here would re-quantize the int8 tree.
        quant=None,
    )
    r = ModelRunner(cfg32, params=engine.runner.params)
    B, steps = 32, 16
    # The gate shape is FIXED at ctx 192 (ISL 128 + OSL 64) regardless of
    # the env scenario — long-context ISL would also overrun the small
    # 512-block arena this runner allocates.
    ctx_len = 192
    blocks_per = (ctx_len + steps + cfg32.block_size - 1) // cfg32.block_size
    tables = np.zeros((B, cfg32.max_blocks_per_seq), np.int32)
    nb = 1
    for b in range(B):
        tables[b, :blocks_per] = range(nb, nb + blocks_per)
        nb += blocks_per
    ctx = np.full(B, ctx_len, np.int32)
    zf, zi, of = (
        np.zeros(B, np.float32), np.zeros(B, np.int32), np.ones(B, np.float32),
    )
    toks = np.ones(B, np.int32)
    out = r.decode_multi(toks, ctx - 1, tables, ctx, zf, zi, of, steps)
    _ = np.asarray(out)  # compile + sync
    t0 = time.monotonic()
    N = 4
    for _i in range(N):
        out = r.decode_multi(toks, ctx - 1, tables, ctx, zf, zi, of, steps)
    _ = np.asarray(out)  # tokens forced (see _decode_microbench)
    per_step = (time.monotonic() - t0) / (N * steps)
    jax.block_until_ready(r.kv_caches[0][0])
    kv_read = (
        2 * cfg.model.num_layers * B * ctx_len * cfg.model.num_kv_heads
        * r.cache_head_dim * np.dtype(cfg.dtype).itemsize
    )
    return {
        "decode_step_ms_b32c16": round(per_step * 1000, 2),
        "effective_hbm_gbps_b32c16": round(
            (weight_bytes + kv_read) / per_step / 1e9, 1
        ),
    }


async def _sweep(engine) -> list[dict]:
    """Concurrency sweep over a prefix-structured synthetic workload
    (benchmarks/sweep.py) — the TTFT/ITL-vs-load curve VERDICT r02 asked
    for. Prompt lengths are clamped into the warmed buckets."""
    from benchmarks.sweep import run_level
    from benchmarks.synthesizer import WorkloadConfig, generate

    # Through c=64 — the committed lane width; >=32 requests per level so
    # per-level medians aren't tunnel-noise artifacts (VERDICT r03 #8:
    # 12-request levels made c=32 look slower than c=16).
    levels = (1, 4, 16) if SMOKE else (1, 4, 16, 32, 64)
    out = []
    for c in levels:
        reqs = generate(
            WorkloadConfig(
                num_requests=8 if SMOKE else max(32, c),
                isl_mean=ISL - ISL // 4,
                osl_mean=max(OSL // 2, 4),
                vocab_size=min(1000, engine.cfg.model.vocab_size),
                seed=c,
            )
        )
        for r in reqs:
            r.token_ids = r.token_ids[:ISL]
            r.max_tokens = min(r.max_tokens, OSL)
        out.append(await run_level(engine, reqs, c))
    return out


async def _run_disagg() -> dict:
    """Agg vs disagg on REAL engines (VERDICT r04 #2): the same workload
    through one aggregated engine, then through a prefill+decode engine
    pair co-located on this chip and wired over the device (HBM→HBM)
    transfer plane. One chip can't add compute, so the honest claim this
    measures is the SPLIT's overhead/benefit at fixed silicon: does
    dedicating prefill to a second engine (decode batches never stall
    behind a prompt) beat the aggregated engine's chunked interleave, and
    what does the KV handoff cost end to end."""
    import dataclasses

    from benchmarks.sweep import run_level
    from benchmarks.synthesizer import Request
    from dynamo_tpu.disagg import (
        DecodeOperator,
        DisaggConfig,
        DisaggRouter,
        PrefillQueue,
        PrefillWorker,
    )
    from dynamo_tpu.engine.engine import TpuEngine
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    cfg = _engine_config()
    rng = np.random.default_rng(0)
    reqs = [
        Request(
            token_ids=rng.integers(0, cfg.model.vocab_size, ISL).tolist(),
            max_tokens=OSL,
        )
        for _ in range(NUM_REQ)
    ]
    conc = min(NUM_REQ, cfg.max_num_seqs)

    # Aggregated baseline. Full pruned-grid warmup, not just bucket(ISL):
    # a prompt whose length is not a chunk multiple buckets its LAST
    # chunk small (the r05 hole) — and the persistent cache makes the
    # second/third engine's identical warmups disk replays.
    agg = TpuEngine(cfg)
    await agg.start()
    await agg.warmup()
    agg_res = await run_level(agg, reqs, concurrency=conc)
    params = agg.runner.params  # share weights with the pair (same HBM)
    await agg.stop()

    # Disagg pair: decode keeps the serving arena; prefill gets its own
    # smaller arena (it only holds in-flight prompts' KV). Weights are
    # SHARED device buffers — co-located engines don't pay them twice.
    drt = await DistributedRuntime.in_process()
    queue = PrefillQueue(drt, "bench")
    dis = DisaggRouter.__new__(DisaggRouter)
    if os.environ.get("BENCH_DISAGG_ADAPTIVE"):
        # Production router behavior: the queue-age SLA sheds prefills
        # back to local when the prefill pool can't keep up.
        dis.cfg = DisaggConfig(
            max_local_prefill_length=min(32, ISL - 1),
            max_prefill_queue_size=NUM_REQ * 2,
        )
    else:
        # Forced split: EVERY prefill goes remote so the handoff path
        # (queue + prefill engine + KV transfer) is what gets measured.
        dis.cfg = DisaggConfig(
            max_local_prefill_length=min(32, ISL - 1),
            max_prefill_queue_size=10**6,
            max_prefill_queue_age_s=1e9,
        )
    decode = TpuEngine(dataclasses.replace(cfg, quant=None), params=params)
    await decode.start()
    prefill = TpuEngine(
        dataclasses.replace(
            cfg,
            quant=None,
            num_blocks=max(512, cfg.num_blocks // 2),
        ),
        params=params,
    )
    await prefill.start()
    op = await DecodeOperator(decode, queue, dis, transport="device").start()
    pw = PrefillWorker(prefill, queue).start()
    await decode.warmup()
    await prefill.warmup()
    disagg_res = await run_level(op, reqs, concurrency=conc)
    remote = op.remote_count
    await pw.stop()
    await op.stop()
    await decode.stop()
    await prefill.stop()
    await drt.shutdown()
    return {
        "agg": agg_res,
        "disagg": disagg_res,
        "remote_prefills": remote,
        "transport": "device",
        "concurrency": conc,
        "ratio_tok_per_s": round(
            disagg_res["tok_per_s"] / max(agg_res["tok_per_s"], 1e-9), 3
        ),
    }


def _run_ab(var: str, settings: list[tuple[str, str]]) -> dict:
    """Run the E2E scenario in child processes with `var` set per setting;
    returns all results (the evidence-backed-default pattern from the r03
    Pallas A/B)."""
    results = {}
    for name, flag in settings:
        env = dict(os.environ)
        env[var] = flag
        env.pop("BENCH_AB", None)
        env.pop("BENCH_QUANT_AB", None)
        env.pop("BENCH_SPEC_AB", None)
        for attempt in (1, 2):  # one retry: the tunnel drops compiles rarely
            out = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, capture_output=True, text=True,
                cwd=os.path.dirname(os.path.abspath(__file__)),
            )
            if out.returncode == 0:
                break
            sys.stderr.write(out.stderr)
            if attempt == 2:
                raise RuntimeError(
                    f"A/B child {name!r} failed rc={out.returncode}"
                )
        results[name] = json.loads(out.stdout.strip().splitlines()[-1])
    return results


async def _run_overload() -> dict:
    """Overload smoke (ci.sh BENCH_OVERLOAD=1): the FULL HTTP stack over a
    slow mocker engine, driven at offered load ≫ capacity. Hard asserts
    (the acceptance criteria of the overload-safe serving work):

    - a low-load leg sheds NOTHING (every request 200);
    - the overload leg produces 429s carrying ``Retry-After`` (excess
      refused, not queued unboundedly) and zero hangs (everything
      bounded);
    - admitted requests finish within their deadlines;
    - ``shed_requests_total`` / ``deadline_exceeded_total`` / ``draining``
      appear on HTTP /metrics with shed > 0.
    """
    import aiohttp

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.llm.admission import AdmissionConfig, AdmissionController
    from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher, register_llm
    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.llm.model_card import ModelDeploymentCard
    from dynamo_tpu.mocker import MockerConfig, MockerEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    cfg = EngineConfig(
        model=ModelConfig.tiny_test(),
        num_blocks=128,
        max_num_seqs=4,
        max_model_len=256,
        dtype="float32",
        max_waiting=8,           # bounded engine waiting list
    )
    # Slow cost model: ~4 concurrent lanes at ~2 ms/step makes a 64-way
    # burst genuinely over capacity without making the leg slow.
    engine = MockerEngine(
        cfg,
        MockerConfig(
            prefill_time_per_token_us=100.0,
            decode_time_per_step_us=2000.0,
            vocab_size=cfg.model.vocab_size,
        ),
    )
    await engine.start()
    await engine.warmup()

    drt = await DistributedRuntime.in_process()
    ep = drt.namespace("bench").component("mock").endpoint("generate")
    await ep.serve(engine)
    await register_llm(
        drt, ep, ModelDeploymentCard(name="mock", model_path="toy")
    )
    manager = ModelManager()
    await ModelWatcher(drt, manager).start()
    admission = AdmissionController(
        AdmissionConfig(
            max_inflight=8,
            max_engine_waiting=8,
            default_deadline_s=30.0,
            retry_after_s=1.0,
        ),
        engine_stats=engine.readiness,
    )
    service = HttpService(
        manager, host="127.0.0.1", port=0,
        readiness=engine.readiness, admission=admission,
    )
    await service.start()
    base = f"http://127.0.0.1:{service.port}"
    body = {
        "model": "mock",
        "messages": [{"role": "user", "content": "overload probe"}],
        "stream": False,
        "max_tokens": 8,
    }

    async def one(session):
        t0 = time.monotonic()
        async with session.post(
            f"{base}/v1/chat/completions", json=body
        ) as resp:
            await resp.read()
            return resp.status, dict(resp.headers), time.monotonic() - t0

    try:
        async with aiohttp.ClientSession() as session:
            # Low-load leg: sequential trickle well under capacity —
            # nothing may shed.
            low = [await one(session) for _ in range(4)]
            low_bad = [s for s, _, _ in low if s != 200]
            if low_bad:
                raise RuntimeError(f"low-load leg shed/failed: {low_bad}")
            shed_low = OVERLOAD_SHED_SNAPSHOT()
            # Overload leg: one 64-way burst at max_inflight=8. Bounded
            # end to end — a hang here IS the failure being guarded.
            results = await asyncio.wait_for(
                asyncio.gather(*[one(session) for _ in range(64)]),
                timeout=120.0,
            )
            ok = [r for r in results if r[0] == 200]
            shed = [r for r in results if r[0] == 429]
            other = [r[0] for r in results if r[0] not in (200, 429)]
            if other:
                raise RuntimeError(f"unexpected statuses under overload: {other}")
            if not shed:
                raise RuntimeError(
                    "offered load >> capacity produced no 429s — "
                    "admission gate inert"
                )
            missing_retry_after = [
                h for _, h, _ in shed if "Retry-After" not in h
            ]
            if missing_retry_after:
                raise RuntimeError("429 responses missing Retry-After")
            # Admitted requests must finish within the default deadline.
            slow = [t for _, _, t in ok if t > 30.0]
            if slow:
                raise RuntimeError(f"admitted requests blew deadline: {slow}")
            async with session.get(f"{base}/metrics") as resp:
                metrics_text = await resp.text()
    finally:
        await service.stop()
        await drt.shutdown()
        await engine.stop()
    for needle in (
        "shed_requests_total",
        "deadline_exceeded_total",
        "_draining",
    ):
        if needle not in metrics_text:
            raise RuntimeError(f"/metrics missing {needle}")
    shed_total = OVERLOAD_SHED_SNAPSHOT()
    if shed_total <= shed_low:
        raise RuntimeError("shed_requests_total did not increase under overload")
    ttfts = sorted(t for _, _, t in ok)
    return {
        "offered": 64,
        "completed_200": len(ok),
        "shed_429": len(shed),
        "low_load_shed": shed_low,
        "shed_requests_total": shed_total,
        "p95_admitted_latency_ms": round(
            1000 * ttfts[int(0.95 * (len(ttfts) - 1))], 1
        ) if ttfts else None,
    }


async def _run_route_audit() -> dict:
    """KV-observatory leg (ci.sh BENCH_ROUTE_AUDIT=1): a multi-worker
    mocker deployment behind the production KV-aware routing plane
    (KvEventPublisher → bus → radix indexer → PushRouter KV mode) with
    the DYNTPU_TRACE capture on. Every decision writes a ``route`` record
    (predicted overlap + candidates + indexer watermark); every engine
    admission writes a ``kv_actual`` record (per-tier actual reuse); both
    stream into the capture, which ci.sh then feeds to
    benchmarks/route_audit.py --assert — the gate that ≥95% of requests
    join predicted↔actual by trace id, with zero orphan routes and a
    non-zero actual-reuse report.

    Inline hard asserts (this process's half of the contract):
    - every request completes;
    - a route-audit record exists for every routed request;
    - the hit-rate plane carries BOTH kinds (predicted + actual);
    - the indexer applied events and recorded publish→apply lag;
    - follow-up turns actually reused KV (affinity held).
    """
    import random as _random

    import msgpack as _msgpack

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.llm.kv_router.audit import ROUTE_OBS
    from dynamo_tpu.llm.kv_router.protocols import KV_HIT_RATE_PLANE
    from dynamo_tpu.llm.kv_router.publisher import (
        KvEventPublisher,
        WorkerMetricsPublisher,
    )
    from dynamo_tpu.llm.kv_router.router import KvRouter
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.mocker import MockerConfig, MockerEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.runtime.egress import PushRouter, RouterMode
    from dynamo_tpu.runtime.engine import Context

    num_workers = _env_int("BENCH_ROUTE_WORKERS", 3)
    sessions = _env_int("BENCH_ROUTE_SESSIONS", 12)
    cfg = EngineConfig(
        model=ModelConfig.tiny_test(),
        num_blocks=512,
        max_num_seqs=8,
        max_model_len=512,
        dtype="float32",
    )

    drt0 = await DistributedRuntime.in_process()
    drts = [drt0]
    engines = []
    for i in range(num_workers):
        drt = (
            drt0
            if i == 0
            else await DistributedRuntime.in_process(
                store=drt0.store, bus=drt0.bus, runtime=drt0.runtime
            )
        )
        if i > 0:
            drts.append(drt)
        comp = drt.namespace("bench").component("worker")
        wm = WorkerMetricsPublisher()
        pub = KvEventPublisher(drt, comp, drt.primary_lease_id)
        eng = MockerEngine(cfg, MockerConfig(seed=i))
        eng._external_kv_event = pub.publish_engine_event
        eng._on_metrics = wm.publish
        # The loop-closing half: per-request actuals onto the hit-rate
        # plane (and the trace capture, via the engine's own flush).
        eng._on_kv_actual = pub.publish_hit_actual
        await eng.start()
        await comp.endpoint("generate").serve(eng)
        await wm.create_endpoint(comp)
        engines.append(eng)

    comp0 = drt0.namespace("bench").component("worker")
    # Count both payload kinds on the hit-rate plane — the loop must be
    # closed ON THE BUS, not just in this process's capture file.
    plane_counts = {"predicted": 0, "actual": 0}
    plane_sub = await drt0.bus.subscribe(
        comp0.event_subject(KV_HIT_RATE_PLANE)
    )

    async def count_plane():
        async for raw in plane_sub:
            kind = _msgpack.unpackb(raw).get("kind", "predicted")
            plane_counts[kind] = plane_counts.get(kind, 0) + 1

    plane_task = asyncio.ensure_future(count_plane())

    router = await KvRouter(drt0, comp0).start()
    push = await PushRouter.create(
        drt0,
        "bench.worker.generate",
        mode=RouterMode.KV,
        selector=router.selector_fn,
    )

    rng = _random.Random(7)
    prompts = [
        [rng.randrange(0, cfg.model.vocab_size) for _ in range(64 + 16 * (s % 3))]
        for s in range(sessions)
    ]

    async def send(tokens, osl=4):
        req = PreprocessedRequest(
            token_ids=list(tokens),
            sampling=SamplingOptions(temperature=0.0),
            stop=StopConditions(max_tokens=osl, ignore_eos=True),
        )
        ctx = Context(req.to_wire())
        out = []
        async for item in push.generate(ctx):
            out += item.get("token_ids", [])
        return out

    routes_before = ROUTE_OBS.routes_total
    # Turn 1: place every session's prefix on whichever worker wins.
    turn1 = await asyncio.gather(*[send(p) for p in prompts])
    await asyncio.sleep(0.4)  # KV events → indexer (lag gets measured)
    # Turn 2: full-history follow-ups — the predicted overlap should be
    # nonzero and the chosen worker should ACTUALLY reuse blocks.
    turn2 = await asyncio.gather(
        *[send(p + o + p[:16]) for p, o in zip(prompts, turn1)]
    )
    await asyncio.sleep(0.4)  # actual records flush + plane broadcasts land

    bad = [i for i, o in enumerate(turn2) if len(o) != 4]
    if bad:
        raise RuntimeError(f"turn-2 requests incomplete: {bad}")
    total_requests = 2 * sessions
    routed = ROUTE_OBS.routes_total - routes_before
    if routed < total_requests:
        raise RuntimeError(
            f"route-audit records missing: {routed} < {total_requests}"
        )
    obs = router.observability()
    if obs["kv_events_applied_total"] <= 0:
        raise RuntimeError("indexer applied no KV events")
    if obs["kv_event_lag_count"] <= 0:
        raise RuntimeError("no publish→apply lag samples recorded")
    reused = sum(
        e._reused_device_blocks + e._reused_host_blocks + e._reused_disk_blocks
        for e in engines
    )
    if reused <= 0:
        raise RuntimeError(
            "follow-up turns reused zero blocks — affinity/actual loop broken"
        )
    if plane_counts["predicted"] <= 0 or plane_counts["actual"] <= 0:
        raise RuntimeError(
            f"hit-rate plane incomplete: {plane_counts} — both kinds required"
        )
    # Turn-2 affinity as seen by the AUDIT RECORDS themselves.
    recent = ROUTE_OBS.snapshot(total_requests)["recent"]
    turn2_recs = recent[-sessions:]
    with_overlap = sum(1 for r in turn2_recs if r["overlap_blocks"] > 0)

    plane_sub.close()
    plane_task.cancel()
    try:
        await plane_task
    except (asyncio.CancelledError, Exception):  # noqa: BLE001 — teardown
        pass
    await router.stop()
    for eng in engines:
        await eng.stop()
    await drt0.shutdown()
    return {
        "workers": num_workers,
        "sessions": sessions,
        "requests": total_requests,
        "route_records": routed,
        "turn2_with_predicted_overlap": with_overlap,
        "kv_events_applied": obs["kv_events_applied_total"],
        "kv_event_lag_p99_ms": obs["kv_event_lag_p99_ms"],
        "reused_blocks_total": reused,
        "hit_rate_plane": dict(plane_counts),
        "trace_capture": os.environ.get("DYNTPU_TRACE", ""),
        "aggregator_scrape_failures_total": obs[
            "aggregator_scrape_failures_total"
        ],
    }


async def _run_spec() -> dict:
    """Unified speculative-decode A/B (ci.sh BENCH_SPEC=1; ROADMAP #2's
    last leg): spec decode now rides the ragged unified step — draft-
    verify spans on the SAME budget-ladder programs, acceptance computed
    in-dispatch. Three mocker legs over one decode-heavy workload:

    - **spec** (accepting regime): deterministic position-free token
      chain (MockerConfig.det_positional=False, small vocab) with the
      prompt pre-seeded on the chain, so prompt-lookup drafts verify —
      the regime speculation exists for;
    - **plain**: the same engine with speculative_k=0;
    - **losing** (free-when-losing): the positional chain (drafts never
      accept) with tight gate windows — the auto-gate must disable and
      keep re-probe overhead inside the probe-window bound.

    Hard gates:
    - warmup ≤ 8 programs (``warmup_programs_total`` — spec adds ZERO
      programs to the ladder) and zero mid-traffic compiles on every
      leg;
    - accepting-draft spec throughput ≥ the plain unified leg's;
    - accepting-draft spec throughput ≥ the RECORDED phased-spec
      baseline — computed from the phased pricing law this suite
      retained when the phased engine was deleted
      (``decode_multi_spec`` charged the dispatch base ×(1+K) per
      1-token step; BENCHMARKS.md "Speculative decode A/B");
    - the losing leg's spec steps stay within
      window + probes × probe_window (the phased gate's bound,
      preserved).
    """
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.mocker import MockerConfig, MockerEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.runtime.engine import Context

    spec_k = _env_int("BENCH_SPEC_LEG_K", 4)
    n_req, osl, isl = 4, 120, 64
    # vocab 23 puts the position-free affine chain on an 11-cycle, so a
    # 64-token chain prompt repeats its bigrams several times over —
    # prompt-lookup drafts verify from the first decode step.
    vocab = 23

    def cfg(k: int, **kw) -> EngineConfig:
        return EngineConfig(
            model=ModelConfig.tiny_test(),
            num_blocks=256,
            max_num_seqs=n_req,
            max_model_len=512,
            dtype="float32",
            speculative_k=k,
            unified=True,
            unified_token_budget=64,
            sampling_extras=False,
            **kw,
        )

    from dynamo_tpu.mocker import det_next_token

    def chain_prompt(seed_tok: int) -> list[int]:
        # The prompt IS the closed-form chain (built through the SAME
        # helper the sim verifies drafts against), so the trailing
        # bigram always has an earlier occurrence once the cycle closes
        # — the accepting-draft setting.
        toks = [seed_tok]
        for _ in range(isl - 1):
            toks.append(int(det_next_token(toks[-1], 0, vocab, positional=False)))
        return toks

    async def run_leg(engine) -> dict:
        await engine.start()
        await engine.warmup()
        reqs = [
            PreprocessedRequest(
                token_ids=chain_prompt(3 + i),
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=osl, ignore_eos=True),
            )
            for i in range(n_req)
        ]

        async def one(req):
            n = 0
            async for out in engine.generate(Context(req.to_wire())):
                n += len(out["token_ids"])
            return n

        t0 = time.monotonic()
        counts = await asyncio.gather(*[one(r) for r in reqs])
        dt = time.monotonic() - t0
        cs = engine.runner.compile_stats
        leg = {
            "tok_per_s": round(sum(counts) / dt, 1),
            "tokens": sum(counts),
            "warmup_programs_total": cs.snapshot()["warmup_programs_total"],
            "mid_traffic_compiles": cs.mid_traffic_compiles,
            "spec_tokens_per_step": round(engine.spec_tokens_per_step, 3),
            "spec_drafted": engine._spec_drafted,
            "spec_accepted": engine._spec_accepted,
            "spec_active_at_end": engine.spec_active,
        }
        await engine.stop()
        return leg

    sim_accept = MockerConfig(
        vocab_size=vocab, deterministic_tokens=True, det_positional=False
    )
    spec = await run_leg(MockerEngine(cfg(spec_k), sim_accept))
    plain = await run_leg(MockerEngine(cfg(0), sim_accept))

    # Free-when-losing: positional chain (drafts never verify) + tight
    # gate windows; bound identical to the phased gate's contract.
    window, probe_window, probe_steps = 8, 2, 32
    losing_engine = MockerEngine(
        cfg(
            spec_k,
            speculative_window=window,
            speculative_probe_window=probe_window,
            speculative_probe_steps=probe_steps,
        ),
        MockerConfig(vocab_size=vocab, deterministic_tokens=True),
    )
    losing = await run_leg(losing_engine)
    losing["spec_steps"] = losing_engine._spec_steps
    losing["probes"] = losing_engine.spec_probe_count
    # Each window close (the initial window + every probe) can overshoot
    # by up to n_req - 1 steps: the closing dispatch retires one spec
    # step per concurrent lane at once.
    probes = losing_engine.spec_probe_count
    losing_budget = (
        window + probes * probe_window + (probes + 1) * (n_req - 1)
    )

    # The recorded phased-spec baseline: the deleted decode_multi_spec
    # sim charged decode_time_per_step_us × (1+K) per fused step and
    # delivered 1 token per lane per step — its throughput at these
    # constants is the closed form below (BENCHMARKS.md keeps the
    # history; the law is retained here so the comparison outlives the
    # deleted code).
    base_us = sim_accept.decode_time_per_step_us
    phased_spec_tps = round(n_req / (base_us * (1 + spec_k) / 1e6), 1)

    failures = []
    for name, leg in (("spec", spec), ("plain", plain), ("losing", losing)):
        if leg["warmup_programs_total"] > UNIFIED_MAX_WARMUP_PROGRAMS:
            failures.append(
                f"{name} leg warmed {leg['warmup_programs_total']} programs "
                f"(> {UNIFIED_MAX_WARMUP_PROGRAMS}) — spec must add ZERO "
                "programs to the budget ladder"
            )
        if leg["mid_traffic_compiles"]:
            failures.append(
                f"{name} leg paid {leg['mid_traffic_compiles']} mid-traffic "
                "compile(s)"
            )
    if spec["spec_tokens_per_step"] <= 1.5:
        failures.append(
            f"accepting-draft leg delivered only "
            f"{spec['spec_tokens_per_step']} tok/step — drafts are not "
            "being accepted"
        )
    if spec["tok_per_s"] < plain["tok_per_s"]:
        failures.append(
            f"unified spec {spec['tok_per_s']} tok/s < unified non-spec "
            f"{plain['tok_per_s']} at accepting-draft settings"
        )
    if spec["tok_per_s"] < phased_spec_tps:
        failures.append(
            f"unified spec {spec['tok_per_s']} tok/s < the recorded "
            f"phased-spec baseline {phased_spec_tps}"
        )
    if losing["spec_active_at_end"]:
        failures.append("losing leg never auto-gated speculation off")
    if losing["spec_steps"] > losing_budget:
        failures.append(
            f"losing leg ran {losing['spec_steps']} spec steps; "
            f"free-when-losing bound is {losing_budget}"
        )
    if failures:
        raise RuntimeError(
            "BENCH_SPEC gates failed:\n  " + "\n  ".join(failures)
        )
    return {
        "spec_k": spec_k,
        "spec": spec,
        "plain": plain,
        "losing": losing,
        "phased_spec_baseline_tok_per_s": phased_spec_tps,
        "speedup_vs_plain": round(
            spec["tok_per_s"] / max(plain["tok_per_s"], 1e-9), 3
        ),
        "speedup_vs_phased_spec": round(
            spec["tok_per_s"] / max(phased_spec_tps, 1e-9), 3
        ),
    }


async def _run_coloc() -> dict:
    """Co-location A/B (ci.sh BENCH_COLOC=1; ROADMAP item #3): the same
    ISL3000-style mixed load through (a) SLO-aware ADAPTIVE co-located
    serving (AIMD quantum, engine/coloc.py) and (b) the STATIC-quantum
    baseline (the hand-tuned default the controller replaces), on the
    mocker's per-phase cost model. The phase-alternating aggregated
    baseline is GONE with the phased engine — its recorded numbers live
    in BENCHMARKS.md history; the live A/B now proves the adaptive
    controller beats the static default it ships over. Hard asserts,
    the acceptance criteria of the co-location work:

    - the adaptive leg's decode ITL p95 DURING the prefill burst stays
      within ``itl_slo_ms``;
    - its prefill throughput (burst prompt tokens / time-to-last-TTFT)
      meets or exceeds the static baseline's (headroom under the SLO
      must convert into quantum growth);
    - zero mid-traffic compiles on the adaptive leg (adaptation is
      batch composition — totals still snap onto the warmed budget
      ladder).
    """
    import dataclasses

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.mocker import MockerConfig, MockerEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.runtime.engine import Context

    slo_ms = float(os.environ.get("BENCH_COLOC_SLO_MS", 15.0))
    isl = _env_int("BENCH_COLOC_ISL", 3000)
    n_decode, osl_decode, isl_decode = 8, 200, 64
    n_burst, osl_burst = 6, 4
    base_cfg = EngineConfig(
        model=ModelConfig.tiny_test(),
        num_blocks=2048,
        block_size=16,
        # Slots for BOTH populations: the decode cohort holds 8 lanes
        # for the whole run while the prefill burst co-locates into the
        # remaining 4 — otherwise prefill would only run as decode
        # drains and the A/B would measure slot starvation, not
        # co-location.
        max_num_seqs=n_decode + 4,
        max_model_len=4096,
        prefill_batch=4,
        dtype="float32",
        sampling_extras=False,
    )
    # Per-phase cost model: 2 ms dispatch base (weight pass) + 100 us
    # per decode lane + 10 us per prefill token; a standalone prefill
    # dispatch pays a 4 ms base of its own. The steady co-located
    # dispatch is therefore ~2.8 ms + 10 us/quantum-token: quantum
    # changes visibly move ITL, which is what the controller steers.
    sim = MockerConfig(
        prefill_time_per_token_us=10.0,
        prefill_quadratic_us=0.0,
        decode_time_per_step_us=2000.0,
        decode_time_per_lane_us=100.0,
        prefill_dispatch_base_us=4000.0,
        vocab_size=base_cfg.model.vocab_size,
    )

    async def leg(colocated: bool) -> dict:
        if colocated:
            cfg = dataclasses.replace(
                base_cfg,
                unified=True,
                unified_token_budget=1024,
                unified_prefill_quantum=64,
                coloc="adaptive",
                itl_slo_ms=slo_ms,
                coloc_min_quantum=16,
            )
        else:
            # Static baseline: the same budget, the hand-tuned default
            # quantum, no controller — what serving looks like without
            # adaptation.
            cfg = dataclasses.replace(
                base_cfg,
                unified=True,
                unified_token_budget=1024,
                unified_prefill_quantum=64,
                coloc="static",
            )
        eng = MockerEngine(cfg, sim)
        await eng.start()
        await eng.warmup()
        rng = np.random.default_rng(7)
        gaps: list[tuple[float, float]] = []  # (t_gap_end, gap_ms)

        async def run_decode():
            req = PreprocessedRequest(
                token_ids=rng.integers(
                    0, cfg.model.vocab_size, isl_decode
                ).tolist(),
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=osl_decode, ignore_eos=True),
            )
            last = None
            async for out in eng.generate(Context(req.to_wire())):
                if not out["token_ids"]:
                    continue
                # One gap per delivery frame: tokens sharing a frame
                # arrived together, and recording a zero per extra
                # token would dilute the percentiles with artifacts of
                # delivery batching instead of measuring arrival gaps.
                now = time.monotonic()
                if last is not None:
                    gaps.append((now, 1000.0 * (now - last)))
                last = now

        async def run_burst():
            req = PreprocessedRequest(
                token_ids=rng.integers(0, cfg.model.vocab_size, isl).tolist(),
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=osl_burst, ignore_eos=True),
            )
            first = None
            async for out in eng.generate(Context(req.to_wire())):
                if out["token_ids"] and first is None:
                    first = time.monotonic()
            return first

        decode_tasks = [
            asyncio.create_task(run_decode()) for _ in range(n_decode)
        ]
        await asyncio.sleep(0.15)  # decode population reaches steady state
        t_burst = time.monotonic()
        firsts = await asyncio.gather(*[run_burst() for _ in range(n_burst)])
        t_done = max(f for f in firsts if f is not None)
        # Controller state AT burst end — the p95 window still holds the
        # burst-era dispatch intervals (the post-burst decode-only tail
        # would flush them out).
        coloc_at_burst = dict(eng.coloc.snapshot()) if colocated else None
        await asyncio.gather(*decode_tasks)
        burst_gaps = sorted(
            g for t, g in gaps if t_burst <= t <= t_done
        ) or sorted(g for _, g in gaps)
        p95 = burst_gaps[min(len(burst_gaps) - 1, int(0.95 * len(burst_gaps)))]
        cs = eng.runner.compile_stats
        await eng.stop()
        out = {
            "prefill_tok_per_s": round(n_burst * isl / (t_done - t_burst), 1),
            # Client-observed inter-token gaps: dispatch cadence PLUS
            # asyncio delivery jitter (frames queue behind the event
            # loop). Reported for both legs; the SLO gate below reads
            # the engine-side dispatch-interval p95 — the cadence the
            # controller actually regulates.
            "client_itl_p95_ms": round(p95, 2),
            "client_itl_p50_ms": round(burst_gaps[len(burst_gaps) // 2], 2),
            "mid_traffic_compiles": cs.mid_traffic_compiles,
        }
        if coloc_at_burst is not None:
            out["itl_p95_ms"] = coloc_at_burst["itl_p95_ms"]
            out["itl_ema_ms"] = coloc_at_burst["itl_ema_ms"]
            out["coloc_quantum"] = coloc_at_burst["coloc_quantum"]
            out["itl_slo_violations_total"] = coloc_at_burst[
                "itl_slo_violations_total"
            ]
            out["coloc_prefill_deferrals_total"] = coloc_at_burst[
                "coloc_prefill_deferrals_total"
            ]
        return out

    coloc = await leg(colocated=True)
    agg = await leg(colocated=False)
    if coloc["mid_traffic_compiles"]:
        raise RuntimeError(
            f"co-located leg paid {coloc['mid_traffic_compiles']} "
            "mid-traffic compile(s) — adaptive quantum must stay on the "
            "warmed budget ladder"
        )
    if coloc["itl_p95_ms"] > slo_ms:
        raise RuntimeError(
            f"co-located decode ITL p95 {coloc['itl_p95_ms']} ms (engine "
            f"dispatch-interval, at burst end) violates the {slo_ms} ms "
            "SLO — the quantum controller failed to hold it"
        )
    if coloc["prefill_tok_per_s"] < agg["prefill_tok_per_s"]:
        raise RuntimeError(
            f"adaptive co-located prefill throughput "
            f"{coloc['prefill_tok_per_s']} tok/s fell below the "
            f"static-quantum baseline's {agg['prefill_tok_per_s']} — "
            "SLO headroom must convert into quantum growth"
        )
    return {
        "slo_ms": slo_ms,
        "isl": isl,
        "coloc": coloc,
        "static_baseline": agg,
        "prefill_ratio": round(
            coloc["prefill_tok_per_s"] / max(agg["prefill_tok_per_s"], 1e-9),
            3,
        ),
    }


async def _run_quant() -> dict:
    """Quantized-KV A/B (ci.sh BENCH_QUANT=1; ROADMAP #3 raw-bandwidth
    item; docs/architecture/kv_quant.md): long-context decode through
    (a) an int8-KV unified engine and (b) the bf16 baseline, priced by
    the mocker's decode HBM-bytes term CALIBRATED to BENCH_r04's
    measured 282.8 GB/s effective decode bandwidth
    (planner/calibration.py DECODE_HBM_GBPS). The int8 leg gets the
    SAME simulated HBM KV byte budget — which fits ~2× the blocks, so
    it runs 2× the decode lanes — and its per-lane KV reads stream at
    the packed int8 ratio (~0.502 of bf16 bytes). Hard asserts:

    - int8 decode throughput ≥ 1.5× the bf16 leg's tok/s/chip;
    - EQUAL SLO: both legs' engine-side decode ITL p95 within
      ``BENCH_QUANT_SLO_MS``;
    - zero mid-traffic compiles and warmup ≤ 8 programs per leg
      (quantization only changes dtypes inside the budget ladder).

    Prefill constants are deliberately cheap (2 µs/token): the gate
    measures the DECODE phase (engine decode-token counters between
    all-lanes-decoding and completion), and pricing prefill at chip
    rates would only slow CI without touching the gated quantity.
    """
    import dataclasses

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.mocker import MockerConfig, MockerEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.planner import calibration as cal
    from dynamo_tpu.runtime.engine import Context

    slo_ms = float(os.environ.get("BENCH_QUANT_SLO_MS", 25.0))
    isl = _env_int("BENCH_QUANT_ISL", 2048)
    # OSL long enough that decode outlives the staggered prefill span:
    # the gate's window is [last lane's TTFT, first lane's completion],
    # when EVERY lane is decoding — an empty window hard-fails below.
    osl = _env_int("BENCH_QUANT_OSL", 150)
    lanes_bf16 = _env_int("BENCH_QUANT_LANES", 24)
    blocks_bf16 = 3328
    ratio = cal.kv_quant_bytes_ratio()           # ~0.502 (1B layout)
    # Equal HBM budget: the int8 leg spends the SAME KV bytes on ~2×
    # the blocks, and fills them with 2× the decode lanes.
    blocks_int8 = int(blocks_bf16 / ratio)

    base_cfg = EngineConfig(
        model=ModelConfig.tiny_test(),
        block_size=16,
        max_model_len=4096,
        prefill_batch=4,
        dtype="float32",
        sampling_extras=False,
        unified=True,
        unified_token_budget=1024,
        unified_prefill_quantum=256,
        coloc="static",
        itl_slo_ms=slo_ms,  # measurement only (static mode): ITL p95
    )

    async def leg(kv_quant: str | None) -> dict:
        lanes = lanes_bf16 * 2 if kv_quant else lanes_bf16
        cfg = dataclasses.replace(
            base_cfg,
            kv_quant=kv_quant,
            num_blocks=blocks_int8 if kv_quant else blocks_bf16,
            max_num_seqs=lanes,
        )
        sim = MockerConfig(
            prefill_time_per_token_us=2.0,
            prefill_quadratic_us=0.0,
            decode_time_per_step_us=cal.DECODE_TIME_PER_STEP_US,
            decode_time_per_lane_us=cal.DECODE_TIME_PER_LANE_US,
            decode_hbm_gbps=cal.DECODE_HBM_GBPS,
            kv_bytes_per_token=cal.KV_BYTES_PER_TOKEN,
            kv_bytes_ratio=ratio if kv_quant else 1.0,
            vocab_size=base_cfg.model.vocab_size,
        )
        snap: dict = {}
        eng = MockerEngine(cfg, sim, on_metrics=snap.update)
        await eng.start()
        await eng.warmup()
        rng = np.random.default_rng(11)
        firsts: list[float] = []
        done_at: list[float] = []

        async def one():
            req = PreprocessedRequest(
                token_ids=rng.integers(
                    0, cfg.model.vocab_size, isl
                ).tolist(),
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=osl, ignore_eos=True),
            )
            first = None
            async for out in eng.generate(Context(req.to_wire())):
                if out["token_ids"] and first is None:
                    first = time.monotonic()
                    firsts.append(first)
            done_at.append(time.monotonic())

        # Decode-phase window: engine decode-token counter deltas over
        # [last lane's TTFT, first lane's completion] — the span where
        # every lane decodes, so neither prefill stragglers nor the
        # drain tail dilute the measured steady-state decode rate.
        tasks = [asyncio.create_task(one()) for _ in range(lanes)]
        while len(firsts) < lanes:
            await asyncio.sleep(0.01)
        await asyncio.sleep(0.05)  # one metrics flush past the last TTFT
        t0 = time.monotonic()
        d0 = snap.get("unified_step_tokens_decode_total", 0)
        while not done_at:
            await asyncio.sleep(0.01)
        t1 = time.monotonic()
        d1 = snap.get("unified_step_tokens_decode_total", 0)
        await asyncio.gather(*tasks)
        coloc = dict(eng.coloc.snapshot())
        cs = eng.runner.compile_stats
        warm = cs.snapshot()
        await eng.stop()
        if t1 - t0 < 0.2 or d1 <= d0:
            raise RuntimeError(
                f"all-lanes decode window too short ({t1 - t0:.3f}s, "
                f"{d1 - d0} tokens) — raise BENCH_QUANT_OSL so decode "
                "outlives the prefill span"
            )
        decode_tokens = d1 - d0
        return {
            "kv_quant": kv_quant or "bf16",
            "lanes": lanes,
            "num_blocks": cfg.num_blocks,
            "decode_tok_per_s": round(decode_tokens / max(t1 - t0, 1e-9), 1),
            "itl_p95_ms": coloc["itl_p95_ms"],
            "mid_traffic_compiles": cs.mid_traffic_compiles,
            "warmup_programs": warm.get("warmup_programs_total", 0),
        }

    int8 = await leg("int8")
    bf16 = await leg(None)
    ratio_tok = int8["decode_tok_per_s"] / max(bf16["decode_tok_per_s"], 1e-9)
    for name, r in (("int8", int8), ("bf16", bf16)):
        if r["mid_traffic_compiles"]:
            raise RuntimeError(
                f"{name} leg paid {r['mid_traffic_compiles']} mid-traffic "
                "compile(s) — quantization must not leave the warmed "
                "budget ladder"
            )
        if r["warmup_programs"] > 8:
            raise RuntimeError(
                f"{name} leg warmed {r['warmup_programs']} programs "
                "(> 8) — the unified budget ladder grew"
            )
        if r["itl_p95_ms"] > slo_ms:
            raise RuntimeError(
                f"{name} leg decode ITL p95 {r['itl_p95_ms']} ms violates "
                f"the shared {slo_ms} ms SLO — the legs are not at equal "
                "SLO and the throughput ratio is not comparable"
            )
    if ratio_tok < 1.5:
        raise RuntimeError(
            f"int8 decode {int8['decode_tok_per_s']} tok/s is only "
            f"{ratio_tok:.2f}x bf16's {bf16['decode_tok_per_s']} — "
            "the quantized path must deliver >= 1.5x at equal SLO"
        )
    return {
        "slo_ms": slo_ms,
        "isl": isl,
        "osl": osl,
        "hbm_gbps": cal.DECODE_HBM_GBPS,
        "kv_bytes_ratio_int8": round(ratio, 4),
        "int8": int8,
        "bf16": bf16,
        "decode_ratio": round(ratio_tok, 3),
    }


def wquant_equal_budget(
    blocks_bf16: int,
    lanes_bf16: int,
    wratio: float,
    tokens_per_lane: int,
    block_size: int = 16,
) -> tuple[int, int]:
    """Equal simulated-HBM-budget lane math for the BENCH_WQUANT A/B
    (unit-gated by tests/test_weight_quant.py): the shared budget is the
    bf16 leg's weight bytes PLUS its KV bytes; the quantized-weights leg
    spends ``wratio`` of the weight bytes and converts every byte it
    frees into KV blocks — and decode lanes scale with the blocks,
    capped so every lane's full ``tokens_per_lane`` sequence fits
    simultaneously (oversubscribing blocks would serialize lanes and
    collapse the all-lanes-decoding measurement window). Returns
    (blocks, lanes) for the quantized leg."""
    import math

    from dynamo_tpu.planner import calibration as cal

    kv_block_bytes = cal.KV_BYTES_PER_TOKEN * block_size
    budget = cal.WEIGHT_BYTES_PER_STEP + blocks_bf16 * kv_block_bytes
    kv_budget = budget - cal.WEIGHT_BYTES_PER_STEP * wratio
    blocks = int(kv_budget // kv_block_bytes)
    blocks_per_lane = math.ceil(tokens_per_lane / block_size)
    lanes = min(
        round(lanes_bf16 * blocks / blocks_bf16),
        blocks // blocks_per_lane,
    )
    return blocks, lanes


async def _run_wquant() -> dict:
    """Quantized-weights A/B (ci.sh BENCH_WQUANT=1; docs/architecture/
    weight_quant.md): long-context decode through (a) an int8-weights
    unified engine and (b) the bf16-weights baseline at the SAME
    simulated HBM byte budget — weight bytes + KV bytes. The quantized
    leg's weight pass streams at the packed ratio (~0.501 of bf16
    bytes, planner/calibration.py weight_quant_bytes_ratio) and every
    byte it frees becomes KV blocks, so it runs ~1.9x the decode lanes
    (bench.wquant_equal_budget). Both legs keep bf16 KV — this gate
    isolates the WEIGHT precision axis; kv_quant composes on top.
    Pricing: the r04-calibrated weight-bytes term (calibration.py
    WEIGHT_BYTES_PER_STEP / DECODE_HBM_GBPS — the same artifact the
    mocker's flat decode base was re-derived from). Hard asserts:

    - int8-weights decode throughput >= 1.3x the bf16 leg's tok/s/chip;
    - EQUAL SLO: both legs' engine-side decode ITL p95 within
      ``BENCH_WQUANT_SLO_MS``;
    - zero mid-traffic compiles and warmup <= 8 programs per leg (the
      policy is value-level — zero new XLA programs).

    Prefill constants are deliberately cheap (2 µs/token), as in the
    kv_quant gate: the measured quantity is the decode phase.
    """
    import dataclasses

    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.llm.protocols.common import (
        PreprocessedRequest,
        SamplingOptions,
        StopConditions,
    )
    from dynamo_tpu.mocker import MockerConfig, MockerEngine
    from dynamo_tpu.models.config import ModelConfig
    from dynamo_tpu.planner import calibration as cal
    from dynamo_tpu.runtime.engine import Context

    slo_ms = float(os.environ.get("BENCH_WQUANT_SLO_MS", 25.0))
    isl = _env_int("BENCH_WQUANT_ISL", 2048)
    # OSL long enough that decode outlives the staggered prefill span
    # (the gate's window is [last lane's TTFT, first completion]).
    osl = _env_int("BENCH_WQUANT_OSL", 150)
    lanes_bf16 = _env_int("BENCH_WQUANT_LANES", 24)
    blocks_bf16 = 3328
    wratio = cal.weight_quant_bytes_ratio()      # ~0.501 (int8 + f32 row)
    blocks_wq, lanes_wq = wquant_equal_budget(
        blocks_bf16, lanes_bf16, wratio, tokens_per_lane=isl + osl
    )

    base_cfg = EngineConfig(
        model=ModelConfig.tiny_test(),
        block_size=16,
        max_model_len=4096,
        prefill_batch=4,
        dtype="float32",
        sampling_extras=False,
        unified=True,
        unified_token_budget=1024,
        unified_prefill_quantum=256,
        coloc="static",
        itl_slo_ms=slo_ms,  # measurement only (static mode): ITL p95
    )

    async def leg(weight_quant: str | None) -> dict:
        cfg = dataclasses.replace(
            base_cfg,
            weight_quant=weight_quant,
            num_blocks=blocks_wq if weight_quant else blocks_bf16,
            max_num_seqs=lanes_wq if weight_quant else lanes_bf16,
        )
        lanes = cfg.max_num_seqs
        sim = MockerConfig(
            prefill_time_per_token_us=2.0,
            prefill_quadratic_us=0.0,
            decode_time_per_step_us=cal.DECODE_TIME_PER_STEP_US,
            decode_time_per_lane_us=cal.DECODE_TIME_PER_LANE_US,
            decode_hbm_gbps=cal.DECODE_HBM_GBPS,
            kv_bytes_per_token=cal.KV_BYTES_PER_TOKEN,
            kv_bytes_ratio=1.0,                  # bf16 KV on BOTH legs
            weight_bytes_per_step=cal.WEIGHT_BYTES_PER_STEP,
            weight_bytes_ratio=wratio if weight_quant else 1.0,
            vocab_size=base_cfg.model.vocab_size,
        )
        snap: dict = {}
        eng = MockerEngine(cfg, sim, on_metrics=snap.update)
        await eng.start()
        await eng.warmup()
        rng = np.random.default_rng(11)
        firsts: list[float] = []
        done_at: list[float] = []

        async def one():
            req = PreprocessedRequest(
                token_ids=rng.integers(
                    0, cfg.model.vocab_size, isl
                ).tolist(),
                sampling=SamplingOptions(temperature=0.0),
                stop=StopConditions(max_tokens=osl, ignore_eos=True),
            )
            first = None
            async for out in eng.generate(Context(req.to_wire())):
                if out["token_ids"] and first is None:
                    first = time.monotonic()
                    firsts.append(first)
            done_at.append(time.monotonic())

        # Decode-phase window: engine decode-token counter deltas over
        # [last lane's TTFT, first lane's completion] — the span where
        # every lane decodes (same law as the kv_quant gate).
        tasks = [asyncio.create_task(one()) for _ in range(lanes)]
        while len(firsts) < lanes:
            await asyncio.sleep(0.01)
        await asyncio.sleep(0.05)  # one metrics flush past the last TTFT
        t0 = time.monotonic()
        d0 = snap.get("unified_step_tokens_decode_total", 0)
        while not done_at:
            await asyncio.sleep(0.01)
        t1 = time.monotonic()
        d1 = snap.get("unified_step_tokens_decode_total", 0)
        await asyncio.gather(*tasks)
        coloc = dict(eng.coloc.snapshot())
        cs = eng.runner.compile_stats
        warm = cs.snapshot()
        await eng.stop()
        if t1 - t0 < 0.2 or d1 <= d0:
            raise RuntimeError(
                f"all-lanes decode window too short ({t1 - t0:.3f}s, "
                f"{d1 - d0} tokens) — raise BENCH_WQUANT_OSL so decode "
                "outlives the prefill span"
            )
        decode_tokens = d1 - d0
        return {
            "weight_quant": weight_quant or "bf16",
            "lanes": lanes,
            "num_blocks": cfg.num_blocks,
            "decode_tok_per_s": round(decode_tokens / max(t1 - t0, 1e-9), 1),
            "itl_p95_ms": coloc["itl_p95_ms"],
            "mid_traffic_compiles": cs.mid_traffic_compiles,
            "warmup_programs": warm.get("warmup_programs_total", 0),
        }

    wq = await leg("int8")
    bf16 = await leg(None)
    ratio_tok = wq["decode_tok_per_s"] / max(bf16["decode_tok_per_s"], 1e-9)
    for name, r in (("int8-weights", wq), ("bf16", bf16)):
        if r["mid_traffic_compiles"]:
            raise RuntimeError(
                f"{name} leg paid {r['mid_traffic_compiles']} mid-traffic "
                "compile(s) — the weight-quant policy must not leave the "
                "warmed budget ladder"
            )
        if r["warmup_programs"] > 8:
            raise RuntimeError(
                f"{name} leg warmed {r['warmup_programs']} programs "
                "(> 8) — the unified budget ladder grew"
            )
        if r["itl_p95_ms"] > slo_ms:
            raise RuntimeError(
                f"{name} leg decode ITL p95 {r['itl_p95_ms']} ms violates "
                f"the shared {slo_ms} ms SLO — the legs are not at equal "
                "SLO and the throughput ratio is not comparable"
            )
    if ratio_tok < 1.3:
        raise RuntimeError(
            f"int8-weights decode {wq['decode_tok_per_s']} tok/s is only "
            f"{ratio_tok:.2f}x bf16's {bf16['decode_tok_per_s']} — "
            "the quantized-weights path must deliver >= 1.3x at equal "
            "simulated HBM budget"
        )
    return {
        "slo_ms": slo_ms,
        "isl": isl,
        "osl": osl,
        "hbm_gbps": cal.DECODE_HBM_GBPS,
        "weight_bytes_ratio_int8": round(wratio, 4),
        "weight_bytes_per_step": cal.WEIGHT_BYTES_PER_STEP,
        "int8_weights": wq,
        "bf16": bf16,
        "decode_ratio": round(ratio_tok, 3),
    }


def OVERLOAD_SHED_SNAPSHOT() -> int:
    from dynamo_tpu.utils.deadline import OVERLOAD

    return OVERLOAD.shed_total


def main() -> None:
    if os.environ.get("BENCH_CHAOS"):
        # Self-healing-fleet proof (docs/architecture/failure_model.md
        # "Mid-stream failover"): a seeded randomized chaos schedule —
        # mid-stream worker kills, a bus partition, dropped KV frames —
        # over a >=4-worker mocker fleet. HARD-FAILS unless every
        # request resolves (zero hangs under the watchdog), failover
        # succeeds whenever healthy capacity remains, greedy streams
        # stay byte-identical across kills, and the planner's crash
        # path heals the fleet back to target size.
        from benchmarks.chaos_bench import run_chaos, run_gates

        report = asyncio.run(run_chaos(
            seed=int(os.environ.get("BENCH_CHAOS_SEED", 1234)),
            decode_workers=_env_int("BENCH_CHAOS_WORKERS", 4),
            requests=_env_int("BENCH_CHAOS_REQUESTS", 24),
        ))
        print(
            json.dumps(
                {
                    "metric": "chaos_fleet_mocker",
                    "value": report["failover_success_total"],
                    "unit": (
                        f"successful mid-stream failovers "
                        f"({report['ok']}/{report['requests']} requests "
                        "ok, fleet healed to target)"
                    ),
                    "extras": report,
                }
            )
        )
        run_gates(report)
        return
    if os.environ.get("BENCH_G4"):
        # G4 peer-tier proof (docs/architecture/kvbm_g4.md): a cold
        # worker PULLS a fleet peer's packed KV rows instead of
        # recomputing them (priced against planner/calibration's
        # recorded link), pre-placement warms a joining worker before
        # traffic reaches it, and a peer killed mid-pull degrades to
        # local recompute. HARD-FAILS unless the pulled TTFT beats
        # recompute >=2x at the calibrated link rate, the pre-placed
        # join reaches steady-state warm-hit rate >=2x faster (in
        # requests) than the cold join, and the mid-pull kill completes
        # byte-identically via recompute with zero hangs.
        from benchmarks.g4_bench import run_g4, run_gates as g4_gates

        report = asyncio.run(run_g4(
            seed=int(os.environ.get("BENCH_G4_SEED", 20260806)),
            prefixes=_env_int("BENCH_G4_PREFIXES", 8),
            join_requests=_env_int("BENCH_G4_REQUESTS", 24),
        ))
        failures = g4_gates(report)
        print(
            json.dumps(
                {
                    "metric": "g4_peer_tier_mocker",
                    "value": report["pull"]["speedup"],
                    "unit": (
                        "x TTFT (pull vs recompute, calibrated link; "
                        f"pre-placed join "
                        f"{report['preplace']['speedup']}x faster to "
                        "steady state, mid-pull peer kill degraded "
                        "cleanly)"
                    ),
                    "extras": report,
                }
            )
        )
        if failures:
            print(
                "BENCH FAILED: G4 gates:\n  " + "\n  ".join(failures),
                file=sys.stderr,
            )
            raise SystemExit(1)
        return
    if os.environ.get("BENCH_INTEGRITY"):
        # End-to-end KV-block integrity proof (docs/architecture/
        # integrity.md): a seeded randomized corruption schedule at all
        # five trust-boundary seams — G2 onboard, G3 read/scrub, G4
        # pull, disagg tcp, disagg native — across multiple seeds.
        # HARD-FAILS unless every injected corruption is detected and
        # attributed to the right tier, every request resolves through
        # degrade-to-recompute with ZERO stream deviations from the
        # deterministic closed form, and the envelope's measured CRC
        # cost stays under 2% of serve wall time.
        from benchmarks.chaos_bench import run_integrity, run_integrity_gates

        base = int(os.environ.get("BENCH_INTEGRITY_SEED", 20260806))
        n_seeds = _env_int("BENCH_INTEGRITY_SEEDS", 3)
        reports, failures = [], []
        for s in range(base, base + n_seeds):
            report = asyncio.run(run_integrity(seed=s))
            reports.append(report)
            failures += [f"seed {s}: {f}" for f in run_integrity_gates(report)]
        detected = sum(
            r[leg]["detected"]
            for r in reports
            for leg in (
                "host_onboard", "disk_scrub", "peer_pull",
                "disagg_tcp", "disagg_native",
            )
        )
        print(
            json.dumps(
                {
                    "metric": "kv_integrity_mocker",
                    "value": detected,
                    "unit": (
                        f"corruptions detected across {n_seeds} seed(s) "
                        "x 5 seams (zero stream deviations, overhead "
                        f"{reports[-1]['overhead']['overhead_fraction']:.4%}"
                        " of serve time)"
                    ),
                    "extras": {"seeds": reports},
                }
            )
        )
        if failures:
            print(
                "BENCH FAILED: integrity gates:\n  " + "\n  ".join(failures),
                file=sys.stderr,
            )
            raise SystemExit(1)
        return
    if os.environ.get("BENCH_INGRESS"):
        # Million-user ingress replay (docs/architecture/
        # ingress_scale.md; ROADMAP #4): >=100k requests of a Mooncake-
        # style trace through >=2 router replicas over >=8 mocker
        # workers, with a mid-replay replica kill + rejoin and an
        # overload burst. HARD-FAILS unless zero requests are lost or
        # hung through the kill, per-class p99 TTFT holds its SLO with
        # zero cross-class inversions, the burst sheds batch (not
        # interactive) with load-proportional Retry-After, rejoin
        # staleness is measured, and route_audit.py's predicted-vs-
        # actual error bound holds across ALL replicas.
        from benchmarks.ingress_bench import run_gates as ingress_gates
        from benchmarks.ingress_bench import run_ingress

        report = asyncio.run(run_ingress(
            requests=_env_int("BENCH_INGRESS_REQUESTS", 100_000),
            workers=_env_int("BENCH_INGRESS_WORKERS", 8),
            replicas=_env_int("BENCH_INGRESS_REPLICAS", 2),
            seed=int(os.environ.get("BENCH_INGRESS_SEED", 20260805)),
        ))
        failures = ingress_gates(report)
        # The full prefix curve + staleness series are bulky; keep the
        # one-line metric digestible and ship the full report as extras.
        print(
            json.dumps(
                {
                    "metric": "ingress_replay_mocker",
                    "value": report["requests"],
                    "unit": (
                        f"requests replayed over {report['replicas']} "
                        f"router replicas / {report['workers']} workers "
                        f"(interactive p99 TTFT "
                        f"{report['ttft_p99_ms']['interactive']} ms, "
                        f"{report['burst'].get('batch_shed', 0)} batch "
                        "429s absorbed)"
                    ),
                    "extras": report,
                }
            )
        )
        if failures:
            print(
                "BENCH FAILED: ingress gates:\n  " + "\n  ".join(failures),
                file=sys.stderr,
            )
            raise SystemExit(1)
        return
    if os.environ.get("BENCH_XPYD"):
        # Fleet projection (ROADMAP #4): the calibrated-mocker xPyD
        # simulation (planner/simulate.py, constants pinned to the
        # recorded r04/r05 runs by planner/calibration.py). HARD-FAILS
        # unless the calibration reproduces the r04 headline within
        # 10%, the 2P1D topology beats the 1-worker aggregated baseline
        # on the prefill-heavy replay, and a decode scale-down mid-run
        # drops zero requests (BENCHMARKS.md "xPyD projection").
        from benchmarks.xpyd_bench import run_gates

        report = run_gates()
        print(
            json.dumps(
                {
                    "metric": "xpyd_projection",
                    "value": report["headline_ratio"],
                    "unit": (
                        "x (2P1D over equal-chip SLO-holding co-located "
                        "fleet, calibrated-mocker sim)"
                    ),
                    "extras": report,
                }
            )
        )
        if not all(report["gates"].values()):
            print(
                f"BENCH FAILED: xPyD gates {report['gates']}",
                file=sys.stderr,
            )
            raise SystemExit(1)
        return
    if os.environ.get("BENCH_ROUTE_AUDIT"):
        # KV-observatory leg: multi-worker mocker behind the KV-aware
        # router with the trace capture on. Hard-fails unless every
        # request is routed+audited, the hit-rate plane carries both
        # predicted and actual kinds, the indexer measured event lag,
        # and follow-up turns actually reused KV. ci.sh then closes the
        # loop with benchmarks/route_audit.py --assert on the capture.
        r = asyncio.run(_run_route_audit())
        print(
            json.dumps(
                {
                    "metric": "route_audit_mocker",
                    "value": r["turn2_with_predicted_overlap"],
                    "unit": (
                        f"of {r['sessions']} follow-ups routed with "
                        "predicted overlap (loop closed by route_audit.py)"
                    ),
                    "extras": r,
                }
            )
        )
        return
    if os.environ.get("BENCH_QUANT"):
        # Quantized-KV A/B (docs/architecture/kv_quant.md): int8 KV at
        # the SAME simulated HBM byte budget must deliver >= 1.5x the
        # bf16 leg's decode tok/s/chip at equal ITL SLO, with zero
        # mid-traffic compiles and the unchanged <= 8-program budget
        # ladder. Pricing: the r04-calibrated decode HBM-bytes term.
        r = asyncio.run(_run_quant())
        print(
            json.dumps(
                {
                    "metric": "kv_quant_ab_mocker",
                    "value": r["decode_ratio"],
                    "unit": (
                        "x (int8 decode tok/s/chip over bf16 at equal "
                        "SLO, r04-calibrated HBM pricing)"
                    ),
                    "extras": r,
                }
            )
        )
        return
    if os.environ.get("BENCH_WQUANT"):
        # Quantized-weights A/B (docs/architecture/weight_quant.md):
        # int8 weights at the SAME simulated HBM byte budget (weight
        # bytes + KV bytes) convert the freed weight HBM into KV lanes
        # and must deliver >= 1.3x the bf16 leg's decode tok/s/chip at
        # equal ITL SLO, with zero mid-traffic compiles and the
        # unchanged <= 8-program budget ladder. Pricing: the
        # r04-calibrated weight-bytes term.
        r = asyncio.run(_run_wquant())
        print(
            json.dumps(
                {
                    "metric": "wquant_ab_mocker",
                    "value": r["decode_ratio"],
                    "unit": (
                        "x (int8-weights decode tok/s/chip over bf16 at "
                        "equal simulated HBM budget and SLO, "
                        "r04-calibrated weight-bytes pricing)"
                    ),
                    "extras": r,
                }
            )
        )
        return
    if os.environ.get("BENCH_SPEC"):
        # Unified speculative-decode A/B (ROADMAP #2's last leg):
        # accepting-draft spec throughput must beat both the unified
        # non-spec leg and the recorded phased-spec baseline, warmup
        # must stay within the budget ladder (spec adds zero programs),
        # and the auto-gate must stay free-when-losing. Hard-fails
        # otherwise.
        r = asyncio.run(_run_spec())
        print(
            json.dumps(
                {
                    "metric": "spec_ab_mocker",
                    "value": r["speedup_vs_plain"],
                    "unit": (
                        "x (unified spec tok/s over unified non-spec at "
                        "accepting-draft settings; "
                        f"{r['speedup_vs_phased_spec']}x over the "
                        "recorded phased-spec baseline)"
                    ),
                    "extras": r,
                }
            )
        )
        return
    if os.environ.get("BENCH_COLOC"):
        # Co-location A/B (ROADMAP #3): co-located unified serving must
        # hold decode ITL p95 within the SLO through an ISL3000-style
        # prefill burst while matching the aggregated baseline's
        # prefill throughput. Hard-fails otherwise.
        r = asyncio.run(_run_coloc())
        print(
            json.dumps(
                {
                    "metric": "coloc_ab_mocker",
                    "value": r["prefill_ratio"],
                    "unit": (
                        "x (co-located prefill tok/s over aggregated, "
                        "decode ITL p95 held within SLO)"
                    ),
                    "extras": r,
                }
            )
        )
        return
    if os.environ.get("BENCH_OVERLOAD"):
        # Overload-safety smoke: offered load >> capacity must shed with
        # 429 + Retry-After, zero hangs, bounded admitted latency.
        r = asyncio.run(_run_overload())
        print(
            json.dumps(
                {
                    "metric": "overload_smoke",
                    "value": r["shed_429"],
                    "unit": "requests shed with 429 (offered >> capacity)",
                    "extras": r,
                }
            )
        )
        return
    if os.environ.get("BENCH_KVSP"):
        # kv_sp striped-scan scaling microbench (benchmarks/kv_sp_bench.py)
        from benchmarks.kv_sp_bench import main as kvsp_main

        print(json.dumps(kvsp_main()))
        return
    if os.environ.get("BENCH_8B"):
        # 8B device-efficiency probe (benchmarks/eff8b_bench.py)
        from benchmarks.eff8b_bench import main as eff_main

        print(json.dumps(eff_main()))
        return
    if os.environ.get("BENCH_ROUTER"):
        # KV-aware vs random routing A/B (benchmarks/router_bench.py;
        # reference bar: 3x TTFT, architecture.md:86-91)
        from benchmarks.router_bench import main as router_main

        print(json.dumps(router_main()))
        return
    if os.environ.get("BENCH_OFFLOAD"):
        # Host-DRAM KV offload A/B (benchmarks/offload_bench.py; reference
        # bar: +40% TTFT, architecture.md:95-99)
        from benchmarks.offload_bench import main as offload_main

        print(json.dumps(offload_main()))
        return
    if os.environ.get("BENCH_DISAGG"):
        r = asyncio.run(_run_disagg())
        print(
            json.dumps(
                {
                    "metric": f"disagg_vs_agg_isl{ISL}_osl{OSL}",
                    "value": r["ratio_tok_per_s"],
                    "unit": "x (disagg tok/s over aggregated; ref bar +30% multi-node)",
                    "vs_baseline": r["ratio_tok_per_s"],
                    "extras": r,
                }
            )
        )
        return
    ab = None
    if os.environ.get("BENCH_AB"):
        ab = _run_ab("DYNAMO_TPU_PALLAS", [("pallas", "1"), ("jnp", "0")])
    elif os.environ.get("BENCH_QUANT_AB"):
        ab = _run_ab("DYNAMO_TPU_QUANT", [("int8", "int8"), ("bf16", "")])
    elif os.environ.get("BENCH_SPEC_AB"):
        # Speculative decode A/B (VERDICT r04 weak #6): same scenario with
        # prompt-lookup drafting (auto-gated) vs plain decode.
        ab = _run_ab("BENCH_SPEC_K", [("spec4", "4"), ("plain", "0")])
    if ab is not None:
        win = max(ab, key=lambda k: ab[k]["value"])
        result = dict(ab[win])
        result["extras"] = dict(result.get("extras", {}))
        result["extras"]["ab"] = {
            k: {
                "tok_per_s": v["value"],
                "p50_ttft_ms": v["extras"]["p50_ttft_ms"],
                "decode_step_ms": v["extras"].get("decode_step_ms"),
            }
            for k, v in ab.items()
        }
        result["extras"]["ab_winner"] = win
        print(json.dumps(result))
        return

    r = asyncio.run(_run_e2e())
    print(
        json.dumps(
            {
                "metric": ("decode_throughput_mocker_smoke" if MOCKER
                           else "decode_throughput_tiny_smoke")
                if SMOKE or MOCKER
                else (
                    "decode_throughput_"
                    + {"llama32_1b": "1b", "llama31_8b": "8b"}.get(
                        os.environ.get("BENCH_MODEL", "llama32_1b"),
                        os.environ.get("BENCH_MODEL", "model"),
                    )
                    + f"_isl{ISL}_osl{OSL}"
                ),
                "value": r["tok_per_s"],
                "unit": "tok/s/chip",
                "vs_baseline": round(r["tok_per_s"] / 100.0, 3),
                "extras": {
                    k: v for k, v in r.items() if k != "tok_per_s"
                }
                | {"num_requests": NUM_REQ, "isl": ISL, "osl": OSL}
                | (
                    {"trace_capture": os.environ["DYNTPU_TRACE"]}
                    if TRACE
                    else {}
                ),
            }
        )
    )


if __name__ == "__main__":
    main()

#!/usr/bin/env bash
# Build the dynamo-tpu serving image (reference analogue:
# container/build.sh). One image serves every component role.
set -euo pipefail

TAG="${1:-dynamo-tpu:latest}"
cd "$(dirname "$0")/.."
exec docker build -f container/Dockerfile -t "$TAG" .

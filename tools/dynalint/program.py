"""dynaflow program model: the whole-program side of dynalint.

The per-file rules (DT001–DT011) see one AST at a time; the laws PRs
12–18 accreted are *interprocedural* — a tier-crossing write in
`block_manager/storage.py` is legal only because a caller three frames
up in `manager.py` stamped the envelope, and a fault point registered in
`utils/faults.py` is only proven if some test in `tests/` arms it. This
module builds the project-wide context those rules (DT012–DT016) reason
over:

- **file set**: every Python file in the lint universe (the default lint
  targets) plus the evidence-only extras (`tests/` — scanned for fault
  arms and jit roots, never linted) parsed ONCE into the same
  `FileContext` objects the per-file pass reuses;
- **module table**: repo-relative path ⇄ dotted module name
  (`dynamo_tpu/block_manager/manager.py` ⇄
  `dynamo_tpu.block_manager.manager`);
- **symbol table**: every function/method, keyed `path::qualname`
  (`FunctionInfo`), with terminal-name and dotted-name indexes for the
  call-graph resolver;
- **import graph**: which project files each file imports (reachability
  over modules, used by tests and future rules).

`ProgramContext.from_sources` builds the same structure from an
in-memory `{path: source}` dict so rule fixtures in
tests/test_dynalint.py can exercise interprocedural rules without a
checkout-shaped tmp tree.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from pathlib import Path

from tools.dynalint.core import FileContext

#: Evidence-only roots: parsed into the program (fault-arm lists, jit
#: roots) but never linted — findings may cite them, not anchor in them.
EVIDENCE_TARGETS = ("tests",)

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def module_name(path: str) -> str:
    """Dotted module name of a repo-relative posix path
    (`a/b/c.py` -> `a.b.c`, `a/b/__init__.py` -> `a.b`)."""
    p = path[:-3] if path.endswith(".py") else path
    parts = [s for s in p.split("/") if s]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


@dataclass(frozen=True)
class FunctionInfo:
    """One function/method in the project symbol table."""

    path: str          # repo-relative posix path
    qualname: str      # "Class.method", "func", "outer.inner"
    node: ast.AST      # the def node
    class_name: str    # enclosing class ("" at module level)
    lineno: int

    @property
    def id(self) -> str:
        return f"{self.path}::{self.qualname}"

    @property
    def terminal(self) -> str:
        return self.qualname.rsplit(".", 1)[-1]

    @property
    def dotted(self) -> str:
        """Fully dotted import name: `module.Class.method`."""
        return f"{module_name(self.path)}.{self.qualname}"


@dataclass
class ProgramContext:
    """Everything the interprocedural rules need, parsed once per run."""

    root: Path
    files: dict[str, FileContext] = field(default_factory=dict)
    #: dotted module name -> repo-relative path (project modules only)
    modules: dict[str, str] = field(default_factory=dict)
    #: function id ("path::qualname") -> FunctionInfo
    functions: dict[str, FunctionInfo] = field(default_factory=dict)
    #: terminal name -> [function ids] (the inheritance over-approx index)
    by_terminal: dict[str, list[str]] = field(default_factory=dict)
    #: dotted import name ("module.Class.method") -> function id
    by_dotted: dict[str, str] = field(default_factory=dict)
    #: path -> set of project paths it imports
    import_graph: dict[str, set[str]] = field(default_factory=dict)
    #: scratch space for rules that cache an expensive derived model
    #: (call graph, fault model) across per-file check calls.
    cache: dict[str, object] = field(default_factory=dict)

    # -- construction -------------------------------------------------------
    def add_file(self, ctx: FileContext) -> None:
        self.files[ctx.path] = ctx
        self.modules[module_name(ctx.path)] = ctx.path
        self._collect_functions(ctx)

    def finish(self) -> None:
        """Resolve the import graph once every module is known."""
        for path, ctx in self.files.items():
            deps: set[str] = set()
            for dotted in ctx.imports.values():
                target = self._module_of(dotted)
                if target is not None and target != path:
                    deps.add(target)
            self.import_graph[path] = deps

    def _module_of(self, dotted: str) -> str | None:
        """Project path a dotted import resolves to, trying the longest
        module prefix first (`a.b.sym` -> module `a.b` when `a.b.sym` is
        a from-import of a symbol rather than a module)."""
        parts = dotted.split(".")
        for n in range(len(parts), 0, -1):
            cand = ".".join(parts[:n])
            if cand in self.modules:
                return self.modules[cand]
        return None

    def _collect_functions(self, ctx: FileContext) -> None:
        def collect(node: ast.AST, stack: list[str], cls: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _FUNC_NODES):
                    qual = ".".join(stack + [child.name])
                    info = FunctionInfo(
                        ctx.path, qual, child, cls, child.lineno
                    )
                    self.functions[info.id] = info
                    self.by_terminal.setdefault(child.name, []).append(
                        info.id
                    )
                    self.by_dotted.setdefault(info.dotted, info.id)
                    collect(child, stack + [child.name], cls)
                elif isinstance(child, ast.ClassDef):
                    collect(child, stack + [child.name], child.name)
                else:
                    collect(child, stack, cls)

        collect(ctx.tree, [], "")

    # -- queries ------------------------------------------------------------
    def function(self, fid: str) -> FunctionInfo | None:
        return self.functions.get(fid)

    def resolve_dotted(self, dotted: str) -> str | None:
        """Function id for a fully dotted name, tolerating the
        from-import shape where the module is named by a prefix."""
        return self.by_dotted.get(dotted)

    def find_method(self, qualname: str) -> list[str]:
        """Function ids whose qualname matches `Class.method` (or a bare
        function name) anywhere in the project — the lookup the
        doc-grounded rules use for names like
        `KvBlockManager.match_host`."""
        return [
            fid for fid, info in self.functions.items()
            if info.qualname == qualname
        ]

    def methods_of_class(self, class_name: str) -> list[str]:
        return [
            fid for fid, info in self.functions.items()
            if info.class_name == class_name
        ]

    def imports_of(self, path: str) -> set[str]:
        return self.import_graph.get(path, set())

    def read_doc(self, rel: str) -> str | None:
        """A non-Python evidence file (architecture doc) by repo-relative
        path; None when absent (fixture program / partial checkout)."""
        p = self.root / rel
        try:
            return p.read_text()
        except OSError:
            return None

    # -- builders -----------------------------------------------------------
    @staticmethod
    def from_sources(
        sources: dict[str, str], root: Path | None = None
    ) -> "ProgramContext":
        """Fixture builder: parse `{repo-relative path: source}`.
        Files that do not parse are skipped (the per-file pass reports
        the syntax error)."""
        prog = ProgramContext(root=root or Path("."))
        for path, source in sources.items():
            try:
                tree = ast.parse(source, filename=path)
            except SyntaxError:
                continue
            prog.add_file(FileContext(path=path, source=source, tree=tree))
        prog.finish()
        return prog


def build_program(
    targets: list[str],
    root: Path,
    parsed: dict[str, tuple[str, ast.AST]] | None = None,
) -> ProgramContext:
    """Build the program over `targets` plus the evidence-only extras.
    `parsed` lets the caller (lint_paths) share already-parsed files so
    each file is read and parsed exactly once per run."""
    from tools.dynalint.core import _rel, iter_python_files

    prog = ProgramContext(root=root)
    universe = list(targets)
    for extra in EVIDENCE_TARGETS:
        if extra not in universe and (root / extra).is_dir():
            universe.append(extra)
    for f in iter_python_files(universe, root):
        rel = _rel(f, root)
        if rel in prog.files:
            continue
        if parsed is not None and rel in parsed:
            source, tree = parsed[rel]
        else:
            try:
                source = f.read_text()
                tree = ast.parse(source, filename=rel)
            except (OSError, SyntaxError):
                continue
        prog.add_file(FileContext(path=rel, source=source, tree=tree))
    prog.finish()
    return prog

"""dynalint — project-native AST analysis for async/TPU serving invariants.

Run with `python -m tools.dynalint`; see docs/development/static_analysis.md.
"""

from tools.dynalint.baseline import Baseline, diff_against
from tools.dynalint.core import (
    Finding,
    Rule,
    all_rules,
    lint_paths,
    lint_source,
    register,
)

__all__ = [
    "Baseline",
    "Finding",
    "Rule",
    "all_rules",
    "diff_against",
    "lint_paths",
    "lint_source",
    "register",
]

"""dynarace thread-context model: which execution context runs each function.

The serving stack deliberately spans several execution contexts in one
process — the dedicated engine dispatch thread (`TpuEngine._engine_loop`),
the asyncio event loop (HTTP handlers, routers, pumps), `asyncio.to_thread`
executor workers (block transfers, blocking waits), and ad-hoc daemon
threads (operator watch pumps). Rust's compiler enforces Send/Sync across
that split in the source framework; here the equivalent guarantee is this
model plus the DT007–DT010 rules built on it.

A function's context set is derived, in priority order, from:

1. An explicit annotation on (or immediately above) its ``def`` line::

       def record(self, event):  # dynarace: context[engine, loop]

2. The seed registry below — the known entry-point seams, so the analyzer
   is useful on the existing tree without annotating everything.
3. ``async def`` ⇒ ``loop`` (coroutines execute on the event loop).
4. Intra-file spawn inference: a function passed as ``target=`` to
   ``threading.Thread(...)`` gets ``thread:<name>``; a function passed to
   ``asyncio.to_thread(...)`` / ``loop.run_in_executor(...)`` gets
   ``worker``.

Contexts then PROPAGATE through the intra-file call graph: a sync helper
called from the engine loop runs on the engine thread; one called from
both an async handler and the engine loop runs in both contexts (exactly
the functions DT007 cares about). Propagation never enters an ``async
def`` — calling a coroutine function from a thread produces a coroutine
object, not execution in that thread.

Functions that end up with no known context are ignored by the rules —
the model is deliberately precise-over-complete, so every finding is
worth reading.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field

from tools.dynalint.core import FileContext

#: Canonical context labels (annotations may also introduce new ones —
#: e.g. per-thread labels like ``thread:pump`` from spawn inference).
LOOP = "loop"          # the asyncio event loop
ENGINE = "engine"      # the dedicated TPU engine dispatch thread
WORKER = "worker"      # asyncio.to_thread / run_in_executor pool threads
CONTROL = "control"    # control-plane pump / operator reconcile

_ANNOTATION_RE = re.compile(
    r"#\s*dynarace:\s*context\[([A-Za-z0-9_:\-,\s]+)\]"
)

#: Seed registry: (repo-relative path) -> {function qualname -> contexts}.
#: These are the known entry-point seams; everything else is reached by
#: annotation, async-def inference, spawn inference, or call-graph
#: propagation from these.
SEED_CONTEXTS: dict[str, dict[str, tuple[str, ...]]] = {
    "dynamo_tpu/engine/engine.py": {
        # The dispatch loop IS the engine thread (started in start()).
        "TpuEngine._engine_loop": (ENGINE,),
        # First runner build + device allocation run on a to_thread worker.
        "TpuEngine._build_runner": (WORKER,),
        # Read by /health + /metrics handlers on the asyncio loop.
        "TpuEngine.readiness": (LOOP,),
    },
    "dynamo_tpu/engine/compile_cache.py": {
        # observe() wraps every jitted dispatch: the engine thread in a
        # single-process engine, executor threads under the stepcast
        # follower (parallel/stepcast.py runs runner ops via to_thread).
        "CompileStats.observe": (ENGINE, WORKER),
        # Scraped by readiness()/metrics callbacks on the asyncio loop.
        "CompileStats.snapshot": (LOOP, ENGINE),
        "ShapeManifest.record": (ENGINE, WORKER),
        "PersistentCompileCache.note": (ENGINE, WORKER),
    },
    "dynamo_tpu/engine/flight_recorder.py": {
        "FlightRecorder.note_step": (ENGINE,),
        "FlightRecorder.note_event": (ENGINE,),
        # /debug/steps handler reads the ring from the loop.
        "FlightRecorder.snapshot": (LOOP,),
    },
    "dynamo_tpu/utils/recorder.py": {
        # The tracer streams capture records from both the engine
        # dispatch thread and the asyncio thread (PR 9's litigated seam).
        "Recorder.record": (ENGINE, LOOP),
    },
    "dynamo_tpu/utils/tracing.py": {
        # Span open/close happens on the engine hot path AND in HTTP
        # handlers; render()/snapshot() on scrapes from the loop.
        "Tracer.mark": (ENGINE, LOOP),
        "Tracer.span_begin": (ENGINE, LOOP),
        "Tracer.span_end": (ENGINE, LOOP),
        "Tracer.add_span": (ENGINE, LOOP),
        "Tracer.mark_if_active": (ENGINE, LOOP),
        "Tracer.finish": (ENGINE, LOOP),
        "Tracer.export": (ENGINE, LOOP),
        "Tracer.render": (LOOP,),
        "Tracer.snapshot": (LOOP,),
    },
    "dynamo_tpu/block_manager/offload.py": {
        # Blocking byte moves run on to_thread workers so the loop never
        # blocks on PCIe/disk; the shared pool lock serializes them with
        # the engine thread's match/offer.
        "OffloadManager._store": (WORKER,),
        "OffloadManager._onboard_blocking": (WORKER,),
    },
    "dynamo_tpu/block_manager/manager.py": {
        # match/offer are driven from the engine thread; stats() is the
        # deliberately lock-free telemetry probe on the asyncio loop.
        "KvBlockManager.match_host": (ENGINE,),
        "KvBlockManager.offer": (ENGINE,),
        "KvBlockManager.stats": (LOOP,),
        # The scrubber's verify slice runs via asyncio.to_thread (the
        # _scrub_loop pacer stays on the loop); tests also call it
        # directly — the manager lock is the shared-state contract.
        "KvBlockManager.scrub_tick": (WORKER, LOOP),
    },
    "dynamo_tpu/block_manager/integrity.py": {
        # The process-wide corruption ledger is written from EVERY
        # verification seam: the engine thread's match_host, to_thread
        # workers (G3 promotion, scrub ticks, sidecar recovery), and
        # the asyncio loop's wire receivers (G4 pulls, disagg frames).
        # snapshot() feeds the loop-side stats probe and the engine
        # thread's metrics flush; its own lock is the contract.
        "IntegrityStats.note_failure": (ENGINE, WORKER, LOOP),
        "IntegrityStats.note_scrub": (WORKER, LOOP),
        "IntegrityStats.snapshot": (LOOP, ENGINE),
    },
    "dynamo_tpu/block_manager/storage.py": {
        # Crash-consistent sidecar writes happen under the offload
        # worker's _store (and scrub quarantines, also on workers); the
        # pool lock serializes them with the engine thread.
        "DiskStorage.record_block": (WORKER,),
        "DiskStorage.drop_block": (WORKER, LOOP),
    },
    "dynamo_tpu/llm/http_service.py": {
        # aiohttp handlers are coroutines — async-def inference covers
        # them; listed here only to anchor the seam in one place.
    },
    "dynamo_tpu/llm/kv_router/audit.py": {
        # Routers record decisions on the loop; /metrics scrapes (loop)
        # and worker-side HealthServer probes read gauges.
        "RouteObservatory.record": (LOOP,),
        "RouteObservatory.gauges": (LOOP,),
        "RouteObservatory.snapshot": (LOOP,),
    },
    "dynamo_tpu/llm/kv_router/publisher.py": {
        # Engine-side fire-and-forget publishes cross from the engine
        # thread onto the loop (the call_soon_threadsafe seam).
        "KvEventPublisher.publish": (ENGINE,),
        "KvEventPublisher.publish_hit_actual": (ENGINE,),
    },
    "dynamo_tpu/runtime/failover.py": {
        # The failover loop runs on the asyncio loop (ingress-side);
        # the FAILOVER counters are ALSO read by the engine thread's
        # metrics flush (engine.py _flush_side_channels) and by scrape
        # handlers — the registry's lock is the shared-state contract.
        "FailoverStats.note_attempt": (LOOP,),
        "FailoverStats.note_success": (LOOP,),
        "FailoverStats.note_marked_dead": (LOOP,),
        "FailoverStats.snapshot": (LOOP, ENGINE),
        "FailoverStats.render_labeled": (LOOP,),
    },
    "benchmarks/chaos_bench.py": {
        # Pure asyncio driver: async-def inference covers the harness;
        # listed to anchor the chaos seam in the registry.
    },
    "benchmarks/ingress_bench.py": {
        # Pure asyncio driver (the 100k replicated-ingress replay):
        # async-def inference covers it; anchored like chaos_bench.
    },
    "dynamo_tpu/llm/admission.py": {
        # The gate runs inside HTTP handlers (and bench drivers) on the
        # asyncio loop; snapshot() is scraped from the same loop. The
        # per-class OVERLOAD counters it feeds are ALSO read by the
        # engine thread's metrics flush — that registry carries its own
        # lock (utils/deadline.py).
        "AdmissionController.admit": (LOOP,),
        "AdmissionController.snapshot": (LOOP,),
    },
    "dynamo_tpu/llm/kv_router/replicas.py": {
        # Replica fleet management (spawn/kill/rejoin/staleness) is
        # loop-only; the module-level dynarace annotation covers the
        # rest — anchored here for the registry.
        "RouterReplicaSet.staleness": (LOOP,),
    },
    "dynamo_tpu/block_manager/peer.py": {
        # The G4 tier lives on the asyncio loop (discovery watch, pull
        # transfers, re-announce pump); its counters/EMAs are written
        # loop-side only and read lock-free by manager.stats() — the
        # same GIL-atomic contract as every other KVBM gauge. PrefixHeat
        # is the exception: noted from the ENGINE thread's kv_actual
        # hook and read by the planner hook on the loop (its own lock).
        "PeerBlockClient.stats": (LOOP,),
        "PrefixHeat.note": (ENGINE, LOOP),
        "PrefixHeat.hottest": (LOOP,),
    },
    "benchmarks/g4_bench.py": {
        # Pure asyncio driver (the G4 pull/pre-place/peer-death legs):
        # async-def inference covers it; anchored like chaos_bench.
    },
    "dynamo_tpu/ops/quant.py": {
        # Weight-quant math (docs/architecture/weight_quant.md):
        # policy quantize-on-load runs on the runner build's to_thread
        # worker (TpuEngine._build_runner); qdot/qeinsum execute inside
        # jitted programs driven from the engine thread. Pure functions
        # over immutable trees — anchored for the registry.
        "quantize_params_policy": (WORKER,),
        "init_params_policy": (WORKER,),
        "quant_tree_stats": (WORKER,),
    },
    "dynamo_tpu/mocker/engine.py": {
        # The simulated runner is driven by MockerEngine's engine
        # thread — the same dispatch-loop seam as the real TpuEngine;
        # its weight-pass pricing and quant gauges live there.
        "_SimRunner._weight_pass_us": (ENGINE,),
    },
    "dynamo_tpu/planner/obs.py": {
        # Planner control loop runs on the loop; scrapes read from HTTP
        # handlers and the standalone exporter (also loop).
        "PlannerObservatory.note_decision": (LOOP,),
        "PlannerObservatory.note_size": (LOOP,),
        "PlannerObservatory.gauges": (LOOP,),
        "PlannerObservatory.snapshot": (LOOP,),
    },
    # operator/kube.py's watch pump is covered by spawn inference
    # (threading.Thread(target=pump) in the same file).
}


@dataclass
class ContextModel:
    """Context assignment for every function in one file."""

    #: qualname ("Class.method", "func", "outer.inner") -> context set.
    contexts: dict[str, frozenset[str]] = field(default_factory=dict)
    #: qualname -> def node (for rules that re-walk bodies).
    functions: dict[str, ast.AST] = field(default_factory=dict)
    #: qualname -> enclosing class name ("" at module level).
    owner_class: dict[str, str] = field(default_factory=dict)

    def of(self, qualname: str) -> frozenset[str]:
        return self.contexts.get(qualname, frozenset())


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def _parse_annotations(source: str) -> dict[int, frozenset[str]]:
    """Line -> contexts for every `# dynarace: context[...]` marker."""
    out: dict[int, frozenset[str]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _ANNOTATION_RE.search(line)
        if m:
            out[i] = frozenset(
                s.strip() for s in m.group(1).split(",") if s.strip()
            )
    return out


def _spawn_inference(ctx: FileContext) -> dict[str, frozenset[str]]:
    """Contexts for functions handed to Thread(target=...) /
    asyncio.to_thread(...) / run_in_executor(...) within this file.
    Keyed by the TERMINAL name (methods resolve per owning class later —
    a terminal-name match is deliberate: `self._store` passed to
    to_thread marks every `_store` in the file, which is conservative in
    the right direction for a single-module analysis)."""
    out: dict[str, set[str]] = {}

    def _note(funcref: ast.AST, context: str) -> None:
        name = None
        if isinstance(funcref, ast.Attribute):
            name = funcref.attr
        elif isinstance(funcref, ast.Name):
            name = funcref.id
        if name:
            out.setdefault(name, set()).add(context)

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Call):
            continue
        qn = ctx.qualname(node.func)
        terminal = node.func.attr if isinstance(
            node.func, ast.Attribute) else getattr(node.func, "id", None)
        if qn == "threading.Thread" or terminal == "Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    tname = None
                    if isinstance(kw.value, ast.Attribute):
                        tname = kw.value.attr
                    elif isinstance(kw.value, ast.Name):
                        tname = kw.value.id
                    if tname:
                        # The Thread name= kwarg, when a literal, labels
                        # the context; else the target's own name does.
                        label = tname
                        for kw2 in node.keywords:
                            if kw2.arg == "name" and isinstance(
                                kw2.value, ast.Constant
                            ) and isinstance(kw2.value.value, str):
                                label = kw2.value.value
                        out.setdefault(tname, set()).add(f"thread:{label}")
        elif qn == "asyncio.to_thread" and node.args:
            _note(node.args[0], WORKER)
        elif terminal == "run_in_executor" and len(node.args) >= 2:
            _note(node.args[1], WORKER)
    return {k: frozenset(v) for k, v in out.items()}


def build_context_model(ctx: FileContext) -> ContextModel:
    """Assign contexts to every function in `ctx` and propagate through
    the intra-file call graph to a fixpoint. Memoized on the context:
    DT007/DT009/DT010 all need the model, and one build per file per
    lint run is enough."""
    cached = getattr(ctx, "_dynarace_model", None)
    if cached is not None:
        return cached
    model = ContextModel()
    annotations = _parse_annotations(ctx.source)
    seeds = SEED_CONTEXTS.get(ctx.path, {})
    spawned = _spawn_inference(ctx)

    # Pass 1: collect functions with qualnames + direct context evidence.
    async_funcs: set[str] = set()

    def collect(node: ast.AST, stack: list[str], class_name: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_NODES):
                qual = ".".join(stack + [child.name])
                model.functions[qual] = child
                model.owner_class[qual] = class_name
                ctxs: set[str] = set()
                for line in (child.lineno, child.lineno - 1):
                    ctxs |= annotations.get(line, frozenset())
                ctxs |= set(seeds.get(qual, ()))
                if isinstance(child, ast.AsyncFunctionDef):
                    ctxs.add(LOOP)
                    async_funcs.add(qual)
                if not ctxs:
                    # Spawn inference is the weakest evidence: an explicit
                    # seed/annotation already NAMES the thread a target
                    # runs on — adding a second `thread:` label for the
                    # same spawn would fake a two-context function.
                    ctxs |= set(spawned.get(child.name, frozenset()))
                if ctxs:
                    model.contexts[qual] = frozenset(ctxs)
                collect(child, stack + [child.name], class_name)
            elif isinstance(child, ast.ClassDef):
                collect(child, stack + [child.name], child.name)
            else:
                collect(child, stack, class_name)

    collect(ctx.tree, [], "")

    # Pass 2: intra-file call graph (resolvable edges only).
    edges: dict[str, set[str]] = {q: set() for q in model.functions}
    for qual, fnode in model.functions.items():
        class_name = model.owner_class[qual]
        for node in ast.walk(fnode):
            if not isinstance(node, ast.Call):
                continue
            callee = None
            f = node.func
            if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                    and f.value.id in ("self", "cls") and class_name:
                cand = f"{class_name}.{f.attr}"
                if cand in model.functions:
                    callee = cand
            elif isinstance(f, ast.Name):
                # Nested helper of this function first, else module-level.
                nested = f"{qual}.{f.id}"
                if nested in model.functions:
                    callee = nested
                elif f.id in model.functions:
                    callee = f.id
            if callee is not None and callee != qual:
                edges[qual].add(callee)

    # Pass 3: propagate caller contexts into sync callees to a fixpoint.
    changed = True
    rounds = 0
    while changed and rounds < 50:
        changed = False
        rounds += 1
        for caller, callees in edges.items():
            cctx = model.contexts.get(caller)
            if not cctx:
                continue
            for callee in callees:
                if callee in async_funcs:
                    continue  # calling a coroutine fn ≠ executing it here
                cur = model.contexts.get(callee, frozenset())
                merged = cur | cctx
                if merged != cur:
                    model.contexts[callee] = frozenset(merged)
                    changed = True
    ctx._dynarace_model = model
    return model


def has_context_annotations(source: str) -> bool:
    """Cheap pre-check rules use to opt un-seeded files into analysis."""
    return _ANNOTATION_RE.search(source) is not None

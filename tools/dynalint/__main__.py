"""CLI: `python -m tools.dynalint [paths...]`.

Exit codes: 0 clean (all findings baselined), 1 new findings or
suppression-hygiene errors, 2 bad invocation / unreadable baseline.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from tools.dynalint.baseline import DEFAULT_BASELINE, Baseline, diff_against
from tools.dynalint.core import (
    DEFAULT_TARGETS,
    SUPPRESSION_RULE,
    all_rules,
    lint_paths,
)


def _repo_root() -> Path:
    # tools/dynalint/__main__.py -> repo root is two parents above tools/.
    return Path(__file__).resolve().parent.parent.parent


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.dynalint",
        description="project-native AST analysis (see docs/development/"
                    "static_analysis.md for the rule catalog)",
    )
    ap.add_argument(
        "paths", nargs="*", default=list(DEFAULT_TARGETS),
        help=f"files/dirs to lint (default: {' '.join(DEFAULT_TARGETS)})",
    )
    ap.add_argument(
        "--baseline", default=DEFAULT_BASELINE,
        help="burn-down baseline file (relative to the repo root)",
    )
    ap.add_argument(
        "--no-baseline", action="store_true",
        help="report every finding, grandfathered or not",
    )
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline to the current finding set and exit 0",
    )
    ap.add_argument(
        "--select", default=None,
        help="comma-separated rule ids to run (default: all)",
    )
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument(
        "--stats", action="store_true", help="print per-rule finding counts"
    )
    args = ap.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.id}  {r.name:<28} {r.summary}")
        return 0
    if args.select:
        wanted = {s.strip().upper() for s in args.select.split(",") if s.strip()}
        unknown = wanted - {r.id for r in rules}
        if unknown:
            print(f"unknown rule id(s): {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2
        rules = [r for r in rules if r.id in wanted]

    root = _repo_root()
    findings = lint_paths(list(args.paths), root, rules)

    baseline_path = root / args.baseline
    if args.update_baseline:
        # A baseline rebuilt from a narrowed run would silently drop every
        # grandfathered entry outside the scope, turning the next full run
        # red — only the default full sweep may rewrite it.
        if args.select or list(args.paths) != list(DEFAULT_TARGETS):
            print(
                "error: --update-baseline requires the default scope "
                "(no --select, no explicit paths) so out-of-scope "
                "grandfathered entries are not dropped",
                file=sys.stderr,
            )
            return 2
        Baseline.from_findings(findings).save(baseline_path)
        print(f"baseline rewritten: {len(findings)} finding(s) -> "
              f"{args.baseline}")
        return 0

    if args.no_baseline:
        baseline = Baseline()
    else:
        try:
            baseline = Baseline.load(baseline_path)
        except ValueError as exc:
            print(f"error: {exc}", file=sys.stderr)
            return 2

    diff = diff_against(findings, baseline)
    # Suppression hygiene gets its own section: a stale pragma failing as
    # one more anonymous finding is opaque — name the pragma's rule id(s)
    # and file:line so the fix (delete or justify the marker) is obvious.
    hygiene = [f for f in diff.new if f.rule == SUPPRESSION_RULE]
    for f in diff.new:
        if f.rule != SUPPRESSION_RULE:
            print(f.render())
    if hygiene:
        print("suppression hygiene (fix the pragma in-file, "
              "never the baseline):")
        for f in hygiene:
            print(f"  {f.path}:{f.line}: {f.message}")
    if args.stats:
        counts: dict[str, int] = {}
        for f in findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        for rule in sorted(counts):
            print(f"# {rule}: {counts[rule]} total")
    # Stale detection is only meaningful on the full sweep — a narrowed
    # run trivially "misses" every out-of-scope baseline entry.
    full_scope = not args.select and list(args.paths) == list(DEFAULT_TARGETS)
    if not full_scope:
        diff.stale = {}
    for key, surplus in sorted(diff.stale.items()):
        print(f"# stale baseline entry ({surplus} surplus): {key}")
    if diff.stale:
        print("# run `python -m tools.dynalint --update-baseline` to shrink "
              "the baseline")

    n_new, n_known = len(diff.new), len(diff.known)
    if n_new:
        print(f"dynalint: {n_new} new finding(s) "
              f"({n_known} baselined, {len(diff.stale)} stale entries)")
        return 1
    print(f"dynalint: clean ({n_known} baselined finding(s), "
          f"{len(diff.stale)} stale entries)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

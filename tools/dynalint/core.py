"""dynalint core: findings, the rule registry, suppressions, file linting.

dynalint is this project's AST analyzer for the serving-stack invariants
generic linters cannot see: event-loop hygiene on the request path, JAX
donation/bucketing discipline, and the swallowed-exception shapes that
produced the r05 donated-KV-buffer bug. Rules are small `ast` visitors
registered here; `python -m tools.dynalint` runs them over the tree and
diffs against a checked-in baseline so pre-existing findings are
grandfathered while any NEW finding fails CI.

Suppression syntax (reason is mandatory — enforced as DT000):

    something_flagged()  # dynalint: allow[DT005] one-off admin path
    # dynalint: allow[DT003] failure is propagated via the result future
    except Exception:

An inline comment suppresses findings on its own line; a comment-only
line suppresses findings on the next line. Unused suppressions and
suppressions without a reason are themselves findings, so the allow-list
can only shrink honestly.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path

#: Pseudo-rule id for suppression hygiene (empty reason, unknown rule id,
#: suppression that no longer suppresses anything). Always on.
SUPPRESSION_RULE = "DT000"

_ALLOW_RE = re.compile(
    r"#\s*dynalint:\s*allow\[([A-Za-z0-9,\s]*)\]\s*(.*?)\s*$"
)


@dataclass(frozen=True)
class Finding:
    """One analyzer hit. `key()` intentionally omits the line number so
    baseline entries survive unrelated edits that shift code around."""

    path: str  # repo-relative posix path
    line: int
    col: int
    rule: str  # "DT001"
    message: str

    def key(self) -> str:
        return f"{self.path}::{self.rule}::{self.message}"

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"


@dataclass
class Suppression:
    line: int          # line the comment sits on
    target_line: int   # line whose findings it suppresses
    rules: tuple[str, ...]
    reason: str
    used: bool = False


@dataclass
class FileContext:
    """Everything a rule needs about one file, parsed once."""

    path: str                    # repo-relative posix path
    source: str
    tree: ast.AST
    imports: dict[str, str] = field(default_factory=dict)

    def __post_init__(self) -> None:
        self.imports = _collect_imports(self.tree)

    def qualname(self, node: ast.AST) -> str | None:
        """Dotted name of a Name/Attribute chain, resolved through this
        file's import table: `_time.sleep` -> `time.sleep`,
        `sleep` (from time import sleep) -> `time.sleep`. None when the
        chain bottoms out in something dynamic (a call, subscript, ...)."""
        parts: list[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        root = self.imports.get(cur.id, cur.id)
        parts.append(root)
        return ".".join(reversed(parts))


def _collect_imports(tree: ast.AST) -> dict[str, str]:
    table: dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                table[alias.asname or alias.name.split(".")[0]] = alias.name
        elif isinstance(node, ast.ImportFrom) and node.module and node.level == 0:
            for alias in node.names:
                if alias.name == "*":
                    continue
                table[alias.asname or alias.name] = f"{node.module}.{alias.name}"
    return table


class Rule:
    """Base class. Subclasses set `id`/`name`/`summary`, optionally narrow
    `applies_to`, and implement `check`.

    Interprocedural (dynaflow) rules set `requires_program = True`,
    implement `check_program`, and leave `check` at its default empty
    return: they see the per-file AST *and* the whole-program
    `ProgramContext` (symbol table, call graph, evidence files) and run
    only when the driver built one. Findings must still anchor inside
    `ctx.path` so line-anchored suppressions keep working."""

    id: str = ""
    name: str = ""
    summary: str = ""
    #: True for rules that can only run with a ProgramContext; they are
    #: skipped (and their suppressions exempt from unused-hygiene) when
    #: linting a lone source string with no program.
    requires_program: bool = False

    def applies_to(self, path: str) -> bool:
        return path.endswith(".py")

    def check(self, ctx: FileContext) -> list[Finding]:
        if self.requires_program:
            return []
        raise NotImplementedError

    def check_program(self, ctx: FileContext, program) -> list[Finding]:
        """Whole-program pass for one file. `program` is a
        `tools.dynalint.program.ProgramContext`; default is a no-op so
        per-file rules need not care."""
        return []


REGISTRY: dict[str, Rule] = {}


def register(cls: type[Rule]) -> type[Rule]:
    rule = cls()
    if not rule.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if rule.id in REGISTRY:
        raise ValueError(f"duplicate rule id {rule.id}")
    REGISTRY[rule.id] = rule
    return cls


def all_rules() -> list[Rule]:
    # Import for side effect: each rule module registers itself.
    from tools.dynalint import rules  # noqa: F401

    return [REGISTRY[k] for k in sorted(REGISTRY)]


# -- suppressions ------------------------------------------------------------

def parse_suppressions(source: str) -> tuple[list[Suppression], list[tuple[int, str]]]:
    """Scan comments for `# dynalint: allow[...]` markers.

    Returns (suppressions, problems) where problems are (line, message)
    pairs for malformed markers (empty reason, empty/garbage rule list).
    Malformed markers do NOT suppress anything.
    """
    sups: list[Suppression] = []
    problems: list[tuple[int, str]] = []
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return sups, problems  # the parse-error finding covers it
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        lineno, col = tok.start
        text = tok.string
        m = _ALLOW_RE.search(text)
        if m is None:
            if "dynalint:" in text:
                problems.append(
                    (lineno, "malformed dynalint marker (expected "
                             "`# dynalint: allow[DTxxx] reason`)")
                )
            continue
        ids = tuple(
            s.strip().upper() for s in m.group(1).split(",") if s.strip()
        )
        reason = m.group(2).strip()
        if not ids:
            problems.append((lineno, "suppression lists no rule ids"))
            continue
        bad = [i for i in ids if not re.fullmatch(r"[A-Z]{2}\d{3}", i)]
        if bad:
            problems.append(
                (lineno, f"suppression names malformed rule id(s): {', '.join(bad)}")
            )
            continue
        if not reason:
            problems.append(
                (lineno,
                 f"suppression of {', '.join(ids)} carries no justification "
                 "— a non-empty reason is required")
            )
            continue
        standalone = not tok.line[:col].strip()
        target = lineno + 1 if standalone else lineno
        sups.append(Suppression(lineno, target, ids, reason))
    return sups, problems


# -- linting -----------------------------------------------------------------

def lint_source(
    source: str,
    path: str,
    rules: list[Rule] | None = None,
    program=None,
    ctx: FileContext | None = None,
) -> list[Finding]:
    """Lint one file's source. `path` is the repo-relative posix path the
    rules use for scoping and that findings report. `program` (a
    `ProgramContext`) enables the interprocedural rules; `ctx` lets the
    driver pass an already-parsed FileContext so files are parsed once
    per run."""
    if rules is None:
        rules = all_rules()
    if ctx is None:
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as exc:
            return [
                Finding(path, exc.lineno or 1, (exc.offset or 1) - 1,
                        SUPPRESSION_RULE, f"file does not parse: {exc.msg}")
            ]
        ctx = FileContext(path=path, source=source, tree=tree)
    raw: list[Finding] = []
    for rule in rules:
        if not rule.applies_to(path):
            continue
        raw.extend(rule.check(ctx))
        if program is not None and rule.requires_program:
            raw.extend(rule.check_program(ctx, program))

    sups, problems = parse_suppressions(source)
    kept: list[Finding] = []
    for f in sorted(raw, key=lambda f: (f.line, f.col, f.rule)):
        hit = None
        for s in sups:
            if s.target_line == f.line and f.rule in s.rules:
                hit = s
                break
        if hit is not None:
            hit.used = True
        else:
            kept.append(f)
    # Unused-suppression hygiene is only decidable when every rule the
    # marker names was in the executed set — under `--select DT001` an
    # allow[DT003] marker cannot prove itself used and must not be
    # reported as dead. Program rules only count as executed when a
    # program was actually built (lone lint_source calls skip them).
    # Path scoping intentionally does NOT exempt:
    # an allow[DT005] in a non-step-path file can never fire and IS dead.
    executed = {
        r.id for r in rules
        if program is not None or not r.requires_program
    }
    for s in sups:
        if not s.used and set(s.rules) <= executed:
            kept.append(
                Finding(path, s.line, 0, SUPPRESSION_RULE,
                        f"unused suppression of {', '.join(s.rules)} — "
                        "remove it (nothing on the target line fires)")
            )
    for line, msg in problems:
        kept.append(Finding(path, line, 0, SUPPRESSION_RULE, msg))
    kept.sort(key=lambda f: (f.line, f.col, f.rule))
    return kept


DEFAULT_TARGETS = ("dynamo_tpu", "bench.py", "tools", "benchmarks")
_SKIP_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}


def _rel(f: Path, root: Path) -> str:
    """Repo-relative posix path; targets outside `root` stay absolute."""
    try:
        return f.relative_to(root).as_posix()
    except ValueError:
        return f.as_posix()


def iter_python_files(targets: list[str], root: Path) -> list[Path]:
    out: list[Path] = []
    for t in targets:
        p = (root / t) if not Path(t).is_absolute() else Path(t)
        if p.is_file() and p.suffix == ".py":
            out.append(p)
        elif p.is_dir():
            out.extend(
                f for f in sorted(p.rglob("*.py"))
                if not _SKIP_DIRS.intersection(f.parts)
            )
    return out


def lint_paths(
    targets: list[str],
    root: Path,
    rules: list[Rule] | None = None,
) -> list[Finding]:
    """Lint `targets`, building the whole-program context once.

    The ProgramContext is ALWAYS built over the full default universe
    (plus evidence-only extras like tests/), even when `targets` narrows
    the linted set — interprocedural laws like fault-point parity are
    facts about the whole program, and linting `utils/faults.py` alone
    must still see the chaos-bench arm lists. Files linted here are
    parsed once and shared with the program build.
    """
    from tools.dynalint.program import build_program

    if rules is None:
        rules = all_rules()
    lintees: list[tuple[str, str | None, FileContext | None]] = []
    parsed: dict[str, tuple[str, ast.AST]] = {}
    for f in iter_python_files(targets, root):
        rel = _rel(f, root)
        source = f.read_text()
        try:
            tree = ast.parse(source, filename=rel)
        except SyntaxError:
            lintees.append((rel, source, None))  # lint_source reports it
            continue
        parsed[rel] = (source, tree)
        lintees.append(
            (rel, source, FileContext(path=rel, source=source, tree=tree))
        )
    program = None
    if any(r.requires_program for r in rules):
        program = build_program(list(DEFAULT_TARGETS), root, parsed=parsed)
    findings: list[Finding] = []
    for rel, source, ctx in lintees:
        findings.extend(
            lint_source(source or "", rel, rules, program=program, ctx=ctx)
        )
    return findings

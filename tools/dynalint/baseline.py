"""Baseline (burn-down) file handling.

The baseline grandfathers pre-existing findings: entries are
`Finding.key()` strings (path::rule::message — line numbers excluded so
unrelated edits don't churn it) mapped to an allowed COUNT. A run fails
only on findings beyond the allowed count for their key; keys whose
count dropped are reported as stale so `--update-baseline` shrinks the
file and the debt can only burn down.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path

from tools.dynalint.core import Finding

BASELINE_VERSION = 1
DEFAULT_BASELINE = "tools/dynalint/baseline.json"


@dataclass
class Baseline:
    entries: dict[str, int] = field(default_factory=dict)

    @staticmethod
    def load(path: Path) -> "Baseline":
        if not path.exists():
            return Baseline()
        data = json.loads(path.read_text())
        if data.get("version") != BASELINE_VERSION:
            raise ValueError(
                f"baseline {path} has version {data.get('version')!r}; "
                f"this dynalint reads version {BASELINE_VERSION}"
            )
        entries = data.get("entries", {})
        if not all(
            isinstance(k, str) and isinstance(v, int) and v > 0
            for k, v in entries.items()
        ):
            raise ValueError(f"baseline {path} has malformed entries")
        return Baseline(dict(entries))

    def save(self, path: Path) -> None:
        payload = {
            "version": BASELINE_VERSION,
            "comment": (
                "dynalint burn-down baseline. Grandfathered findings only: "
                "new findings always fail. Update via "
                "`python -m tools.dynalint --update-baseline` and review "
                "the diff — entries should only ever disappear."
            ),
            "entries": {k: self.entries[k] for k in sorted(self.entries)},
        }
        path.write_text(json.dumps(payload, indent=2) + "\n")

    @staticmethod
    def from_findings(findings: list[Finding]) -> "Baseline":
        entries: dict[str, int] = {}
        for f in findings:
            entries[f.key()] = entries.get(f.key(), 0) + 1
        return Baseline(entries)


@dataclass
class Diff:
    new: list[Finding]          # beyond the baselined count — FAIL
    known: list[Finding]        # covered by the baseline
    stale: dict[str, int]       # key -> surplus allowance no longer used


def diff_against(findings: list[Finding], baseline: Baseline) -> Diff:
    seen: dict[str, int] = {}
    new: list[Finding] = []
    known: list[Finding] = []
    for f in findings:
        k = f.key()
        seen[k] = seen.get(k, 0) + 1
        # The first `allowed` occurrences (in file order) are the
        # grandfathered ones; everything past that is new debt.
        if seen[k] <= baseline.entries.get(k, 0):
            known.append(f)
        else:
            new.append(f)
    stale = {
        k: allowed - seen.get(k, 0)
        for k, allowed in baseline.entries.items()
        if seen.get(k, 0) < allowed
    }
    return Diff(new=new, known=known, stale=stale)

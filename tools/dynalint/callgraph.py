"""dynaflow call graph: who can call whom, at two precision tiers.

Two laws need reachability with OPPOSITE error preferences, so the
graph keeps two edge sets:

- **resolved** — only edges the resolver can pin to a concrete
  function: `self.method`/`cls.method` within the same class,
  same-file names (module-level and nested defs), and dotted names
  that resolve through the import table to a project function. Used
  where a wrong edge creates a wrong *finding* (DT016 recompile
  hazards: claiming a function is jit-reachable must be defensible).
- **loose** — a superset adding terminal-name fallback (any project
  function with the same trailing name — the inheritance / duck-typing
  over-approximation) and callback-reference edges (a function name
  passed as a call *argument*: `retry_async(attempt)`, `jax.jit(fn)`,
  `asyncio.to_thread(f)` all count as "may invoke"). Used where a
  missing edge creates a wrong finding (DT012 envelope completeness:
  "this write never reaches a stamp" must only fire when no plausible
  path exists).

Nodes are function ids from the program symbol table
(`path::qualname`). Build once per run via `CallGraph.of(program)`,
which memoizes in `program.cache`.
"""

from __future__ import annotations

import ast
from collections import deque
from dataclasses import dataclass, field

from tools.dynalint.astutil import walk_in_scope
from tools.dynalint.program import FunctionInfo, ProgramContext

#: Terminal names too generic to create loose edges for — they connect
#: everything to everything and drown the over-approximation's signal.
_NOISE_TERMINALS = {
    "__init__", "__post_init__", "get", "set", "put", "pop", "add",
    "append", "items", "keys", "values", "update", "copy", "close",
    "start", "stop", "run", "main", "wait", "send", "recv", "read",
    "write", "open", "next", "clear", "register",
}


@dataclass
class CallGraph:
    program: ProgramContext
    #: caller fid -> callee fids, precise tier
    resolved: dict[str, set[str]] = field(default_factory=dict)
    #: caller fid -> callee fids, superset tier (includes resolved)
    loose: dict[str, set[str]] = field(default_factory=dict)

    # -- construction -------------------------------------------------------
    @staticmethod
    def of(program: ProgramContext) -> "CallGraph":
        cached = program.cache.get("callgraph")
        if isinstance(cached, CallGraph):
            return cached
        graph = CallGraph(program)
        for info in program.functions.values():
            graph._resolve_function(info)
        program.cache["callgraph"] = graph
        return graph

    def _edges(self, fid: str) -> tuple[set[str], set[str]]:
        return (
            self.resolved.setdefault(fid, set()),
            self.loose.setdefault(fid, set()),
        )

    def _resolve_function(self, info: FunctionInfo) -> None:
        prog = self.program
        ctx = prog.files[info.path]
        res, loose = self._edges(info.id)

        def add_resolved(target: str) -> None:
            res.add(target)
            loose.add(target)

        def add_loose_terminal(name: str) -> None:
            if name in _NOISE_TERMINALS:
                return
            for fid in prog.by_terminal.get(name, ()):
                loose.add(fid)

        def resolve_ref(node: ast.AST) -> None:
            """One edge for a callee or callback reference expression."""
            if isinstance(node, ast.Name):
                target = self._same_file(info, node.id)
                if target is not None:
                    add_resolved(target)
                    return
                dotted = ctx.imports.get(node.id)
                if dotted is not None:
                    fid = self._project_dotted(dotted)
                    if fid is not None:
                        add_resolved(fid)
                        return
                add_loose_terminal(node.id)
            elif isinstance(node, ast.Attribute):
                base = node.value
                if (
                    isinstance(base, ast.Name)
                    and base.id in ("self", "cls")
                    and info.class_name
                ):
                    target = self._same_class(info, node.attr)
                    if target is not None:
                        add_resolved(target)
                        return
                    add_loose_terminal(node.attr)
                    return
                dotted = ctx.qualname(node)
                if dotted is not None:
                    fid = self._project_dotted(dotted)
                    if fid is not None:
                        add_resolved(fid)
                        return
                add_loose_terminal(node.attr)

        for node in walk_in_scope(info.node):
            if not isinstance(node, ast.Call):
                continue
            resolve_ref(node.func)
            # Callback references: bare function names handed to another
            # call. Loose tier only — being passed is "may be invoked",
            # not "is invoked", so the precise tier must not claim it.
            for arg in list(node.args) + [k.value for k in node.keywords]:
                if isinstance(arg, ast.Name):
                    target = self._same_file(info, arg.id)
                    if target is None and arg.id in ctx.imports:
                        target = self._project_dotted(ctx.imports[arg.id])
                    if target is not None:
                        loose.add(target)
                    elif arg.id not in ctx.imports:
                        # Unresolvable bare name: only worth a loose edge
                        # if some project function carries the name.
                        add_loose_terminal(arg.id)
                elif isinstance(arg, ast.Attribute):
                    if (
                        isinstance(arg.value, ast.Name)
                        and arg.value.id in ("self", "cls")
                        and info.class_name
                    ):
                        target = self._same_class(info, arg.attr)
                        if target is not None:
                            loose.add(target)
                            continue
                    add_loose_terminal(arg.attr)

    def _same_file(self, caller: FunctionInfo, name: str) -> str | None:
        """A function named `name` visible from `caller` in its own file:
        a nested child first, then any same-file def with that qualname
        tail at module or class level."""
        prog = self.program
        child = f"{caller.path}::{caller.qualname}.{name}"
        if child in prog.functions:
            return child
        module_level = f"{caller.path}::{name}"
        if module_level in prog.functions:
            return module_level
        # Enclosing-scope nested defs: strip trailing components.
        parts = caller.qualname.split(".")
        for n in range(len(parts) - 1, 0, -1):
            cand = f"{caller.path}::{'.'.join(parts[:n])}.{name}"
            if cand in prog.functions:
                return cand
        return None

    def _same_class(self, caller: FunctionInfo, method: str) -> str | None:
        prog = self.program
        for fid in prog.by_terminal.get(method, ()):
            info = prog.functions[fid]
            if info.path == caller.path and info.class_name == caller.class_name:
                return fid
        return None

    def _project_dotted(self, dotted: str) -> str | None:
        """Function id for an import-resolved dotted name, tolerating
        attribute chains hung off an imported symbol
        (`mod.Class.method`, `pkg.mod.func`)."""
        return self.program.by_dotted.get(dotted)

    # -- queries ------------------------------------------------------------
    def callees(self, fid: str, loose: bool = False) -> set[str]:
        tier = self.loose if loose else self.resolved
        return tier.get(fid, set())

    def reachable(self, roots, loose: bool = False) -> set[str]:
        """Forward closure: every function id reachable from `roots`
        (roots included)."""
        tier = self.loose if loose else self.resolved
        seen: set[str] = set()
        queue = deque(r for r in roots if r in self.program.functions)
        seen.update(queue)
        while queue:
            cur = queue.popleft()
            for nxt in tier.get(cur, ()):
                if nxt not in seen:
                    seen.add(nxt)
                    queue.append(nxt)
        return seen

    def reaches(self, start: str, targets, loose: bool = False) -> bool:
        """True when any of `targets` is in `start`'s forward closure."""
        wanted = set(targets)
        if not wanted:
            return False
        return bool(wanted & self.reachable([start], loose=loose))

    def callers_closure(self, targets, loose: bool = False) -> set[str]:
        """Backward closure: every function id from which some target is
        reachable (targets included). Used for "is this write under a
        stamping caller" queries."""
        tier = self.loose if loose else self.resolved
        inverse: dict[str, set[str]] = {}
        for src, dsts in tier.items():
            for dst in dsts:
                inverse.setdefault(dst, set()).add(src)
        seen: set[str] = set()
        queue = deque(t for t in targets if t in self.program.functions)
        seen.update(queue)
        while queue:
            cur = queue.popleft()
            for prv in inverse.get(cur, ()):
                if prv not in seen:
                    seen.add(prv)
                    queue.append(prv)
        return seen

"""Small AST helpers shared by dynalint rules."""

from __future__ import annotations

import ast
from typing import Iterator

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)


def walk_in_scope(node: ast.AST) -> Iterator[ast.AST]:
    """Yield `node`'s descendants WITHOUT descending into nested function
    / lambda / class scopes — the async rules reason about what runs in
    the enclosing frame, not in code that merely gets defined there."""
    stack = list(ast.iter_child_nodes(node))
    while stack:
        child = stack.pop()
        yield child
        if not isinstance(child, _SCOPE_NODES):
            stack.extend(ast.iter_child_nodes(child))


def contains_await(node: ast.AST) -> bool:
    return any(
        isinstance(n, (ast.Await, ast.AsyncFor, ast.AsyncWith))
        for n in walk_in_scope(node)
    )


def contains_raise(node: ast.AST) -> bool:
    return any(isinstance(n, ast.Raise) for n in walk_in_scope(node))


def enclosing_name(stack: list[ast.AST]) -> str:
    """Dotted label of the innermost named scopes, for finding messages.
    Messages key the baseline, so this must be stable under line moves."""
    names = [
        n.name for n in stack
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef))
    ]
    return ".".join(names) or "<module>"


def call_name(node: ast.Call) -> str | None:
    """Terminal attribute/function name of a call: `a.b.c()` -> "c"."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None

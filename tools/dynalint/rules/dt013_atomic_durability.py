"""DT013 — atomic durable writes: no raw write paths outside atomic_io.

The crash-consistency law (docs/architecture/integrity.md
"Crash-consistent persistence"): durable state is written tmp +
`os.replace` + fsync — the `utils/atomic_io.py` discipline the shape
manifest, compile-cache ledger, G3 sidecar, and planner state all ride.
A raw `open(path, "w")` / `json.dump` / `Path.write_text` torn by a
crash leaves half-written state that a restart then trusts; PR 18's
torn-sidecar drill exists precisely because this bug class was real.

This rule flags every raw durable-write shape in `dynamo_tpu/`,
`benchmarks/`, and `bench.py` outside `utils/atomic_io.py` itself:

- `open(..., "w"/"wb"/"x"...)` and `Path.open("w"...)` — write-mode
  opens (append and read/update modes pass: appends are journal-shaped
  and `r+b` is the mmap arena's in-place row write, whose consistency
  the sidecar protocol owns);
- `json.dump(...)` — serializing straight into a stream someone opened;
- `os.replace(...)` — hand-rolling the atomic rename outside the one
  blessed implementation (fsync of file AND parent dir is the part
  hand-rolls forget);
- `Path.write_text` / `Path.write_bytes` — one-shot raw writes.

Not every hit is durable state (a build artifact, a bench report
regenerated per run); those take a line suppression whose reason says
why a torn write is acceptable there. The default is: route it through
`atomic_write_text` / `atomic_write_bytes`.
"""

from __future__ import annotations

import ast

from tools.dynalint.core import FileContext, Finding, Rule, register

BLESSED = "dynamo_tpu/utils/atomic_io.py"
SCOPES = ("dynamo_tpu/", "benchmarks/")

_WRITE_ATTRS = ("write_text", "write_bytes")


def _mode_of(call: ast.Call) -> str | None:
    """The mode argument of an open()/Path.open() call, when literal."""
    mode = None
    args = call.args
    if isinstance(call.func, ast.Attribute):  # p.open(mode=...)
        if args and isinstance(args[0], ast.Constant):
            mode = args[0].value
    elif len(args) > 1 and isinstance(args[1], ast.Constant):
        mode = args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    return mode if isinstance(mode, str) else None


@register
class AtomicDurability(Rule):
    id = "DT013"
    name = "atomic-durability"
    summary = "raw durable write outside utils/atomic_io.py"

    def applies_to(self, path: str) -> bool:
        if not path.endswith(".py") or path == BLESSED:
            return False
        return path == "bench.py" or any(
            path.startswith(s) for s in SCOPES
        )

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            qual = ctx.qualname(node.func)
            msg = None
            if qual == "open" or (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "open"
            ):
                mode = _mode_of(node)
                if mode is not None and ("w" in mode or "x" in mode):
                    msg = (
                        f"raw write-mode open({mode!r}) — a crash tears "
                        "the file; durable state goes through "
                        "utils/atomic_io.py (suppress with the reason "
                        "this state may legally tear)"
                    )
            elif qual == "json.dump":
                msg = (
                    "json.dump into a raw stream — serialize with "
                    "json.dumps and write via atomic_write_text so a "
                    "crash mid-serialize cannot leave torn JSON"
                )
            elif qual == "os.replace":
                msg = (
                    "hand-rolled os.replace — the blessed tmp+replace+"
                    "fsync lives in utils/atomic_io.py (hand-rolls skip "
                    "the file/parent-dir fsync that makes it durable)"
                )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _WRITE_ATTRS
            ):
                msg = (
                    f"raw .{node.func.attr}() — one-shot write with no "
                    "atomicity; durable state goes through "
                    "utils/atomic_io.py"
                )
            if msg is not None:
                out.append(Finding(
                    ctx.path, node.lineno, node.col_offset, self.id, msg
                ))
        return out

"""DT007 — instance attribute / module global mutated from ≥2 execution
contexts with no lock on any mutation path.

The bug class every review-hardening cycle since PR 7 re-found by hand:
state shared between the engine dispatch thread, the asyncio loop, and
executor workers, written with no lock — lost `+=` updates, torn
multi-field publishes, scrape clones that interleave with a writer.
CPython's GIL makes each bytecode atomic, not each statement: a
`self.total += 1` from two threads drops increments, and a reader
walking two related fields can see them mid-update.

The rule leans on the thread-context model (tools/dynalint/contexts.py):
for every attribute written outside ``__init__``, it collects the set of
contexts the writing functions execute in and whether any write happens
inside a ``with <lock>:`` block. Two or more distinct contexts and zero
locked writes ⇒ finding. One locked write exempts the attribute — a
*partially* locked attribute is a different (harder) judgment the
reviewer makes at the suppression site.

Scope: the concurrency-seam modules below, plus any file carrying a
``# dynarace: context[...]`` annotation (annotating a file opts it in).
"""

from __future__ import annotations

import ast

from tools.dynalint.contexts import (
    SEED_CONTEXTS,
    build_context_model,
    has_context_annotations,
)
from tools.dynalint.core import FileContext, Finding, Rule, register
from tools.dynalint.rules.dt004_lock_across_await import _lock_like

#: Modules whose code demonstrably runs in several contexts (the seam
#: set the seed registry describes). Files outside this list join the
#: analysis by carrying a `# dynarace: context[...]` annotation.
CONCURRENCY_SEAMS = tuple(SEED_CONTEXTS) + (
    "dynamo_tpu/parallel/stepcast.py",
)

#: Constructor-shaped functions: single-threaded by construction
#: (the object cannot be shared before __init__ returns).
_CONSTRUCTORS = {"__init__", "__post_init__", "__new__"}


@register
class CrossContextMutation(Rule):
    id = "DT007"
    name = "cross-context-unlocked-mutation"
    summary = "attribute written from ≥2 thread contexts with no lock"

    def applies_to(self, path: str) -> bool:
        return path.endswith(".py")

    def check(self, ctx: FileContext) -> list[Finding]:
        if ctx.path not in CONCURRENCY_SEAMS and not has_context_annotations(
            ctx.source
        ):
            return []
        model = build_context_model(ctx)

        # key -> list of (context set, locked, line, col, func qualname)
        sites: dict[str, list[tuple[frozenset, bool, int, int, str]]] = {}

        for qual, fnode in model.functions.items():
            contexts = model.of(qual)
            if not contexts or fnode.name in _CONSTRUCTORS:
                continue
            owner = model.owner_class[qual]
            # The repo's `_locked` suffix convention: the function is
            # documented (and reviewed) as only-called-with-the-lock-held
            # — its writes count as locked sites.
            locked_by_convention = fnode.name.endswith("_locked")
            self._collect_sites(
                ctx, fnode, qual, owner, contexts, sites,
                locked_by_convention,
            )

        out: list[Finding] = []
        for key, entries in sorted(sites.items()):
            all_ctxs: set[str] = set()
            for cset, _, _, _, _ in entries:
                all_ctxs |= cset
            if len(all_ctxs) < 2:
                continue
            if any(locked for _, locked, _, _, _ in entries):
                continue
            funcs = sorted({q for _, _, _, _, q in entries})
            line, col = min((ln, c) for _, _, ln, c, _ in entries)
            out.append(Finding(
                ctx.path, line, col, self.id,
                f"`{key}` is written from contexts "
                f"{{{', '.join(sorted(all_ctxs))}}} "
                f"({', '.join(funcs)}) with no lock on any write — "
                "a lost update / torn publish; guard every write with "
                "one lock or confine writes to one context",
            ))
        return out

    def _collect_sites(
        self,
        ctx: FileContext,
        fnode: ast.AST,
        qual: str,
        owner: str,
        contexts: frozenset,
        sites: dict,
        locked_by_convention: bool = False,
    ) -> None:
        """Record every attribute/global write in `fnode`'s own frame,
        tagged with whether a lock-ish `with` encloses it."""
        globals_declared: set[str] = set()
        scope_nodes = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

        def visit(node: ast.AST, lock_depth: int) -> None:
            if isinstance(node, ast.Global):
                globals_declared.update(node.names)
            held = lock_depth
            if isinstance(node, (ast.With, ast.AsyncWith)):
                if any(
                    _lock_like(ctx, item.context_expr) for item in node.items
                ):
                    held += 1
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    key = self._target_key(t, owner, globals_declared)
                    if key is not None:
                        sites.setdefault(key, []).append(
                            (contexts, held > 0, node.lineno,
                             node.col_offset, qual)
                        )
            for child in ast.iter_child_nodes(node):
                # Nested defs are separate functions with their own
                # contexts — collected via their own qualname pass.
                if not isinstance(child, scope_nodes):
                    visit(child, held)

        visit(fnode, 1 if locked_by_convention else 0)

    @staticmethod
    def _target_key(
        t: ast.AST, owner: str, globals_declared: set[str]
    ) -> str | None:
        if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                and t.value.id == "self":
            return f"{owner or '<module>'}.{t.attr}"
        if isinstance(t, ast.Name) and t.id in globals_declared:
            return f"<module>.{t.id}"
        if isinstance(t, ast.Tuple):
            # tuple-unpack writes: report each matching element.
            for elt in t.elts:
                key = CrossContextMutation._target_key(
                    elt, owner, globals_declared
                )
                if key is not None:
                    return key
        return None

"""DT004 — `threading.Lock` held across an `await`.

A sync `with some_lock:` whose body awaits parks the coroutine WHILE the
OS lock is held. Any other coroutine on the same loop that then touches
the lock blocks the entire event loop (the loop thread itself sits in
`acquire()`), and with the engine thread also contending — the
block-manager pumps share locks with engine-thread donation code — this
deadlocks the serving path. Hold sync locks only around straight-line
sections, or use `asyncio.Lock` (`async with`).
"""

from __future__ import annotations

import ast

from tools.dynalint.astutil import contains_await, enclosing_name
from tools.dynalint.core import FileContext, Finding, Rule, register

_LOCKISH = ("lock", "mutex", "sem", "cond")


def _lock_like(ctx: FileContext, expr: ast.AST) -> str | None:
    """Terminal name of a context-manager expression that smells like a
    sync lock (`self._lock`, `pool_lock`, `MUTEX`...)."""
    if isinstance(expr, ast.Call):  # e.g. `with lock_for(h):`
        expr = expr.func
    name = None
    if isinstance(expr, ast.Attribute):
        name = expr.attr
    elif isinstance(expr, ast.Name):
        name = expr.id
    if name is None:
        return None
    low = name.lower()
    return name if any(t in low for t in _LOCKISH) else None


@register
class LockAcrossAwait(Rule):
    id = "DT004"
    name = "lock-across-await"
    summary = "sync `with lock:` body contains an await"

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        stack: list[ast.AST] = []

        def visit(node: ast.AST) -> None:
            stack.append(node)
            if isinstance(node, ast.With):
                for item in node.items:
                    name = _lock_like(ctx, item.context_expr)
                    if name and any(contains_await(b) for b in node.body):
                        out.append(Finding(
                            ctx.path, node.lineno, node.col_offset, self.id,
                            f"sync lock `{name}` held across an await in "
                            f"{enclosing_name(stack)} — the loop thread can "
                            "deadlock on it; release before awaiting or use "
                            "asyncio.Lock",
                        ))
                        break
            for child in ast.iter_child_nodes(node):
                visit(child)
            stack.pop()

        visit(ctx.tree)
        return out

"""DT009 — asyncio loop-affinity violation from a non-loop context.

``loop.create_task`` / ``call_soon`` / ``Future.set_result`` are NOT
thread-safe: invoked from the engine dispatch thread or an executor
worker they mutate the loop's internals unsynchronized — the loop may
never wake for the callback, the future's waiters run on the wrong
thread, or the heap corrupts outright. The only legal cross-thread
entries are ``loop.call_soon_threadsafe(...)`` and
``asyncio.run_coroutine_threadsafe(...)``.

The rule fires on the unsafe calls inside functions whose thread-context
(tools/dynalint/contexts.py) is known and does NOT include the loop.
Functions with unknown context stay silent — precision over recall; the
runtime checker covers the rest under ``DYNTPU_CHECK_THREADS=1``.

``set_result`` / ``set_exception`` on a ``concurrent.futures.Future`` IS
thread-safe — when the rule cannot tell (it sees only the call shape),
suppress with a reason naming the future type.
"""

from __future__ import annotations

import ast

from tools.dynalint.astutil import call_name, walk_in_scope
from tools.dynalint.contexts import LOOP, build_context_model
from tools.dynalint.core import FileContext, Finding, Rule, register

#: Loop-affine call names: only safe on the loop's own thread.
_LOOP_ONLY = {
    "create_task", "ensure_future", "call_soon", "call_later", "call_at",
    "set_result", "set_exception", "cancel",
}

#: ...and their sanctioned cross-thread counterparts (never flagged;
#: their presence is the fix DT009 asks for).
_THREADSAFE = {"call_soon_threadsafe", "run_coroutine_threadsafe"}


@register
class LoopAffinityViolation(Rule):
    id = "DT009"
    name = "loop-affinity-violation"
    summary = "loop/future API touched from a non-loop thread context"

    def applies_to(self, path: str) -> bool:
        return path.endswith(".py")

    def check(self, ctx: FileContext) -> list[Finding]:
        model = build_context_model(ctx)
        out: list[Finding] = []
        for qual, fnode in model.functions.items():
            contexts = model.of(qual)
            if not contexts or LOOP in contexts:
                continue
            for node in walk_in_scope(fnode):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if name in _THREADSAFE:
                    continue
                if name in _LOOP_ONLY and isinstance(
                    node.func, ast.Attribute
                ):
                    if name == "cancel" and not self._future_ish(node.func):
                        continue  # task.cancel is also loop-affine, but
                        # bare `.cancel()` on arbitrary objects is noise
                    out.append(Finding(
                        ctx.path, node.lineno, node.col_offset, self.id,
                        f"`.{name}(...)` called from non-loop context(s) "
                        f"{{{', '.join(sorted(contexts))}}} ({qual}) — "
                        "asyncio loop/future APIs are not thread-safe; "
                        "cross via loop.call_soon_threadsafe / "
                        "asyncio.run_coroutine_threadsafe (or suppress "
                        "naming the concurrent.futures type)",
                    ))
        return out

    @staticmethod
    def _future_ish(attr: ast.Attribute) -> bool:
        """`fut.cancel()` / `task.cancel()` — receiver name suggests an
        asyncio object (keeps `.cancel()` on timers/guards quiet)."""
        base = attr.value
        name = None
        if isinstance(base, ast.Attribute):
            name = base.attr
        elif isinstance(base, ast.Name):
            name = base.id
        if name is None:
            return False
        low = name.lower()
        return any(t in low for t in ("fut", "task"))

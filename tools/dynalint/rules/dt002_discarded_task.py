"""DT002 — discarded `asyncio.create_task` / `ensure_future` result.

The event loop holds only a WEAK reference to tasks: a task whose handle
is dropped can be garbage-collected mid-flight, and when it dies its
exception is silently swallowed (a fire-and-forget ingress pump that
crashes just stops consuming — requests hang with no log line). Retain
the handle: `dynamo_tpu.utils.task.spawn_tracked()` keeps it in a
module-level set until done and logs any exception.
"""

from __future__ import annotations

import ast

from tools.dynalint.astutil import enclosing_name
from tools.dynalint.core import FileContext, Finding, Rule, register

_SPAWNERS = {"asyncio.create_task", "asyncio.ensure_future"}


def _is_spawn(ctx: FileContext, node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    qn = ctx.qualname(node.func)
    if qn in _SPAWNERS:
        return True
    # loop.create_task(...) — any attribute named create_task.
    return (
        isinstance(node.func, ast.Attribute)
        and node.func.attr == "create_task"
        and qn not in _SPAWNERS
        and ctx.qualname(node.func.value) != "asyncio"
    )


@register
class DiscardedTask(Rule):
    id = "DT002"
    name = "discarded-task"
    summary = "create_task/ensure_future result dropped (GC + lost exceptions)"

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        stack: list[ast.AST] = []

        def flag(node: ast.AST, how: str) -> None:
            out.append(Finding(
                ctx.path, node.lineno, node.col_offset, self.id,
                f"asyncio task spawned and {how} in {enclosing_name(stack)} "
                "— task can be GC'd mid-flight and its exception is lost; "
                "retain it (utils/task.spawn_tracked)",
            ))

        def visit(node: ast.AST) -> None:
            stack.append(node)
            if isinstance(node, ast.Expr) and _is_spawn(ctx, node.value):
                flag(node.value, "discarded")
            elif isinstance(node, ast.Assign) and _is_spawn(ctx, node.value):
                targets = node.targets
                if all(
                    isinstance(t, ast.Name) and t.id == "_" for t in targets
                ):
                    flag(node.value, "assigned to `_`")
            elif isinstance(node, ast.Lambda) and _is_spawn(ctx, node.body):
                flag(node.body, "returned from a lambda (caller drops it)")
            for child in ast.iter_child_nodes(node):
                visit(child)
            stack.pop()

        visit(ctx.tree)
        return out

"""DT005 — host synchronization on the engine step path.

`np.asarray(device_array)`, `.block_until_ready()`, `.item()` and
`jax.device_get` force a device→host round trip. On a tunneled TPU each
one costs a full RTT; inside the per-step dispatch loop that serializes
the pipeline the async-dispatch design exists to hide (the engine issues
step N+1 while N executes — a host sync parks it). Keep step results
device-resident until a batch boundary, or batch the transfer
(`gather_many` exists for exactly this).

Scope: the step-path modules only. Host syncs in offline tools, tests,
or the HTTP edge are fine.
"""

from __future__ import annotations

import ast

from tools.dynalint.astutil import call_name, enclosing_name
from tools.dynalint.core import FileContext, Finding, Rule, register

#: Modules whose code runs per engine step (dispatch loop, runner, KV
#: bookkeeping, stepcast broadcast).
STEP_PATH_MODULES = (
    "dynamo_tpu/engine/engine.py",
    "dynamo_tpu/engine/runner.py",
    "dynamo_tpu/engine/kv_cache.py",
    "dynamo_tpu/engine/scheduler.py",
    "dynamo_tpu/parallel/stepcast.py",
)

_SYNC_CALLS = {
    "numpy.asarray": "np.asarray",
    "numpy.array": "np.array",
    "jax.device_get": "jax.device_get",
    "jax.block_until_ready": "jax.block_until_ready",
}

_SYNC_METHODS = {"block_until_ready", "item", "tolist"}


@register
class HostSyncInStepPath(Rule):
    id = "DT005"
    name = "host-sync-in-step-path"
    summary = "device→host sync (asarray/.item()/block_until_ready) per step"

    def applies_to(self, path: str) -> bool:
        return any(path.endswith(m) or path == m for m in STEP_PATH_MODULES)

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        stack: list[ast.AST] = []

        def visit(node: ast.AST) -> None:
            stack.append(node)
            if isinstance(node, ast.Call):
                label = self._sync_label(ctx, node)
                if label is not None:
                    out.append(Finding(
                        ctx.path, node.lineno, node.col_offset, self.id,
                        f"host sync {label} on the step path "
                        f"({enclosing_name(stack)}) — forces a device "
                        "round trip; keep device-resident or batch it",
                    ))
            for child in ast.iter_child_nodes(node):
                visit(child)
            stack.pop()

        visit(ctx.tree)
        return out

    def _sync_label(self, ctx: FileContext, node: ast.Call) -> str | None:
        qn = ctx.qualname(node.func)
        if qn in _SYNC_CALLS:
            return f"`{_SYNC_CALLS[qn]}(...)`"
        name = call_name(node)
        if (
            name in _SYNC_METHODS
            and isinstance(node.func, ast.Attribute)
            and not node.args
            and not node.keywords
        ):
            return f"`.{name}()`"
        return None

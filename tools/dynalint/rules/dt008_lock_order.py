"""DT008 — lock-order inversion (and nested reacquisition) per module.

Two code paths that take the same pair of locks in opposite orders
deadlock the moment they interleave — the engine thread holding A
waiting on B, the loop thread holding B waiting on A, and the whole
serving process freezes with no exception anywhere. The runtime checker
(dynamo_tpu/utils/concurrency.py) catches *observed* inversions under
``DYNTPU_CHECK_THREADS=1``; this rule catches the ones visible in the
source, before a scheduler ever interleaves them.

The per-module lock-acquisition graph comes from ``with lock:`` nesting:
an outer ``with A:`` whose in-scope body takes ``with B:`` adds edge
A→B. Any cycle in the graph (including the 2-cycle A→B + B→A) is an
inversion; a self-edge A→A is a nested reacquisition — instant deadlock
for a plain ``threading.Lock`` (name the attribute ``rlock``-ish if the
object really is reentrant).

Lock identity is the ``with`` expression qualified by the enclosing
class (`self._lock` in class Pool ⇒ ``Pool._lock``), so two classes each
having a ``_lock`` don't alias. Cross-module cycles are the runtime
checker's job — a static cross-module lock alias analysis would drown
in false positives.
"""

from __future__ import annotations

import ast

from tools.dynalint.astutil import enclosing_name
from tools.dynalint.core import FileContext, Finding, Rule, register
from tools.dynalint.rules.dt004_lock_across_await import _lock_like

_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _lock_id(ctx: FileContext, expr: ast.AST, class_name: str) -> str | None:
    """Stable identity for a lock-ish `with` expression, or None."""
    if _lock_like(ctx, expr) is None:
        return None
    if isinstance(expr, ast.Call):  # `with lock_for(h):` — identity is fn
        expr = expr.func
    try:
        text = ast.unparse(expr)
    except Exception:  # pragma: no cover - unparse of odd nodes
        return None
    if text.startswith("self.") and class_name:
        return f"{class_name}.{text[len('self.'):]}"
    return text


@register
class LockOrderInversion(Rule):
    id = "DT008"
    name = "lock-order-inversion"
    summary = "`with` nesting acquires two locks in conflicting orders"

    def applies_to(self, path: str) -> bool:
        return path.endswith(".py")

    def check(self, ctx: FileContext) -> list[Finding]:
        # edge (outer, inner) -> (line, col, enclosing function label)
        edges: dict[tuple[str, str], tuple[int, int, str]] = {}
        stack: list[ast.AST] = []
        class_stack: list[str] = []

        def visit(node: ast.AST, held: tuple[str, ...]) -> None:
            stack.append(node)
            if isinstance(node, ast.ClassDef):
                class_stack.append(node.name)
            now_held = held
            if isinstance(node, (ast.With, ast.AsyncWith)):
                cls = class_stack[-1] if class_stack else ""
                for item in node.items:
                    lid = _lock_id(ctx, item.context_expr, cls)
                    if lid is None:
                        continue
                    for outer in now_held:
                        key = (outer, lid)
                        if key not in edges:
                            edges[key] = (
                                node.lineno, node.col_offset,
                                enclosing_name(stack),
                            )
                    now_held = now_held + (lid,)
            for child in ast.iter_child_nodes(node):
                # A nested def's body does not execute under the outer
                # lock — its own `with` nesting starts fresh.
                visit(child, () if isinstance(child, _SCOPE_NODES) else now_held)
            if isinstance(node, ast.ClassDef):
                class_stack.pop()
            stack.pop()

        visit(ctx.tree, ())

        out: list[Finding] = []
        reported: set[frozenset[str]] = set()
        for (a, b), (line, col, func) in sorted(
            edges.items(), key=lambda kv: kv[1][:2]
        ):
            if a == b:
                out.append(Finding(
                    ctx.path, line, col, self.id,
                    f"nested reacquisition of `{a}` ({func}) — instant "
                    "deadlock for a non-reentrant lock; restructure or "
                    "use an explicitly reentrant lock",
                ))
                continue
            if (b, a) in edges and frozenset((a, b)) not in reported:
                reported.add(frozenset((a, b)))
                other_line, _, other_func = edges[(b, a)]
                out.append(Finding(
                    ctx.path, line, col, self.id,
                    f"lock-order inversion: `{a}` → `{b}` in {func} but "
                    f"`{b}` → `{a}` in {other_func} — interleaved, these "
                    "two paths deadlock; pick one global order",
                ))
        # Longer cycles (A→B→C→A) without any 2-cycle: detect via DFS.
        out.extend(self._long_cycles(ctx, edges, reported))
        return out

    def _long_cycles(
        self,
        ctx: FileContext,
        edges: dict[tuple[str, str], tuple[int, int, str]],
        reported: set[frozenset[str]],
    ) -> list[Finding]:
        graph: dict[str, set[str]] = {}
        for a, b in edges:
            if a != b:
                graph.setdefault(a, set()).add(b)
        out: list[Finding] = []
        seen_cycles: set[frozenset[str]] = set(reported)

        def dfs(start: str, node: str, path: list[str]) -> None:
            for nxt in sorted(graph.get(node, ())):
                if nxt == start and len(path) > 2:
                    cyc = frozenset(path)
                    if cyc not in seen_cycles:
                        seen_cycles.add(cyc)
                        first = min(
                            edges[(path[i], path[(i + 1) % len(path)])][:2]
                            for i in range(len(path))
                        )
                        out.append(Finding(
                            ctx.path, first[0], first[1], self.id,
                            "lock-order cycle through "
                            f"`{' → '.join(path + [start])}` — no single "
                            "acquisition order exists; break the cycle",
                        ))
                elif nxt not in path and nxt > start:
                    # only walk nodes > start so each cycle enumerates once
                    dfs(start, nxt, path + [nxt])

        for n in sorted(graph):
            dfs(n, n, [n])
        return out

"""DT006 — jit-visible shape built from raw `len()` instead of a bucket.

Every distinct array shape that reaches a jitted step function compiles
a fresh XLA program — mid-traffic, at tens of seconds per shape on a
tunneled chip (the r05 1746→357 tok/s/chip collapse). The compile-
lifecycle design therefore requires every data-dependent extent to snap
through the bucket helpers (`_bucket`, `token_budget`) so runtime shapes
land on the warmed grid. A shape-constructing call whose extent is a raw
`len(...)` (or arithmetic over one) re-opens the unbounded-shape-set
hazard: `np.zeros((len(tokens), D))` compiles once per prompt length.

Scope: the step-path modules, where constructed arrays feed the jitted
steps. `len()` is fine once it has passed through a bucket helper —
`np.zeros(_bucket(len(tokens)))` does not fire.
"""

from __future__ import annotations

import ast

from tools.dynalint.astutil import call_name, enclosing_name
from tools.dynalint.core import FileContext, Finding, Rule, register
from tools.dynalint.rules.dt005_host_sync import STEP_PATH_MODULES

#: Array/shape constructors whose integer extents become XLA shapes.
_SHAPE_FNS = {
    "zeros", "ones", "full", "empty", "arange",
    "broadcast_to", "reshape", "pad", "tile", "repeat",
}

#: Passing through any of these snaps the extent onto the warmed grid.
#: `token_budget` is the serving path's snap (engine/compile_cache.py):
#: flat-batch extents land on the budget ladder, not a raw token count.
#: (`lane_bucket` is gone with the phase-alternating lane ladder.)
BUCKET_HELPERS = {
    "_bucket", "bucket", "bucket_for", "token_budget",
}


def _raw_len_in(node: ast.AST) -> ast.Call | None:
    """First `len(...)` call under `node` NOT nested inside a bucket-helper
    call (which would snap it to the warmed shape grid)."""
    if isinstance(node, ast.Call):
        name = call_name(node)
        if name in BUCKET_HELPERS:
            return None  # snapped — don't descend
        if isinstance(node.func, ast.Name) and node.func.id == "len":
            return node
    for child in ast.iter_child_nodes(node):
        hit = _raw_len_in(child)
        if hit is not None:
            return hit
    return None


@register
class UnbucketedShape(Rule):
    id = "DT006"
    name = "unbucketed-shape"
    summary = "shape constructor fed raw len() — per-length XLA recompile"

    def applies_to(self, path: str) -> bool:
        return any(path.endswith(m) or path == m for m in STEP_PATH_MODULES)

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        stack: list[ast.AST] = []

        def visit(node: ast.AST) -> None:
            stack.append(node)
            if isinstance(node, ast.Call) and call_name(node) in _SHAPE_FNS:
                for arg in list(node.args) + [k.value for k in node.keywords]:
                    hit = _raw_len_in(arg)
                    if hit is not None:
                        out.append(Finding(
                            ctx.path, node.lineno, node.col_offset, self.id,
                            f"`{call_name(node)}` extent uses raw `len()` in "
                            f"{enclosing_name(stack)} — unbucketed shapes "
                            "compile one XLA program per length; snap "
                            "through _bucket()/token_budget()",
                        ))
                        break
            for child in ast.iter_child_nodes(node):
                visit(child)
            stack.pop()

        visit(ctx.tree)
        return out

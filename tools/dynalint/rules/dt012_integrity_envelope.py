"""DT012 — integrity-envelope completeness: stamp once, verify everywhere.

The envelope law (docs/architecture/integrity.md) is a whole-program
property: a CRC is minted at exactly one place (`KvBlockManager.
_store_host`), rides beside the bytes through every tier, and every
trust-boundary crossing verifies it. The doc's **Verification matrix**
is the canonical seam list. This rule grounds itself in that doc and
checks three things against the program:

1. **Doc rows resolve and verify** — every `Seam | site | split` row
   names a function that exists and from which a `verify_block` /
   `block_checksum` call is reachable (loose call graph: required-call
   reachability must over-approximate, a missing edge here would be a
   false alarm). A row whose function vanished or stopped verifying is
   exactly the drift this doc was written to prevent. Anchored at
   `block_manager/integrity.py` line 1 (the envelope's home).
2. **The stamp law** — the doc's single stamp site exists and calls
   `block_checksum` directly (resolved edge; the mint must be local and
   provable).
3. **Corruption seams live under the envelope** — every
   `FAULTS.corrupt(...)` site in `block_manager/` + `disagg/` marks
   bytes crossing a trust boundary; its enclosing function must either
   reach a checksum call itself (sender stamping the frame) or be
   reachable from a stamping/verifying function (a write leg whose
   envelope was minted upstream — e.g. `DiskStorage.write_block`, whose
   rows were stamped at `_store_host` and are re-verified by scrub /
   recovery). A corrupt seam with no plausible path to the envelope is
   injectable-but-undetectable corruption: the exact bug class the
   envelope exists to kill.

Zero-baseline rule: new findings fail CI outright on the target
modules (ci.sh runs it --no-baseline over block_manager/, disagg/,
planner/, engine/).
"""

from __future__ import annotations

import ast
import re

from tools.dynalint.core import FileContext, Finding, Rule, register

DOC = "docs/architecture/integrity.md"
ANCHOR = "dynamo_tpu/block_manager/integrity.py"
INTEGRITY_MODULE = "dynamo_tpu/block_manager/integrity.py"
CORRUPT_SCOPES = ("dynamo_tpu/block_manager/", "dynamo_tpu/disagg/")

#: `| seam | `Class.method` | `split` |` rows of the verification matrix.
_ROW_RE = re.compile(r"\|[^|\n]*\|\s*`([\w.]+)`\s*\|\s*`?(\w+)`?\s*\|")
#: "computed exactly once, at the G1→G2 store law (`KvBlockManager._store_host`)"
_STAMP_RE = re.compile(r"computed exactly once[^(]*\(`([\w.]+)`\)")


def parse_envelope_doc(text: str) -> tuple[str | None, list[tuple[str, str]]]:
    """(stamp qualname, [(verify qualname, counter split), ...]) from the
    architecture doc. Rows before the matrix heading are ignored so the
    markdown table header itself never matches."""
    m = _STAMP_RE.search(text)
    stamp = m.group(1) if m else None
    rows: list[tuple[str, str]] = []
    matrix = text.split("## Verification matrix", 1)
    body = matrix[1] if len(matrix) > 1 else ""
    body = body.split("##", 1)[0]
    for qual, split in _ROW_RE.findall(body):
        rows.append((qual, split))
    return stamp, rows


def _build_model(program) -> dict:
    """Whole-program envelope facts, computed once per run."""
    from tools.dynalint.callgraph import CallGraph

    cached = program.cache.get("dt012")
    if cached is not None:
        return cached
    graph = CallGraph.of(program)
    integ = {
        fid for fid in program.functions
        if fid.startswith(INTEGRITY_MODULE + "::")
    }
    # Functions with a plausible call into integrity.py (stampers and
    # verifiers), excluding integrity.py's own helpers.
    stampers = {
        fid for fid, outs in graph.loose.items()
        if outs & integ and fid not in integ
    }
    model = {
        "graph": graph,
        "integ": integ,
        "stampers": stampers,
        "under_envelope": graph.reachable(stampers, loose=True),
        "doc": program.read_doc(DOC),
    }
    program.cache["dt012"] = model
    return model


def _enclosing_function(program, path: str, line: int) -> str | None:
    """Innermost program function containing `line` in `path`."""
    best = None
    for fid, info in program.functions.items():
        if info.path != path:
            continue
        end = getattr(info.node, "end_lineno", info.lineno)
        if info.lineno <= line <= end:
            if best is None or info.lineno > program.functions[best].lineno:
                best = fid
    return best


@register
class IntegrityEnvelope(Rule):
    id = "DT012"
    name = "integrity-envelope"
    summary = "tier-crossing bytes escape the stamp/verify envelope"
    requires_program = True

    def applies_to(self, path: str) -> bool:
        return path.endswith(".py") and (
            path == ANCHOR
            or any(path.startswith(s) for s in CORRUPT_SCOPES)
        )

    def check_program(self, ctx: FileContext, program) -> list[Finding]:
        model = _build_model(program)
        if model["doc"] is None:
            return []  # partial checkout / fixture tree: nothing to ground
        out: list[Finding] = []
        if ctx.path == ANCHOR:
            out.extend(self._doc_findings(ctx, program, model))
        out.extend(self._corrupt_findings(ctx, program, model))
        return out

    def _doc_findings(self, ctx, program, model) -> list[Finding]:
        graph = model["graph"]
        integ = model["integ"]
        stamp_qual, rows = parse_envelope_doc(model["doc"])
        out: list[Finding] = []
        if not rows:
            out.append(Finding(
                ctx.path, 1, 0, self.id,
                f"{DOC} has no parseable Verification matrix rows — the "
                "envelope law lost its canonical seam list",
            ))
        for qual, split in rows:
            fids = program.find_method(qual)
            if not fids:
                out.append(Finding(
                    ctx.path, 1, 0, self.id,
                    f"{DOC} names verification site `{qual}` ({split}) "
                    "but no such function exists — update the matrix or "
                    "restore the seam",
                ))
                continue
            if not any(g in graph.reachable([f], loose=True)
                       for f in fids for g in integ):
                out.append(Finding(
                    ctx.path, 1, 0, self.id,
                    f"verification site `{qual}` ({split}, {DOC}) no "
                    "longer reaches a verify_block/block_checksum call — "
                    "the seam went unverified",
                ))
        if stamp_qual:
            fids = program.find_method(stamp_qual)
            chk = f"{INTEGRITY_MODULE}::block_checksum"
            if not fids:
                out.append(Finding(
                    ctx.path, 1, 0, self.id,
                    f"{DOC} names stamp site `{stamp_qual}` but no such "
                    "function exists",
                ))
            elif not any(chk in graph.callees(f) for f in fids):
                out.append(Finding(
                    ctx.path, 1, 0, self.id,
                    f"stamp site `{stamp_qual}` ({DOC}) does not call "
                    "block_checksum directly — the envelope mint moved "
                    "or vanished",
                ))
        return out

    def _corrupt_findings(self, ctx, program, model) -> list[Finding]:
        if not any(ctx.path.startswith(s) for s in CORRUPT_SCOPES):
            return []
        out: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "corrupt"
                and "FAULTS" in (ctx.qualname(node.func) or "")
            ):
                continue
            fid = _enclosing_function(program, ctx.path, node.lineno)
            covered = fid is not None and (
                fid in model["stampers"] or fid in model["under_envelope"]
            )
            if not covered:
                point = ""
                if node.args and isinstance(node.args[0], ast.Constant):
                    point = f" ({node.args[0].value})"
                out.append(Finding(
                    ctx.path, node.lineno, node.col_offset, self.id,
                    f"corruption seam{point} is outside the integrity "
                    "envelope — no stamping/verifying function reaches "
                    "this write, so injected corruption here would be "
                    "served, not caught (stamp upstream or verify "
                    "downstream; see docs/architecture/integrity.md)",
                ))
        return out

"""DT016 — recompile hazards: the "zero new XLA programs" law, statically.

The unified-path law (PRs 13–17, ROADMAP): the engine serves every
batch shape from a FIXED ladder of compiled programs; anything that
mints a new XLA program at serve time is a latency cliff measured in
seconds. The budget ladder lives in `engine/runner.py` (the `_jit`
wrapper counts and caps program builds) and the kernel library under
`ops/`. This rule enforces the law's three static hazard shapes over
`dynamo_tpu/`:

1. **Unbudgeted jit sites** — a `jax.jit` / `pjit` call or decorator
   outside the budget ladder (`engine/runner.py`, `ops/**`) creates
   programs nobody counts. Offline/tooling paths (an embedding
   one-shot, a training script) suppress with the reason they are not
   on the serving path.
2. **Traced-value branches** — a function reachable from a jit entry
   point (resolved call graph: a hazard claim must be defensible, so
   no loose edges) that branches on `.any()` / `.all()` / `.item()` /
   `.tolist()` of what is a traced array inside the trace. Under jit
   this either crashes at trace time or forces a host sync +
   per-value recompile.
3. **Unhashable static args** — `jit(..., static_argnums/names=...)`
   pointing at a parameter whose default is a list/dict/set literal:
   every call site with a fresh container is a fresh cache miss.

Jit entry points are the first arguments of jit calls plus decorated
functions; reachability is computed once per run on the precise tier
of the dynaflow call graph.
"""

from __future__ import annotations

import ast

from tools.dynalint.core import FileContext, Finding, Rule, register

#: The budget ladder: files allowed to create XLA programs.
ALLOWED = ("dynamo_tpu/engine/runner.py",)
ALLOWED_PREFIXES = ("dynamo_tpu/ops/",)
JIT_NAMES = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}
_BRANCH_ATTRS = ("any", "all", "item", "tolist")
_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
               ast.SetComp)


def _in_budget(path: str) -> bool:
    return path in ALLOWED or any(
        path.startswith(p) for p in ALLOWED_PREFIXES
    )


def _is_jit_decorator(ctx: FileContext, dec: ast.AST) -> bool:
    """`@jax.jit`, `@jax.jit(...)`, or `@partial(jax.jit, ...)`."""
    if isinstance(dec, ast.Call):
        if ctx.qualname(dec.func) in JIT_NAMES:
            return True
        return any(ctx.qualname(a) in JIT_NAMES for a in dec.args)
    return ctx.qualname(dec) in JIT_NAMES


def _jit_callables(ctx: FileContext):
    """(site node, jit Call or None, decorated def or None) for jit
    call sites AND decorators. Decorator entries carry the decorated
    function so the static-arg check sees its signature; bare
    `@jax.jit` decorators have no Call (no kwargs to inspect)."""
    out: list[tuple[ast.AST, ast.Call | None, ast.AST | None]] = []
    decorator_calls: set[int] = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if _is_jit_decorator(ctx, dec):
                    call = dec if isinstance(dec, ast.Call) else None
                    if call is not None:
                        decorator_calls.add(id(call))
                    out.append((dec, call, node))
    for node in ast.walk(ctx.tree):
        if (
            isinstance(node, ast.Call)
            and ctx.qualname(node.func) in JIT_NAMES
            and id(node) not in decorator_calls
        ):
            out.append((node, node, None))
    return out


def _jit_roots(program) -> set[str]:
    """Function ids jit tracing enters: first args of jit calls,
    decorated functions, and functions handed to the engine's budget
    wrapper."""
    roots: set[str] = set()
    for path, ctx in program.files.items():
        if not path.startswith("dynamo_tpu/"):
            continue
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(
                    _is_jit_decorator(ctx, dec)
                    for dec in node.decorator_list
                ):
                    for fid, info in program.functions.items():
                        if info.path == path and info.node is node:
                            roots.add(fid)
            if not isinstance(node, ast.Call):
                continue
            if ctx.qualname(node.func) not in JIT_NAMES or not node.args:
                continue
            arg = node.args[0]
            fid = None
            if isinstance(arg, ast.Name):
                cand = f"{path}::{arg.id}"
                if cand in program.functions:
                    fid = cand
                elif arg.id in ctx.imports:
                    fid = program.by_dotted.get(ctx.imports[arg.id])
            elif (
                isinstance(arg, ast.Attribute)
                and isinstance(arg.value, ast.Name)
                and arg.value.id in ("self", "cls")
            ):
                for cand in program.by_terminal.get(arg.attr, ()):
                    if program.functions[cand].path == path:
                        fid = cand
                        break
            if fid is not None:
                roots.add(fid)
    return roots


def _jit_reachable(program) -> set[str]:
    from tools.dynalint.callgraph import CallGraph

    cached = program.cache.get("dt016")
    if cached is not None:
        return cached
    graph = CallGraph.of(program)
    reach = graph.reachable(_jit_roots(program), loose=False)
    program.cache["dt016"] = reach
    return reach


@register
class RecompileHazard(Rule):
    id = "DT016"
    name = "recompile-hazard"
    summary = "XLA program outside the budget ladder or a retrace hazard"
    requires_program = True

    def applies_to(self, path: str) -> bool:
        return path.endswith(".py") and path.startswith("dynamo_tpu/")

    def check_program(self, ctx: FileContext, program) -> list[Finding]:
        out: list[Finding] = []
        out.extend(self._site_findings(ctx))
        out.extend(self._branch_findings(ctx, program))
        return out

    def _site_findings(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        budget = _in_budget(ctx.path)
        for node, call, decorated in _jit_callables(ctx):
            if not budget:
                out.append(Finding(
                    ctx.path, node.lineno, node.col_offset, self.id,
                    "jit/pjit site outside the engine budget ladder "
                    "(engine/runner.py, ops/) — serve-path programs "
                    "must be counted and capped; suppress only with "
                    "the reason this path never serves",
                ))
            if call is None:
                continue
            # Unhashable static-arg defaults: resolve the jitted fn.
            static: set[str] = set()
            static_idx: set[int] = set()
            for kw in call.keywords:
                if kw.arg == "static_argnames":
                    for c in ast.walk(kw.value):
                        if isinstance(c, ast.Constant) and isinstance(
                            c.value, str
                        ):
                            static.add(c.value)
                elif kw.arg == "static_argnums":
                    for c in ast.walk(kw.value):
                        if isinstance(c, ast.Constant) and isinstance(
                            c.value, int
                        ):
                            static_idx.add(c.value)
            if not (static or static_idx):
                continue
            target = decorated
            if target is None and call.args:
                fn = call.args[0]
                if isinstance(fn, ast.Name):
                    for n in ast.walk(ctx.tree):
                        if (
                            isinstance(
                                n, (ast.FunctionDef, ast.AsyncFunctionDef)
                            )
                            and n.name == fn.id
                        ):
                            target = n
                            break
            if target is None:
                continue
            params = target.args.args
            defaults = target.args.defaults
            offset = len(params) - len(defaults)
            for i, p in enumerate(params):
                d_i = i - offset
                if d_i < 0 or d_i >= len(defaults):
                    continue
                if (p.arg in static or i in static_idx) and isinstance(
                    defaults[d_i], _UNHASHABLE
                ):
                    out.append(Finding(
                        ctx.path, defaults[d_i].lineno,
                        defaults[d_i].col_offset, self.id,
                        f"static arg `{p.arg}` of jitted "
                        f"`{target.name}` defaults to an unhashable "
                        "container — every fresh container is a fresh "
                        "trace-cache miss (use a tuple or hashable "
                        "config object)",
                    ))
        return out

    def _branch_findings(self, ctx: FileContext, program) -> list[Finding]:
        reach = _jit_reachable(program)
        local = [
            program.functions[fid] for fid in reach
            if program.functions[fid].path == ctx.path
        ]
        out: list[Finding] = []
        for info in local:
            for node in ast.walk(info.node):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                for c in ast.walk(node.test):
                    if (
                        isinstance(c, ast.Call)
                        and isinstance(c.func, ast.Attribute)
                        and c.func.attr in _BRANCH_ATTRS
                    ):
                        out.append(Finding(
                            ctx.path, node.lineno, node.col_offset,
                            self.id,
                            f"`{info.qualname}` is jit-reachable and "
                            f"branches on .{c.func.attr}() — under "
                            "trace this is a host sync / per-value "
                            "retrace (hoist the branch out of the "
                            "traced region or use lax.cond)",
                        ))
        return out

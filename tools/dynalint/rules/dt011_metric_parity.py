"""DT011 — metric-surface parity: engine callback vs HTTP /metrics vs
the standalone exporter.

The same gauge set is hand-wired in three places every PR: the engine's
metrics callback (``TpuEngine._flush_side_channels`` building the ``m``
dict the WorkerMetricsPublisher ships), the frontend's ``/metrics``
handler (``llm/http_service.py`` copying named keys out of the readiness
snapshot), and the standalone exporter's ``_GAUGES`` table
(``llm/metrics_exporter.py``). A name added to one and forgotten on
another silently vanishes from dashboards — drift nobody notices until
an incident needs the missing counter.

This rule extracts the three name sets statically and diffs them:

- **engine names**: string keys written via ``m["name"] = ...`` inside
  ``_flush_side_channels``, plus the ``kvbm_*`` dict-literal keys in
  ``_kvbm_gauges`` (merged via ``m.update``).
- **HTTP surface**: string constants inside every ``_metrics`` handler
  in ``llm/http_service.py`` (the copy tuple + ``set_gauge`` literals),
  with ``.startswith((...))`` prefixes treated as wildcard covers.
- **exporter surface**: first elements of the module-level ``_GAUGES``
  tuple in ``llm/metrics_exporter.py``.

Every engine name must be covered by both downstream surfaces. Names
that reach the callback through dynamic ``m.update(...)`` merges
(CompileStats/coloc snapshots) are invisible to this extraction — the
rule's contract covers the literally-registered names, which is where
every historical drift happened.

Findings anchor at the engine-side registration line, so a deliberate
engine-only gauge is suppressed exactly where it is registered.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path

from tools.dynalint.core import FileContext, Finding, Rule, register

ENGINE_ANCHOR = "dynamo_tpu/engine/engine.py"
HTTP_SURFACE = "dynamo_tpu/llm/http_service.py"
EXPORTER_SURFACE = "dynamo_tpu/llm/metrics_exporter.py"

#: Functions in the anchor whose literal keys define the callback set.
_ENGINE_FUNCS = ("_flush_side_channels", "_kvbm_gauges")

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]{2,}$")


def _functions_named(tree: ast.AST, names: tuple[str, ...]) -> list[ast.AST]:
    return [
        n for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        and n.name in names
    ]


def engine_metric_names(tree: ast.AST) -> dict[str, tuple[int, int]]:
    """Metric name -> (line, col) of its registration in the engine
    callback: `m["x"] = ...` subscript-assign keys plus metric-shaped
    dict-literal keys, within the anchor functions."""
    out: dict[str, tuple[int, int]] = {}
    for fn in _functions_named(tree, _ENGINE_FUNCS):
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Subscript)
                        and isinstance(t.slice, ast.Constant)
                        and isinstance(t.slice.value, str)
                        and _NAME_RE.match(t.slice.value)
                    ):
                        out.setdefault(
                            t.slice.value, (node.lineno, node.col_offset)
                        )
            elif isinstance(node, ast.Dict):
                for k in node.keys:
                    if (
                        isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                        and _NAME_RE.match(k.value)
                    ):
                        out.setdefault(k.value, (k.lineno, k.col_offset))
    return out


def http_metric_surface(tree: ast.AST) -> tuple[set[str], set[str]]:
    """(explicit names, wildcard prefixes) exported by the `/metrics`
    handlers. Every string constant in a handler body counts as an
    explicit name (over-approximate on purpose — extra strings only make
    the surface more permissive, never produce a false finding);
    constants inside `.startswith(...)` arguments become prefixes."""
    names: set[str] = set()
    prefixes: set[str] = set()
    for fn in _functions_named(tree, ("_metrics",)):
        startswith_args: set[int] = set()
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "startswith"
            ):
                for arg in node.args:
                    for c in ast.walk(arg):
                        if isinstance(c, ast.Constant) and isinstance(
                            c.value, str
                        ):
                            prefixes.add(c.value)
                            startswith_args.add(id(c))
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and id(node) not in startswith_args
                and _NAME_RE.match(node.value)
            ):
                names.add(node.value)
    return names, prefixes


def exporter_metric_names(tree: ast.AST) -> set[str]:
    """First elements of the module-level `_GAUGES` tuple-of-tuples."""
    out: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(
            isinstance(t, ast.Name) and t.id == "_GAUGES"
            for t in node.targets
        ):
            continue
        for elt in getattr(node.value, "elts", []):
            first = getattr(elt, "elts", [None])[0]
            if isinstance(first, ast.Constant) and isinstance(
                first.value, str
            ):
                out.add(first.value)
    return out


def parity_findings(
    engine_ctx: FileContext,
    http_source: str,
    exporter_source: str,
    rule_id: str = "DT011",
) -> list[Finding]:
    """Pure parity diff — the rule's core, separated for fixture tests."""
    engine = engine_metric_names(engine_ctx.tree)
    http_names, http_prefixes = http_metric_surface(
        ast.parse(http_source, filename=HTTP_SURFACE)
    )
    exporter = exporter_metric_names(
        ast.parse(exporter_source, filename=EXPORTER_SURFACE)
    )
    out: list[Finding] = []
    for name, (line, col) in sorted(engine.items()):
        missing = []
        if name not in http_names and not any(
            name.startswith(p) for p in http_prefixes
        ):
            missing.append(f"HTTP /metrics ({HTTP_SURFACE})")
        if name not in exporter:
            missing.append(f"the standalone exporter ({EXPORTER_SURFACE})")
        if missing:
            out.append(Finding(
                engine_ctx.path, line, col, rule_id,
                f"engine metric `{name}` is missing from "
                f"{' and '.join(missing)} — register it on every "
                "surface (and ForwardPassMetrics if the exporter "
                "scrapes it) or suppress here with the reason it is "
                "engine-local",
            ))
    return out


@register
class MetricSurfaceParity(Rule):
    id = "DT011"
    name = "metric-surface-parity"
    summary = "engine metric name absent from /metrics or the exporter"

    def applies_to(self, path: str) -> bool:
        return path.endswith(ENGINE_ANCHOR) or path == ENGINE_ANCHOR

    def check(self, ctx: FileContext) -> list[Finding]:
        root = Path(__file__).resolve().parents[3]
        http = root / HTTP_SURFACE
        exporter = root / EXPORTER_SURFACE
        if not http.exists() or not exporter.exists():
            return []  # partial checkout / fixture tree: nothing to diff
        return parity_findings(
            ctx, http.read_text(), exporter.read_text(), self.id
        )

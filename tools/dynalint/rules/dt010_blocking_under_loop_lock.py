"""DT010 — blocking work inside a ``with lock:`` whose lock the asyncio
context also takes.

The `_store`/`stats()` shape PR 9 litigated: a worker thread holds the
pool lock across a memcpy-scale transfer (device gather, host block
materialization, disk write) while a loop-side probe — `stats()`, a
scrape, an admission check — blocks on the same lock. The event loop
thread itself then sits in ``acquire()`` for the duration of the IO, and
every in-flight request stalls behind a telemetry read.

Detection, per module:

1. A lock is **loop-shared** when some ``with lock:`` on it appears in
   an ``async def`` or in a function whose thread-context
   (tools/dynalint/contexts.py) includes the loop.
2. Any ``with`` on a loop-shared lock — in ANY function — whose in-scope
   body performs blocking work (sleep, file/storage IO, zero-arg
   ``.result()``) is flagged.

The fix is the offload-manager idiom: capture bytes under the lock,
move them outside it — or time only the transfer, not the lock wait.
Deliberate holds (tiny writes, rate-sample honesty) get a reasoned
suppression; that is a recorded decision, which is the point.
"""

from __future__ import annotations

import ast

from tools.dynalint.astutil import call_name, enclosing_name, walk_in_scope
from tools.dynalint.contexts import LOOP, build_context_model
from tools.dynalint.core import FileContext, Finding, Rule, register
from tools.dynalint.rules.dt008_lock_order import _lock_id

#: Blocking terminal call names: IO and waits worth a finding when they
#: run under a loop-shared lock. memcpy-scale block-storage moves
#: (read_block/write_block) are the exact shape from the motivation.
_BLOCKING_METHODS = {
    "sleep", "read_block", "write_block", "read_text", "write_text",
    "read_bytes", "write_bytes", "flush", "fsync", "wait",
}
_BLOCKING_QUALNAMES = {
    "time.sleep": "time.sleep",
    "json.dump": "json.dump",
    "os.replace": "os.replace",
    "os.rename": "os.rename",
}


def _blocking_label(ctx: FileContext, node: ast.Call) -> str | None:
    qn = ctx.qualname(node.func)
    if qn in _BLOCKING_QUALNAMES:
        return f"`{_BLOCKING_QUALNAMES[qn]}(...)`"
    if qn == "open":
        return "`open(...)`"
    name = call_name(node)
    if name in _BLOCKING_METHODS and isinstance(node.func, ast.Attribute):
        return f"`.{name}(...)`"
    if (
        name == "result"
        and isinstance(node.func, ast.Attribute)
        and not node.args
        and not node.keywords
    ):
        return "`.result()`"
    return None


@register
class BlockingUnderLoopLock(Rule):
    id = "DT010"
    name = "blocking-under-loop-shared-lock"
    summary = "IO/wait inside `with lock:` on a lock the loop also takes"

    def applies_to(self, path: str) -> bool:
        return path.endswith(".py")

    def check(self, ctx: FileContext) -> list[Finding]:
        model = build_context_model(ctx)

        # Pass 1: which lock ids does the loop context acquire?
        loop_locks: set[str] = set()
        for qual, fnode in model.functions.items():
            if LOOP not in model.of(qual):
                continue
            cls = model.owner_class[qual]
            for node in walk_in_scope(fnode):
                if isinstance(node, (ast.With, ast.AsyncWith)):
                    for item in node.items:
                        lid = _lock_id(ctx, item.context_expr, cls)
                        if lid is not None:
                            loop_locks.add(lid)
        if not loop_locks:
            return []

        # Pass 2: blocking work under any `with` on those locks.
        out: list[Finding] = []
        stack: list[ast.AST] = []
        class_stack: list[str] = []

        def visit(node: ast.AST) -> None:
            stack.append(node)
            if isinstance(node, ast.ClassDef):
                class_stack.append(node.name)
            if isinstance(node, ast.With):
                cls = class_stack[-1] if class_stack else ""
                for item in node.items:
                    lid = _lock_id(ctx, item.context_expr, cls)
                    if lid in loop_locks:
                        hit = self._first_blocking(ctx, node)
                        if hit is not None:
                            label, line, col = hit
                            out.append(Finding(
                                ctx.path, line, col, self.id,
                                f"blocking {label} while holding `{lid}` "
                                f"({enclosing_name(stack)}) — the asyncio "
                                "context also takes this lock, so the "
                                "loop thread stalls for the IO; move the "
                                "work outside the lock (capture-then-"
                                "release) or split the lock",
                            ))
                        break
            for child in ast.iter_child_nodes(node):
                visit(child)
            if isinstance(node, ast.ClassDef):
                class_stack.pop()
            stack.pop()

        visit(ctx.tree)
        return out

    @staticmethod
    def _first_blocking(
        ctx: FileContext, with_node: ast.With
    ) -> tuple[str, int, int] | None:
        # Awaited calls are not blocking in the DT010 sense — they yield
        # the loop (holding a sync lock across them is DT004's finding).
        awaited: set[int] = set()
        for body_stmt in with_node.body:
            for node in [body_stmt, *walk_in_scope(body_stmt)]:
                if isinstance(node, ast.Await) and isinstance(
                    node.value, ast.Call
                ):
                    awaited.add(id(node.value))
        for body_stmt in with_node.body:
            for node in [body_stmt, *walk_in_scope(body_stmt)]:
                if isinstance(node, ast.Call) and id(node) not in awaited:
                    label = _blocking_label(ctx, node)
                    if label is not None:
                        return label, node.lineno, node.col_offset
        return None

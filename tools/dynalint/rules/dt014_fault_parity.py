"""DT014 — fault-point parity: sites ↔ registry ↔ arms, three ways.

A fault point is only worth its runtime cost if all three legs exist:
the **site** (`FAULTS.maybe_fail*` / `FAULTS.corrupt` on the seam), the
**registry entry** (`KNOWN_FAULT_POINTS` in `utils/faults.py`, the
canonical list failure_model.md documents), and the **proof** (a test
or chaos bench that actually arms it — an uninjected seam is a recovery
path nobody has ever watched fire). tests/test_failover.py gates
docs↔code at runtime; this rule is the static, three-way superset over
the whole program:

- a site whose point name is not registered → finding at the call site
  (the seam was added without joining the canon);
- a registry entry no site references → finding at the tuple entry
  (dead canon: the docs promise a seam that does not exist);
- a registry entry with sites but no `FAULTS.arm("point", ...)`
  anywhere in tests/ or benchmarks/ → finding at the tuple entry (the
  seam exists but its recovery path is unproven).

The arm evidence comes from the whole-program context — `tests/` is in
the program universe even though it is never linted — so narrowed runs
(`python -m tools.dynalint dynamo_tpu/utils/faults.py`) still see every
arm. Dynamic arming (env `DYNAMO_TPU_FAULTS`, variables) is invisible
to this extraction on purpose: the law wants a *committed* test.
"""

from __future__ import annotations

import ast

from tools.dynalint.core import FileContext, Finding, Rule, register

REGISTRY_FILE = "dynamo_tpu/utils/faults.py"
REGISTRY_NAME = "KNOWN_FAULT_POINTS"
_SITE_ATTRS = ("maybe_fail", "maybe_fail_async", "corrupt")
#: Where arm() calls count as proof.
_ARM_SCOPES = ("tests/", "benchmarks/")


def _const_point(call: ast.Call) -> str | None:
    if call.args and isinstance(call.args[0], ast.Constant) and isinstance(
        call.args[0].value, str
    ):
        return call.args[0].value
    return None


def fault_model(program) -> dict:
    """sites: point -> [(path, line)]; registry: point -> line;
    arms: set of armed point names. Computed once per run."""
    cached = program.cache.get("dt014")
    if cached is not None:
        return cached
    sites: dict[str, list[tuple[str, int]]] = {}
    registry: dict[str, int] = {}
    arms: set[str] = set()
    for path, ctx in program.files.items():
        in_arm_scope = any(path.startswith(s) for s in _ARM_SCOPES)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            attr = node.func.attr
            point = _const_point(node)
            if point is None:
                continue
            if (
                attr in _SITE_ATTRS
                and path.startswith("dynamo_tpu/")
                and path != REGISTRY_FILE
                and "FAULTS" in (ctx.qualname(node.func) or "")
            ):
                sites.setdefault(point, []).append((path, node.lineno))
            elif attr == "arm" and in_arm_scope:
                arms.add(point)
        if path == REGISTRY_FILE:
            for node in ast.walk(ctx.tree):
                target = None
                if isinstance(node, ast.Assign) and node.targets:
                    target = node.targets[0]
                elif isinstance(node, ast.AnnAssign):
                    target = node.target
                if not (
                    isinstance(target, ast.Name)
                    and target.id == REGISTRY_NAME
                ):
                    continue
                for elt in getattr(node.value, "elts", []):
                    if isinstance(elt, ast.Constant) and isinstance(
                        elt.value, str
                    ):
                        registry[elt.value] = elt.lineno
    model = {"sites": sites, "registry": registry, "arms": arms}
    program.cache["dt014"] = model
    return model


@register
class FaultPointParity(Rule):
    id = "DT014"
    name = "fault-point-parity"
    summary = "fault point missing a site, registry entry, or arming test"
    requires_program = True

    def applies_to(self, path: str) -> bool:
        return path.endswith(".py") and path.startswith("dynamo_tpu/")

    def check_program(self, ctx: FileContext, program) -> list[Finding]:
        model = fault_model(program)
        sites, registry, arms = (
            model["sites"], model["registry"], model["arms"]
        )
        if not registry:
            return []  # fixture program without the registry: no canon
        out: list[Finding] = []
        # Unregistered sites anchor where the seam was instrumented.
        for point, locs in sorted(sites.items()):
            if point in registry:
                continue
            for path, line in locs:
                if path != ctx.path:
                    continue
                out.append(Finding(
                    ctx.path, line, 0, self.id,
                    f"fault point '{point}' is not in {REGISTRY_NAME} "
                    f"({REGISTRY_FILE}) — register the seam (and "
                    "document it in failure_model.md) or drop the call",
                ))
        # Dead / unproven registry entries anchor at the tuple entry.
        if ctx.path == REGISTRY_FILE:
            for point, line in sorted(registry.items()):
                if point not in sites:
                    out.append(Finding(
                        ctx.path, line, 0, self.id,
                        f"registry entry '{point}' has no "
                        "FAULTS.maybe_fail/corrupt call site — dead "
                        "canon; remove it or instrument the seam",
                    ))
                elif point not in arms:
                    out.append(Finding(
                        ctx.path, line, 0, self.id,
                        f"fault point '{point}' is never armed by any "
                        "test or bench (FAULTS.arm in tests/ or "
                        "benchmarks/) — its recovery path is unproven",
                    ))
        return out

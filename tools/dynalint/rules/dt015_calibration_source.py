"""DT015 — calibration single-source: no literal shadows of measured
constants.

`planner/calibration.py` pins the cost model to RECORDED chip runs
(its header: "Derived, not tuned: change these only against a NEW
recorded run"), and its consumers import the symbols so a re-fit
reprices everyone at once. A numeric literal elsewhere that *equals* a
calibration constant is a shadow copy: it agrees today and silently
diverges at the next re-fit — the drift class
tests/test_calibration.py gates for two named consumers, generalized
here to every pricing module.

Detection: collect module-level numeric constants from calibration.py
(ints < 1000 are skipped — `R04_ISL = 128` would indict every
unrelated 128), then flag any equal literal in the pricing scopes
(planner/, mocker/, block_manager/, llm/kv_router/, engine/, disagg/,
benchmarks/, bench.py). Unit-scaled shadows are matched too —
`21.7e9` is `HANDOFF_GBPS` in bytes/s — but only for literals ≥ 1e6,
where the magnitude itself is distinctive (small scaled values like
0.5 collide with half the numbers in the codebase).

The fix is an import, not a suppression: a genuinely unrelated literal
that happens to collide takes a line suppression saying what it
actually is.
"""

from __future__ import annotations

import ast
import math

from tools.dynalint.core import FileContext, Finding, Rule, register

SOURCE = "dynamo_tpu/planner/calibration.py"
SCOPES = (
    "dynamo_tpu/planner/",
    "dynamo_tpu/mocker/",
    "dynamo_tpu/block_manager/",
    "dynamo_tpu/llm/kv_router/",
    "dynamo_tpu/engine/",
    "dynamo_tpu/disagg/",
    "benchmarks/",
)
#: Ints below this are too common to treat as calibration shadows.
_MIN_INT = 1000
#: Scaled (unit-conversion) matches require the literal itself to be
#: this large — magnitude is what makes `21.7e9` unmistakable.
_MIN_SCALED = 1e6
_SCALES = (1e3, 1e6, 1e9)


def calibration_constants(tree: ast.AST) -> dict[str, float]:
    """Module-level `NAME = <numeric literal>` bindings worth policing
    (derived BinOp constants are compositions of these, so covering the
    leaves covers them)."""
    out: dict[str, float] = {}
    for node in getattr(tree, "body", []):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        t = node.targets[0]
        if not (isinstance(t, ast.Name) and t.id.isupper()):
            continue
        v = node.value
        if not (
            isinstance(v, ast.Constant)
            and isinstance(v.value, (int, float))
            and not isinstance(v.value, bool)
        ):
            continue
        if isinstance(v.value, int) and abs(v.value) < _MIN_INT:
            continue
        out[t.id] = float(v.value)
    return out


def shadow_of(value: float, constants: dict[str, float]) -> str | None:
    """The calibration symbol `value` shadows, or None."""
    for name, c in constants.items():
        if math.isclose(value, c, rel_tol=1e-9):
            return name
        if abs(value) >= _MIN_SCALED:
            for scale in _SCALES:
                if math.isclose(value, c * scale, rel_tol=1e-9):
                    return f"{name} (×{scale:g})"
    return None


@register
class CalibrationSingleSource(Rule):
    id = "DT015"
    name = "calibration-single-source"
    summary = "numeric literal shadows a planner/calibration.py constant"
    requires_program = True

    def applies_to(self, path: str) -> bool:
        if not path.endswith(".py") or path == SOURCE:
            return False
        return path == "bench.py" or any(
            path.startswith(s) for s in SCOPES
        )

    def check_program(self, ctx: FileContext, program) -> list[Finding]:
        constants = program.cache.get("dt015")
        if constants is None:
            src = program.files.get(SOURCE)
            constants = (
                calibration_constants(src.tree) if src is not None else {}
            )
            program.cache["dt015"] = constants
        if not constants:
            return []  # fixture program without calibration.py
        out: list[Finding] = []
        for node in ast.walk(ctx.tree):
            if not (
                isinstance(node, ast.Constant)
                and isinstance(node.value, (int, float))
                and not isinstance(node.value, bool)
            ):
                continue
            if isinstance(node.value, int) and abs(node.value) < _MIN_INT:
                continue
            sym = shadow_of(float(node.value), constants)
            if sym is not None:
                out.append(Finding(
                    ctx.path, node.lineno, node.col_offset, self.id,
                    f"literal {node.value!r} shadows calibration symbol "
                    f"{sym} ({SOURCE}) — import the symbol so the next "
                    "re-fit reprices this site too (or suppress with "
                    "what this number actually is)",
                ))
        return out

"""DT003 — broad `except` that swallows and continues in a critical seam.

The exact shape of the r05 donated-KV-buffer bug: a `except Exception:`
around a DONATING dispatch logged the failure and carried on, leaving
`kv_caches` pointing at invalidated device memory — every later request
read garbage. Inside the engine step path, KV donation/transfer, the
block-manager pumps, and stepcast, a handler that catches everything and
does not re-raise must be a DELIBERATE decision: either narrow the
exception, re-raise after cleanup, or suppress with a written reason
(`# dynalint: allow[DT003] <why continuing is safe>`).

Scope is the critical-seam file set below, not the whole tree — broad
handlers at the HTTP edge or in CLI glue are ordinary defensive code.
"""

from __future__ import annotations

import ast

from tools.dynalint.astutil import contains_raise, enclosing_name
from tools.dynalint.core import FileContext, Finding, Rule, register

#: Critical seams: engine dispatch + donation, disaggregated KV transfer,
#: block-manager offload/onboard pumps, stepcast collectives.
CRITICAL_SEAMS = (
    "dynamo_tpu/engine/",
    "dynamo_tpu/disagg/",
    "dynamo_tpu/block_manager/",
    "dynamo_tpu/parallel/stepcast.py",
)

_BROAD = {"Exception", "BaseException"}


def _is_broad(ctx: FileContext, handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:  # bare `except:`
        return True
    if isinstance(t, ast.Tuple):
        return any(ctx.qualname(e) in _BROAD for e in t.elts)
    return ctx.qualname(t) in _BROAD


@register
class BroadExceptContinue(Rule):
    id = "DT003"
    name = "broad-except-continues"
    summary = "except Exception without re-raise in a critical seam"

    def applies_to(self, path: str) -> bool:
        return path.endswith(".py") and any(
            path.startswith(seam) or ("/" + seam) in path
            for seam in CRITICAL_SEAMS
        )

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        stack: list[ast.AST] = []

        def visit(node: ast.AST) -> None:
            stack.append(node)
            if isinstance(node, ast.ExceptHandler) and _is_broad(ctx, node):
                # Any `raise` in the handler body (outside nested defs)
                # counts as a deliberate propagation path.
                if not contains_raise(node):
                    caught = "bare except" if node.type is None else (
                        f"except {ast.unparse(node.type)}"
                    )
                    out.append(Finding(
                        ctx.path, node.lineno, node.col_offset, self.id,
                        f"broad `{caught}` swallows and continues in "
                        f"{enclosing_name(stack)} — narrow it, re-raise, "
                        "or justify with `# dynalint: allow[DT003] <reason>`",
                    ))
            for child in ast.iter_child_nodes(node):
                visit(child)
            stack.pop()

        visit(ctx.tree)
        return out

"""Rule modules — importing this package registers every rule.

Add a new rule by dropping a `dtNNN_*.py` module here that defines a
`Rule` subclass decorated with `@register`, then import it below, add a
fixture pair to tests/test_dynalint.py, and document it in
docs/development/static_analysis.md.
"""

from tools.dynalint.rules import (  # noqa: F401
    dt001_blocking_async,
    dt002_discarded_task,
    dt003_broad_except,
    dt004_lock_across_await,
    dt005_host_sync,
    dt006_unbucketed_shapes,
    dt007_cross_context_mutation,
    dt008_lock_order,
    dt009_loop_affinity,
    dt010_blocking_under_loop_lock,
    dt011_metric_parity,
    dt012_integrity_envelope,
    dt013_atomic_durability,
    dt014_fault_parity,
    dt015_calibration_source,
    dt016_recompile_hazard,
)

"""DT001 — blocking call inside `async def`.

A synchronous sleep, subprocess call, sync file read, or
`Future.result()` inside a coroutine stalls the whole event loop: on the
serving path that freezes EVERY in-flight request, not just the caller
(ingress pumps, control-plane keepalives and stream watchers all share
one loop). Use `await asyncio.sleep`, `asyncio.to_thread`, the async
subprocess API, or move the work onto an executor.
"""

from __future__ import annotations

import ast

from tools.dynalint.astutil import call_name, enclosing_name, walk_in_scope
from tools.dynalint.core import FileContext, Finding, Rule, register

# Qualified-name prefixes that block the loop outright. A trailing dot
# matches the whole module namespace.
_BLOCKING_PREFIXES = (
    "time.sleep",
    "subprocess.",
    "os.system",
    "os.popen",
    "os.waitpid",
    "os.wait",
    "socket.create_connection",
    "requests.",
    "urllib.request.",
)

# Methods that synchronously wait or do sync file IO. `.result()` only
# counts with no arguments — `result(timeout=...)` is an explicit bounded
# wait the author chose.
_BLOCKING_METHODS = {
    "result": "Future.result() blocks until completion",
    "read_text": "sync file read",
    "write_text": "sync file write",
    "read_bytes": "sync file read",
    "write_bytes": "sync file write",
}
_ZERO_ARG_ONLY = {"result"}


@register
class BlockingCallInAsync(Rule):
    id = "DT001"
    name = "blocking-call-in-async"
    summary = "sync sleep/subprocess/file-IO/.result() inside async def"

    def check(self, ctx: FileContext) -> list[Finding]:
        out: list[Finding] = []
        stack: list[ast.AST] = []

        def visit(node: ast.AST) -> None:
            stack.append(node)
            if isinstance(node, ast.AsyncFunctionDef):
                self._check_coroutine(ctx, node, stack, out)
            for child in ast.iter_child_nodes(node):
                visit(child)
            stack.pop()

        visit(ctx.tree)
        return out

    def _check_coroutine(
        self,
        ctx: FileContext,
        fn: ast.AsyncFunctionDef,
        stack: list[ast.AST],
        out: list[Finding],
    ) -> None:
        where = enclosing_name(stack)
        for node in walk_in_scope(fn):
            if not isinstance(node, ast.Call):
                continue
            label = self._blocking_label(ctx, node)
            if label is not None:
                out.append(Finding(
                    ctx.path, node.lineno, node.col_offset, self.id,
                    f"blocking call {label} inside `async def` "
                    f"({where}) stalls the event loop",
                ))

    def _blocking_label(self, ctx: FileContext, node: ast.Call) -> str | None:
        qn = ctx.qualname(node.func)
        if qn is not None:
            if qn == "open":
                return "`open(...)` (sync file IO)"
            for prefix in _BLOCKING_PREFIXES:
                if qn == prefix or (prefix.endswith(".") and qn.startswith(prefix)):
                    return f"`{qn}(...)`"
        name = call_name(node)
        if name in _BLOCKING_METHODS and isinstance(node.func, ast.Attribute):
            if name in _ZERO_ARG_ONLY and (node.args or node.keywords):
                return None
            return f"`.{name}()` ({_BLOCKING_METHODS[name]})"
        return None

"""``python -m dynamo_tpu`` → the dynamo-tpu CLI (cli.py)."""

from dynamo_tpu.cli import main

if __name__ == "__main__":
    main()

"""GraphOperator: reconciles api-store deployment specs into k8s objects.

Role of the reference's Go kubebuilder operator (reference:
deploy/cloud/operator — controllers reconciling DynamoGraphDeployment CRDs
into Deployments/Services, with etcd cleanup on teardown). TPU re-design:
specs live in the control plane's object store (the same bucket
sdk/api_store.py serves over REST), the reconcile loop is plain asyncio,
and kubectl is the only cluster dependency (kube.KubectlApi; tests drive
kube.FakeKube). Reconciliation is level-triggered: every interval, desired
manifests are re-rendered from the stored specs, diffed by spec-hash
annotation, applied, and orphans — children of deleted or shrunk specs —
are garbage-collected by owner label. Status (ready/desired per service)
is written back to the `operator-status` bucket, which the api-store can
serve alongside the spec (the CRD status subresource analogue).
"""

from __future__ import annotations

import asyncio
import json
import logging
import time

from dynamo_tpu.operator.kube import KubeApi, Manifest
from dynamo_tpu.operator.resources import (
    ANNOTATION_SPEC_HASH,
    LABEL_APP,
    LABEL_DEPLOYMENT,
    GraphDeployment,
    render,
)
from dynamo_tpu.sdk.api_store import DEPLOYMENT_BUCKET

logger = logging.getLogger(__name__)

STATUS_BUCKET = "operator-status"
#: bus subject the api-store publishes on every deployment-spec mutation —
#: the operator's second watch source (cluster watch being the first).
SPEC_EVENTS_SUBJECT = "operator.spec-events"


class GraphOperator:
    def __init__(
        self,
        drt,
        kube: KubeApi,
        namespace: str = "dynamo",
        interval_s: float = 30.0,
    ) -> None:
        """``interval_s`` is the RESYNC period, not the reaction time: the
        loop is watch-driven (cluster watch + api-store spec events kick
        an immediate reconcile); the periodic pass only covers missed
        events — the informer resync pattern of the reference's
        controller-runtime operator."""
        self._store = drt.bus
        self.kube = kube
        self.namespace = namespace
        self.interval_s = interval_s
        self._task: asyncio.Task | None = None
        self._kick = asyncio.Event()
        self._stop_watch = None
        self._spec_sub = None
        self.reconcile_count = 0
        # Reconciles are SERIALIZED: the watch-kicked background pass and
        # a caller's reconcile_once otherwise interleave at every
        # to_thread kube call, and two passes reading pre-apply state
        # double-apply the same children (benign in k8s — server-side
        # apply is idempotent — but wasted API calls and nondeterministic
        # patch counts). controller-runtime serializes per key; one lock
        # is the single-operator equivalent.
        self._reconcile_lock = asyncio.Lock()

    # -- lifecycle ----------------------------------------------------------
    async def start(self) -> "GraphOperator":
        loop = asyncio.get_running_loop()

        ensure_crd = getattr(self.kube, "ensure_crd", None)
        self._mirror_crs = ensure_crd is not None
        if ensure_crd is not None:
            # Backend speaks CRDs (restkube.RestKube / KubectlApi):
            # install the GraphDeployment definition so specs are
            # cluster-visible via `kubectl get graphdeployments` with
            # live status. The manifest is a packaged constant
            # (resources.GRAPHDEPLOYMENT_CRD) — installed trees have no
            # deploy/ directory to read from.
            from dynamo_tpu.operator.resources import GRAPHDEPLOYMENT_CRD

            await asyncio.to_thread(ensure_crd, GRAPHDEPLOYMENT_CRD)

        def on_cluster_event(_obj) -> None:
            # May fire from a watch reader thread.
            loop.call_soon_threadsafe(self._kick.set)

        watch = getattr(self.kube, "watch", None)
        if watch is not None:
            # namespace=None: children live in each SPEC's namespace, so
            # the watch must span all of them (label-scoped).
            self._stop_watch = watch(
                None, {"app": LABEL_APP}, on_cluster_event
            )
        self._spec_sub = await self._store.subscribe(SPEC_EVENTS_SUBJECT)
        self._spec_task = asyncio.create_task(self._pump_spec_events())
        self._task = asyncio.create_task(self._run())
        return self

    async def _pump_spec_events(self) -> None:
        try:
            async for _msg in self._spec_sub:
                self._kick.set()
            # A CLOSED subscription ends the async-for without raising —
            # that silent path degrades to resync-only too, so log it.
            logger.warning(
                "spec-event subscription closed; reconciles now resync-only"
            )
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001
            # Spec kicks degrade to the resync net — say so, loudly.
            logger.exception(
                "spec-event subscription died; reconciles now resync-only"
            )

    async def stop(self) -> None:
        if self._stop_watch is not None:
            self._stop_watch()
        if self._spec_sub is not None:
            # Deregister from the bus: a dangling open subscription keeps
            # soaking up queue-group deliveries (and memory) forever.
            self._spec_sub.close()
        for t in (getattr(self, "_spec_task", None), self._task):
            if t:
                t.cancel()
                try:
                    await t
                except asyncio.CancelledError:
                    pass
                except Exception:  # noqa: BLE001 — already logged; a dead
                    pass          # helper must not break shutdown

    async def _run(self) -> None:
        while True:
            try:
                await self.reconcile_once()
            except Exception:  # noqa: BLE001 - the loop must survive
                logger.exception("reconcile failed")
            # Watch-driven: a cluster or spec event wakes the loop now;
            # the timeout is only the resync safety net.
            try:
                await asyncio.wait_for(
                    self._kick.wait(), timeout=self.interval_s
                )
            except asyncio.TimeoutError:
                pass
            self._kick.clear()

    # -- reconciliation -----------------------------------------------------
    async def reconcile_once(self) -> dict[str, dict]:
        """One level-triggered pass over every stored deployment spec.

        Returns the status map written to the status bucket (per
        deployment: per-service desired/ready + Ready condition). All
        kube calls run in a worker thread so a slow kubectl never stalls
        the event loop (and its control-plane heartbeats). Passes are
        serialized (see _reconcile_lock): a kicked background pass queues
        behind a running one instead of interleaving with it."""
        async with self._reconcile_lock:
            return await self._reconcile_locked()

    async def _reconcile_locked(self) -> dict[str, dict]:
        self.reconcile_count += 1
        names = await self._store.list_objects(DEPLOYMENT_BUCKET)
        statuses: dict[str, dict] = {}
        desired_children: dict[tuple[str, str, str], Manifest] = {}
        deployments: list[GraphDeployment] = []
        errored: set[str] = set()
        for name in names:
            raw = await self._store.get_object(DEPLOYMENT_BUCKET, name)
            if raw is None:
                continue
            try:
                record = json.loads(raw)
            except ValueError as exc:
                statuses[name] = {"error": str(exc), "ready": False}
                errored.add(name)
                continue
            try:
                dep = GraphDeployment.from_record(record)
            except (ValueError, KeyError) as exc:
                # A bad spec must never trigger GC of its running
                # children — mark the owner protected and keep state.
                # Keep the namespace on record so a later deletion still
                # garbage-collects in the right place.
                statuses[name] = {
                    "error": str(exc),
                    "ready": False,
                    "namespace": (record.get("spec") or {}).get("namespace"),
                }
                errored.add(name)
                continue
            deployments.append(dep)
            for m in render(dep):
                md = m["metadata"]
                desired_children[(m["kind"], md["namespace"], md["name"])] = m

        # GC must look everywhere children may live: the operator's own
        # namespace, every current spec's namespace, and any namespace a
        # previous pass recorded in the status bucket (so children of a
        # deleted spec in a non-default namespace still get cleaned up).
        namespaces = {self.namespace} | {d.namespace for d in deployments}
        for sname in await self._store.list_objects(STATUS_BUCKET):
            raw = await self._store.get_object(STATUS_BUCKET, sname)
            if raw:
                ns = json.loads(raw).get("namespace")
                if ns:
                    namespaces.add(ns)

        kube_statuses = await asyncio.to_thread(
            self._reconcile_kube, desired_children, deployments, errored,
            namespaces,
        )
        statuses.update(kube_statuses)

        # Drop status entries for deleted specs.
        for stale in set(await self._store.list_objects(STATUS_BUCKET)) - set(
            statuses
        ):
            await self._store.delete_object(STATUS_BUCKET, stale)
        for name, status in statuses.items():
            await self._store.put_object(
                STATUS_BUCKET, name, json.dumps(status).encode()
            )
        return statuses

    def _reconcile_kube(
        self,
        desired_children: dict[tuple[str, str, str], Manifest],
        deployments: list[GraphDeployment],
        errored: set[str],
        namespaces: set[str],
    ) -> dict[str, dict]:
        """Synchronous cluster half of the pass (runs in a thread)."""
        # Apply new/changed children (spec-hash annotation is the detector).
        for key, manifest in desired_children.items():
            kind, ns, name = key
            existing = self.kube.get(kind, ns, name)
            want_hash = (
                manifest["metadata"].get("annotations", {})
                .get(ANNOTATION_SPEC_HASH)
            )
            have_hash = (
                (existing or {}).get("metadata", {}).get("annotations", {})
                .get(ANNOTATION_SPEC_HASH)
            )
            if existing is None or (want_hash and want_hash != have_hash):
                self.kube.apply(manifest)

        # Garbage-collect orphans: app-labelled children whose owning spec
        # (or service) no longer exists (reference: operator teardown
        # cleanup, deploy/cloud/operator/internal/etcd/etcd.go). Children
        # of errored specs are protected until the spec parses again.
        for kind in ("Deployment", "Service"):
            for ns in sorted(namespaces):
                for obj in self.kube.list(kind, ns, {"app": LABEL_APP}):
                    md = obj.get("metadata", {})
                    owner = md.get("labels", {}).get(LABEL_DEPLOYMENT)
                    key = (kind, md.get("namespace"), md.get("name"))
                    if owner and owner not in errored and (
                        key not in desired_children
                    ):
                        self.kube.delete(*key)

        # Status per deployment (namespace recorded for future GC passes).
        statuses: dict[str, dict] = {}
        for dep in deployments:
            svc_status = {}
            all_ready = True
            for svc in dep.services:
                obj = self.kube.get(
                    "Deployment", dep.namespace,
                    f"{dep.name}-{svc.name.lower()}",
                )
                ready = (
                    (obj or {}).get("status", {}).get("readyReplicas", 0)
                )
                svc_status[svc.name] = {
                    "desired": svc.replicas, "ready": ready,
                }
                all_ready = all_ready and ready >= svc.replicas
            statuses[dep.name] = {
                "services": svc_status,
                "ready": all_ready,
                "namespace": dep.namespace,
                "updated_at": time.time(),
            }

        self._mirror_graphdeployments(deployments, statuses, errored,
                                      namespaces)
        return statuses

    def _mirror_graphdeployments(
        self,
        deployments: list[GraphDeployment],
        statuses: dict[str, dict],
        errored: set[str],
        namespaces: set[str],
    ) -> None:
        """Keep one GraphDeployment custom object per spec (the CRD
        mirror — cluster-visible spec + readiness; reference: the status
        subresource its Go operator writes). Applied only when content
        changes (volatile timestamps excluded) so steady-state reconciles
        stay apply-free; stale mirrors GC by owner label like any child.

        Only runs on backends that installed the CRD (start() gates on
        ensure_crd) — a backend without it would fail EVERY apply with
        'no matches for kind GraphDeployment' and poison the whole
        reconcile pass."""
        if not getattr(self, "_mirror_crs", False):
            return
        mirror_keys = set()
        for dep in deployments:
            status = {
                k: v
                for k, v in statuses[dep.name].items()
                if k != "updated_at"
            }
            manifest = {
                "apiVersion": "dynamo.tpu/v1alpha1",
                "kind": "GraphDeployment",
                "metadata": {
                    "name": dep.name,
                    "namespace": dep.namespace,
                    "labels": {
                        "app": LABEL_APP,
                        LABEL_DEPLOYMENT: dep.name,
                    },
                },
                "spec": {
                    "services": {
                        s.name: {"role": s.role, "replicas": s.replicas}
                        for s in dep.services
                    }
                },
                "status": status,
            }
            mirror_keys.add(("GraphDeployment", dep.namespace, dep.name))
            have = self.kube.get("GraphDeployment", dep.namespace, dep.name)
            if have is None or any(
                (have.get(k) or {}) != manifest[k]
                for k in ("spec", "status")
            ):
                self.kube.apply(manifest)
        for ns in sorted(namespaces):
            try:
                objs = self.kube.list("GraphDeployment", ns, {"app": LABEL_APP})
            except Exception:  # noqa: BLE001 — CRD not installed (e.g.
                return        # kubectl backend without ensure_crd)
            for obj in objs:
                md = obj.get("metadata", {})
                owner = md.get("labels", {}).get(LABEL_DEPLOYMENT)
                key = ("GraphDeployment", md.get("namespace"), md.get("name"))
                if owner and owner not in errored and key not in mirror_keys:
                    self.kube.delete(*key)

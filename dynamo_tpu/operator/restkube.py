"""Direct Kubernetes REST API client for the operator.

The client-go role of the reference's kubebuilder operator
(reference: deploy/cloud/operator — controller-runtime over client-go):
instead of shelling out to kubectl (kube.KubectlApi, kept as a fallback),
talk to the API server's documented REST surface directly:

- server-side apply: ``PATCH .../{name}?fieldManager=...&force=true``
  with ``application/apply-patch+yaml`` (the canonical declarative verb);
- list: ``GET`` with ``labelSelector``;
- watch: streaming ``GET ...?watch=1`` (one JSON event per line), with
  reconnect+backoff — API servers close watches routinely;
- CRDs: ensure our GraphDeployment CRD exists
  (``/apis/apiextensions.k8s.io/v1/customresourcedefinitions``), so the
  operator's deployment records are ALSO visible to ``kubectl get
  graphdeployments`` with live status (the CRD status the reference
  operator writes via the status subresource).

Configuration follows the in-cluster convention: when constructed via
``RestKube.in_cluster()`` the client reads KUBERNETES_SERVICE_HOST/PORT
and the mounted service-account token. Tests drive the same wire
protocol against tests/k8s_apiserver.py, an in-repo API-server emulator
(this build environment has no kubectl/kind/network egress — see
deploy/README.md "validation level").
"""

from __future__ import annotations

import json
import logging
import threading
import time
from typing import Any

import httpx

Manifest = dict[str, Any]

logger = logging.getLogger(__name__)

FIELD_MANAGER = "dynamo-tpu-operator"

#: kind -> (API group/version prefix, plural, namespaced)
KINDS: dict[str, tuple[str, str, bool]] = {
    "Deployment": ("apis/apps/v1", "deployments", True),
    "Service": ("api/v1", "services", True),
    "GraphDeployment": (
        "apis/dynamo.tpu/v1alpha1", "graphdeployments", True,
    ),
    "CustomResourceDefinition": (
        "apis/apiextensions.k8s.io/v1", "customresourcedefinitions", False,
    ),
}

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class RestKube:
    def __init__(
        self,
        base_url: str,
        token: str | None = None,
        verify: bool | str = True,
        timeout_s: float = 30.0,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        headers = {}
        if token:
            headers["Authorization"] = f"Bearer {token}"
        self._client = httpx.Client(
            base_url=self.base_url,
            headers=headers,
            verify=verify,
            timeout=timeout_s,
        )

    @staticmethod
    def in_cluster() -> "RestKube":
        import os

        host = os.environ["KUBERNETES_SERVICE_HOST"]
        port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
        with open(f"{SA_DIR}/token") as f:
            token = f.read().strip()
        return RestKube(
            f"https://{host}:{port}", token=token, verify=f"{SA_DIR}/ca.crt"
        )

    # -- path helpers -------------------------------------------------------
    def _collection(self, kind: str, namespace: str | None) -> str:
        prefix, plural, namespaced = KINDS[kind]
        if not namespaced or namespace is None:
            return f"/{prefix}/{plural}"
        return f"/{prefix}/namespaces/{namespace}/{plural}"

    def _object(self, kind: str, namespace: str | None, name: str) -> str:
        return f"{self._collection(kind, namespace)}/{name}"

    # -- KubeApi ------------------------------------------------------------
    def apply(self, manifest: Manifest) -> None:
        kind = manifest["kind"]
        md = manifest["metadata"]
        url = self._object(kind, md.get("namespace"), md["name"])
        r = self._client.patch(
            url,
            params={"fieldManager": FIELD_MANAGER, "force": "true"},
            content=json.dumps(manifest).encode(),
            headers={"Content-Type": "application/apply-patch+yaml"},
        )
        r.raise_for_status()

    def get(self, kind: str, namespace: str, name: str) -> Manifest | None:
        r = self._client.get(self._object(kind, namespace, name))
        if r.status_code == 404:
            return None
        r.raise_for_status()
        return r.json()

    def list(
        self, kind: str, namespace: str, selector: dict[str, str]
    ) -> list[Manifest]:
        r = self._client.get(
            self._collection(kind, namespace),
            params={
                "labelSelector": ",".join(
                    f"{k}={v}" for k, v in selector.items()
                )
            },
        )
        r.raise_for_status()
        return r.json().get("items", [])

    def delete(self, kind: str, namespace: str, name: str) -> bool:
        r = self._client.delete(self._object(kind, namespace, name))
        if r.status_code == 404:
            return False
        r.raise_for_status()
        return True

    # -- CRD ----------------------------------------------------------------
    def ensure_crd(self, manifest: Manifest) -> None:
        """Install the CRD if absent (409 Conflict = already there)."""
        r = self._client.post(
            self._collection("CustomResourceDefinition", None),
            json=manifest,
        )
        if r.status_code not in (200, 201, 409):
            r.raise_for_status()

    # -- watch --------------------------------------------------------------
    def watch(self, namespace, selector, on_event):
        """Streaming watches over app-labelled Deployments + Services
        (all namespaces when ``namespace is None``); one reader thread per
        resource, reconnecting with backoff. Events are level-triggering
        kicks — the reconciler re-reads everything — so only arrival
        matters, not payload."""
        sel = ",".join(f"{k}={v}" for k, v in selector.items())
        stopped = threading.Event()

        def pump(kind: str) -> None:
            backoff = 1.0
            url = self._collection(kind, namespace)
            while not stopped.is_set():
                try:
                    with self._client.stream(
                        "GET",
                        url,
                        params={"watch": "1", "labelSelector": sel},
                        timeout=httpx.Timeout(30.0, read=None),
                    ) as resp:
                        resp.raise_for_status()
                        for line in resp.iter_lines():
                            if stopped.is_set():
                                return
                            if line.strip():
                                backoff = 1.0
                                on_event(None)
                except Exception as exc:  # noqa: BLE001
                    if stopped.is_set():
                        return
                    logger.warning("%s watch errored: %s", kind, exc)
                if stopped.is_set():
                    return
                logger.warning(
                    "%s watch disconnected; reconnecting in %.0fs",
                    kind, backoff,
                )
                time.sleep(backoff)
                backoff = min(backoff * 2, 30.0)

        for kind in ("Deployment", "Service"):
            threading.Thread(
                target=pump, args=(kind,), daemon=True
            ).start()

        return stopped.set

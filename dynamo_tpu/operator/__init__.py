"""K8s operator: api-store deployment specs → reconciled cluster objects
(reference: deploy/cloud/operator, re-designed as a Python reconcile loop
over kubectl — see operator.py)."""

from dynamo_tpu.operator.kube import FakeKube, KubeApi, KubectlApi
from dynamo_tpu.operator.operator import STATUS_BUCKET, GraphOperator
from dynamo_tpu.operator.resources import (
    GraphDeployment,
    ServiceSpec,
    render,
)

__all__ = [
    "FakeKube",
    "GraphDeployment",
    "GraphOperator",
    "KubeApi",
    "KubectlApi",
    "STATUS_BUCKET",
    "ServiceSpec",
    "render",
]

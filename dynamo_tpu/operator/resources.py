"""Graph-deployment resource model + Kubernetes manifest rendering.

Role of the reference's operator CRD layer (reference:
deploy/cloud/operator/api/v1alpha1/dynamographdeployment_types.go — a
DynamoGraphDeployment names a set of services, each with replicas and
resources, that the controller reconciles into Deployments/Services).
TPU re-design: the "CRD" is a plain JSON spec in the api-store's
deployment bucket (sdk/api_store.py), and each service maps onto the
`dynamo-tpu` CLI's subcommands — the same commands a human would run from
a shell (deploy/k8s/*.yaml are hand-written instances of exactly these
manifests). Chips replace GPUs as the resource unit (`google.com/tpu`).
"""

from __future__ import annotations

import hashlib
import json
import re
from dataclasses import dataclass, field
from typing import Any

LABEL_APP = "dynamo-tpu"
LABEL_DEPLOYMENT = "dynamo-tpu/deployment"
ANNOTATION_SPEC_HASH = "dynamo-tpu/spec-hash"

#: service role → CLI invocation builder
ROLES = ("control-plane", "frontend", "worker", "planner", "metrics")

DEFAULT_IMAGE = "dynamo-tpu:latest"
CONTROL_PLANE_PORT = 6380

#: GraphDeployment CRD the operator installs at startup (reference
#: analogue: DynamoGraphDeployment, deploy/cloud/operator/api/v1alpha1).
#: A packaged CONSTANT — installed/containerized trees have no deploy/
#: directory; deploy/k8s/crd-graphdeployment.yaml mirrors this for
#: manual `kubectl apply` installs (tests/test_operator_rest.py keeps
#: the two in sync).
GRAPHDEPLOYMENT_CRD: dict[str, Any] = {
    "apiVersion": "apiextensions.k8s.io/v1",
    "kind": "CustomResourceDefinition",
    "metadata": {"name": "graphdeployments.dynamo.tpu"},
    "spec": {
        "group": "dynamo.tpu",
        "scope": "Namespaced",
        "names": {
            "plural": "graphdeployments",
            "singular": "graphdeployment",
            "kind": "GraphDeployment",
            "shortNames": ["gd"],
        },
        "versions": [
            {
                "name": "v1alpha1",
                "served": True,
                "storage": True,
                # NO status subresource: the operator mirrors status via
                # the same server-side apply as the spec; with the
                # subresource enabled a real apiserver would silently
                # DROP .status from main-resource applies (and the
                # change detector would re-apply every tick).
                "additionalPrinterColumns": [
                    {
                        "name": "Ready",
                        "type": "boolean",
                        "jsonPath": ".status.ready",
                    }
                ],
                "schema": {
                    "openAPIV3Schema": {
                        "type": "object",
                        "properties": {
                            "spec": {
                                "type": "object",
                                "x-kubernetes-preserve-unknown-fields": True,
                            },
                            "status": {
                                "type": "object",
                                "x-kubernetes-preserve-unknown-fields": True,
                            },
                        },
                    }
                },
            }
        ],
    },
}


@dataclass
class ServiceSpec:
    name: str
    role: str                      # one of ROLES
    replicas: int = 1
    chips: int = 0                 # TPU chips per replica (workers)
    image: str = DEFAULT_IMAGE
    args: dict[str, Any] = field(default_factory=dict)  # extra CLI flags
    port: int | None = None        # exposed service port (frontend/metrics)

    @staticmethod
    def from_dict(name: str, d: dict) -> "ServiceSpec":
        role = d.get("role", name.lower())
        if role not in ROLES:
            raise ValueError(f"service {name!r}: unknown role {role!r}")
        return ServiceSpec(
            name=name,
            role=role,
            replicas=int(d.get("replicas", 1)),
            chips=int(d.get("chips", 0)),
            image=d.get("image", DEFAULT_IMAGE),
            args=dict(d.get("args", {})),
            port=d.get("port"),
        )


_DNS1123 = re.compile(r"^[a-z0-9]([a-z0-9-]{0,61}[a-z0-9])?$")


def validate_record(record: dict) -> list[str]:
    """CRD-style schema validation of a stored graph-deployment record —
    the role of the reference operator's OpenAPI CRD schema
    (deploy/cloud/operator/api/v1alpha1/*_types.go + kubebuilder
    validation markers). Returns a list of precise violation messages
    (empty = valid); the operator surfaces them as status conditions
    instead of reconciling a malformed spec."""
    errs: list[str] = []
    name = record.get("name")
    if not isinstance(name, str) or not _DNS1123.match(name or ""):
        errs.append(
            f"name {name!r} must be DNS-1123 (lowercase alphanumeric/-, "
            f"max 63 chars)"
        )
    spec = record.get("spec")
    if not isinstance(spec, dict):
        return errs + ["spec must be an object"]
    ns = spec.get("namespace", "dynamo")
    if not isinstance(ns, str) or not _DNS1123.match(ns):
        errs.append(f"spec.namespace {ns!r} must be DNS-1123")
    services = spec.get("services")
    if not isinstance(services, dict) or not services:
        return errs + ["spec.services must be a non-empty object"]
    cp = 0
    seen_child_names: dict[str, str] = {}
    for sname, sd in services.items():
        where = f"spec.services.{sname}"
        if not isinstance(sname, str) or not _DNS1123.match(sname.lower()):
            errs.append(f"{where}: service name must be DNS-1123")
        elif isinstance(name, str):
            # Rendered child objects are named "{name}-{service}" — the
            # COMBINED name must satisfy DNS-1123's 63-char bound, and
            # case-folded services must not collide ("Worker"+"worker"
            # would silently render onto one child).
            child = f"{name}-{sname.lower()}"
            if len(child) > 63:
                errs.append(
                    f"{where}: rendered name {child!r} exceeds 63 chars"
                )
            if child in seen_child_names:
                errs.append(
                    f"{where}: collides with service "
                    f"{seen_child_names[child]!r} after lowercasing"
                )
            seen_child_names[child] = sname
        if not isinstance(sd, dict):
            errs.append(f"{where}: must be an object")
            continue
        role = sd.get("role", str(sname).lower())
        if role not in ROLES:
            errs.append(f"{where}.role {role!r} not in {ROLES}")
        if role == "control-plane":
            cp += 1
        for fieldname, lo in (("replicas", 0), ("chips", 0)):
            v = sd.get(fieldname, lo)
            if not isinstance(v, int) or isinstance(v, bool) or v < lo:
                errs.append(f"{where}.{fieldname} must be an int >= {lo}")
        port = sd.get("port")
        if port is not None and (
            not isinstance(port, int) or not 1 <= port <= 65535
        ):
            errs.append(f"{where}.port must be in [1, 65535]")
        if "args" in sd and not isinstance(sd["args"], dict):
            errs.append(f"{where}.args must be an object")
    if cp > 1:
        errs.append("at most one control-plane service per graph")
    return errs


@dataclass
class GraphDeployment:
    name: str
    namespace: str = "dynamo"
    services: list[ServiceSpec] = field(default_factory=list)

    @staticmethod
    def from_record(record: dict) -> "GraphDeployment":
        errs = validate_record(record)
        if errs:
            raise ValueError("; ".join(errs))
        spec = record.get("spec", {})
        services = [
            ServiceSpec.from_dict(n, s)
            for n, s in spec.get("services", {}).items()
        ]
        return GraphDeployment(
            name=record["name"],
            namespace=spec.get("namespace", "dynamo"),
            services=services,
        )


def spec_hash(obj: Any) -> str:
    return hashlib.sha256(
        json.dumps(obj, sort_keys=True).encode()
    ).hexdigest()[:16]


def control_plane_addr(dep: GraphDeployment) -> str:
    """DNS address of the graph's control-plane Service — derived from the
    actual control-plane ServiceSpec's rendered name, so whatever the spec
    calls it ("ControlPlane", "cp", ...) the other services dial the
    Service that actually exists."""
    cp = next((s for s in dep.services if s.role == "control-plane"), None)
    name = f"{dep.name}-{cp.name.lower()}" if cp else f"{dep.name}-control-plane"
    return f"{name}:{CONTROL_PLANE_PORT}"


def _cli_command(dep: GraphDeployment, svc: ServiceSpec) -> list[str]:
    cp_addr = control_plane_addr(dep)
    flags = [f"--{k.replace('_', '-')}={v}" for k, v in sorted(svc.args.items())]
    if svc.role == "control-plane":
        return ["dynamo-tpu", "control-plane",
                f"--port={CONTROL_PLANE_PORT}", *flags]
    if svc.role == "frontend":
        return ["dynamo-tpu", "run", "--in=http", "--out=dyn://auto",
                f"--control-plane={cp_addr}",
                f"--http-port={svc.port or 8080}", *flags]
    if svc.role == "worker":
        return ["dynamo-tpu", "run",
                "--in=dyn://dynamo.tpu.generate", "--out=tpu",
                f"--control-plane={cp_addr}", *flags]
    if svc.role == "planner":
        return ["dynamo-tpu", "planner", f"--control-plane={cp_addr}", *flags]
    return ["dynamo-tpu", "metrics", f"--control-plane={cp_addr}",
            f"--port={svc.port or 9091}", *flags]


def render(dep: GraphDeployment) -> list[dict]:
    """GraphDeployment → k8s manifests (Deployments + Services).

    Every child carries the owning deployment's label so the reconciler
    can diff and garbage-collect; the spec hash annotation is the change
    detector (reference analogue: controller-runtime owned objects +
    resource generation)."""
    manifests: list[dict] = []
    for svc in dep.services:
        labels = {
            "app": LABEL_APP,
            LABEL_DEPLOYMENT: dep.name,
            "component": svc.name,
        }
        container: dict[str, Any] = {
            "name": svc.name.lower(),
            "image": svc.image,
            "command": _cli_command(dep, svc),
        }
        if svc.chips:
            container["resources"] = {
                "limits": {"google.com/tpu": str(svc.chips)}
            }
        dep_manifest = {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {
                "name": f"{dep.name}-{svc.name.lower()}",
                "namespace": dep.namespace,
                "labels": labels,
            },
            "spec": {
                "replicas": svc.replicas,
                "selector": {"matchLabels": labels},
                "template": {
                    "metadata": {"labels": labels},
                    "spec": {"containers": [container]},
                },
            },
        }
        dep_manifest["metadata"]["annotations"] = {
            ANNOTATION_SPEC_HASH: spec_hash(dep_manifest["spec"])
        }
        manifests.append(dep_manifest)

        needs_service = svc.role in ("frontend", "metrics") or (
            svc.role == "control-plane"
        )
        if needs_service:
            port = (
                CONTROL_PLANE_PORT
                if svc.role == "control-plane"
                else svc.port or (8080 if svc.role == "frontend" else 9091)
            )
            svc_manifest = {
                "apiVersion": "v1",
                "kind": "Service",
                "metadata": {
                    "name": f"{dep.name}-{svc.name.lower()}",
                    "namespace": dep.namespace,
                    "labels": labels,
                },
                "spec": {
                    "selector": labels,
                    "ports": [{"port": port, "targetPort": port}],
                },
            }
            svc_manifest["metadata"]["annotations"] = {
                ANNOTATION_SPEC_HASH: spec_hash(svc_manifest["spec"])
            }
            manifests.append(svc_manifest)
    return manifests

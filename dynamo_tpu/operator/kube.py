"""Kubernetes API shim for the operator.

The reconciler only needs four verbs (apply/get/list/delete) over
label-selected namespaced objects, so the interface is exactly that —
implemented by `KubectlApi` (shells out to kubectl; no k8s client library
in the image) and `FakeKube`, the in-memory double every operator test
drives (the envtest role in the reference's Go operator,
reference: deploy/cloud/operator/test/e2e)."""

from __future__ import annotations

import json
import subprocess
from typing import Any, Protocol

Manifest = dict[str, Any]


def _meta(m: Manifest) -> tuple[str, str, str]:
    md = m.get("metadata", {})
    return m.get("kind", ""), md.get("namespace", "default"), md.get("name", "")


class KubeApi(Protocol):
    def apply(self, manifest: Manifest) -> None: ...
    def get(self, kind: str, namespace: str, name: str) -> Manifest | None: ...
    def list(
        self, kind: str, namespace: str, selector: dict[str, str]
    ) -> list[Manifest]: ...
    def delete(self, kind: str, namespace: str, name: str) -> bool: ...


class FakeKube:
    """In-memory cluster: stores manifests, simulates replica readiness."""

    def __init__(self) -> None:
        self.objects: dict[tuple[str, str, str], Manifest] = {}
        self.apply_count = 0

    def apply(self, manifest: Manifest) -> None:
        self.apply_count += 1
        self.objects[_meta(manifest)] = json.loads(json.dumps(manifest))

    def get(self, kind: str, namespace: str, name: str) -> Manifest | None:
        return self.objects.get((kind, namespace, name))

    def list(
        self, kind: str, namespace: str, selector: dict[str, str]
    ) -> list[Manifest]:
        out = []
        for (k, ns, _), m in self.objects.items():
            if k != kind or ns != namespace:
                continue
            labels = m.get("metadata", {}).get("labels", {})
            if all(labels.get(lk) == lv for lk, lv in selector.items()):
                out.append(m)
        return out

    def delete(self, kind: str, namespace: str, name: str) -> bool:
        return self.objects.pop((kind, namespace, name), None) is not None

    # -- test helpers -------------------------------------------------------
    def mark_ready(self, kind: str, namespace: str, name: str) -> None:
        """Simulate the kubelet bringing every desired replica up."""
        m = self.objects[(kind, namespace, name)]
        m["status"] = {"readyReplicas": m.get("spec", {}).get("replicas", 0)}


class KubectlApi:  # pragma: no cover - needs a cluster
    """kubectl-backed implementation (apply -f -, get/delete -o json)."""

    def __init__(self, kubectl: str = "kubectl") -> None:
        self.kubectl = kubectl

    def _run(self, *args: str, stdin: str | None = None) -> str:
        proc = subprocess.run(
            [self.kubectl, *args],
            input=stdin, capture_output=True, text=True, check=True,
        )
        return proc.stdout

    def apply(self, manifest: Manifest) -> None:
        self._run("apply", "-f", "-", stdin=json.dumps(manifest))

    def get(self, kind: str, namespace: str, name: str) -> Manifest | None:
        try:
            return json.loads(
                self._run("get", kind, name, "-n", namespace, "-o", "json")
            )
        except subprocess.CalledProcessError:
            return None

    def list(
        self, kind: str, namespace: str, selector: dict[str, str]
    ) -> list[Manifest]:
        sel = ",".join(f"{k}={v}" for k, v in selector.items())
        out = json.loads(
            self._run("get", kind, "-n", namespace, "-l", sel, "-o", "json")
        )
        return out.get("items", [])

    def delete(self, kind: str, namespace: str, name: str) -> bool:
        try:
            self._run("delete", kind, name, "-n", namespace,
                      "--ignore-not-found")
            return True
        except subprocess.CalledProcessError:
            return False

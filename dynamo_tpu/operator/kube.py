"""Kubernetes API shim for the operator.

The reconciler only needs four verbs (apply/get/list/delete) over
label-selected namespaced objects, so the interface is exactly that —
implemented by `KubectlApi` (shells out to kubectl; no k8s client library
in the image) and `FakeKube`, the in-memory double every operator test
drives (the envtest role in the reference's Go operator,
reference: deploy/cloud/operator/test/e2e)."""

from __future__ import annotations

import json
import subprocess
from typing import Any, Protocol

Manifest = dict[str, Any]


def _meta(m: Manifest) -> tuple[str, str, str]:
    md = m.get("metadata", {})
    return m.get("kind", ""), md.get("namespace", "default"), md.get("name", "")


class KubeApi(Protocol):
    def apply(self, manifest: Manifest) -> None: ...
    def get(self, kind: str, namespace: str, name: str) -> Manifest | None: ...
    def list(
        self, kind: str, namespace: str, selector: dict[str, str]
    ) -> list[Manifest]: ...
    def delete(self, kind: str, namespace: str, name: str) -> bool: ...

    def watch(
        self, namespace: str, selector: dict[str, str], on_event
    ) -> "object":
        """Start a cluster watch over app-labelled Deployments/Services in
        `namespace`; `on_event(manifest_or_none)` fires on every change
        (possibly from a non-asyncio thread). Returns a stop() callable.
        The informer role of the reference's controller-runtime watches —
        reconciles become event-driven instead of fixed-interval polls."""
        ...


class FakeKube:
    """In-memory cluster: stores manifests, simulates replica readiness,
    and fires watch callbacks on every mutation (the envtest double for
    the watch-driven reconcile path)."""

    def __init__(self) -> None:
        self.objects: dict[tuple[str, str, str], Manifest] = {}
        self.apply_count = 0
        self._watchers: list = []

    def _notify(self, obj) -> None:
        for cb in list(self._watchers):
            cb(obj)

    def watch(self, namespace, selector, on_event):
        self._watchers.append(on_event)

        def stop():
            if on_event in self._watchers:
                self._watchers.remove(on_event)

        return stop

    def apply(self, manifest: Manifest) -> None:
        self.apply_count += 1
        self.objects[_meta(manifest)] = json.loads(json.dumps(manifest))
        self._notify(manifest)

    def get(self, kind: str, namespace: str, name: str) -> Manifest | None:
        return self.objects.get((kind, namespace, name))

    def list(
        self, kind: str, namespace: str, selector: dict[str, str]
    ) -> list[Manifest]:
        out = []
        for (k, ns, _), m in self.objects.items():
            if k != kind or ns != namespace:
                continue
            labels = m.get("metadata", {}).get("labels", {})
            if all(labels.get(lk) == lv for lk, lv in selector.items()):
                out.append(m)
        return out

    def delete(self, kind: str, namespace: str, name: str) -> bool:
        gone = self.objects.pop((kind, namespace, name), None)
        if gone is not None:
            self._notify(None)
        return gone is not None

    # -- test helpers -------------------------------------------------------
    def mark_ready(self, kind: str, namespace: str, name: str) -> None:
        """Simulate the kubelet bringing every desired replica up."""
        m = self.objects[(kind, namespace, name)]
        m["status"] = {"readyReplicas": m.get("spec", {}).get("replicas", 0)}

    def external_delete(self, kind: str, namespace: str, name: str) -> None:
        """Simulate an out-of-band actor (human, another controller)
        deleting a child — fires the watch like a real apiserver would."""
        self.delete(kind, namespace, name)


class KubectlApi:  # pragma: no cover - needs a cluster
    """kubectl-backed implementation (apply -f -, get/delete -o json)."""

    def __init__(self, kubectl: str = "kubectl") -> None:
        self.kubectl = kubectl

    def _run(self, *args: str, stdin: str | None = None) -> str:
        proc = subprocess.run(
            [self.kubectl, *args],
            input=stdin, capture_output=True, text=True, check=True,
        )
        return proc.stdout

    def apply(self, manifest: Manifest) -> None:
        self._run("apply", "-f", "-", stdin=json.dumps(manifest))

    def ensure_crd(self, manifest: Manifest) -> None:
        """`kubectl apply` is already create-or-update for CRDs."""
        self.apply(manifest)

    def get(self, kind: str, namespace: str, name: str) -> Manifest | None:
        try:
            return json.loads(
                self._run("get", kind, name, "-n", namespace, "-o", "json")
            )
        except subprocess.CalledProcessError:
            return None

    def list(
        self, kind: str, namespace: str, selector: dict[str, str]
    ) -> list[Manifest]:
        sel = ",".join(f"{k}={v}" for k, v in selector.items())
        out = json.loads(
            self._run("get", kind, "-n", namespace, "-l", sel, "-o", "json")
        )
        return out.get("items", [])

    def delete(self, kind: str, namespace: str, name: str) -> bool:
        try:
            self._run("delete", kind, name, "-n", namespace,
                      "--ignore-not-found")
            return True
        except subprocess.CalledProcessError:
            return False

    def watch(self, namespace, selector, on_event):
        """`kubectl get -w` reader thread: one event per output line
        (names suffice to trigger a level-based reconcile, which re-reads
        everything). API servers close watches routinely (~5 min), so the
        thread RESTARTS the process with backoff — a dropped watch must
        degrade to a logged reconnect, not silently fall back to resync
        for the rest of the operator's life. ``namespace=None`` watches
        every namespace (children live in each spec's namespace)."""
        import logging
        import threading
        import time as _time

        log = logging.getLogger(__name__)
        sel = ",".join(f"{k}={v}" for k, v in selector.items())
        ns_args = (
            ["--all-namespaces"] if namespace is None else ["-n", namespace]
        )
        state = {"proc": None, "stopped": False}

        def pump():
            backoff = 1.0
            while not state["stopped"]:
                try:
                    proc = subprocess.Popen(
                        [self.kubectl, "get", "deployments,services",
                         *ns_args, "-l", sel, "-w", "--no-headers"],
                        stdout=subprocess.PIPE, text=True,
                    )
                    state["proc"] = proc
                    if state["stopped"]:
                        # stop() may have run between the loop check and
                        # the spawn — it saw no (or the previous) proc, so
                        # terminate this one ourselves.
                        proc.terminate()
                        return
                    assert proc.stdout is not None
                    for _line in proc.stdout:
                        backoff = 1.0
                        on_event(None)
                except Exception as exc:  # noqa: BLE001
                    log.warning("cluster watch errored: %s", exc)
                if state["stopped"]:
                    return
                log.warning(
                    "cluster watch disconnected; reconnecting in %.0fs",
                    backoff,
                )
                _time.sleep(backoff)
                backoff = min(backoff * 2, 30.0)

        threading.Thread(target=pump, daemon=True).start()

        def stop():
            state["stopped"] = True
            if state["proc"] is not None:
                state["proc"].terminate()

        return stop

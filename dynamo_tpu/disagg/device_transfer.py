"""Device-path KV transfer: same-process prefill→decode HBM→HBM block moves.

SURVEY §7 hard-part #1's first rung. When the prefill and decode engines
share one process (the standard TPU-host topology: one process drives the
host's chips, running both roles of a colocated xPyD pair), block bytes
never need to touch host memory: the prefill side snapshots blocks as
device-resident arrays (ops/kv_copy.gather_block_device) and the decode
side scatters them straight into its cache (runner.scatter_block's device
branch). XLA performs the copy in HBM — and when the two engines' caches
carry different shardings over the mesh, the resharding rides ICI.

Cross-process transfers keep the existing host-staged paths (native C++
agent / TCP) — the DCN story. A decode operator advertises BOTH in the
queue entry; the prefill worker picks the device path only if the address
resolves in its own process registry (reference analogue: NIXL chooses
RDMA vs staged transports per peer, docs/architecture/disagg_serving.md:
78-109).
"""

from __future__ import annotations

import logging
import secrets
import threading
from typing import Callable

logger = logging.getLogger(__name__)

_REGISTRY: dict[str, "DeviceKvReceiver"] = {}
_REGISTRY_LOCK = threading.Lock()

SCHEME = "device://"


class BlockBatch:
    """Device-resident [N, ...] block snapshot shipped as ONE unit: the
    prefill side gathers every block in one program
    (ops/kv_copy.gather_blocks_device) and the decode side scatters them in
    one program — 2 dispatches per handoff instead of 2·N. Supports the
    list operations the ship path uses (len / slicing).

    ``scales`` ([N, L, 2, kvH] device array) rides along for quantized
    (kv_quant int8) pairs — the decode side scatters it into its own
    per-block scale state next to the data."""

    def __init__(self, data, scales=None) -> None:
        self.data = data
        self.scales = scales

    def __len__(self) -> int:
        return int(self.data.shape[0])

    @property
    def shape(self):
        """Delegates to the data snapshot so batch consumers that size
        by ``data.shape[0]`` accept either form."""
        return self.data.shape

    def __getitem__(self, key):
        if isinstance(key, slice):
            return BlockBatch(
                self.data[key],
                self.scales[key] if self.scales is not None else None,
            )
        return self.data[key]


def resolve(address: str) -> "DeviceKvReceiver | None":
    """Look the address up in THIS process's registry (None ⇒ the sender
    lives in another process and must use the wire path)."""
    with _REGISTRY_LOCK:
        return _REGISTRY.get(address)


class DeviceKvReceiver:
    """Decode-side registration for in-process device transfers. The same
    callback contract as the wire receivers (engine submit-queue targets),
    but `data` is a device array the engine scatters without host staging."""

    def __init__(
        self,
        on_block: Callable[[str, int, object], None],
        on_finish: Callable[[str, int], None],
        on_blocks: Callable[[str, int, object], None] | None = None,
    ) -> None:
        self._on_block = on_block
        self._on_finish = on_finish
        self._on_blocks = on_blocks  # batched form: (req, start_idx, [N,...])
        self.address = SCHEME + secrets.token_hex(8)
        self.auth = secrets.token_hex(16)
        self.blocks_received = 0

    async def start(self) -> "DeviceKvReceiver":
        with _REGISTRY_LOCK:
            _REGISTRY[self.address] = self
        return self

    async def stop(self) -> None:
        with _REGISTRY_LOCK:
            _REGISTRY.pop(self.address, None)

    # Called by DeviceKvSender (same process, possibly another task/thread).
    def deliver_block(self, request_id: str, idx: int, data) -> None:
        self.blocks_received += 1
        self._on_block(request_id, idx, data)

    def deliver_batch(self, request_id: str, start_idx: int, data) -> None:
        """One [N, ...] device snapshot. Falls back to per-block delivery
        when the receiver has no batched callback."""
        n = int(data.shape[0])
        self.blocks_received += n
        if self._on_blocks is not None:
            self._on_blocks(request_id, start_idx, data)
        else:
            for i in range(n):
                self._on_block(request_id, start_idx + i, data[i])

    def deliver_finish(self, request_id: str, first_token: int) -> None:
        self._on_finish(request_id, first_token)


class DeviceKvSender:
    """Prefill-side: hand device-resident block snapshots to the in-process
    receiver. `send_blocks` mirrors the wire senders' signature."""

    async def send_blocks(
        self,
        address: str,
        request_id: str,
        blocks: list,
        first_token: int,
        start_idx: int = 0,
        auth: str | None = None,
        **_ignored,
    ) -> None:
        receiver = resolve(address)
        if receiver is None:
            raise ConnectionError(f"{address} not registered in this process")
        if auth != receiver.auth:
            raise PermissionError("bad device-channel auth token")
        if isinstance(blocks, BlockBatch):
            if len(blocks):
                # Quantized batches ship the whole BlockBatch (scales
                # attached); legacy receivers get the bare array.
                payload = blocks if blocks.scales is not None else blocks.data
                receiver.deliver_batch(request_id, start_idx, payload)
        else:
            for i, block in enumerate(blocks):
                receiver.deliver_block(request_id, start_idx + i, block)
        receiver.deliver_finish(request_id, first_token)

    async def close(self) -> None:
        pass

"""Disaggregated workers: the decode-side operator and the prefill loop.

DecodeOperator wraps a decode TpuEngine as the served AsyncEngine: per
request it makes the local/remote decision, and for remote ones admits the
sequence (blocks pre-allocated), enqueues a RemotePrefillRequest carrying
this worker's transfer address, and streams tokens that start flowing once
the prefill worker pushes KV + first token back (reference:
examples/llm/components/worker.py:186-235).

PrefillWorker drains the shared queue: prefill on its own engine (its local
prefix cache still applies), push blocks to the decode worker, done
(reference: examples/llm/components/prefill_worker.py:139-211). SIGTERM
semantics: `stop()` finishes the current item then exits (reference:
disagg_serving.md:187-194 graceful drain).
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from typing import AsyncIterator

from dynamo_tpu.block_manager.integrity import CHECKSUM_ALGO
from dynamo_tpu.disagg.queue import PrefillQueue
from dynamo_tpu.disagg.router import DisaggRouter
from dynamo_tpu.disagg.transfer import KvReceiver, KvSender
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.llm.protocols.common import (
    PreprocessedRequest,
    SamplingOptions,
)
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.utils.deadline import OVERLOAD
from dynamo_tpu.utils.logging import request_scope
from dynamo_tpu.utils.retry import QUEUE_REDELIVERY, RETRIES
from dynamo_tpu.utils.tracing import TraceContext, tracer

logger = logging.getLogger(__name__)


class DecodeOperator:
    """AsyncEngine served by a decode worker in a disagg deployment."""

    def __init__(
        self,
        engine: TpuEngine,
        queue: PrefillQueue,
        router: DisaggRouter,
        transport: str = "auto",  # "native" (C++ agent) | "tcp" | "auto"
        staging_slots: int = 64,
        transfer_host: str = "127.0.0.1",
    ) -> None:
        """transfer_host: the address prefill workers reach this worker at,
        advertised in enqueued requests. Anything other than loopback makes
        the receiver bind all interfaces (cross-host disaggregation)."""
        self.engine = engine
        self.queue = queue
        self.router = router
        self.transport = transport
        self._staging_slots = staging_slots
        self._transfer_host = transfer_host
        self.receiver = None
        # Under "auto": a plain TCP receiver kept alongside the native
        # one, so a request the staging arena can't fund degrades to the
        # staging-free tcp wire instead of shedding to LOCAL prefill
        # (r05: at ISL 3000 every request needs ~190 staging blocks — a
        # 64-slot arena turned "disagg" into silent aggregated serving).
        self.tcp_receiver = None
        self.device_receiver = None
        self.remote_count = 0
        self.local_count = 0

    def _layout(self) -> dict:
        """KV block layout advertised in queue entries so a mismatched
        prefill worker can repack (lane padding) or reject (ADVICE r02:
        heterogeneous pairs shipped mismatched bytes silently).

        ``tp`` advertises the decode pool's tensor-parallel degree
        (reference: heterogeneous-TP KV reconciliation,
        docs/architecture/disagg_serving.md:100-109). The WIRE path is
        tp-agnostic by construction — blocks travel in the LOGICAL
        [L, 2, bs, H_total, D] layout: the prefill side's gather
        all-gathers its tp-sharded heads to the host, and the decode
        side's scatter re-slices them onto its own head partition — so a
        tp=4 prefill pool feeds a tp=2 (or tp=1) decode pool without a
        separate transpose step. The in-process DEVICE path is the one
        that needs identical shardings; _device_addr falls back to the
        wire when tp differs."""
        m = self.engine.cfg.model
        mesh = getattr(self.engine.runner, "mesh", None)
        tp = int(dict(mesh.shape).get("tp", 1)) if mesh is not None else 1
        sp = int(dict(mesh.shape).get("sp", 1)) if mesh is not None else 1
        return {
            "num_layers": m.num_layers,
            "num_kv_heads": m.num_cache_heads,
            "head_dim": self.engine.runner.cache_head_dim,
            "block_size": self.engine.cfg.block_size,
            "dtype": str(self.engine.cfg.dtype),
            # KV precision (docs/architecture/kv_quant.md): quantized
            # pairs ship PACKED rows (int8 data + scale sidecar) and
            # must match exactly — a mixed-precision pair rejects at
            # _check_layout and the decode side recomputes locally.
            "kv_quant": self.engine.cfg.kv_quant,
            "tp": tp,
            # Slot-axis sharding degree (kv_sp long-context mode): the
            # device path needs the WHOLE cache sharding to match, not
            # just tp.
            "kv_sp": sp if self.engine.cfg.kv_sp else 1,
            # Integrity-envelope algorithm this receiver verifies KV
            # frames with: a prefill worker speaking a DIFFERENT
            # algorithm must refuse the pair (its crc headers would be
            # unverifiable noise here), while a legacy peer that omits
            # the field is tolerated — its frames arrive unchecksummed
            # and ride the pre-envelope trust path.
            "checksum": CHECKSUM_ALGO,
        }

    async def start(self) -> "DecodeOperator":
        # Under "auto"/"device" the in-process channel (HBM→HBM,
        # disagg/device_transfer.py) is registered and advertised; senders
        # use it only when the address resolves in their own process. Wire
        # receivers below are the cross-process fallback. Explicit
        # "tcp"/"native" pins the wire path (tests, forced staging).
        want_device = self.transport in ("auto", "device")
        if self.transport == "device":
            self.transport = "auto"
        await self._start_wire()
        if want_device:
            from dynamo_tpu.disagg.device_transfer import DeviceKvReceiver

            def on_finish(request_id: str, first_token: int) -> None:
                # The wire receiver may hold a staging reservation made
                # before the sender chose the device path — release it, or
                # the staging arena leaks one slot set per device transfer.
                release = getattr(self.receiver, "release", None)
                if release is not None:
                    release(request_id)
                self.engine.on_remote_finish(request_id, first_token)

            self.device_receiver = await DeviceKvReceiver(
                on_block=self.engine.on_remote_block,
                on_finish=on_finish,
                on_blocks=self.engine.on_remote_blocks,
            ).start()
        return self

    async def _start_wire(self) -> "DecodeOperator":
        pinned = self.transport
        if self.transport in ("auto", "native"):
            try:
                from dynamo_tpu.block_manager.config import KvLayoutConfig
                from dynamo_tpu.disagg.native_transfer import NativeKvReceiver

                m = self.engine.cfg.model
                layout = KvLayoutConfig(
                    num_layers=m.num_layers,
                    page_size=self.engine.cfg.block_size,
                    num_kv_heads=m.num_cache_heads,
                    # Actual cache head dim (lane-padded under the Pallas
                    # path) — shipped blocks carry the padded bytes.
                    head_dim=self.engine.runner.cache_head_dim,
                    dtype=self.engine.cfg.dtype,
                    # Quantized pairs stage PACKED rows (block_bytes
                    # includes the scale sidecar).
                    quant=self.engine.cfg.kv_quant,
                )
                self.receiver = await NativeKvReceiver(
                    on_block=self.engine.on_remote_block,
                    on_finish=self.engine.on_remote_finish,
                    layout=layout,
                    num_slots=self._staging_slots,
                    host=self._transfer_host,
                ).start()
                self.transport = "native"
                if pinned == "auto":
                    self.tcp_receiver = await KvReceiver(
                        on_block=self.engine.on_remote_block,
                        on_finish=self.engine.on_remote_finish,
                        host=self._transfer_host,
                    ).start()
                return self
            except Exception:
                if self.transport == "native":
                    raise
                logger.info("native transfer unavailable; using tcp")
        self.transport = "tcp"
        self.receiver = await KvReceiver(
            on_block=self.engine.on_remote_block,
            on_finish=self.engine.on_remote_finish,
            host=self._transfer_host,
        ).start()
        return self

    async def stop(self) -> None:
        if self.receiver is not None:
            await self.receiver.stop()
        if self.tcp_receiver is not None:
            await self.tcp_receiver.stop()
        if self.device_receiver is not None:
            await self.device_receiver.stop()

    async def generate(self, request: Context) -> AsyncIterator[dict]:
        pre = (
            PreprocessedRequest.from_wire(request.payload)
            if isinstance(request.payload, dict)
            else request.payload
        )
        depth, age = await self.queue.stats()
        remote = self.router.prefill_remote(
            len(pre.token_ids),
            self.engine.prefix_overlap(list(pre.token_ids)),
            depth,
            queue_age_s=age,
        )
        if pre.logprobs is not None:
            # The first token samples on the PREFILL worker, which has no
            # channel for its logprob arrays — a remote prefill would drop
            # that token's entry and misalign logprobs vs tokens. Serve
            # logprob requests locally.
            remote = False
        stream = None
        if remote:
            admitted = await self.engine.begin_remote(request, pre)
            if admitted is not None:
                info, stream = admitted
                tracer().adopt(request.id, pre.trace)
                req = {
                    "request_id": request.id,
                    "token_ids": list(pre.token_ids),
                    "sampling": pre.sampling.to_wire(),
                    # SLO class tag (llm/slo.py): the consumer threads
                    # it into its prefill sequences, so class-aware shed
                    # /preempt decisions hold on the PREFILL worker too
                    # — a batch prompt must not displace an interactive
                    # one in a shared prefill pool.
                    "request_class": (pre.annotations or {}).get(
                        "request_class", "interactive"
                    ),
                    "transport": self.transport,
                    "transfer_address": self.receiver.address,
                    # Shared secret for the transfer plane; the queue is
                    # the trusted control plane that carries it.
                    "transfer_auth": self.receiver.auth,
                    "layout": self._layout(),
                    # Decode already holds blocks [0, start_block) from
                    # its prefix cache — ship only the suffix.
                    "start_block": info["start_block"],
                    # Trace identity + enqueue stamp: the consumer adopts
                    # the trace and retro-records the queue wait as a
                    # ``queue_wait`` span (wall clock — the wait itself
                    # crosses processes, same rationale as deadline_unix).
                    "trace": tracer().context_wire(
                        request.id, parent_span="queue_wait"
                    ),
                    "trace_pid": os.getpid(),
                    "enqueued_unix": time.time(),
                }
                if pre.deadline is not None:
                    # Wall-clock absolute: the QUEUE WAIT itself must
                    # count against the budget across processes (a
                    # remaining-ms re-anchor at dequeue would forgive it).
                    req["deadline_unix"] = pre.deadline.to_unix()
                if self.device_receiver is not None:
                    # Same-process fast path: HBM→HBM, no host staging.
                    req["device_address"] = self.device_receiver.address
                    req["device_auth"] = self.device_receiver.auth
                ok = True
                if self.transport == "native":
                    n_transfer = info["num_blocks"] - info["start_block"]
                    slots = self.receiver.reserve(request.id, n_transfer)
                    if slots is not None:
                        req["staging_slots"] = slots
                        req["staging_pitch"] = self.receiver.block_bytes
                    elif self.tcp_receiver is not None:
                        # Staging arena can't fund this transfer — keep it
                        # REMOTE over the staging-free tcp wire (the
                        # device fast path, if the sender resolves it,
                        # still wins and ignores these fields).
                        req["transport"] = "tcp"
                        req["transfer_address"] = self.tcp_receiver.address
                        req["transfer_auth"] = self.tcp_receiver.auth
                    else:
                        ok = False  # pinned native — do it locally
                if ok:
                    # Bounded enqueue: a full/stalled queue keeps this
                    # prefill LOCAL (graceful fallback) rather than
                    # queueing work the pool can't absorb.
                    if await self.queue.try_enqueue(req):
                        self.remote_count += 1
                        # Enqueued for REAL: from here a kv_transfer
                        # span is required for a complete timeline
                        # (trace_merge checks) — marked only after the
                        # bounded queue accepted, so a local fallback
                        # never demands a transfer that won't happen.
                        tracer().mark(request.id, "remote_prefill")
                    else:
                        self.engine.cancel_remote(request.id)
                        stream = None
                else:
                    self.engine.cancel_remote(request.id)
                    stream = None
        if stream is None:
            self.local_count += 1
            stream = self.engine.generate(request)
        async for item in stream:
            yield item


class PrefillWorker:
    """Queue consumer: prefill → push KV → notify."""

    def __init__(self, engine: TpuEngine, queue: PrefillQueue) -> None:
        self.engine = engine
        self.queue = queue
        self.sender = KvSender()
        self._native_sender = None  # lazily built on first native request
        self._task: asyncio.Task | None = None
        self._stopping = asyncio.Event()
        self.served = 0

    def start(self) -> "PrefillWorker":
        self._task = asyncio.ensure_future(self._run())
        return self

    async def _run(self) -> None:
        # Drain in BATCHES up to the engine's fused prefill width: a
        # serial per-request drain left the prefill engine at 1/lanes of
        # its fused prefill throughput (the r05 disagg-bench diagnosis —
        # BENCHMARKS.md "Disaggregation measured on the chip").
        width = max(1, getattr(self.engine.cfg, "prefill_batch", 1))
        while not self._stopping.is_set():
            got = await self.queue.dequeue(timeout_s=0.2)
            if got is None:
                continue
            batch = [got]
            while len(batch) < width:
                more = await self.queue.dequeue(timeout_s=0.0)
                if more is None:
                    break
                batch.append(more)
            # Shed expired entries at the dequeue hop: a queued prefill
            # past its deadline is acked away, never executed — the decode
            # side's own deadline sweep cancels the waiting sequence.
            live = []
            for item_id, req in batch:
                du = req.get("deadline_unix")
                if du is not None and time.time() > du:
                    OVERLOAD.note_deadline("prefill_queue")
                    logger.warning(
                        "shedding expired queued prefill %s",
                        req.get("request_id"),
                    )
                    try:
                        await self.queue.ack(item_id)
                    except Exception:  # dynalint: allow[DT003] unacked expired item just redelivers and re-sheds
                        pass
                else:
                    live.append((item_id, req))
            batch = live
            if not batch:
                continue
            try:
                await self._serve_batch([r for _, r in batch])
            except Exception:  # dynalint: allow[DT003] batch is re-enqueued below with a bounded attempt count
                logger.exception("prefill batch failed")
                # Retry elsewhere, but BOUNDED: re-enqueue with an
                # attempt count and ack the originals, so a poison
                # request can't nack-to-front spin forever. Worker
                # DEATH (no ack at all) is covered by lease redelivery.
                for item_id, req in batch:
                    try:
                        attempts = req.get("attempts", 0) + 1
                        if attempts >= self.MAX_ATTEMPTS:
                            logger.error(
                                "dropping prefill %s after %d failed "
                                "attempts",
                                req.get("request_id"), attempts,
                            )
                        else:
                            RETRIES.note("prefill.requeue")
                            await self.queue.enqueue(
                                {**req, "attempts": attempts}
                            )
                        await self.queue.ack(item_id)
                    except Exception:  # dynalint: allow[DT003] requeue/ack failure is covered by lease-expiry redelivery
                        pass
                continue
            self.served += len(batch)
            for item_id, req in batch:
                try:
                    await self.queue.ack(item_id)
                # dynalint: allow[DT003] served but un-acked: at-least-once delivery; decode drops duplicate frames
                except Exception:
                    # Served but un-acked: at-least-once means a possible
                    # duplicate prefill later; the decode side drops
                    # frames for unknown/finished request ids — safe.
                    logger.warning(
                        "ack of served prefill %s failed "
                        "(duplicate possible)",
                        req.get("request_id"),
                    )

    # One attempt budget for both requeue paths (engine-full and failed
    # batch), shared with the rest of the stack (utils/retry.py).
    MAX_ATTEMPTS = QUEUE_REDELIVERY.attempts

    def _check_layout(self, req: dict) -> bool:
        """Validate the decode side's advertised block layout against this
        engine's. Hard mismatches (layer/head counts, block size, dtype)
        reject explicitly; a head-dim difference (lane padding) is repacked
        in _repack (ADVICE r02: previously surfaced as a reshape error deep
        in scatter_block)."""
        layout = req.get("layout")
        if layout is None:
            return True  # legacy peer — old behavior (pitch check remains)
        m = self.engine.cfg.model
        hard = (
            layout.get("num_layers", m.num_layers) == m.num_layers
            and layout.get("num_kv_heads", m.num_cache_heads)
            == m.num_cache_heads
            and layout.get("block_size", self.engine.cfg.block_size)
            == self.engine.cfg.block_size
            and layout.get("dtype", self.engine.cfg.dtype)
            == self.engine.cfg.dtype
            # Precision must match exactly: packed int8 rows are not
            # repackable into a bf16 cache's layout (and vice versa).
            and layout.get("kv_quant", self.engine.cfg.kv_quant)
            == self.engine.cfg.kv_quant
        )
        if hard and self.engine.cfg.kv_quant:
            # Quantized pairs also need head_dim EXACT (the soft lane
            # repack below does not apply to packed rows).
            hard = (
                layout.get("head_dim", self.engine.runner.cache_head_dim)
                == self.engine.runner.cache_head_dim
            )
        if hard and layout.get("checksum", CHECKSUM_ALGO) != CHECKSUM_ALGO:
            # Mixed-fleet refusal (loud, same posture as the G4 blockset
            # reject): the decode side verifies frames under an algorithm
            # this worker does not speak — its receiver would quarantine
            # every block we ship. A layout that OMITS the field is a
            # legacy peer and stays accepted (frames ride unchecksummed).
            logger.error(
                "prefill %s: decode peer verifies KV with %r, this worker "
                "stamps %r — rejecting (mixed integrity fleet; upgrade "
                "the lagging side)",
                req.get("request_id"), layout.get("checksum"),
                CHECKSUM_ALGO,
            )
            hard = False
        elif not hard:
            logger.error(
                "prefill %s: incompatible KV layout %s vs local "
                "(layers=%d kvH=%d bs=%d dtype=%s) — rejecting",
                req.get("request_id"), layout, m.num_layers,
                m.num_cache_heads,
                self.engine.cfg.block_size, self.engine.cfg.dtype,
            )
        return hard

    def _repack(self, blocks: list, req: dict) -> list:
        """Pad/trim the lane (head_dim) axis to the decode side's cache
        layout. Lane padding is zeros, so this is exact both ways."""
        layout = req.get("layout")
        if layout is None:
            return blocks
        if self.engine.cfg.kv_quant:
            # Packed quantized rows carry a scale sidecar — lane repack
            # does not apply (layout check already enforced an exact
            # match, including head_dim, for quantized pairs).
            return blocks
        want = layout.get("head_dim")
        have = self.engine.runner.cache_head_dim
        if want is None or want == have:
            return blocks
        import numpy as np

        out = []
        for b in blocks:
            arr = np.asarray(b)
            if want > have:
                pad = [(0, 0)] * (arr.ndim - 1) + [(0, want - have)]
                out.append(np.pad(arr, pad))
            else:
                out.append(np.ascontiguousarray(arr[..., :want]))
        return out

    def _device_addr(self, req: dict) -> str | None:
        """Same-process decode peer ⇒ device path (HBM→HBM, no host
        staging, no repack) — but ONLY for matching shardings:
        device-resident block snapshots carry this runner's sharding, and
        scattering them into a differently-sharded cache must go through
        the logical (host/wire) layout instead. A layout WITHOUT sharding
        fields (older peer) must not be assumed to match — the sentinel
        forces the sharding-agnostic wire path. kv_sp (slot-sharded)
        caches count too: tp alone would wave a replicated->slot-sharded
        pair through."""
        from dynamo_tpu.disagg import device_transfer

        mesh = getattr(self.engine.runner, "mesh", None)
        my_tp = int(dict(mesh.shape).get("tp", 1)) if mesh is not None else 1
        my_sp = int(dict(mesh.shape).get("sp", 1)) if mesh is not None else 1
        my_sharding = (my_tp, my_sp if self.engine.cfg.kv_sp else 1)
        layout = req.get("layout") or {}
        peer_sharding = (layout.get("tp", -1), layout.get("kv_sp", -1))
        dev_addr = (
            req.get("device_address") if peer_sharding == my_sharding else None
        )
        if dev_addr and device_transfer.resolve(dev_addr) is not None:
            return dev_addr
        return None

    async def _serve_batch(self, reqs: list[dict]) -> None:
        """Prefill a batch of queue entries through the engine's FUSED
        lanes (prefill_only_batch), then ship each result over its own
        transport (device / native / tcp)."""
        good: list[dict] = []
        devs: list[str | None] = []
        for req in reqs:
            if not self._check_layout(req):
                continue  # decode's remote_kv_timeout reclaims the slot
            rid = req.get("request_id", "")
            # Join the request's trace: spans this worker records land
            # under the decode side's trace id, and the queue wait it
            # just finished is retro-recorded from the enqueue stamp.
            ctx_trace = TraceContext.from_wire(req.get("trace"))
            if ctx_trace is not None:
                # The queue entry's context is serialized at ENQUEUE, so
                # recv - sent here measures queue dwell (already recorded
                # as queue_wait below), not clock offset — a loaded queue
                # would otherwise report seconds of "skew" between
                # NTP-synced hosts. Low-latency seams (bus envelope) keep
                # their hints.
                ctx_trace.sent_unix = None
            tracer().adopt(rid, ctx_trace)
            # Span only entries that CARRY trace context: add_span
            # auto-opens, and a legacy (pre-trace) entry would emit a
            # junk single-process trace under a fresh id no other
            # process shares.
            if ctx_trace is not None and req.get("enqueued_unix"):
                tracer().add_span(
                    rid, "queue_wait", start_unix=float(req["enqueued_unix"])
                )
            good.append(req)
            devs.append(self._device_addr(req))
        if not good:
            return
        items = [
            (
                PreprocessedRequest(
                    token_ids=req["token_ids"],
                    sampling=SamplingOptions.from_wire(
                        req.get("sampling") or {}
                    ),
                    # Class-tagged queue entry (llm/slo.py): rides into
                    # the prefill sequence's slo_class via annotations.
                    annotations=(
                        {"request_class": req["request_class"]}
                        if req.get("request_class") else {}
                    ),
                ),
                req["request_id"],
                dev is not None,
            )
            for req, dev in zip(good, devs)
        ]
        futs = self.engine.prefill_only_batch(items)

        async def ship(req: dict, dev: str | None, fut) -> None:
            # Each item resolves as ITS prompt completes — ship right
            # then, not when the whole batch lands (TTFT would otherwise
            # pay the full batch's prefill time). Failures stay PER-ITEM:
            # one flaky send must not propagate and re-enqueue batch
            # mates that already shipped (they'd be prefilled twice).
            rid = req.get("request_id", "")
            # Trace id from the WIRE, not tracer().trace_id(): the
            # latter auto-opens a capture, and an entry without trace
            # context (pre-upgrade producer in a rolling deploy) would
            # open one nothing ever finishes.
            tid = (req.get("trace") or {}).get("trace_id") or None
            with request_scope(rid, tid):
                requeued = False
                try:
                    result = await fut
                    if result is None:
                        requeued = await self._requeue_full(req)
                        return
                    first_token, blocks = result
                    # Record kv_transfer only once the send SUCCEEDS: a
                    # failed attempt is requeued and retried, and a span
                    # per failed try would be summed by trace_merge's
                    # decomposition, overstating kv_transfer for exactly
                    # the retried requests.
                    t0_send = time.monotonic()
                    await self._send_result(
                        req, dev, first_token, blocks, tid
                    )
                    if tid:
                        # Same traceless-legacy guard as queue_wait
                        # above: never auto-open a junk trace.
                        tracer().add_span(
                            rid, "kv_transfer", start_mono=t0_send
                        )
                # dynalint: allow[DT003] failed ship is requeued in full; decode's timeout degrades it if that loses too
                except Exception:
                    logger.exception(
                        "shipping prefill %s failed", req.get("request_id")
                    )
                    requeued = await self._requeue_full(req)
                finally:
                    if req.get("trace_pid") != os.getpid():
                        # Cross-process item (including trace_pid=None —
                        # an entry from a producer that predates trace
                        # context): this worker's half of the capture
                        # closes here (its spans already streamed out);
                        # the decode/frontend side owns the real finish.
                        # In-process the trace is SHARED — leave it to
                        # the decode side's finish.
                        if not requeued:
                            tracer().finish(rid)
                        else:
                            # A REQUEUED item is still in flight and its
                            # next consumption may land on a DIFFERENT
                            # worker — holding this capture open for a
                            # same-process re-adopt would TTL-reap it as
                            # "abandoned" whenever a peer wins the pop,
                            # inflating abandoned_traces_total on routine
                            # engine-full churn. Close it without stats:
                            # re-consumption (here or elsewhere) adopts a
                            # fresh capture under the same trace id, and
                            # the requeue re-stamps enqueued_unix.
                            tracer().abandon(rid, reason="requeued")

        await asyncio.gather(
            *(ship(r, d, f) for r, d, f in zip(good, devs, futs))
        )

    async def _send_result(
        self,
        req: dict,
        dev_addr: str | None,
        first_token: int,
        blocks,
        trace_id: str | None = None,
    ) -> None:
        from dynamo_tpu.disagg import device_transfer

        start = req.get("start_block", 0)
        if dev_addr is not None:
            await device_transfer.DeviceKvSender().send_blocks(
                dev_addr,
                req["request_id"],
                blocks[start:],
                first_token,
                start_idx=start,
                auth=req.get("device_auth"),
            )
            return
        blocks = self._repack(blocks, req)
        if req.get("transport") == "native":
            if self._native_sender is None:
                from dynamo_tpu.disagg.native_transfer import NativeKvSender

                self._native_sender = NativeKvSender()
            await self._native_sender.send_blocks(
                req["transfer_address"],
                req["request_id"],
                blocks[start:],
                first_token,
                start_idx=start,
                staging_slots=req["staging_slots"],
                staging_pitch=req.get("staging_pitch"),
                auth=req.get("transfer_auth"),
            )
        else:
            await self.sender.send_blocks(
                req["transfer_address"],
                req["request_id"],
                blocks[start:],
                first_token,
                start_idx=start,
                auth=req.get("transfer_auth"),
                # Wire-derived id from ship(): tracer().trace_id() here
                # would auto-open (and stamp frames with) a meaningless
                # fresh trace for legacy entries without trace context.
                trace_id=trace_id,
            )

    async def _requeue_full(self, req: dict) -> bool:
        """Engine full — requeue for another worker / a quieter moment.
        Bounded by the shared backoff policy: a never-admittable request
        must not cycle forever (the decode side's remote_kv_timeout
        reclaims its slot), and each cycle backs off exponentially so a
        saturated pool isn't hammered. Returns True when the item went
        back on the queue (it is still in flight), False when it was
        dropped for good."""
        attempts = req.get("attempts", 0) + 1
        if attempts >= self.MAX_ATTEMPTS:
            logger.error(
                "dropping prefill %s after %d attempts",
                req.get("request_id"), attempts,
            )
            return False
        RETRIES.note("prefill.requeue")
        # Fresh enqueue stamp: the retro-recorded queue_wait span on the
        # NEXT consumption must cover only that dwell — keeping the
        # original stamp would fold this attempt's prefill + transfer
        # time into queue_wait and corrupt the TTFT decomposition.
        await self.queue.enqueue(
            {**req, "attempts": attempts, "enqueued_unix": time.time()}
        )
        await asyncio.sleep(QUEUE_REDELIVERY.delay_for(attempts - 1))
        return True

    async def stop(self) -> None:
        """Graceful drain: finish the in-flight item, then stop."""
        self._stopping.set()
        if self._task is not None:
            await self._task
        await self.sender.close()
        if self._native_sender is not None:
            await self._native_sender.close()

"""Disaggregated prefill/decode serving (pillar 1 of the reference).

Decode workers keep interactive ITL by pushing long prefills to dedicated
prefill workers; computed KV blocks stream back over the transfer plane
into the decode worker's pre-allocated HBM blocks (reference:
docs/architecture/disagg_serving.md; examples/llm/components/
{worker,prefill_worker,disagg_router}.py; NIXL xfer → our DCN TCP agent,
upgradeable to the C++ native agent).
"""

from dynamo_tpu.disagg.queue import PrefillQueue
from dynamo_tpu.disagg.router import DisaggConfig, DisaggRouter
from dynamo_tpu.disagg.transfer import KvReceiver, KvSender
from dynamo_tpu.disagg.worker import DecodeOperator, PrefillWorker

__all__ = [
    "DecodeOperator",
    "DisaggConfig",
    "DisaggRouter",
    "KvReceiver",
    "KvSender",
    "PrefillQueue",
    "PrefillWorker",
]

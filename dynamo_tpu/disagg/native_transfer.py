"""Disagg KV transfer over the native C++ agent.

The production data path (reference analogue: NIXL write + notification,
docs/architecture/disagg_serving.md:78-109): the decode worker reserves
staging slots in a registered host arena; the prefill worker's C++ client
writes block bytes straight into those slots (no Python on the receive
path) and posts one notification; the decode side drains completions,
scatters host→HBM on the engine thread, and frees the slots.

Each reservation registers its slots as their own generation-tagged
regions (region id = generation<<16 | slot) and unregisters them on
release/expiry — a LATE write from a slow prefill whose reservation
expired bounces at the C++ region lookup instead of corrupting whatever
request now owns the physical slot.

Falls back to disagg/transfer.py's asyncio implementation when the native
library can't build.
"""

from __future__ import annotations

import asyncio
import logging
import time

import msgpack
import numpy as np

from dynamo_tpu.block_manager.config import KvLayoutConfig
from dynamo_tpu.block_manager.integrity import INTEGRITY, block_checksum
from dynamo_tpu.native.transfer import TransferClient, TransferServer
from dynamo_tpu.utils.faults import FAULTS
from dynamo_tpu.utils.retry import TRANSFER, retry_async

logger = logging.getLogger(__name__)

class NativeKvReceiver:
    """Decode-side: staging arena + completion pump."""

    def __init__(
        self,
        on_block,
        on_finish,
        layout: KvLayoutConfig,
        num_slots: int = 64,
        host: str = "127.0.0.1",
        reservation_timeout_s: float = 30.0,
    ) -> None:
        self._on_block = on_block
        self._on_finish = on_finish
        self.layout = layout
        self._host = host
        self.block_bytes = layout.block_bytes
        self._arena = np.zeros((num_slots, self.block_bytes), np.uint8)
        self._free = list(range(num_slots - 1, -1, -1))
        # request_id -> (region_ids, reserve_time). Region ids are
        # generation-tagged (gen<<16 | slot) and registered/unregistered
        # with the C++ server per reservation.
        self._reserved: dict[str, tuple[list[int], float]] = {}
        self._gen = 1
        self._timeout_s = reservation_timeout_s
        self.server: TransferServer | None = None
        self.auth: str | None = None  # hex token peers must present
        self._pump: asyncio.Task | None = None

    async def start(self) -> "NativeKvReceiver":
        from dynamo_tpu.disagg.net import bind_for_advertise

        self.server = TransferServer(bind_host=bind_for_advertise(self._host))
        self.auth = self.server.token.hex()
        self._pump = asyncio.ensure_future(self._poll_loop())
        return self

    @property
    def address(self) -> str:
        return f"{self._host}:{self.server.port}"

    def reserve(self, request_id: str, n_blocks: int) -> list[int] | None:
        """Claim staging slots for one inbound transfer; None if exhausted.

        Returns generation-tagged REGION ids (not raw slot indices): each
        is registered with the server for exactly this reservation's
        lifetime, so a late write from an expired transfer bounces at the
        region lookup instead of landing in a recycled slot."""
        if len(self._free) < n_blocks:
            self._expire()
            if len(self._free) < n_blocks:
                return None
        gen = self._gen
        self._gen += 1
        regions = []
        for _ in range(n_blocks):
            slot = self._free.pop()
            region = (gen << 16) | slot
            self.server.register(region, self._arena[slot])
            regions.append(region)
        self._reserved[request_id] = (regions, time.monotonic())
        return regions

    def _expire(self) -> None:
        now = time.monotonic()
        for rid, (slots, t0) in list(self._reserved.items()):
            if now - t0 > self._timeout_s:
                logger.warning("expiring staging reservation %s", rid)
                self._release(rid)

    def release(self, request_id: str) -> None:
        """Public release of a reservation whose transfer completed out of
        band (e.g. the sender took the same-process device path)."""
        self._release(request_id)

    def _release(self, request_id: str) -> None:
        regions, _ = self._reserved.pop(request_id, ([], 0.0))
        for region in regions:
            self.server.unregister(region)
            self._free.append(region & 0xFFFF)

    async def _poll_loop(self) -> None:
        while True:
            ev = self.server.poll()
            if ev is None:
                await asyncio.sleep(0.002)
                continue
            try:
                self._handle(ev)
            except Exception:  # dynalint: allow[DT003] one bad completion event must not kill the poll loop; the request times out and degrades
                logger.exception("bad native transfer completion")

    def _handle(self, ev: tuple[int, bytes]) -> None:
        _, meta = ev
        m = msgpack.unpackb(meta)
        rid = m["req"]
        if rid not in self._reserved:
            logger.warning("completion for unknown reservation %s", rid)
            return
        # The sender's metadata is untrusted: only regions actually
        # reserved for THIS request may be read, else a buggy or malicious
        # peer could feed another request's staged bytes into this one.
        owned = set(self._reserved[rid][0])
        try:
            shape = tuple(m["shape"])
            dtype = np.dtype(m["dtype"])
            if not shape or any(
                not isinstance(d, int) or d <= 0 for d in shape
            ):
                raise ValueError(f"bad block shape {shape}")
            nbytes = dtype.itemsize * int(np.prod(shape))
            if nbytes > self.block_bytes:
                raise ValueError(f"block payload {nbytes}B > {self.block_bytes}B")
            crcs = m.get("crcs")
            for j, (seq_idx, region) in enumerate(m["blocks"]):
                if region not in owned:
                    raise ValueError(
                        f"region {region} not reserved for request {rid}"
                    )
                staged = self._arena[region & 0xFFFF, :nbytes]
                if crcs is not None and block_checksum(staged) != crcs[j]:
                    # Staged bytes drifted from what the sender hashed
                    # (wire corruption or a torn write into the slot):
                    # skip the block — the hole in the completeness
                    # ledger degrades the request to local recompute,
                    # byte-identical. Checked before the dtype view so a
                    # short write can never surface as garbage KV.
                    INTEGRITY.note_failure("frame")
                    logger.warning(
                        "native kv receiver: staged block %s/%s failed "
                        "checksum; dropped", rid, seq_idx,
                    )
                    continue
                data = (
                    staged
                    .view(dtype)
                    .reshape(shape)
                    .copy()  # slot is about to be freed/reused
                )
                self._on_block(rid, seq_idx, data)
            self._on_finish(rid, m["first_token"])
        finally:
            # Always free the reservation — a malformed completion must not
            # leak slots until the expiry sweep.
            self._release(rid)

    async def stop(self) -> None:
        if self._pump is not None:
            self._pump.cancel()
            try:
                await self._pump
            except asyncio.CancelledError:
                pass
        if self.server is not None:
            self.server.close()


class NativeKvSender:
    """Prefill-side: one C++ connection per destination."""

    def __init__(self) -> None:
        self._conns: dict[str, TransferClient] = {}

    def _conn(self, address: str, auth: str | None = None) -> TransferClient:
        if address not in self._conns:
            host, port = address.rsplit(":", 1)
            token = bytes.fromhex(auth) if auth else None
            self._conns[address] = TransferClient(host, int(port), token)
        return self._conns[address]

    async def send_blocks(
        self,
        address: str,
        request_id: str,
        blocks: list[np.ndarray],
        first_token: int,
        start_idx: int = 0,
        staging_slots: list[int] | None = None,
        staging_pitch: int | None = None,
        auth: str | None = None,
    ) -> None:
        assert staging_slots is not None and len(staging_slots) == len(blocks)

        def push(client: TransferClient) -> None:
            entries = []
            crcs = []
            shape, dtype = None, None
            for j, data in enumerate(blocks):
                arr = np.ascontiguousarray(data)
                if arr.dtype.name == "bfloat16":
                    arr = arr.view(np.uint16)
                shape, dtype = list(arr.shape), arr.dtype.str
                pitch = staging_pitch or arr.nbytes
                if arr.nbytes > pitch:
                    raise ValueError(
                        f"block {arr.nbytes}B exceeds staging pitch {pitch}B"
                    )
                # staging_slots carry generation-tagged region ids; each
                # region IS one staging slot, so the write offset is 0.
                region = staging_slots[j]
                # Integrity envelope over the exact bytes handed to the
                # C++ client; the decode side re-hashes the staged slot
                # before trusting it (corruption on the wire or in the
                # staging arena shows up as a mismatch there).
                payload = arr.tobytes()
                crcs.append(block_checksum(payload))
                if FAULTS.active:
                    # Mutate AFTER the crc was stamped — wire corruption
                    # the receiver-side check must catch. A truncating
                    # mutation writes only a prefix of the slot.
                    payload = FAULTS.corrupt("kvbm.corrupt_frame", payload)
                client.write(region, 0, np.frombuffer(payload, np.uint8))
                entries.append([start_idx + j, region])
            client.notify(
                0,
                msgpack.packb(
                    {
                        "req": request_id,
                        "first_token": first_token,
                        "blocks": entries,
                        "shape": shape,
                        "dtype": dtype,
                        "crcs": crcs,
                    }
                ),
            )

        # Connection construction (incl. DNS resolution) happens inside the
        # worker thread — a slow resolver must not stall the event loop.
        def attempt() -> None:
            FAULTS.maybe_fail("disagg.send")
            push(self._conn(address, auth))

        def drop_stale(_exc, _n) -> None:
            stale = self._conns.pop(address, None)
            if stale is not None:
                stale.close()

        # Shared backoff policy (utils/retry.py), fresh connection per
        # retry. Re-pushing already-landed writes is safe: the receiver's
        # completion handler frees the reservation, so a duplicate notify
        # after success bounces at the region lookup instead of landing.
        try:
            await retry_async(
                lambda: asyncio.to_thread(attempt),
                TRANSFER,
                seam="disagg.native_send",
                on_retry=drop_stale,
            )
        except BaseException:
            # Budget exhausted: a half-written frame may sit on the cached
            # socket — never reuse it for the next request.
            drop_stale(None, 0)
            raise

    async def close(self) -> None:
        for c in self._conns.values():
            c.close()
        self._conns.clear()

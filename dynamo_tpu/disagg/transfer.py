"""KV-block transfer plane: prefill worker → decode worker HBM.

The role NIXL plays in the reference (reference: docs/architecture/
disagg_serving.md:78-109 — RDMA write of computed KV into the decode
worker's pre-allocated blocks + completion notification). TPU path: DCN/TCP
into the decode host's staging memory, then host→HBM scatter on the decode
engine's thread. Framing is the runtime's two-part codec; payloads are raw
block bytes (dtype/shape from the header), so a future C++ agent can speak
the identical protocol (native/transfer_agent).

Wire: header msgpack {"req": id, "kind": "block"|"finish", "idx": n,
"dtype": str, "shape": [..]} + payload bytes.
"""

from __future__ import annotations

import asyncio
import logging
from typing import Callable

import msgpack
import numpy as np

from dynamo_tpu.block_manager.integrity import INTEGRITY, block_checksum
from dynamo_tpu.runtime.transports.codec import encode_frame, read_frame
from dynamo_tpu.utils.faults import FAULTS
from dynamo_tpu.utils.retry import TRANSFER, retry_async

logger = logging.getLogger(__name__)


class KvReceiver:
    """Decode-side landing server. `on_block(req, idx, data)` and
    `on_finish(req, first_token)` are called as frames land (thread-safe
    targets: the engine's submit queue)."""

    def __init__(
        self,
        on_block: Callable[[str, int, np.ndarray], None],
        on_finish: Callable[[str, int], None],
        host: str = "127.0.0.1",
    ) -> None:
        import secrets

        self._on_block = on_block
        self._on_finish = on_finish
        self._host = host
        self._server: asyncio.AbstractServer | None = None
        self.port: int = 0
        # Hex token peers must present in their first frame (distributed
        # via the trusted control plane — the queue entry).
        self.auth: str = secrets.token_hex(16)

    async def start(self) -> "KvReceiver":
        # `host` is the ADVERTISE address; a non-loopback one implies
        # remote peers, so bind all interfaces (shared policy).
        from dynamo_tpu.disagg.net import bind_for_advertise

        self._server = await asyncio.start_server(
            self._on_conn, bind_for_advertise(self._host), 0
        )
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    @property
    def address(self) -> str:
        return f"{self._host}:{self.port}"

    async def _on_conn(self, reader, writer) -> None:
        import hmac

        try:
            # Auth-first: the connection's first frame must carry the token.
            header, _ = await read_frame(reader)
            h = msgpack.unpackb(header)
            if h.get("kind") != "auth" or not hmac.compare_digest(
                str(h.get("token", "")), self.auth
            ):
                logger.warning("kv receiver: rejected unauthenticated peer")
                return
            while True:
                header, payload = await read_frame(reader)
                # Injected receive failure: raise/partition kills the
                # connection mid-transfer (the sender's retry/requeue
                # path takes over); drop silently loses ONE frame — the
                # decode side's remote_kv_timeout then degrades the
                # request to local recompute.
                if FAULTS.active and not await FAULTS.maybe_fail_async(
                    "disagg.recv", can_drop=True
                ):
                    continue
                h = msgpack.unpackb(header)
                if h["kind"] == "block":
                    crc = h.get("crc")
                    if crc is not None and block_checksum(payload) != crc:
                        # Corrupt KV frame: treated EXACTLY like a
                        # dropped one (checked before frombuffer — a
                        # truncated payload must not raise) — the hole
                        # in the completeness ledger degrades the
                        # request to local recompute, byte-identical.
                        INTEGRITY.note_failure("frame")
                        logger.warning(
                            "kv receiver: frame %s/%s failed checksum; "
                            "dropped", h.get("req"), h.get("idx"),
                        )
                        continue
                    data = np.frombuffer(payload, dtype=h["dtype"]).reshape(
                        h["shape"]
                    )
                    self._on_block(h["req"], h["idx"], data)
                elif h["kind"] == "finish":
                    # Correlate the landing with the request's trace —
                    # mark ONLY an already-open trace: a late finish
                    # frame for a cancelled request must not re-open one
                    # that would then leak until the TTL sweep.
                    from dynamo_tpu.utils.tracing import tracer

                    tracer().mark_if_active(h["req"], "kv_landed")
                    self._on_finish(h["req"], h["first_token"])
                    # ack so the sender can sequence completion
                    writer.write(encode_frame(msgpack.packb({"ok": True})))
                    await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        # dynalint: allow[DT003] per-connection handler: the lost transfer degrades to recompute via the seq ledger
        except Exception:
            logger.exception("kv receiver connection failed")
        finally:
            writer.close()

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()


class KvSender:
    """Prefill-side pusher. One connection per destination worker, reused
    across requests."""

    # Bound on the completion-ack wait: a receiver that accepted every
    # frame but never acks (wedged process, lost finish frame) must fail
    # the attempt — retryable TimeoutError — not hang the prefill worker.
    # Sized so the WHOLE retried send (3 ack waits + backoff, capped by
    # TRANSFER.deadline_s) finishes inside the decode side's
    # remote_kv_timeout_s (default 30 s): retrying past the moment the
    # decode engine degrades the request to local recompute only holds
    # the per-destination lock against other requests' sends.
    ACK_TIMEOUT_S = 8.0

    def __init__(self) -> None:
        self._conns: dict[str, tuple] = {}
        self._locks: dict[str, asyncio.Lock] = {}

    def _lock(self, address: str) -> asyncio.Lock:
        if address not in self._locks:
            self._locks[address] = asyncio.Lock()
        return self._locks[address]

    async def _conn(self, address: str, auth: str | None = None):
        if address not in self._conns:
            host, port = address.rsplit(":", 1)
            reader, writer = await asyncio.open_connection(host, int(port))
            # Auth-first frame (see KvReceiver._on_conn).
            writer.write(
                encode_frame(
                    msgpack.packb({"kind": "auth", "token": auth or ""})
                )
            )
            await writer.drain()
            self._conns[address] = (reader, writer)
        return self._conns[address]

    async def send_blocks(
        self,
        address: str,
        request_id: str,
        blocks: list[np.ndarray],
        first_token: int,
        start_idx: int = 0,
        auth: str | None = None,
        trace_id: str | None = None,
    ) -> None:
        """Push all blocks then the completion notification; awaits the
        receiver's ack (the reference's NIXL completion semantics). The
        per-destination lock keeps concurrent requests' ack reads ordered.
        Transport loss retries on a FRESH connection under the shared
        backoff policy (utils/retry.py TRANSFER — the reference's NIXL
        transfer-retry role); resends are safe because the receiver
        scatters blocks idempotently by (req, idx).

        ``trace_id`` rides the frame headers (docs/architecture/
        observability.md): a transfer captured on the wire — or logged by
        the receiver — stays attributable to its request's trace."""
        async with self._lock(address):
            try:
                await retry_async(
                    lambda: self._send_locked(
                        address, request_id, blocks, first_token, start_idx,
                        auth, trace_id,
                    ),
                    TRANSFER,
                    seam="disagg.send",
                    on_retry=lambda _exc, _n: self._drop_conn(address),
                )
            except BaseException:
                # Budget exhausted (or non-retryable): the cached socket
                # may still be live with THIS request's ack pending — a
                # reuse would read that late ack as the NEXT request's
                # completion and desync every send after it.
                self._drop_conn(address)
                raise

    def _drop_conn(self, address: str) -> None:
        conn = self._conns.pop(address, None)
        if conn is not None:
            conn[1].close()

    async def _send_locked(
        self, address, request_id, blocks, first_token, start_idx=0,
        auth=None, trace_id=None,
    ) -> None:
        await FAULTS.maybe_fail_async("disagg.send")
        reader, writer = await self._conn(address, auth)
        for i, data in enumerate(blocks, start=start_idx):
            arr = np.ascontiguousarray(data)
            # bf16 has no portable wire name — ship its uint16 bits.
            if arr.dtype.name == "bfloat16":
                arr = arr.view(np.uint16)
            payload = arr.tobytes()
            header = {
                "req": request_id,
                "kind": "block",
                "idx": i,
                "dtype": arr.dtype.str,
                "shape": list(arr.shape),
                # Integrity envelope over the exact payload bytes: the
                # receiver refuses a frame whose bytes drifted in flight
                # (the layout handshake advertised the algorithm —
                # disagg/worker.py _check_layout).
                "crc": block_checksum(payload),
            }
            if trace_id:
                header["trace"] = trace_id
            if FAULTS.active:
                # Wire corruption after the crc was stamped — exactly
                # what the receiver-side check must catch.
                payload = FAULTS.corrupt("kvbm.corrupt_frame", payload)
            writer.write(encode_frame(msgpack.packb(header), payload))
        fin = {
            "req": request_id, "kind": "finish", "first_token": first_token,
        }
        if trace_id:
            fin["trace"] = trace_id
        writer.write(encode_frame(msgpack.packb(fin)))
        await writer.drain()
        # Completion ack, bounded (see ACK_TIMEOUT_S). The conn is
        # dropped on every failure path — between retries AND at budget
        # exhaustion (send_blocks) — so a late ack on this socket can
        # never be read as a later request's completion.
        await asyncio.wait_for(read_frame(reader), self.ACK_TIMEOUT_S)

    async def close(self) -> None:
        for _, writer in self._conns.values():
            writer.close()
        self._conns.clear()

"""Prefill work queue.

A named work queue on the bus shared by all prefill workers of a namespace
(reference: lib/runtime/src/transports/nats.rs:345-478 `NatsQueue` over
JetStream; examples/llm/utils/prefill_queue.py). Decode workers enqueue
RemotePrefillRequests; prefill workers compete to dequeue; queue depth
feeds the disagg decision and the planner.
"""

from __future__ import annotations

import msgpack


class PrefillQueue:
    # A prefill (chunked, possibly queued behind the engine) should finish
    # well within this; a worker that dies mid-item redelivers at expiry
    # (or immediately on connection death under the control plane).
    LEASE_S = 60.0

    def __init__(self, drt, namespace: str = "default") -> None:
        self._queue = drt.bus.work_queue(f"{namespace}.prefill_queue")

    async def enqueue(self, request: dict) -> None:
        await self._queue.enqueue(msgpack.packb(request))

    async def dequeue(
        self, timeout_s: float | None = None
    ) -> tuple[int, dict] | None:
        """Leased dequeue: returns (item_id, request); the consumer must
        ``ack(item_id)`` after the KV push completes or the item redelivers
        to another worker (at-least-once, reference NatsQueue semantics)."""
        got = await self._queue.dequeue_leased(timeout_s, lease_s=self.LEASE_S)
        if got is None:
            return None
        item_id, raw = got
        return item_id, msgpack.unpackb(raw)

    async def ack(self, item_id: int) -> bool:
        return await self._queue.ack(item_id)

    async def nack(self, item_id: int) -> bool:
        return await self._queue.nack(item_id)

    async def depth(self) -> int:
        return await self._queue.depth()

    async def oldest_age_s(self) -> float:
        """Wait time of the oldest live item — the per-item SLA signal
        for the disagg decision (depth alone misses a stalled consumer)."""
        return await self._queue.oldest_age_s()

    async def stats(self) -> tuple[int, float]:
        """(depth, oldest age) in one control-plane round trip."""
        return await self._queue.stats()

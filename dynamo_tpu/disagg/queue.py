"""Prefill work queue.

A named work queue on the bus shared by all prefill workers of a namespace
(reference: lib/runtime/src/transports/nats.rs:345-478 `NatsQueue` over
JetStream; examples/llm/utils/prefill_queue.py). Decode workers enqueue
RemotePrefillRequests; prefill workers compete to dequeue; queue depth
feeds the disagg decision and the planner.

Overload bounds (docs/architecture/overload_and_drain.md): the queue is
BOUNDED — ``try_enqueue`` refuses work when depth or oldest-item age is
over its bound, and the decode side keeps that prefill LOCAL instead (a
graceful fallback, not a client error: the request still completes at
local-prefill cost). Depth alone misses a stalled consumer pool, which is
why the age bound exists. Expired-deadline entries are shed by the
CONSUMER at dequeue (disagg/worker.py) — work nobody can finish on time
must not occupy prefill lanes.
"""

from __future__ import annotations

import logging

import msgpack

from dynamo_tpu.utils.deadline import OVERLOAD

logger = logging.getLogger(__name__)


class PrefillQueue:
    # A prefill (chunked, possibly queued behind the engine) should finish
    # well within this; a worker that dies mid-item redelivers at expiry
    # (or immediately on connection death under the control plane).
    LEASE_S = 60.0

    def __init__(
        self,
        drt,
        namespace: str = "default",
        max_depth: int = 256,
        max_age_s: float = 0.0,
    ) -> None:
        """``max_depth``/``max_age_s`` bound ``try_enqueue`` (0 = that
        bound is off). The router's ``max_prefill_queue_size`` is the
        soft, decision-level bound; these are the hard backstop against
        races and multi-decoder bursts."""
        self._queue = drt.bus.work_queue(f"{namespace}.prefill_queue")
        self.max_depth = max_depth
        self.max_age_s = max_age_s

    async def enqueue(self, request: dict) -> None:
        await self._queue.enqueue(msgpack.packb(request))

    async def try_enqueue(self, request: dict) -> bool:
        """Bounded enqueue: False when the queue is over its depth or age
        bound — the caller keeps the prefill local (shed from the REMOTE
        plane, not from the client)."""
        if self.max_depth or self.max_age_s:
            depth, age = await self.stats()
            # Entries are SLO-class-tagged (disagg/worker.py; llm/slo.py)
            # — the per-class shed split must cover this plane too, or
            # shed_{interactive,batch}_total diverge from
            # shed_requests_total on disagg deployments. Untagged legacy
            # entries normalize to interactive like every other seam.
            from dynamo_tpu.llm import slo

            cls = slo.normalize_class(request.get("request_class"))
            if self.max_depth and depth >= self.max_depth:
                OVERLOAD.note_shed("prefill_queue.depth", request_class=cls)
                logger.warning(
                    "prefill queue at depth bound (%d) — keeping prefill "
                    "local for %s",
                    self.max_depth, request.get("request_id"),
                )
                return False
            if self.max_age_s and age > self.max_age_s:
                OVERLOAD.note_shed("prefill_queue.age", request_class=cls)
                logger.warning(
                    "prefill queue oldest item %.1fs old (bound %.1fs) — "
                    "keeping prefill local for %s",
                    age, self.max_age_s, request.get("request_id"),
                )
                return False
        await self.enqueue(request)
        return True

    async def dequeue(
        self, timeout_s: float | None = None
    ) -> tuple[int, dict] | None:
        """Leased dequeue: returns (item_id, request); the consumer must
        ``ack(item_id)`` after the KV push completes or the item redelivers
        to another worker (at-least-once, reference NatsQueue semantics)."""
        got = await self._queue.dequeue_leased(timeout_s, lease_s=self.LEASE_S)
        if got is None:
            return None
        item_id, raw = got
        return item_id, msgpack.unpackb(raw)

    async def ack(self, item_id: int) -> bool:
        return await self._queue.ack(item_id)

    async def nack(self, item_id: int) -> bool:
        return await self._queue.nack(item_id)

    async def depth(self) -> int:
        return await self._queue.depth()

    async def oldest_age_s(self) -> float:
        """Wait time of the oldest live item — the per-item SLA signal
        for the disagg decision (depth alone misses a stalled consumer)."""
        return await self._queue.oldest_age_s()

    async def stats(self) -> tuple[int, float]:
        """(depth, oldest age) in one control-plane round trip."""
        return await self._queue.stats()

"""Prefill work queue.

A named work queue on the bus shared by all prefill workers of a namespace
(reference: lib/runtime/src/transports/nats.rs:345-478 `NatsQueue` over
JetStream; examples/llm/utils/prefill_queue.py). Decode workers enqueue
RemotePrefillRequests; prefill workers compete to dequeue; queue depth
feeds the disagg decision and the planner.
"""

from __future__ import annotations

import msgpack


class PrefillQueue:
    def __init__(self, drt, namespace: str = "default") -> None:
        self._queue = drt.bus.work_queue(f"{namespace}.prefill_queue")

    async def enqueue(self, request: dict) -> None:
        await self._queue.enqueue(msgpack.packb(request))

    async def dequeue(self, timeout_s: float | None = None) -> dict | None:
        raw = await self._queue.dequeue(timeout_s)
        return msgpack.unpackb(raw) if raw is not None else None

    async def depth(self) -> int:
        return await self._queue.depth()

"""Shared network policy for the KV transfer planes."""

from __future__ import annotations

_LOOPBACK = ("127.0.0.1", "localhost", "::1")


def bind_for_advertise(host: str) -> str:
    """Bind address for a receiver advertising `host`.

    A loopback advertise address keeps the listener loopback-only; anything
    else (NAT/VIP/service name or a real interface) implies remote peers,
    so bind all interfaces. One policy for both the native (C++ agent) and
    TCP-fallback planes — it is security-sensitive and must not drift.
    """
    return host if host in _LOOPBACK else "0.0.0.0"

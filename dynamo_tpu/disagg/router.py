"""Conditional disaggregation decision.

Prefill goes remote iff the *effective* prefill work (prompt beyond the
local prefix hit) is above threshold AND the shared prefill queue isn't
backed up (reference: lib/llm/src/disagg_router.rs:25-262 and its Python
mirror examples/llm/components/disagg_router.py:47-67:
``remote iff prefill_len*(1-prefix_hit_rate) > max_local AND
queue_size < max_queue``). Thresholds live in the discovery store and are
watched, so operators can retune a live system (reference:
EtcdKvCache transports/etcd.rs:471-597).
"""

from __future__ import annotations

import asyncio
import json
import logging
from dataclasses import dataclass

from dynamo_tpu.runtime.transports.store import EventKind

logger = logging.getLogger(__name__)

CONFIG_KEY = "disagg_router/config/"


@dataclass
class DisaggConfig:
    max_local_prefill_length: int = 512
    max_prefill_queue_size: int = 16
    # Per-item SLA: if the oldest queued prefill has waited longer than
    # this, the pool is stalled (dead/slow workers) even at low depth —
    # keep prefill local rather than queue behind it.
    max_prefill_queue_age_s: float = 10.0

    def to_json(self) -> bytes:
        return json.dumps(self.__dict__).encode()

    @staticmethod
    def from_json(raw: bytes) -> "DisaggConfig":
        d = json.loads(raw)
        return DisaggConfig(
            max_local_prefill_length=d.get("max_local_prefill_length", 512),
            max_prefill_queue_size=d.get("max_prefill_queue_size", 16),
            max_prefill_queue_age_s=d.get("max_prefill_queue_age_s", 10.0),
        )


class DisaggRouter:
    def __init__(
        self, drt, namespace: str = "default", cfg: DisaggConfig | None = None
    ) -> None:
        self._drt = drt
        self._ns = namespace
        self.cfg = cfg or DisaggConfig()
        self._watch_task: asyncio.Task | None = None

    @property
    def _key(self) -> str:
        return f"{CONFIG_KEY}{self._ns}"

    async def start(self) -> "DisaggRouter":
        """Load + live-watch config from the store."""
        watch = await self._drt.store.watch_prefix(self._key)
        for _, raw in watch.initial.items():
            self.cfg = DisaggConfig.from_json(raw)

        async def pump():
            async for ev in watch:
                if ev.kind is EventKind.PUT and ev.value:
                    self.cfg = DisaggConfig.from_json(ev.value)
                    logger.info("disagg config updated: %s", self.cfg)

        self._watch_task = asyncio.ensure_future(pump())
        self._drt.runtime.token.on_cancel(watch.cancel)
        return self

    async def publish_config(self, cfg: DisaggConfig) -> None:
        self.cfg = cfg
        await self._drt.store.put(self._key, cfg.to_json())

    def prefill_remote(
        self,
        prefill_length: int,
        prefix_hit_rate: float,
        queue_size: int,
        queue_age_s: float = 0.0,
    ) -> bool:
        effective = prefill_length * (1.0 - prefix_hit_rate)
        return (
            effective > self.cfg.max_local_prefill_length
            and queue_size < self.cfg.max_prefill_queue_size
            and queue_age_s < self.cfg.max_prefill_queue_age_s
        )

"""dynamo-tpu: a TPU-native distributed LLM inference serving framework.

Capabilities mirror NVIDIA Dynamo (see SURVEY.md; reference at /root/reference):
disaggregated prefill/decode serving, KV-cache-aware radix routing, multi-tier
KV block management, dynamic worker scaling, and an OpenAI-compatible streaming
frontend — rebuilt TPU-first on JAX/XLA/Pallas/pjit rather than ported.

Layering (bottom → top), mirroring the reference's structure
(reference: lib/runtime, lib/llm, lib/engines, launch/, deploy/):

- ``dynamo_tpu.runtime``  — distributed runtime: component model, discovery,
  request plane, response streaming, pipeline/engine abstractions.
- ``dynamo_tpu.llm``      — tokens/bock hashing, tokenizer, model cards,
  OpenAI protocols, preprocessor/detokenizer operators, HTTP service.
- ``dynamo_tpu.engine``   — the first-class JAX engine: paged KV cache,
  continuous batching scheduler, sampling (replaces vLLM/TRT-LLM/SGLang).
- ``dynamo_tpu.models``   — model families (Llama, Qwen, ...), pure JAX.
- ``dynamo_tpu.ops``      — attention and other hot ops; Pallas TPU kernels
  with jnp reference implementations for CPU testing.
- ``dynamo_tpu.parallel`` — device mesh, sharding rules, ring attention.
- ``dynamo_tpu.router``   — KV-cache-aware routing (radix indexer, scheduler).
- ``dynamo_tpu.kvbm``     — KV block manager: multi-tier pools and offload.
- ``dynamo_tpu.planner``  — dynamic worker scaling.
"""

__version__ = "0.1.0"

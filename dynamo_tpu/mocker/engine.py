"""Mocker: a device-free simulated engine.

The reference builds a full vLLM simulator (reference: lib/llm/src/mocker/
{scheduler,kv_manager,sequence,evictor}.rs — watermark scheduling, LRU
eviction, quadratic-prefill/linear-decode cost model) to test routing and
KV planes without GPUs. Our engine's scheduler and block allocator are
already framework-owned, so the mocker is simply the real TpuEngine with
the ModelRunner swapped for a cost-model simulator: everything above the
runner (continuous batching, prefix cache, preemption, KV events, metrics)
is the *production* code path, exercised at simulation speed.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine


@dataclass
class MockerConfig:
    """Cost model (reference: mocker/scheduler.rs:16-42)."""

    prefill_time_per_token_us: float = 2.0   # linear term
    prefill_quadratic_us: float = 0.0005     # * len^2 — attention cost
    decode_time_per_step_us: float = 500.0   # per batch step
    vocab_size: int = 32000
    seed: int = 0


class _SimRunner:
    """ModelRunner lookalike: sleeps per the cost model, emits pseudo-tokens.

    Tokens are deterministic in (seed, inputs) so tests can assert streams.
    """

    def __init__(self, cfg: EngineConfig, sim: MockerConfig) -> None:
        self.cfg = cfg
        self.sim = sim
        self.cache_head_dim = cfg.model.head_dim  # layout-handshake parity
        self._rng = np.random.default_rng(sim.seed)
        # Simulated per-block KV bytes so KVBM/disagg paths can verify
        # byte fidelity without a device.
        self._fake_kv: dict[int, np.ndarray] = {}

    def slot_of(self, block_ids: list[int], position: int) -> int:
        bs = self.cfg.block_size
        return block_ids[position // bs] * bs + position % bs

    def gather_block(self, block_idx: int) -> np.ndarray:
        return self._fake_kv.get(
            block_idx, np.full(8, block_idx, np.float32)
        )

    def gather_block_device(self, block_idx: int) -> np.ndarray:
        # No device in the mocker — the "device-resident snapshot" is the
        # same host array (keeps the device transfer path runnable).
        return self.gather_block(block_idx)

    def scatter_block(self, block_idx: int, data: np.ndarray) -> None:
        self._fake_kv[block_idx] = np.asarray(data)

    # Batched forms (ops/kv_copy.py parity): one "program" for N blocks.
    def gather_many(self, block_idxs) -> np.ndarray:
        return np.stack([self.gather_block(b) for b in block_idxs])

    def gather_many_device(self, block_idxs) -> np.ndarray:
        return self.gather_many(block_idxs)

    def scatter_many(self, block_idxs, datas) -> None:
        for b, d in zip(block_idxs, datas):
            self.scatter_block(b, d)

    def scatter_many_device(self, block_idxs, data) -> None:
        self.scatter_many(block_idxs, data)

    # The sim never inspects sampling extras; `last_logprobs` mirrors the
    # real runner's post-prefill attribute so the engine's capture path
    # runs (None = no logprob arrays, which the engine treats as absent).
    last_logprobs = None

    def prefill(
        self, new_tokens, block_ids, prefix_len, sampling, mm_embeds=None
    ) -> int:
        n = len(new_tokens)
        cost_us = (
            self.sim.prefill_time_per_token_us * n
            + self.sim.prefill_quadratic_us * n * n
        )
        time.sleep(cost_us / 1e6)
        return int(self._rng.integers(0, self.sim.vocab_size))

    def prefill_batch(self, lanes) -> list[int]:
        return [
            self.prefill(toks, blocks, prefix, samp)
            for toks, blocks, prefix, samp in lanes
        ]

    def decode(
        self, token_ids, positions, block_tables, context_lens, slot_mapping,
        temp, top_k, top_p, seed=None,
    ) -> np.ndarray:
        time.sleep(self.sim.decode_time_per_step_us / 1e6)
        return self._rng.integers(
            0, self.sim.vocab_size, len(token_ids)
        ).astype(np.int32)

    def decode_multi(
        self, token_ids, positions, block_tables, context_lens,
        temp, top_k, top_p, num_steps: int, seed=None,
    ) -> np.ndarray:
        time.sleep(self.sim.decode_time_per_step_us * num_steps / 1e6)
        return self._rng.integers(
            0, self.sim.vocab_size, (num_steps, len(token_ids))
        ).astype(np.int32)

    def decode_multi_full(
        self, token_ids, positions, block_tables, context_lens, counts_reset,
        temp, top_k, top_p, freq_pen, pres_pen, num_steps: int, seed=None,
    ):
        toks = self.decode_multi(
            token_ids, positions, block_tables, context_lens,
            temp, top_k, top_p, num_steps,
        )
        S, B = toks.shape
        K = 8  # MAX_LOGPROBS-shaped fake alternatives
        clp = np.full((S, B), -0.5, np.float32)
        tids = np.tile(toks[:, :, None], (1, 1, K)).astype(np.int32)
        tlps = np.full((S, B, K), -0.5, np.float32)
        return toks, clp, tids, tlps


class MockerEngine(TpuEngine):
    """TpuEngine with a simulated runner — the router/KVBM testbed."""

    def __init__(self, cfg: EngineConfig, sim: MockerConfig | None = None,
                 **kwargs) -> None:
        super().__init__(cfg, **kwargs)
        self._sim = sim or MockerConfig()

    def _build_runner(self) -> None:
        self.runner = _SimRunner(self.cfg, self._sim)

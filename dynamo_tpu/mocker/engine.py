"""Mocker: a device-free simulated engine.

The reference builds a full vLLM simulator (reference: lib/llm/src/mocker/
{scheduler,kv_manager,sequence,evictor}.rs — watermark scheduling, LRU
eviction, quadratic-prefill/linear-decode cost model) to test routing and
KV planes without GPUs. Our engine's scheduler and block allocator are
already framework-owned, so the mocker is simply the real TpuEngine with
the ModelRunner swapped for a cost-model simulator: everything above the
runner (continuous batching, prefix cache, preemption, KV events, metrics)
is the *production* code path, exercised at simulation speed.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from dynamo_tpu.engine.compile_cache import (
    CompileStats,
    WarmupPlanMixin,
    _bucket,
    token_budget,
)
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.engine.runner import _unified_warm_lanes


@dataclass
class MockerConfig:
    """Cost model (reference: mocker/scheduler.rs:16-42).

    Per-PHASE pricing (ROADMAP #3 / the coloc A/B): a dispatch costs
    f(decode_lanes, prefill_tokens), not a flat per-step constant —
    ``decode_time_per_step_us`` is the per-dispatch base (the weight
    pass every step streams regardless of content),
    ``decode_time_per_lane_us`` prices each decode lane's KV read, and
    prefill tokens pay the linear(+quadratic) compute term. Standalone
    phase-path prefill calls additionally pay
    ``prefill_dispatch_base_us`` — their OWN weight pass, which is
    exactly what co-located prefill quanta don't pay (they ride the
    mixed dispatch's): the measurable mechanism behind the Nexus /
    FlexNPU co-location win, and what makes quantum changes visibly
    move simulated ITL. Defaults keep the legacy flat pricing
    (both new knobs 0) so existing scenarios are unchanged.

    CALIBRATED constants pinned to the recorded r04/r05 chip runs live
    in ``planner/calibration.py`` (``calibrated_mocker_config()``) —
    the fleet simulator's xPyD projections (planner/simulate.py,
    ``BENCH_XPYD=1``) replay this cost model with those values, and
    tests/test_xpyd.py gates the reproduction of the r04 headline at
    <10 % so edits here can't silently drift the projections.
    """

    prefill_time_per_token_us: float = 2.0   # linear term
    prefill_quadratic_us: float = 0.0005     # * len^2 — attention cost
    decode_time_per_step_us: float = 500.0   # per dispatch (weight pass)
    decode_time_per_lane_us: float = 0.0     # per decode lane per step
    prefill_dispatch_base_us: float = 0.0    # per standalone prefill call
    # Decode HBM-bytes bandwidth term (the BENCH_QUANT A/B's pricing —
    # docs/architecture/kv_quant.md): each decode lane's step reads its
    # whole KV context from HBM, so a dispatch additionally costs
    #   Σ_lanes ctx_tokens · kv_bytes_per_token · kv_bytes_ratio
    #     / (decode_hbm_gbps · 1e9)   seconds.
    # 0.0 keeps the legacy context-free pricing (every existing
    # scenario unchanged). Calibrated values live in
    # planner/calibration.py: decode_hbm_gbps from BENCH_r04's measured
    # 282.8 GB/s effective, kv_bytes_per_token = the 32 KiB/token 1B
    # layout, kv_bytes_ratio ~0.502 for int8+scales (1.0 bf16).
    decode_hbm_gbps: float = 0.0
    kv_bytes_per_token: float = 32768.0
    kv_bytes_ratio: float = 1.0
    vocab_size: int = 32000
    seed: int = 0
    # Deterministic greedy stream: every sampled token is a pure affine
    # hash of (previous token, its position), so ANY worker resuming
    # from (last token, length) — e.g. a failover replay of
    # prompt + already-emitted tokens — continues the byte-identical
    # stream a single uninterrupted worker would have produced. This is
    # the device-free stand-in for greedy decoding's determinism, which
    # the mid-stream-failover proof gates on
    # (docs/architecture/failure_model.md "Mid-stream failover").
    # Default off: the seeded-RNG streams every existing test pins.
    deterministic_tokens: bool = False


class _SimRunner(WarmupPlanMixin):
    """ModelRunner lookalike: sleeps per the cost model, emits pseudo-tokens.

    Tokens are deterministic in (seed, inputs) so tests can assert streams.
    Mirrors the real runner's compile lifecycle (shape bucketing,
    CompileStats, warmup planning) so readiness gating and mid-traffic-
    compile accounting are testable device-free.
    """

    def __init__(self, cfg: EngineConfig, sim: MockerConfig) -> None:
        self.cfg = cfg
        self.sim = sim
        self.cache_head_dim = cfg.model.head_dim  # layout-handshake parity
        self._rng = np.random.default_rng(sim.seed)
        self.compile_cache = None
        self.compile_stats = CompileStats()
        self._lane_buckets = sorted(
            {2, _bucket(max(1, cfg.prefill_batch), minimum=2)}
        )
        # Simulated per-block KV bytes so KVBM/disagg paths can verify
        # byte fidelity without a device.
        self._fake_kv: dict[int, np.ndarray] = {}

    def _warm_op(self, spec):
        """Warm calls for the sim's program kinds (WarmupPlanMixin)."""
        cfg = self.cfg
        kind, t, lanes, steps, _k = spec
        sampling = (0.0, 0, 1.0)
        trash = [0] * cfg.max_blocks_per_seq
        if kind == "unified":
            warm_lanes = _unified_warm_lanes(
                t, self.unified_slots, cfg.max_model_len, trash, sampling
            )
            return (
                (lambda: self.unified_step(warm_lanes))
                if warm_lanes
                else None
            )
        if kind == "prefill":
            toks = [1] * min(t, cfg.max_model_len - 1, cfg.prefill_chunk)
            return (lambda: self.prefill(toks, trash, 0, sampling)) if toks else None
        if kind == "prefill_batch":
            toks = [1] * min(t, cfg.max_model_len - 1, cfg.prefill_chunk)
            lanes_list = [(toks, trash, 0, sampling)] * min(
                max(lanes, 1), cfg.prefill_batch
            )
            return (lambda: self.prefill_batch(lanes_list)) if toks else None
        if kind in ("decode_multi", "decode_multi_full"):
            B = cfg.max_num_seqs
            z = np.zeros(B, np.int32)
            return lambda: self.decode_multi(
                z, z, np.zeros((B, 1), np.int32), np.ones(B, np.int32),
                z, z, z, steps,
            )
        if kind == "decode_spec":
            B, L = cfg.max_num_seqs, cfg.max_model_len
            z = np.zeros(B, np.int32)
            return lambda: self.decode_multi_spec(
                z, z, np.zeros((B, L), np.int32),
                np.zeros((B, 1), np.int32), np.ones(B, np.int32),
                np.ones(B, np.int32), z, z, z, steps, cfg.speculative_k,
            )
        return None  # decode / mm variants don't exist in the sim

    def slot_of(self, block_ids: list[int], position: int) -> int:
        bs = self.cfg.block_size
        return block_ids[position // bs] * bs + position % bs

    def gather_block(self, block_idx: int) -> np.ndarray:
        return self._fake_kv.get(
            block_idx, np.full(8, block_idx, np.float32)
        )

    def gather_block_device(self, block_idx: int) -> np.ndarray:
        # No device in the mocker — the "device-resident snapshot" is the
        # same host array (keeps the device transfer path runnable).
        return self.gather_block(block_idx)

    def scatter_block(self, block_idx: int, data: np.ndarray) -> None:
        self._fake_kv[block_idx] = np.asarray(data)

    # Batched forms (ops/kv_copy.py parity): one "program" for N blocks.
    def gather_many(self, block_idxs) -> np.ndarray:
        return np.stack([self.gather_block(b) for b in block_idxs])

    def gather_many_device(self, block_idxs) -> np.ndarray:
        return self.gather_many(block_idxs)

    def scatter_many(self, block_idxs, datas) -> None:
        for b, d in zip(block_idxs, datas):
            self.scatter_block(b, d)

    def scatter_many_device(self, block_idxs, data) -> None:
        self.scatter_many(block_idxs, data)

    # The sim never inspects sampling extras; `last_logprobs` mirrors the
    # real runner's post-prefill attribute so the engine's capture path
    # runs (None = no logprob arrays, which the engine treats as absent).
    last_logprobs = None

    def _prefill_cost_us(self, n: int) -> float:
        """The one cost model both prefill entry points sleep by."""
        return (
            self.sim.prefill_time_per_token_us * n
            + self.sim.prefill_quadratic_us * n * n
        )

    # -- deterministic greedy stream (MockerConfig.deterministic_tokens) --
    def _det_next(self, prev_tok, next_pos):
        """Next token = affine hash of (previous token, its position) —
        the property that makes failover replay byte-identical: worker B
        prefilling prompt+emitted (length P+K) samples
        f(emitted[-1], P+K), exactly what worker A's decode at position
        P+K-1 would have produced. int64 math: no overflow at any
        vocab/position this sim sees."""
        prev = np.asarray(prev_tok, np.int64)
        pos = np.asarray(next_pos, np.int64)
        return (prev * 1103515245 + pos * 12345 + 7) % self.sim.vocab_size

    def _det_prefill_token(self, new_tokens, prefix_len: int) -> int:
        return int(
            self._det_next(new_tokens[-1], prefix_len + len(new_tokens))
        )

    def _kv_read_us(self, ctx_tokens: float) -> float:
        """HBM time to stream `ctx_tokens` of KV at the configured
        effective bandwidth and precision (0 when the term is off)."""
        if self.sim.decode_hbm_gbps <= 0:
            return 0.0
        bytes_ = (
            ctx_tokens * self.sim.kv_bytes_per_token * self.sim.kv_bytes_ratio
        )
        return bytes_ / (self.sim.decode_hbm_gbps * 1e9) * 1e6

    def prefill(
        self, new_tokens, block_ids, prefix_len, sampling, mm_embeds=None
    ) -> int:
        n = len(new_tokens)
        with self.compile_stats.observe(
            "prefill_mm" if mm_embeds else "prefill", t=_bucket(max(n, 1))
        ):
            time.sleep(
                (self.sim.prefill_dispatch_base_us + self._prefill_cost_us(n))
                / 1e6
            )
        if self.sim.deterministic_tokens and n:
            return self._det_prefill_token(new_tokens, prefix_len)
        return int(self._rng.integers(0, self.sim.vocab_size))

    def prefill_batch(self, lanes) -> list[int]:
        T = _bucket(max(max(len(t) for t, _, _, _ in lanes), 1))
        with self.compile_stats.observe(
            "prefill_batch", t=T, lanes=self.lane_bucket(len(lanes))
        ):
            # One dispatch base for the fused call (the lanes share its
            # weight pass), then each lane's token compute.
            time.sleep(self.sim.prefill_dispatch_base_us / 1e6)
            out = []
            for toks, _blocks, prefix, _samp in lanes:
                time.sleep(self._prefill_cost_us(len(toks)) / 1e6)
                out.append(
                    self._det_prefill_token(toks, prefix)
                    if self.sim.deterministic_tokens and toks
                    else int(self._rng.integers(0, self.sim.vocab_size))
                )
        return out

    @property
    def unified_slots(self) -> int:
        return self.cfg.max_num_seqs + self.cfg.prefill_batch

    @property
    def kv_bytes_ratio(self) -> float:
        """Advertised stored-KV precision ratio (kv_quant parity with
        the real runner) — what the network-aware selector prices
        transfers with on a mocker fleet."""
        if self.cfg.kv_quant != "int8":
            return 1.0
        from dynamo_tpu.block_manager.config import KvLayoutConfig

        lay = KvLayoutConfig.for_engine(self.cfg, self.cache_head_dim)
        return lay.block_bytes / lay.unquantized_block_bytes

    def unified_step(self, lanes, feed=None) -> np.ndarray:
        """Sim twin of ModelRunner.unified_step: one mixed dispatch
        priced per phase — the dispatch base (weight pass) + each decode
        lane's KV read + the prefill quanta's token compute — bucketed
        on the budget ladder for compile accounting. Decode lanes are
        the 1-token spans (a 1-token prefill TAIL quantum misclassifies
        by one token — negligible at sim fidelity). Co-located prefill
        pays NO separate dispatch base, so shrinking/growing the quantum
        visibly moves the simulated ITL the ColocController measures."""
        total = sum(len(t) for t, _, _, _ in lanes)
        decode_lanes = sum(1 for t, _, _, _ in lanes if len(t) == 1)
        prefill_tokens = total - decode_lanes
        # Decode lanes stream their whole context from HBM each step
        # (prefix + the new token) — the bytes the HBM term prices.
        decode_ctx = sum(
            prefix + len(t) for t, _, prefix, _ in lanes if len(t) == 1
        )
        T = token_budget(total, self.cfg.unified_token_budget)
        with self.compile_stats.observe("unified", t=T):
            time.sleep(
                (
                    self.sim.decode_time_per_step_us
                    + self.sim.decode_time_per_lane_us * decode_lanes
                    + self._kv_read_us(decode_ctx)
                    + self._prefill_cost_us(prefill_tokens)
                )
                / 1e6
            )
        if self.sim.deterministic_tokens:
            # Lane-row placement (the engine reads row i for roles[i]).
            # Best-effort: lanes whose token rides the device feed
            # (feed/use_prev) fall outside the host-visible chain — the
            # deterministic proof runs on the phased path, where every
            # lane's previous token is host-known.
            out = np.zeros(self.unified_slots, np.int32)
            for i, (toks, _blocks, prefix, _samp) in enumerate(lanes):
                if toks:
                    out[i] = self._det_next(toks[-1], prefix + len(toks))
            return out
        return self._rng.integers(
            0, self.sim.vocab_size, self.unified_slots
        ).astype(np.int32)

    def decode(
        self, token_ids, positions, block_tables, context_lens, slot_mapping,
        temp, top_k, top_p, seed=None,
    ) -> np.ndarray:
        time.sleep(self.sim.decode_time_per_step_us / 1e6)
        if self.sim.deterministic_tokens:
            return self._det_next(
                np.asarray(token_ids), np.asarray(positions) + 1
            ).astype(np.int32)
        return self._rng.integers(
            0, self.sim.vocab_size, len(token_ids)
        ).astype(np.int32)

    def decode_multi(
        self, token_ids, positions, block_tables, context_lens,
        temp, top_k, top_p, num_steps: int, seed=None,
    ) -> np.ndarray:
        # KV bytes grow one token per active lane per fused step:
        # sum(ctx) + active·s at step s.
        active = int(np.sum(np.asarray(context_lens) > 0))
        ctx_total = float(np.sum(np.maximum(np.asarray(context_lens), 0)))
        kv_us = sum(
            self._kv_read_us(ctx_total + active * s)
            for s in range(num_steps)
        )
        with self.compile_stats.observe("decode_multi", steps=num_steps):
            time.sleep(
                (
                    (
                        self.sim.decode_time_per_step_us
                        + self.sim.decode_time_per_lane_us * len(token_ids)
                    )
                    * num_steps
                    + kv_us
                )
                / 1e6
            )
        if self.sim.deterministic_tokens:
            # Chain the affine hash through the fused steps: lane b's
            # step-s token is f(step s-1's token, positions[b]+1+s).
            prev = np.asarray(token_ids, np.int64)
            pos = np.asarray(positions, np.int64)
            out = np.zeros((num_steps, len(prev)), np.int32)
            for s in range(num_steps):
                prev = self._det_next(prev, pos + 1 + s)
                out[s] = prev.astype(np.int32)
            return out
        return self._rng.integers(
            0, self.sim.vocab_size, (num_steps, len(token_ids))
        ).astype(np.int32)

    def decode_multi_spec(
        self, token_ids, positions, hist, block_tables, context_lens,
        write_limit, temp, top_k, top_p, num_steps: int, draft_k: int,
        seed=None,
    ):
        """Speculative decode in the sim: drafts NEVER accept (random
        tokens have no repeated bigrams to look up), so every lane
        delivers exactly 1 token/step — the losing regime the auto-gate
        must detect — while each step PAYS the verify width (scoring
        draft_k+1 positions costs ~(draft_k+1)x the single-position logits
        work on a real chip, modeled as sleep here so mocker-mode A/Bs see
        the overhead the gate exists to eliminate)."""
        B = len(token_ids)
        with self.compile_stats.observe(
            "decode_spec", steps=num_steps, draft_k=draft_k
        ):
            time.sleep(
                self.sim.decode_time_per_step_us
                * num_steps * (1 + draft_k) / 1e6
            )
        toks = self._rng.integers(
            0, self.sim.vocab_size, (num_steps, B, draft_k + 1)
        ).astype(np.int32)
        counts = np.ones((num_steps, B), np.int32)
        return toks, counts

    def decode_multi_full(
        self, token_ids, positions, block_tables, context_lens, counts_reset,
        temp, top_k, top_p, freq_pen, pres_pen, num_steps: int, seed=None,
    ):
        toks = self.decode_multi(
            token_ids, positions, block_tables, context_lens,
            temp, top_k, top_p, num_steps,
        )
        S, B = toks.shape
        K = 8  # MAX_LOGPROBS-shaped fake alternatives
        clp = np.full((S, B), -0.5, np.float32)
        tids = np.tile(toks[:, :, None], (1, 1, K)).astype(np.int32)
        tlps = np.full((S, B, K), -0.5, np.float32)
        return toks, clp, tids, tlps


class MockerEngine(TpuEngine):
    """TpuEngine with a simulated runner — the router/KVBM testbed."""

    def __init__(self, cfg: EngineConfig, sim: MockerConfig | None = None,
                 **kwargs) -> None:
        super().__init__(cfg, **kwargs)
        self._sim = sim or MockerConfig()

    def _build_runner(self) -> None:
        self.runner = _SimRunner(self.cfg, self._sim)

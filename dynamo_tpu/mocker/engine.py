"""Mocker: a device-free simulated engine.

The reference builds a full vLLM simulator (reference: lib/llm/src/mocker/
{scheduler,kv_manager,sequence,evictor}.rs — watermark scheduling, LRU
eviction, quadratic-prefill/linear-decode cost model) to test routing and
KV planes without GPUs. Our engine's scheduler and block allocator are
already framework-owned, so the mocker is simply the real TpuEngine with
the ModelRunner swapped for a cost-model simulator: everything above the
runner (continuous batching, prefix cache, preemption, KV events, metrics)
is the *production* code path, exercised at simulation speed.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

import numpy as np

from dynamo_tpu.engine.compile_cache import (
    CompileStats,
    WarmupPlanMixin,
    _bucket,
    token_budget,
)
from dynamo_tpu.engine.config import EngineConfig
from dynamo_tpu.engine.engine import TpuEngine
from dynamo_tpu.engine.runner import UnifiedOut, _unified_warm_lanes
from dynamo_tpu.planner.calibration import (
    KV_BYTES_PER_TOKEN,
    PREFILL_QUADRATIC_US,
)


@dataclass
class MockerConfig:
    """Cost model (reference: mocker/scheduler.rs:16-42).

    Per-PHASE pricing (ROADMAP #3 / the coloc A/B): a dispatch costs
    f(decode_lanes, prefill_tokens), not a flat per-step constant —
    ``decode_time_per_step_us`` is the per-dispatch base (the weight
    pass every step streams regardless of content),
    ``decode_time_per_lane_us`` prices each decode lane's KV read, and
    prefill tokens pay the linear(+quadratic) compute term. Standalone
    phase-path prefill calls additionally pay
    ``prefill_dispatch_base_us`` — their OWN weight pass, which is
    exactly what co-located prefill quanta don't pay (they ride the
    mixed dispatch's): the measurable mechanism behind the Nexus /
    FlexNPU co-location win, and what makes quantum changes visibly
    move simulated ITL. Defaults keep the legacy flat pricing
    (both new knobs 0) so existing scenarios are unchanged.

    CALIBRATED constants pinned to the recorded r04/r05 chip runs live
    in ``planner/calibration.py`` (``calibrated_mocker_config()``) —
    the fleet simulator's xPyD projections (planner/simulate.py,
    ``BENCH_XPYD=1``) replay this cost model with those values, and
    tests/test_xpyd.py gates the reproduction of the r04 headline at
    <10 % so edits here can't silently drift the projections.
    """

    prefill_time_per_token_us: float = 2.0   # linear term
    prefill_quadratic_us: float = PREFILL_QUADRATIC_US  # * len^2 — attention
    decode_time_per_step_us: float = 500.0   # per dispatch (weight pass)
    decode_time_per_lane_us: float = 0.0     # per decode lane per step
    prefill_dispatch_base_us: float = 0.0    # per standalone prefill call
    # Decode HBM-bytes bandwidth term (the BENCH_QUANT A/B's pricing —
    # docs/architecture/kv_quant.md): each decode lane's step reads its
    # whole KV context from HBM, so a dispatch additionally costs
    #   Σ_lanes ctx_tokens · kv_bytes_per_token · kv_bytes_ratio
    #     / (decode_hbm_gbps · 1e9)   seconds.
    # 0.0 keeps the legacy context-free pricing (every existing
    # scenario unchanged). Calibrated values live in
    # planner/calibration.py: decode_hbm_gbps from BENCH_r04's measured
    # 282.8 GB/s effective, kv_bytes_per_token = the 32 KiB/token 1B
    # layout, kv_bytes_ratio ~0.502 for int8+scales (1.0 bf16).
    decode_hbm_gbps: float = 0.0
    kv_bytes_per_token: float = float(KV_BYTES_PER_TOKEN)
    kv_bytes_ratio: float = 1.0
    # Weight-pass bytes term (the BENCH_WQUANT A/B's pricing —
    # docs/architecture/weight_quant.md): the dispatch base above IS the
    # per-step weight pass, so when ``weight_bytes_per_step`` > 0 AND
    # ``decode_hbm_gbps`` > 0 the base is REPLACED (not added to) by
    #   weight_bytes_per_step · weight_bytes_ratio
    #     / (decode_hbm_gbps · 1e9)   seconds,
    # for both the decode dispatch base and the standalone-prefill
    # dispatch base — co-located quanta and standalone prefill now price
    # the SAME precision-aware pass instead of a flat constant. With the
    # bytes term off, ``weight_bytes_ratio`` still scales the flat bases
    # so un-calibrated scenarios can A/B precision. Defaults (0.0 / 1.0)
    # keep every existing scenario byte-identical. Calibrated value:
    # planner/calibration.py WEIGHT_BYTES_PER_STEP (~3.02 GB, the r04
    # base at the measured 282.8 GB/s); int8-weights ratio ~0.501 from
    # calibration.weight_quant_bytes_ratio().
    weight_bytes_per_step: float = 0.0
    weight_bytes_ratio: float = 1.0
    vocab_size: int = 32000
    seed: int = 0
    # Deterministic greedy stream: every sampled token is a pure affine
    # hash of (previous token, its position), so ANY worker resuming
    # from (last token, length) — e.g. a failover replay of
    # prompt + already-emitted tokens — continues the byte-identical
    # stream a single uninterrupted worker would have produced. This is
    # the device-free stand-in for greedy decoding's determinism, which
    # the mid-stream-failover proof gates on
    # (docs/architecture/failure_model.md "Mid-stream failover").
    # Default off: the seeded-RNG streams every existing test pins.
    deterministic_tokens: bool = False
    # Position term of the deterministic hash. True (default) keeps the
    # PR 13 failover form f(prev, pos). False makes the chain a pure
    # function of the previous token — f(prev) — which (with a small
    # vocab) cycles, so prompt-lookup drafts EVENTUALLY match the chain:
    # the accepting-draft regime the BENCH_SPEC A/B measures. Either
    # way the emitted stream follows the closed form exactly, across
    # accepted AND rejected drafts (the failover byte-identity
    # invariant is acceptance-independent).
    det_positional: bool = True
    # G4 peer-link cost model (docs/architecture/kvbm_g4.md): the pacing
    # rate a mocker worker's PeerBlockServer serves fleet pulls at, in
    # GB/s. 0.0 = serve unpaced (legacy; no G4 scenario armed). The
    # BENCH_G4 A/B sets this to the calibrated HANDOFF_GBPS so the
    # pull-vs-recompute pricing sees a realistic transfer time, and the
    # slow-link leg sets it tiny so pricing must choose recompute.
    peer_link_gbps: float = 0.0


def det_next_token(prev_tok, next_pos, vocab: int, positional: bool = True):
    """The deterministic-token closed form (MockerConfig.deterministic_
    tokens): next token = affine hash of (previous token[, its
    position]). Module-level so the BENCH_SPEC leg and tests build
    on-chain prompts through the SAME law the sim verifies against —
    a constant edit here cannot silently break their acceptance setup."""
    prev = np.asarray(prev_tok, np.int64)
    if not positional:
        return (prev * 1103515245 + 7) % vocab
    pos = np.asarray(next_pos, np.int64)
    return (prev * 1103515245 + pos * 12345 + 7) % vocab


class _SimRunner(WarmupPlanMixin):
    """ModelRunner lookalike: sleeps per the cost model, emits pseudo-tokens.

    Tokens are deterministic in (seed, inputs) so tests can assert streams.
    Mirrors the real runner's compile lifecycle (shape bucketing,
    CompileStats, warmup planning) so readiness gating and mid-traffic-
    compile accounting are testable device-free.
    """

    def __init__(self, cfg: EngineConfig, sim: MockerConfig) -> None:
        self.cfg = cfg
        self.sim = sim
        self.cache_head_dim = cfg.model.head_dim  # layout-handshake parity
        self._rng = np.random.default_rng(sim.seed)
        self.compile_cache = None
        self.compile_stats = CompileStats()
        # Simulated per-block KV bytes so KVBM/disagg paths can verify
        # byte fidelity without a device.
        self._fake_kv: dict[int, np.ndarray] = {}

    def _warm_op(self, spec):
        """Warm calls for the sim's program kinds (WarmupPlanMixin) —
        the unified family only, like the real runner."""
        cfg = self.cfg
        kind, t, _lanes, _steps, _k = spec
        sampling = (0.0, 0, 1.0)
        trash = [0] * cfg.max_blocks_per_seq
        warm_lanes = _unified_warm_lanes(
            t, self.unified_slots, cfg.max_model_len, trash, sampling
        )
        if not warm_lanes:
            return None
        if kind == "unified":
            return lambda: self.unified_step(warm_lanes)
        if kind == "unified_full":
            if not cfg.sampling_extras:
                return None
            extras = {
                "slots": [0] * len(warm_lanes),
                "counts_add": [False] * len(warm_lanes),
                "reset": [False] * len(warm_lanes),
                "freq": [0.0] * len(warm_lanes),
                "pres": [0.0] * len(warm_lanes),
            }
            return lambda: self.unified_step(warm_lanes, extras=extras)
        if kind == "unified_mm":
            if not cfg.multimodal:
                return None
            mm = [None] * len(warm_lanes)
            mm[0] = [(0, np.zeros((1, 4), np.float32))]
            return lambda: self.unified_step(warm_lanes, mm=mm)
        return None

    def slot_of(self, block_ids: list[int], position: int) -> int:
        bs = self.cfg.block_size
        return block_ids[position // bs] * bs + position % bs

    def gather_block(self, block_idx: int) -> np.ndarray:
        return self._fake_kv.get(
            block_idx, np.full(8, block_idx, np.float32)
        )

    def gather_block_device(self, block_idx: int) -> np.ndarray:
        # No device in the mocker — the "device-resident snapshot" is the
        # same host array (keeps the device transfer path runnable).
        return self.gather_block(block_idx)

    def scatter_block(self, block_idx: int, data: np.ndarray) -> None:
        self._fake_kv[block_idx] = np.asarray(data)

    # Batched forms (ops/kv_copy.py parity): one "program" for N blocks.
    def gather_many(self, block_idxs) -> np.ndarray:
        return np.stack([self.gather_block(b) for b in block_idxs])

    def gather_many_device(self, block_idxs) -> np.ndarray:
        return self.gather_many(block_idxs)

    def scatter_many(self, block_idxs, datas) -> None:
        for b, d in zip(block_idxs, datas):
            self.scatter_block(b, d)

    def scatter_many_device(self, block_idxs, data) -> None:
        self.scatter_many(block_idxs, data)

    # The sim never inspects sampling extras; `last_logprobs` mirrors the
    # real runner's post-prefill attribute so the engine's capture path
    # runs (None = no logprob arrays, which the engine treats as absent).
    last_logprobs = None
    # unified_full/mm twin of the real runner's logprob-array attribute
    # (fake constant arrays set per extras dispatch).
    last_unified_logprobs = None

    def _prefill_cost_us(self, n: int) -> float:
        """The one cost model both prefill entry points sleep by."""
        return (
            self.sim.prefill_time_per_token_us * n
            + self.sim.prefill_quadratic_us * n * n
        )

    # -- deterministic greedy stream (MockerConfig.deterministic_tokens) --
    def _det_next(self, prev_tok, next_pos):
        """Next token = affine hash of (previous token, its position) —
        the property that makes failover replay byte-identical: worker B
        prefilling prompt+emitted (length P+K) samples
        f(emitted[-1], P+K), exactly what worker A's decode at position
        P+K-1 would have produced. int64 math: no overflow at any
        vocab/position this sim sees. With ``det_positional=False`` the
        position term drops — the chain is f(prev) alone (cyclic at
        small vocab: the accepting-draft spec regime)."""
        return det_next_token(
            prev_tok, next_pos, self.sim.vocab_size,
            positional=self.sim.det_positional,
        )

    def _det_prefill_token(self, new_tokens, prefix_len: int) -> int:
        return int(
            self._det_next(new_tokens[-1], prefix_len + len(new_tokens))
        )

    def _weight_pass_us(self, base_us: float) -> float:
        """The dispatch's weight-pass time at the configured precision:
        bytes-priced when the calibrated term is armed (replacing the
        flat base — the base IS the weight pass), else the flat base
        scaled by the precision ratio. Shared by the decode dispatch
        base and the standalone-prefill dispatch base, which is exactly
        the asymmetry fix: both passes stream the same weights, so both
        must reprice together when precision changes."""
        sim = self.sim
        if sim.weight_bytes_per_step > 0 and sim.decode_hbm_gbps > 0:
            return (
                sim.weight_bytes_per_step * sim.weight_bytes_ratio
                / (sim.decode_hbm_gbps * 1e9) * 1e6
            )
        return base_us * sim.weight_bytes_ratio

    def _kv_read_us(self, ctx_tokens: float) -> float:
        """HBM time to stream `ctx_tokens` of KV at the configured
        effective bandwidth and precision (0 when the term is off)."""
        if self.sim.decode_hbm_gbps <= 0:
            return 0.0
        bytes_ = (
            ctx_tokens * self.sim.kv_bytes_per_token * self.sim.kv_bytes_ratio
        )
        return bytes_ / (self.sim.decode_hbm_gbps * 1e9) * 1e6

    def prefill(
        self, new_tokens, block_ids, prefix_len, sampling, mm_embeds=None
    ) -> int:
        n = len(new_tokens)
        with self.compile_stats.observe(
            "prefill_mm" if mm_embeds else "prefill", t=_bucket(max(n, 1))
        ):
            time.sleep(
                (
                    self._weight_pass_us(self.sim.prefill_dispatch_base_us)
                    + self._prefill_cost_us(n)
                )
                / 1e6
            )
        if self.sim.deterministic_tokens and n:
            return self._det_prefill_token(new_tokens, prefix_len)
        return int(self._rng.integers(0, self.sim.vocab_size))

    def prefill_batch(self, lanes) -> list[int]:
        T = _bucket(max(max(len(t) for t, _, _, _ in lanes), 1))
        with self.compile_stats.observe(
            "prefill_batch", t=T, lanes=_bucket(max(len(lanes), 1), minimum=2)
        ):
            # One dispatch base for the fused call (the lanes share its
            # weight pass), then each lane's token compute.
            time.sleep(
                self._weight_pass_us(self.sim.prefill_dispatch_base_us) / 1e6
            )
            out = []
            for toks, _blocks, prefix, _samp in lanes:
                time.sleep(self._prefill_cost_us(len(toks)) / 1e6)
                out.append(
                    self._det_prefill_token(toks, prefix)
                    if self.sim.deterministic_tokens and toks
                    else int(self._rng.integers(0, self.sim.vocab_size))
                )
        return out

    @property
    def unified_slots(self) -> int:
        return self.cfg.max_num_seqs + self.cfg.prefill_batch

    @property
    def kv_bytes_ratio(self) -> float:
        """Advertised stored-KV precision ratio (kv_quant parity with
        the real runner) — what the network-aware selector prices
        transfers with on a mocker fleet."""
        if self.cfg.kv_quant != "int8":
            return 1.0
        from dynamo_tpu.block_manager.config import KvLayoutConfig

        lay = KvLayoutConfig.for_engine(self.cfg, self.cache_head_dim)
        return lay.block_bytes / lay.unquantized_block_bytes

    # Weight-quant gauge parity with the real runner (engine
    # _flush_side_channels reads these via getattr): the sim has no
    # resident weights, so "bytes saved" is the simulated per-step
    # streaming saving the cost model actually prices.
    @property
    def weight_quant_bytes_saved(self) -> float:
        return (
            (1.0 - self.sim.weight_bytes_ratio)
            * self.sim.weight_bytes_per_step
        )

    @property
    def weight_quant_density(self) -> float:
        return 1.0 if getattr(self.cfg, "weight_quant", None) else 0.0

    def unified_step(
        self, lanes, feed=None, draft_lens=None, extras=None, mm=None
    ) -> UnifiedOut:
        """Sim twin of ModelRunner.unified_step: one mixed dispatch
        priced per phase — the dispatch base (weight pass) + each decode
        lane's KV read + the prefill quanta's token compute — bucketed
        on the budget ladder for compile accounting. Decode lanes are
        the 1-token spans (a 1-token prefill TAIL quantum misclassifies
        by one token — negligible at sim fidelity). Co-located prefill
        pays NO separate dispatch base, so shrinking/growing the quantum
        visibly moves the simulated ITL the ColocController measures.

        Spec verify spans (``draft_lens``): a lane of 1 + dl tokens
        stays a DECODE lane (its per-lane KV-read term covers the whole
        context) and its dl draft rows price as prefill tokens riding
        the dispatch — the verify-width term, consistent with the
        deleted phased ``decode_multi_spec`` law in that cost scales
        linearly with verify width; the shared weight pass is paid once
        (which is the point of the port). Acceptance is deterministic
        against the closed-form chain, so the emitted stream follows the
        PR 13 failover byte-identity form across accepted AND rejected
        drafts; RNG mode accepts nothing (the losing regime the
        auto-gate must detect)."""
        dls = list(draft_lens) if draft_lens else [0] * len(lanes)
        dls += [0] * (len(lanes) - len(dls))
        total = sum(len(t) for t, _, _, _ in lanes)
        drafted = sum(dls)
        decode_lanes = sum(
            1 for (t, _, _, _), dl in zip(lanes, dls) if len(t) - dl == 1
        )
        prefill_tokens = total - decode_lanes - drafted
        # Decode lanes stream their whole context from HBM each step
        # (prefix + the new token) — the bytes the HBM term prices.
        decode_ctx = sum(
            prefix + len(t)
            for (t, _, prefix, _), dl in zip(lanes, dls)
            if len(t) - dl == 1
        )
        use_mm = mm is not None and any(seg for seg in mm)
        use_full = use_mm or extras is not None
        if use_full:
            kind = "unified_mm" if use_mm else "unified_full"
            T = token_budget(
                self.cfg.unified_token_budget, self.cfg.unified_token_budget
            )
        else:
            kind = "unified"
            T = token_budget(total, self.cfg.unified_token_budget)
        with self.compile_stats.observe(kind, t=T):
            time.sleep(
                (
                    self._weight_pass_us(self.sim.decode_time_per_step_us)
                    + self.sim.decode_time_per_lane_us * decode_lanes
                    + self._kv_read_us(decode_ctx)
                    + self._prefill_cost_us(prefill_tokens + drafted)
                )
                / 1e6
            )
        S = self.unified_slots
        K = max(1, self.cfg.speculative_k)
        last = np.zeros(S, np.int32)
        toks2d = np.zeros((S, K + 1), np.int32)
        counts = np.zeros(S, np.int32)
        if feed is not None:
            # Sim "device" arrays are host numpy — the feed substitution
            # reads the previous return directly.
            prev_toks, prev_row, use_prev = feed
            prev_toks = np.asarray(prev_toks)
        for i, (toks, _blocks, prefix, _samp) in enumerate(lanes):
            dl = dls[i]
            if not toks:
                continue
            fed_last = toks[-1 - dl] if dl else toks[-1]
            if feed is not None and bool(use_prev[i]):
                # The device feed substitutes the span's FIRST row; for
                # the 1-token spans that use it, that IS the fed token —
                # so the deterministic chain stays host-visible through
                # pipelined dispatches (unlike the phased-era caveat).
                fed_last = int(prev_toks[int(prev_row[i])])
            if not self.sim.deterministic_tokens:
                last[i] = int(self._rng.integers(0, self.sim.vocab_size))
                toks2d[i, 0] = last[i]
                counts[i] = 1
                continue
            # Closed-form chain: verify drafts against it, deliver the
            # accepted prefix + the bonus — the emitted tokens ARE the
            # chain whatever the drafts were.
            base_pos = prefix + len(toks) - dl  # index of the next token
            acc = 0
            prev = fed_last
            if dl:
                drafts = list(toks[-dl:])
                for j in range(dl):
                    want = int(self._det_next(prev, base_pos + j))
                    if drafts[j] != want:
                        break
                    acc += 1
                    prev = want
            delivered = []
            prev = fed_last
            for j in range(acc + 1):
                prev = int(self._det_next(prev, base_pos + j))
                delivered.append(prev)
            counts[i] = len(delivered)
            toks2d[i, : len(delivered)] = delivered
            last[i] = delivered[-1]
        if use_full:
            KL = 8  # MAX_LOGPROBS-shaped fake alternatives
            clp = np.full(S, -0.5, np.float32)
            tids = np.tile(last[:, None], (1, KL)).astype(np.int32)
            tlps = np.full((S, KL), -0.5, np.float32)
            self.last_unified_logprobs = (clp, tids, tlps)
            return UnifiedOut(last=last, toks=None, counts=None)
        if self.cfg.speculative_k > 0:
            return UnifiedOut(last=last, toks=toks2d, counts=counts)
        return UnifiedOut(last=last, toks=None, counts=None)

    def decode(
        self, token_ids, positions, block_tables, context_lens, slot_mapping,
        temp, top_k, top_p, seed=None,
    ) -> np.ndarray:
        time.sleep(
            self._weight_pass_us(self.sim.decode_time_per_step_us) / 1e6
        )
        if self.sim.deterministic_tokens:
            return self._det_next(
                np.asarray(token_ids), np.asarray(positions) + 1
            ).astype(np.int32)
        return self._rng.integers(
            0, self.sim.vocab_size, len(token_ids)
        ).astype(np.int32)

    def decode_multi(
        self, token_ids, positions, block_tables, context_lens,
        temp, top_k, top_p, num_steps: int, seed=None,
    ) -> np.ndarray:
        # KV bytes grow one token per active lane per fused step:
        # sum(ctx) + active·s at step s.
        active = int(np.sum(np.asarray(context_lens) > 0))
        ctx_total = float(np.sum(np.maximum(np.asarray(context_lens), 0)))
        kv_us = sum(
            self._kv_read_us(ctx_total + active * s)
            for s in range(num_steps)
        )
        with self.compile_stats.observe("decode_multi", steps=num_steps):
            time.sleep(
                (
                    (
                        self._weight_pass_us(self.sim.decode_time_per_step_us)
                        + self.sim.decode_time_per_lane_us * len(token_ids)
                    )
                    * num_steps
                    + kv_us
                )
                / 1e6
            )
        if self.sim.deterministic_tokens:
            # Chain the affine hash through the fused steps: lane b's
            # step-s token is f(step s-1's token, positions[b]+1+s).
            prev = np.asarray(token_ids, np.int64)
            pos = np.asarray(positions, np.int64)
            out = np.zeros((num_steps, len(prev)), np.int32)
            for s in range(num_steps):
                prev = self._det_next(prev, pos + 1 + s)
                out[s] = prev.astype(np.int32)
            return out
        return self._rng.integers(
            0, self.sim.vocab_size, (num_steps, len(token_ids))
        ).astype(np.int32)



class MockerEngine(TpuEngine):
    """TpuEngine with a simulated runner — the router/KVBM testbed."""

    def __init__(self, cfg: EngineConfig, sim: MockerConfig | None = None,
                 **kwargs) -> None:
        super().__init__(cfg, **kwargs)
        self._sim = sim or MockerConfig()

    def _build_runner(self) -> None:
        self.runner = _SimRunner(self.cfg, self._sim)

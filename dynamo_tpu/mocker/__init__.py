from dynamo_tpu.mocker.engine import MockerConfig, MockerEngine

__all__ = ["MockerConfig", "MockerEngine"]

from dynamo_tpu.mocker.engine import (
    MockerConfig,
    MockerEngine,
    det_next_token,
)

__all__ = ["MockerConfig", "MockerEngine", "det_next_token"]

"""Sparse Mixture-of-Experts MLP block with expert-parallel sharding.

The stage-5 prerequisite (BASELINE.md: DeepSeek-R1 671B on multi-host) the
reference never had to build — it delegated intra-model parallelism to
backend engines (SURVEY §2 "Parallelism strategies"). Here the MoE layer is
first-class JAX: a top-k softmax router and a dense einsum formulation of
the expert MLPs, with the expert dimension sharded over the mesh's ``ep``
axis and the per-expert intermediate dim over ``tp`` (specs in
``moe_param_specs``). GSPMD turns the expert-dim contractions into
psums over ep — no hand-written all-to-all at this stage; a capacity-based
dispatch kernel is the later optimization.

The dense formulation computes every expert on every token and masks by
the router's top-k gates. That is O(E/topk) extra FLOPs — acceptable for
correctness scaffolding and small expert counts; the Pallas blocked
dispatch replaces it when perf work reaches MoE.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P


@dataclass(frozen=True)
class MoeConfig:
    hidden_size: int = 64
    intermediate_size: int = 128   # per expert
    num_experts: int = 8
    num_experts_per_tok: int = 2
    # Router scoring (DeepSeek-V3/R1 uses "sigmoid" with a per-expert
    # selection-bias correction; Mixtral/V2 use "softmax").
    gating: str = "softmax"
    norm_topk_prob: bool = True
    routed_scaling_factor: float = 1.0
    # Group-limited selection ("noaux_tc"): experts split into n_group
    # groups; each group scores as the sum of its top-2 biased scores and
    # only the topk_group best groups stay eligible.
    n_group: int = 1
    topk_group: int = 1
    # Expert execution: "dense" (all experts, gate-masked), "capacity"
    # (per-expert token buffers, only selected FLOPs — see moe_mlp), or
    # "auto" (capacity when num_experts >= AUTO_CAPACITY_MIN_EXPERTS).
    dispatch: str = "auto"
    capacity_factor: float = 2.0

    @property
    def resolved_dispatch(self) -> str:
        """Expert-count half of the "auto" rule; moe_mlp additionally
        requires enough tokens per call (see auto_capacity_ok) — at
        decode-size T the capacity C collapses toward 1 and collisions
        DROP routed contributions, so "auto" falls back to dense there
        (dense at tiny T is cheap anyway)."""
        if self.dispatch == "auto":
            return (
                "capacity"
                if self.num_experts >= AUTO_CAPACITY_MIN_EXPERTS
                else "dense"
            )
        return self.dispatch

    def auto_capacity_ok(self, num_tokens: int) -> bool:
        """Token-count guard for "auto": expect >= 2 tokens per expert
        so C = ceil(T*k/E * factor) stays comfortably above collision
        range. Explicit dispatch="capacity" bypasses this (caller's
        choice)."""
        return (
            num_tokens * self.num_experts_per_tok >= 2 * self.num_experts
        )


# Dense runs E/topk times the selected FLOPs; capacity pays scatter/gather
# overhead. E=16 is the measured crossover region (BENCHMARKS.md).
AUTO_CAPACITY_MIN_EXPERTS = 16


def init_moe_params(key: jax.Array, cfg: MoeConfig, dtype=jnp.float32) -> dict:
    D, I, E = cfg.hidden_size, cfg.intermediate_size, cfg.num_experts

    def dense(key, shape, fan_in):
        return (
            jax.random.normal(key, shape, jnp.float32) / (fan_in**0.5)
        ).astype(dtype)

    k1, k2, k3, k4 = jax.random.split(key, 4)
    return {
        "w_router": dense(k1, (D, E), D),
        "w_gate": dense(k2, (E, D, I), D),
        "w_up": dense(k3, (E, D, I), D),
        "w_down": dense(k4, (E, I, D), I),
    }


def moe_param_specs() -> dict:
    """Experts over ep, per-expert intermediate over tp; the router is
    replicated (it is tiny and every token needs it)."""
    return {
        "w_router": P(),
        "w_gate": P("ep", None, "tp"),
        "w_up": P("ep", None, "tp"),
        "w_down": P("ep", "tp", None),
    }


def moe_router(params: dict, x: jnp.ndarray, cfg: MoeConfig) -> jnp.ndarray:
    """Top-k routing → dense gates [T, E] with mass only on each token's
    selected experts.

    softmax (Mixtral/DeepSeek-V2): probs = softmax over all experts, top-k
    by prob, optionally renormalized over the selection.
    sigmoid (DeepSeek-V3/R1): probs = sigmoid(logits); SELECTION ranks
    probs + per-expert bias (the load-balancing correction term,
    `router_bias`), but the WEIGHTS are the raw probs of the selected
    experts, renormalized, then scaled by routed_scaling_factor.
    """
    T = x.shape[0]
    logits = (x.astype(jnp.float32) @ params["w_router"].astype(jnp.float32))
    if cfg.gating == "sigmoid":
        probs = jax.nn.sigmoid(logits)
        sel = probs + params.get("router_bias", jnp.zeros(()))
    else:
        probs = jax.nn.softmax(logits, axis=-1)
        sel = probs
    if cfg.n_group > 1:
        # Group-limited eligibility: keep only the topk_group best groups
        # in selection (weights still come from the raw probs). Group
        # score follows the checkpoint family: V3/R1 sigmoid ("noaux_tc")
        # sums each group's top-2 biased scores; V2 softmax
        # ("group_limited_greedy") takes the group max.
        E = cfg.num_experts
        per = E // cfg.n_group
        grouped = sel.reshape(T, cfg.n_group, per)
        if cfg.gating == "sigmoid":
            top2, _ = jax.lax.top_k(grouped, min(2, per))        # [T, G, 2]
            group_scores = top2.sum(axis=-1)                     # [T, G]
        else:
            group_scores = grouped.max(axis=-1)                  # [T, G]
        _, keep = jax.lax.top_k(group_scores, cfg.topk_group)    # [T, kg]
        group_mask = jnp.zeros_like(group_scores).at[
            jnp.arange(T)[:, None], keep
        ].set(1.0)
        sel = jnp.where(
            jnp.repeat(group_mask, per, axis=-1) > 0, sel, -jnp.inf
        )
    _, topi = jax.lax.top_k(sel, cfg.num_experts_per_tok)        # [T, k]
    gates_k = jnp.take_along_axis(probs, topi, axis=-1)          # [T, k]
    if cfg.norm_topk_prob:
        gates_k = gates_k / jnp.maximum(
            gates_k.sum(axis=-1, keepdims=True), 1e-20
        )
    gates_k = gates_k * cfg.routed_scaling_factor
    return jnp.zeros_like(logits).at[
        jnp.arange(T)[:, None], topi
    ].set(gates_k)


def _expert_einsum(pattern: str, x: jnp.ndarray, w) -> jnp.ndarray:
    """Einsum against a stacked expert weight that may be int8-quantized
    (ops/quant.py dict {"q", "s"} with per-(expert, out-channel) scales —
    the scale multiplies the [T, E, out] result, broadcast over tokens)."""
    from dynamo_tpu.ops.quant import is_quantized

    if not is_quantized(w):
        return jnp.einsum(pattern, x, w.astype(jnp.float32))
    out = jnp.einsum(pattern, x, w["q"].astype(jnp.float32))
    return out * w["s"][None]  # s [E, out] → [1, E, out]


def moe_mlp(
    params: dict, x: jnp.ndarray, cfg: MoeConfig, mesh=None
) -> jnp.ndarray:
    """x [T, D] → [T, D] through top-k routed experts.

    dispatch="dense" computes every expert for every token and masks by
    the gates — exact, simple, O(E/topk) extra FLOPs; right for small
    expert counts and tiny tests. dispatch="capacity" gathers each
    expert's assigned tokens into fixed [E, C, D] buffers and runs only
    the selected experts' FLOPs (≈ topk/E of dense — at DeepSeek-R1
    scale, 256 experts top-8, that is 32× less MLP compute); tokens
    beyond an expert's capacity C = ceil(T·topk/E · factor) drop to zero
    contribution for that expert, the standard capacity-overflow rule.
    "auto" (default) picks by expert count. ``mesh`` (when ep > 1) pins
    the dispatch collectives explicitly — see _moe_mlp_capacity.
    """
    use_capacity = cfg.resolved_dispatch == "capacity" and (
        cfg.dispatch != "auto" or cfg.auto_capacity_ok(x.shape[0])
    )
    if use_capacity:
        return _moe_mlp_capacity(params, x, cfg, mesh)
    gates = moe_router(params, x, cfg)
    xf = x.astype(jnp.float32)
    up = _expert_einsum("td,edi->tei", xf, params["w_up"])
    gate = _expert_einsum("td,edi->tei", xf, params["w_gate"])
    h = jax.nn.silu(gate) * up                                    # [T, E, I]
    out = _expert_einsum("tei,eid->ted", h, params["w_down"])
    return jnp.einsum("ted,te->td", out, gates).astype(x.dtype)


def _moe_mlp_capacity(
    params: dict, x: jnp.ndarray, cfg: MoeConfig, mesh=None
) -> jnp.ndarray:
    """Capacity-dispatch formulation: scatter tokens to per-expert
    buffers, run per-expert SwiGLU as one [E, C, :] batched einsum (the
    expert dim stays sharded over ep), gather weighted results back.
    Static shapes throughout — C derives from T at trace time — so XLA
    compiles one program per prefill bucket exactly like the dense path.

    With a mesh carrying ep > 1, the token buffers are PINNED ep-sharded
    (with_sharding_constraint), so the communication pattern is explicit
    and stable: the scatter lands as a dispatch to each expert shard
    (serving activations are replicated across ep, so this is a local
    slice, not an all-to-all), each shard computes ONLY its local
    experts' [E/ep, C, :] einsums, and the token-side gather of expert
    outputs is the combine step. GSPMD left unpinned was free to
    replicate the buffers and waste the ep axis entirely."""
    T, D = x.shape
    E, k = cfg.num_experts, cfg.num_experts_per_tok
    gates = moe_router(params, x, cfg)                      # [T, E]
    gate_vals, expert_idx = jax.lax.top_k(gates, k)         # [T, k]
    C = max(1, int(math.ceil(T * k / E * cfg.capacity_factor)))

    flat_e = expert_idx.reshape(-1)                         # [T*k]
    onehot = jax.nn.one_hot(flat_e, E, dtype=jnp.int32)
    # rank of each entry within its expert (arrival order)
    pos = ((jnp.cumsum(onehot, axis=0) - onehot) * onehot).sum(-1)
    keep = pos < C
    idx_c = jnp.where(keep, pos, C)                         # C = drop slot

    ep_sharded = (
        mesh is not None and dict(mesh.shape).get("ep", 1) > 1
    )

    def pin(arr, spec):
        if not ep_sharded:
            return arr
        from jax.sharding import NamedSharding

        return jax.lax.with_sharding_constraint(
            arr, NamedSharding(mesh, spec)
        )

    xf = x.astype(jnp.float32)
    xx = jnp.repeat(xf, k, axis=0)                          # [T*k, D]
    buf = jnp.zeros((E, C, D), jnp.float32).at[flat_e, idx_c].set(
        xx, mode="drop"
    )
    buf = pin(buf, P("ep", None, None))
    gate = _expert_einsum3("ecd,edi->eci", buf, params["w_gate"])
    up = _expert_einsum3("ecd,edi->eci", buf, params["w_up"])
    h = jax.nn.silu(gate) * up                              # [E, C, I]
    h = pin(h, P("ep", None, "tp"))
    out_e = _expert_einsum3("eci,eid->ecd", h, params["w_down"])
    out_e = pin(out_e, P("ep", None, None))

    y = out_e[flat_e, jnp.minimum(pos, C - 1)]              # [T*k, D]
    y = jnp.where(keep[:, None], y, 0.0)
    out = (y.reshape(T, k, D) * gate_vals[:, :, None]).sum(axis=1)
    return pin(out.astype(x.dtype), P(None, None))


def _expert_einsum3(pattern: str, x: jnp.ndarray, w) -> jnp.ndarray:
    """Batched-over-experts einsum against a possibly-quantized stacked
    weight; the [E, out] scale broadcasts onto the [E, C, out] result."""
    from dynamo_tpu.ops.quant import is_quantized

    if not is_quantized(w):
        return jnp.einsum(pattern, x, w.astype(jnp.float32))
    out = jnp.einsum(pattern, x, w["q"].astype(jnp.float32))
    return out * w["s"][:, None, :]


def shard_moe_params(params: dict, mesh) -> dict:
    from jax.sharding import NamedSharding

    specs = moe_param_specs()
    return {
        k: jax.device_put(v, NamedSharding(mesh, specs[k]))
        for k, v in params.items()
    }

"""Llama-family transformer in pure JAX over a paged KV cache.

The in-process engine's model: RMSNorm + RoPE + GQA + SwiGLU, written as
plain functions over a params pytree so `jit`/`pjit` can shard it with
NamedSharding annotations (parallel/sharding.py). Weight layout is
``[in, out]`` (already transposed from torch) so the hot matmuls are plain
``x @ w`` on the MXU.

Replaces the reference's delegated engines (vLLM/mistralrs/llamacpp — e.g.
reference: lib/engines/mistralrs/src/lib.rs:48) with a TPU-native model;
covers Llama-2/3/3.x, Qwen2 (qkv_bias), and Mixtral-style sparse MoE
(num_experts > 0 — routed expert MLPs from models/moe.py, ep/tp-sharded).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from dynamo_tpu.models.config import ModelConfig
from dynamo_tpu.ops.attention import (
    AttnDispatch,
    decode_attention,
    full_causal_attention,
    prefill_attention,
)
from dynamo_tpu.ops.norms import rms_norm
from dynamo_tpu.ops.quant import (
    CONTRACT_AXIS,
    QUANT_AXES,
    WEIGHT_FORMATS,
    embed_lookup,
    policy_layer_fmts,
    qdot,
    qeinsum,
    quantize_weight,
    tied_head_mm,
)
from dynamo_tpu.ops.rope import apply_rope

Params = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class WeightQuantPolicy:
    """Per-matmul weight-quantization policy (docs/architecture/
    weight_quant.md): each SITE — the embedding gather, the attention
    projections (qkv+o and the MLA ladder), the SwiGLU/expert matrices,
    and the unembed head — independently selects None (full precision)
    or a storage format from ops/quant.py WEIGHT_FORMATS.

    The policy is value-level, not code-level: quantized sites store
    ``{"q", "s"}`` dicts in the params tree and every matmul already
    dispatches on the VALUE through ops/quant.py ``qdot``/``qeinsum``/
    ``embed_lookup``/``tied_head_mm`` — so the forward functions compile
    the same call graph either way and the compiled program set (the
    unified budget ladder) is unchanged by any policy choice.
    """

    embedding: str | None = None
    attn: str | None = None
    mlp: str | None = None
    unembed: str | None = None

    SITES = ("embedding", "attn", "mlp", "unembed")

    @classmethod
    def from_string(cls, spec: str | None) -> "WeightQuantPolicy":
        """Parse an EngineConfig.weight_quant / ``--weight-quant`` spec:
        a bare format ("int8", "fp8") selects every site; a comma list
        of ``site=fmt`` pairs ("attn=int8,mlp=int8") selects per site.
        None/"" parses to the all-off policy."""
        if not spec:
            return cls()
        spec = spec.strip()
        if "=" not in spec:
            if spec not in WEIGHT_FORMATS:
                raise ValueError(
                    f"weight_quant format {spec!r} not in {WEIGHT_FORMATS}"
                )
            return cls(embedding=spec, attn=spec, mlp=spec, unembed=spec)
        kw: dict[str, str] = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            site, _, fmt = part.partition("=")
            site, fmt = site.strip(), fmt.strip()
            if site not in cls.SITES:
                raise ValueError(
                    f"weight_quant site {site!r} not in {cls.SITES}"
                )
            if fmt not in WEIGHT_FORMATS:
                raise ValueError(
                    f"weight_quant format {fmt!r} not in {WEIGHT_FORMATS}"
                )
            kw[site] = fmt
        return cls(**kw)

    @property
    def active(self) -> bool:
        return any(getattr(self, s) for s in self.SITES)

    def describe(self) -> str:
        """Canonical spec string (compile-cache fingerprint / gauges)."""
        if not self.active:
            return "off"
        return ",".join(
            f"{s}={getattr(self, s)}" for s in self.SITES if getattr(self, s)
        )


def _attn_fns(attn: AttnDispatch | None):
    """Resolve the attention implementation: a per-runner AttnDispatch
    (engine/runner.py threads one in — per-runner Pallas/mesh choice) or
    the env-driven module defaults."""
    if attn is None:
        return prefill_attention, decode_attention
    return attn.prefill, attn.decode


def _dense_init(key, shape, dtype):
    return (
        jax.random.normal(key, shape, jnp.float32) / (shape[0] ** 0.5)
    ).astype(dtype)


def _ln(x: jnp.ndarray, w: jnp.ndarray, cfg: ModelConfig) -> jnp.ndarray:
    """RMSNorm with the family's scale convention: Gemma checkpoints store
    w and scale by (1 + w) (HF Gemma3RMSNorm), everyone else scales by w."""
    if cfg.norm_offset:
        w = 1.0 + w.astype(jnp.float32)
    return rms_norm(x, w, cfg.rms_eps)


def _layer_rope(cfg: ModelConfig, li: int) -> tuple:
    """(theta, scaling) for layer li: Gemma-3 runs its windowed (local)
    layers on rope_local_theta with NO position scaling; global layers
    keep rope_theta + rope_scaling (HF Gemma3 rope_local_base_freq)."""
    if cfg.rope_local_theta and cfg.layer_window(li):
        return cfg.rope_local_theta, None
    return cfg.rope_theta, cfg.rope_scaling


def _embed(params: Params, cfg: ModelConfig, token_ids: jnp.ndarray) -> jnp.ndarray:
    x = embed_lookup(params["embed"], token_ids)
    if cfg.embed_scale:
        # Normalizer cast to the activation dtype BEFORE the multiply —
        # bf16 rounding of sqrt(hidden) is part of HF Gemma numerics.
        x = x * jnp.asarray(cfg.hidden_size ** 0.5, x.dtype)
    return x


def _residual_attn(x, layer, attn_out, cfg: ModelConfig):
    """Attention residual add; Gemma's sandwich post-attention norm sits
    on the branch, not the trunk."""
    if cfg.post_norms:
        attn_out = _ln(attn_out, layer["ln_post_attn"], cfg)
    return x + attn_out


def _residual_mlp(x, layer, cfg: ModelConfig, mesh=None):
    """Pre-norm → gated MLP → (optional post-norm) → residual add."""
    h = _ln(x, layer["ln_mlp"], cfg)
    m = _mlp(layer, h, cfg, mesh)
    if cfg.post_norms:
        m = _ln(m, layer["ln_post_mlp"], cfg)
    return x + m


def init_layer_params(
    key: jax.Array, cfg: ModelConfig, li: int, dtype=jnp.bfloat16
) -> Params:
    """Random-init ONE layer's params (layer-wise so big models can init →
    quantize → free incrementally; ops/quant.py init_params_int8)."""
    D, H, kvH, hd = cfg.hidden_size, cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    I = cfg.intermediate_size

    def dense(key, shape):
        return _dense_init(key, shape, dtype)

    # Gemma stores w with effective scale (1 + w): identity init is zeros.
    def norm_init(shape):
        return (jnp.zeros if cfg.norm_offset else jnp.ones)(shape, dtype)

    keys = iter(jax.random.split(key, 16))
    if cfg.is_mla:
        # DeepSeek-V2/V3 MLA: latent KV compression (kv_lora_rank)
        # plus a decoupled roped path (qk_rope_head_dim); see
        # _qkv_mla for the absorbed-projection attention math.
        dn, dr, dc = (
            cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.kv_lora_rank
        )
        layer = {
            "w_dkv": dense(next(keys), (D, dc + dr)),
            "ln_kv": jnp.ones((dc,), dtype),
            "w_uk": _dense3(next(keys), (H, dn, dc), dn, dtype),
            "w_uv": _dense3(next(keys), (H, cfg.v_head_dim, dc), dc, dtype),
            "wo": dense(next(keys), (H * cfg.v_head_dim, D)),
            "ln_attn": jnp.ones((D,), dtype),
            "ln_mlp": jnp.ones((D,), dtype),
        }
        if cfg.q_lora_rank:
            layer["w_dq"] = dense(next(keys), (D, cfg.q_lora_rank))
            layer["ln_q"] = jnp.ones((cfg.q_lora_rank,), dtype)
            layer["w_uq"] = dense(
                next(keys), (cfg.q_lora_rank, H * (dn + dr))
            )
        else:
            layer["wq"] = dense(next(keys), (D, H * (dn + dr)))
    else:
        layer = {
            "wq": dense(next(keys), (D, H * hd)),
            "wk": dense(next(keys), (D, kvH * hd)),
            "wv": dense(next(keys), (D, kvH * hd)),
            "wo": dense(next(keys), (H * hd, D)),
            "ln_attn": norm_init((D,)),
            "ln_mlp": norm_init((D,)),
        }
        if cfg.post_norms:
            layer["ln_post_attn"] = norm_init((D,))
            layer["ln_post_mlp"] = norm_init((D,))
    if cfg.moe_layer(li):
        # Sparse MLP (models/moe.py): router + stacked expert weights,
        # ep/tp-shardable; DeepSeekMoE adds always-on shared experts
        # and (V3/R1) a sigmoid router with a selection-bias term.
        E = cfg.num_experts
        Im = cfg.moe_intermediate_size or I
        layer["w_router"] = dense(next(keys), (D, E))
        if cfg.gating == "sigmoid":
            layer["router_bias"] = jnp.zeros((E,), jnp.float32)
        layer["w_gate"] = _dense3(next(keys), (E, D, Im), D, dtype)
        layer["w_up"] = _dense3(next(keys), (E, D, Im), D, dtype)
        layer["w_down"] = _dense3(next(keys), (E, Im, D), Im, dtype)
        if cfg.n_shared_experts:
            Is = Im * cfg.n_shared_experts
            layer["w_shared_gate"] = dense(next(keys), (D, Is))
            layer["w_shared_up"] = dense(next(keys), (D, Is))
            layer["w_shared_down"] = dense(next(keys), (Is, D))
    else:
        layer["w_gate"] = dense(next(keys), (D, I))
        layer["w_up"] = dense(next(keys), (D, I))
        layer["w_down"] = dense(next(keys), (I, D))
    if cfg.qkv_bias:
        layer["bq"] = jnp.zeros((H * hd,), dtype)
        layer["bk"] = jnp.zeros((kvH * hd,), dtype)
        layer["bv"] = jnp.zeros((kvH * hd,), dtype)
    if cfg.qk_norm:
        layer["ln_q_head"] = norm_init((hd,))
        layer["ln_k_head"] = norm_init((hd,))
    return layer


def init_params(
    key: jax.Array, cfg: ModelConfig, dtype=jnp.bfloat16
) -> Params:
    """Random-init params with 1/sqrt(fan_in) scaling."""
    lk, ek, hk = jax.random.split(key, 3)
    layer_keys = jax.random.split(lk, cfg.num_layers)
    params: Params = {
        "embed": _dense_init(ek, (cfg.vocab_size, cfg.hidden_size), dtype),
        "layers": [
            init_layer_params(layer_keys[li], cfg, li, dtype)
            for li in range(cfg.num_layers)
        ],
        "ln_f": (jnp.zeros if cfg.norm_offset else jnp.ones)(
            (cfg.hidden_size,), dtype
        ),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = _dense_init(
            hk, (cfg.hidden_size, cfg.vocab_size), dtype
        )
    return params


def _qkv(layer: Params, x: jnp.ndarray, cfg: ModelConfig):
    q = qdot(x, layer["wq"])
    k = qdot(x, layer["wk"])
    v = qdot(x, layer["wv"])
    if cfg.qkv_bias:
        q = q + layer["bq"]
        k = k + layer["bk"]
        v = v + layer["bv"]
    T = x.shape[0]
    q = q.reshape(T, cfg.num_heads, cfg.head_dim)
    k = k.reshape(T, cfg.num_kv_heads, cfg.head_dim)
    if cfg.qk_norm:
        # Qwen3/Gemma-3: per-head RMSNorm on q/k before rope (HF
        # q_norm/k_norm over head_dim; Gemma's (1+w) scale via _ln).
        q = _ln(q, layer["ln_q_head"], cfg)
        k = _ln(k, layer["ln_k_head"], cfg)
    if cfg.query_pre_attn_scalar:
        # Kernels scale scores by 1/sqrt(head_dim); fold the family's
        # 1/sqrt(query_pre_attn_scalar) in as a q pre-multiply.
        q = q * jnp.asarray(
            (cfg.head_dim / cfg.query_pre_attn_scalar) ** 0.5, q.dtype
        )
    return (q, k, v.reshape(T, cfg.num_kv_heads, cfg.head_dim))


def _dense3(key, shape, fan_in, dtype):
    return (jax.random.normal(key, shape, jnp.float32) / (fan_in**0.5)).astype(
        dtype
    )


def _qkv_mla(layer: Params, x: jnp.ndarray, cfg: ModelConfig, positions):
    """DeepSeek MLA projections with the absorbed-matrix trick.

    Instead of materializing per-head K/V (reference models do at decode
    cost), queries are projected INTO the latent space: scores
    q_nope·(W_uk c) ≡ (W_uk^T q_nope)·c, so the paged cache stores one
    shared entry [latent ‖ roped k_pe] per token and attention runs as
    MQA over kv_lora_rank + rope dims — the kernels (ops/attention.py,
    ops/pallas) are reused unchanged with kvH=1. Returns
    (q [T, H, dc+dr], k_entry [T, 1, dc+dr], v_entry [T, 1, dc+dr])
    where v_entry is the latent zero-padded to the key width (its roped
    tail contributes nothing to the value read; _mla_out up-projects).
    """
    H = cfg.num_heads
    dn, dr, dc = cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.kv_lora_rank
    T = x.shape[0]

    if cfg.q_lora_rank:
        cq = rms_norm(qdot(x, layer["w_dq"]), layer["ln_q"], cfg.rms_eps)
        q = qdot(cq, layer["w_uq"])
    else:
        q = qdot(x, layer["wq"])
    q = q.reshape(T, H, dn + dr)
    q_nope, q_pe = q[..., :dn], q[..., dn:]
    q_pe = apply_rope(q_pe, positions, cfg.rope_theta, cfg.rope_scaling)
    # Absorb W_uk: per-head query in latent space.
    q_lat = qeinsum("thn,hnc->thc", q_nope, layer["w_uk"])

    ckr = qdot(x, layer["w_dkv"])                       # [T, dc + dr]
    c = rms_norm(ckr[:, :dc], layer["ln_kv"], cfg.rms_eps)
    k_pe = apply_rope(
        ckr[:, None, dc:], positions, cfg.rope_theta, cfg.rope_scaling
    )[:, 0]                                            # [T, dr] (1 shared head)

    # Attention kernels scale by 1/sqrt(q_width); MLA's true scale is
    # 1/sqrt(dn + dr) — fold the correction into q, along with DeepSeek's
    # yarn softmax-scale multiplier. The reference multiplies the softmax
    # scale by mscale² (HF: softmax_scale * mscale * mscale), and only q
    # carries our correction, so q gets the full square.
    corr = ((dc + dr) / (dn + dr)) ** 0.5
    if cfg.rope_scaling is not None:
        corr *= cfg.rope_scaling.attn_mscale() ** 2
    q_full = jnp.concatenate([q_lat, q_pe], axis=-1) * corr
    k_entry = jnp.concatenate([c, k_pe], axis=-1)[:, None, :]
    v_entry = jnp.pad(c, ((0, 0), (0, dr)))[:, None, :]
    return q_full.astype(x.dtype), k_entry, v_entry


def _mla_out(layer: Params, attn: jnp.ndarray, cfg: ModelConfig):
    """Attention output [..., H, dc+dr] → up-project the latent part per
    head (absorbed W_uv) and apply the output projection."""
    dc = cfg.kv_lora_rank
    o_lat = attn[..., :dc]
    o = qeinsum("...hc,hvc->...hv", o_lat, layer["w_uv"])
    lead = o.shape[:-2]
    return qdot(
        o.reshape(*lead, cfg.num_heads * cfg.v_head_dim).astype(attn.dtype),
        layer["wo"],
    )


def _swiglu(
    layer: Params, x: jnp.ndarray, prefix: str = "w_", act: str = "silu"
) -> jnp.ndarray:
    # "silu" = Llama SwiGLU; "gelu_tanh" = Gemma GeGLU (HF
    # hidden_activation="gelu_pytorch_tanh" = tanh-approximated gelu).
    gate = qdot(x, layer[f"{prefix}gate"])
    gate = (
        jax.nn.silu(gate) if act == "silu"
        else jax.nn.gelu(gate, approximate=True)
    )
    return qdot(gate * qdot(x, layer[f"{prefix}up"]), layer[f"{prefix}down"])


def _mlp(
    layer: Params, x: jnp.ndarray, cfg: ModelConfig, mesh=None
) -> jnp.ndarray:
    # Structure-driven: a router in the layer means routed experts (MoE
    # models may keep their first_k_dense_replace layers dense). `mesh`
    # (from the AttnDispatch) lets capacity dispatch pin its ep
    # collectives explicitly (models/moe.py _moe_mlp_capacity).
    if "w_router" in layer:
        return _moe_mlp(layer, x, cfg, mesh)
    return _swiglu(layer, x, act=cfg.hidden_act)


def _moe_mlp(
    layer: Params, x: jnp.ndarray, cfg: ModelConfig, mesh=None
) -> jnp.ndarray:
    """Top-k routed expert MLP over arbitrary leading dims (models/moe.py
    dense-einsum formulation, ep/tp-sharded under the mesh), plus
    DeepSeekMoE always-on shared experts when present."""
    from dynamo_tpu.models.moe import MoeConfig, moe_mlp

    mcfg = MoeConfig(
        hidden_size=cfg.hidden_size,
        intermediate_size=cfg.moe_intermediate_size or cfg.intermediate_size,
        num_experts=cfg.num_experts,
        num_experts_per_tok=cfg.num_experts_per_tok,
        gating=cfg.gating,
        norm_topk_prob=cfg.norm_topk_prob,
        routed_scaling_factor=cfg.routed_scaling_factor,
        n_group=cfg.n_group,
        topk_group=cfg.topk_group,
        dispatch=cfg.moe_dispatch,
        capacity_factor=cfg.moe_capacity_factor,
    )
    lead = x.shape[:-1]
    flat = x.reshape(-1, cfg.hidden_size)
    out = moe_mlp(layer, flat, mcfg, mesh=mesh)
    if "w_shared_gate" in layer:
        out = out + _swiglu(layer, flat, prefix="w_shared_")
    return out.reshape(*lead, cfg.hidden_size)


def _to_cache(vals: jnp.ndarray, cache: jnp.ndarray) -> jnp.ndarray:
    """Cast (and lane-pad, when the cache head dim is padded for the
    Pallas kernels) K/V values for a cache scatter."""
    pad = cache.shape[-1] - vals.shape[-1]
    if pad:
        vals = jnp.pad(vals, ((0, 0),) * (vals.ndim - 1) + ((0, pad),))
    return vals.astype(cache.dtype)


def _logits(params: Params, cfg: ModelConfig, h: jnp.ndarray) -> jnp.ndarray:
    h = _ln(h, params["ln_f"], cfg)
    if cfg.tie_word_embeddings:
        return tied_head_mm(h, params["embed"]).astype(jnp.float32)
    return qdot(h, params["lm_head"]).astype(jnp.float32)


def prefill(
    cfg: ModelConfig,
    params: Params,
    kv_caches: list[tuple[jnp.ndarray, jnp.ndarray]],
    token_ids: jnp.ndarray,    # [T] padded new tokens
    block_table: jnp.ndarray,  # [max_blocks]
    slot_mapping: jnp.ndarray, # [T] cache slots (trash slots for padding)
    prefix_len: jnp.ndarray,   # scalar — prefix-cache hit length
    total_len: jnp.ndarray,    # scalar — prefix + real new tokens
    block_size: int,
    attn: AttnDispatch | None = None,
    embeds: jnp.ndarray | None = None,      # [T, D] soft-prompt overrides
    embed_mask: jnp.ndarray | None = None,  # [T] bool — rows taken from embeds
) -> tuple[jnp.ndarray, list[tuple[jnp.ndarray, jnp.ndarray]]]:
    """Prefill one sequence's new tokens; returns (last-token logits [V],
    updated kv_caches). Supports prefix reuse via prefix_len > 0.

    `embeds`/`embed_mask` (a static trace-time branch — text-only runners
    compile without the extra inputs) substitute projected multimodal
    embeddings for placeholder-token rows: the soft-prompt mechanism the
    multimodal encode worker feeds (llm/multimodal.py; reference analogue:
    examples/multimodal encode_worker ahead of the decode worker)."""
    prefill_attention, _ = _attn_fns(attn)
    mesh = attn.mesh if attn is not None else None
    T = token_ids.shape[0]
    positions = prefix_len + jnp.arange(T)
    x = _embed(params, cfg, token_ids)
    if embeds is not None:
        x = jnp.where(embed_mask[:, None], embeds.astype(x.dtype), x)

    new_caches = []
    for li, (layer, (k_cache, v_cache)) in enumerate(
        zip(params["layers"], kv_caches)
    ):
        h = _ln(x, layer["ln_attn"], cfg)
        if cfg.is_mla:
            q, k, v = _qkv_mla(layer, h, cfg, positions)
        else:
            q, k, v = _qkv(layer, h, cfg)
            th, sc = _layer_rope(cfg, li)
            q = apply_rope(q, positions, th, sc)
            k = apply_rope(k, positions, th, sc)
        k_cache = k_cache.at[slot_mapping].set(_to_cache(k, k_cache))
        v_cache = v_cache.at[slot_mapping].set(_to_cache(v, v_cache))
        attn = prefill_attention(
            q[None], k_cache, v_cache, block_table[None], prefix_len[None],
            total_len[None], block_size, window=cfg.layer_window(li),
        )[0]
        if cfg.is_mla:
            x = x + _mla_out(layer, attn, cfg)
        else:
            x = _residual_attn(x, layer, qdot(attn.reshape(T, -1), layer["wo"]), cfg)
        x = _residual_mlp(x, layer, cfg, mesh)
        new_caches.append((k_cache, v_cache))

    last = jnp.clip(total_len - prefix_len - 1, 0, T - 1)
    return _logits(params, cfg, x[last]), new_caches


def prefill_batch(
    cfg: ModelConfig,
    params: Params,
    kv_caches: list[tuple[jnp.ndarray, jnp.ndarray]],
    token_ids: jnp.ndarray,     # [N, T] padded new tokens per lane
    block_tables: jnp.ndarray,  # [N, max_blocks]
    slot_mapping: jnp.ndarray,  # [N, T] (trash slots for padding/idle lanes)
    prefix_len: jnp.ndarray,    # [N]
    total_len: jnp.ndarray,     # [N] (0 = idle lane)
    block_size: int,
    attn: AttnDispatch | None = None,
) -> tuple[jnp.ndarray, list[tuple[jnp.ndarray, jnp.ndarray]]]:
    """N sequences' prefills fused into one call: the projections/MLP run as
    one [N*T] batch on the MXU, K/V scatter once, and only the attention is
    vmapped per lane (it reads the shared cache through per-lane block
    tables). One dispatch amortizes host→device latency over N prompts —
    the batched-prefill trick the reference inherits from vLLM's scheduler.
    Returns last-token logits [N, V]. (Speculative verification lives on
    the unified path now — ``unified(verify_rows=k+1)`` returns per-span
    verify logits; this raw program serves parity tests and tools.)"""
    prefill_attention, _ = _attn_fns(attn)
    mesh = attn.mesh if attn is not None else None
    N, T = token_ids.shape
    H, kvH, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    positions = prefix_len[:, None] + jnp.arange(T)[None, :]
    x = _embed(params, cfg, token_ids)  # [N, T, D]

    new_caches = []
    for li, (layer, (k_cache, v_cache)) in enumerate(
        zip(params["layers"], kv_caches)
    ):
        h = _ln(x, layer["ln_attn"], cfg)
        flat_slots = slot_mapping.reshape(N * T)
        if cfg.is_mla:
            q, k, v = jax.vmap(
                lambda xi, pi: _qkv_mla(layer, xi, cfg, pi)
            )(h, positions)                     # q [N,T,H,dm], k/v [N,T,1,dm]
            dm = k.shape[-1]
            k_cache = k_cache.at[flat_slots].set(
                _to_cache(k.reshape(N * T, 1, dm), k_cache)
            )
            v_cache = v_cache.at[flat_slots].set(
                _to_cache(v.reshape(N * T, 1, dm), v_cache)
            )
        else:
            q = qdot(h, layer["wq"])
            k = qdot(h, layer["wk"])
            v = qdot(h, layer["wv"])
            if cfg.qkv_bias:
                q, k, v = q + layer["bq"], k + layer["bk"], v + layer["bv"]
            q = q.reshape(N, T, H, hd)
            k = k.reshape(N, T, kvH, hd)
            if cfg.qk_norm:
                q = _ln(q, layer["ln_q_head"], cfg)
                k = _ln(k, layer["ln_k_head"], cfg)
            if cfg.query_pre_attn_scalar:
                q = q * jnp.asarray(
                    (hd / cfg.query_pre_attn_scalar) ** 0.5, q.dtype
                )
            th, sc = _layer_rope(cfg, li)
            rope = jax.vmap(lambda t, p: apply_rope(t, p, th, sc))
            q = rope(q, positions)
            k = rope(k, positions)
            v = v.reshape(N, T, kvH, hd)
            k_cache = k_cache.at[flat_slots].set(
                _to_cache(k.reshape(N * T, kvH, hd), k_cache)
            )
            v_cache = v_cache.at[flat_slots].set(
                _to_cache(v.reshape(N * T, kvH, hd), v_cache)
            )
        attn = prefill_attention(
            q, k_cache, v_cache, block_tables, prefix_len, total_len,
            block_size, window=cfg.layer_window(li),
        )
        if cfg.is_mla:
            x = x + _mla_out(layer, attn, cfg)
        else:
            x = _residual_attn(
                x, layer, qdot(attn.reshape(N, T, H * hd), layer["wo"]), cfg
            )
        x = _residual_mlp(x, layer, cfg, mesh)
        new_caches.append((k_cache, v_cache))

    last = jnp.clip(total_len - prefix_len - 1, 0, T - 1)  # [N]
    hs = jnp.take_along_axis(x, last[:, None, None], axis=1)[:, 0]  # [N, D]
    return _logits(params, cfg, hs), new_caches


def unified(
    cfg: ModelConfig,
    params: Params,
    kv_caches: list[tuple[jnp.ndarray, jnp.ndarray]],
    token_ids: jnp.ndarray,     # [T] flat mixed batch (budget-padded)
    token_pos: jnp.ndarray,     # [T] global position per token (-1 = pad)
    slot_mapping: jnp.ndarray,  # [T] cache slots (trash slots for padding)
    token_seq: jnp.ndarray,     # [T] owning metadata row per token
    block_tables: jnp.ndarray,  # [S, max_blocks]
    q_start: jnp.ndarray,       # [S] span prefix length
    q_len: jnp.ndarray,         # [S] span rows (0 = idle row)
    kv_len: jnp.ndarray,        # [S] context after this step
    row_start: jnp.ndarray,     # [S] span's first flat row
    block_size: int,
    attn: AttnDispatch | None = None,
    kv_scales: jnp.ndarray | None = None,  # [L, 2, num_blocks, kvH] f32
    draft_len: jnp.ndarray | None = None,  # [S] draft rows in each span tail
    verify_rows: int = 1,                  # static: logit rows per span
    embeds: jnp.ndarray | None = None,     # [T, D] soft-prompt overrides
    embed_mask: jnp.ndarray | None = None, # [T] bool — rows from embeds
):
    """ONE forward for a mixed prefill+decode token batch (the unified
    step — docs/architecture/unified_step.md). The trunk is the single-
    sequence prefill trunk over arbitrary per-token positions: embed,
    RoPE at ``token_pos``, K/V scatter at ``slot_mapping``, ragged paged
    attention (ops/attention.py AttnDispatch.ragged), MLP. Decode lanes
    are spans of length 1; prefill quanta are their chunk's rows; a
    speculative draft-verify span is ``q_len = draft_len + 1`` rows
    (the fed token plus its drafts — verification is just a short
    "prefill" over the draft positions); the only compiled extent is
    the token budget ``T`` (plus the fixed metadata width ``S``), which
    is what deletes the phase×bucket×lane program grid.

    With ``kv_scales`` (int8 KV caches — docs/architecture/kv_quant.md)
    the K/V scatter quantizes through the shared per-block write law
    (ops/quant.py quantize_kv_write) and attention dequantizes in the
    kernel/oracle; returns (logits, caches, new_scales) then, or the
    legacy (logits, caches) pair when unquantized.

    ``embeds``/``embed_mask`` (a static trace-time branch, same as
    ``prefill``) substitute multimodal soft-prompt rows into the FLAT
    token batch — the one scatter path per-lane embed tensors needed.

    Returns per-span logits: ``verify_rows == 1`` keeps the legacy
    last-row contract ``[S, V]`` (span s's logits come from its LAST
    real token row — mid-prompt quanta's samples are discarded by the
    engine, exactly as chunked prefill did). ``verify_rows = R > 1``
    returns ``[S, R, V]``: row ``j`` of span ``s`` is the logits at
    span row ``q_len - 1 - draft_len + j`` (clamped into the span) —
    for a draft-verify span row 0 scores the first draft and row
    ``draft_len`` is the bonus position; spans with fewer rows repeat
    their last row (masked by the caller's acceptance law)."""
    if attn is None:
        from dynamo_tpu.ops import attention as attn_ops

        ragged_fn = attn_ops.ragged_attention
    else:
        ragged_fn = attn.ragged
    mesh = attn.mesh if attn is not None else None
    T = token_ids.shape[0]
    positions = jnp.maximum(token_pos, 0)
    x = _embed(params, cfg, token_ids)
    if embeds is not None:
        x = jnp.where(embed_mask[:, None], embeds.astype(x.dtype), x)
    if kv_scales is not None:
        from dynamo_tpu.ops.quant import quantize_kv_write

    new_caches = []
    new_scales = []
    for li, (layer, (k_cache, v_cache)) in enumerate(
        zip(params["layers"], kv_caches)
    ):
        h = _ln(x, layer["ln_attn"], cfg)
        if cfg.is_mla:
            q, k, v = _qkv_mla(layer, h, cfg, positions)
        else:
            q, k, v = _qkv(layer, h, cfg)
            th, sc = _layer_rope(cfg, li)
            q = apply_rope(q, positions, th, sc)
            k = apply_rope(k, positions, th, sc)
        if kv_scales is not None:
            pad = k_cache.shape[-1] - k.shape[-1]
            if pad:  # lane-padded cache (Pallas head-dim contract)
                widen = ((0, 0),) * (k.ndim - 1) + ((0, pad),)
                k, v = jnp.pad(k, widen), jnp.pad(v, widen)
            k_cache, k_sc = quantize_kv_write(
                k_cache, kv_scales[li, 0], slot_mapping, k, block_size
            )
            v_cache, v_sc = quantize_kv_write(
                v_cache, kv_scales[li, 1], slot_mapping, v, block_size
            )
            new_scales.append(jnp.stack([k_sc, v_sc]))
            scale_kw = {"k_scales": k_sc, "v_scales": v_sc}
        else:
            k_cache = k_cache.at[slot_mapping].set(_to_cache(k, k_cache))
            v_cache = v_cache.at[slot_mapping].set(_to_cache(v, v_cache))
            scale_kw = {}
        attn_out = ragged_fn(
            q, k_cache, v_cache, block_tables, token_seq, token_pos,
            q_start, q_len, kv_len, row_start, block_size,
            window=cfg.layer_window(li), **scale_kw,
        )
        if cfg.is_mla:
            x = x + _mla_out(layer, attn_out, cfg)
        else:
            x = _residual_attn(
                x, layer, qdot(attn_out.reshape(T, -1), layer["wo"]), cfg
            )
        x = _residual_mlp(x, layer, cfg, mesh)
        new_caches.append((k_cache, v_cache))

    if verify_rows == 1:
        last = jnp.clip(row_start + q_len - 1, 0, T - 1)  # [S]
        logits = _logits(params, cfg, x[last])
    else:
        # Per-span verify rows: the last draft_len + 1 rows of each span,
        # aligned so row j scores draft j+1 (row draft_len = the bonus
        # position). Short spans clamp onto their own last row — never
        # into a neighbouring span — and idle spans (q_len = 0) clamp to
        # row 0 of the batch, masked by the caller (q_len > 0).
        dl = (
            draft_len
            if draft_len is not None
            else jnp.zeros_like(q_len)
        )
        offs = jnp.arange(verify_rows)                      # [R]
        span_row = jnp.clip(
            (q_len - 1 - dl)[:, None] + offs[None, :],
            0,
            jnp.maximum(q_len - 1, 0)[:, None],
        )                                                    # [S, R]
        rows = jnp.clip(row_start[:, None] + span_row, 0, T - 1)
        logits = _logits(params, cfg, x[rows])               # [S, R, V]
    if kv_scales is not None:
        return logits, new_caches, jnp.stack(new_scales)
    return logits, new_caches


def decode(
    cfg: ModelConfig,
    params: Params,
    kv_caches: list[tuple[jnp.ndarray, jnp.ndarray]],
    token_ids: jnp.ndarray,     # [B]
    positions: jnp.ndarray,     # [B] — context_len - 1 for active slots
    block_tables: jnp.ndarray,  # [B, max_blocks]
    context_lens: jnp.ndarray,  # [B] — 0 marks an inactive slot
    slot_mapping: jnp.ndarray,  # [B] cache slots for the new token
    block_size: int,
    attn: AttnDispatch | None = None,
) -> tuple[jnp.ndarray, list[tuple[jnp.ndarray, jnp.ndarray]]]:
    """One decode step for the whole running batch; returns (logits [B, V],
    updated kv_caches)."""
    _, decode_attention = _attn_fns(attn)
    mesh = attn.mesh if attn is not None else None
    B = token_ids.shape[0]
    x = _embed(params, cfg, token_ids)

    new_caches = []
    for li, (layer, (k_cache, v_cache)) in enumerate(
        zip(params["layers"], kv_caches)
    ):
        h = _ln(x, layer["ln_attn"], cfg)
        if cfg.is_mla:
            q, k, v = _qkv_mla(layer, h, cfg, positions)
        else:
            q, k, v = _qkv(layer, h, cfg)
            th, sc = _layer_rope(cfg, li)
            q = apply_rope(q, positions, th, sc)
            k = apply_rope(k, positions, th, sc)
        k_cache = k_cache.at[slot_mapping].set(_to_cache(k, k_cache))
        v_cache = v_cache.at[slot_mapping].set(_to_cache(v, v_cache))
        attn = decode_attention(
            q, k_cache, v_cache, block_tables, context_lens, block_size,
            window=cfg.layer_window(li),
        )
        if cfg.is_mla:
            x = x + _mla_out(layer, attn, cfg)
        else:
            x = _residual_attn(x, layer, qdot(attn.reshape(B, -1), layer["wo"]), cfg)
        x = _residual_mlp(x, layer, cfg, mesh)
        new_caches.append((k_cache, v_cache))

    return _logits(params, cfg, x), new_caches


def hidden_states(
    cfg: ModelConfig,
    params: Params,
    token_ids: jnp.ndarray,
    embeds: jnp.ndarray | None = None,
    embed_mask: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Full no-cache trunk [T] -> pre-final-norm hidden states [T, D] —
    shared by the logits oracle below and the embeddings pooled forward
    (llm/embedding.py), so architecture changes live in one place.
    `embeds`/`embed_mask` mirror prefill's soft-prompt substitution so the
    oracle covers the multimodal path too."""
    T = token_ids.shape[0]
    positions = jnp.arange(T)
    x = _embed(params, cfg, token_ids)
    if embeds is not None:
        x = jnp.where(embed_mask[:, None], embeds.astype(x.dtype), x)
    for li, layer in enumerate(params["layers"]):
        h = _ln(x, layer["ln_attn"], cfg)
        if cfg.is_mla:
            q, k, v = _qkv_mla(layer, h, cfg, positions)
            attn = full_causal_attention(q, k, v)
            x = x + _mla_out(layer, attn, cfg)
        else:
            q, k, v = _qkv(layer, h, cfg)
            th, sc = _layer_rope(cfg, li)
            q = apply_rope(q, positions, th, sc)
            k = apply_rope(k, positions, th, sc)
            attn = full_causal_attention(q, k, v, window=cfg.layer_window(li))
            x = _residual_attn(x, layer, qdot(attn.reshape(T, -1), layer["wo"]), cfg)
        x = _residual_mlp(x, layer, cfg)
    return x


def reference_forward(
    cfg: ModelConfig,
    params: Params,
    token_ids: jnp.ndarray,
    embeds: jnp.ndarray | None = None,
    embed_mask: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Full no-cache forward [T] -> logits [T, V]; the correctness oracle the
    paged prefill/decode paths are tested against."""
    return _logits(
        params, cfg, hidden_states(cfg, params, token_ids, embeds, embed_mask)
    )


def load_hf_weights(
    cfg: ModelConfig,
    model_dir: str,
    dtype=jnp.bfloat16,
    policy: WeightQuantPolicy | None = None,
) -> Params:
    """Load params from a HF checkout's safetensors shards (torch [out,in]
    weights transposed to our [in,out] layout).

    With a ``policy`` (WeightQuantPolicy) each selected weight quantizes
    AS ITS LAYER LOADS — the full-precision transient never exceeds one
    layer, so the resident tree is quantized from the start and the
    bf16 copy of the model never materializes (the same discipline as
    ops/quant.py init_params_policy for random init)."""
    import glob
    import os

    import numpy as np
    from safetensors import safe_open

    fmts = policy_layer_fmts(policy) if policy is not None else {}

    def quantize_layer(layer: Params) -> Params:
        for k, fmt in fmts.items():
            if k in layer:
                layer[k] = quantize_weight(
                    layer[k], axis=QUANT_AXES.get(k, CONTRACT_AXIS), fmt=fmt
                )
        return layer

    tensors: dict[str, np.ndarray] = {}
    files = sorted(glob.glob(os.path.join(model_dir, "*.safetensors")))
    if not files:
        raise FileNotFoundError(f"no safetensors in {model_dir}")
    for path in files:
        with safe_open(path, framework="np") as f:
            for name in f.keys():
                tensors[name] = f.get_tensor(name)

    def w(name: str, transpose: bool = True) -> jnp.ndarray:
        arr = tensors[name]
        if transpose and arr.ndim == 2:
            arr = arr.T
        return jnp.asarray(arr, dtype=dtype)

    layers = []
    for i in range(cfg.num_layers):
        p = f"model.layers.{i}"
        if cfg.is_mla:
            # DeepSeek-V2/V3 MLA layout. kv_b_proj packs per-head
            # [k_nope ‖ v] up-projections over the latent; split it into
            # the absorbed w_uk [H, dn, dc] / w_uv [H, v, dc] our
            # attention uses (models/llama.py _qkv_mla). HF DeepSeek
            # stores the roped dims PAIR-INTERLEAVED and permutes them to
            # half-split at runtime (modeling's view/transpose before
            # rotate_half); we bake that permutation into the q_pe
            # columns / k_pe rows at load so ops/rope.py's NeoX halves
            # reproduce the checkpoint's numerics exactly.
            dn, dr, dc = (
                cfg.qk_nope_head_dim, cfg.qk_rope_head_dim, cfg.kv_lora_rank
            )
            H, dv = cfg.num_heads, cfg.v_head_dim
            perm = np.concatenate(
                [np.arange(0, dr, 2), np.arange(1, dr, 2)]
            )  # interleaved pairs -> [evens ‖ odds] (NeoX halves)

            def permute_q(arr):  # [in, H*(dn+dr)] our layout, post-.T
                qr = arr.reshape(arr.shape[0], H, dn + dr)
                pe = qr[..., dn:][..., perm]
                return jnp.concatenate(
                    [qr[..., :dn], pe], axis=-1
                ).reshape(arr.shape[0], H * (dn + dr))

            kvb = tensors[f"{p}.self_attn.kv_b_proj.weight"]  # [H*(dn+dv), dc]
            kvb = kvb.reshape(H, dn + dv, dc)
            dkv = np.asarray(tensors[f"{p}.self_attn.kv_a_proj_with_mqa.weight"]).T
            dkv = np.concatenate([dkv[:, :dc], dkv[:, dc:][:, perm]], axis=1)
            layer = {
                "w_dkv": jnp.asarray(dkv, dtype=dtype),
                "ln_kv": w(f"{p}.self_attn.kv_a_layernorm.weight",
                           transpose=False),
                "w_uk": jnp.asarray(kvb[:, :dn, :], dtype=dtype),
                "w_uv": jnp.asarray(kvb[:, dn:, :], dtype=dtype),
                "wo": w(f"{p}.self_attn.o_proj.weight"),
                "ln_attn": w(f"{p}.input_layernorm.weight", transpose=False),
                "ln_mlp": w(f"{p}.post_attention_layernorm.weight",
                            transpose=False),
            }
            if cfg.q_lora_rank:
                layer["w_dq"] = w(f"{p}.self_attn.q_a_proj.weight")
                layer["ln_q"] = w(f"{p}.self_attn.q_a_layernorm.weight",
                                  transpose=False)
                layer["w_uq"] = permute_q(w(f"{p}.self_attn.q_b_proj.weight"))
            else:
                layer["wq"] = permute_q(w(f"{p}.self_attn.q_proj.weight"))
        else:
            layer = {
                "wq": w(f"{p}.self_attn.q_proj.weight"),
                "wk": w(f"{p}.self_attn.k_proj.weight"),
                "wv": w(f"{p}.self_attn.v_proj.weight"),
                "wo": w(f"{p}.self_attn.o_proj.weight"),
                "ln_attn": w(f"{p}.input_layernorm.weight", transpose=False),
            }
            if cfg.post_norms:
                # Gemma-3 sandwich norms: HF post_attention_layernorm is
                # the POST-attention branch norm; the MLP pre-norm is
                # pre_feedforward_layernorm.
                layer["ln_post_attn"] = w(
                    f"{p}.post_attention_layernorm.weight", transpose=False
                )
                layer["ln_mlp"] = w(
                    f"{p}.pre_feedforward_layernorm.weight", transpose=False
                )
                layer["ln_post_mlp"] = w(
                    f"{p}.post_feedforward_layernorm.weight", transpose=False
                )
            else:
                layer["ln_mlp"] = w(
                    f"{p}.post_attention_layernorm.weight", transpose=False
                )
        if cfg.moe_layer(i):
            if f"{p}.block_sparse_moe.gate.weight" in tensors:
                # Mixtral layout: block_sparse_moe.gate + per-expert
                # w1/w3/w2 (gate/up/down), stacked over the expert dim.
                m = f"{p}.block_sparse_moe"
                enames = ("w1.weight", "w3.weight", "w2.weight")
            else:
                # DeepSeek layout: mlp.gate router (+ optional V3 bias),
                # mlp.experts.{e}.gate/up/down, mlp.shared_experts.*.
                m = f"{p}.mlp"
                enames = ("gate_proj.weight", "up_proj.weight",
                          "down_proj.weight")
            layer["w_router"] = w(f"{m}.gate.weight")
            bias_name = f"{m}.gate.e_score_correction_bias"
            if cfg.gating == "sigmoid":
                layer["router_bias"] = (
                    jnp.asarray(tensors[bias_name], jnp.float32)
                    if bias_name in tensors
                    else jnp.zeros((cfg.num_experts,), jnp.float32)
                )
            for key, ename in zip(("w_gate", "w_up", "w_down"), enames):
                layer[key] = jnp.stack(
                    [
                        w(f"{m}.experts.{e}.{ename}")
                        for e in range(cfg.num_experts)
                    ]
                )
            if cfg.n_shared_experts:
                layer["w_shared_gate"] = w(f"{m}.shared_experts.gate_proj.weight")
                layer["w_shared_up"] = w(f"{m}.shared_experts.up_proj.weight")
                layer["w_shared_down"] = w(f"{m}.shared_experts.down_proj.weight")
        else:
            layer["w_gate"] = w(f"{p}.mlp.gate_proj.weight")
            layer["w_up"] = w(f"{p}.mlp.up_proj.weight")
            layer["w_down"] = w(f"{p}.mlp.down_proj.weight")
        if cfg.qkv_bias:
            layer["bq"] = w(f"{p}.self_attn.q_proj.bias", transpose=False)
            layer["bk"] = w(f"{p}.self_attn.k_proj.bias", transpose=False)
            layer["bv"] = w(f"{p}.self_attn.v_proj.bias", transpose=False)
        if cfg.qk_norm:
            layer["ln_q_head"] = w(
                f"{p}.self_attn.q_norm.weight", transpose=False
            )
            layer["ln_k_head"] = w(
                f"{p}.self_attn.k_norm.weight", transpose=False
            )
        layers.append(quantize_layer(layer))

    embed = w("model.embed_tokens.weight", transpose=False)
    unembed_fmt = getattr(policy, "unembed", None)
    embed_fmt = getattr(policy, "embedding", None) or (
        unembed_fmt if cfg.tie_word_embeddings else None
    )
    if embed_fmt:
        # Per-ROW scales: the table is a gather (and, tied, the unembed
        # matmul operand whose output channels ARE the rows).
        embed = quantize_weight(embed, axis=-1, fmt=embed_fmt)
    params: Params = {
        "embed": embed,
        "layers": layers,
        "ln_f": w("model.norm.weight", transpose=False),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = (
            quantize_weight(w("lm_head.weight"), fmt=unembed_fmt)
            if unembed_fmt
            else w("lm_head.weight")
        )
    return params

"""Model architecture configs.

Covers the Llama family tree (Llama-2/3/3.x, TinyLlama, Qwen2 via qkv_bias,
DeepSeek-R1-Distill-Llama) — the architectures named in BASELINE.md's
progression. Loadable from a HF checkout's config.json.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, replace
from pathlib import Path


def _rope_scaling(d):
    from dynamo_tpu.ops.rope import RopeScaling

    return RopeScaling.from_hf(d)


@dataclass(frozen=True)
class ModelConfig:
    name: str = "llama"
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    rope_theta: float = 500000.0
    rms_eps: float = 1e-5
    max_position: int = 8192
    tie_word_embeddings: bool = False
    qkv_bias: bool = False  # Qwen2-style
    # Qwen3-style per-head RMSNorm on q/k (applied after the head reshape,
    # before rope).
    qk_norm: bool = False
    # Sliding-window attention (Mistral-style): each token attends to at
    # most the last `sliding_window` keys. 0 = full causal attention.
    sliding_window: int = 0
    # Qwen2-style layer gate: the FIRST max_window_layers layers run full
    # attention; only layers at or above it window. 0 = window every layer.
    max_window_layers: int = 0
    # --- Gemma-3 family knobs (models/llama.py; HF Gemma3TextConfig) ---
    # Gated-MLP activation: "silu" (Llama SwiGLU) or "gelu_tanh" (Gemma
    # GeGLU, HF hidden_activation="gelu_pytorch_tanh").
    hidden_act: str = "silu"
    # Gemma RMSNorm stores w and scales by (1 + w) — checkpoints init
    # norms at 0, not 1.
    norm_offset: bool = False
    # Sandwich norms: post-attention and post-feedforward RMSNorms on the
    # residual branches (Gemma-2/3 layer plan).
    post_norms: bool = False
    # Embedding rows are multiplied by sqrt(hidden_size) at lookup
    # (normalizer cast to the activation dtype, matching HF numerics).
    embed_scale: bool = False
    # Gemma-3 layer plan: every `window_pattern`-th layer ((i+1) % p == 0)
    # runs FULL attention, the rest sliding_window. 0 = no pattern.
    window_pattern: int = 0
    # Rope base for the windowed (local) layers; global layers keep
    # rope_theta (+ rope_scaling). 0 = single rope everywhere.
    rope_local_theta: float = 0.0
    # Attention score scale override: scores use 1/sqrt(this) instead of
    # 1/sqrt(head_dim) (HF query_pre_attn_scalar; applied as a q
    # pre-multiply so the kernels stay unchanged). 0 = head_dim.
    query_pre_attn_scalar: float = 0.0
    # Mixtral-style sparse MoE MLP: num_experts > 0 swaps each layer's
    # SwiGLU for top-k routed experts (models/moe.py; ep/tp sharding).
    num_experts: int = 0
    num_experts_per_tok: int = 2
    # Llama-3.1+ long-context rope scaling (ops/rope.py RopeScaling).
    rope_scaling: "object | None" = None
    # --- DeepSeek-V2/V3/R1 family (models/llama.py MLA branch) ---
    # kv_lora_rank > 0 enables MLA: K/V compress into one shared latent
    # vector per token; the paged cache stores [latent ‖ roped k_pe] as a
    # single "kv head" of kv_lora_rank + qk_rope_head_dim dims.
    kv_lora_rank: int = 0
    q_lora_rank: int = 0          # 0 = direct q projection (V2-Lite style)
    qk_nope_head_dim: int = 128
    qk_rope_head_dim: int = 64
    v_head_dim: int = 128
    # DeepSeekMoE: dense layers first, then shared + routed experts.
    n_shared_experts: int = 0
    moe_intermediate_size: int = 0  # routed/shared expert width (per expert)
    first_k_dense_replace: int = 0  # leading layers that keep dense MLP
    # Router scoring: "softmax" (Mixtral/V2) or "sigmoid" (V3/R1, with a
    # per-expert selection-bias correction term).
    gating: str = "softmax"
    norm_topk_prob: bool = True
    routed_scaling_factor: float = 1.0
    # Group-limited routing (DeepSeek "noaux_tc": experts partition into
    # n_group groups; only the topk_group best groups are eligible).
    n_group: int = 1
    topk_group: int = 1
    # Expert execution strategy (models/moe.py): "dense" runs every
    # expert gate-masked (exact; fine for few experts); "capacity"
    # dispatches tokens to per-expert buffers and runs only selected
    # FLOPs — the large-expert-count serving mode (R1: 32× less MLP
    # compute; capacity overflow drops follow the standard rule).
    # "auto" (default) picks capacity when num_experts >= 16 — the
    # crossover where dense's E/topk FLOP waste outweighs dispatch
    # overhead (measured in BENCHMARKS.md "MoE dispatch").
    moe_dispatch: str = "auto"
    moe_capacity_factor: float = 2.0

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    @property
    def is_mla(self) -> bool:
        return self.kv_lora_rank > 0

    @property
    def kv_cache_head_dim(self) -> int:
        """Logical per-head cache width (pre-Pallas-padding)."""
        return (
            self.kv_lora_rank + self.qk_rope_head_dim
            if self.is_mla
            else self.head_dim
        )

    @property
    def num_cache_heads(self) -> int:
        return 1 if self.is_mla else self.num_kv_heads

    def moe_layer(self, layer_idx: int) -> bool:
        """Does this layer use the routed-experts MLP?"""
        return self.is_moe and layer_idx >= self.first_k_dense_replace

    def layer_window(self, layer_idx: int) -> int:
        """Sliding-window size for one layer (0 = full attention): HF
        Qwen2 runs the first max_window_layers layers full-attention;
        Gemma-3 makes every window_pattern-th layer global."""
        if not self.sliding_window:
            return 0
        if self.window_pattern:
            if (layer_idx + 1) % self.window_pattern == 0:
                return 0  # global layer
            return self.sliding_window
        if layer_idx >= self.max_window_layers:
            return self.sliding_window
        return 0

    @property
    def rolling_buffer(self) -> bool:
        """True when EVERY layer is sliding-window attention, so KV blocks
        wholly behind the window can be reclaimed (Mistral's rolling
        buffer cache — reference analogue: mistral.rs rotating KV cache).
        A single full-attention layer (Qwen2's max_window_layers > 0, or a
        Gemma-3 global layer in the pattern) pins the whole history and
        disables eviction."""
        return (
            bool(self.sliding_window)
            and self.max_window_layers == 0
            and self.window_pattern == 0
        )

    @staticmethod
    def from_hf(model_dir: str) -> "ModelConfig":
        cfg = json.loads((Path(model_dir) / "config.json").read_text())
        arch = (cfg.get("architectures") or ["LlamaForCausalLM"])[0]
        if arch.startswith("Gemma2") or cfg.get("attn_logit_softcapping"):
            raise NotImplementedError(
                "Gemma-2 attention-logit softcapping is not implemented; "
                "the Gemma-3 family (softcap-free) is supported"
            )
        if arch.startswith("Gemma3") or "gemma3" in cfg.get("model_type", ""):
            if "text_config" in cfg:  # multimodal wrapper config
                cfg = {**cfg["text_config"],
                       "model_type": cfg.get("model_type", "gemma3")}
            return ModelConfig._from_hf_gemma3(cfg)
        num_heads = cfg["num_attention_heads"]
        hidden = cfg["hidden_size"]
        deepseek = "Deepseek" in arch or "deepseek" in cfg.get("model_type", "")
        return ModelConfig(
            name=cfg.get("model_type", "llama"),
            vocab_size=cfg["vocab_size"],
            hidden_size=hidden,
            intermediate_size=cfg["intermediate_size"],
            num_layers=cfg["num_hidden_layers"],
            num_heads=num_heads,
            num_kv_heads=cfg.get("num_key_value_heads", num_heads),
            head_dim=cfg.get("head_dim", hidden // num_heads),
            rope_theta=cfg.get("rope_theta", 10000.0),
            rms_eps=cfg.get("rms_norm_eps", 1e-5),
            max_position=cfg.get("max_position_embeddings", 8192),
            tie_word_embeddings=cfg.get("tie_word_embeddings", False),
            qkv_bias="Qwen2" in arch,
            qk_norm="Qwen3" in arch,
            # Mistral carries sliding_window unconditionally (null = full
            # attention in v0.2+); Qwen2 gates it behind use_sliding_window.
            sliding_window=int(cfg.get("sliding_window") or 0)
            if cfg.get("use_sliding_window", True)
            else 0,
            max_window_layers=int(cfg.get("max_window_layers") or 0)
            if cfg.get("use_sliding_window", True)
            else 0,
            # DeepSeek uses n_routed_experts; Mixtral num_local_experts.
            num_experts=cfg.get(
                "n_routed_experts", cfg.get("num_local_experts", 0)
            ) or 0,
            num_experts_per_tok=cfg.get("num_experts_per_tok", 2),
            rope_scaling=_rope_scaling(cfg.get("rope_scaling")),
            kv_lora_rank=(cfg.get("kv_lora_rank") or 0) if deepseek else 0,
            q_lora_rank=(cfg.get("q_lora_rank") or 0) if deepseek else 0,
            qk_nope_head_dim=cfg.get("qk_nope_head_dim", 128),
            qk_rope_head_dim=cfg.get("qk_rope_head_dim", 64),
            v_head_dim=cfg.get("v_head_dim", cfg.get("head_dim", hidden // num_heads)),
            n_shared_experts=cfg.get("n_shared_experts", 0) or 0,
            moe_intermediate_size=cfg.get("moe_intermediate_size", 0) or 0,
            first_k_dense_replace=cfg.get("first_k_dense_replace", 0) or 0,
            gating="sigmoid" if cfg.get("scoring_func") == "sigmoid" else "softmax",
            norm_topk_prob=cfg.get("norm_topk_prob", True),
            routed_scaling_factor=cfg.get("routed_scaling_factor", 1.0),
            n_group=cfg.get("n_group", 1) or 1,
            topk_group=cfg.get("topk_group", 1) or 1,
        )

    @staticmethod
    def _from_hf_gemma3(cfg: dict) -> "ModelConfig":
        """HF Gemma3TextConfig → ModelConfig (Gemma-3 1B/4B/12B/27B text
        trunk: GeGLU, (1+w) norms, sandwich norms, scaled embeddings,
        QK-norm, 5-local:1-global window pattern with a separate local
        rope base)."""
        if cfg.get("final_logit_softcapping") or cfg.get(
            "attn_logit_softcapping"
        ):
            raise NotImplementedError(
                "Gemma logit softcapping is not implemented"
            )
        # Published multimodal checkpoints (gemma-3-4b/12b/27b) ship SPARSE
        # text_configs that rely on HF Gemma3TextConfig defaults — fill
        # them in (values from transformers Gemma3TextConfig()).
        defaults = {
            "vocab_size": 262208,
            "hidden_size": 2304,
            "intermediate_size": 9216,
            "num_hidden_layers": 26,
            "num_attention_heads": 8,
            "num_key_value_heads": 4,
            "head_dim": 256,
            "rope_theta": 1_000_000.0,
            "rope_local_base_freq": 10_000.0,
            "sliding_window": 4096,
            "sliding_window_pattern": 6,
            "rms_norm_eps": 1e-6,
            "max_position_embeddings": 131072,
            "tie_word_embeddings": True,
            "query_pre_attn_scalar": 256,
        }
        cfg = {**defaults, **{k: v for k, v in cfg.items() if v is not None}}
        # The global/local layer plan ships either as sliding_window_pattern
        # (config.json) or as an explicit layer_types list (newer HF).
        pattern = int(cfg.get("sliding_window_pattern") or 0)
        lt = cfg.get("layer_types")
        if lt and "full_attention" in lt:
            pattern = lt.index("full_attention") + 1
        return ModelConfig(
            name=cfg.get("model_type", "gemma3"),
            vocab_size=cfg["vocab_size"],
            hidden_size=cfg["hidden_size"],
            intermediate_size=cfg["intermediate_size"],
            num_layers=cfg["num_hidden_layers"],
            num_heads=cfg["num_attention_heads"],
            num_kv_heads=cfg["num_key_value_heads"],
            head_dim=cfg["head_dim"],
            rope_theta=cfg["rope_theta"],
            rms_eps=cfg["rms_norm_eps"],
            max_position=cfg["max_position_embeddings"],
            tie_word_embeddings=cfg["tie_word_embeddings"],
            qk_norm=True,
            sliding_window=int(cfg["sliding_window"] or 0),
            window_pattern=pattern,
            rope_local_theta=float(cfg["rope_local_base_freq"] or 0.0),
            rope_scaling=_rope_scaling(cfg.get("rope_scaling")),
            hidden_act="gelu_tanh",
            norm_offset=True,
            post_norms=True,
            embed_scale=True,
            query_pre_attn_scalar=float(cfg["query_pre_attn_scalar"] or 0.0),
        )

    @staticmethod
    def gemma3_1b() -> "ModelConfig":
        """Gemma-3 1B text (HF google/gemma-3-1b-pt config.json)."""
        return ModelConfig(
            name="gemma3-1b",
            vocab_size=262144,
            hidden_size=1152,
            intermediate_size=6912,
            num_layers=26,
            num_heads=4,
            num_kv_heads=1,
            head_dim=256,
            rope_theta=1_000_000.0,
            rms_eps=1e-6,
            max_position=32768,
            tie_word_embeddings=True,
            qk_norm=True,
            sliding_window=512,
            window_pattern=6,
            rope_local_theta=10000.0,
            hidden_act="gelu_tanh",
            norm_offset=True,
            post_norms=True,
            embed_scale=True,
            query_pre_attn_scalar=256.0,
        )

    @staticmethod
    def tiny_gemma_test(vocab_size: int = 384) -> "ModelConfig":
        """Hermetic Gemma-3-style test model: every family knob on, with a
        window pattern that exercises local AND global layers."""
        return ModelConfig(
            name="tiny-gemma-test",
            vocab_size=vocab_size,
            hidden_size=64,
            intermediate_size=128,
            num_layers=4,
            num_heads=4,
            num_kv_heads=2,
            head_dim=16,
            rope_theta=1_000_000.0,
            rms_eps=1e-6,
            max_position=512,
            tie_word_embeddings=True,
            qk_norm=True,
            sliding_window=32,
            window_pattern=2,
            rope_local_theta=10000.0,
            hidden_act="gelu_tanh",
            norm_offset=True,
            post_norms=True,
            embed_scale=True,
            # Deliberately != head_dim so the score-scale fold is a real
            # multiplier in the tests (27B-style configs have qpa 168 vs
            # head_dim 128; equal values would make the fold a no-op).
            query_pre_attn_scalar=32.0,
        )

    @staticmethod
    def mistral_7b() -> "ModelConfig":
        """Mistral-7B-v0.1 (HF mistralai/Mistral-7B-v0.1): Llama-shaped
        with 4096-token sliding-window attention."""
        return ModelConfig(
            name="mistral-7b",
            vocab_size=32000,
            hidden_size=4096,
            intermediate_size=14336,
            num_layers=32,
            num_heads=32,
            num_kv_heads=8,
            head_dim=128,
            rope_theta=10000.0,
            max_position=32768,
            sliding_window=4096,
        )

    @staticmethod
    def qwen3_06b() -> "ModelConfig":
        """Qwen3-0.6B (HF Qwen/Qwen3-0.6B config.json): QK-norm, no qkv
        bias, explicit head_dim 128."""
        return ModelConfig(
            name="qwen3-0.6b",
            vocab_size=151936,
            hidden_size=1024,
            intermediate_size=3072,
            num_layers=28,
            num_heads=16,
            num_kv_heads=8,
            head_dim=128,
            rope_theta=1000000.0,
            rms_eps=1e-6,
            max_position=40960,
            tie_word_embeddings=True,
            qk_norm=True,
        )

    # -- presets ------------------------------------------------------------
    @staticmethod
    def tiny_test(vocab_size: int = 384) -> "ModelConfig":
        """Hermetic test model (pairs with the byte-level ToyTokenizer)."""
        return ModelConfig(
            name="tiny-test",
            vocab_size=vocab_size,
            hidden_size=64,
            intermediate_size=128,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            head_dim=16,
            rope_theta=10000.0,
            max_position=512,
        )

    @staticmethod
    def tiny_moe_test(vocab_size: int = 384) -> "ModelConfig":
        """Hermetic Mixtral-style MoE test model."""
        return ModelConfig(
            name="tiny-moe-test",
            vocab_size=vocab_size,
            hidden_size=64,
            intermediate_size=96,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            head_dim=16,
            rope_theta=10000.0,
            max_position=512,
            num_experts=4,
            num_experts_per_tok=2,
        )

    @staticmethod
    def tiny_mla_test(vocab_size: int = 384) -> "ModelConfig":
        """Hermetic DeepSeek-style test model: MLA + shared/routed experts
        with sigmoid gating and one leading dense layer (the V3/R1 layer
        plan in miniature)."""
        return ModelConfig(
            name="tiny-mla-test",
            vocab_size=vocab_size,
            hidden_size=64,
            intermediate_size=128,
            num_layers=3,
            num_heads=4,
            num_kv_heads=4,
            head_dim=16,
            rope_theta=10000.0,
            max_position=512,
            kv_lora_rank=32,
            q_lora_rank=48,
            qk_nope_head_dim=16,
            qk_rope_head_dim=8,
            v_head_dim=16,
            num_experts=4,
            num_experts_per_tok=2,
            n_shared_experts=1,
            moe_intermediate_size=48,
            first_k_dense_replace=1,
            gating="sigmoid",
            routed_scaling_factor=2.5,
        )

    @staticmethod
    def _deepseek_yarn(mscale: float) -> "object":
        from dynamo_tpu.ops.rope import RopeScaling

        return RopeScaling(
            kind="yarn",
            factor=40.0,
            original_max_position=4096,
            beta_fast=32.0,
            beta_slow=1.0,
            mscale=mscale,
            mscale_all_dim=mscale,
        )

    @staticmethod
    def deepseek_v2_lite() -> "ModelConfig":
        """DeepSeek-V2-Lite 15.7B (MLA, no q-lora, softmax gating)."""
        return ModelConfig(
            name="deepseek-v2-lite",
            vocab_size=102400,
            hidden_size=2048,
            intermediate_size=10944,
            num_layers=27,
            num_heads=16,
            num_kv_heads=16,
            head_dim=128,
            rope_theta=10000.0,
            max_position=163840,
            kv_lora_rank=512,
            q_lora_rank=0,
            qk_nope_head_dim=128,
            qk_rope_head_dim=64,
            v_head_dim=128,
            num_experts=64,
            num_experts_per_tok=6,
            n_shared_experts=2,
            moe_intermediate_size=1408,
            first_k_dense_replace=1,
            gating="softmax",
            norm_topk_prob=False,
            routed_scaling_factor=1.0,
            n_group=1,
            topk_group=1,
            rope_scaling=ModelConfig._deepseek_yarn(0.707),
        )

    @staticmethod
    def deepseek_r1() -> "ModelConfig":
        """DeepSeek-R1/V3 671B (MLA + q-lora, sigmoid gating, 256 experts)
        — the BASELINE.md stage-5 target; serve ep×tp-sharded."""
        return ModelConfig(
            name="deepseek-r1",
            vocab_size=129280,
            hidden_size=7168,
            intermediate_size=18432,
            num_layers=61,
            num_heads=128,
            num_kv_heads=128,
            head_dim=128,
            rope_theta=10000.0,
            max_position=163840,
            kv_lora_rank=512,
            q_lora_rank=1536,
            qk_nope_head_dim=128,
            qk_rope_head_dim=64,
            v_head_dim=128,
            num_experts=256,
            num_experts_per_tok=8,
            n_shared_experts=1,
            moe_intermediate_size=2048,
            first_k_dense_replace=3,
            gating="sigmoid",
            norm_topk_prob=True,
            routed_scaling_factor=2.5,
            n_group=8,
            topk_group=4,
            rope_scaling=ModelConfig._deepseek_yarn(1.0),
        )

    @staticmethod
    def mixtral_8x7b() -> "ModelConfig":
        return ModelConfig(
            name="mixtral-8x7b",
            vocab_size=32000,
            hidden_size=4096,
            intermediate_size=14336,
            num_layers=32,
            num_heads=32,
            num_kv_heads=8,
            head_dim=128,
            rope_theta=1e6,
            max_position=32768,
            num_experts=8,
            num_experts_per_tok=2,
        )

    @staticmethod
    def llama3_8b() -> "ModelConfig":
        return ModelConfig(
            name="llama3-8b",
            vocab_size=128256,
            hidden_size=4096,
            intermediate_size=14336,
            num_layers=32,
            num_heads=32,
            num_kv_heads=8,
            head_dim=128,
            rope_theta=500000.0,
            max_position=8192,
        )

    @staticmethod
    def llama31_8b() -> "ModelConfig":
        from dynamo_tpu.ops.rope import RopeScaling

        return ModelConfig(
            name="llama3.1-8b",
            vocab_size=128256,
            hidden_size=4096,
            intermediate_size=14336,
            num_layers=32,
            num_heads=32,
            num_kv_heads=8,
            head_dim=128,
            rope_theta=500000.0,
            max_position=131072,
            rope_scaling=RopeScaling(
                factor=8.0,
                low_freq_factor=1.0,
                high_freq_factor=4.0,
                original_max_position=8192,
            ),
        )

    @staticmethod
    def llama32_1b() -> "ModelConfig":
        from dynamo_tpu.ops.rope import RopeScaling

        return ModelConfig(
            name="llama3.2-1b",
            vocab_size=128256,
            hidden_size=2048,
            intermediate_size=8192,
            num_layers=16,
            num_heads=32,
            num_kv_heads=8,
            head_dim=64,
            rope_theta=500000.0,
            max_position=131072,
            tie_word_embeddings=True,
            rope_scaling=RopeScaling(
                factor=32.0,
                low_freq_factor=1.0,
                high_freq_factor=4.0,
                original_max_position=8192,
            ),
        )

    @staticmethod
    def llama3_70b() -> "ModelConfig":
        return ModelConfig(
            name="llama3-70b",
            vocab_size=128256,
            hidden_size=8192,
            intermediate_size=28672,
            num_layers=80,
            num_heads=64,
            num_kv_heads=8,
            head_dim=128,
            rope_theta=500000.0,
            max_position=8192,
        )

    @staticmethod
    def qwen25_05b() -> "ModelConfig":
        return ModelConfig(
            name="qwen2.5-0.5b",
            vocab_size=151936,
            hidden_size=896,
            intermediate_size=4864,
            num_layers=24,
            num_heads=14,
            num_kv_heads=2,
            head_dim=64,
            rope_theta=1000000.0,
            max_position=32768,
            tie_word_embeddings=True,
            qkv_bias=True,
        )

    def scaled(self, **kwargs) -> "ModelConfig":
        return replace(self, **kwargs)


PRESETS = {
    "tiny-test": ModelConfig.tiny_test,
    "tiny-moe-test": ModelConfig.tiny_moe_test,
    "tiny-mla-test": ModelConfig.tiny_mla_test,
    "deepseek-v2-lite": ModelConfig.deepseek_v2_lite,
    "deepseek-r1": ModelConfig.deepseek_r1,
    "llama3-8b": ModelConfig.llama3_8b,
    "llama3.1-8b": ModelConfig.llama31_8b,
    "llama3.2-1b": ModelConfig.llama32_1b,
    "llama3-70b": ModelConfig.llama3_70b,
    "mixtral-8x7b": ModelConfig.mixtral_8x7b,
    "qwen2.5-0.5b": ModelConfig.qwen25_05b,
    "qwen3-0.6b": ModelConfig.qwen3_06b,
    "mistral-7b": ModelConfig.mistral_7b,
    "gemma3-1b": ModelConfig.gemma3_1b,
    "tiny-gemma-test": ModelConfig.tiny_gemma_test,
}

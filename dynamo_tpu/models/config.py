"""Model architecture configs.

Covers the Llama family tree (Llama-2/3/3.x, TinyLlama, Qwen2 via qkv_bias,
DeepSeek-R1-Distill-Llama) — the architectures named in BASELINE.md's
progression. Loadable from a HF checkout's config.json.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, replace
from pathlib import Path


def _rope_scaling(d):
    from dynamo_tpu.ops.rope import RopeScaling

    return RopeScaling.from_hf(d)


@dataclass(frozen=True)
class ModelConfig:
    name: str = "llama"
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    rope_theta: float = 500000.0
    rms_eps: float = 1e-5
    max_position: int = 8192
    tie_word_embeddings: bool = False
    qkv_bias: bool = False  # Qwen2-style
    # Mixtral-style sparse MoE MLP: num_experts > 0 swaps each layer's
    # SwiGLU for top-k routed experts (models/moe.py; ep/tp sharding).
    num_experts: int = 0
    num_experts_per_tok: int = 2
    # Llama-3.1+ long-context rope scaling (ops/rope.py RopeScaling).
    rope_scaling: "object | None" = None

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0

    @staticmethod
    def from_hf(model_dir: str) -> "ModelConfig":
        cfg = json.loads((Path(model_dir) / "config.json").read_text())
        num_heads = cfg["num_attention_heads"]
        hidden = cfg["hidden_size"]
        arch = (cfg.get("architectures") or ["LlamaForCausalLM"])[0]
        return ModelConfig(
            name=cfg.get("model_type", "llama"),
            vocab_size=cfg["vocab_size"],
            hidden_size=hidden,
            intermediate_size=cfg["intermediate_size"],
            num_layers=cfg["num_hidden_layers"],
            num_heads=num_heads,
            num_kv_heads=cfg.get("num_key_value_heads", num_heads),
            head_dim=cfg.get("head_dim", hidden // num_heads),
            rope_theta=cfg.get("rope_theta", 10000.0),
            rms_eps=cfg.get("rms_norm_eps", 1e-5),
            max_position=cfg.get("max_position_embeddings", 8192),
            tie_word_embeddings=cfg.get("tie_word_embeddings", False),
            qkv_bias="Qwen2" in arch,
            num_experts=cfg.get("num_local_experts", 0),
            num_experts_per_tok=cfg.get("num_experts_per_tok", 2),
            rope_scaling=_rope_scaling(cfg.get("rope_scaling")),
        )

    # -- presets ------------------------------------------------------------
    @staticmethod
    def tiny_test(vocab_size: int = 384) -> "ModelConfig":
        """Hermetic test model (pairs with the byte-level ToyTokenizer)."""
        return ModelConfig(
            name="tiny-test",
            vocab_size=vocab_size,
            hidden_size=64,
            intermediate_size=128,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            head_dim=16,
            rope_theta=10000.0,
            max_position=512,
        )

    @staticmethod
    def tiny_moe_test(vocab_size: int = 384) -> "ModelConfig":
        """Hermetic Mixtral-style MoE test model."""
        return ModelConfig(
            name="tiny-moe-test",
            vocab_size=vocab_size,
            hidden_size=64,
            intermediate_size=96,
            num_layers=2,
            num_heads=4,
            num_kv_heads=2,
            head_dim=16,
            rope_theta=10000.0,
            max_position=512,
            num_experts=4,
            num_experts_per_tok=2,
        )

    @staticmethod
    def mixtral_8x7b() -> "ModelConfig":
        return ModelConfig(
            name="mixtral-8x7b",
            vocab_size=32000,
            hidden_size=4096,
            intermediate_size=14336,
            num_layers=32,
            num_heads=32,
            num_kv_heads=8,
            head_dim=128,
            rope_theta=1e6,
            max_position=32768,
            num_experts=8,
            num_experts_per_tok=2,
        )

    @staticmethod
    def llama3_8b() -> "ModelConfig":
        return ModelConfig(
            name="llama3-8b",
            vocab_size=128256,
            hidden_size=4096,
            intermediate_size=14336,
            num_layers=32,
            num_heads=32,
            num_kv_heads=8,
            head_dim=128,
            rope_theta=500000.0,
            max_position=8192,
        )

    @staticmethod
    def llama31_8b() -> "ModelConfig":
        from dynamo_tpu.ops.rope import RopeScaling

        return ModelConfig(
            name="llama3.1-8b",
            vocab_size=128256,
            hidden_size=4096,
            intermediate_size=14336,
            num_layers=32,
            num_heads=32,
            num_kv_heads=8,
            head_dim=128,
            rope_theta=500000.0,
            max_position=131072,
            rope_scaling=RopeScaling(
                factor=8.0,
                low_freq_factor=1.0,
                high_freq_factor=4.0,
                original_max_position=8192,
            ),
        )

    @staticmethod
    def llama32_1b() -> "ModelConfig":
        from dynamo_tpu.ops.rope import RopeScaling

        return ModelConfig(
            name="llama3.2-1b",
            vocab_size=128256,
            hidden_size=2048,
            intermediate_size=8192,
            num_layers=16,
            num_heads=32,
            num_kv_heads=8,
            head_dim=64,
            rope_theta=500000.0,
            max_position=131072,
            tie_word_embeddings=True,
            rope_scaling=RopeScaling(
                factor=32.0,
                low_freq_factor=1.0,
                high_freq_factor=4.0,
                original_max_position=8192,
            ),
        )

    @staticmethod
    def llama3_70b() -> "ModelConfig":
        return ModelConfig(
            name="llama3-70b",
            vocab_size=128256,
            hidden_size=8192,
            intermediate_size=28672,
            num_layers=80,
            num_heads=64,
            num_kv_heads=8,
            head_dim=128,
            rope_theta=500000.0,
            max_position=8192,
        )

    @staticmethod
    def qwen25_05b() -> "ModelConfig":
        return ModelConfig(
            name="qwen2.5-0.5b",
            vocab_size=151936,
            hidden_size=896,
            intermediate_size=4864,
            num_layers=24,
            num_heads=14,
            num_kv_heads=2,
            head_dim=64,
            rope_theta=1000000.0,
            max_position=32768,
            tie_word_embeddings=True,
            qkv_bias=True,
        )

    def scaled(self, **kwargs) -> "ModelConfig":
        return replace(self, **kwargs)


PRESETS = {
    "tiny-test": ModelConfig.tiny_test,
    "tiny-moe-test": ModelConfig.tiny_moe_test,
    "llama3-8b": ModelConfig.llama3_8b,
    "llama3.1-8b": ModelConfig.llama31_8b,
    "llama3.2-1b": ModelConfig.llama32_1b,
    "llama3-70b": ModelConfig.llama3_70b,
    "mixtral-8x7b": ModelConfig.mixtral_8x7b,
    "qwen2.5-0.5b": ModelConfig.qwen25_05b,
}

"""Vision encoder: ViT-style patch encoder producing text-space soft prompts.

Role of the reference's multimodal encode worker's model (reference:
examples/multimodal — an encode_worker runs a vision encoder ahead of the
decode worker and hands its embeddings over; README.md:18-30). TPU
mapping: a compact pre-LN ViT in pure JAX — patchify is a reshape (no
conv), attention/MLP are plain matmuls the MXU eats directly, and the
final projection lands in the language model's hidden space so the
engine's soft-prompt prefill (models/llama.py `embeds`) can splice the
patches in place of placeholder tokens.

Deterministic seeded init (like ModelConfig.tiny_test) keeps multimodal
tests hermetic; real checkpoints load through the same param tree.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp


@dataclass(frozen=True)
class VisionConfig:
    image_size: int = 32
    patch_size: int = 8
    hidden_size: int = 64
    num_layers: int = 2
    num_heads: int = 2
    mlp_ratio: int = 4
    out_dim: int = 64          # language-model hidden size
    ln_eps: float = 1e-5

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.patch_size * self.patch_size * 3

    @staticmethod
    def tiny_test(out_dim: int = 64) -> "VisionConfig":
        return VisionConfig(out_dim=out_dim)


def init_vision_params(key, cfg: VisionConfig, dtype=jnp.float32) -> dict:
    k = iter(jax.random.split(key, 4 + 8 * cfg.num_layers))

    def dense(shape, scale=None):
        scale = scale if scale is not None else shape[0] ** -0.5
        return (jax.random.normal(next(k), shape) * scale).astype(dtype)

    D, H = cfg.hidden_size, cfg.num_heads
    params = {
        "patch_proj": dense((cfg.patch_dim, D)),
        "pos_embed": dense((cfg.num_patches, D), scale=0.02),
        "ln_f": jnp.ones(D, dtype),
        "out_proj": dense((D, cfg.out_dim)),
        "layers": [],
    }
    for _ in range(cfg.num_layers):
        params["layers"].append(
            {
                "ln_attn": jnp.ones(D, dtype),
                "wq": dense((D, D)),
                "wk": dense((D, D)),
                "wv": dense((D, D)),
                "wo": dense((D, D)),
                "ln_mlp": jnp.ones(D, dtype),
                "w_up": dense((D, cfg.mlp_ratio * D)),
                "w_down": dense((cfg.mlp_ratio * D, D)),
            }
        )
    return params


def _ln(x, g, eps):
    mu = x.mean(-1, keepdims=True)
    var = ((x - mu) ** 2).mean(-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * g


def encode_image(
    params: dict, cfg: VisionConfig, image: jnp.ndarray
) -> jnp.ndarray:
    """[image_size, image_size, 3] float in [0,1] → [num_patches, out_dim]
    soft-prompt embeddings (bidirectional attention over patches)."""
    S, P = cfg.image_size, cfg.patch_size
    n = S // P
    # Patchify as a reshape/transpose — XLA fuses this into the first matmul.
    patches = (
        image.reshape(n, P, n, P, 3)
        .transpose(0, 2, 1, 3, 4)
        .reshape(cfg.num_patches, cfg.patch_dim)
    )
    x = patches @ params["patch_proj"] + params["pos_embed"]

    D, H = cfg.hidden_size, cfg.num_heads
    hd = D // H
    for layer in params["layers"]:
        h = _ln(x, layer["ln_attn"], cfg.ln_eps)
        q = (h @ layer["wq"]).reshape(-1, H, hd)
        k = (h @ layer["wk"]).reshape(-1, H, hd)
        v = (h @ layer["wv"]).reshape(-1, H, hd)
        scores = jnp.einsum("qhd,khd->hqk", q, k) / jnp.sqrt(hd)
        attn = jnp.einsum(
            "hqk,khd->qhd", jax.nn.softmax(scores, axis=-1), v
        ).reshape(-1, D)
        x = x + attn @ layer["wo"]
        h = _ln(x, layer["ln_mlp"], cfg.ln_eps)
        x = x + jax.nn.gelu(h @ layer["w_up"]) @ layer["w_down"]

    return _ln(x, params["ln_f"], cfg.ln_eps) @ params["out_proj"]

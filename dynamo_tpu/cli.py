"""dynamo-tpu CLI: launch the framework from a shell.

Mirrors the reference's ``dynamo-run`` input/output matrix (reference:
launch/dynamo-run/src/opt.rs:22-188, lib.rs:51-326):

  dynamo-tpu run [--in {http,text,batch:FILE,dyn://ns.comp.ep}]
                 [--out {tpu,echo_core,echo_full,dyn}] --model-path REF ...

- ``--in http  --out tpu``   one-process OpenAI server on the local engine
- ``--in http  --out dyn``   frontend only: discover workers via the
                             control plane (``--control-plane ADDR``)
- ``--in dyn://ns.c.e --out tpu``  worker only: serve the engine at that
                             endpoint and register the model
- ``--in text``              interactive chat against the same pipeline
- ``--in batch:FILE``        run a prompt file, report TTFT/throughput
                             (reference: input/batch.rs:143-191)
- ``dynamo-tpu control-plane``  standalone discovery/messaging server
- ``dynamo-tpu planner``        auto-scaler (components/planner)

Model references (``--model-path``): ``preset:NAME`` (random weights, toy
tokenizer), a local HF checkout dir, or ``hf://org/name`` (local hub cache).
"""

from __future__ import annotations

import argparse
import asyncio
import contextlib
import json
import logging
import os
import signal
import sys
import time
from pathlib import Path

logger = logging.getLogger(__name__)

DEFAULT_ENDPOINT = "dyn://dynamo.tpu.generate"


def _parse_mesh(spec: str | None) -> dict[str, int]:
    """``tp=4,dp=2`` → {"tp": 4, "dp": 2}."""
    if not spec:
        return {}
    shape: dict[str, int] = {}
    for part in spec.split(","):
        axis, _, n = part.partition("=")
        if axis not in ("dp", "tp", "sp", "ep") or not n.isdigit():
            raise SystemExit(
                f"bad --mesh entry {part!r} (want axis=N, axes dp/tp/sp/ep)"
            )
        shape[axis] = int(n)
    return shape


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="dynamo-tpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    run = sub.add_parser("run", help="serve / chat / batch")
    run.add_argument(
        "--in", dest="input", default="http",
        help="http | text | batch:FILE | dyn://ns.component.endpoint",
    )
    run.add_argument(
        "--out", dest="output", default="tpu",
        help="tpu | echo_core | echo_full | dyn",
    )
    run.add_argument(
        "--model-path", default="preset:llama3.2-1b",
        help="preset:NAME | HF checkout dir | hf://org/name",
    )
    run.add_argument("--model-name", default=None)
    run.add_argument("--model-type", default="chat",
                     choices=["chat", "embeddings"])
    run.add_argument("--endpoint", default=DEFAULT_ENDPOINT,
                     help="endpoint a local engine serves at")
    run.add_argument("--http-host", default="0.0.0.0")
    run.add_argument("--http-port", type=int, default=8080)
    run.add_argument("--control-plane", default=None, metavar="HOST:PORT",
                     help="join an existing control-plane server")
    run.add_argument("--spawn-control-plane", nargs="?", const="0",
                     default=None, metavar="PORT",
                     help="host a control-plane server in this process")
    run.add_argument("--router-mode", default="round_robin",
                     choices=["round_robin", "random", "kv"])
    run.add_argument("--route-network-aware", action="store_true",
                     help="KV router mode: add the NetKV-style transfer-"
                          "cost term to the selection score — candidates "
                          "pay for moving the non-overlapping prefix over "
                          "their per-link ingest-rate EMA "
                          "(docs/architecture/planner.md)")
    run.add_argument("--mesh", default=None, help="e.g. tp=4 or tp=2,dp=2")
    run.add_argument("--kv-sp", action="store_true",
                     help="shard the KV cache's slot axis over the mesh's "
                          "sp axis: max-model-len beyond one device's "
                          "cache (long-context mode; needs --mesh sp=N)")
    # Multi-host engine bootstrap (reference: MultiNodeConfig
    # lib/llm/src/engines.rs:42-60; launch/dynamo-run/src/lib.rs:176-258):
    # every node runs the same command with its own --node-rank; the mesh
    # then spans all nodes' chips (parallel/multihost.py).
    run.add_argument("--coordinator", default=None, metavar="HOST:PORT",
                     help="jax.distributed coordinator (leader) address")
    run.add_argument("--num-nodes", type=int, default=1)
    run.add_argument("--node-rank", type=int, default=0)
    run.add_argument("--dtype", default="bfloat16")
    run.add_argument("--quant", default=None, choices=["int8"],
                     help="weight-only quantization (halves decode's "
                          "weight-streaming bytes; ops/quant.py)")
    run.add_argument("--kv-quant", default=None, choices=["int8"],
                     help="KV-cache quantization — the per-tier precision "
                          "policy's G1 knob (docs/architecture/"
                          "kv_quant.md): int8 KV blocks with per-block "
                          "scales, dequantized in-kernel on the ragged "
                          "path (requires --unified); roughly halves "
                          "decode's KV HBM reads and doubles KV capacity "
                          "per chip. G2 host / G3 disk KVBM tiers "
                          "quantize independently via their layout "
                          "(always int8 when a quantized layout is "
                          "configured), whatever this G1 choice is")
    run.add_argument("--weight-quant", default=None, metavar="POLICY",
                     help="per-matmul weight-quantization policy (docs/"
                          "architecture/weight_quant.md): 'int8' or 'fp8' "
                          "quantizes every site; 'attn=int8,mlp=fp8' "
                          "selects per site group (sites: embedding, "
                          "attn, mlp, unembed). Quantize-on-load — the "
                          "bf16 copy never materializes resident; scales "
                          "ride as jit state beside the matrices. Zero "
                          "new XLA programs (requires --unified; composes "
                          "with --kv-quant; supersedes --quant)")
    run.add_argument("--speculative-k", type=int, default=0,
                     help="prompt-lookup speculative decoding: draft up to "
                          "K tokens per step from the sequence's own "
                          "history, verify in one forward (0 = off)")
    run.add_argument("--max-num-seqs", type=int, default=32)
    run.add_argument("--max-model-len", type=int, default=2048)
    run.add_argument("--num-blocks", type=int, default=2048)
    run.add_argument("--kv-cache-block-size", type=int, default=16)
    # --decode-chunk (the phased fused-decode ladder knob) is GONE with
    # the phase-alternating engine: argparse rejects it loudly
    # ("unrecognized arguments"), which is the deprecation contract —
    # a deploy still passing it must be updated, not silently ignored.
    run.add_argument("--prefill-batch", type=int, default=4)
    run.add_argument("--unified", action="store_true",
                     help="DEPRECATED no-op: unified single-dispatch "
                     "serving is the ONLY engine path now (the "
                     "phase-alternating engine was deleted; docs/"
                     "architecture/unified_step.md)")
    run.add_argument("--unified-token-budget", type=int, default=256,
                     help="max tokens per unified dispatch (snapped to a "
                     "power-of-two ladder)")
    run.add_argument("--unified-prefill-quantum", type=int, default=64,
                     help="prefill tokens per sequence per unified step "
                     "while decode lanes share the batch (decode-ITL "
                     "bound); also the budget reserved for prefill; "
                     "with --coloc adaptive this is only the STARTING "
                     "quantum — the controller owns it from there")
    # SLO-aware co-location (engine/coloc.py; ROADMAP #3).
    run.add_argument("--itl-slo-ms", type=float, default=0.0,
                     help="decode inter-token-latency target in ms the "
                     "co-location controller measures each unified "
                     "dispatch against (0 = no SLO: no violation "
                     "accounting, no adaptation)")
    run.add_argument("--coloc", choices=["static", "adaptive"],
                     default="static",
                     help="unified-step prefill-quantum policy: static "
                     "keeps --unified-prefill-quantum hand-tuned; "
                     "adaptive runs the AIMD feedback loop against "
                     "--itl-slo-ms (grow on headroom, shrink on SLO "
                     "pressure, floor at --coloc-min-quantum) plus "
                     "phase-aware prefill admission")
    run.add_argument("--coloc-min-quantum", type=int, default=16,
                     help="adaptive-quantum floor: minimum prefill "
                     "tokens per unified step, so prefill TTFT "
                     "progress never fully starves under decode SLO "
                     "pressure")
    run.add_argument("--max-prefill-backlog-tokens", type=int, default=0,
                     help="HTTP admission watermark (phase-aware): "
                     "reject (429) while the engine's un-prefilled "
                     "backlog exceeds this many prompt TOKENS (0 = "
                     "off; fed by live engine readiness)")
    run.add_argument("--context-length", type=int, default=None,
                     help="override the card/engine context limit")
    run.add_argument("--no-warmup", action="store_true",
                     help="skip ahead-of-traffic shape compilation")
    run.add_argument("--compile-cache-dir", default="auto",
                     metavar="DIR|auto|none",
                     help="persistent XLA compile cache base dir "
                          "(fingerprint-namespaced; warmed programs "
                          "replay from disk on relaunch). auto = "
                          "$DYNAMO_TPU_COMPILE_CACHE_DIR, else under the "
                          "model dir, else ~/.cache/dynamo_tpu/xla; "
                          "none disables")
    run.add_argument("--shape-manifest", default=None, metavar="FILE.json",
                     help="shape-manifest path (records the shapes "
                          "serving executes; warmup compiles exactly "
                          "that set first). Default: alongside the "
                          "compile cache")
    # Overload-safe serving (docs/architecture/overload_and_drain.md).
    run.add_argument("--max-inflight", type=int, default=256,
                     help="HTTP admission gate: max concurrently admitted "
                          "requests; excess gets 429 + Retry-After")
    run.add_argument("--max-engine-waiting", type=int, default=0,
                     help="HTTP admission watermark: reject (429) while "
                          "the engine already has this many requests "
                          "queued (0 = off; fed by live engine metrics)")
    run.add_argument("--default-request-class", default="interactive",
                     choices=["interactive", "batch"],
                     help="SLO class assumed when the client sends no "
                          "X-Request-Class header (docs/architecture/"
                          "ingress_scale.md)")
    run.add_argument("--batch-watermark-scale", type=float, default=0.5,
                     help="batch-class admission watermark scale: batch "
                          "requests 429 at this fraction of every "
                          "configured watermark/cap (cheapest-first "
                          "degradation; 1.0 = class-blind)")
    run.add_argument("--default-deadline-s", type=float, default=0.0,
                     help="per-request deadline applied when the client "
                          "sends no X-Request-Timeout-Ms header (0 = "
                          "none); expired work is cancelled at every hop")
    run.add_argument("--max-waiting", type=int, default=128,
                     help="engine waiting-list depth bound: over it the "
                          "OLDEST waiter is shed with a typed error "
                          "(0 = unbounded)")
    run.add_argument("--max-queue-delay-s", type=float, default=0.0,
                     help="engine waiting-list age bound: waiters older "
                          "than this are shed (0 = unbounded)")
    run.add_argument("--drain-grace-s", type=float, default=30.0,
                     help="graceful-drain budget on SIGTERM / the "
                          "control-plane drain verb: in-flight requests "
                          "get this long to finish before exit")
    run.add_argument("--health-port", type=int, default=0,
                     help="worker-mode health/metrics HTTP port (0 = off): "
                          "/health flips 503 while warming or draining — "
                          "the k8s readinessProbe target (also serves the "
                          "/debug/steps|trace|profile surface)")
    run.add_argument("--profile-dir", default=None, metavar="DIR",
                     help="enable on-demand TPU profiling: /debug/profile"
                          "?seconds=N and the control-plane profile verb "
                          "capture jax.profiler windows under DIR without "
                          "a restart (default $DYNTPU_PROFILE_DIR; unset "
                          "= endpoint disabled — see docs/architecture/"
                          "observability.md security note)")
    run.add_argument("--concurrency", type=int, default=32,
                     help="batch mode: in-flight request cap")
    run.add_argument("--max-tokens", type=int, default=128,
                     help="text/batch mode: generation cap per request")
    run.add_argument("--config", default=None, metavar="FILE.yaml",
                     help="layered deployment config (sections: Frontend, "
                          "Engine, Router; Common + common-configs "
                          "inheritance)")
    run.add_argument("--set", dest="overrides", action="append", default=[],
                     metavar="Component.key=value",
                     help="config override, highest precedence (repeatable)")
    run.add_argument("-v", "--verbose", action="store_true")

    cp = sub.add_parser("control-plane", help="standalone control plane")
    cp.add_argument("--host", default="0.0.0.0")
    cp.add_argument("--port", type=int, default=6380)
    cp.add_argument("--token", default=None)
    cp.add_argument("-v", "--verbose", action="store_true")

    mx = sub.add_parser("metrics", help="Prometheus exporter for worker load")
    mx.add_argument("--control-plane", required=True, metavar="HOST:PORT")
    mx.add_argument("--namespace", default="dynamo")
    mx.add_argument("--component", default="tpu")
    mx.add_argument("--host", default="0.0.0.0")
    mx.add_argument("--port", type=int, default=9091)
    mx.add_argument(
        "--push-url", default=None, metavar="URL",
        help="also push to a Prometheus PushGateway at URL (scrape-"
        "hostile networks; reference components/metrics push mode)",
    )
    mx.add_argument("--push-interval", type=float, default=15.0)
    mx.add_argument("--push-job", default="dynamo_tpu")
    mx.add_argument("-v", "--verbose", action="store_true")

    ap = sub.add_parser("api-store", help="deployment/artifact REST registry")
    ap.add_argument("--control-plane", required=True, metavar="HOST:PORT")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8090)
    ap.add_argument("-v", "--verbose", action="store_true")

    rt = sub.add_parser("router", help="standalone KV-aware router service")
    rt.add_argument("--control-plane", required=True, metavar="HOST:PORT")
    rt.add_argument("--endpoint", required=True,
                    metavar="dyn://ns.component.endpoint",
                    help="target worker endpoint to route to")
    rt.add_argument("--component", default="router",
                    help="component name the routed endpoint is served on")
    rt.add_argument("--block-size", type=int, default=16)
    rt.add_argument("--route-network-aware", action="store_true",
                    help="add the NetKV-style transfer-cost term to the "
                         "KV selection score (docs/architecture/planner.md)")
    rt.add_argument("--replica-id", type=int, default=0,
                    help="this router replica's id (docs/architecture/"
                         "ingress_scale.md): run one router process per "
                         "replica on the SAME --component; the id labels "
                         "per-replica route audits so route_audit.py can "
                         "bound each replica's predicted-vs-actual error")
    rt.add_argument("-v", "--verbose", action="store_true")

    pl = sub.add_parser("planner", help="auto-scaler (queue/KV watermarks)")
    pl.add_argument("--control-plane", required=True, metavar="HOST:PORT")
    pl.add_argument("--namespace", default="dynamo")
    pl.add_argument("--min-workers", type=int, default=1)
    pl.add_argument("--max-workers", type=int, default=4, help="chip budget")
    pl.add_argument("--adjustment-interval", type=float, default=10.0)
    pl.add_argument("--metric-interval", type=float, default=1.0)
    pl.add_argument("--worker-cmd", required=True,
                    help="shell command template spawning one worker")
    pl.add_argument("--state-path", default=None, metavar="FILE.json",
                    help="checkpoint for crash/restart resume (default "
                         "~/.dynamo_tpu/state/<namespace>.json)")
    pl.add_argument("--profile", default=None, metavar="BENCH.json",
                    help="perf profile (bench.py output) enabling "
                         "SLA-driven scaling")
    pl.add_argument("--ttft-sla-ms", type=float, default=None)
    pl.add_argument("--itl-sla-ms", type=float, default=None)
    pl.add_argument("--decision-log", default=None, metavar="FILE.jsonl",
                    help="append one JSONL line per scaling decision "
                         "(time-series artifact; reference planner logs "
                         "these to TensorBoard)")
    # Two-pool fleet mode (ROADMAP #4, docs/architecture/planner.md):
    # independent prefill (queue depth/age) and decode (KV util + ITL)
    # pools; --worker-cmd spawns DECODE workers, --prefill-worker-cmd
    # spawns prefill workers.
    pl.add_argument("--two-pool", action="store_true",
                    help="scale prefill and decode pools independently "
                         "(docs/architecture/planner.md)")
    pl.add_argument("--prefill-worker-cmd", default=None,
                    help="shell command template spawning one PREFILL "
                         "worker (required with --two-pool)")
    pl.add_argument("--prefill-min-workers", type=int, default=1)
    pl.add_argument("--prefill-max-workers", type=int, default=4)
    pl.add_argument("--prefill-queue-age-up-s", type=float, default=5.0,
                    help="oldest queued prefill older than this scales "
                         "the prefill pool up at ANY depth")
    pl.add_argument("--decode-component", default="tpu",
                    help="component whose metrics plane scores the "
                         "decode pool")
    pl.add_argument("--decode-itl-up-ms", type=float, default=None,
                    help="decode pool scales up when the pool ITL EMA "
                         "exceeds this (off by default)")
    pl.add_argument("-v", "--verbose", action="store_true")

    op = sub.add_parser(
        "operator",
        help="reconcile api-store deployment specs into k8s objects",
    )
    op.add_argument("--control-plane", required=True, metavar="HOST:PORT")
    op.add_argument("--namespace", default="dynamo",
                    help="k8s namespace the children live in")
    op.add_argument("--interval", type=float, default=30.0,
                    help="resync interval seconds (reconciles are "
                         "watch-driven; this is the missed-event net)")
    op.add_argument("--kubectl", default="kubectl",
                    help="kubectl binary to drive the cluster with")
    op.add_argument("-v", "--verbose", action="store_true")
    return p


def _apply_platform_env() -> None:
    """Honor JAX_PLATFORMS even when the interpreter's startup hooks
    (sitecustomize) pre-registered another platform: the env var must
    win, or `JAX_PLATFORMS=cpu dynamo-tpu run --mesh sp=8 ...` silently
    lands on whatever backend was pre-selected. Called from the
    device-using command handlers only — non-device subcommands
    (control-plane, api-store, operator, --help) must not pay the jax
    import."""
    want_platform = os.environ.get("JAX_PLATFORMS")
    if not want_platform:
        return
    import jax

    try:
        jax.config.update("jax_platforms", want_platform)
    except Exception as exc:  # noqa: BLE001 — backend already initialized
        print(
            f"warning: JAX_PLATFORMS={want_platform} did not take "
            f"effect (backend already initialized: {exc}) — running on "
            f"{jax.default_backend()}",
            file=sys.stderr,
        )


def main(argv: list[str] | None = None) -> None:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s",
    )
    if args.cmd == "run":
        _apply_platform_env()
        asyncio.run(_run(args))
    elif args.cmd == "control-plane":
        asyncio.run(_control_plane(args))
    elif args.cmd == "planner":
        asyncio.run(_planner(args))
    elif args.cmd == "metrics":
        asyncio.run(_metrics(args))
    elif args.cmd == "router":
        asyncio.run(_router(args))
    elif args.cmd == "api-store":
        asyncio.run(_api_store(args))
    elif args.cmd == "operator":
        asyncio.run(_operator(args))


# ---------------------------------------------------------------------------
# subcommands
# ---------------------------------------------------------------------------


async def _control_plane(args) -> None:
    from dynamo_tpu.runtime.transports.control_plane import ControlPlaneServer

    server = await ControlPlaneServer(
        host=args.host, port=args.port, token=args.token
    ).start()
    print(f"control plane on {server.address}", flush=True)
    await _wait_for_signal()
    await server.stop()


async def _metrics(args) -> None:
    from dynamo_tpu.llm.metrics_exporter import MetricsExporter
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    drt = await DistributedRuntime.connect(args.control_plane)
    exporter = await MetricsExporter(
        drt,
        namespace=args.namespace,
        component=args.component,
        host=args.host,
        port=args.port,
        push_url=args.push_url,
        push_interval_s=args.push_interval,
        push_job=args.push_job,
    ).start()
    print(f"metrics exporter on {args.host}:{exporter.port}", flush=True)
    try:
        await _wait_for_signal()
    finally:
        await exporter.stop()
        await drt.shutdown()


async def _api_store(args) -> None:
    from dynamo_tpu.runtime.distributed import DistributedRuntime
    from dynamo_tpu.sdk.api_store import ApiStore

    drt = await DistributedRuntime.connect(args.control_plane)
    store = await ApiStore(drt, host=args.host, port=args.port).start()
    print(f"api store on {args.host}:{store.port}", flush=True)
    try:
        await _wait_for_signal()
    finally:
        await store.stop()
        await drt.shutdown()


async def _operator(args) -> None:
    from dynamo_tpu.operator import GraphOperator, KubectlApi
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    drt = await DistributedRuntime.connect(args.control_plane)
    operator = await GraphOperator(
        drt,
        KubectlApi(args.kubectl),
        namespace=args.namespace,
        interval_s=args.interval,
    ).start()
    print("operator reconciling", flush=True)
    try:
        await _wait_for_signal()
    finally:
        await operator.stop()
        await drt.shutdown()


async def _router(args) -> None:
    from dynamo_tpu.llm.kv_router.scheduler import KvRouterConfig
    from dynamo_tpu.llm.router_service import RouterService
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    drt = await DistributedRuntime.connect(args.control_plane)
    service = await RouterService(
        drt,
        args.endpoint,
        component_name=args.component,
        cfg=KvRouterConfig(
            block_size=args.block_size,
            network_aware=args.route_network_aware,
        ),
        replica_id=args.replica_id,
    ).start()
    print(
        f"router service at {service.endpoint_path} "
        f"(replica {args.replica_id})",
        flush=True,
    )
    try:
        await _wait_for_signal()
    finally:
        await service.stop()
        await drt.shutdown()


async def _planner(args) -> None:
    from dynamo_tpu.planner.planner import Planner, PlannerConfig
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    if args.two_pool:
        if args.profile or args.ttft_sla_ms is not None \
                or args.itl_sla_ms is not None:
            # The SLA/profile law is single-pool only; accepting the
            # flags and ignoring them would be exactly the silent half-
            # config the guard below rejects. Two-pool SLA shaping is
            # --decode-itl-up-ms (decode) + the queue-age bound
            # (prefill).
            raise SystemExit(
                "--two-pool does not support --profile/--ttft-sla-ms/"
                "--itl-sla-ms (single-pool SLA law); use "
                "--decode-itl-up-ms and --prefill-queue-age-up-s"
            )
        await _fleet_planner(args)
        return
    has_sla = args.ttft_sla_ms is not None or args.itl_sla_ms is not None
    if bool(args.profile) != has_sla:
        raise SystemExit(
            "SLA scaling needs BOTH --profile and at least one of "
            "--ttft-sla-ms/--itl-sla-ms (got only one half; the other "
            "would be silently ignored)"
        )
    profile = None
    if args.profile:
        from dynamo_tpu.planner.profiles import PerfProfile

        profile = PerfProfile.from_bench_json(args.profile)
    drt = await DistributedRuntime.connect(args.control_plane)
    state_path = args.state_path or str(
        Path.home() / ".dynamo_tpu" / "state" / f"{args.namespace}.json"
    )
    planner = Planner(
        drt,
        PlannerConfig(
            namespace=args.namespace,
            min_workers=args.min_workers,
            max_workers=args.max_workers,
            adjustment_interval_s=args.adjustment_interval,
            metric_interval_s=args.metric_interval,
            state_path=state_path,
            ttft_sla_ms=args.ttft_sla_ms,
            itl_sla_ms=args.itl_sla_ms,
            decision_log_path=args.decision_log,
        ),
        worker_cmd=args.worker_cmd,
        profile=profile,
    )
    await planner.start()
    print("planner running", flush=True)
    try:
        await _wait_for_signal()
    finally:
        await planner.stop()
        await drt.shutdown()


async def _fleet_planner(args) -> None:
    """Two-pool mode (docs/architecture/planner.md): --worker-cmd spawns
    decode workers, --prefill-worker-cmd spawns prefill workers; each
    pool runs its own law + hysteresis over the shared sample loop."""
    from dynamo_tpu.planner.fleet import FleetPlanner, FleetPlannerConfig
    from dynamo_tpu.planner.planner import SubprocessConnector
    from dynamo_tpu.planner.pools import (
        DecodeLaw,
        PoolConfig,
        PrefillLaw,
        default_pools,
    )
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    if not args.prefill_worker_cmd:
        raise SystemExit("--two-pool requires --prefill-worker-cmd")
    drt = await DistributedRuntime.connect(args.control_plane)
    state_path = args.state_path or str(
        Path.home() / ".dynamo_tpu" / "state" / f"{args.namespace}.json"
    )
    prefill_pool, decode_pool = default_pools(
        SubprocessConnector(args.prefill_worker_cmd),
        SubprocessConnector(args.worker_cmd),
        prefill_cfg=PoolConfig(
            name="prefill",
            min_workers=args.prefill_min_workers,
            max_workers=args.prefill_max_workers,
        ),
        decode_cfg=PoolConfig(
            name="decode",
            min_workers=args.min_workers,
            max_workers=args.max_workers,
        ),
        prefill_law=PrefillLaw(age_up_s=args.prefill_queue_age_up_s),
        decode_law=DecodeLaw(itl_up_ms=args.decode_itl_up_ms),
    )
    planner = FleetPlanner(
        drt,
        FleetPlannerConfig(
            namespace=args.namespace,
            decode_component=args.decode_component,
            adjustment_interval_s=args.adjustment_interval,
            metric_interval_s=args.metric_interval,
            state_path=state_path,
            decision_log_path=args.decision_log,
        ),
        prefill_pool,
        decode_pool,
    )
    await planner.start()
    print("fleet planner running (two-pool)", flush=True)
    try:
        await _wait_for_signal()
    finally:
        await planner.stop()
        await drt.shutdown()


#: config-section → args-attribute aliases (section key is dash/underscore
#: insensitive; unknown keys in a known section are rejected loudly).
_CONFIG_SECTIONS = {
    "Run": {"in": "input", "out": "output"},
    "Frontend": {"host": "http_host", "port": "http_port"},
    "Engine": {"block_size": "kv_cache_block_size"},
    "Router": {"mode": "router_mode"},
}


def _apply_config(args) -> None:
    """Layer configuration onto the parsed args. Precedence, highest first:
    `--set Component.key=value` > explicit CLI flags > config file / env >
    argparse defaults (the reference SDK's YAML + --Component.key=value
    override model). "Explicit" is detected by comparing against a
    defaults-only parse, so a flag repeated in the YAML never silently
    loses to the file."""
    from dynamo_tpu.utils.config import load_config

    defaults = vars(build_parser().parse_args(["run"]))

    def apply(cfg, force: bool) -> None:
        for section, aliases in _CONFIG_SECTIONS.items():
            for key, val in cfg.component(section).as_dict().items():
                if section == "Engine" and key == "warmup":
                    # Engine.warmup: false == --no-warmup
                    if force or args.no_warmup == defaults["no_warmup"]:
                        args.no_warmup = not val
                    continue
                attr = aliases.get(key, key)
                if not hasattr(args, attr):
                    raise SystemExit(
                        f"unknown config key {section}.{key} "
                        f"(no matching --{attr.replace('_', '-')} option)"
                    )
                if force or getattr(args, attr) == defaults.get(attr):
                    setattr(args, attr, val)
        unknown = set(cfg.sections()) - set(_CONFIG_SECTIONS)
        if unknown:
            raise SystemExit(
                f"unknown config sections: {', '.join(sorted(unknown))} "
                f"(expected {', '.join(_CONFIG_SECTIONS)})"
            )

    # File + env layer: fills in anything the user didn't set on the line.
    apply(
        load_config(args.config, defaults={s: {} for s in _CONFIG_SECTIONS}),
        force=False,
    )
    # --set layer: beats everything, including explicit flags.
    if args.overrides:
        apply(
            load_config(
                None,
                overrides=args.overrides,
                defaults={s: {} for s in _CONFIG_SECTIONS},
                env={},
            ),
            force=True,
        )


async def _run(args) -> None:
    from dynamo_tpu.runtime.distributed import DistributedRuntime

    _apply_config(args)
    stack = _Stack()
    try:
        # 1. control plane / runtime
        if args.spawn_control_plane is not None:
            from dynamo_tpu.runtime.transports.control_plane import (
                ControlPlaneServer,
            )

            server = await ControlPlaneServer(
                port=int(args.spawn_control_plane)
            ).start()
            stack.push(server.stop)
            print(f"control plane on {server.address}", flush=True)
            args.control_plane = server.address
        if args.control_plane:
            drt = await DistributedRuntime.connect(args.control_plane)
        else:
            drt = await DistributedRuntime.in_process()
        stack.push(drt.shutdown)

        # 2. engine side (unless frontend-only out=dyn)
        endpoint_path = args.endpoint
        if args.input.startswith("dyn://"):
            endpoint_path = args.input
        if (
            args.output == "tpu"
            and args.num_nodes > 1
            and args.node_rank > 0
        ):
            # Multi-host follower rank: replay the leader's step stream
            # until it stops; serves no endpoint of its own.
            await _run_follower(args, drt)
            return
        engine_obj = None
        served = None
        if args.output != "dyn":
            endpoint_path, engine_obj, served = await _start_engine(
                args, drt, stack, endpoint_path
            )

        # 3. input side
        if args.input.startswith("dyn://"):
            print(f"worker serving {endpoint_path}", flush=True)
            await _worker_until_drain(
                args, drt, endpoint_path, engine_obj, served, stack
            )
            return
        manager = await _start_frontend(args, drt, stack)
        if args.input == "http":
            service = await _serve_http(args, stack, manager, engine_obj)
            await _wait_for_signal()
            # Graceful drain before unwind: refuse new requests (admission
            # 503s, /health flips), let admitted ones finish streaming.
            await service.drain(args.drain_grace_s)
            if engine_obj is not None:
                engine_obj.begin_drain()
                await engine_obj.wait_drained(args.drain_grace_s)
        elif args.input == "text":
            await _text_chat(args, manager)
        elif args.input.startswith("batch:"):
            await _batch(args, manager, args.input.split(":", 1)[1])
        else:
            raise SystemExit(f"bad --in {args.input!r}")
    finally:
        await stack.unwind()


class _Stack(contextlib.AsyncExitStack):
    """AsyncExitStack with log-and-continue cleanup callbacks."""

    def push(self, fn) -> None:
        async def _safe() -> None:
            try:
                await fn()
            except Exception:  # noqa: BLE001
                logger.exception("cleanup failed")

        self.push_async_callback(_safe)

    async def unwind(self) -> None:
        await self.aclose()


async def _wait_for_signal() -> None:
    loop = asyncio.get_running_loop()
    stop = asyncio.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:  # pragma: no cover - non-unix
            pass
    await stop.wait()
    print("shutting down", flush=True)


async def _worker_until_drain(
    args, drt, endpoint_path: str, engine, served, stack
) -> None:
    """Worker-mode main loop with graceful drain: wait for SIGTERM/SIGINT
    or the control-plane drain verb, then stop admitting, finish in-flight
    sequences, flip readiness, deregister, and return (the caller's unwind
    revokes the lease and exits) — a loss-free rolling restart
    (docs/architecture/overload_and_drain.md)."""
    from dynamo_tpu.runtime.component import EndpointId
    from dynamo_tpu.runtime.drain import watch_drain

    loop = asyncio.get_running_loop()
    stop = asyncio.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:  # pragma: no cover - non-unix
            pass
    eid = EndpointId.parse(endpoint_path)
    watch = await watch_drain(
        drt, eid.namespace, eid.component, stop.set
    )
    from dynamo_tpu.utils.profiling import Profiler

    profiler = Profiler(base_dir=getattr(args, "profile_dir", None))
    if profiler.configured:
        # Control-plane profile verb: operators capture a jax.profiler
        # window on this worker without port-forwarding to its debug
        # endpoint (runtime/debug.py mirrors the drain verb).
        from dynamo_tpu.runtime.debug import watch_profile

        pwatch = await watch_profile(
            drt, eid.namespace, eid.component, profiler
        )
        stack.callback(pwatch.close)
    if args.health_port and engine is not None:
        from dynamo_tpu.llm.http_service import HealthServer

        health = await HealthServer(
            engine.readiness, host="0.0.0.0", port=args.health_port,
            debug=engine if hasattr(engine, "debug_steps") else None,
            profiler=profiler,
        ).start()
        stack.push(health.stop)
    await stop.wait()
    watch.close()
    print("draining", flush=True)
    await _graceful_drain(engine, served, args.drain_grace_s)


async def _graceful_drain(engine, served, grace_s: float) -> bool:
    """The drain state machine's in-process half: (1) the engine stops
    admitting IMMEDIATELY (readiness flips); (2) the served instance
    deregisters FIRST — routers evict now, not after the grace period —
    then awaits its in-flight request handlers (which complete: admitted
    work runs to completion under drain); (3) anything not tied to an
    ingress handler gets the remaining grace. The lease is revoked by the
    runtime unwind right after."""
    t0 = time.monotonic()
    ok = True
    if engine is not None and hasattr(engine, "begin_drain"):
        engine.begin_drain()
    if served is not None:
        ok = await served.drain(grace_s)
    if engine is not None and hasattr(engine, "wait_drained"):
        remaining = max(1.0, grace_s - (time.monotonic() - t0))
        ok = await engine.wait_drained(remaining) and ok
    print(
        "drain complete" if ok else "drain grace expired", flush=True
    )
    return ok


def _tpu_local_and_cfg(args):
    """Model artifacts + EngineConfig for the tpu engine path — shared by
    the serving leader and multi-host follower ranks, which MUST build
    identical runners (parallel/stepcast.py lockstep contract)."""
    from dynamo_tpu.engine.config import EngineConfig
    from dynamo_tpu.llm.local_model import LocalModel

    from dynamo_tpu.engine.compile_cache import resolve_cache_base

    if getattr(args, "unified", False):
        logger.warning(
            "--unified is deprecated and a no-op: the unified step is "
            "the only engine path (the phase-alternating engine was "
            "deleted)"
        )
    local = LocalModel.prepare(
        args.model_path,
        name=args.model_name,
        context_length=args.context_length,
        kv_block_size=args.kv_cache_block_size,
    )
    max_len = min(args.max_model_len, local.card.context_length)
    local.card.context_length = max_len
    model_dir = (
        local.model_path
        if local.model_path and Path(local.model_path).is_dir()
        else None
    )
    ecfg = EngineConfig(
        model=local.config,
        dtype=args.dtype,
        block_size=args.kv_cache_block_size,
        num_blocks=args.num_blocks,
        max_num_seqs=args.max_num_seqs,
        max_model_len=max_len,
        prefill_batch=args.prefill_batch,
        unified=True,
        unified_token_budget=args.unified_token_budget,
        unified_prefill_quantum=args.unified_prefill_quantum,
        itl_slo_ms=args.itl_slo_ms,
        coloc=args.coloc,
        coloc_min_quantum=args.coloc_min_quantum,
        mesh_shape=_parse_mesh(args.mesh),
        kv_sp=args.kv_sp,
        quant=args.quant,
        kv_quant=args.kv_quant,
        weight_quant=args.weight_quant,
        speculative_k=args.speculative_k,
        coordinator=args.coordinator,
        num_nodes=args.num_nodes,
        node_rank=args.node_rank,
        compile_cache_dir=resolve_cache_base(
            args.compile_cache_dir, model_dir
        ),
        shape_manifest_path=args.shape_manifest,
        # With warmup on, hold admission until the hot shape set compiles
        # (requests queue instead of racing the compiles); --no-warmup
        # serves immediately in the documented degraded mode.
        warmup_gate="degraded" if args.no_warmup else "hold",
        # Bounded engine waiting list (overload shedding).
        max_waiting=args.max_waiting,
        max_queue_delay_s=args.max_queue_delay_s,
    )
    return local, ecfg


async def _run_follower(args, drt) -> None:
    """Multi-host follower rank (node_rank > 0): no endpoint, no HTTP —
    build the identical ModelRunner over the global mesh and replay the
    leader's step stream so the SPMD collectives line up
    (parallel/stepcast.py)."""
    from dynamo_tpu.engine.runner import ModelRunner
    from dynamo_tpu.parallel.multihost import MultiHostConfig, initialize
    from dynamo_tpu.parallel.stepcast import follower_serve

    initialize(MultiHostConfig(
        args.coordinator, args.num_nodes, args.node_rank
    ))
    local, ecfg = _tpu_local_and_cfg(args)
    params = await asyncio.to_thread(local.load_params, args.dtype)
    runner = await asyncio.to_thread(
        lambda: ModelRunner(
            ecfg, params=params, rng_seed=ecfg.seed, donate_params=True
        )
    )
    ns = _endpoint_namespace(args)
    print(
        f"multihost follower rank {args.node_rank} ready", flush=True
    )
    await follower_serve(runner, drt, namespace=ns, rank=args.node_rank)


def _endpoint_namespace(args) -> str:
    from dynamo_tpu.runtime.component import EndpointId

    path = args.input if args.input.startswith("dyn://") else args.endpoint
    return EndpointId.parse(path).namespace


async def _start_engine(args, drt, stack, endpoint_path: str):
    """Build the local engine (tpu or echo), serve it at the endpoint, and
    register the model. Returns (endpoint path served, engine or None for
    non-tpu outputs — the HTTP /health readiness hook, and the
    ServedInstance handle for graceful drain)."""
    from dynamo_tpu.llm.discovery import register_llm
    from dynamo_tpu.llm.local_model import LocalModel
    from dynamo_tpu.runtime.component import EndpointId

    eid = EndpointId.parse(endpoint_path)
    endpoint = (
        drt.namespace(eid.namespace).component(eid.component).endpoint(eid.name)
    )
    if args.output == "tpu":
        # jax's first import/backend-init costs seconds and must not starve
        # the event loop past the lease TTL (see _build_embed note).
        await asyncio.to_thread(__import__, "jax")

    if args.output in ("echo_core", "echo_full"):
        from dynamo_tpu.llm.engines import EchoEngineCore, EchoEngineFull
        from dynamo_tpu.llm.model_card import ModelDeploymentCard

        engine = (
            EchoEngineCore() if args.output == "echo_core" else EchoEngineFull()
        )
        card = ModelDeploymentCard(
            name=args.model_name or args.output, model_path=None
        )
    elif args.output == "tpu" and args.model_type == "embeddings":
        local = LocalModel.prepare(
            args.model_path,
            name=args.model_name,
            context_length=args.context_length,
        )

        def _build_embed():
            # Heavy jax work stays OFF the event loop: starving it for
            # >lease-TTL kills the runtime's own lease (keepalive is a
            # CriticalTask) and deregisters the model we just announced.
            from dynamo_tpu.llm.embedding import EmbeddingEngine

            eng = EmbeddingEngine(
                local.config, params=local.load_params(args.dtype),
                dtype=args.dtype,
            )
            if not args.no_warmup:
                eng._run([1] * 8)  # compile the smallest bucket
            return eng

        engine = await asyncio.to_thread(_build_embed)
        card = local.card
        card.model_type = "embeddings"
    elif args.output == "tpu":
        from dynamo_tpu.engine.config import EngineConfig
        from dynamo_tpu.engine.engine import TpuEngine
        from dynamo_tpu.llm.kv_router.publisher import (
            KvEventPublisher,
            WorkerMetricsPublisher,
        )

        if args.num_nodes > 1:
            # Must precede any device use (weight loading creates device
            # arrays) or jax.distributed cannot form the global mesh.
            from dynamo_tpu.parallel.multihost import (
                MultiHostConfig,
                initialize,
            )

            initialize(MultiHostConfig(
                args.coordinator, args.num_nodes, args.node_rank
            ))
        local, ecfg = _tpu_local_and_cfg(args)
        # KV events + per-pass metrics feed the KV-aware router and the
        # planner over the control plane (in-process — no ZMQ bridge).
        comp = drt.namespace(eid.namespace).component(eid.component)
        kv_pub = KvEventPublisher(drt, comp, drt.primary_lease_id)
        metrics_pub = WorkerMetricsPublisher()
        await metrics_pub.create_endpoint(comp)
        params = await asyncio.to_thread(local.load_params, args.dtype)
        engine = TpuEngine(
            ecfg,
            params=params,
            on_kv_event=kv_pub.publish_engine_event,
            on_metrics=metrics_pub.publish,
            # KV observatory: per-request ACTUAL-reuse records onto the
            # hit-rate plane, closing the router's predicted loop.
            on_kv_actual=kv_pub.publish_hit_actual,
            # Freshly loaded — hand ownership over so a quantized load
            # frees the bf16 buffers as the int8 copies materialize.
            donate_params=True,
        )
        await engine.start()
        if args.num_nodes > 1:
            # Multi-host leader: broadcast every device step so follower
            # ranks replay it (parallel/stepcast.py). Pushed BEFORE
            # engine.stop so unwind stops the engine first, then sends
            # the followers their stop sentinel.
            from dynamo_tpu.parallel.stepcast import StepLeader

            leader = await StepLeader(
                engine.runner, drt, namespace=eid.namespace,
                num_followers=args.num_nodes - 1,
            ).start()
            stack.push(leader.stop)
            engine.runner = leader
        stack.push(engine.stop)
        cache = getattr(engine.runner, "compile_cache", None)
        if cache is not None:
            print(
                f"compile cache: {cache.dir} "
                f"({cache.num_ledger_entries} warmed shapes on disk)",
                flush=True,
            )
        if not args.no_warmup:
            t0 = time.monotonic()
            n = await engine.warmup()
            cs = engine.runner.compile_stats
            tail = engine.warm_tail_pending
            print(
                f"warmup: {n} programs in {time.monotonic() - t0:.1f}s "
                f"({cs.replayed_programs} replayed from cache"
                + (f", {tail} deferred to background" if tail else "")
                + ") — engine ready",
                flush=True,
            )
        card = local.card
    else:
        raise SystemExit(f"bad --out {args.output!r}")

    served = await endpoint.serve(engine)
    await register_llm(drt, endpoint, card, model_type=card.model_type)
    print(f"model {card.name!r} registered at {endpoint_path}", flush=True)
    tpu_engine = engine if args.output == "tpu" and hasattr(
        engine, "readiness"
    ) else None
    return endpoint_path, tpu_engine, served


async def _start_frontend(args, drt, stack):
    """ModelWatcher + ModelManager over the runtime's discovery plane."""
    from dynamo_tpu.llm.discovery import ModelManager, ModelWatcher
    from dynamo_tpu.llm.kv_router.router import kv_selector_factory
    from dynamo_tpu.llm.kv_router.scheduler import KvRouterConfig
    from dynamo_tpu.runtime.egress import RouterMode

    mode = RouterMode(args.router_mode)
    kv_cfg = KvRouterConfig(
        network_aware=bool(getattr(args, "route_network_aware", False)),
    )
    manager = ModelManager()
    watcher = ModelWatcher(
        drt,
        manager,
        router_mode=mode,
        kv_selector_factory=(
            kv_selector_factory(drt, kv_cfg) if mode is RouterMode.KV else None
        ),
    )
    await watcher.start()
    # Give initial discovery a beat: a worker registered just above is
    # visible immediately (same store), remote ones arrive via the watch.
    for _ in range(50):
        if manager.models():
            break
        await asyncio.sleep(0.1)
    return manager


async def _serve_http(args, stack, manager, engine=None):
    from dynamo_tpu.llm.admission import AdmissionConfig, AdmissionController
    from dynamo_tpu.llm.http_service import HttpService
    from dynamo_tpu.utils.profiling import Profiler

    readiness = engine.readiness if engine is not None else None
    service = HttpService(
        manager, host=args.http_host, port=args.http_port,
        # Local-engine deployments expose the compile-lifecycle state on
        # /health (503 while warming) and /metrics; frontend-only (--out
        # dyn) has no local engine to probe.
        readiness=readiness,
        # Ingress overload gate: 429 + Retry-After past capacity, with
        # watermarks fed by the live engine snapshot when one is local.
        admission=AdmissionController(
            AdmissionConfig(
                max_inflight=args.max_inflight,
                max_engine_waiting=args.max_engine_waiting,
                max_prefill_backlog_tokens=getattr(
                    args, "max_prefill_backlog_tokens", 0
                ),
                default_deadline_s=args.default_deadline_s,
                # SLO classes (docs/architecture/ingress_scale.md):
                # the header-less default and the cheapest-first
                # batch watermark scale.
                default_request_class=getattr(
                    args, "default_request_class", "interactive"
                ),
                class_watermark_scale={
                    "interactive": 1.0,
                    "batch": getattr(args, "batch_watermark_scale", 0.5),
                },
            ),
            engine_stats=readiness,
        ),
        # Observability plane (docs/architecture/observability.md):
        # /debug/steps reads the local engine's flight recorder;
        # /debug/profile captures jax.profiler windows when a directory
        # is configured.
        debug=engine if hasattr(engine, "debug_steps") else None,
        profiler=Profiler(base_dir=getattr(args, "profile_dir", None)),
    )
    await service.start()
    stack.push(service.stop)
    print(
        f"OpenAI server on http://{args.http_host}:{service.port} "
        f"(models: {manager.models() or '<awaiting workers>'})",
        flush=True,
    )
    return service


def _first_model(manager):
    models = manager.models()
    if not models:
        raise SystemExit("no models registered (is a worker connected?)")
    return models[0]


async def _text_chat(args, manager) -> None:
    """Interactive chat loop (reference: input/text.rs)."""
    from dynamo_tpu.llm.protocols.openai import ChatCompletionRequest
    from dynamo_tpu.runtime.engine import Context

    model = _first_model(manager)
    engine = manager.get(model)
    history: list[dict] = []
    print(f"chatting with {model!r} — empty line or Ctrl-D to exit", flush=True)
    while True:
        try:
            line = await asyncio.to_thread(input, "> ")
        except (EOFError, KeyboardInterrupt):
            break
        if not line.strip():
            break
        history.append({"role": "user", "content": line})
        req = ChatCompletionRequest.model_validate(
            {
                "model": model,
                "messages": history,
                "stream": True,
                "max_tokens": args.max_tokens,
            }
        )
        parts: list[str] = []
        async for chunk in engine.generate(Context(req)):
            obj = chunk.model_dump(exclude_none=True) if hasattr(
                chunk, "model_dump"
            ) else chunk
            for choice in obj.get("choices", []):
                piece = (choice.get("delta") or {}).get("content")
                if piece:
                    parts.append(piece)
                    print(piece, end="", flush=True)
        print(flush=True)
        history.append({"role": "assistant", "content": "".join(parts)})


async def _batch(args, manager, path: str) -> None:
    """Prompt-file mini-benchmark: one prompt per line; reports per-request
    latency and aggregate token rates (reference: input/batch.rs:45,143-191)."""
    import numpy as np

    from dynamo_tpu.llm.protocols.openai import ChatCompletionRequest
    from dynamo_tpu.runtime.engine import Context

    def _read_prompts() -> list[str]:
        with open(path) as f:
            return [ln.strip() for ln in f if ln.strip()]

    prompts = await asyncio.to_thread(_read_prompts)
    if not prompts:
        raise SystemExit(f"{path} contains no prompts")
    model = _first_model(manager)
    engine = manager.get(model)
    sem = asyncio.Semaphore(args.concurrency)

    async def run_one(prompt: str):
        async with sem:
            req = ChatCompletionRequest.model_validate(
                {
                    "model": model,
                    "messages": [{"role": "user", "content": prompt}],
                    "stream": True,
                    "max_tokens": args.max_tokens,
                }
            )
            t0 = time.monotonic()
            first = None
            n_tokens = 0
            usage = None
            async for chunk in engine.generate(Context(req)):
                obj = chunk.model_dump(exclude_none=True) if hasattr(
                    chunk, "model_dump"
                ) else chunk
                for choice in obj.get("choices", []):
                    if (choice.get("delta") or {}).get("content"):
                        n_tokens += 1
                        if first is None:
                            first = time.monotonic() - t0
                if obj.get("usage"):
                    usage = obj["usage"]
            out = usage["completion_tokens"] if usage else n_tokens
            inp = usage["prompt_tokens"] if usage else 0
            return time.monotonic() - t0, first, inp, out

    t0 = time.monotonic()
    results = await asyncio.gather(*[run_one(p) for p in prompts])
    elapsed = time.monotonic() - t0
    ttfts = [r[1] for r in results if r[1] is not None]
    toks_in = sum(r[2] for r in results)
    toks_out = sum(r[3] for r in results)
    report = {
        "requests": len(prompts),
        "elapsed_s": round(elapsed, 2),
        "tokens_in_per_s": round(toks_in / elapsed, 1),
        "tokens_out_per_s": round(toks_out / elapsed, 1),
        "p50_ttft_ms": round(1000 * float(np.median(ttfts)), 1) if ttfts else None,
        "p95_ttft_ms": round(
            1000 * float(np.percentile(ttfts, 95)), 1
        ) if ttfts else None,
        "mean_request_s": round(
            float(np.mean([r[0] for r in results])), 2
        ),
    }
    print(json.dumps(report), flush=True)


if __name__ == "__main__":
    main(sys.argv[1:])

"""Fleet simulator: xPyD topology projection on the calibrated mocker.

Replays a workload through the mocker's per-phase cost model
(mocker/engine.py ``MockerConfig``) on a VIRTUAL clock — no sleeping, no
Python-scheduler contamination, deterministic — so CI can project
1P1D / 2P1D / 2P2D disaggregated topologies against aggregated
baselines in milliseconds of real time (benchmarks/xpyd_bench.py emits
the table; BENCHMARKS.md records it).

Pricing (planner/calibration.py pins the constants to the recorded
r04/r05 chip runs; tests/test_xpyd.py gates the single-worker
reproduction of the r04 headline to <10 % error):

- prefill batch: ``HOST_OVERHEAD + prefill_dispatch_base +
  Σ (isl·per_token + isl²·quadratic)`` — the fused-lane prefill the
  real PrefillWorker drains in batches;
- decode step:  ``HOST_OVERHEAD + decode_base + lanes·per_lane``;
- KV handoff:   fixed 2-dispatch cost + ``isl·KV_BYTES_PER_TOKEN`` over
  the decode worker's link (heterogeneous links model NetKV-style
  network-aware selection — docs/architecture/planner.md).

The simulator also models FLEET ELASTICITY: a decode worker can start
DRAINING mid-run (``drain_decode_at``) — it takes no new selections,
finishes everything already routed to it, and the run must end with
zero dropped requests (the ci.sh BENCH_XPYD gate).

Scheduling policy (deliberately the simple, documented one the
calibration was fitted against): aggregated workers run
prefill-priority phase alternation with per-step decode pricing;
disagg decode workers admit up to ``max_num_seqs`` lanes between steps.
"""

from __future__ import annotations

import heapq
import itertools
from dataclasses import dataclass, field

from dynamo_tpu.mocker.engine import MockerConfig
from dynamo_tpu.planner import calibration as cal


@dataclass
class SimRequest:
    arrival_s: float
    isl: int
    osl: int
    # filled by the simulation
    ttft_s: float | None = None
    done_s: float | None = None
    decode_worker: int | None = None
    dropped: bool = False


def synth_workload(
    n: int, isl: int, osl: int, rate_rps: float = 0.0
) -> list[SimRequest]:
    """``rate_rps`` 0 = all-at-once burst (the bench.py shape); >0 =
    uniform open arrivals."""
    gap = 1.0 / rate_rps if rate_rps > 0 else 0.0
    return [SimRequest(arrival_s=i * gap, isl=isl, osl=osl) for i in range(n)]


@dataclass
class SimConfig:
    mocker: MockerConfig = field(default_factory=cal.calibrated_mocker_config)
    host_overhead_us: float = cal.HOST_OVERHEAD_US
    prefill_batch: int = 16
    max_num_seqs: int = 64
    handoff_fixed_us: float = cal.HANDOFF_FIXED_US
    kv_bytes_per_token: int = cal.KV_BYTES_PER_TOKEN
    # KV precision of the simulated fleet (docs/architecture/
    # kv_quant.md): "int8" scales the handoff byte term by the packed
    # int8 ratio (~0.502), so xPyD projections for quantized fleets
    # price the halved prefill→decode transfers.
    kv_quant: str | None = None
    # WEIGHT precision of the simulated fleet (docs/architecture/
    # weight_quant.md): "int8" scales every dispatch base — the weight
    # pass standalone prefill and decode steps both pay — by the
    # calibration weight-bytes term (calibration.weight_bytes_per_step),
    # so xPyD / NetKV projections for int8-weight fleets price the
    # ~halved per-dispatch weight streaming. None = bf16 baseline
    # (every base unchanged).
    weight_quant: str | None = None
    # Network-aware selection trade-off: one queued-ahead request is
    # worth about one decode dispatch of delay (docs/architecture/
    # planner.md "network-aware decode selection").
    load_penalty_s: float = 0.025

    def weight_pass_s(self, base_us: float) -> float:
        """A dispatch base (= its weight pass) repriced at the fleet's
        weight precision: the calibration bytes term scales the base by
        quantized/bf16 streamed bytes (~0.501 for int8; exactly 1.0 at
        None, so bf16 projections are byte-identical to before the term
        existed)."""
        ratio = (
            cal.weight_bytes_per_step(self.weight_quant)
            / cal.WEIGHT_BYTES_PER_STEP
        )
        return base_us * ratio / 1e6

    def prefill_batch_cost_s(self, isls: list[int]) -> float:
        m = self.mocker
        us = self.host_overhead_us
        s = self.weight_pass_s(m.prefill_dispatch_base_us)
        for isl in isls:
            us += m.prefill_time_per_token_us * isl
            us += m.prefill_quadratic_us * isl * isl
        return s + us / 1e6

    def decode_step_cost_s(self, lanes: int) -> float:
        m = self.mocker
        return self.weight_pass_s(m.decode_time_per_step_us) + (
            self.host_overhead_us
            + m.decode_time_per_lane_us * lanes
        ) / 1e6

    def handoff_s(self, isl: int, link_gbps: float) -> float:
        bytes_ = isl * self.kv_bytes_per_token
        if self.kv_quant == "int8":
            bytes_ *= cal.kv_quant_bytes_ratio()
        return self.handoff_fixed_us / 1e6 + bytes_ / (link_gbps * 1e9)


@dataclass
class SimResult:
    topology: str
    chips: int
    elapsed_s: float
    tok_s: float
    tok_s_per_chip: float
    p50_ttft_ms: float
    p95_ttft_ms: float
    itl_p50_ms: float
    itl_p95_ms: float
    itl_max_ms: float
    dropped: int
    completed: int
    per_decode_worker: list[int] = field(default_factory=list)
    # When a drain_decode_at event fired: the simulated time the
    # draining worker went EMPTY (finished everything routed to it) —
    # None means it never completed its drain within the run.
    decode_drained_at_s: float | None = None

    def to_wire(self) -> dict:
        return {
            "topology": self.topology,
            "chips": self.chips,
            "elapsed_s": round(self.elapsed_s, 3),
            "tok_s": round(self.tok_s, 1),
            "tok_s_per_chip": round(self.tok_s_per_chip, 1),
            "p50_ttft_ms": round(self.p50_ttft_ms, 1),
            "p95_ttft_ms": round(self.p95_ttft_ms, 1),
            "itl_p50_ms": round(self.itl_p50_ms, 2),
            "itl_p95_ms": round(self.itl_p95_ms, 2),
            "itl_max_ms": round(self.itl_max_ms, 2),
            "dropped": self.dropped,
            "completed": self.completed,
            "per_decode_worker": self.per_decode_worker,
            "decode_drained_at_s": (
                round(self.decode_drained_at_s, 3)
                if self.decode_drained_at_s is not None else None
            ),
        }


def _pct(xs: list[float], q: float) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    pos = (len(s) - 1) * q
    lo = int(pos)
    hi = min(lo + 1, len(s) - 1)
    return s[lo] + (s[hi] - s[lo]) * (pos - lo)


def _result(
    topology: str, chips: int, reqs: list[SimRequest],
    gaps_ms: list[float] | None = None,
    per_worker: list[int] | None = None,
) -> SimResult:
    done = [r for r in reqs if r.done_s is not None and not r.dropped]
    dropped = sum(1 for r in reqs if r.dropped)
    elapsed = max((r.done_s for r in done), default=0.0)
    out_tokens = sum(r.osl for r in done)
    ttfts = [1000.0 * r.ttft_s for r in done if r.ttft_s is not None]
    tok_s = out_tokens / elapsed if elapsed > 0 else 0.0
    gaps_ms = gaps_ms or []
    return SimResult(
        topology=topology,
        chips=chips,
        elapsed_s=elapsed,
        tok_s=tok_s,
        tok_s_per_chip=tok_s / max(chips, 1),
        p50_ttft_ms=_pct(ttfts, 0.50),
        p95_ttft_ms=_pct(ttfts, 0.95),
        itl_p50_ms=_pct(gaps_ms, 0.50),
        itl_p95_ms=_pct(gaps_ms, 0.95),
        itl_max_ms=max(gaps_ms, default=0.0),
        dropped=dropped,
        completed=len(done),
        per_decode_worker=per_worker or [],
    )


# ---------------------------------------------------------------------------
# aggregated (both phases on every chip)


def _deliver(active: list[list], t: float, gaps_ms: list[float]) -> list[list]:
    """One decode token to every active lane at time ``t``; records the
    per-lane inter-token gap (lane[2] = last delivery time — prefill
    stalls between deliveries surface here as ITL spikes)."""
    still = []
    for lane in active:
        lane[1] -= 1
        gaps_ms.append(1000.0 * (t - lane[2]))
        lane[2] = t
        if lane[1] <= 0:
            lane[0].done_s = t
        else:
            still.append(lane)
    return still


def _run_aggregated_one(
    cfg: SimConfig, reqs: list[SimRequest], gaps_ms: list[float]
) -> None:
    """One aggregated worker: prefill-priority phase alternation —
    pending prompts prefill in fused batches first (bounded by the
    admission cap), decode steps run otherwise. The policy the
    calibration constants were fitted against (calibration.py). Maximum
    throughput; decode lanes STALL for whole prefill batches (the ITL
    percentiles make that visible — the SLO problem co-location and
    disaggregation both exist to fix)."""
    reqs = sorted(reqs, key=lambda r: r.arrival_s)
    t = 0.0
    idx = 0
    pending: list[SimRequest] = []
    active: list[list] = []  # [req, remaining_tokens, last_token_t]
    while idx < len(reqs) or pending or active:
        while idx < len(reqs) and reqs[idx].arrival_s <= t + 1e-12:
            pending.append(reqs[idx])
            idx += 1
        if not pending and not active:
            t = reqs[idx].arrival_s
            continue
        room = cfg.max_num_seqs - len(active)
        take = min(len(pending), cfg.prefill_batch, max(room, 0))
        if take > 0:
            batch, pending = pending[:take], pending[take:]
            t += cfg.prefill_batch_cost_s([r.isl for r in batch])
            for r in batch:
                r.ttft_s = t
                if r.osl <= 1:
                    r.done_s = t
                else:
                    active.append([r, r.osl - 1, t])
            continue
        t += cfg.decode_step_cost_s(len(active))
        active = _deliver(active, t, gaps_ms)


def _run_coloc_one(
    cfg: SimConfig, reqs: list[SimRequest], gaps_ms: list[float],
    quantum: int,
) -> None:
    """One aggregated worker in SLO-holding CO-LOCATED mode (the PR 8
    unified-step shape, mocker ``unified_step`` pricing): every
    dispatch carries all decode lanes plus up to ``quantum`` prefill
    tokens chunked off the head of the prompt queue — decode never
    stalls longer than one dispatch, and prefill pays the quantum tax
    (the dispatch base amortizes over ``quantum`` tokens instead of a
    full fused batch — exactly the efficiency a dedicated prefill pool
    recovers, docs/architecture/planner.md)."""
    reqs = sorted(reqs, key=lambda r: r.arrival_s)
    t = 0.0
    idx = 0
    pending: list[list] = []      # [req, prefilled_tokens]
    active: list[list] = []       # [req, remaining, last_token_t]
    while idx < len(reqs) or pending or active:
        while idx < len(reqs) and reqs[idx].arrival_s <= t + 1e-12:
            pending.append([reqs[idx], 0])
            idx += 1
        if not pending and not active:
            t = reqs[idx].arrival_s
            continue
        ptoks = 0
        finishing: list[SimRequest] = []
        if len(active) < cfg.max_num_seqs:
            for ent in pending:
                if ptoks >= quantum:
                    break
                req, done_toks = ent
                take = min(quantum - ptoks, req.isl - done_toks)
                ent[1] += take
                ptoks += take
                if ent[1] >= req.isl:
                    finishing.append(req)
        pending = [e for e in pending if e[1] < e[0].isl]
        m = cfg.mocker
        t += cfg.weight_pass_s(m.decode_time_per_step_us) + (
            cfg.host_overhead_us
            + m.decode_time_per_lane_us * len(active)
            + m.prefill_time_per_token_us * ptoks
        ) / 1e6
        for r in finishing:
            r.ttft_s = t
            if r.osl <= 1:
                r.done_s = t
            else:
                active.append([r, r.osl - 1, t])
        if active:
            # Finishing lanes joined AFTER this dispatch's deliveries —
            # deliver only to lanes that were active going in.
            joined = {id(r) for r in finishing}
            carried = [ln for ln in active if id(ln[0]) not in joined]
            delivered = _deliver(carried, t, gaps_ms)
            active = delivered + [ln for ln in active if id(ln[0]) in joined]


def simulate_aggregated(
    cfg: SimConfig,
    workload: list[SimRequest],
    n_workers: int = 1,
    mode: str = "batch",           # "batch" | "coloc"
    quantum: int = 64,
) -> SimResult:
    """N aggregated chips, requests round-robined at arrival (the
    baseline every disagg topology is judged against). ``mode="batch"``
    maximizes throughput with fused prefill batches that stall decode;
    ``mode="coloc"`` holds decode ITL by chunking prefill into
    ``quantum``-token co-located slices (the SLO-respecting baseline —
    what a production aggregated fleet actually runs)."""
    shards: list[list[SimRequest]] = [[] for _ in range(n_workers)]
    for i, r in enumerate(sorted(workload, key=lambda r: r.arrival_s)):
        shards[i % n_workers].append(r)
    gaps_ms: list[float] = []
    for shard in shards:
        if mode == "coloc":
            _run_coloc_one(cfg, shard, gaps_ms, quantum)
        else:
            _run_aggregated_one(cfg, shard, gaps_ms)
    tag = "coloc" if mode == "coloc" else "AGG"
    return _result(f"{n_workers}x{tag}", n_workers, workload, gaps_ms)


# ---------------------------------------------------------------------------
# disaggregated (xP yD)


class _DecodeSim:
    def __init__(self, idx: int, link_gbps: float) -> None:
        self.idx = idx
        self.link_gbps = link_gbps
        self.buffer: list[SimRequest] = []   # landed, not yet admitted
        self.active: list[list] = []         # [req, remaining]
        self.assigned = 0                    # routed but not finished
        self.busy = False
        self.draining = False
        self.drained_at: float | None = None
        self.served = 0

    @property
    def load(self) -> int:
        return self.assigned


def simulate_xpyd(
    cfg: SimConfig,
    workload: list[SimRequest],
    n_prefill: int,
    n_decode: int,
    decode_links_gbps: list[float] | None = None,
    selector: str = "plain",            # "plain" | "netaware"
    drain_decode_at: tuple[float, int] | None = None,
) -> SimResult:
    """xP yD: ``n_prefill`` chips drain a shared FIFO prefill queue in
    fused batches; each prompt's KV hands off over ITS decode worker's
    link; decode chips run pure decode steps. The decode worker is
    chosen at ingress (as the real DecodeOperator does):

    - ``plain``: least outstanding requests (the load-only score);
    - ``netaware``: least ``handoff_s + load · load_penalty_s`` — the
      NetKV-style transfer-cost term (llm/kv_router/scheduler.py is the
      production twin of this policy).

    ``drain_decode_at=(t, idx)`` starts draining decode worker ``idx``
    at simulated time ``t``: no new selections, everything already
    routed finishes — zero dropped requests is the elasticity gate.
    """
    links = list(decode_links_gbps or [cal.HANDOFF_GBPS] * n_decode)
    if len(links) != n_decode:
        raise ValueError("decode_links_gbps must have n_decode entries")
    decode = [_DecodeSim(i, links[i]) for i in range(n_decode)]
    pf_free = [0.0] * n_prefill
    queue: list[SimRequest] = []
    gaps_ms: list[float] = []
    seq = itertools.count()
    events: list[tuple] = []   # (time, seq, kind, payload)

    def push(t: float, kind: str, payload) -> None:
        heapq.heappush(events, (t, next(seq), kind, payload))

    def select_worker(req: SimRequest, t: float) -> _DecodeSim | None:
        live = [w for w in decode if not w.draining]
        if not live:
            return None
        if selector == "netaware":
            return min(
                live,
                key=lambda w: (
                    cfg.handoff_s(req.isl, w.link_gbps)
                    + w.load * cfg.load_penalty_s,
                    w.idx,
                ),
            )
        return min(live, key=lambda w: (w.load, w.idx))

    def kick_prefill(t: float) -> None:
        for i in range(n_prefill):
            if pf_free[i] <= t + 1e-12 and queue:
                take = min(len(queue), cfg.prefill_batch)
                batch = [queue.pop(0) for _ in range(take)]
                cost = cfg.prefill_batch_cost_s([r.isl for r in batch])
                pf_free[i] = t + cost
                push(t + cost, "pf_done", (i, batch))

    def start_decode(w: _DecodeSim, t: float) -> None:
        if w.busy:
            return
        room = cfg.max_num_seqs - len(w.active)
        while w.buffer and room > 0:
            r = w.buffer.pop(0)
            if r.osl <= 1:
                r.done_s = t
                w.assigned -= 1
                w.served += 1
                continue
            w.active.append([r, r.osl - 1, t])
            room -= 1
        if not w.active:
            if w.draining and not w.buffer and w.assigned == 0:
                w.drained_at = t
            return
        w.busy = True
        push(t + cfg.decode_step_cost_s(len(w.active)), "dec_done", w)

    for r in sorted(workload, key=lambda r: r.arrival_s):
        push(r.arrival_s, "arrive", r)
    if drain_decode_at is not None:
        push(drain_decode_at[0], "drain", drain_decode_at[1])

    while events:
        t, _, kind, payload = heapq.heappop(events)
        if kind == "arrive":
            req = payload
            w = select_worker(req, t)
            if w is None:
                req.dropped = True
                continue
            req.decode_worker = w.idx
            w.assigned += 1
            queue.append(req)
            kick_prefill(t)
        elif kind == "pf_done":
            _i, batch = payload
            for req in batch:
                w = decode[req.decode_worker]
                push(t + cfg.handoff_s(req.isl, w.link_gbps), "land", req)
            kick_prefill(t)
        elif kind == "land":
            req = payload
            req.ttft_s = t   # first token travels with the handoff
            w = decode[req.decode_worker]
            w.buffer.append(req)
            start_decode(w, t)
        elif kind == "dec_done":
            w = payload
            w.busy = False
            before = len(w.active)
            w.active = _deliver(w.active, t, gaps_ms)
            finished = before - len(w.active)
            w.assigned -= finished
            w.served += finished
            start_decode(w, t)
        elif kind == "drain":
            w = decode[payload]
            w.draining = True
            # Anything queued toward it still lands and finishes —
            # drain ≠ kill (docs/architecture/planner.md). An already-
            # empty worker is drained on the spot (no later event
            # would re-check it).
            if not w.active and not w.buffer and w.assigned == 0:
                w.drained_at = t

    chips = n_prefill + n_decode
    res = _result(
        f"{n_prefill}P{n_decode}D", chips, workload, gaps_ms,
        per_worker=[w.served for w in decode],
    )
    res.decode_drained_at_s = next(
        (w.drained_at for w in decode if w.draining), None
    )
    return res

from dynamo_tpu.planner.planner import (
    Planner,
    PlannerConfig,
    SubprocessConnector,
    WorkerConnector,
)

__all__ = ["Planner", "PlannerConfig", "SubprocessConnector", "WorkerConnector"]

from dynamo_tpu.planner.fleet import FleetPlanner, FleetPlannerConfig
from dynamo_tpu.planner.obs import PLANNER_OBS, PlannerObservatory
from dynamo_tpu.planner.planner import (
    Planner,
    PlannerConfig,
    SubprocessConnector,
    WorkerConnector,
)
from dynamo_tpu.planner.pools import (
    DecodeLaw,
    FleetSample,
    PoolConfig,
    PrefillLaw,
    WorkerPool,
    default_pools,
)

__all__ = [
    "PLANNER_OBS",
    "DecodeLaw",
    "FleetPlanner",
    "FleetPlannerConfig",
    "FleetSample",
    "Planner",
    "PlannerConfig",
    "PlannerObservatory",
    "PoolConfig",
    "PrefillLaw",
    "SubprocessConnector",
    "WorkerConnector",
    "WorkerPool",
    "default_pools",
]

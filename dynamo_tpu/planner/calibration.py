"""Mocker cost-model calibration against the measured BENCH_r04/r05 runs.

The mocker (mocker/engine.py) prices a dispatch as
``f(decode_lanes, prefill_tokens)`` but its default constants are
arbitrary. This module pins them to the RECORDED chip runs so the fleet
simulator's xPyD projections (planner/simulate.py, BENCHMARKS.md) stand
on measured ground:

- **decode dispatch**: r04's device microbench measured
  ``decode_step_ms`` 11.59 at 64 lanes and 11.13 at 32 lanes
  (BENCH_r04.json extras). Two points, one line:
  per-lane = (11590 − 11130) / 32 ≈ 14.4 µs, base =
  11130 − 32·14.4 ≈ 10670 µs (the per-step weight pass). r05 measured
  the same slope (12.51/11.68 ms) within 8% — the constant is stable
  across runs.
- **prefill + host overhead**: fitted so the calibrated single-worker
  simulation of the r04 headline workload (64 requests, ISL 128,
  OSL 64, all-at-once) reproduces the recorded aggregated throughput
  (1746.1 tok/s) and p50 TTFT (662.4 ms) — the <10 % gate
  tests/test_xpyd.py enforces so future mocker edits can't silently
  drift the projections. ``HOST_OVERHEAD_US`` is the per-dispatch
  scheduler/tunnel cost the device-side step time doesn't see (the gap
  between r04's 11.59 ms device step and its engine-side elapsed).
- **handoff transfer**: the measured batched device channel
  (BENCHMARKS.md "Batched KV block IO"): 21.7 GB/s, 2 dispatches per
  handoff at ~456 µs each (2193 per-block dispatches/s measured).

Derived, not tuned: change these only against a NEW recorded run.
"""

from __future__ import annotations

import json
from pathlib import Path

# -- decode dispatch (r04 device microbench, see module docstring) ----------
DECODE_TIME_PER_STEP_US = 10670.0
DECODE_TIME_PER_LANE_US = 14.4

# -- decode HBM bandwidth (r04 device microbench: effective_hbm_gbps in
#    BENCH_r04.json extras — total streamed bytes / measured decode step
#    time at B=64). The mocker's decode HBM-bytes term
#    (MockerConfig.decode_hbm_gbps) prices KV reads against this, so the
#    BENCH_QUANT A/B's bf16 baseline stands on the measured chip number;
#    tests re-derive it from the artifact (recorded_r04) so the constant
#    and the recording can't drift apart. -------------------------------
DECODE_HBM_GBPS = 282.8

# -- weight pass (derived from the decode dispatch base) --------------------
# The decode dispatch base IS the per-step weight pass (module docstring:
# base = 11130 − 32·14.4 ≈ 10670 µs), so at the measured effective HBM
# rate it streams base·rate bytes per dispatch. Publishing the BYTES
# (not the time) lets the mocker reprice the pass by weight precision:
# int8 weights stream ~half the bytes, so the base shrinks by the same
# ratio the KV term already applies to context reads.
WEIGHT_BYTES_PER_STEP = DECODE_TIME_PER_STEP_US * 1e-6 * DECODE_HBM_GBPS * 1e9

# -- prefill (fitted to the r04 headline; test-gated to <10%) ---------------
PREFILL_TIME_PER_TOKEN_US = 119.8
PREFILL_QUADRATIC_US = 0.0005
# Standalone prefill pays its own weight pass — same streaming bytes as
# the decode dispatch base (what co-located quanta share instead). NOT a
# second fitted constant: derived from the weight-bytes term at the
# measured rate (numerically the decode base, 10670 µs), so repricing
# the weight pass by precision moves standalone prefill and the decode
# base together instead of leaving prefill at a stale flat copy.
PREFILL_DISPATCH_BASE_US = WEIGHT_BYTES_PER_STEP / (DECODE_HBM_GBPS * 1e9) * 1e6

# -- per-dispatch host overhead (fitted; simulator-only, the real engine
#    pays its real scheduler) ----------------------------------------------
HOST_OVERHEAD_US = 8900.0

# -- KV handoff (measured r05-late batched BlockBatch channel) --------------
# THE single source for the fleet's default link-rate fallback: the
# router's NetKV term (kv_router/scheduler.py KvRouterConfig.
# default_link_gbps) and the G4 peer tier's pricing fallback
# (block_manager/peer.py) both import this symbol, and
# tests/test_calibration.py drift-gates that neither carries its own
# copy — a re-fit here repriced every consumer at once.
HANDOFF_GBPS = 21.7
HANDOFF_FIXED_US = 912.0          # 2 dispatches/handoff × ~456 µs
# llama3.2-1b KV bytes/token: 2 (K,V) × 16 layers × 8 kv-heads ×
# 64 head-dim × 2 B (bf16) — the model every recorded run served.
KV_BYTES_PER_TOKEN = 32768


def kv_quant_bytes_ratio(
    block_size: int = 16,
    num_layers: int = 16,
    num_kv_heads: int = 8,
    head_dim: int = 64,
    dtype_bytes: int = 2,
) -> float:
    """Stored-KV bytes ratio of an int8 block (data + f32 per-(layer,
    K/V, head) scale sidecar) vs the bf16 layout — the precision-aware
    factor for the mocker's HBM term and the xPyD simulator's
    32 KiB/token handoff constant (defaults: the 1B layout every
    recorded run served; ~0.502)."""
    data = num_layers * 2 * block_size * num_kv_heads * head_dim
    scales = num_layers * 2 * num_kv_heads * 4
    return (data + scales) / (data * dtype_bytes)


def kv_bytes_per_token(quant: str | None = None) -> float:
    """Handoff/HBM bytes per token for the calibrated 1B layout at the
    given KV precision (None = bf16 baseline)."""
    if quant == "int8":
        return KV_BYTES_PER_TOKEN * kv_quant_bytes_ratio()
    return float(KV_BYTES_PER_TOKEN)


def weight_quant_bytes_ratio(
    in_dim: int = 2048,
    dtype_bytes: int = 2,
) -> float:
    """Resident/streamed bytes ratio of an int8 weight matrix (int8 data
    + one f32 scale per output channel, ops/quant.py ``quantize_weight``)
    vs the bf16 layout: ``(in·1 + 4) / (in·2)`` per output column.
    Defaults: the 1B model's 2048 hidden dim (~0.501 — the scale row
    amortizes over the contraction axis, like the KV block scales)."""
    return (in_dim * 1 + 4) / (in_dim * dtype_bytes)


def weight_bytes_per_step(weight_quant: str | None = None) -> float:
    """Weight bytes one dispatch streams at the given weight precision
    (None = bf16 baseline = the full recorded pass). A non-None policy
    is priced at the full-int8 ratio — partial per-matmul policies
    should pass their blended ratio to MockerConfig.weight_bytes_ratio
    directly instead."""
    if weight_quant:
        return WEIGHT_BYTES_PER_STEP * weight_quant_bytes_ratio()
    return WEIGHT_BYTES_PER_STEP

# -- recorded r04 headline (the calibration target, from BENCH_r04.json) ----
R04_HEADLINE_TOK_S = 1746.1
R04_P50_TTFT_MS = 662.4
R04_NUM_REQUESTS = 64
R04_ISL = 128
R04_OSL = 64


def calibrated_mocker_config(**overrides):
    """A MockerConfig priced by the measured constants (the per-phase
    cost model the fleet simulator replays; also usable for live
    mocker-engine runs that should approximate chip pacing)."""
    # Deferred import keeps this module a LEAF: the router scheduler
    # imports HANDOFF_GBPS at class-definition time, and pulling the
    # mocker (→ engine → jax) in transitively would make every router
    # import pay the accelerator stack.
    from dynamo_tpu.mocker.engine import MockerConfig

    kw = dict(
        prefill_time_per_token_us=PREFILL_TIME_PER_TOKEN_US,
        prefill_quadratic_us=PREFILL_QUADRATIC_US,
        decode_time_per_step_us=DECODE_TIME_PER_STEP_US,
        decode_time_per_lane_us=DECODE_TIME_PER_LANE_US,
        prefill_dispatch_base_us=PREFILL_DISPATCH_BASE_US,
        # Bytes-priced weight pass: inert until a scenario also arms
        # decode_hbm_gbps (bytes/rate then round-trips to the flat
        # base, so every calibrated projection is unchanged at bf16).
        weight_bytes_per_step=WEIGHT_BYTES_PER_STEP,
    )
    kw.update(overrides)
    return MockerConfig(**kw)


def handoff_seconds(
    isl_tokens: int,
    link_gbps: float = HANDOFF_GBPS,
    kv_quant: str | None = None,
) -> float:
    """Prefill→decode KV handoff time for one prompt over a link of
    ``link_gbps`` (the NetKV transfer term, priced like the measured
    device channel: fixed 2-dispatch cost + bytes/rate). ``kv_quant``
    makes the byte term precision-aware: an int8 fleet moves ~half the
    bytes per token (docs/architecture/kv_quant.md)."""
    bytes_ = isl_tokens * kv_bytes_per_token(kv_quant)
    return HANDOFF_FIXED_US / 1e6 + bytes_ / (link_gbps * 1e9)


def recorded_r04(path: str | Path | None = None) -> dict:
    """The recorded r04 headline straight from the checked-in
    BENCH_r04.json (tests cross-check the constants above against the
    artifact so they can't drift apart)."""
    if path is None:
        path = Path(__file__).resolve().parents[2] / "BENCH_r04.json"
    d = json.loads(Path(path).read_text())
    parsed = d.get("parsed") or {}
    extras = parsed.get("extras") or {}
    return {
        "tok_s": float(parsed["value"]),
        "p50_ttft_ms": float(extras["p50_ttft_ms"]),
        "num_requests": int(extras["num_requests"]),
        "isl": int(extras["isl"]),
        "osl": int(extras["osl"]),
        "decode_step_ms": float(extras["decode_step_ms"]),
        "decode_step_ms_b32": float(extras["decode_step_ms_b32c16"]),
        "effective_hbm_gbps": float(extras["effective_hbm_gbps"]),
    }

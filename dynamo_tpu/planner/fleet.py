"""FleetPlanner: two-pool prefill/decode autoscaling.

The fleet-scale successor to the single-pool ``Planner`` (ROADMAP #4,
docs/architecture/planner.md): one metric-sampling loop feeds two
independent :class:`~dynamo_tpu.planner.pools.WorkerPool`s —

- the **prefill** pool scales on the shared prefill queue's depth (per
  worker) and oldest-item age;
- the **decode** pool scales on KV utilization, per-worker waiting
  requests, and the decode ITL EMA scraped from the metrics plane
  (``ForwardPassMetrics.itl_ema_ms`` — the coloc controller's export).

Pools are isolated by construction: each holds its own handles, law,
hysteresis state, and connector (prefill and decode workers are
different commands), so a queue spike grows ONLY the prefill pool and
KV pressure grows ONLY the decode pool (tests/test_fleet_planner.py).

Every adjustment tick writes three sinks (planner/obs.py): the
decision JSONL, the ``PLANNER_OBS`` gauges on the /metrics surfaces,
and ``kind="planner"`` records into the ``DYNTPU_TRACE`` capture.

State checkpointing is versioned: v2 files store per-pool worker
slices; a v1 file from a pre-split single-pool planner loads its
workers into the DECODE pool (decode workers are what the old planner
managed — they serve ``generate``; adopting them as prefill consumers
would point the wrong law at them) and never crashes the restore.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
from dataclasses import dataclass, field
from pathlib import Path

from dynamo_tpu.llm.kv_router.metrics_aggregator import KvMetricsAggregator
from dynamo_tpu.planner.obs import PLANNER_OBS
from dynamo_tpu.planner.pools import FleetSample, WorkerPool
from dynamo_tpu.utils.atomic_io import atomic_write_text

logger = logging.getLogger(__name__)

STATE_VERSION = 2


@dataclass
class FleetPlannerConfig:
    namespace: str = "dynamo"
    # Component whose metrics plane the DECODE pool is scored on (decode
    # workers serve `generate` + `load_metrics` there). The prefill pool
    # needs no metrics endpoint — its signal is the queue itself.
    decode_component: str = "tpu"
    metric_interval_s: float = 1.0
    adjustment_interval_s: float = 10.0
    state_path: str | None = None
    decision_log_path: str | None = None


@dataclass
class _Window:
    """Raw samples accumulated between adjustment ticks."""

    depths: list[float] = field(default_factory=list)
    ages: list[float] = field(default_factory=list)
    kvs: list[float] = field(default_factory=list)
    waitings: list[float] = field(default_factory=list)
    waitings_interactive: list[float] = field(default_factory=list)
    waitings_batch: list[float] = field(default_factory=list)
    itls: list[float] = field(default_factory=list)
    workers_seen: int = 0

    def add_queue(self, depth: int, age_s: float) -> None:
        self.depths.append(float(depth))
        self.ages.append(float(age_s))

    def add_metrics(self, metrics: dict) -> None:
        if metrics:
            vals = list(metrics.values())
            self.workers_seen = max(self.workers_seen, len(vals))
            self.kvs.append(
                sum(m.gpu_cache_usage_perc for m in vals) / len(vals)
            )
            self.waitings.append(
                sum(m.num_requests_waiting for m in vals) / len(vals)
            )
            # Per-SLO-class split (llm/slo.py): zero on class-blind
            # workers, in which case the laws fall back to the unsplit
            # axis (pools.DecodeLaw.effective_waiting).
            self.waitings_interactive.append(
                sum(
                    getattr(m, "num_waiting_interactive", 0) for m in vals
                ) / len(vals)
            )
            self.waitings_batch.append(
                sum(
                    getattr(m, "num_waiting_batch", 0) for m in vals
                ) / len(vals)
            )
            self.itls.append(sum(m.itl_ema_ms for m in vals) / len(vals))

    def add(self, depth: int, age_s: float, metrics: dict) -> None:
        self.add_queue(depth, age_s)
        self.add_metrics(metrics)

    @staticmethod
    def _avg(xs: list[float]) -> float:
        return sum(xs) / len(xs) if xs else 0.0

    def digest(self) -> FleetSample:
        # Coverage fields report what ACTUALLY arrived this window: a
        # window whose every sample attempt failed digests to zeros
        # with zero coverage, and the laws hold instead of shrinking
        # (pools.py — blind ≠ idle).
        return FleetSample(
            queue_depth=self._avg(self.depths),
            queue_age_s=self._avg(self.ages),
            kv_usage=self._avg(self.kvs),
            waiting=self._avg(self.waitings),
            waiting_interactive=self._avg(self.waitings_interactive),
            waiting_batch=self._avg(self.waitings_batch),
            itl_ema_ms=self._avg(self.itls),
            decode_workers_seen=self.workers_seen,
            queue_samples=len(self.depths),
        )


class FleetPlanner:
    def __init__(
        self,
        drt,
        cfg: FleetPlannerConfig,
        prefill_pool: WorkerPool,
        decode_pool: WorkerPool,
        on_scale_up=None,
    ) -> None:
        from dynamo_tpu.disagg.queue import PrefillQueue

        self._drt = drt
        self.cfg = cfg
        self.prefill = prefill_pool
        self.decode = decode_pool
        self._queue = PrefillQueue(drt, cfg.namespace)
        self._aggregator: KvMetricsAggregator | None = None
        self._task: asyncio.Task | None = None
        # G4 pre-placement hook (docs/architecture/kvbm_g4.md): awaited
        # as ``on_scale_up(pool_name, new_size)`` after a pool grows, so
        # the deployment can push the hottest prefixes to the joining
        # worker before traffic reaches it (block_manager/peer.preplace).
        # Failures are logged, never allowed to break the control loop.
        self._on_scale_up = on_scale_up

    @property
    def pools(self) -> tuple[WorkerPool, WorkerPool]:
        return (self.prefill, self.decode)

    # -- checkpoint/resume -------------------------------------------------
    def _save_state(self) -> None:
        if self.cfg.state_path is None:
            return
        path = Path(self.cfg.state_path)
        path.parent.mkdir(parents=True, exist_ok=True)
        pools = {}
        for pool in self.pools:
            snapshot = getattr(pool.connector, "snapshot", None)
            pools[pool.cfg.name] = {
                "workers": pool.snapshot_workers(),
                "connector": snapshot() if snapshot is not None else {},
            }
        state = {
            "version": STATE_VERSION,
            "namespace": self.cfg.namespace,
            "pools": pools,
            "ts": time.time(),
        }
        # Atomic AND durable (utils/atomic_io): the bare rename left the
        # replace able to roll back to a zero-length file across power
        # loss — which _resume_state would read as "start fresh" and
        # orphan both pools' checkpointed workers.
        atomic_write_text(path, json.dumps(state))

    def _resume_state(self) -> None:
        if self.cfg.state_path is None:
            return
        path = Path(self.cfg.state_path)
        if not path.exists():
            return
        try:
            state = json.loads(path.read_text())
        except ValueError:
            logger.warning("planner state %s unreadable; starting fresh", path)
            return
        if not isinstance(state, dict):
            logger.warning("planner state %s malformed; starting fresh", path)
            return
        if "pools" not in state:
            # v1 single-pool file (planner/planner.py layout): its
            # workers were decode-serving `generate` workers — adopt
            # them into the decode pool, leave prefill to spawn fresh.
            workers = state.get("workers") or []
            restore = getattr(self.decode.connector, "restore", None)
            if restore is not None and state.get("connector"):
                restore(state["connector"])
            alive = self.decode.restore_workers(workers)
            if alive:
                logger.info(
                    "planner: migrated %d worker(s) from single-pool "
                    "state %s into the decode pool", alive, path,
                )
            return
        for pool in self.pools:
            slice_ = state["pools"].get(pool.cfg.name)
            if not isinstance(slice_, dict):
                continue
            restore = getattr(pool.connector, "restore", None)
            if restore is not None and slice_.get("connector"):
                restore(slice_["connector"])
            alive = pool.restore_workers(slice_.get("workers") or [])
            if alive:
                logger.info(
                    "planner: resumed %d %s worker(s) from %s",
                    alive, pool.cfg.name, path,
                )

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> "FleetPlanner":
        comp = self._drt.namespace(self.cfg.namespace).component(
            self.cfg.decode_component
        )
        self._aggregator = await KvMetricsAggregator(
            self._drt, comp, interval_s=self.cfg.metric_interval_s
        ).start()
        self._resume_state()
        for pool in self.pools:
            await pool.ensure_min()
        self._save_state()
        self._task = asyncio.ensure_future(self._run())
        return self

    async def _run(self) -> None:
        window = _Window()
        next_adjust = (
            asyncio.get_running_loop().time() + self.cfg.adjustment_interval_s
        )
        while True:
            # The two sample sources are INDEPENDENT coverage axes
            # (pools.py FleetSample): a failing queue probe must not
            # blind the decode pool's metrics read (which is a
            # non-raising attribute access) or vice versa.
            try:
                depth, age = await self._queue.stats()
                window.add_queue(depth, age)
            except asyncio.CancelledError:
                return
            except Exception:
                logger.exception("planner queue sample failed")
            window.add_metrics(self._aggregator.endpoints.metrics)
            # Crash healing every METRIC tick, not just adjustment
            # ticks: a dead worker is replaced immediately at target
            # size with no drain accounting (pools.reap_dead — crash ≠
            # drain), so detection latency is one sample interval.
            healed = False
            for pool in self.pools:
                try:
                    healed = bool(await pool.reap_dead()) or healed
                    if pool.size < pool.cfg.min_workers:
                        # Replacement spawns can fail (backend outage):
                        # keep retrying the deficit every tick rather
                        # than serving a worker-sized hole until the
                        # next law-driven scale-up.
                        await pool.ensure_min()
                        healed = True
                except asyncio.CancelledError:
                    return
                except Exception:
                    logger.exception(
                        "planner[%s] dead-worker reap failed",
                        pool.cfg.name,
                    )
            if healed:
                self._save_state()
            if asyncio.get_running_loop().time() >= next_adjust:
                try:
                    await self._adjust(window.digest())
                except asyncio.CancelledError:
                    return
                except Exception:
                    logger.exception("planner adjustment failed")
                window = _Window()
                next_adjust = (
                    asyncio.get_running_loop().time()
                    + self.cfg.adjustment_interval_s
                )
            await asyncio.sleep(self.cfg.metric_interval_s)

    async def _adjust(self, sample: FleetSample) -> None:
        from dynamo_tpu.utils.tracing import tracer

        changed = False
        for pool in self.pools:
            decision = await pool.adjust(sample)
            changed = changed or decision != "hold"
            rec = PLANNER_OBS.note_decision(
                pool.cfg.name,
                decision,
                pool.size,
                signals=pool.law.signals(sample),
                draining=pool.draining,
            )
            tracer().export(rec)
            self._log_decision(rec)
            if decision == "up" and self._on_scale_up is not None:
                try:
                    await self._on_scale_up(pool.cfg.name, pool.size)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    logger.exception(
                        "planner[%s] scale-up hook failed", pool.cfg.name
                    )
        if changed:
            self._save_state()

    def _log_decision(self, rec: dict) -> None:
        """Append one pool-decision line to the decision JSONL (same
        shape as the capture record; write failures never break the
        control loop)."""
        if self.cfg.decision_log_path is None:
            return
        try:
            path = Path(self.cfg.decision_log_path)
            path.parent.mkdir(parents=True, exist_ok=True)
            with path.open("a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError as exc:
            logger.warning("planner decision log write failed: %s", exc)

    async def stop(self, drain_workers: bool = False) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._aggregator is not None:
            await self._aggregator.stop()
        if drain_workers:
            for pool in self.pools:
                await pool.drain_all()
        else:
            for pool in self.pools:
                await pool.wait_drained()
        self._save_state()

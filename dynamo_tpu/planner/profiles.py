"""Perf profiles: measured TTFT/ITL-vs-load curves driving SLA scaling.

Role of the reference's planner profiling (reference:
docs/architecture/planner.md:53-90 — pre-profiled per-engine TTFT/ITL
curves, interpolated to pick how many replicas meet an SLA at the
current load). TPU mapping: `bench.py`'s concurrency sweep already
measures exactly these points per chip configuration; a `PerfProfile`
holds them and answers "how many concurrent requests can ONE worker
carry while staying inside the SLA", which turns observed load into a
target worker count (`target_workers`).

Load a profile from a bench result (`PerfProfile.from_bench_json`) or
construct it from any (concurrency, ttft_ms, itl_ms) points.
"""

from __future__ import annotations

import json
import math
from dataclasses import dataclass
from pathlib import Path


@dataclass(frozen=True)
class PerfPoint:
    concurrency: int
    ttft_ms: float
    itl_ms: float


class PerfProfile:
    def __init__(self, points: list[PerfPoint]) -> None:
        if not points:
            raise ValueError("profile needs at least one measured point")
        self.points = sorted(points, key=lambda p: p.concurrency)

    @staticmethod
    def from_bench_json(path: str | Path) -> "PerfProfile":
        """Build from a bench.py output line (extras.sweep)."""
        d = json.loads(Path(path).read_text())
        sweep = (d.get("extras") or {}).get("sweep") or []
        points = [
            PerfPoint(
                concurrency=int(lvl["concurrency"]),
                ttft_ms=float(lvl["p50_ttft_ms"]),
                itl_ms=float(lvl["p50_itl_ms"]),
            )
            for lvl in sweep
            # Smoke/short runs can emit null percentiles (a level where no
            # request produced the metric) — skip those levels.
            if lvl.get("p50_ttft_ms") is not None
            and lvl.get("p50_itl_ms") is not None
        ]
        if not points:
            raise ValueError(
                f"{path}: no usable sweep levels (extras.sweep missing or "
                f"all percentiles null)"
            )
        return PerfProfile(points)

    def _interp(self, c: float, attr: str) -> float:
        """Piecewise-linear metric estimate at concurrency `c` (clamped to
        the measured range; past the last point, extrapolate along the
        final segment — load beyond what was measured only gets worse)."""
        pts = self.points
        if c <= pts[0].concurrency:
            return getattr(pts[0], attr)
        for lo, hi in zip(pts, pts[1:]):
            if c <= hi.concurrency:
                f = (c - lo.concurrency) / (hi.concurrency - lo.concurrency)
                return getattr(lo, attr) + f * (
                    getattr(hi, attr) - getattr(lo, attr)
                )
        if len(pts) == 1:
            return getattr(pts[0], attr)
        lo, hi = pts[-2], pts[-1]
        slope = (getattr(hi, attr) - getattr(lo, attr)) / (
            hi.concurrency - lo.concurrency
        )
        return getattr(hi, attr) + slope * (c - hi.concurrency)

    def ttft_ms(self, concurrency: float) -> float:
        return self._interp(concurrency, "ttft_ms")

    def itl_ms(self, concurrency: float) -> float:
        return self._interp(concurrency, "itl_ms")

    def max_concurrency_within(
        self,
        ttft_sla_ms: float | None = None,
        itl_sla_ms: float | None = None,
    ) -> float:
        """Highest per-worker concurrency meeting every given SLA bound
        (binary search over the interpolated curves; both curves are
        treated as non-decreasing in load). At least 1.0 — a worker can
        always serve one request, however slowly."""
        if ttft_sla_ms is None and itl_sla_ms is None:
            return float(self.points[-1].concurrency)

        def ok(c: float) -> bool:
            if ttft_sla_ms is not None and self.ttft_ms(c) > ttft_sla_ms:
                return False
            if itl_sla_ms is not None and self.itl_ms(c) > itl_sla_ms:
                return False
            return True

        lo, hi = 1.0, float(self.points[-1].concurrency) * 2.0
        if not ok(lo):
            return 1.0
        if ok(hi):
            return hi
        for _ in range(40):
            mid = (lo + hi) / 2
            if ok(mid):
                lo = mid
            else:
                hi = mid
        return lo

    def target_workers(
        self,
        observed_load: float,
        ttft_sla_ms: float | None = None,
        itl_sla_ms: float | None = None,
    ) -> int:
        """Workers needed so per-worker load stays within the SLA envelope
        (reference planner.md:53-90: replicas = load / per-replica
        capacity at the SLA point)."""
        cap = self.max_concurrency_within(ttft_sla_ms, itl_sla_ms)
        # The capacity search converges from below (7.999...); the epsilon
        # keeps an exact-boundary load from rounding up a spurious worker.
        return max(1, math.ceil(observed_load / cap - 1e-6))

"""Planner observability plane (docs/architecture/planner.md).

Every scaling decision the planner takes lands in three places:

- the process-wide ``PLANNER_OBS`` singleton below — counters
  (``planner_scale_{up,down}_total``, per-pool splits), per-pool size
  gauges, and the last-decision age — merged into the ``/metrics``
  surfaces (llm/http_service.py HttpService + HealthServer) and the
  standalone exporter (llm/metrics_exporter.py), the same pattern as
  the KV observatory's ``ROUTE_OBS``;
- the ``DYNTPU_TRACE`` capture as ``kind="planner"`` records (via
  ``tracer().export``) so benchmarks/trace_merge.py and
  benchmarks/route_audit.py can line scaling decisions up against the
  request timelines and route decisions they caused;
- the planner's own decision JSONL (``decision_log_path``) — the
  pre-existing after-the-fact artifact, unchanged.

Before this module the decision JSONL was the ONLY sink: a planner
that flapped or wedged was invisible to Prometheus (the satellite gap
this closes).
"""

from __future__ import annotations

import logging
import time
from collections import deque
from typing import Any

from dynamo_tpu.utils.concurrency import make_lock

logger = logging.getLogger(__name__)


class PlannerObservatory:
    """Process-wide planner decision counters + pool gauges."""

    def __init__(self, capacity: int = 512) -> None:
        self._lock = make_lock("planner_obs")
        self._ring: deque[dict] = deque(maxlen=capacity)
        self.scale_up_total = 0
        self.scale_down_total = 0
        self.replaced_dead_total = 0
        # pool name -> per-pool state
        self._pool_sizes: dict[str, int] = {}
        self._pool_draining: dict[str, int] = {}
        self._pool_up: dict[str, int] = {}
        self._pool_down: dict[str, int] = {}
        self._pool_dead: dict[str, int] = {}
        self._last_decision_unix: float | None = None

    def note_replaced_dead(self, pool: str, n: int = 1) -> dict:
        """A crashed worker was reaped and immediately replaced at
        target size (pools.py ``reap_dead`` — crash, not drain: no
        drain accounting, no grace period). Returns the capture-ready
        ``kind="planner"`` record for the trace stream."""
        now = time.time()
        with self._lock:
            self.replaced_dead_total += n
            self._pool_dead[pool] = self._pool_dead.get(pool, 0) + n
            self._last_decision_unix = now
            rec = {
                "kind": "planner",
                "pool": pool,
                "decision": "replace_dead",
                "replaced": int(n),
                "unix": round(now, 6),
            }
            self._ring.append(rec)
        return rec

    def note_size(self, pool: str, size: int, draining: int = 0) -> None:
        """Live pool-size gauge (set on every spawn/drain, not just on
        adjustment ticks, so the gauge can't lag a mid-window change)."""
        with self._lock:
            self._pool_sizes[pool] = int(size)
            self._pool_draining[pool] = int(draining)

    def note_decision(
        self,
        pool: str,
        decision: str,
        size: int,
        signals: dict[str, Any] | None = None,
        draining: int = 0,
    ) -> dict:
        """Record one adjustment-tick decision. Returns the capture-ready
        ``kind="planner"`` record (the caller streams it through
        ``tracer().export`` — this module stays import-light so the
        exporter can pull gauges without the tracing stack)."""
        now = time.time()
        with self._lock:
            self._pool_sizes[pool] = int(size)
            self._pool_draining[pool] = int(draining)
            if decision == "up":
                self.scale_up_total += 1
                self._pool_up[pool] = self._pool_up.get(pool, 0) + 1
            elif decision == "down":
                self.scale_down_total += 1
                self._pool_down[pool] = self._pool_down.get(pool, 0) + 1
            self._last_decision_unix = now
            rec = {
                "kind": "planner",
                "pool": pool,
                "decision": decision,
                "size": int(size),
                "unix": round(now, 6),
            }
            for k, v in (signals or {}).items():
                if isinstance(v, float):
                    rec[k] = round(v, 4)
                elif isinstance(v, (int, str)):
                    rec[k] = v
            self._ring.append(rec)
        return rec

    def snapshot(self, n: int = 64) -> dict[str, Any]:
        """Most recent n decisions + totals (``/debug`` surface and
        tests)."""
        with self._lock:
            recent = list(self._ring)[-n:] if n > 0 else []
            return {
                "scale_up_total": self.scale_up_total,
                "scale_down_total": self.scale_down_total,
                "pools": dict(self._pool_sizes),
                "recent": recent,
            }

    def gauges(self) -> dict[str, float]:
        """Flat gauge dict for the /metrics surfaces. The last-decision
        age is computed at scrape time (a gauge that only moved on
        decisions would read "fresh" forever on a wedged control loop —
        the age growing without bound is exactly the wedge signal)."""
        with self._lock:
            out: dict[str, float] = {
                "planner_scale_up_total": float(self.scale_up_total),
                "planner_scale_down_total": float(self.scale_down_total),
                "planner_replaced_dead_total": float(
                    self.replaced_dead_total
                ),
            }
            for pool, n in self._pool_dead.items():
                out[f"planner_{pool}_replaced_dead_total"] = float(n)
            for pool, size in self._pool_sizes.items():
                out[f"planner_pool_size_{pool}"] = float(size)
            for pool, n in self._pool_draining.items():
                out[f"planner_pool_draining_{pool}"] = float(n)
            for pool, n in self._pool_up.items():
                out[f"planner_{pool}_scale_up_total"] = float(n)
            for pool, n in self._pool_down.items():
                out[f"planner_{pool}_scale_down_total"] = float(n)
            if self._last_decision_unix is not None:
                out["planner_last_decision_age_s"] = round(
                    max(0.0, time.time() - self._last_decision_unix), 3
                )
        return out

    def reset(self) -> None:
        """Test isolation only — serving code never resets counters."""
        with self._lock:
            self._ring.clear()
            self.scale_up_total = 0
            self.scale_down_total = 0
            self.replaced_dead_total = 0
            self._pool_sizes.clear()
            self._pool_draining.clear()
            self._pool_up.clear()
            self._pool_down.clear()
            self._pool_dead.clear()
            self._last_decision_unix = None


PLANNER_OBS = PlannerObservatory()

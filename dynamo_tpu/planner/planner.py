"""Planner: the auto-scaler watching load watermarks and scaling workers.

The reference's planner component re-designed for chips-as-unit scaling
(reference: components/planner/src/dynamo/planner/local_connector.py:105-304,
examples/llm/components/planner.py:142-380, docs/architecture/planner.md:39-49).

Control loop:
- every ``metric_interval_s``: sample the prefill-queue depth and each live
  worker's ForwardPassMetrics (KV utilization, waiting requests) via the
  metrics plane; accumulate into the current observation window.
- every ``adjustment_interval_s``: scale ±1 worker within
  [min_workers, max_workers] — up when the average queue depth or KV
  utilization crosses the high watermark, down when both sit under the low
  watermarks.

Scale-down is graceful by construction: the connector revokes the worker's
lease / SIGTERMs it, which deregisters its instances (routers drain to
survivors, proven by tests/test_multiprocess.py) while in-flight responses
finish over their TCP streams (reference: disagg_serving.md:187-194).

Connectors abstract "what is a worker": `SubprocessConnector` spawns shell
commands (the local deployment backend — circus in the reference); tests
inject an in-process connector. A k8s connector patching replica counts
slots in the same interface (kubernetes_connector.py:25-64).
"""

from __future__ import annotations

import asyncio
import logging
import signal
import subprocess
from dataclasses import dataclass, field
from typing import Protocol

from dynamo_tpu.llm.kv_router.metrics_aggregator import KvMetricsAggregator
from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics

logger = logging.getLogger(__name__)


@dataclass
class PlannerConfig:
    namespace: str = "dynamo"
    component: str = "tpu"
    min_workers: int = 1
    max_workers: int = 4          # the chip budget
    metric_interval_s: float = 1.0
    adjustment_interval_s: float = 10.0
    # Watermarks (reference defaults: planner defaults.py)
    queue_up_threshold: float = 1.0    # avg queued prefills per sample
    queue_down_threshold: float = 0.1
    kv_up_threshold: float = 0.80      # avg gpu_cache_usage_perc
    kv_down_threshold: float = 0.30
    waiting_up_threshold: float = 2.0  # avg requests waiting per worker
    waiting_down_threshold: float = 0.5  # hysteresis: don't flap around _up


class WorkerConnector(Protocol):
    """Deployment backend: spawn/retire one worker."""

    async def spawn(self) -> object: ...
    async def drain(self, handle: object) -> None: ...


class SubprocessConnector:
    """Spawns workers as OS processes from a shell command template.

    ``cmd`` runs under the shell with ``{index}`` substituted; retirement
    sends SIGTERM (prefill workers finish their current item; decode workers
    drop their lease on shutdown — reference: planner.md:39-49)."""

    def __init__(self, cmd: str) -> None:
        self.cmd = cmd
        self._count = 0

    async def spawn(self) -> subprocess.Popen:
        self._count += 1
        cmd = self.cmd.format(index=self._count)
        logger.info("planner: spawning worker: %s", cmd)
        return subprocess.Popen(cmd, shell=True, start_new_session=True)

    async def drain(self, handle: subprocess.Popen) -> None:
        logger.info("planner: draining worker pid %d", handle.pid)
        handle.send_signal(signal.SIGTERM)
        try:
            await asyncio.to_thread(handle.wait, 30)
        except subprocess.TimeoutExpired:
            # A worker stuck past the grace period (e.g. mid-XLA-compile)
            # must not keep holding its chip after the planner released it.
            logger.warning("worker pid %d ignored SIGTERM; killing", handle.pid)
            handle.kill()
            await asyncio.to_thread(handle.wait)


@dataclass
class _Window:
    """One observation window's accumulated samples."""

    queue_depths: list[int] = field(default_factory=list)
    kv_usages: list[float] = field(default_factory=list)
    waitings: list[float] = field(default_factory=list)

    def add(self, depth: int, metrics: dict[int, ForwardPassMetrics]) -> None:
        self.queue_depths.append(depth)
        if metrics:
            vals = list(metrics.values())
            self.kv_usages.append(
                sum(m.gpu_cache_usage_perc for m in vals) / len(vals)
            )
            self.waitings.append(
                sum(m.num_requests_waiting for m in vals) / len(vals)
            )

    @staticmethod
    def _avg(xs: list) -> float:
        return sum(xs) / len(xs) if xs else 0.0

    @property
    def avg_queue(self) -> float:
        return self._avg(self.queue_depths)

    @property
    def avg_kv(self) -> float:
        return self._avg(self.kv_usages)

    @property
    def avg_waiting(self) -> float:
        return self._avg(self.waitings)


class Planner:
    def __init__(
        self,
        drt,
        cfg: PlannerConfig,
        connector: WorkerConnector | None = None,
        worker_cmd: str | None = None,
    ) -> None:
        if connector is None:
            if worker_cmd is None:
                raise ValueError("need a connector or --worker-cmd")
            connector = SubprocessConnector(worker_cmd)
        from dynamo_tpu.disagg.queue import PrefillQueue

        self._drt = drt
        self.cfg = cfg
        self.connector = connector
        # Reuse PrefillQueue so the queue-name contract lives in one place.
        self._queue = PrefillQueue(drt, cfg.namespace)
        self._aggregator: KvMetricsAggregator | None = None
        self._handles: list[object] = []
        self._task: asyncio.Task | None = None
        self.decisions: list[str] = []  # audit log ("up"/"down"/"hold")

    @property
    def num_workers(self) -> int:
        return len(self._handles)

    async def start(self) -> "Planner":
        comp = self._drt.namespace(self.cfg.namespace).component(
            self.cfg.component
        )
        self._aggregator = await KvMetricsAggregator(
            self._drt, comp, interval_s=self.cfg.metric_interval_s
        ).start()
        while len(self._handles) < self.cfg.min_workers:
            self._handles.append(await self.connector.spawn())
        self._task = asyncio.ensure_future(self._run())
        return self

    async def _run(self) -> None:
        window = _Window()
        next_adjust = (
            asyncio.get_running_loop().time() + self.cfg.adjustment_interval_s
        )
        while True:
            try:
                depth = await self._queue.depth()
                window.add(depth, self._aggregator.endpoints.metrics)
            except asyncio.CancelledError:
                return
            except Exception:
                logger.exception("planner metric sample failed")
            if asyncio.get_running_loop().time() >= next_adjust:
                try:
                    await self._adjust(window)
                except asyncio.CancelledError:
                    return
                except Exception:
                    logger.exception("planner adjustment failed")
                window = _Window()
                next_adjust = (
                    asyncio.get_running_loop().time()
                    + self.cfg.adjustment_interval_s
                )
            await asyncio.sleep(self.cfg.metric_interval_s)

    async def _adjust(self, w: _Window) -> None:
        cfg = self.cfg
        n = len(self._handles)
        pressure = (
            w.avg_queue > cfg.queue_up_threshold
            or w.avg_kv > cfg.kv_up_threshold
            or w.avg_waiting > cfg.waiting_up_threshold
        )
        idle = (
            w.avg_queue < cfg.queue_down_threshold
            and w.avg_kv < cfg.kv_down_threshold
            and w.avg_waiting < cfg.waiting_down_threshold
        )
        if pressure and n < cfg.max_workers:
            logger.info(
                "planner: scale UP %d->%d (queue %.2f kv %.2f waiting %.2f)",
                n, n + 1, w.avg_queue, w.avg_kv, w.avg_waiting,
            )
            self._handles.append(await self.connector.spawn())
            self.decisions.append("up")
        elif idle and n > cfg.min_workers:
            logger.info(
                "planner: scale DOWN %d->%d (queue %.2f kv %.2f)",
                n, n - 1, w.avg_queue, w.avg_kv,
            )
            handle = self._handles.pop()
            await self.connector.drain(handle)
            self.decisions.append("down")
        else:
            self.decisions.append("hold")

    async def stop(self, drain_workers: bool = False) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._aggregator is not None:
            await self._aggregator.stop()
        if drain_workers:
            while self._handles:
                await self.connector.drain(self._handles.pop())

"""Planner: the auto-scaler watching load watermarks and scaling workers.

The reference's planner component re-designed for chips-as-unit scaling
(reference: components/planner/src/dynamo/planner/local_connector.py:105-304,
examples/llm/components/planner.py:142-380, docs/architecture/planner.md:39-49).

Control loop:
- every ``metric_interval_s``: sample the prefill-queue depth and each live
  worker's ForwardPassMetrics (KV utilization, waiting requests) via the
  metrics plane; accumulate into the current observation window.
- every ``adjustment_interval_s``: scale ±1 worker within
  [min_workers, max_workers] — up when the average queue depth or KV
  utilization crosses the high watermark, down when both sit under the low
  watermarks.

Scale-down is graceful by construction: the connector revokes the worker's
lease / SIGTERMs it, which deregisters its instances (routers drain to
survivors, proven by tests/test_multiprocess.py) while in-flight responses
finish over their TCP streams (reference: disagg_serving.md:187-194).

Connectors abstract "what is a worker": `SubprocessConnector` spawns shell
commands (the local deployment backend — circus in the reference); tests
inject an in-process connector. A k8s connector patching replica counts
slots in the same interface (kubernetes_connector.py:25-64).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import signal
import subprocess
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Protocol

from dynamo_tpu.llm.kv_router.metrics_aggregator import KvMetricsAggregator
from dynamo_tpu.llm.kv_router.protocols import ForwardPassMetrics
from dynamo_tpu.utils.atomic_io import atomic_write_text

logger = logging.getLogger(__name__)


@dataclass
class PlannerConfig:
    namespace: str = "dynamo"
    component: str = "tpu"
    min_workers: int = 1
    max_workers: int = 4          # the chip budget
    metric_interval_s: float = 1.0
    adjustment_interval_s: float = 10.0
    # Watermarks (reference defaults: planner defaults.py)
    queue_up_threshold: float = 1.0    # avg queued prefills per sample
    queue_down_threshold: float = 0.1
    kv_up_threshold: float = 0.80      # avg gpu_cache_usage_perc
    kv_down_threshold: float = 0.30
    waiting_up_threshold: float = 2.0  # avg requests waiting per worker
    waiting_down_threshold: float = 0.5  # hysteresis: don't flap around _up
    # Checkpoint file for crash/restart resume (reference: local connector
    # state ~/.dynamo/state/{ns}.json). None disables persistence.
    state_path: str | None = None
    # SLA-driven scaling (reference: planner.md:53-90 profiled TTFT/ITL
    # interpolation): when a PerfProfile is set on the Planner and either
    # bound is given, the adjustment targets load/capacity directly
    # (±1 per interval toward the target) instead of pure watermarks.
    ttft_sla_ms: float | None = None
    itl_sla_ms: float | None = None
    # Scaling-decision time series: one JSONL line per adjustment tick
    # ({ts, decision, workers, queue, kv, waiting[, load, target]}) — the
    # after-the-fact inspection artifact the reference gets from its
    # TensorBoard logging (docs/architecture/planner.md:104,131). None
    # disables.
    decision_log_path: str | None = None


class WorkerConnector(Protocol):
    """Deployment backend: spawn/retire one worker. ``alive`` is
    optional — connectors exposing it opt their pools into crash
    healing (pools.WorkerPool.reap_dead)."""

    async def spawn(self) -> object: ...
    async def drain(self, handle: object) -> None: ...


class SubprocessConnector:
    """Spawns workers as OS processes from a shell command template.

    ``cmd`` runs under the shell with ``{index}`` substituted; retirement
    sends SIGTERM (prefill workers finish their current item; decode workers
    drop their lease on shutdown — reference: planner.md:39-49)."""

    def __init__(self, cmd: str) -> None:
        self.cmd = cmd
        self._count = 0

    async def spawn(self) -> subprocess.Popen:
        self._count += 1
        cmd = self.cmd.format(index=self._count)
        logger.info("planner: spawning worker: %s", cmd)
        # fork/exec can stall the loop for tens of ms under memory
        # pressure; the planner shares its loop with the metrics watch.
        return await asyncio.to_thread(
            subprocess.Popen, cmd, shell=True, start_new_session=True
        )

    # Checkpointed alongside the worker pids so a planner restart doesn't
    # hand out {index} values still held by adopted workers.
    def snapshot(self) -> dict:
        return {"count": self._count}

    def restore(self, state: dict) -> None:
        self._count = max(self._count, int(state.get("count", 0)))

    def alive(self, handle) -> bool:
        """Crash detection for pools.reap_dead: a spawned Popen that
        exited (poll() returns its code) or an adopted pid that vanished
        is DEAD — it gets replaced immediately, with none of drain's
        grace accounting (crash ≠ drain)."""
        poll = getattr(handle, "poll", None)
        if poll is not None:
            return poll() is None
        try:
            os.kill(handle.pid, 0)
        except (ProcessLookupError, PermissionError):
            return False
        return True

    async def drain(self, handle) -> None:
        logger.info("planner: draining worker pid %d", handle.pid)
        handle.send_signal(signal.SIGTERM)
        try:
            await asyncio.to_thread(handle.wait, 30)
        except subprocess.TimeoutExpired:
            # A worker stuck past the grace period (e.g. mid-XLA-compile)
            # must not keep holding its chip after the planner released it.
            logger.warning("worker pid %d ignored SIGTERM; killing", handle.pid)
            handle.kill()
            await asyncio.to_thread(handle.wait)

    def adopt(self, pid: int, started: float | None = None):
        """Re-attach a worker from a previous planner life (checkpoint
        resume). Returns a drain-able handle, or None if the pid is gone —
        or was RECYCLED: the checkpointed process start time must match, so
        the planner never SIGTERMs an unrelated process that inherited the
        pid after a reboot/crash."""
        try:
            os.kill(pid, 0)
        except (ProcessLookupError, PermissionError):
            return None
        if started is not None:
            now_started = _proc_start_ticks(pid)
            if now_started is not None and now_started != started:
                logger.info(
                    "planner: pid %d was recycled (start %s != %s); "
                    "not adopting", pid, now_started, started,
                )
                return None
        return _AdoptedProcess(pid)


def _proc_start_ticks(pid: int) -> float | None:
    """Kernel start time of `pid` in clock ticks (/proc/<pid>/stat field 22);
    None where /proc isn't available."""
    try:
        stat = Path(f"/proc/{pid}/stat").read_text()
    except OSError:
        return None
    # Field 2 (comm) may contain spaces/parens — split after the last ')'.
    fields = stat.rsplit(")", 1)[-1].split()
    return float(fields[19])  # 22nd overall; 20th after pid+comm


class _AdoptedProcess:
    """A worker process we didn't spawn this life but still own: quacks
    enough like Popen for SubprocessConnector.drain."""

    def __init__(self, pid: int) -> None:
        self.pid = pid

    def send_signal(self, sig: int) -> None:
        try:
            os.kill(self.pid, sig)
        except ProcessLookupError:
            pass

    def kill(self) -> None:
        self.send_signal(signal.SIGKILL)

    def wait(self, timeout: float | None = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                os.kill(self.pid, 0)
            except (ProcessLookupError, PermissionError):
                return
            if deadline is not None and time.monotonic() > deadline:
                raise subprocess.TimeoutExpired(f"pid {self.pid}", timeout)
            time.sleep(0.1)


@dataclass
class _Window:
    """One observation window's accumulated samples."""

    queue_depths: list[int] = field(default_factory=list)
    kv_usages: list[float] = field(default_factory=list)
    waitings: list[float] = field(default_factory=list)
    loads: list[float] = field(default_factory=list)  # total concurrency

    def add(self, depth: int, metrics: dict[int, ForwardPassMetrics]) -> None:
        self.queue_depths.append(depth)
        # Observed total concurrent demand (the perf profile's concurrency
        # axis): running + waiting across the pool, OR the queue depth when
        # it's larger / when no metrics arrive. max() rather than sum
        # because a queued remote prefill is usually ALSO an admitted
        # decode-side slot — summing would double-count every disagg
        # request — while depth alone keeps a backlog visible when the
        # metrics plane is empty (fresh spawn, crashed workers).
        load = float(depth)
        if metrics:
            vals = list(metrics.values())
            self.kv_usages.append(
                sum(m.gpu_cache_usage_perc for m in vals) / len(vals)
            )
            self.waitings.append(
                sum(m.num_requests_waiting for m in vals) / len(vals)
            )
            load = max(
                load,
                float(
                    sum(
                        m.request_active_slots + m.num_requests_waiting
                        for m in vals
                    )
                ),
            )
        self.loads.append(load)

    @staticmethod
    def _avg(xs: list) -> float:
        return sum(xs) / len(xs) if xs else 0.0

    @property
    def avg_queue(self) -> float:
        return self._avg(self.queue_depths)

    @property
    def avg_kv(self) -> float:
        return self._avg(self.kv_usages)

    @property
    def avg_waiting(self) -> float:
        return self._avg(self.waitings)

    @property
    def avg_load(self) -> float:
        return self._avg(self.loads)


class Planner:
    def __init__(
        self,
        drt,
        cfg: PlannerConfig,
        connector: WorkerConnector | None = None,
        worker_cmd: str | None = None,
        profile=None,  # PerfProfile for SLA-driven scaling (profiles.py)
    ) -> None:
        if connector is None:
            if worker_cmd is None:
                raise ValueError("need a connector or --worker-cmd")
            connector = SubprocessConnector(worker_cmd)
        from dynamo_tpu.disagg.queue import PrefillQueue

        self._drt = drt
        self.cfg = cfg
        self.connector = connector
        # Reuse PrefillQueue so the queue-name contract lives in one place.
        self._queue = PrefillQueue(drt, cfg.namespace)
        self._aggregator: KvMetricsAggregator | None = None
        self._handles: list[object] = []
        self._task: asyncio.Task | None = None
        self.decisions: list[str] = []  # audit log ("up"/"down"/"hold")
        self.profile = profile

    @property
    def num_workers(self) -> int:
        return len(self._handles)

    # -- checkpoint/resume (reference: local_connector state file) ---------
    def _save_state(self) -> None:
        if self.cfg.state_path is None:
            return
        path = Path(self.cfg.state_path)
        path.parent.mkdir(parents=True, exist_ok=True)
        workers = []
        for h in self._handles:
            pid = getattr(h, "pid", None)
            workers.append(
                {
                    "pid": pid,
                    "started": (
                        _proc_start_ticks(pid) if pid is not None else None
                    ),
                }
            )
        snapshot = getattr(self.connector, "snapshot", None)
        state = {
            "namespace": self.cfg.namespace,
            "workers": workers,
            "connector": snapshot() if snapshot is not None else {},
            "decisions": self.decisions[-32:],
            "ts": time.time(),
        }
        # Atomic AND durable (utils/atomic_io): the bare rename left the
        # replace able to roll back to a zero-length file across power
        # loss — which _resume_state would read as "start fresh" and
        # orphan every checkpointed worker.
        atomic_write_text(path, json.dumps(state))

    def _resume_state(self) -> None:
        if self.cfg.state_path is None:
            return
        path = Path(self.cfg.state_path)
        if not path.exists():
            return
        try:
            state = json.loads(path.read_text())
        except ValueError:
            logger.warning("planner state %s unreadable; starting fresh", path)
            return
        if isinstance(state, dict) and (
            state.get("version", 1) >= 2 or "pools" in state
        ):
            # A two-pool fleet checkpoint (planner/fleet.py). Silently
            # ignoring it would adopt NOTHING, spawn fresh workers, and
            # then overwrite the file in v1 format — orphaning every
            # worker the fleet planner had checkpointed (they'd hold
            # their chips forever, unmanaged). Refuse loudly instead.
            raise RuntimeError(
                f"planner state {path} was written by the two-pool fleet "
                "planner — restart with --two-pool (or move the state "
                "file) instead of orphaning its workers"
            )
        restore = getattr(self.connector, "restore", None)
        if restore is not None and state.get("connector"):
            restore(state["connector"])
        adopt = getattr(self.connector, "adopt", None)
        if adopt is None:
            return
        alive = 0
        for w in state.get("workers") or []:
            if isinstance(w, dict):
                pid, started = w.get("pid"), w.get("started")
            else:  # older state files stored bare pids
                pid, started = w, None
            if pid is None:
                continue
            try:
                handle = adopt(pid, started)
            except TypeError:  # connector with a pid-only adopt()
                handle = adopt(pid)
            if handle is not None:
                self._handles.append(handle)
                alive += 1
        if alive:
            logger.info(
                "planner: resumed %d worker(s) from %s", alive, path
            )

    async def start(self) -> "Planner":
        comp = self._drt.namespace(self.cfg.namespace).component(
            self.cfg.component
        )
        self._aggregator = await KvMetricsAggregator(
            self._drt, comp, interval_s=self.cfg.metric_interval_s
        ).start()
        self._resume_state()
        while len(self._handles) < self.cfg.min_workers:
            self._handles.append(await self.connector.spawn())
        self._save_state()
        self._task = asyncio.ensure_future(self._run())
        return self

    async def _run(self) -> None:
        window = _Window()
        next_adjust = (
            asyncio.get_running_loop().time() + self.cfg.adjustment_interval_s
        )
        while True:
            try:
                depth = await self._queue.depth()
                window.add(depth, self._aggregator.endpoints.metrics)
            except asyncio.CancelledError:
                return
            except Exception:
                logger.exception("planner metric sample failed")
            if asyncio.get_running_loop().time() >= next_adjust:
                try:
                    await self._adjust(window)
                except asyncio.CancelledError:
                    return
                except Exception:
                    logger.exception("planner adjustment failed")
                window = _Window()
                next_adjust = (
                    asyncio.get_running_loop().time()
                    + self.cfg.adjustment_interval_s
                )
            await asyncio.sleep(self.cfg.metric_interval_s)

    async def _adjust(self, w: _Window) -> None:
        cfg = self.cfg
        n = len(self._handles)
        if self.profile is not None and (
            cfg.ttft_sla_ms is not None or cfg.itl_sla_ms is not None
        ):
            await self._adjust_sla(w, n)
            return
        pressure = (
            w.avg_queue > cfg.queue_up_threshold
            or w.avg_kv > cfg.kv_up_threshold
            or w.avg_waiting > cfg.waiting_up_threshold
        )
        idle = (
            w.avg_queue < cfg.queue_down_threshold
            and w.avg_kv < cfg.kv_down_threshold
            and w.avg_waiting < cfg.waiting_down_threshold
        )
        if pressure and n < cfg.max_workers:
            logger.info(
                "planner: scale UP %d->%d (queue %.2f kv %.2f waiting %.2f)",
                n, n + 1, w.avg_queue, w.avg_kv, w.avg_waiting,
            )
            self._handles.append(await self.connector.spawn())
            self.decisions.append("up")
        elif idle and n > cfg.min_workers:
            logger.info(
                "planner: scale DOWN %d->%d (queue %.2f kv %.2f)",
                n, n - 1, w.avg_queue, w.avg_kv,
            )
            handle = self._handles.pop()
            await self.connector.drain(handle)
            self.decisions.append("down")
        else:
            self.decisions.append("hold")
        self._log_decision(w)
        self._save_state()

    async def _adjust_sla(self, w: _Window, n: int) -> None:
        """Profile-driven scaling (reference: planner.md:53-90): workers
        needed = observed load / per-worker SLA capacity, stepped ±1 per
        interval toward the target within the chip budget."""
        cfg = self.cfg
        target = self.profile.target_workers(
            w.avg_load,
            ttft_sla_ms=cfg.ttft_sla_ms,
            itl_sla_ms=cfg.itl_sla_ms,
        )
        target = max(cfg.min_workers, min(cfg.max_workers, target))
        if target > n:
            logger.info(
                "planner[sla]: scale UP %d->%d (load %.1f, target %d)",
                n, n + 1, w.avg_load, target,
            )
            self._handles.append(await self.connector.spawn())
            self.decisions.append("up")
        elif target < n:
            logger.info(
                "planner[sla]: scale DOWN %d->%d (load %.1f, target %d)",
                n, n - 1, w.avg_load, target,
            )
            await self.connector.drain(self._handles.pop())
            self.decisions.append("down")
        else:
            self.decisions.append("hold")
        self._log_decision(w, load=w.avg_load, target=target)
        self._save_state()

    def _log_decision(self, w: _Window, **extra) -> None:
        """Append one adjustment tick to the decision JSONL (see
        PlannerConfig.decision_log_path). Append-only so an operator can
        tail/plot it live; write failures never break the control loop.

        The same decision also lands on the metric surfaces and in the
        ``DYNTPU_TRACE`` capture via the planner observatory
        (planner/obs.py) — the JSONL used to be the ONLY sink, which
        left a flapping planner invisible to Prometheus. The legacy
        single pool reports under the pool name ``worker``."""
        from dynamo_tpu.planner.obs import PLANNER_OBS
        from dynamo_tpu.utils.tracing import tracer

        decision = self.decisions[-1] if self.decisions else "hold"
        rec = PLANNER_OBS.note_decision(
            "worker",
            decision,
            len(self._handles),
            signals={
                "queue": w.avg_queue,
                "kv": w.avg_kv,
                "waiting": w.avg_waiting,
                **extra,
            },
        )
        tracer().export(rec)
        if self.cfg.decision_log_path is None:
            return
        line = {
            "ts": round(time.time(), 3),
            "decision": decision,
            "workers": len(self._handles),
            "queue": round(w.avg_queue, 4),
            "kv": round(w.avg_kv, 4),
            "waiting": round(w.avg_waiting, 4),
            **{k: round(v, 4) for k, v in extra.items()},
        }
        try:
            path = Path(self.cfg.decision_log_path)
            path.parent.mkdir(parents=True, exist_ok=True)
            with path.open("a") as f:
                f.write(json.dumps(line) + "\n")
        except OSError as exc:
            logger.warning("planner decision log write failed: %s", exc)

    async def stop(self, drain_workers: bool = False) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None
        if self._aggregator is not None:
            await self._aggregator.stop()
        if drain_workers:
            while self._handles:
                await self.connector.drain(self._handles.pop())
        self._save_state()

"""Worker pools: the per-phase scaling unit of the fleet planner.

The single-pool ``Planner`` (planner/planner.py) scales one homogeneous
worker set off averaged signals. A disaggregated deployment has two
POPULATIONS with different physics (docs/architecture/planner.md):

- **prefill** workers are queue consumers — the right scaling signal is
  the shared prefill queue's depth (per live worker) and the age of its
  oldest item (depth alone misses a stalled pool);
- **decode** workers hold long-lived streams — the right signals are KV
  utilization and the decode ITL EMA the coloc controller already
  exports per worker (``ForwardPassMetrics.itl_ema_ms``).

Each :class:`WorkerPool` owns its handles, its scaling law, and its
hysteresis state, so a queue-driven prefill scale-up never touches the
decode pool and vice versa. Drain semantics differ by construction and
are enforced by tests (tests/test_fleet_planner.py):

- a shrinking **decode** pool DRAINS, never kills: the connector's
  retirement path (SIGTERM / control-plane drain verb — both funnel
  into cli.py ``_graceful_drain``, docs/architecture/
  overload_and_drain.md) finishes in-flight streams before exit;
- a shrinking **prefill** pool REQUEUES, never drops: queued items live
  on the shared bus work queue (survivors keep consuming), and the
  retired worker's leased-but-unacked item redelivers exactly once
  (at-least-once lease semantics + the decode side's completeness
  ledger de-duplicate the landing).

Scale-downs run as tracked background tasks: a 30 s subprocess grace
period must not freeze the OTHER pool's control loop.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass, field

from dynamo_tpu.planner.obs import PLANNER_OBS
from dynamo_tpu.utils.task import spawn_tracked

logger = logging.getLogger(__name__)


@dataclass
class FleetSample:
    """One observation window's averaged signals, shared by both laws.

    The fleet planner accumulates raw samples (fleet.py ``_Window``)
    and hands each pool this digest at adjustment time; a law reads
    only the axes it owns.

    The two ``*_seen``/``*_samples`` fields are COVERAGE, not load: a
    dead metrics plane or a failing queue probe yields all-zero
    averages that would otherwise read as "idle" and shed capacity
    under a telemetry blip — a blind window must HOLD instead. They
    default to 1 (sighted) so hand-built samples in tests/tools carry
    their face value; only the fleet planner's window digest, which
    knows whether samples actually arrived, reports 0."""

    queue_depth: float = 0.0        # avg queued prefills
    queue_age_s: float = 0.0        # avg oldest-item age
    kv_usage: float = 0.0           # avg gpu_cache_usage_perc (decode pool)
    waiting: float = 0.0            # avg requests waiting per decode worker
    itl_ema_ms: float = 0.0         # avg decode ITL EMA across the pool
    # Per-SLO-class split of the waiting depth (llm/slo.py; the workers'
    # num_waiting_{interactive,batch} gauges). Zero/zero means the
    # deployment is class-blind (pre-SLO workers) and the laws fall back
    # to the unsplit ``waiting`` axis.
    waiting_interactive: float = 0.0
    waiting_batch: float = 0.0
    decode_workers_seen: int = 1    # decode metrics-plane coverage (0=blind)
    queue_samples: int = 1          # queue-probe coverage (0 = blind)


@dataclass
class PrefillLaw:
    """Queue depth/age-driven law. Thresholds are PER LIVE WORKER on the
    depth axis — 8 queued items are pressure for one worker and idle
    backlog for sixteen — while the age bound is absolute: one item
    older than ``age_up_s`` means the pool is stalled at ANY size."""

    queue_up_per_worker: float = 1.0
    queue_down_per_worker: float = 0.1
    age_up_s: float = 5.0

    def decide(self, s: FleetSample, n: int) -> str:
        per_worker = s.queue_depth / max(n, 1)
        if per_worker > self.queue_up_per_worker or s.queue_age_s > self.age_up_s:
            return "up"
        if s.queue_samples == 0:
            # Blind window: every queue probe failed, so the zeros above
            # are absence of telemetry, not absence of work — never
            # shed capacity on a control-plane blip.
            return "hold"
        if (
            per_worker < self.queue_down_per_worker
            and s.queue_age_s < self.age_up_s / 2
        ):
            return "down"
        return "hold"

    def signals(self, s: FleetSample) -> dict:
        return {"queue": s.queue_depth, "queue_age_s": s.queue_age_s}


@dataclass
class DecodeLaw:
    """KV-utilization + ITL-driven law. ITL bounds are optional (None =
    axis off): with an SLO configured, a pool running hot on ITL scales
    up even at low KV occupancy (many short sequences saturate compute
    before memory). Scale-down requires EVERY axis under its low
    watermark — any single hot axis holds the pool.

    The waiting axis is SLO-class-weighted (llm/slo.py;
    docs/architecture/ingress_scale.md): when the scraped metrics carry
    the per-class split, interactive waiters count at full weight and
    batch waiters at ``batch_weight`` — a deep queue of batch work is
    real pressure but not an interactive-latency emergency, so the pool
    grows for it more slowly than for the same depth of humans waiting.
    Class-blind samples (both splits zero) fall back to the unsplit
    depth unchanged."""

    kv_up_threshold: float = 0.80
    kv_down_threshold: float = 0.30
    waiting_up_per_worker: float = 2.0
    waiting_down_per_worker: float = 0.5
    itl_up_ms: float | None = None
    itl_down_ms: float | None = None
    batch_weight: float = 0.5

    def effective_waiting(self, s: FleetSample) -> float:
        """Class-weighted waiting depth. Only waiting that is POSITIVELY
        attributed to the batch class is discounted; any residual
        between the unsplit axis and the split sum (class-blind workers
        in a mixed/rolling-upgrade fleet report zeros for the split
        fields) counts at FULL weight — otherwise one upgraded worker's
        tiny split would mask nine pre-upgrade workers' real backlog
        and the pool would shed capacity under load."""
        split = s.waiting_interactive + s.waiting_batch
        unattributed = max(0.0, s.waiting - split)
        return (
            s.waiting_interactive
            + self.batch_weight * s.waiting_batch
            + unattributed
        )

    def decide(self, s: FleetSample, n: int) -> str:
        waiting = self.effective_waiting(s)
        if (
            s.kv_usage > self.kv_up_threshold
            or waiting > self.waiting_up_per_worker
            or (self.itl_up_ms is not None and s.itl_ema_ms > self.itl_up_ms)
        ):
            return "up"
        if s.decode_workers_seen == 0:
            # Blind window: the metrics plane produced NOTHING, so the
            # all-zero averages are a telemetry outage, not an idle
            # fleet — a loaded pool must not be drained on a blip.
            return "hold"
        idle = (
            s.kv_usage < self.kv_down_threshold
            and waiting < self.waiting_down_per_worker
        )
        if idle and self.itl_down_ms is not None:
            idle = s.itl_ema_ms < self.itl_down_ms
        return "down" if idle else "hold"

    def signals(self, s: FleetSample) -> dict:
        return {
            "kv": s.kv_usage,
            "waiting": round(self.effective_waiting(s), 3),
            "waiting_interactive": s.waiting_interactive,
            "waiting_batch": s.waiting_batch,
            "itl_ema_ms": s.itl_ema_ms,
        }


@dataclass
class PoolConfig:
    name: str                       # "prefill" | "decode" (gauge suffix)
    min_workers: int = 1
    max_workers: int = 4
    # Hysteresis: scale-up reacts immediately (an overloaded pool is the
    # expensive failure) but respects a cooldown so one hot window can't
    # ladder straight to max; scale-down additionally needs
    # ``down_consecutive`` idle adjustment windows in a row — a single
    # quiet window between bursts must not shed capacity the next burst
    # re-pays cold-start for.
    up_cooldown_s: float = 0.0
    down_cooldown_s: float = 0.0
    down_consecutive: int = 2


class WorkerPool:
    """One elastic worker population: handles + law + hysteresis."""

    def __init__(self, cfg: PoolConfig, connector, law) -> None:
        self.cfg = cfg
        self.connector = connector
        self.law = law
        self.handles: list[object] = []
        self.decisions: list[str] = []      # audit tail ("up"/"down"/"hold")
        self._idle_streak = 0
        self._last_up_mono: float | None = None
        self._last_down_mono: float | None = None
        self._drain_tasks: set[asyncio.Task] = set()

    @property
    def size(self) -> int:
        return len(self.handles)

    @property
    def draining(self) -> int:
        return len(self._drain_tasks)

    def _note_size(self) -> None:
        PLANNER_OBS.note_size(self.cfg.name, self.size, self.draining)

    async def ensure_min(self) -> None:
        while len(self.handles) < self.cfg.min_workers:
            self.handles.append(await self.connector.spawn())
        self._note_size()

    async def reap_dead(self) -> int:
        """Crash handling — distinct from drain by construction
        (docs/architecture/failure_model.md "Mid-stream failover"): a
        DEAD worker (process exit, missed heartbeats — whatever the
        connector's ``alive()`` judges) left ``handles`` without ever
        passing through retirement, so there is nothing to drain — no
        grace period, no drain task, no drain accounting. It is removed
        and REPLACED IMMEDIATELY at target size: the fleet heals to the
        capacity the laws last decided, instead of serving a silent
        worker-sized hole until the next scale-up window. Returns the
        number replaced. Connectors without ``alive()`` opt out (0)."""
        alive = getattr(self.connector, "alive", None)
        if alive is None:
            return 0
        dead = [h for h in self.handles if not alive(h)]
        if not dead:
            return 0
        for h in dead:
            self.handles.remove(h)
        replaced = 0
        for h in dead:
            logger.warning(
                "planner[%s]: worker %s died — replacing immediately "
                "(crash path, no drain)", self.cfg.name,
                getattr(h, "pid", h),
            )
            try:
                self.handles.append(await self.connector.spawn())
                replaced += 1
            except Exception:  # noqa: BLE001 — next tick retries via ensure_min
                logger.exception(
                    "planner[%s]: replacement spawn failed", self.cfg.name
                )
        if replaced:
            # Count what actually HEALED, not what died: a spawn-backend
            # outage must not report a fleet at target when it is short
            # (the next tick's reap/ensure_min retries the deficit).
            rec = PLANNER_OBS.note_replaced_dead(self.cfg.name, replaced)
            from dynamo_tpu.utils.tracing import tracer

            tracer().export(rec)
        self._note_size()
        return replaced

    async def adjust(self, sample: FleetSample) -> str:
        """One adjustment tick: law verdict → hysteresis → action.
        Returns the APPLIED decision ("hold" when hysteresis or bounds
        vetoed the law)."""
        loop_now = asyncio.get_running_loop().time()
        n = self.size
        want = self.law.decide(sample, n)
        decision = "hold"
        if want == "down":
            self._idle_streak += 1
        else:
            self._idle_streak = 0
        if want == "up" and n < self.cfg.max_workers:
            if (
                self._last_up_mono is None
                or loop_now - self._last_up_mono >= self.cfg.up_cooldown_s
            ):
                logger.info(
                    "planner[%s]: scale UP %d->%d (%s)",
                    self.cfg.name, n, n + 1, self.law.signals(sample),
                )
                self.handles.append(await self.connector.spawn())
                self._last_up_mono = loop_now
                decision = "up"
        elif want == "down" and n > self.cfg.min_workers:
            cooled = (
                self._last_down_mono is None
                or loop_now - self._last_down_mono >= self.cfg.down_cooldown_s
            )
            if cooled and self._idle_streak >= self.cfg.down_consecutive:
                logger.info(
                    "planner[%s]: scale DOWN %d->%d (%s)",
                    self.cfg.name, n, n - 1, self.law.signals(sample),
                )
                self._retire(self.handles.pop())
                self._last_down_mono = loop_now
                self._idle_streak = 0
                decision = "down"
        self.decisions.append(decision)
        return decision

    def _retire(self, handle) -> None:
        """Graceful retirement in the background: the connector's drain
        (SIGTERM → cli.py ``_graceful_drain`` / lease revoke) finishes
        in-flight work; the control loop must not block on the grace
        period. The handle leaves ``handles`` NOW (capacity accounting)
        and the drain task is tracked until completion."""

        async def _drain() -> None:
            try:
                await self.connector.drain(handle)
            finally:
                self._drain_tasks.discard(task)
                self._note_size()

        task = spawn_tracked(
            _drain(), name=f"planner-drain-{self.cfg.name}"
        )
        self._drain_tasks.add(task)
        self._note_size()

    async def drain_all(self) -> None:
        """Retire every worker and wait for all drains (planner stop)."""
        while self.handles:
            self._retire(self.handles.pop())
        await self.wait_drained()

    async def wait_drained(self) -> None:
        while self._drain_tasks:
            await asyncio.gather(*list(self._drain_tasks),
                                 return_exceptions=True)
        self._note_size()

    # -- checkpoint (fleet.py owns the file; pools own their slice) --------
    def snapshot_workers(self) -> list[dict]:
        from dynamo_tpu.planner.planner import _proc_start_ticks

        out = []
        for h in self.handles:
            pid = getattr(h, "pid", None)
            out.append(
                {
                    "pid": pid,
                    "started": (
                        _proc_start_ticks(pid) if pid is not None else None
                    ),
                }
            )
        return out

    def restore_workers(self, workers: list) -> int:
        """Adopt still-alive workers from a checkpoint slice. Start-tick
        mismatches (recycled PIDs) are REFUSED by the connector — the
        planner must never manage a stranger process that inherited a
        pid (tests/test_fleet_planner.py regression)."""
        adopt = getattr(self.connector, "adopt", None)
        if adopt is None:
            return 0
        alive = 0
        for w in workers or []:
            if isinstance(w, dict):
                pid, started = w.get("pid"), w.get("started")
            else:  # oldest state files stored bare pids
                pid, started = w, None
            if pid is None:
                continue
            try:
                handle = adopt(pid, started)
            except TypeError:  # connector with a pid-only adopt()
                handle = adopt(pid)
            if handle is not None:
                self.handles.append(handle)
                alive += 1
        self._note_size()
        return alive


def default_pools(
    prefill_connector,
    decode_connector,
    prefill_cfg: PoolConfig | None = None,
    decode_cfg: PoolConfig | None = None,
    prefill_law: PrefillLaw | None = None,
    decode_law: DecodeLaw | None = None,
) -> tuple[WorkerPool, WorkerPool]:
    """The standard two-pool wiring (CLI + tests)."""
    return (
        WorkerPool(
            prefill_cfg or PoolConfig(name="prefill"),
            prefill_connector,
            prefill_law or PrefillLaw(),
        ),
        WorkerPool(
            decode_cfg or PoolConfig(name="decode"),
            decode_connector,
            decode_law or DecodeLaw(),
        ),
    )

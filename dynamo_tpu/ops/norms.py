"""Normalization ops (RMSNorm). XLA fuses these into surrounding matmuls;
no Pallas needed."""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    """RMSNorm in fp32 accumulation, cast back to input dtype."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    normed = xf * jnp.reciprocal(jnp.sqrt(var + eps))
    return (normed * weight.astype(jnp.float32)).astype(dtype)

"""Ragged paged-attention Pallas kernels (decode + prefill).

Same math as the jnp reference (ops/attention.py — the test oracle); the
kernels add what XLA can't express over a paged cache:
- each sequence loops only over ITS OWN blocks (``cdiv(context_len, bs)``
  trip count) instead of scanning the full ``max_blocks`` table;
- KV pages stream HBM→VMEM with double-buffered async DMA (linear copies
  at full bandwidth, not XLA gathers);
- score/PV matmuls batch over kv heads with the query-group dim folded
  into rows, keeping the MXU shapes sane for GQA.

Cache-layout contract (Mosaic DMA constraints drove this):
- logical cache stays ``[num_slots, kvH, D]`` (ops/attention.py contract);
- the kernels view it as pages ``[num_blocks, bs*kvH, D]`` — a free
  contiguous reshape whose trailing 2D ``(bs*kvH, D)`` tiles exactly on
  (sublane, 128-lane) boundaries, which page slicing for DMA requires;
- therefore ``D % 128 == 0`` inside the kernel. Models with smaller head
  dims (Llama-3.2-1B: D=64) run with lane-PADDED caches: the engine
  allocates ``[num_slots, kvH, 128]``, K/V scatter zero-pads, and the
  padding is mathematically transparent to attention (zero lanes add
  nothing to scores or outputs). ``pallas_supported()`` gates the path;
  unsupported shapes fall back to the jnp reference.
- inside the kernel, per-page refs are re-viewed as ``[bs, kvH, D]`` via
  ``Ref.reshape`` (a sublane-merge view, which Mosaic supports — lane
  splits are not) and consumed by dot_generals whose batch dim sits at
  different positions per operand, avoiding any VMEM transposes.

Reference provenance: the reference delegates paged attention to
vLLM/FlashAttention CUDA kernels (SURVEY §2 'Native components' #3 makes a
TPU-native kernel our job); blockwise online softmax per the
ragged-paged-attention recipe in PAPERS.md.

On CPU backends (tests, virtual mesh) the kernels run in Pallas interpret
mode — same code path, no Mosaic compile.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamo_tpu.utils.jax_compat import MEMORY_SPACE_ANY

NEG_INF = -1e30
LANE = 128


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def pallas_supported(block_size: int, kvH: int, D: int, dtype) -> bool:
    """Shapes the compiled kernels can handle. Interpret mode (non-TPU)
    has no tiling constraints but keeps the same gate so tests cover the
    production envelope."""
    # Min sublane tile per dtype width: f32 8, bf16 16, int8 32 (the
    # quantized-KV cache dtype — docs/architecture/kv_quant.md).
    sublane = {1: 32, 2: 16}.get(jnp.dtype(dtype).itemsize, 8)
    return D % LANE == 0 and (block_size * kvH) % sublane == 0


def cache_head_dim(D: int) -> int:
    """Lane-padded head dim for cache allocation under the Pallas path."""
    return ((D + LANE - 1) // LANE) * LANE


# ---------------------------------------------------------------------------
# Decode: one query token per sequence.
# ---------------------------------------------------------------------------


# DMA ring depth for the decode kernel's KV page stream. Pages are small
# (bs*kvH x D ~= 32 KB at 1B shapes), so per-copy LATENCY — not bytes —
# bounds the stream at depth 2; a deeper ring keeps ~2*(NBUF-1) copies in
# flight and lets the HBM controller pipeline them (measured 2.4x on the
# in-scan decode step at B=32, ctx 192, 1B shapes).
DECODE_NBUF = 8
# Pages folded into one decode pipeline step (one wait + one attention
# fold per PP pages): amortizes per-iteration fixed costs (loop scalars,
# mask/softmax VPU ops) and widens the score matmuls' key dimension.
# Measured on-chip at 1B/B=32/ctx192 (us per layer-call):
# PP=1 -> 160, PP=2 -> 112, PP=4 -> 92, PP=8 -> 78. Short-context lanes
# waste at most one PP-wide (masked) fold, which is noise at these sizes.
DECODE_PP = 8


def _decode_kernel(
    # scalar prefetch
    block_tables_ref,  # [B, max_blocks] SMEM (LOCAL stripe when strided)
    context_lens_ref,  # [B] SMEM
    page_off_ref,      # [1] SMEM — this shard's logical-page residue
    # inputs
    q_ref,             # [1, H, D] VMEM (this program's sequence)
    k_hbm,             # [num_blocks, bs*kvH, D] HBM pages
    v_hbm,
    # outputs
    o_ref,             # [1, H, D] VMEM (+ m_ref/l_ref [1, H] with stats)
    # scratch (trailing; m/l outputs spliced before when with_stats)
    *refs,
    block_size: int,
    num_kv_heads: int,
    window: int = 0,
    page_stride: int = 1,
    with_stats: bool = False,
):
    """Per-lane grid programs; DECODE_PP pages per pipeline step: each
    slot holds PP pages fetched by independent DMAs, and the body computes
    one [PP*bs]-wide attention fold — dividing per-iteration fixed costs
    (loop scalar work, mask/softmax VPU ops) by PP and widening the score
    matmuls' key dimension (see the DECODE_PP ladder above). The DMA ring
    still spans grid programs (scratch/semaphores persist across TPU grid
    steps), with a uniform padded trip count so the flat ring position is
    b*nsteps + i.

    ``page_stride > 1``: kv_sp striped-scan mode. The table is this sp
    shard's COMPACTED stripe (column j = local page id of logical page
    off + j*stride); the kernel scans only those pages, computing key
    positions from the logical index — FLOPs and DMA partition sp-ways.
    ``with_stats`` additionally emits the online-softmax (m, l) per head
    so the caller can logsumexp-merge shards."""
    if with_stats:
        m_ref, l_ref = refs[0], refs[1]
        k_buf, v_buf, k_sem, v_sem = refs[2:]
    else:
        k_buf, v_buf, k_sem, v_sem = refs
    b = pl.program_id(0)
    B = pl.num_programs(0)
    ctx = context_lens_ref[b]
    off = page_off_ref[0]

    H, D = q_ref.shape[1], q_ref.shape[2]
    kvH = num_kv_heads
    G = H // kvH
    bs = block_size
    scale = 1.0 / (D**0.5)
    NBUF = DECODE_NBUF
    PP = DECODE_PP

    def local_pages(c):
        """This shard's page count for a lane: local indices j with
        off + j*stride < cdiv(c, bs)."""
        n = pl.cdiv(c, bs)
        if page_stride == 1:
            return n
        return jnp.maximum(
            (n - off + page_stride - 1) // page_stride, 0
        )

    nb = local_pages(ctx)              # real (local) pages this lane

    def start_page(c):
        """First local page this lane must scan, aligned DOWN to PP so the
        PP-wide folds stay uniform: with a sliding window, pages wholly
        behind it are never fetched or scored — windowed decode cost is
        O(window), not O(ctx)."""
        if not window:
            return jnp.int32(0)
        slog = jnp.maximum(c - window, 0) // bs
        s = jnp.maximum(
            (slog - off + page_stride - 1) // page_stride, 0
        ) if page_stride > 1 else slog
        return s // PP * PP

    s0 = start_page(ctx)
    # Uniform per-lane step count across the batch.
    def lane_steps(c):
        return pl.cdiv(
            jnp.maximum(local_pages(c) - start_page(c), 0), PP
        )

    nsteps_g = lane_steps(context_lens_ref[0])
    for i in range(1, B):
        nsteps_g = jnp.maximum(nsteps_g, lane_steps(context_lens_ref[i]))
    total = B * nsteps_g

    # [H, D] -> [kvH, G, D], queries pre-scaled in f32. (Measured: f32
    # loads + f32 dots beat native-bf16 dots here; Mosaic requires dot
    # batch dims at EQUAL operand positions, hence the head-major swaps.)
    q3 = (q_ref[0].astype(jnp.float32) * scale).reshape(kvH, G, D)

    def issue(pos):
        """Issue the K/V DMAs for flat position pos."""
        lane = jnp.minimum(pos // jnp.maximum(nsteps_g, 1), B - 1)
        i = pos - lane * nsteps_g
        lane_ctx = context_lens_ref[lane]
        nb_l = local_pages(lane_ctx)
        slot = jax.lax.rem(pos, NBUF)
        for h in range(PP):
            j = start_page(lane_ctx) + i * PP + h

            @pl.when((pos < total) & (j < nb_l))
            def _():
                page = block_tables_ref[lane, j]
                pltpu.make_async_copy(
                    k_hbm.at[page],
                    k_buf.at[slot, pl.ds(h * bs * kvH, bs * kvH)],
                    k_sem.at[slot, h],
                ).start()
                pltpu.make_async_copy(
                    v_hbm.at[page],
                    v_buf.at[slot, pl.ds(h * bs * kvH, bs * kvH)],
                    v_sem.at[slot, h],
                ).start()

    @pl.when(b == 0)
    def _():
        jax.lax.fori_loop(0, NBUF - 1, lambda p, _: (issue(p), 0)[1], 0)

    base = b * nsteps_g

    def body(i, carry):
        m, l, acc = carry
        issue(base + i + NBUF - 1)
        slot = jax.lax.rem(base + i, NBUF)

        def compute(carry):
            m, l, acc = carry
            for h in range(PP):
                @pl.when(s0 + i * PP + h < nb)
                def _():
                    pltpu.make_async_copy(
                        k_hbm.at[0],
                        k_buf.at[slot, pl.ds(h * bs * kvH, bs * kvH)],
                        k_sem.at[slot, h],
                    ).wait()
                    pltpu.make_async_copy(
                        v_hbm.at[0],
                        v_buf.at[slot, pl.ds(h * bs * kvH, bs * kvH)],
                        v_sem.at[slot, h],
                    ).wait()
            # Sublane-merge view [PP*bs*kvH, D] -> [PP*bs, kvH, D], then
            # head-major. An unfetched odd-tail half holds GARBAGE (stale
            # or uninitialized VMEM): its probability columns are masked
            # to 0, but 0 * NaN = NaN through the PV matmul — zero V's
            # unfetched rows. (K needs nothing: NaN scores land only in
            # masked columns, which `where` replaces before use.)
            fetched = (
                (s0 + i * PP) * bs
                + jax.lax.broadcasted_iota(jnp.int32, (PP * bs, 1, 1), 0)
            ) < nb * bs
            k = k_buf.at[slot].reshape(PP * bs, kvH, D)[...].astype(
                jnp.float32
            )
            v = v_buf.at[slot].reshape(PP * bs, kvH, D)[...].astype(
                jnp.float32
            )
            v = jnp.where(fetched, v, 0.0)
            kT = jnp.swapaxes(k, 0, 1)  # [kvH, PP*bs, D]
            vT = jnp.swapaxes(v, 0, 1)

            # [kvH, G, D] x [kvH, PP*bs, D] -> [kvH, G, PP*bs]
            scores = jax.lax.dot_general(
                q3, kT,
                (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            elem = jax.lax.broadcasted_iota(jnp.int32, (1, 1, PP * bs), 2)
            if page_stride == 1:
                key_pos = (s0 + i * PP) * bs + elem
            else:
                # Logical position of a strided page's keys.
                key_pos = (
                    off + (s0 + i * PP + elem // bs) * page_stride
                ) * bs + elem % bs
            mask = key_pos < ctx  # also masks an unfetched odd tail page
            if window:
                # Sliding window: the (single) query position is ctx-1.
                mask = mask & (key_pos >= ctx - window)
            scores = jnp.where(mask, scores, NEG_INF)

            m_new = jnp.maximum(m, scores.max(axis=-1))
            corr = jnp.exp(m - m_new)
            p = jnp.where(mask, jnp.exp(scores - m_new[..., None]), 0.0)
            l_new = l * corr + p.sum(axis=-1)
            # [kvH, G, PP*bs] x [kvH, PP*bs, D] -> [kvH, G, D]
            pv = jax.lax.dot_general(
                p, vT,
                (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32,
            )
            return m_new, l_new, acc * corr[..., None] + pv

        return jax.lax.cond(s0 + i * PP < nb, compute, lambda c: c, carry)

    init = (
        jnp.full((kvH, G), NEG_INF, jnp.float32),
        jnp.zeros((kvH, G), jnp.float32),
        jnp.zeros((kvH, G, D), jnp.float32),
    )
    m, l, acc = jax.lax.fori_loop(0, nsteps_g, body, init)
    out = jnp.where(
        l[..., None] > 0, acc / jnp.maximum(l[..., None], 1e-30), 0.0
    )
    o_ref[0] = out.reshape(H, D).astype(o_ref.dtype)
    if with_stats:
        # Stats land as [B, 1, H] (block (1, 1, H)): a 2-D [B, H] output
        # with block (1, H) violates Mosaic's second-to-minor tiling rule.
        m_ref[0, 0] = m.reshape(H)
        l_ref[0, 0] = l.reshape(H)


@functools.partial(
    jax.jit,
    static_argnames=("block_size", "window", "page_stride", "with_stats"),
)
def paged_decode_attention_pallas(
    q: jnp.ndarray,             # [B, H, D]
    k_cache: jnp.ndarray,       # [num_slots, kvH, D]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks] int32
    context_lens: jnp.ndarray,  # [B] int32 (0 = inactive slot -> zeros)
    block_size: int,
    window: int = 0,
    page_offset: jnp.ndarray | None = None,  # [1] — kv_sp shard residue
    page_stride: int = 1,
    with_stats: bool = False,
):
    """Returns out [B, H, D]; with ``with_stats`` returns (out, m, l) with
    out in float32 and m/l [B, H] — the kv_sp per-shard call whose stats
    the caller merges across shards (ops/attention.py AttnDispatch)."""
    B, H, D = q.shape
    kvH = k_cache.shape[1]
    kp = k_cache.reshape(-1, block_size * kvH, D)
    vp = v_cache.reshape(-1, block_size * kvH, D)
    if page_offset is None:
        page_offset = jnp.zeros((1,), jnp.int32)

    qspec = pl.BlockSpec(
        (1, H, D), lambda b, *_: (b, 0, 0), memory_space=pltpu.VMEM
    )
    hspec = pl.BlockSpec(
        (1, 1, H), lambda b, *_: (b, 0, 0), memory_space=pltpu.VMEM
    )
    out_shape = jax.ShapeDtypeStruct(
        (B, H, D), jnp.float32 if with_stats else q.dtype
    )
    out_specs = qspec
    if with_stats:
        out_shape = (
            out_shape,
            jax.ShapeDtypeStruct((B, 1, H), jnp.float32),
            jax.ShapeDtypeStruct((B, 1, H), jnp.float32),
        )
        out_specs = (qspec, hspec, hspec)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B,),
        in_specs=[
            qspec,
            pl.BlockSpec(memory_space=MEMORY_SPACE_ANY),
            pl.BlockSpec(memory_space=MEMORY_SPACE_ANY),
        ],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM(
                (DECODE_NBUF, DECODE_PP * block_size * kvH, D), k_cache.dtype
            ),
            pltpu.VMEM(
                (DECODE_NBUF, DECODE_PP * block_size * kvH, D), v_cache.dtype
            ),
            pltpu.SemaphoreType.DMA((DECODE_NBUF, DECODE_PP)),
            pltpu.SemaphoreType.DMA((DECODE_NBUF, DECODE_PP)),
        ],
    )
    kernel = functools.partial(
        _decode_kernel, block_size=block_size, num_kv_heads=kvH,
        window=window, page_stride=page_stride, with_stats=with_stats,
    )
    res = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid_spec=grid_spec,
        interpret=_interpret(),
    )(
        block_tables.astype(jnp.int32),
        context_lens.astype(jnp.int32),
        page_offset.astype(jnp.int32),
        q,
        kp,
        vp,
    )
    if with_stats:
        o, m, l = res
        return o, m[:, 0], l[:, 0]
    return res


# ---------------------------------------------------------------------------
# Prefill: a tile of query tokens per program, batched over lanes.
# ---------------------------------------------------------------------------

# Pages folded into one prefill pipeline step, mirroring DECODE_PP: one
# wait + ONE attention fold per PP pages widens the score matmuls' key
# dimension from bs (=16) to PP*bs (=128) — the r05 8B profile measured
# the single-page prefill kernel at ~65% of prefill device time with
# ~2.6% MFU in its dots; PP-wide folds are the same fix that took the
# decode kernel 160→78 µs/layer in r04.
PREFILL_PP = 8


def _prefill_kernel(
    # scalar prefetch
    block_tables_ref,  # [N, max_blocks] SMEM (LOCAL stripe when strided)
    q_start_ref,       # [N] SMEM — prefix length per lane
    total_len_ref,     # [N] SMEM — prefix + real new tokens (0 = idle lane)
    page_off_ref,      # [1] SMEM — this shard's logical-page residue
    # inputs
    q_ref,             # [1, TQ, H, D] VMEM (this lane + q tile)
    k_hbm,             # [num_blocks, bs*kvH, D] HBM pages
    v_hbm,
    # outputs
    o_ref,             # [1, TQ, H, D] VMEM (+ m/l [1, TQ, H] with stats)
    # scratch (trailing; m/l outputs spliced before when with_stats)
    *refs,
    block_size: int,
    num_kv_heads: int,
    q_tile: int,
    window: int = 0,
    page_stride: int = 1,
    with_stats: bool = False,
):
    if with_stats:
        m_ref, l_ref = refs[0], refs[1]
        k_buf, v_buf, k_sem, v_sem = refs[2:]
    else:
        k_buf, v_buf, k_sem, v_sem = refs
    n = pl.program_id(0)
    t0 = pl.program_id(1) * q_tile
    q_start = q_start_ref[n]
    total = total_len_ref[n]
    off = page_off_ref[0]

    TQ, H, D = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]
    kvH = num_kv_heads
    G = H // kvH
    bs = block_size
    scale = 1.0 / (D**0.5)

    def to_local(pages):
        """Logical page count/index -> this shard's local count/index."""
        if page_stride == 1:
            return pages
        return jnp.maximum(
            (pages - off + page_stride - 1) // page_stride, 0
        )

    # Keys this tile can see: causal bound (q_start + t0 + TQ) clipped to
    # the sequence's real length; with a sliding window, pages wholly
    # before the tile's earliest visible key are skipped entirely.
    hi = jnp.minimum(q_start + t0 + TQ, total)
    nb = to_local(pl.cdiv(hi, block_size))
    lo = (
        to_local(jnp.maximum(q_start + t0 - window + 1, 0) // block_size)
        if window
        else jnp.int32(0)
    )

    # [TQ, H, D] -> [kvH, TQ*G, D]: fold the group dim into rows so each
    # kv head's score matmul is a well-shaped [TQ*G, D] x [D, PP*bs].
    q4 = (q_ref[0].astype(jnp.float32) * scale).reshape(TQ, kvH, G, D)
    qf = jnp.transpose(q4, (1, 0, 2, 3)).reshape(kvH, TQ * G, D)
    # Global query position per folded row (row r -> token r // G).
    row_tok = jax.lax.broadcasted_iota(jnp.int32, (1, TQ * G, 1), 1) // G
    q_pos = q_start + t0 + row_tok  # [1, TQ*G, 1]

    # PP pages per pipeline step (see PREFILL_PP); ring as in the decode
    # kernel, per-program (tiles have differing causal trip counts, so
    # the flat cross-program ring position doesn't apply).
    NBUF = DECODE_NBUF
    PP = PREFILL_PP
    lo_f = lo // PP          # first fold (window start aligns DOWN;
    hi_f = pl.cdiv(nb, PP)   # behind-window pages mask out)

    def issue(f):
        """Issue the K/V DMAs for fold f's fetched pages."""
        slot = jax.lax.rem(f, NBUF)
        for h in range(PP):
            j = f * PP + h

            @pl.when((f < hi_f) & (j < nb))
            def _():
                page = block_tables_ref[n, j]
                pltpu.make_async_copy(
                    k_hbm.at[page],
                    k_buf.at[slot, pl.ds(h * bs * kvH, bs * kvH)],
                    k_sem.at[slot, h],
                ).start()
                pltpu.make_async_copy(
                    v_hbm.at[page],
                    v_buf.at[slot, pl.ds(h * bs * kvH, bs * kvH)],
                    v_sem.at[slot, h],
                ).start()

    jax.lax.fori_loop(lo_f, lo_f + NBUF - 1, lambda f, _: (issue(f), 0)[1], 0)

    def body(f, carry):
        m, l, acc = carry
        issue(f + NBUF - 1)
        slot = jax.lax.rem(f, NBUF)
        for h in range(PP):
            @pl.when(f * PP + h < nb)
            def _():
                pltpu.make_async_copy(
                    k_hbm.at[0],
                    k_buf.at[slot, pl.ds(h * bs * kvH, bs * kvH)],
                    k_sem.at[slot, h],
                ).wait()
                pltpu.make_async_copy(
                    v_hbm.at[0],
                    v_buf.at[slot, pl.ds(h * bs * kvH, bs * kvH)],
                    v_sem.at[slot, h],
                ).wait()
        # Unfetched tail pages hold garbage (stale/uninitialized VMEM):
        # zero V's rows (0 * NaN = NaN through the PV matmul); K needs
        # nothing — NaN scores land only in masked columns.
        fetched = (
            f * PP + jax.lax.broadcasted_iota(
                jnp.int32, (PP * bs, 1, 1), 0
            ) // bs
        ) < nb
        k = k_buf.at[slot].reshape(PP * bs, kvH, D)[...].astype(jnp.float32)
        v = v_buf.at[slot].reshape(PP * bs, kvH, D)[...].astype(jnp.float32)
        v = jnp.where(fetched, v, 0.0)
        kT = jnp.swapaxes(k, 0, 1)  # [kvH, PP*bs, D]
        vT = jnp.swapaxes(v, 0, 1)

        # [kvH, TQ*G, D] x [kvH, PP*bs, D] -> [kvH, TQ*G, PP*bs]
        scores = jax.lax.dot_general(
            qf, kT,
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        elem = jax.lax.broadcasted_iota(jnp.int32, (1, 1, PP * bs), 2)
        if page_stride == 1:
            key_pos = f * PP * bs + elem
        else:
            key_pos = (
                off + (f * PP + elem // bs) * page_stride
            ) * bs + elem % bs
        mask = (key_pos <= q_pos) & (key_pos < total)  # [1, TQ*G, PP*bs]
        if window:
            mask = mask & (key_pos > q_pos - window)
        scores = jnp.where(mask, scores, NEG_INF)

        m_new = jnp.maximum(m, scores.max(axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.where(mask, jnp.exp(scores - m_new[..., None]), 0.0)
        l_new = l * corr + p.sum(axis=-1)
        pv = jax.lax.dot_general(
            p, vT,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc * corr[..., None] + pv

    init = (
        jnp.full((kvH, TQ * G), NEG_INF, jnp.float32),
        jnp.zeros((kvH, TQ * G), jnp.float32),
        jnp.zeros((kvH, TQ * G, D), jnp.float32),
    )
    m, l, acc = jax.lax.fori_loop(lo_f, hi_f, body, init)
    out = jnp.where(
        l[..., None] > 0, acc / jnp.maximum(l[..., None], 1e-30), 0.0
    )
    # [kvH, TQ*G, D] -> [TQ, H, D]
    out = jnp.transpose(out.reshape(kvH, TQ, G, D), (1, 0, 2, 3))
    o_ref[0] = out.reshape(TQ, H, D).astype(o_ref.dtype)
    if with_stats:
        m_ref[0] = jnp.transpose(
            m.reshape(kvH, TQ, G), (1, 0, 2)
        ).reshape(TQ, H)
        l_ref[0] = jnp.transpose(
            l.reshape(kvH, TQ, G), (1, 0, 2)
        ).reshape(TQ, H)


@functools.partial(
    jax.jit,
    static_argnames=(
        "block_size", "q_tile", "window", "page_stride", "with_stats",
    ),
)
def paged_prefill_attention_pallas(
    q: jnp.ndarray,             # [N, T, H, D] — new tokens' queries per lane
    k_cache: jnp.ndarray,       # [num_slots, kvH, D]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [N, max_blocks] int32
    q_start: jnp.ndarray,       # [N] — prefix length per lane
    total_len: jnp.ndarray,     # [N] — prefix + real new tokens (0 = idle)
    block_size: int,
    q_tile: int = 64,
    window: int = 0,
    page_offset: jnp.ndarray | None = None,  # [1] — kv_sp shard residue
    page_stride: int = 1,
    with_stats: bool = False,
):
    """Returns out [N, T, H, D]; with ``with_stats`` returns (out, m, l)
    with out in float32 and m/l [N, T, H] for the kv_sp shard merge."""
    N, T, H, D = q.shape
    kvH = k_cache.shape[1]
    TQ = min(q_tile, T)
    kp = k_cache.reshape(-1, block_size * kvH, D)
    vp = v_cache.reshape(-1, block_size * kvH, D)
    if page_offset is None:
        page_offset = jnp.zeros((1,), jnp.int32)

    qspec = pl.BlockSpec(
        (1, TQ, H, D),
        lambda n, t, *_: (n, t, 0, 0),
        memory_space=pltpu.VMEM,
    )
    hspec = pl.BlockSpec(
        (1, TQ, H), lambda n, t, *_: (n, t, 0), memory_space=pltpu.VMEM
    )
    out_shape = jax.ShapeDtypeStruct(
        (N, T, H, D), jnp.float32 if with_stats else q.dtype
    )
    out_specs = qspec
    if with_stats:
        out_shape = (
            out_shape,
            jax.ShapeDtypeStruct((N, T, H), jnp.float32),
            jax.ShapeDtypeStruct((N, T, H), jnp.float32),
        )
        out_specs = (qspec, hspec, hspec)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(N, pl.cdiv(T, TQ)),
        in_specs=[
            qspec,
            pl.BlockSpec(memory_space=MEMORY_SPACE_ANY),
            pl.BlockSpec(memory_space=MEMORY_SPACE_ANY),
        ],
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM(
                (DECODE_NBUF, PREFILL_PP * block_size * kvH, D),
                k_cache.dtype,
            ),
            pltpu.VMEM(
                (DECODE_NBUF, PREFILL_PP * block_size * kvH, D),
                v_cache.dtype,
            ),
            pltpu.SemaphoreType.DMA((DECODE_NBUF, PREFILL_PP)),
            pltpu.SemaphoreType.DMA((DECODE_NBUF, PREFILL_PP)),
        ],
    )
    kernel = functools.partial(
        _prefill_kernel, block_size=block_size, num_kv_heads=kvH, q_tile=TQ,
        window=window, page_stride=page_stride, with_stats=with_stats,
    )
    return pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid_spec=grid_spec,
        interpret=_interpret(),
    )(
        block_tables.astype(jnp.int32),
        q_start.astype(jnp.int32),
        total_len.astype(jnp.int32),
        page_offset.astype(jnp.int32),
        q,
        kp,
        vp,
    )

"""Pallas TPU kernels for the hot ops.

The jnp implementations in ops/attention.py are the semantics contract and
test oracle; these kernels keep the same math but stream KV pages
HBM→VMEM explicitly with double-buffered DMA, which is what gets decode
attention to HBM-bandwidth-bound instead of gather-bound.
"""

from dynamo_tpu.ops.pallas.attention import (
    paged_decode_attention_pallas,
    paged_prefill_attention_pallas,
)
from dynamo_tpu.ops.pallas.ragged_attention import (
    ragged_paged_attention_pallas,
)

__all__ = [
    "paged_decode_attention_pallas",
    "paged_prefill_attention_pallas",
    "ragged_paged_attention_pallas",
]

"""Ragged unified paged attention — ONE kernel for mixed prefill+decode.

The phase-split kernels (ops/pallas/attention.py) compile one program per
(kind, T-bucket, lane-bucket) point: the shape grid PR 1's compile cache
manages. This kernel deletes the grid instead (ROADMAP item #2, after the
ragged-paged-attention recipe in PAPERS.md): the step takes ONE flat
token batch ``q: [T, H, D]`` in which each sequence owns a contiguous
ragged span of rows — a decode lane is simply a span of length 1, a
chunked-prefill quantum a span of its chunk length, and a speculative
draft-verify span is ``q_len = k+1`` rows (the fed token plus its k
drafts: verification is a short "prefill" over the draft positions, so
the span math is IDENTICAL to a prefill quantum with
``q_start = ctx-1``) — so the only compiled extent is the total token
budget ``T``. Mixed batches run in a
single dispatch: decode steps no longer queue behind prefill dispatches
(the Nexus head-of-line argument), and warmup shrinks from the
lane×bucket grid to a handful of budget shapes.

Metadata (all per-sequence, scalar-prefetched to SMEM):
- ``block_tables[s]``: the sequence's paged-cache block table;
- ``q_start[s]``: global position of the span's first token (its
  already-cached prefix length);
- ``q_len[s]``: span length in rows (0 = idle metadata row);
- ``kv_len[s]``: total context after this step's KV writes, i.e.
  ``q_start + q_len`` (kept explicit on the wire for clarity);
- ``row_start[s]``: the span's first row in the flat batch.

Layout contract is unchanged from ops/pallas/attention.py: the cache is
``[num_slots, kvH, D]`` viewed as pages ``[num_blocks, bs*kvH, D]``,
``D % 128 == 0`` inside the kernel (lane-padded caches for smaller head
dims), pages stream HBM→VMEM through a double-buffered DMA ring with
``RAGGED_PP`` pages folded per attention step. What is new mechanically:
``q`` and the output live in ANY (HBM) memory space and each grid
program (one per sequence) DMAs its own ragged q rows in — and its
output rows out — at dynamic offsets, full ``q_tile`` blocks where the
span allows and row-by-row for the tail, so spans need no alignment and
a decode row costs a single-row copy.

The jnp semantics twin is ops/attention.py ``ragged_paged_attention``
(the tier-1 oracle); interpret mode runs this kernel's code path on CPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dynamo_tpu.utils.jax_compat import MEMORY_SPACE_ANY, tpu_memory_space

NEG_INF = -1e30

# DMA ring depth and pages-per-fold, matching the measured ladders in
# ops/pallas/attention.py (the fold math and page sizes are identical, so
# the same operating point applies).
RAGGED_NBUF = 8
RAGGED_PP = 8


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _ragged_kernel(
    # scalar prefetch
    block_tables_ref,  # [S, max_blocks] SMEM
    q_start_ref,       # [S] SMEM — prefix length per sequence
    q_len_ref,         # [S] SMEM — span rows (0 = idle row)
    kv_len_ref,        # [S] SMEM — context after this step's writes
    row_start_ref,     # [S] SMEM — span's first row in the flat batch
    # inputs (q/k/v in ANY memory, DMA'd manually; with `quantized`,
    # two per-block scale arrays follow, whole-array-resident in VMEM)
    q_hbm,             # [T + TQ, H, D] flat queries (tail-padded)
    k_hbm,             # [num_blocks, bs*kvH, D] pages
    v_hbm,
    # quantized only: k_scales_ref / v_scales_ref [num_blocks, kvH] VMEM
    *rest,
    block_size: int,
    num_kv_heads: int,
    q_tile_rows: int,
    window: int = 0,
    quantized: bool = False,
):
    """One grid program per sequence; inner loop over its q tiles.

    Each tile DMAs ``TQ`` q rows in from the flat batch at the span's
    (dynamic) offset, streams the causally visible KV pages through the
    fold ring, and DMAs the result rows back out — whole tiles when the
    span still covers ``TQ`` rows, single rows for the ragged tail (so a
    decode span writes exactly its one row and never clobbers a
    neighbouring span's output).

    ``quantized``: K/V pages stream as int8 through the SAME DMA ring
    (half the HBM bytes — the point of the int8 path) and dequantize
    in-register during the fold: each page's [kvH] scale row loads from
    the VMEM-resident scale arrays by its physical page id, and the
    arithmetic is exactly ``int8 * scale`` — matching the XLA oracle's
    gathered multiply, so parity is exact-contract."""
    if quantized:
        k_scales_ref, v_scales_ref = rest[0], rest[1]
        rest = rest[2:]
    else:
        k_scales_ref = v_scales_ref = None
    (
        o_hbm,             # [T + TQ, H, D]
        q_tile,            # VMEM [TQ, H, D]
        o_tile,            # VMEM [TQ, H, D]
        k_buf,             # VMEM [NBUF, PP*bs*kvH, D] (cache dtype)
        v_buf,
        q_sem,
        o_sem,
        k_sem,             # DMA [NBUF, PP]
        v_sem,
    ) = rest
    s = pl.program_id(0)
    ql = q_len_ref[s]
    q0 = q_start_ref[s]
    kv = kv_len_ref[s]
    rs0 = row_start_ref[s]

    TQ = q_tile_rows
    H, D = q_tile.shape[1], q_tile.shape[2]
    kvH = num_kv_heads
    G = H // kvH
    bs = block_size
    scale = 1.0 / (D**0.5)
    NBUF = RAGGED_NBUF
    PP = RAGGED_PP

    row_idx = jax.lax.broadcasted_iota(jnp.int32, (1, TQ * G, 1), 1) // G

    @pl.when(ql > 0)
    def _():
        ntiles = pl.cdiv(ql, TQ)

        def tile_body(t, _):
            row0 = rs0 + t * TQ        # flat-batch row of this tile
            tok0 = t * TQ              # span-local index of its first row
            pltpu.make_async_copy(
                q_hbm.at[pl.ds(row0, TQ)], q_tile, q_sem
            ).start()

            # Keys this tile can see: causal bound clipped to the context;
            # with a window, pages wholly behind every row's window skip.
            hi = jnp.minimum(q0 + tok0 + TQ, kv)
            nb = pl.cdiv(hi, bs)
            lo = (
                jnp.maximum(q0 + tok0 - window + 1, 0) // bs
                if window
                else jnp.int32(0)
            )
            lo_f = lo // PP
            hi_f = pl.cdiv(nb, PP)

            def issue(f):
                slot = jax.lax.rem(f, NBUF)
                for h in range(PP):
                    j = f * PP + h

                    @pl.when((f >= lo_f) & (f < hi_f) & (j < nb))
                    def _():
                        page = block_tables_ref[s, j]
                        pltpu.make_async_copy(
                            k_hbm.at[page],
                            k_buf.at[slot, pl.ds(h * bs * kvH, bs * kvH)],
                            k_sem.at[slot, h],
                        ).start()
                        pltpu.make_async_copy(
                            v_hbm.at[page],
                            v_buf.at[slot, pl.ds(h * bs * kvH, bs * kvH)],
                            v_sem.at[slot, h],
                        ).start()

            jax.lax.fori_loop(
                lo_f, lo_f + NBUF - 1, lambda f, c: (issue(f), c)[1], 0
            )
            pltpu.make_async_copy(
                q_hbm.at[pl.ds(row0, TQ)], q_tile, q_sem
            ).wait()

            # [TQ, H, D] -> [kvH, TQ*G, D] folded rows; masked rows (the
            # tail tile's overhang into the next span) read garbage q but
            # every key is masked for them, so they fold to zero and are
            # never written back.
            q4 = (q_tile[...].astype(jnp.float32) * scale).reshape(
                TQ, kvH, G, D
            )
            qf = jnp.transpose(q4, (1, 0, 2, 3)).reshape(kvH, TQ * G, D)
            q_pos = q0 + tok0 + row_idx          # [1, TQ*G, 1]
            row_ok = row_idx < (ql - tok0)       # [1, TQ*G, 1]

            def fold(f, carry):
                m, l, acc = carry
                issue(f + NBUF - 1)
                slot = jax.lax.rem(f, NBUF)
                for h in range(PP):
                    @pl.when(f * PP + h < nb)
                    def _():
                        pltpu.make_async_copy(
                            k_hbm.at[0],
                            k_buf.at[slot, pl.ds(h * bs * kvH, bs * kvH)],
                            k_sem.at[slot, h],
                        ).wait()
                        pltpu.make_async_copy(
                            v_hbm.at[0],
                            v_buf.at[slot, pl.ds(h * bs * kvH, bs * kvH)],
                            v_sem.at[slot, h],
                        ).wait()
                # Unfetched tail pages hold garbage: zero V's rows
                # (0 * NaN = NaN through the PV matmul); K needs nothing
                # — NaN scores land only in masked columns.
                fetched = (
                    f * PP
                    + jax.lax.broadcasted_iota(
                        jnp.int32, (PP * bs, 1, 1), 0
                    ) // bs
                ) < nb
                k = k_buf.at[slot].reshape(PP * bs, kvH, D)[...].astype(
                    jnp.float32
                )
                v = v_buf.at[slot].reshape(PP * bs, kvH, D)[...].astype(
                    jnp.float32
                )
                if quantized:
                    # In-register dequant: one [kvH] scale row per page,
                    # loaded from VMEM by physical page id (same id the
                    # ring DMA'd the page by). Unfetched tail pages use a
                    # clamped table entry — their columns are masked, and
                    # V additionally zeroes below.
                    max_blocks = block_tables_ref.shape[1]
                    ks_rows, vs_rows = [], []
                    for h in range(PP):
                        j = jnp.minimum(f * PP + h, max_blocks - 1)
                        page = block_tables_ref[s, j]
                        ks = pl.load(
                            k_scales_ref, (pl.ds(page, 1), slice(None))
                        )  # [1, kvH]
                        vs = pl.load(
                            v_scales_ref, (pl.ds(page, 1), slice(None))
                        )
                        ks_rows.append(jnp.broadcast_to(ks, (bs, kvH)))
                        vs_rows.append(jnp.broadcast_to(vs, (bs, kvH)))
                    k = k * jnp.concatenate(ks_rows, axis=0)[:, :, None]
                    v = v * jnp.concatenate(vs_rows, axis=0)[:, :, None]
                v = jnp.where(fetched, v, 0.0)
                kT = jnp.swapaxes(k, 0, 1)  # [kvH, PP*bs, D]
                vT = jnp.swapaxes(v, 0, 1)

                scores = jax.lax.dot_general(
                    qf, kT,
                    (((2,), (2,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32,
                )  # [kvH, TQ*G, PP*bs]
                elem = jax.lax.broadcasted_iota(
                    jnp.int32, (1, 1, PP * bs), 2
                )
                key_pos = f * PP * bs + elem
                mask = (
                    (key_pos <= q_pos) & (key_pos < kv) & row_ok
                )
                if window:
                    mask = mask & (key_pos > q_pos - window)
                scores = jnp.where(mask, scores, NEG_INF)

                m_new = jnp.maximum(m, scores.max(axis=-1))
                corr = jnp.exp(m - m_new)
                p = jnp.where(mask, jnp.exp(scores - m_new[..., None]), 0.0)
                l_new = l * corr + p.sum(axis=-1)
                pv = jax.lax.dot_general(
                    p, vT,
                    (((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32,
                )
                return m_new, l_new, acc * corr[..., None] + pv

            init = (
                jnp.full((kvH, TQ * G), NEG_INF, jnp.float32),
                jnp.zeros((kvH, TQ * G), jnp.float32),
                jnp.zeros((kvH, TQ * G, D), jnp.float32),
            )
            m, l, acc = jax.lax.fori_loop(lo_f, hi_f, fold, init)
            out = jnp.where(
                l[..., None] > 0, acc / jnp.maximum(l[..., None], 1e-30), 0.0
            )
            # [kvH, TQ*G, D] -> [TQ, H, D]
            out = jnp.transpose(out.reshape(kvH, TQ, G, D), (1, 0, 2, 3))
            o_tile[...] = out.reshape(TQ, H, D).astype(o_tile.dtype)

            rem = jnp.minimum(ql - tok0, TQ)  # valid rows in this tile

            @pl.when(rem >= TQ)
            def _full_tile():
                cp = pltpu.make_async_copy(
                    o_tile, o_hbm.at[pl.ds(row0, TQ)], o_sem
                )
                cp.start()
                cp.wait()

            @pl.when(rem < TQ)
            def _tail_rows():
                def row_out(r, c):
                    cp = pltpu.make_async_copy(
                        o_tile.at[pl.ds(r, 1)],
                        o_hbm.at[pl.ds(row0 + r, 1)],
                        o_sem,
                    )
                    cp.start()
                    cp.wait()
                    return c

                jax.lax.fori_loop(0, rem, row_out, 0)

            return 0

        jax.lax.fori_loop(0, ntiles, tile_body, 0)


@functools.partial(
    jax.jit, static_argnames=("block_size", "q_tile", "window")
)
def ragged_paged_attention_pallas(
    q: jnp.ndarray,             # [T, H, D] flat token batch (budget-padded)
    k_cache: jnp.ndarray,       # [num_slots, kvH, D]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [S, max_blocks] int32
    q_start: jnp.ndarray,       # [S] int32 — prefix length per span
    q_len: jnp.ndarray,         # [S] int32 — span rows (0 = idle)
    kv_len: jnp.ndarray,        # [S] int32 — context incl. this step
    row_start: jnp.ndarray,     # [S] int32 — span's first flat row
    block_size: int,
    q_tile: int = 8,
    window: int = 0,
    k_scales: jnp.ndarray | None = None,  # [num_blocks, kvH] f32 (int8 KV)
    v_scales: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """Mixed prefill+decode attention over one flat ragged batch; returns
    ``[T, H, D]``. Rows not covered by any span are returned ZEROED (the
    same contract as the jnp twin). ``q_tile`` trades tail padding
    against per-tile fixed cost; 8 keeps a decode span to one row copy
    while a 256-token quantum still runs 32-row folds.

    With ``k_scales``/``v_scales`` the caches are int8 and pages
    dequantize in-register (docs/architecture/kv_quant.md): the page DMA
    ring moves half the bytes, the scale arrays (a few KB) sit whole in
    VMEM, and the compiled program count is unchanged — quantization
    only changes dtypes inside the existing budget-ladder grid."""
    T, H, D = q.shape
    S = block_tables.shape[0]
    kvH = k_cache.shape[1]
    TQ = min(q_tile, max(T, 1))
    quantized = k_scales is not None
    kp = k_cache.reshape(-1, block_size * kvH, D)
    vp = v_cache.reshape(-1, block_size * kvH, D)
    # Tail pad: the last tile of a span ending near row T-1 reads TQ rows
    # from its dynamic offset; padding keeps every read in bounds without
    # aligning spans. The pad rows are never written back.
    qpad = jnp.pad(q, ((0, TQ), (0, 0), (0, 0)))

    vmem = tpu_memory_space().VMEM
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(S,),
        in_specs=[
            pl.BlockSpec(memory_space=MEMORY_SPACE_ANY),
            pl.BlockSpec(memory_space=MEMORY_SPACE_ANY),
            pl.BlockSpec(memory_space=MEMORY_SPACE_ANY),
        ]
        + (
            # Per-block scales ride whole in VMEM: the kernel loads each
            # page's [kvH] row at a dynamic offset during the fold.
            [
                pl.BlockSpec(memory_space=vmem),
                pl.BlockSpec(memory_space=vmem),
            ]
            if quantized
            else []
        ),
        out_specs=pl.BlockSpec(memory_space=MEMORY_SPACE_ANY),
        scratch_shapes=[
            pltpu.VMEM((TQ, H, D), q.dtype),
            pltpu.VMEM((TQ, H, D), q.dtype),
            pltpu.VMEM(
                (RAGGED_NBUF, RAGGED_PP * block_size * kvH, D), k_cache.dtype
            ),
            pltpu.VMEM(
                (RAGGED_NBUF, RAGGED_PP * block_size * kvH, D), v_cache.dtype
            ),
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA,
            pltpu.SemaphoreType.DMA((RAGGED_NBUF, RAGGED_PP)),
            pltpu.SemaphoreType.DMA((RAGGED_NBUF, RAGGED_PP)),
        ],
    )
    kernel = functools.partial(
        _ragged_kernel, block_size=block_size, num_kv_heads=kvH,
        q_tile_rows=TQ, window=window, quantized=quantized,
    )
    operands = [
        block_tables.astype(jnp.int32),
        q_start.astype(jnp.int32),
        q_len.astype(jnp.int32),
        kv_len.astype(jnp.int32),
        row_start.astype(jnp.int32),
        qpad,
        kp,
        vp,
    ]
    if quantized:
        operands += [
            k_scales.astype(jnp.float32), v_scales.astype(jnp.float32)
        ]
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((T + TQ, H, D), q.dtype),
        grid_spec=grid_spec,
        interpret=_interpret(),
    )(*operands)[:T]
    # Rows no span owns (budget padding between/after spans) may hold
    # whatever the output buffer held — zero them so the contract matches
    # the jnp twin and padding can never leak into downstream residuals.
    span = (
        (jnp.arange(T)[:, None] >= row_start[None, :])
        & (jnp.arange(T)[:, None] < (row_start + q_len)[None, :])
        & (q_len[None, :] > 0)
    ).any(axis=1)
    return jnp.where(span[:, None, None], out, 0)

"""Weight-only int8 quantization for the serving path.

Decode is weight-streaming-bound: every step reads the full parameter set
from HBM, so bytes/param is the throughput ceiling (BENCHMARKS.md measures
the bf16 path at ~48% of v5e HBM peak). Storing the big matmul weights as
int8 with per-output-channel symmetric scales halves the streamed bytes;
XLA fuses the int8→bf16 convert into the matmul operand read, so the MXU
still runs a bf16 contraction and nothing extra round-trips through HBM.

This is the TPU-idiomatic analogue of the reference's quantized serving
configs (its headline disagg numbers run FP8 via vLLM/TRT-LLM backends,
reference: docs/architecture/architecture.md:75-79 "70B FP8"; the engines
own quantization there — here the engine is native, so we own it).

Representation: a quantized weight is a pytree dict ``{"q": int8[..., in,
out], "s": f32 scales}`` where ``s`` is the weight's shape with the
contraction (``in``) axis removed — [out] for 2-D, [E, out] for stacked
MoE experts. Every consumer goes through :func:`qmm` (or reads ``q``/``s``
directly for the MoE einsums), so plain bf16 arrays and quantized dicts
are interchangeable throughout models/llama.py.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

Params = dict[str, Any]

# Weights eligible for quantization: the large matmul operands. Norm gains,
# biases, the router (tiny, routing-accuracy-critical), and the embedding
# table (a gather, not a matmul; also the tied lm_head) stay bf16.
# MLA (models/llama.py): all 2-D projections plus the per-head absorbed
# w_uk/w_uv; DeepSeekMoE shared experts stream every step, so they
# quantize too. w_dq/ln inputs are small but on the per-step path.
QUANT_KEYS = (
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head",
    "w_dq", "w_uq", "w_dkv", "w_uk", "w_uv",
    "w_shared_gate", "w_shared_up", "w_shared_down",
)

# Per-matmul policy sites (models/llama.py WeightQuantPolicy): the attn
# group is every attention projection (GQA qkv+o and the MLA ladder);
# the mlp group is the SwiGLU / expert matrices (the router stays full
# precision — tiny and routing-accuracy-critical). Embedding and unembed
# are handled by name (``embed``/``lm_head``) in the policy functions.
ATTN_KEYS = (
    "wq", "wk", "wv", "wo", "w_dq", "w_uq", "w_dkv", "w_uk", "w_uv",
)
MLP_KEYS = (
    "w_gate", "w_up", "w_down",
    "w_shared_gate", "w_shared_up", "w_shared_down",
)

# fp8 weight storage (the other precision the policy can select):
# e4m3 with per-output-channel scales — same dict representation, same
# qdot arithmetic (q converts on the matmul operand), so every consumer
# is format-agnostic. Gated: older jax builds may lack the dtype.
FP8_DTYPE = getattr(jnp, "float8_e4m3fn", None)
FP8_MAX = 448.0
WEIGHT_FORMATS = ("int8", "fp8")

CONTRACT_AXIS = -2  # our weight layout is [..., in, out]

#: per-key contraction-axis overrides: w_uv [H, v, dc] contracts its LAST
#: axis (the latent) in _mla_out's einsum, so scales are per (head, v-dim).
QUANT_AXES = {"w_uv": -1}


def is_quantized(w) -> bool:
    return isinstance(w, dict) and "q" in w and "s" in w


def quantize_weight(
    w: jnp.ndarray, axis: int = CONTRACT_AXIS, fmt: str = "int8"
) -> Params:
    """Symmetric per-output-channel quantization over the contraction axis.

    ``fmt="int8"`` (default): ``q = round(w / s)`` with ``s = amax|w| /
    127`` per out column, so the reconstruction ``q * s`` has <1%
    per-element error and exact zero preservation (symmetric, no zero
    point — the MXU-friendly choice). ``fmt="fp8"``: e4m3 storage with
    ``s = amax|w| / 448`` (rounding is the dtype cast's). Scales keep the
    weight's dtype so dequantized values land back in the model's
    compute dtype.
    """
    wf = w.astype(jnp.float32)
    amax = jnp.max(jnp.abs(wf), axis=axis)
    if fmt == "fp8":
        if FP8_DTYPE is None:
            raise ValueError(
                "fp8 weight quantization requires a jax build with "
                "float8_e4m3fn — use fmt='int8' on this install"
            )
        s = jnp.maximum(amax, 1e-8) / FP8_MAX
        q = (wf / jnp.expand_dims(s, axis)).astype(FP8_DTYPE)
        return {"q": q, "s": s.astype(w.dtype)}
    if fmt != "int8":
        raise ValueError(f"unknown weight format {fmt!r} (use {WEIGHT_FORMATS})")
    s = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.round(wf / jnp.expand_dims(s, axis))
    q = jnp.clip(q, -127, 127).astype(jnp.int8)
    return {"q": q, "s": s.astype(w.dtype)}


def dequantize_weight(
    w: Params, dtype=jnp.float32, axis: int = CONTRACT_AXIS
) -> jnp.ndarray:
    """Invert quantize_weight; pass the same `axis` it was quantized with
    (axis=-1 for per-row tables like the tied embedding)."""
    return (
        w["q"].astype(jnp.float32) * jnp.expand_dims(w["s"], axis)
    ).astype(dtype)


def qmm(x: jnp.ndarray, w) -> jnp.ndarray:
    """``x @ w`` for a plain array or a quantized dict.

    The int8→x.dtype convert sits directly on the matmul operand so XLA
    fuses it into the contraction's operand read: int8 bytes stream from
    HBM, bf16 math runs on the MXU, and the per-column scale multiplies
    the [.., out] result (post-psum under a row-sharded contraction).
    """
    if not is_quantized(w):
        return x @ w
    return (x @ w["q"].astype(x.dtype)) * w["s"].astype(x.dtype)


def qdot(x: jnp.ndarray, w) -> jnp.ndarray:
    """The dequantize-in-register dot — the one arithmetic contract every
    matmul site on the unified path runs (docs/architecture/
    weight_quant.md "zero new programs"):

    - quantized ``w``: the stored values convert to ``x.dtype`` ON the
      contraction operand (int8/fp8 bytes stream from HBM, the convert
      fuses into the operand read — in-register, never a dequantized
      copy back in HBM) and the per-output-channel scale multiplies the
      result. This IS the XLA twin: tests assert kernel-vs-oracle parity
      as an EXACT contract (same association, bit-identical on CPU), not
      a tolerance.
    - plain ``w``: ``x @ w`` — so policy-off sites compile the very same
      call graph and the budget-ladder program set is unchanged.
    """
    return qmm(x, w)


def qeinsum(pattern: str, x: jnp.ndarray, w) -> jnp.ndarray:
    """Einsum against a possibly-quantized weight whose scale tree was
    built with the weight's contraction axis removed AND whose remaining
    axes appear, in order, as the trailing output axes (true for the MLA
    per-head einsums "thn,hnc->thc" and "...hc,hvc->...hv") — so the
    scale broadcasts onto the result directly."""
    if not is_quantized(w):
        return jnp.einsum(pattern, x, w)
    out = jnp.einsum(pattern, x, w["q"].astype(x.dtype))
    return out * w["s"].astype(x.dtype)


def embed_lookup(embed, token_ids: jnp.ndarray) -> jnp.ndarray:
    """Embedding-table row gather, plain or per-row-quantized."""
    if not is_quantized(embed):
        return embed[token_ids]
    return embed["q"][token_ids].astype(embed["s"].dtype) * (
        embed["s"][token_ids][..., None]
    )


def tied_head_mm(h: jnp.ndarray, embed) -> jnp.ndarray:
    """``h @ embed.T`` (tied lm_head) for a plain or quantized table.

    A per-ROW (vocab) scaled int8 table serves both the gather above and
    this contraction: rows are this matmul's output channels, so the
    scale multiplies the [.., V] logits — the whole table streams int8
    on every decode step (it is the single largest weight in small tied
    models, e.g. 40% of Llama-3.2-1B's bytes)."""
    if not is_quantized(embed):
        return h @ embed.T
    return (h @ embed["q"].T.astype(h.dtype)) * embed["s"].astype(h.dtype)


def quantize_params(
    params: Params,
    include_lm_head: bool = True,
    tie_embed: bool = False,
) -> Params:
    """Quantize the big matmul weights of a models/llama.py params tree.

    Leaves norms, biases, and the router untouched. With ``tie_embed``
    (tie_word_embeddings models) the embedding table quantizes too with
    per-ROW scales — it doubles as the lm_head matmul operand, so it
    streams every decode step (see tied_head_mm). Jit-friendly: callers
    wrap in jit with quantized out_shardings to quantize directly into a
    sharded layout (engine/runner.py does).
    """
    out: Params = {k: v for k, v in params.items()}
    layers = []
    for layer in params["layers"]:
        qlayer = dict(layer)
        for k in QUANT_KEYS:
            if k in qlayer and k != "lm_head":
                qlayer[k] = quantize_weight(
                    qlayer[k], axis=QUANT_AXES.get(k, CONTRACT_AXIS)
                )
        layers.append(qlayer)
    out["layers"] = layers
    if include_lm_head and "lm_head" in params:
        out["lm_head"] = quantize_weight(params["lm_head"])
    if tie_embed:
        out["embed"] = quantize_weight(params["embed"], axis=-1)
    return out


# ---------------------------------------------------------------------------
# Per-matmul weight-quant policy (docs/architecture/weight_quant.md).
#
# The policy object is duck-typed (models/llama.py WeightQuantPolicy):
# four attributes — ``embedding``, ``attn``, ``mlp``, ``unembed`` — each
# None (full precision) or a WEIGHT_FORMATS entry. The functions below
# are the single mapping from policy sites to param-tree keys, shared by
# quantize-on-load, random init, and the mesh sharding-spec transform,
# so the three can't drift.
# ---------------------------------------------------------------------------


def policy_layer_fmts(policy) -> dict[str, str]:
    """Per-LAYER param key → storage format under ``policy`` (the attn
    and mlp sites; embedding/unembed are top-level, see
    quantize_params_policy)."""
    fmts: dict[str, str] = {}
    if getattr(policy, "attn", None):
        fmts.update({k: policy.attn for k in ATTN_KEYS})
    if getattr(policy, "mlp", None):
        fmts.update({k: policy.mlp for k in MLP_KEYS})
    return fmts


def quantize_params_policy(
    params: Params, policy, tie_embed: bool = False
) -> Params:
    """quantize_params with per-matmul site selection.

    The embedding table quantizes with per-ROW scales (it is a gather;
    when tied it doubles as the unembed matmul operand, so a tied model
    with ``unembed`` set quantizes it even if ``embedding`` is None —
    otherwise the unembed selection would silently be a no-op).
    Jit-friendly like quantize_params: the runner jits this with the
    policy spec tree as out_shardings so the bf16 copy never
    materializes resident beside the quantized one.
    """
    fmts = policy_layer_fmts(policy)
    out: Params = {k: v for k, v in params.items()}
    layers = []
    for layer in params["layers"]:
        qlayer = dict(layer)
        for k, fmt in fmts.items():
            if k in qlayer:
                qlayer[k] = quantize_weight(
                    qlayer[k], axis=QUANT_AXES.get(k, CONTRACT_AXIS), fmt=fmt
                )
        layers.append(qlayer)
    out["layers"] = layers
    unembed = getattr(policy, "unembed", None)
    if unembed and "lm_head" in params:
        out["lm_head"] = quantize_weight(params["lm_head"], fmt=unembed)
    embed_fmt = getattr(policy, "embedding", None) or (
        unembed if tie_embed else None
    )
    if embed_fmt:
        out["embed"] = quantize_weight(params["embed"], axis=-1, fmt=embed_fmt)
    return out


def quantize_param_specs_policy(
    specs: Params, policy, tie_embed: bool = False
) -> Params:
    """Mirror quantize_params_policy on a llama_param_specs tree: ``q``
    keeps the matrix's spec, ``s`` drops the contraction axis (per-row
    tables follow the vocab axis) — scales shard exactly like the
    matrices they scale, minus the reduced dimension."""
    fmts = policy_layer_fmts(policy)
    out: Params = {k: v for k, v in specs.items()}
    layers = []
    for layer in specs["layers"]:
        qlayer = dict(layer)
        for k in fmts:
            if k in qlayer:
                qlayer[k] = quant_spec(
                    qlayer[k], axis=QUANT_AXES.get(k, CONTRACT_AXIS)
                )
        layers.append(qlayer)
    out["layers"] = layers
    unembed = getattr(policy, "unembed", None)
    if unembed and "lm_head" in specs:
        out["lm_head"] = quant_spec(specs["lm_head"])
    embed_fmt = getattr(policy, "embedding", None) or (
        unembed if tie_embed else None
    )
    if embed_fmt:
        spec = specs["embed"]
        out["embed"] = {"q": spec, "s": P(spec[0])}
    return out


def quant_tree_stats(params: Params, dtype_bytes: int = 2) -> tuple[float, float]:
    """(bytes_saved, density) of a possibly-quantized params tree:
    resident bytes saved vs storing every parameter at ``dtype_bytes``,
    and the fraction of parameters stored quantized. Shape/dtype math
    only — works on ShapeDtypeStructs and never touches device data, so
    the runner can publish the gauges without a transfer."""
    total = 0
    qcount = 0
    saved = 0.0
    for leaf in jax.tree.leaves(params, is_leaf=is_quantized):
        if is_quantized(leaf):
            n = int(leaf["q"].size)
            stored = (
                n * jnp.dtype(leaf["q"].dtype).itemsize
                + int(leaf["s"].size) * jnp.dtype(leaf["s"].dtype).itemsize
            )
            saved += n * dtype_bytes - stored
            qcount += n
            total += n
        else:
            total += int(leaf.size)
    return saved, (qcount / total if total else 0.0)


# ---------------------------------------------------------------------------
# KV-cache block quantization (docs/architecture/kv_quant.md).
#
# Decode is HBM-bandwidth-bound (BENCH_r04 measured 282.8 GB/s effective),
# so int8 KV blocks roughly double effective decode bandwidth AND double
# KV capacity per chip. The cache keeps its [num_slots, kvH, D] layout but
# stores int8; a per-(block, kv-head) float32 scale rides alongside the
# block-table metadata (``kv_scales: [num_layers, 2, num_blocks, kvH]``).
# Reads dequantize ``int8 * scale`` — in-register inside the Pallas ragged
# kernel, as a gathered multiply in the XLA oracle — with IDENTICAL
# arithmetic, so kernel-vs-oracle parity is exact-contract.
#
# Write law (shared by every dispatch path, so both attention twins see
# the same cache bytes):
#   - a step's new K/V values scatter-max a per-(block, head) amax;
#   - a block whose FIRST slot is written this step is FRESH: its stale
#     scale (from a previous occupant of the physical block) resets, so
#     scales never ratchet up across allocator reuse;
#   - the block scale only GROWS within an occupancy:
#     new_scale = max(old_scale, amax/127). When it grows, the block's
#     EXISTING int8 entries requantize by round(q * old/new) — touched
#     blocks only, so the per-step cost is O(batch · block_size), never
#     O(cache);
#   - new values quantize at the new scale: clip(round(v/new_scale)).
# ---------------------------------------------------------------------------

KV_SCALE_DTYPE = jnp.float32


def quantize_kv_write(
    cache: jnp.ndarray,     # [num_slots, kvH, D] int8
    scales: jnp.ndarray,    # [num_blocks, kvH] float32
    slots: jnp.ndarray,     # [T] int32 — target slot per new token
    vals: jnp.ndarray,      # [T, kvH, D] float — new K or V values
    block_size: int,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Scatter new K/V values into an int8 cache under per-block scales.

    Returns (new_cache, new_scales). Padding rows aimed at trash block 0
    churn only block 0's scale, which is never read as real KV (every
    mask excludes it). Deterministic under duplicate touched blocks: all
    duplicates compute identical requantized rows, so scatter order
    cannot change the result.
    """
    num_blocks = scales.shape[0]
    bs = block_size
    vf = vals.astype(jnp.float32)
    blk = slots // bs                                       # [T]

    # Per-(touched block, head) amax of the NEW values.
    amax = jnp.zeros((num_blocks, scales.shape[1]), jnp.float32)
    amax = amax.at[blk].max(jnp.abs(vf).max(axis=-1))       # [nb, kvH]

    # Fresh-block detection: writing a block's first slot starts a new
    # occupancy — the stale scale from the physical block's previous
    # tenant must not survive into it.
    fresh = jnp.zeros((num_blocks,), bool).at[blk].max(slots % bs == 0)
    old = jnp.where(fresh[:, None], 0.0, scales)
    new_scales = jnp.maximum(old, amax / 127.0)             # [nb, kvH]

    # Requantize the touched blocks' existing entries where the scale
    # grew. Gather/rescale/scatter is bounded by the batch (T*bs slots),
    # not the cache; duplicate blocks write identical values.
    ratio = jnp.where(new_scales > 0, old / jnp.maximum(new_scales, 1e-30), 1.0)
    tslots = (blk[:, None] * bs + jnp.arange(bs)[None, :]).reshape(-1)
    rows = cache[tslots].astype(jnp.float32)                # [T*bs, kvH, D]
    rq = jnp.clip(
        jnp.round(rows * jnp.repeat(ratio[blk], bs, axis=0)[:, :, None]),
        -127, 127,
    ).astype(jnp.int8)
    cache = cache.at[tslots].set(rq)

    # Quantize and write the new tokens at the (possibly grown) scale.
    s_at = new_scales[blk]                                  # [T, kvH]
    q = jnp.clip(
        jnp.round(vf / jnp.maximum(s_at, 1e-30)[:, :, None]), -127, 127
    ).astype(jnp.int8)
    q = jnp.where((s_at > 0)[:, :, None], q, 0)
    # Untouched blocks: amax 0, fresh False => new_scales == scales
    # already; no masking needed.
    return cache.at[slots].set(q), new_scales


def quantize_kv_block_host(
    data: "object", num_kv_heads: int, head_dim: int
):
    """Host-side block quantization for the KVBM tiers: ``data`` is one
    block's values [..., kvH, D] float (any leading dims — typically
    [L, 2, bs, H, D]); scales are per (leading-dims-without-bs, head),
    i.e. amax over (block_size, head_dim). Returns (int8 array, float32
    scales shaped data.shape[:-3] + (kvH,)). numpy-only (pump thread)."""
    import numpy as np

    arr = np.asarray(data, np.float32)
    # amax over the block_size and head_dim axes -> [..., kvH]
    amax = np.abs(arr).max(axis=(-3, -1))
    s = amax / 127.0
    with np.errstate(divide="ignore", invalid="ignore"):
        q = np.where(
            s[..., None, :, None] > 0,
            np.clip(
                np.round(arr / np.maximum(s[..., None, :, None], 1e-30)),
                -127, 127,
            ),
            0.0,
        )
    return q.astype(np.int8), s.astype(np.float32)


def dequantize_kv_block_host(q, scales):
    """Invert quantize_kv_block_host: int8 [..., bs, kvH, D] * scales
    [..., kvH] -> float32 values."""
    import numpy as np

    return np.asarray(q, np.float32) * np.asarray(scales, np.float32)[
        ..., None, :, None
    ]


def quant_spec(spec: P, axis: int = CONTRACT_AXIS) -> Params:
    """Spec pytree for one quantized weight given its bf16 spec.

    ``q`` shards exactly like the original weight; ``s`` drops the
    contraction axis (e.g. wq P(None, "tp") → s P("tp"); wo P("tp", None)
    → s P(); MoE w_gate P("ep", None, "tp") → s P("ep", "tp")).
    """
    axes = list(spec)
    i = len(axes) + axis if axis < 0 else axis
    s_axes = axes[:i] + axes[i + 1 :]
    return {"q": spec, "s": P(*s_axes)}


def quantize_param_specs(
    specs: Params,
    include_lm_head: bool = True,
    tie_embed: bool = False,
) -> Params:
    """Transform a llama_param_specs tree to mirror quantize_params."""
    out: Params = {k: v for k, v in specs.items()}
    layers = []
    for layer in specs["layers"]:
        qlayer = dict(layer)
        for k in QUANT_KEYS:
            if k in qlayer and k != "lm_head":
                qlayer[k] = quant_spec(
                    qlayer[k], axis=QUANT_AXES.get(k, CONTRACT_AXIS)
                )
        layers.append(qlayer)
    out["layers"] = layers
    if include_lm_head and "lm_head" in specs:
        out["lm_head"] = quant_spec(specs["lm_head"])
    if tie_embed:
        # [V, D] with per-row (V) scales: q keeps the table's spec; s
        # follows the vocab axis (unsharded under our feature-sharded
        # embed, parallel/sharding.py).
        spec = specs["embed"]
        out["embed"] = {"q": spec, "s": P(spec[0])}
    return out


def init_params_policy(key, cfg, policy, dtype=jnp.bfloat16):
    """Random-init DIRECTLY into the quantized serving format selected by
    ``policy``, one layer at a time, so the full-precision transient
    never exceeds a single layer — an 8B model (16 GB bf16) can
    therefore init on a 16 GB chip whose steady-state int8 footprint is
    ~8 GB. Weight-IDENTICAL to llama.init_params →
    quantize_params_policy (same lk/ek/hk per-layer key split) —
    tests assert the single-chip and mesh paths produce equal greedy
    tokens, so key consumption here and in init_params must stay in
    lockstep."""
    import functools

    from dynamo_tpu.models import llama

    fmts = policy_layer_fmts(policy)

    @functools.partial(jax.jit, static_argnums=(1,))
    def one_layer(k, li_repr):
        p = llama.init_layer_params(k, cfg, li_repr, dtype)
        return {
            name: (
                quantize_weight(
                    w,
                    axis=QUANT_AXES.get(name, CONTRACT_AXIS),
                    fmt=fmts[name],
                )
                if name in fmts
                else w
            )
            for name, w in p.items()
        }

    # One compile per layer KIND (dense vs MoE), not per layer index — the
    # index only matters through cfg.moe_layer(li).
    kind_repr = {
        flag: next(
            i for i in range(cfg.num_layers) if cfg.moe_layer(i) == flag
        )
        for flag in {cfg.moe_layer(i) for i in range(cfg.num_layers)}
    }
    lk, ek, hk = jax.random.split(key, 3)
    layer_keys = jax.random.split(lk, cfg.num_layers)
    layers = []
    for li in range(cfg.num_layers):
        layer = one_layer(layer_keys[li], kind_repr[cfg.moe_layer(li)])
        jax.block_until_ready(jax.tree.leaves(layer)[0])
        layers.append(layer)

    D, V = cfg.hidden_size, cfg.vocab_size
    unembed = getattr(policy, "unembed", None)
    embed_fmt = getattr(policy, "embedding", None) or (
        unembed if cfg.tie_word_embeddings else None
    )
    if embed_fmt:
        embed = jax.jit(
            lambda k: quantize_weight(
                llama._dense_init(k, (V, D), dtype), axis=-1, fmt=embed_fmt
            )
        )(ek)
    else:
        embed = jax.jit(lambda k: llama._dense_init(k, (V, D), dtype))(ek)
    params = {
        "embed": embed,
        "layers": layers,
        "ln_f": jnp.ones((D,), dtype),
    }
    if not cfg.tie_word_embeddings:
        if unembed:
            params["lm_head"] = jax.jit(
                lambda k: quantize_weight(
                    llama._dense_init(k, (D, V), dtype), fmt=unembed
                )
            )(hk)
        else:
            params["lm_head"] = jax.jit(
                lambda k: llama._dense_init(k, (D, V), dtype)
            )(hk)
    return params


def init_params_int8(key, cfg, dtype=jnp.bfloat16):
    """Legacy whole-model int8 init (EngineConfig.quant="int8"): the
    all-sites policy minus the embedding gather (per-row embed only when
    tied, where the table doubles as the unembed operand)."""
    from types import SimpleNamespace

    policy = SimpleNamespace(
        embedding=None, attn="int8", mlp="int8", unembed="int8"
    )
    return init_params_policy(key, cfg, policy, dtype)

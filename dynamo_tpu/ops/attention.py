"""Attention over a paged KV cache — jnp reference implementations.

The cache layout is the contract shared with the Pallas kernels
(ops/pallas/): per layer, ``k_cache/v_cache: [num_slots, n_kv_heads,
head_dim]`` where ``num_slots = num_blocks * block_size`` and block ``b``
owns slots ``[b*block_size, (b+1)*block_size)``. A sequence's KV lives in
the blocks listed by its block table, in order; the global position of a
token equals its index in that slot ordering. Block 0 is the trash block:
padded query positions write there and it is never allocated.

Both prefill and decode process key blocks with an online-softmax scan
(flash-attention style) so peak memory is one key block per step — no
materialized [ctx, ctx] score matrices and no full-cache gather. This is
the XLA-friendly formulation (static shapes, lax.scan); the Pallas kernels
keep the same math but stream pages HBM→VMEM explicitly.

Role of the reference's engine-internal attention (delegated to vLLM/FA in
the reference — here first-class, per SURVEY.md §2 'Native components' #3).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp

NEG_INF = -1e30


_pallas_override: bool | None = None


def set_pallas_override(value: bool | None) -> None:
    """Process-wide force for the Pallas path (None = auto). The sharded
    (mesh) runner disables it: pallas_call has no SPMD partitioning rule
    yet, so multi-chip serving keeps the jnp path until the kernels are
    integrated under shard_map."""
    global _pallas_override
    _pallas_override = value


def pallas_enabled() -> bool:
    """Use the Pallas kernels (ops/pallas/) for paged attention.

    Default: on for real TPU backends (compiled Mosaic kernels); off
    elsewhere (interpret mode is a correctness tool, far too slow to be a
    default on CPU). ``DYNAMO_TPU_PALLAS=1/0`` overrides either way — the
    A/B switch for benches and the CPU-interpret path for tests.
    """
    if _pallas_override is not None:
        return _pallas_override
    env = os.environ.get("DYNAMO_TPU_PALLAS")
    if env is not None:
        return env.lower() not in ("0", "false", "off")
    return jax.default_backend() == "tpu"


def _pad_q_for_cache(q, k_cache):
    """Lane-pad q to a padded cache's head dim (ops/pallas/attention.py
    cache-layout contract). Every implementation scales scores by
    1/sqrt(q.shape[-1]), so pre-scale by sqrt(Dc/D) to keep the net scale
    at the TRUE head dim; the zero lanes are otherwise transparent."""
    D, Dc = q.shape[-1], k_cache.shape[-1]
    if Dc == D:
        return q
    q = (q * jnp.asarray((Dc / D) ** 0.5, q.dtype)).astype(q.dtype)
    return jnp.pad(q, ((0, 0),) * (q.ndim - 1) + ((0, Dc - D),))


def _use_pallas(k_cache, block_size: int) -> bool:
    if not pallas_enabled():
        return False
    from dynamo_tpu.ops.pallas.attention import pallas_supported

    return pallas_supported(
        block_size, k_cache.shape[1], k_cache.shape[2], k_cache.dtype
    )


def decode_attention(
    q, k_cache, v_cache, block_tables, context_lens, block_size: int
):
    """Dispatch: Pallas kernel on TPU (supported shapes), jnp reference
    elsewhere. Handles lane-padded caches for both paths."""
    D = q.shape[-1]
    qp = _pad_q_for_cache(q, k_cache)
    if _use_pallas(k_cache, block_size):
        from dynamo_tpu.ops.pallas import paged_decode_attention_pallas

        out = paged_decode_attention_pallas(
            qp, k_cache, v_cache, block_tables, context_lens, block_size
        )
    else:
        out = paged_decode_attention(
            qp, k_cache, v_cache, block_tables, context_lens, block_size
        )
    return out[..., :D]


def prefill_attention(
    q, k_cache, v_cache, block_tables, q_start, total_len, block_size: int
):
    """Dispatch for batched prefill attention: q [N, T, H, D], lane-wise
    block tables / prefix lengths. Pallas kernel on TPU, vmapped jnp
    reference elsewhere."""
    D = q.shape[-1]
    qp = _pad_q_for_cache(q, k_cache)
    if _use_pallas(k_cache, block_size):
        from dynamo_tpu.ops.pallas import paged_prefill_attention_pallas

        out = paged_prefill_attention_pallas(
            qp, k_cache, v_cache, block_tables, q_start, total_len, block_size
        )
    else:
        out = jax.vmap(
            lambda qq, bt, ps, tl: paged_prefill_attention(
                qq, k_cache, v_cache, bt, ps, tl, block_size
            )
        )(qp, block_tables, q_start, total_len)
    return out[..., :D]


def _safe_div(acc: jnp.ndarray, l: jnp.ndarray) -> jnp.ndarray:
    """acc / l, returning 0 where nothing was attended (fully masked)."""
    return jnp.where(l[..., None] > 0, acc / jnp.maximum(l[..., None], 1e-30), 0.0)


def paged_prefill_attention(
    q: jnp.ndarray,           # [T, n_heads, head_dim] — new tokens' queries
    k_cache: jnp.ndarray,     # [num_slots, n_kv_heads, head_dim]
    v_cache: jnp.ndarray,
    block_table: jnp.ndarray, # [max_blocks] int32
    q_start: jnp.ndarray,     # scalar: global position of q[0] (prefix length)
    total_len: jnp.ndarray,   # scalar: prefix + new tokens (real, unpadded)
    block_size: int,
) -> jnp.ndarray:
    """Causal attention of new tokens over (cached prefix + themselves).

    Assumes the new tokens' K/V were already scattered into the cache, so
    every key this needs is reachable through `block_table`. Supports
    prefix-cache hits natively: q_start > 0 attends to blocks computed by an
    earlier request (or a remote prefill worker).
    """
    T, H, D = q.shape
    kvH = k_cache.shape[1]
    G = H // kvH
    scale = 1.0 / (D**0.5)
    qr = (q.astype(jnp.float32) * scale).reshape(T, kvH, G, D)
    q_pos = q_start + jnp.arange(T)  # [T]

    def body(carry, j):
        m, l, acc = carry
        slots = block_table[j] * block_size + jnp.arange(block_size)
        k = k_cache[slots].astype(jnp.float32)  # [bs, kvH, D]
        v = v_cache[slots].astype(jnp.float32)
        scores = jnp.einsum("tkgd,skd->tkgs", qr, k)  # [T, kvH, G, bs]
        key_pos = j * block_size + jnp.arange(block_size)
        mask = (key_pos[None, :] <= q_pos[:, None]) & (key_pos[None, :] < total_len)
        scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)

        m_new = jnp.maximum(m, scores.max(axis=-1))
        # Renormalize previous accumulator; masked-out rows stay at zero.
        correction = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])
        p = jnp.where(mask[:, None, None, :], p, 0.0)
        l_new = l * correction + p.sum(axis=-1)
        acc_new = acc * correction[..., None] + jnp.einsum("tkgs,skd->tkgd", p, v)
        return (m_new, l_new, acc_new), None

    num_blocks = block_table.shape[0]
    init = (
        jnp.full((T, kvH, G), NEG_INF, jnp.float32),
        jnp.zeros((T, kvH, G), jnp.float32),
        jnp.zeros((T, kvH, G, D), jnp.float32),
    )
    (m, l, acc), _ = jax.lax.scan(body, init, jnp.arange(num_blocks))
    return _safe_div(acc, l).reshape(T, H, D).astype(q.dtype)


def paged_decode_attention(
    q: jnp.ndarray,             # [B, n_heads, head_dim]
    k_cache: jnp.ndarray,       # [num_slots, n_kv_heads, head_dim]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks] int32
    context_lens: jnp.ndarray,  # [B] int32 — includes the current token
    block_size: int,
) -> jnp.ndarray:
    """One-token-per-sequence attention over each sequence's paged KV.

    Inactive batch slots (context_len == 0) return zeros.
    """
    B, H, D = q.shape
    kvH = k_cache.shape[1]
    G = H // kvH
    scale = 1.0 / (D**0.5)
    qr = (q.astype(jnp.float32) * scale).reshape(B, kvH, G, D)

    def body(carry, j):
        m, l, acc = carry
        slots = block_tables[:, j, None] * block_size + jnp.arange(block_size)
        k = k_cache[slots].astype(jnp.float32)  # [B, bs, kvH, D]
        v = v_cache[slots].astype(jnp.float32)
        scores = jnp.einsum("bkgd,bskd->bkgs", qr, k)  # [B, kvH, G, bs]
        key_pos = j * block_size + jnp.arange(block_size)
        mask = key_pos[None, :] < context_lens[:, None]  # [B, bs]
        scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)

        m_new = jnp.maximum(m, scores.max(axis=-1))
        correction = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])
        p = jnp.where(mask[:, None, None, :], p, 0.0)
        l_new = l * correction + p.sum(axis=-1)
        acc_new = acc * correction[..., None] + jnp.einsum("bkgs,bskd->bkgd", p, v)
        return (m_new, l_new, acc_new), None

    max_blocks = block_tables.shape[1]
    init = (
        jnp.full((B, kvH, G), NEG_INF, jnp.float32),
        jnp.zeros((B, kvH, G), jnp.float32),
        jnp.zeros((B, kvH, G, D), jnp.float32),
    )
    (m, l, acc), _ = jax.lax.scan(body, init, jnp.arange(max_blocks))
    return _safe_div(acc, l).reshape(B, H, D).astype(q.dtype)


def full_causal_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray
) -> jnp.ndarray:
    """Plain causal attention [T, H, D] x [T, kvH, D] — the no-cache
    reference path used to validate the paged implementations."""
    T, H, D = q.shape
    kvH = k.shape[1]
    G = H // kvH
    scale = 1.0 / (D**0.5)
    qr = (q.astype(jnp.float32) * scale).reshape(T, kvH, G, D)
    scores = jnp.einsum("tkgd,skd->tkgs", qr, k.astype(jnp.float32))
    mask = jnp.arange(T)[None, :] <= jnp.arange(T)[:, None]  # [Tq, Tk]
    scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("tkgs,skd->tkgd", p, v.astype(jnp.float32))
    return out.reshape(T, H, D).astype(q.dtype)

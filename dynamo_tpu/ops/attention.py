"""Attention over a paged KV cache — jnp reference implementations.

The cache layout is the contract shared with the Pallas kernels
(ops/pallas/): per layer, ``k_cache/v_cache: [num_slots, n_kv_heads,
head_dim]`` where ``num_slots = num_blocks * block_size`` and block ``b``
owns slots ``[b*block_size, (b+1)*block_size)``. A sequence's KV lives in
the blocks listed by its block table, in order; the global position of a
token equals its index in that slot ordering. Block 0 is the trash block:
padded query positions write there and it is never allocated.

Both prefill and decode process key blocks with an online-softmax scan
(flash-attention style) so peak memory is one key block per step — no
materialized [ctx, ctx] score matrices and no full-cache gather. This is
the XLA-friendly formulation (static shapes, lax.scan); the Pallas kernels
keep the same math but stream pages HBM→VMEM explicitly.

Role of the reference's engine-internal attention (delegated to vLLM/FA in
the reference — here first-class, per SURVEY.md §2 'Native components' #3).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from dynamo_tpu.utils.jax_compat import ensure_current_defaults

# Drift-sensitive defaults (threefry partitionability) must be set before
# the first trace anywhere in the process — every engine/model path
# imports this module ahead of touching params or caches.
ensure_current_defaults()

NEG_INF = -1e30


def pallas_enabled() -> bool:
    """Use the Pallas kernels (ops/pallas/) for paged attention.

    Default: on for real TPU backends (compiled Mosaic kernels); off
    elsewhere (interpret mode is a correctness tool, far too slow to be a
    default on CPU). ``DYNAMO_TPU_PALLAS=1/0`` overrides either way — the
    A/B switch for benches and the CPU-interpret path for tests.
    """
    env = os.environ.get("DYNAMO_TPU_PALLAS")
    if env is not None:
        return env.lower() not in ("0", "false", "off")
    return jax.default_backend() == "tpu"


@dataclass(frozen=True)
class AttnDispatch:
    """Per-runner attention path selection (threaded through the model fns
    instead of process-global state, so two runners in one process — e.g. a
    sharded server plus a single-chip sidecar — never fight over a global).

    With a mesh, the Pallas kernels run under ``shard_map`` over the ``tp``
    axis: the KV cache is head-sharded (parallel/sharding.py kv_cache_spec),
    queries arrive head-sharded from the column-parallel q projection, and
    attention is embarrassingly parallel over kv-head groups — each chip
    runs the kernel on its local heads with zero cross-chip traffic.
    (pallas_call has no GSPMD partitioning rule; shard_map is the supported
    way to place a kernel per-shard.)
    """

    use_pallas: bool = False
    mesh: object | None = None  # jax.sharding.Mesh when TP-sharded
    tp_axis: str = "tp"
    # MLA models: the cache is ONE shared latent head per token, so it
    # replicates across tp while q heads shard — each shard runs the
    # kernel on its local q heads against the full cache.
    kv_replicated: bool = False
    # Long-context mode: the paged cache's SLOT axis is sharded over the
    # sp mesh axis (total KV = sp x one device's arrays); attention runs
    # per-shard partials merged with a logsumexp combine. Composes with
    # tp (heads shard over tp AND slots over sp) and with the Pallas
    # kernels (per-shard kernel call over a compacted stripe of the block
    # table, logsumexp stats merged across sp). Requires the striped
    # allocator: logical block i of a sequence lives on shard i % sp.
    kv_sp: bool = False

    def _wrap(self, fn, in_specs, out_specs):
        from dynamo_tpu.utils.jax_compat import shard_map

        return shard_map(
            fn, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )

    @property
    def _ax(self):
        """The tp axis name if the mesh has one (head-sharded kernels),
        else None (fully replicated per-device kernels — e.g. a dp-only
        mesh, where pallas_call still needs shard_map placement because
        GSPMD has no partitioning rule for it)."""
        shape = getattr(self.mesh, "shape", {})
        return self.tp_axis if self.tp_axis in shape else None

    def _dp(self, batch: int):
        """The dp axis name when the mesh has dp>1 and it divides the
        batch/lane dim — each dp group then runs the kernel on its own
        batch slice (data-parallel serving within one engine)."""
        shape = getattr(self.mesh, "shape", {})
        n = shape.get("dp", 1)
        return "dp" if n > 1 and batch % n == 0 else None

    def _sp(self, T: int):
        """The sp axis name when the mesh has sp>1 dividing the prefill
        query length — sequence-parallel prefill: each sp shard computes
        its query tile against the full (replicated) KV cache, the
        long-context split SURVEY §5 calls for (no backend engine to hide
        behind). Causality is preserved by offsetting q_start per shard."""
        shape = getattr(self.mesh, "shape", {})
        n = shape.get("sp", 1)
        return "sp" if n > 1 and T % n == 0 else None

    @property
    def _sp_n(self) -> int:
        return getattr(self.mesh, "shape", {}).get("sp", 1)

    def _kv_sp_specs(self):
        """(q/out spec, cache spec) for the kv_sp shard_map: q and out are
        head-sharded over tp (replicated if no tp axis / MLA-replicated
        cache keeps its heads whole), cache is slot-sharded over sp and
        head-sharded over tp."""
        from jax.sharding import PartitionSpec as P

        kv_ax = None if self.kv_replicated else self._ax
        return P(None, self._ax, None), P("sp", kv_ax, None)

    @staticmethod
    def _stats_merge(out, m, l, axis: str):
        """Merge per-shard NORMALIZED outputs + logsumexp stats (m, l)
        across `axis`: out_r = acc_r / l_r, so acc_g = Σ out_r·l_r·e^(m_r−m_g)
        and l_g = Σ l_r·e^(m_r−m_g). Empty shards (l=0, m=−inf) weigh 0."""
        m_g = jax.lax.pmax(m, axis)
        w = jnp.exp(m - m_g) * l
        l_g = jax.lax.psum(w, axis)
        o = jax.lax.psum(out.astype(jnp.float32) * w[..., None], axis)
        return jnp.where(
            l_g[..., None] > 0, o / jnp.maximum(l_g[..., None], 1e-30), 0.0
        )

    def _stripe_tables(self, block_tables, local_blocks: int):
        """This sp shard's stripe of the block tables, localized: column j
        holds the LOCAL page id of logical page r + j·sp (r = shard index).
        Entries outside the shard (impossible under the striped allocator;
        padding zeros on r>0) clip into range — their key positions land
        ≥ context and mask out."""
        sp = self._sp_n
        r = jax.lax.axis_index("sp")
        max_blocks = block_tables.shape[-1]
        cols = jnp.minimum(
            r + jnp.arange(-(-max_blocks // sp)) * sp, max_blocks - 1
        )
        local = jnp.take(block_tables, cols, axis=-1) - r * local_blocks
        return jnp.clip(local, 0, local_blocks - 1), r

    def _kv_sp_decode(self, qp, k_cache, v_cache, tables, ctx,
                      block_size: int, window: int):
        """Shared striped-scan body for every decode-shaped kv_sp call
        (one query row per table row): each sp shard scans only its own
        stripe of the paged cache and partials merge with the logsumexp
        combine. ``decode`` feeds per-LANE tables; ``ragged`` reduces
        its flat batch to per-TOKEN tables and reuses this verbatim."""
        from jax.sharding import PartitionSpec as P

        sp = self._sp_n
        qh, sp_cache = self._kv_sp_specs()
        if self.use_pallas:
            from dynamo_tpu.ops.pallas import paged_decode_attention_pallas

            def body(qs, ks, vs, bt, c):
                lt, r = self._stripe_tables(bt, ks.shape[0] // block_size)
                o, m, l = paged_decode_attention_pallas(
                    qs, ks, vs, lt, c, block_size, window=window,
                    page_offset=jnp.reshape(r, (1,)), page_stride=sp,
                    with_stats=True,
                )
                return self._stats_merge(o, m, l, "sp").astype(qs.dtype)

        else:
            body = partial(
                paged_decode_attention_sp, block_size=block_size,
                window=window, num_shards=sp,
            )
        return self._wrap(
            body,
            in_specs=(qh, sp_cache, sp_cache, P(), P()),
            out_specs=qh,
        )(qp, k_cache, v_cache, tables, ctx)

    def decode(self, q, k_cache, v_cache, block_tables, context_lens,
               block_size: int, window: int = 0):
        D = q.shape[-1]
        qp = _pad_q_for_cache(q, k_cache)
        if self.kv_sp:
            out = self._kv_sp_decode(
                qp, k_cache, v_cache, block_tables, context_lens,
                block_size, window,
            )
            return out[..., :D]
        if not self.use_pallas:
            out = paged_decode_attention(
                qp, k_cache, v_cache, block_tables, context_lens, block_size,
                window,
            )
        else:
            from dynamo_tpu.ops.pallas import paged_decode_attention_pallas

            fn = partial(
                paged_decode_attention_pallas, block_size=block_size,
                window=window,
            )
            if self.mesh is not None:
                from jax.sharding import PartitionSpec as P

                dp = self._dp(q.shape[0])
                qh = P(dp, self._ax, None)
                kv_ax = None if self.kv_replicated else self._ax
                kvh = P(None, kv_ax, None)  # cache replicated over dp
                fn = self._wrap(
                    fn,
                    in_specs=(qh, kvh, kvh, P(dp, None), P(dp)),
                    out_specs=qh,
                )
            out = fn(qp, k_cache, v_cache, block_tables, context_lens)
        return out[..., :D]

    def ragged(
        self, q, k_cache, v_cache, block_tables, token_seq, token_pos,
        q_start, q_len, kv_len, row_start, block_size: int, window: int = 0,
        k_scales=None, v_scales=None,
    ):
        """Unified mixed prefill+decode attention over one flat ragged
        token batch (the single-dispatch step — ops/pallas/
        ragged_attention.py). Token-level metadata (``token_seq`` /
        ``token_pos``) drives the XLA twin; span-level metadata drives
        the kernel. Both views describe the same batch and the runner
        builds them together (engine/runner.py unified_step).

        ``k_scales``/``v_scales`` ([num_blocks, kvH] float32) flip the
        int8-KV path on: the cache holds int8 pages that dequantize by
        per-(block, head) scale inside whichever implementation runs
        (kernel in-register, oracle on the gathered page). Under a mesh
        the scales head axis shards exactly like the cache heads."""
        D = q.shape[-1]
        qp = _pad_q_for_cache(q, k_cache)
        if self.kv_sp:
            # Slot-sharded cache: the ragged batch is exactly batched
            # decode attention with per-TOKEN block tables (the oracle's
            # own reduction), so the striped-scan machinery the decode
            # path already runs applies verbatim with T in place of B.
            # (kv_quant × kv_sp stays rejected at config validation.)
            tok_tables = jnp.take(
                block_tables,
                jnp.clip(token_seq, 0, block_tables.shape[0] - 1),
                axis=0,
            )  # [T, max_blocks]
            ctx = jnp.maximum(token_pos + 1, 0)
            out = self._kv_sp_decode(
                qp, k_cache, v_cache, tok_tables, ctx, block_size, window
            )
            return out[..., :D]
        if not self.use_pallas:
            out = ragged_paged_attention(
                qp, k_cache, v_cache, block_tables, token_seq, token_pos,
                block_size, window, k_scales=k_scales, v_scales=v_scales,
            )
        else:
            from dynamo_tpu.ops.pallas.ragged_attention import (
                ragged_paged_attention_pallas,
            )

            base = partial(
                ragged_paged_attention_pallas, block_size=block_size,
                window=window,
            )
            if k_scales is not None:
                # Keyword-forward the trailing scale operands so the
                # positional layout shard_map maps in_specs onto stays
                # (q, k, v, tables, qs, ql, kv, rs[, ks, vs]).
                def fn(qx, kx, vx, bt, a, b, c, d, ks, vs):  # noqa: E306
                    return base(
                        qx, kx, vx, bt, a, b, c, d, k_scales=ks, v_scales=vs
                    )
            else:
                fn = base
            if self.mesh is not None:
                from jax.sharding import PartitionSpec as P

                qh = P(None, self._ax, None)
                kv_ax = None if self.kv_replicated else self._ax
                kvh = P(None, kv_ax, None)
                # Scales shard their head axis with the cache heads
                # (replicated for MLA / headless meshes).
                sc = (P(None, kv_ax),) * 2 if k_scales is not None else ()
                fn = self._wrap(
                    fn,
                    in_specs=(qh, kvh, kvh, P(), P(), P(), P(), P(), *sc),
                    out_specs=qh,
                )
            args = (
                qp, k_cache, v_cache, block_tables, q_start, q_len, kv_len,
                row_start,
            )
            if k_scales is not None:
                args = args + (k_scales, v_scales)
            out = fn(*args)
        return out[..., :D]

    def prefill(self, q, k_cache, v_cache, block_tables, q_start, total_len,
                block_size: int, window: int = 0):
        D = q.shape[-1]
        qp = _pad_q_for_cache(q, k_cache)
        if self.kv_sp:
            from jax.sharding import PartitionSpec as P

            sp = self._sp_n
            _, sp_cache = self._kv_sp_specs()
            qh = P(None, None, self._ax, None)  # [N, T, H, D]
            if self.use_pallas:
                from dynamo_tpu.ops.pallas import (
                    paged_prefill_attention_pallas,
                )

                def body(qs, ks, vs, bt, q_starts, totals):
                    lt, r = self._stripe_tables(bt, ks.shape[0] // block_size)
                    o, m, l = paged_prefill_attention_pallas(
                        qs, ks, vs, lt, q_starts, totals, block_size,
                        window=window, page_offset=jnp.reshape(r, (1,)),
                        page_stride=sp, with_stats=True,
                    )
                    return self._stats_merge(o, m, l, "sp").astype(qs.dtype)

            else:
                body = partial(
                    paged_prefill_attention_sp, block_size=block_size,
                    window=window, num_shards=sp,
                )
            out = self._wrap(
                body,
                in_specs=(qh, sp_cache, sp_cache, P(), P(), P()),
                out_specs=qh,
            )(qp, k_cache, v_cache, block_tables, q_start, total_len)
            return out[..., :D]
        if not self.use_pallas:
            out = jax.vmap(
                lambda qq, bt, ps, tl: paged_prefill_attention(
                    qq, k_cache, v_cache, bt, ps, tl, block_size, window
                )
            )(qp, block_tables, q_start, total_len)
        else:
            from dynamo_tpu.ops.pallas import paged_prefill_attention_pallas

            base = partial(
                paged_prefill_attention_pallas, block_size=block_size,
                window=window,
            )
            fn = base
            if self.mesh is not None:
                from jax.sharding import PartitionSpec as P

                dp = self._dp(q.shape[0])
                sp = self._sp(q.shape[1])
                if sp is not None:
                    def fn(qs, ks, vs, bts, q_starts, totals):  # noqa: E306
                        # Each sp shard holds a contiguous query tile; its
                        # global start is q_start + shard_index * local_T.
                        off = jax.lax.axis_index("sp") * qs.shape[1]
                        return base(qs, ks, vs, bts, q_starts + off, totals)
                qh = P(dp, sp, self._ax, None)
                kv_ax = None if self.kv_replicated else self._ax
                kvh = P(None, kv_ax, None)
                fn = self._wrap(
                    fn,
                    in_specs=(qh, kvh, kvh, P(dp, None), P(dp), P(dp)),
                    out_specs=qh,
                )
            out = fn(qp, k_cache, v_cache, block_tables, q_start, total_len)
        return out[..., :D]


def _pad_q_for_cache(q, k_cache):
    """Lane-pad q to a padded cache's head dim (ops/pallas/attention.py
    cache-layout contract). Every implementation scales scores by
    1/sqrt(q.shape[-1]), so pre-scale by sqrt(Dc/D) to keep the net scale
    at the TRUE head dim; the zero lanes are otherwise transparent."""
    D, Dc = q.shape[-1], k_cache.shape[-1]
    if Dc == D:
        return q
    q = (q * jnp.asarray((Dc / D) ** 0.5, q.dtype)).astype(q.dtype)
    return jnp.pad(q, ((0, 0),) * (q.ndim - 1) + ((0, Dc - D),))


def _use_pallas(k_cache, block_size: int) -> bool:
    if not pallas_enabled():
        return False
    from dynamo_tpu.ops.pallas.attention import pallas_supported

    return pallas_supported(
        block_size, k_cache.shape[1], k_cache.shape[2], k_cache.dtype
    )


def _default_dispatch(k_cache, block_size: int) -> AttnDispatch:
    return AttnDispatch(use_pallas=_use_pallas(k_cache, block_size))


def decode_attention(
    q, k_cache, v_cache, block_tables, context_lens, block_size: int,
    window: int = 0,
):
    """Default (single-chip, env-driven) dispatch — used when no per-runner
    AttnDispatch is threaded in. Handles lane-padded caches for both paths."""
    return _default_dispatch(k_cache, block_size).decode(
        q, k_cache, v_cache, block_tables, context_lens, block_size, window
    )


def prefill_attention(
    q, k_cache, v_cache, block_tables, q_start, total_len, block_size: int,
    window: int = 0,
):
    """Default dispatch for batched prefill attention: q [N, T, H, D],
    lane-wise block tables / prefix lengths."""
    return _default_dispatch(k_cache, block_size).prefill(
        q, k_cache, v_cache, block_tables, q_start, total_len, block_size,
        window,
    )


def _safe_div(acc: jnp.ndarray, l: jnp.ndarray) -> jnp.ndarray:
    """acc / l, returning 0 where nothing was attended (fully masked)."""
    return jnp.where(l[..., None] > 0, acc / jnp.maximum(l[..., None], 1e-30), 0.0)


def _dequant_rows(vals, entry, scales):
    """Per-block dequant for a gathered page: ``vals`` [..., bs, kvH, D]
    float32 (cast from int8), ``entry`` the physical block id(s) ([] or
    [B]), ``scales`` [num_blocks, kvH]. This is the oracle half of the
    exact-contract arithmetic the Pallas ragged kernel performs
    in-register (ops/pallas/ragged_attention.py): int8 * scale, nothing
    else."""
    s = scales[entry]                       # [kvH] or [B, kvH]
    return vals * s[..., None, :, None]


def _prefill_partials(
    q, k_cache, v_cache, block_table, q_start, total_len, block_size: int,
    slot_fn, window: int = 0, page_offset=0, page_stride: int = 1,
    k_scales=None, v_scales=None,
):
    """Online-softmax scan core for one lane's prefill attention; returns
    the UN-normalized partials (m, l, acc) so both the plain path
    (normalize locally) and the sp-sharded path (merge across shards
    first) share one copy of the math. ``slot_fn(cache, slots) ->
    (indices, ownership_mask)`` translates global slot ids; the identity
    hook owns everything.

    ``page_offset``/``page_stride`` restrict the scan to logical pages
    ``offset, offset+stride, offset+2*stride, ...`` — the striped-scan
    mode where sp shard r (holding the blocks the striped allocator
    placed at logical indices ≡ r mod sp) scans ONLY its own pages, so
    attention FLOPs partition sp-ways along with the memory (the r04
    full-scan replication VERDICT flagged is gone)."""
    T, H, D = q.shape
    kvH = k_cache.shape[1]
    G = H // kvH
    scale = 1.0 / (D**0.5)
    qr = (q.astype(jnp.float32) * scale).reshape(T, kvH, G, D)
    q_pos = q_start + jnp.arange(T)  # [T]
    max_blocks = block_table.shape[0]
    if window:
        # Page skip: the earliest key any of this call's queries can see
        # is q_start - window + 1; pages wholly before it are never
        # scanned, so windowed prefill is O(T + window), not O(ctx).
        start = jnp.maximum(q_start - window + 1, 0) // block_size
        span = -(-(T + window) // block_size) + 1
    else:
        start = jnp.int32(0)
        span = max_blocks
    nsteps = min(
        -(-max_blocks // page_stride),
        -(-span // page_stride) + (1 if page_stride > 1 else 0),
    )
    # First strided index at/after `start`: ceil((start - offset)/stride).
    q0 = jnp.maximum((start - page_offset + page_stride - 1) // page_stride, 0)

    def body(carry, j):
        m, l, acc = carry
        blk = page_offset + (q0 + j) * page_stride
        entry = block_table[jnp.minimum(blk, max_blocks - 1)]
        slots = entry * block_size + jnp.arange(block_size)
        idx, ok = slot_fn(k_cache, slots)
        k = k_cache[idx].astype(jnp.float32)  # [bs, kvH, D]
        v = v_cache[idx].astype(jnp.float32)
        if k_scales is not None:
            k = _dequant_rows(k, entry, k_scales)
            v = _dequant_rows(v, entry, v_scales)
        scores = jnp.einsum("tkgd,skd->tkgs", qr, k)  # [T, kvH, G, bs]
        # Positions from the UNCLAMPED page index: a clamped over-the-end
        # gather returns garbage data whose key_pos lands >= total_len and
        # is therefore masked.
        key_pos = blk * block_size + jnp.arange(block_size)
        mask = (
            (key_pos[None, :] <= q_pos[:, None])
            & (key_pos[None, :] < total_len)
            & ok[None, :]
        )
        if window:
            # Sliding-window attention (Mistral-style): each query sees
            # only the last `window` keys.
            mask = mask & (key_pos[None, :] > q_pos[:, None] - window)
        scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)

        m_new = jnp.maximum(m, scores.max(axis=-1))
        # Renormalize previous accumulator; masked-out rows stay at zero.
        correction = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])
        p = jnp.where(mask[:, None, None, :], p, 0.0)
        l_new = l * correction + p.sum(axis=-1)
        acc_new = acc * correction[..., None] + jnp.einsum("tkgs,skd->tkgd", p, v)
        return (m_new, l_new, acc_new), None

    init = (
        jnp.full((T, kvH, G), NEG_INF, jnp.float32),
        jnp.zeros((T, kvH, G), jnp.float32),
        jnp.zeros((T, kvH, G, D), jnp.float32),
    )
    (m, l, acc), _ = jax.lax.scan(body, init, jnp.arange(nsteps))
    return m, l, acc


def _decode_partials(
    q, k_cache, v_cache, block_tables, context_lens, block_size: int,
    slot_fn, window: int = 0, page_offset=0, page_stride: int = 1,
    k_scales=None, v_scales=None,
):
    """Batched decode counterpart of _prefill_partials (one query token per
    lane); returns un-normalized (m, l, acc).

    With a sliding window the scan SKIPS pages wholly behind it: each lane
    starts at its first in-window page and the trip count shrinks to
    ceil(window/bs)+1 — windowed decode cost is O(window), not O(ctx).

    ``page_offset``/``page_stride``: striped-scan mode (see
    _prefill_partials) — scan only logical pages ≡ offset (mod stride)."""
    B, H, D = q.shape
    kvH = k_cache.shape[1]
    G = H // kvH
    scale = 1.0 / (D**0.5)
    qr = (q.astype(jnp.float32) * scale).reshape(B, kvH, G, D)
    max_blocks = block_tables.shape[1]
    if window:
        span = -(-window // block_size) + 1
        start = jnp.maximum(context_lens - window, 0) // block_size  # [B]
    else:
        span = max_blocks
        start = jnp.zeros_like(context_lens)
    nsteps = min(
        -(-max_blocks // page_stride),
        -(-span // page_stride) + (1 if page_stride > 1 else 0),
    )
    q0 = jnp.maximum((start - page_offset + page_stride - 1) // page_stride, 0)

    def body(carry, j):
        m, l, acc = carry
        blk = page_offset + (q0 + j) * page_stride               # [B]
        entry = jnp.take_along_axis(
            block_tables, jnp.minimum(blk, max_blocks - 1)[:, None], axis=1
        )[:, 0]
        slots = entry[:, None] * block_size + jnp.arange(block_size)
        idx, ok = slot_fn(k_cache, slots)
        k = k_cache[idx].astype(jnp.float32)  # [B, bs, kvH, D]
        v = v_cache[idx].astype(jnp.float32)
        if k_scales is not None:
            k = _dequant_rows(k, entry, k_scales)
            v = _dequant_rows(v, entry, v_scales)
        scores = jnp.einsum("bkgd,bskd->bkgs", qr, k)  # [B, kvH, G, bs]
        # Per-lane positions (lanes start at different pages). A clamped
        # over-the-end blk gives key_pos >= ctx, so it is masked.
        key_pos = blk[:, None] * block_size + jnp.arange(block_size)
        mask = (key_pos < context_lens[:, None]) & ok  # [B, bs]
        if window:
            mask = mask & (key_pos >= context_lens[:, None] - window)
        scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)

        m_new = jnp.maximum(m, scores.max(axis=-1))
        correction = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new[..., None])
        p = jnp.where(mask[:, None, None, :], p, 0.0)
        l_new = l * correction + p.sum(axis=-1)
        acc_new = acc * correction[..., None] + jnp.einsum("bkgs,bskd->bkgd", p, v)
        return (m_new, l_new, acc_new), None

    init = (
        jnp.full((B, kvH, G), NEG_INF, jnp.float32),
        jnp.zeros((B, kvH, G), jnp.float32),
        jnp.zeros((B, kvH, G, D), jnp.float32),
    )
    (m, l, acc), _ = jax.lax.scan(body, init, jnp.arange(nsteps))
    return m, l, acc


def _own_all(cache, slots):
    """Identity slot hook: single/replicated cache owns every slot."""
    return slots, jnp.ones(slots.shape, bool)


def paged_prefill_attention(
    q: jnp.ndarray,           # [T, n_heads, head_dim] — new tokens' queries
    k_cache: jnp.ndarray,     # [num_slots, n_kv_heads, head_dim]
    v_cache: jnp.ndarray,
    block_table: jnp.ndarray, # [max_blocks] int32
    q_start: jnp.ndarray,     # scalar: global position of q[0] (prefix length)
    total_len: jnp.ndarray,   # scalar: prefix + new tokens (real, unpadded)
    block_size: int,
    window: int = 0,          # sliding-window size (0 = full causal)
) -> jnp.ndarray:
    """Causal attention of new tokens over (cached prefix + themselves).

    Assumes the new tokens' K/V were already scattered into the cache, so
    every key this needs is reachable through `block_table`. Supports
    prefix-cache hits natively: q_start > 0 attends to blocks computed by an
    earlier request (or a remote prefill worker).
    """
    T, H, D = q.shape
    m, l, acc = _prefill_partials(
        q, k_cache, v_cache, block_table, q_start, total_len, block_size,
        _own_all, window,
    )
    return _safe_div(acc, l).reshape(T, H, D).astype(q.dtype)


def paged_decode_attention(
    q: jnp.ndarray,             # [B, n_heads, head_dim]
    k_cache: jnp.ndarray,       # [num_slots, n_kv_heads, head_dim]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [B, max_blocks] int32
    context_lens: jnp.ndarray,  # [B] int32 — includes the current token
    block_size: int,
    window: int = 0,            # sliding-window size (0 = full causal)
    k_scales: jnp.ndarray | None = None,  # [num_blocks, kvH] (int8 cache)
    v_scales: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """One-token-per-sequence attention over each sequence's paged KV.

    Inactive batch slots (context_len == 0) return zeros. With
    ``k_scales``/``v_scales`` the cache holds int8 blocks and each
    gathered page dequantizes by its per-(block, head) scale — the
    quantized-KV oracle path (docs/architecture/kv_quant.md).
    """
    B, H, D = q.shape
    m, l, acc = _decode_partials(
        q, k_cache, v_cache, block_tables, context_lens, block_size,
        _own_all, window, k_scales=k_scales, v_scales=v_scales,
    )
    return _safe_div(acc, l).reshape(B, H, D).astype(q.dtype)


def ragged_paged_attention(
    q: jnp.ndarray,             # [T, H, D] — flat mixed prefill+decode batch
    k_cache: jnp.ndarray,       # [num_slots, n_kv_heads, head_dim]
    v_cache: jnp.ndarray,
    block_tables: jnp.ndarray,  # [S, max_blocks] int32 — per-sequence rows
    token_seq: jnp.ndarray,     # [T] int32 — owning sequence row per token
    token_pos: jnp.ndarray,     # [T] int32 — global position (-1 = padding)
    block_size: int,
    window: int = 0,
    k_scales: jnp.ndarray | None = None,  # [num_blocks, kvH] (int8 cache)
    v_scales: jnp.ndarray | None = None,
) -> jnp.ndarray:
    """XLA twin of the ragged unified kernel (ops/pallas/
    ragged_attention.py) — identical semantics, jnp formulation, and the
    tier-1 oracle the kernel is tested against. ``k_scales``/``v_scales``
    enable the int8-KV path: pages dequantize by per-(block, head) scale
    with the SAME arithmetic the kernel performs in-register, so parity
    stays exact-contract.

    Every row is one token of SOME sequence: a decode lane contributes one
    row, a chunked-prefill quantum its chunk's rows. Causality makes each
    token's visible context exactly ``token_pos + 1`` keys of its own
    sequence, so the whole mixed batch reduces to batched decode attention
    with per-token block tables — one lax.scan over pages, no per-phase
    program. Padding rows carry ``token_pos = -1`` (context 0) and return
    zeros."""
    tables = jnp.take(
        block_tables,
        jnp.clip(token_seq, 0, block_tables.shape[0] - 1),
        axis=0,
    )  # [T, max_blocks]
    ctx = jnp.maximum(token_pos + 1, 0)
    return paged_decode_attention(
        q, k_cache, v_cache, tables, ctx, block_size, window,
        k_scales=k_scales, v_scales=v_scales,
    )


def ragged_attention(
    q, k_cache, v_cache, block_tables, token_seq, token_pos, q_start,
    q_len, kv_len, row_start, block_size: int, window: int = 0,
    k_scales=None, v_scales=None,
):
    """Default (single-chip, env-driven) dispatch for the unified step."""
    return _default_dispatch(k_cache, block_size).ragged(
        q, k_cache, v_cache, block_tables, token_seq, token_pos, q_start,
        q_len, kv_len, row_start, block_size, window,
        k_scales=k_scales, v_scales=v_scales,
    )


def full_causal_attention(
    q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, window: int = 0
) -> jnp.ndarray:
    """Plain causal attention [T, H, D] x [T, kvH, D] — the no-cache
    reference path used to validate the paged implementations."""
    T, H, D = q.shape
    kvH = k.shape[1]
    G = H // kvH
    scale = 1.0 / (D**0.5)
    qr = (q.astype(jnp.float32) * scale).reshape(T, kvH, G, D)
    scores = jnp.einsum("tkgd,skd->tkgs", qr, k.astype(jnp.float32))
    mask = jnp.arange(T)[None, :] <= jnp.arange(T)[:, None]  # [Tq, Tk]
    if window:
        mask = mask & (
            jnp.arange(T)[None, :] > jnp.arange(T)[:, None] - window
        )
    scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("tkgs,skd->tkgd", p, v.astype(jnp.float32))
    return out.reshape(T, H, D).astype(q.dtype)


# ---------------------------------------------------------------------------
# sp-sharded cache: the paged KV SLOT axis sharded over the `sp` mesh axis,
# so total KV CAPACITY is sp x one device's arrays — the beyond-chip
# long-context mode (SURVEY §5; VERDICT r03 #6). With ``num_shards`` set,
# each shard runs a STRIDED scan over only the logical pages the striped
# allocator (engine/kv_cache.py BlockAllocator num_shards) placed on it —
# attention FLOPs and memory both partition sp-ways. Partials then merge
# with a pmax/psum logsumexp combine. ``num_shards=1`` keeps the legacy
# full-scan-with-ownership-mask mode (any block layout, sp-fold compute).
# Communication is O(query) per call, never O(cache). Composes with tp:
# heads shard over tp, slots over sp (AttnDispatch routes the specs).
# ---------------------------------------------------------------------------


def _sp_merge(acc, m, l, axis: str):
    """Cross-shard online-softmax merge: [., kvH, G(, D)] partials →
    replicated combined output."""
    m_g = jax.lax.pmax(m, axis)
    w = jnp.exp(m - m_g)
    l_g = jax.lax.psum(l * w, axis)
    acc_g = jax.lax.psum(acc * w[..., None], axis)
    return acc_g, l_g


def _local_slot_fn(axis: str):
    """Slot hook for a slot-sharded cache: translate GLOBAL slot ids to
    this shard's local range; non-owned slots are masked."""

    def slot_fn(cache, slots):
        per = cache.shape[0]
        r = jax.lax.axis_index(axis)
        local = slots - r * per
        ok = (local >= 0) & (local < per)
        return jnp.clip(local, 0, per - 1), ok

    return slot_fn


def paged_decode_attention_sp(
    q, k_cache, v_cache, block_tables, context_lens, block_size: int,
    axis: str = "sp", window: int = 0, num_shards: int = 1,
):
    """Per-shard decode body (inside shard_map over `axis`; cache in_spec
    P(axis, head_axis, None), q/out head-sharded over tp, everything else
    replicated). ``num_shards > 1`` enables the striped scan (allocator
    must stripe logical block i onto shard i % num_shards)."""
    B, H, D = q.shape
    off = jax.lax.axis_index(axis) if num_shards > 1 else 0
    m, l, acc = _decode_partials(
        q, k_cache, v_cache, block_tables, context_lens, block_size,
        _local_slot_fn(axis), window, page_offset=off,
        page_stride=num_shards,
    )
    acc_g, l_g = _sp_merge(acc, m, l, axis)
    return _safe_div(acc_g, l_g).reshape(B, H, D).astype(q.dtype)


def paged_prefill_attention_sp(
    q, k_cache, v_cache, block_tables, q_start, total_len, block_size: int,
    axis: str = "sp", window: int = 0, num_shards: int = 1,
):
    """Per-shard batched-prefill body (q [N, T, H, D]); same contract as
    AttnDispatch.prefill but over a slot-sharded cache."""
    N, T, H, D = q.shape
    off = jax.lax.axis_index(axis) if num_shards > 1 else 0
    m, l, acc = jax.vmap(
        lambda qq, bt, ps, tl: _prefill_partials(
            qq, k_cache, v_cache, bt, ps, tl, block_size,
            _local_slot_fn(axis), window, page_offset=off,
            page_stride=num_shards,
        )
    )(q, block_tables, q_start, total_len)
    acc_g, l_g = _sp_merge(acc, m, l, axis)
    return _safe_div(acc_g, l_g).reshape(N, T, H, D).astype(q.dtype)

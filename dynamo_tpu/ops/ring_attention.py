"""Ring attention: causal attention with K/V sharded over the `sp` axis.

The long-context primitive SURVEY §5 requires natively (the reference
delegates long context to its backend engines): sequence-parallel prefill
in ops/attention.py shards only the QUERY tiles and replicates KV, so its
memory ceiling is one chip's KV. Ring attention shards K/V too — each sp
shard holds one sequence block of q, k, v; K/V blocks rotate around the
ring via `lax.ppermute` while every shard folds them into a flash-style
online softmax (running max + normalizer). Per-chip memory is O(T/n) and
the ppermute rides the ICI ring concurrently with compute.

Causality falls out of global position masking (q_pos >= k_pos), so the
same code handles the diagonal block (intra-shard causal), fully-visible
earlier blocks, and fully-masked later blocks.

Use under shard_map with q/k/v sharded P("sp", ...) — see
`ring_attention_sharded` for the canonical binding, and
tests/test_parallel.py for the oracle equivalence proof.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def ring_attention(
    q: jnp.ndarray,  # [Tl, H, D] — this shard's query block
    k: jnp.ndarray,  # [Tl, kvH, D] — this shard's key block
    v: jnp.ndarray,  # [Tl, kvH, D]
    axis_name: str = "sp",
) -> jnp.ndarray:
    """Per-shard body (call inside shard_map over `axis_name`)."""
    n = jax.lax.psum(1, axis_name)
    r = jax.lax.axis_index(axis_name)
    Tl, H, D = q.shape
    kvH = k.shape[1]
    G = H // kvH
    scale = 1.0 / (D**0.5)

    q32 = (q.astype(jnp.float32) * scale).reshape(Tl, kvH, G, D)
    q_pos = r * Tl + jnp.arange(Tl)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def fold(acc, k_cur, v_cur, src):
        o, m, l = acc
        # Scores of our q block against the k/v block currently resident
        # (originating from shard `src`), with global causal masking.
        k_pos = src * Tl + jnp.arange(Tl)
        s = jnp.einsum(
            "tkgd,skd->tkgs", q32, k_cur.astype(jnp.float32)
        )
        mask = q_pos[:, None] >= k_pos[None, :]  # [Tq, Tk]
        s = jnp.where(mask[:, None, None, :], s, NEG_INF)

        # Online softmax fold (flash-attention update). The first fold is
        # always the resident diagonal block, so m is finite before any
        # fully-masked future block arrives.
        m_new = jnp.maximum(m, s.max(axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[..., None])
        l_new = l * alpha + p.sum(axis=-1)
        o_new = o * alpha[..., None] + jnp.einsum(
            "tkgs,skd->tkgd", p, v_cur.astype(jnp.float32)
        )
        return (o_new, m_new, l_new)

    def step(carry, i):
        acc, k_cur, v_cur, src = carry
        # Rotate first, then fold: the resident block was folded before the
        # scan, so only n-1 rotations happen and none is wasted.
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        src = (src - 1) % n
        return (fold(acc, k_cur, v_cur, src), k_cur, v_cur, src), None

    o0 = jnp.zeros((Tl, kvH, G, D), jnp.float32)
    m0 = jnp.full((Tl, kvH, G), NEG_INF, jnp.float32)
    l0 = jnp.zeros((Tl, kvH, G), jnp.float32)
    acc = fold((o0, m0, l0), k, v, r)
    (acc, _, _, _), _ = jax.lax.scan(
        step, (acc, k, v, r), jnp.arange(n - 1)
    )
    o, m, l = acc
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.reshape(Tl, H, D).astype(q.dtype)


def ring_attention_sharded(mesh, q, k, v, axis_name: str = "sp"):
    """Canonical binding: q/k/v [T, H, D] global arrays, sequence sharded
    over `axis_name`; returns [T, H, D] with the same sharding."""
    from jax.sharding import PartitionSpec as P

    from dynamo_tpu.utils.jax_compat import shard_map

    spec = P(axis_name, None, None)
    fn = shard_map(
        partial(ring_attention, axis_name=axis_name),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)

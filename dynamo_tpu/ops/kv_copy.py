"""Device-side KV block gather/scatter — the G1 edge of the offload path.

The TPU analogue of the reference's CUDA block-copy machinery (reference:
lib/llm/src/block_manager/block/transfer/cuda.rs + src/kernels/
block_copy.cu): move one block's KV for all layers between the paged HBM
cache and a host buffer. Jitted slice/update (XLA fuses the per-layer
slices into one D2H/H2D transfer program); called only from the engine
thread, serialized with steps, so the non-donated gather never races a
donated step buffer.

Layout contract: host block = [num_layers, 2(k/v), block_size, kv_heads,
head_dim], matching KvLayoutConfig.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("block_size",), donate_argnums=())
def _gather(kv_caches, start: jnp.ndarray, *, block_size: int):
    outs = []
    for k, v in kv_caches:
        outs.append(
            jnp.stack(
                [
                    jax.lax.dynamic_slice_in_dim(k, start, block_size, 0),
                    jax.lax.dynamic_slice_in_dim(v, start, block_size, 0),
                ]
            )
        )
    return jnp.stack(outs)  # [L, 2, bs, H, D]


@partial(jax.jit, donate_argnums=(0,))
def _scatter(kv_caches, start: jnp.ndarray, data: jnp.ndarray):
    new = []
    for i, (k, v) in enumerate(kv_caches):
        new.append(
            (
                jax.lax.dynamic_update_slice_in_dim(
                    k, data[i, 0].astype(k.dtype), start, 0
                ),
                jax.lax.dynamic_update_slice_in_dim(
                    v, data[i, 1].astype(v.dtype), start, 0
                ),
            )
        )
    return new


def gather_block(kv_caches, block_idx: int, block_size: int) -> np.ndarray:
    """Read one block's KV to host: [L, 2, bs, H, D] numpy (bf16 via
    ml_dtypes)."""
    return np.asarray(gather_block_device(kv_caches, block_idx, block_size))


def gather_block_device(kv_caches, block_idx: int, block_size: int) -> jax.Array:
    """Read one block's KV as a DEVICE-resident array [L, 2, bs, H, D] —
    the HBM→HBM transfer path's snapshot (no host sync; scatter_block
    consumes it directly, so a same-process prefill→decode block move
    never touches host memory)."""
    return _gather(
        kv_caches, jnp.int32(block_idx * block_size), block_size=block_size
    )


def scatter_block(kv_caches, block_idx: int, block_size: int, data: np.ndarray):
    """Write one block's KV from host; returns the new cache list (donated
    update — caller must replace its reference)."""
    return _scatter(kv_caches, jnp.int32(block_idx * block_size), jnp.asarray(data))


# -- batched block IO ---------------------------------------------------------
# One device program moves N blocks at once: through a tunneled chip each
# dispatch costs a host→device RTT, so onboarding a 128-block prefix with
# per-block calls pays 128 RTTs — more than recomputing the prefill. The
# batched forms pad N up to a power-of-two bucket (bounded compile count)
# and aim padding at block 0, the engine's trash block (kv_cache.py:13).


@partial(jax.jit, static_argnames=("block_size",), donate_argnums=())
def _gather_many(kv_caches, starts, *, block_size: int):
    idx = starts[:, None] + jnp.arange(block_size)[None, :]  # [N, bs]
    outs = []
    for k, v in kv_caches:
        outs.append(jnp.stack([k[idx], v[idx]], axis=1))  # [N, 2, bs, H, D]
    return jnp.stack(outs, axis=1)  # [N, L, 2, bs, H, D]


@partial(jax.jit, donate_argnums=(0,))
def _scatter_many(kv_caches, starts, data):
    bs = data.shape[3]
    idx = (starts[:, None] + jnp.arange(bs)[None, :]).reshape(-1)  # [N*bs]
    new = []
    for i, (k, v) in enumerate(kv_caches):
        kd = data[:, i, 0].astype(k.dtype).reshape(-1, *k.shape[1:])
        vd = data[:, i, 1].astype(v.dtype).reshape(-1, *v.shape[1:])
        new.append((k.at[idx].set(kd), v.at[idx].set(vd)))
    return new


def _bucket(n: int) -> int:
    return 1 << (n - 1).bit_length()


def gather_blocks(kv_caches, block_idxs, block_size: int) -> np.ndarray:
    """Read N blocks' KV to host in ONE device call: [N, L, 2, bs, H, D].
    Padding reads the trash block and is dropped before return."""
    return np.asarray(gather_blocks_device(kv_caches, block_idxs, block_size))


def gather_blocks_device(kv_caches, block_idxs, block_size: int) -> jax.Array:
    """Device-resident batched snapshot [N, L, 2, bs, H, D] — one dispatch,
    NO host sync. The copy is ordered before any later cache rewrite, so
    the caller may materialize it lazily (e.g. on the KVBM pump thread)."""
    n = len(block_idxs)
    starts = np.zeros(_bucket(n), np.int32)
    starts[:n] = np.asarray(block_idxs, np.int32) * block_size
    out = _gather_many(kv_caches, jnp.asarray(starts), block_size=block_size)
    return out[:n] if _bucket(n) != n else out


# -- per-block KV scale sidecars (kv_quant int8; kv_quant.md) ---------------
# The scale state is [L, 2, num_blocks, kvH] float32 on device; block IO
# moves [N, L, 2, kvH] rows with the same power-of-two bucketing (padding
# aims at trash block 0, whose scale is never read as real KV).


@jax.jit
def _gather_scales(kv_scales, idxs):
    return jnp.transpose(kv_scales[:, :, idxs], (2, 0, 1, 3))  # [N, L, 2, H]


@partial(jax.jit, donate_argnums=(0,))
def _scatter_scales(kv_scales, idxs, rows):
    return kv_scales.at[:, :, idxs].set(jnp.transpose(rows, (1, 2, 0, 3)))


def gather_scales_device(kv_scales, block_idxs) -> jax.Array:
    """Device-resident [N, L, 2, kvH] scale rows for N blocks (one
    dispatch, no host sync — pairs with gather_blocks_device)."""
    n = len(block_idxs)
    idxs = np.zeros(_bucket(n), np.int32)
    idxs[:n] = np.asarray(block_idxs, np.int32)
    out = _gather_scales(kv_scales, jnp.asarray(idxs))
    return out[:n] if _bucket(n) != n else out


def gather_scales(kv_scales, block_idxs) -> np.ndarray:
    return np.asarray(gather_scales_device(kv_scales, block_idxs))


def scatter_scales(kv_scales, block_idxs, rows):
    """Write N blocks' scale rows ([N, L, 2, kvH], host or device) in one
    donated program; returns the new scale array."""
    n = len(block_idxs)
    b = _bucket(n)
    idxs = np.zeros(b, np.int32)
    idxs[:n] = np.asarray(block_idxs, np.int32)
    if isinstance(rows, jax.Array):
        arr = rows
        if b != n:
            arr = jnp.concatenate(
                [arr, jnp.zeros((b - n, *arr.shape[1:]), arr.dtype)], axis=0
            )
    else:
        arr = np.asarray(rows, np.float32)
        if b != n:
            arr = np.concatenate(
                [arr, np.zeros((b - n, *arr.shape[1:]), arr.dtype)], axis=0
            )
    return _scatter_scales(
        kv_scales, jnp.asarray(idxs), jnp.asarray(arr, jnp.float32)
    )


def scatter_blocks(kv_caches, block_idxs, block_size: int, data):
    """Write N blocks' KV from host in ONE device call (donated update —
    caller must replace its cache reference). `data` is [N, L, 2, bs, H, D]
    (any same-width dtype view; cast happens on device). Padding writes
    zeros into trash block 0, which is never read as real KV."""
    n = len(block_idxs)
    b = _bucket(n)
    starts = np.zeros(b, np.int32)
    starts[:n] = np.asarray(block_idxs, np.int32) * block_size
    if isinstance(data, jax.Array):
        arr = data  # device-resident: pad on device, never touch host
        if b != n:
            arr = jnp.concatenate(
                [arr, jnp.zeros((b - n, *arr.shape[1:]), arr.dtype)], axis=0
            )
    else:
        arr = np.asarray(data)
        if b != n:
            pad = np.zeros((b - n, *arr.shape[1:]), arr.dtype)
            arr = np.concatenate([arr, pad], axis=0)
    return _scatter_many(kv_caches, jnp.asarray(starts), jnp.asarray(arr))

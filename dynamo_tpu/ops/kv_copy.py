"""Device-side KV block gather/scatter — the G1 edge of the offload path.

The TPU analogue of the reference's CUDA block-copy machinery (reference:
lib/llm/src/block_manager/block/transfer/cuda.rs + src/kernels/
block_copy.cu): move one block's KV for all layers between the paged HBM
cache and a host buffer. Jitted slice/update (XLA fuses the per-layer
slices into one D2H/H2D transfer program); called only from the engine
thread, serialized with steps, so the non-donated gather never races a
donated step buffer.

Layout contract: host block = [num_layers, 2(k/v), block_size, kv_heads,
head_dim], matching KvLayoutConfig.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("block_size",), donate_argnums=())
def _gather(kv_caches, start: jnp.ndarray, *, block_size: int):
    outs = []
    for k, v in kv_caches:
        outs.append(
            jnp.stack(
                [
                    jax.lax.dynamic_slice_in_dim(k, start, block_size, 0),
                    jax.lax.dynamic_slice_in_dim(v, start, block_size, 0),
                ]
            )
        )
    return jnp.stack(outs)  # [L, 2, bs, H, D]


@partial(jax.jit, donate_argnums=(0,))
def _scatter(kv_caches, start: jnp.ndarray, data: jnp.ndarray):
    new = []
    for i, (k, v) in enumerate(kv_caches):
        new.append(
            (
                jax.lax.dynamic_update_slice_in_dim(
                    k, data[i, 0].astype(k.dtype), start, 0
                ),
                jax.lax.dynamic_update_slice_in_dim(
                    v, data[i, 1].astype(v.dtype), start, 0
                ),
            )
        )
    return new


def gather_block(kv_caches, block_idx: int, block_size: int) -> np.ndarray:
    """Read one block's KV to host: [L, 2, bs, H, D] numpy (bf16 via
    ml_dtypes)."""
    return np.asarray(gather_block_device(kv_caches, block_idx, block_size))


def gather_block_device(kv_caches, block_idx: int, block_size: int) -> jax.Array:
    """Read one block's KV as a DEVICE-resident array [L, 2, bs, H, D] —
    the HBM→HBM transfer path's snapshot (no host sync; scatter_block
    consumes it directly, so a same-process prefill→decode block move
    never touches host memory)."""
    return _gather(
        kv_caches, jnp.int32(block_idx * block_size), block_size=block_size
    )


def scatter_block(kv_caches, block_idx: int, block_size: int, data: np.ndarray):
    """Write one block's KV from host; returns the new cache list (donated
    update — caller must replace its reference)."""
    return _scatter(kv_caches, jnp.int32(block_idx * block_size), jnp.asarray(data))

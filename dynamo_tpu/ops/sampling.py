"""Token sampling inside the jitted step: greedy / temperature / top-k /
top-p, fully vectorized per batch slot.

Dynamic per-sequence k and p are handled against a static candidate cap
(``MAX_TOP_K``): we take the top-64 logits once (MXU/VPU friendly), then mask
per-sequence within that window — no data-dependent shapes under jit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

MAX_TOP_K = 64
# Static cap on top-logprob alternatives returned per token (the OpenAI
# surface rejects top_logprobs above this — a static shape under jit).
# Defined in the (jax-free) protocol layer so the HTTP front end can
# validate without importing jax.
from dynamo_tpu.llm.protocols.common import MAX_LOGPROBS  # noqa: E402


def lane_keys(
    key: jax.Array,             # global PRNG key (engine step stream)
    seed: jnp.ndarray,          # [B] int64/int32; < 0 means unseeded
    sample_pos: jnp.ndarray,    # [B] int32 — index of the token being sampled
) -> jax.Array:
    """Per-lane sampling keys [B].

    A seeded lane's key depends ONLY on (seed, token index) — so a request
    with `seed` set reproduces its samples regardless of what other traffic
    it was batched with or which engine step picked it up (the determinism
    contract of the OpenAI `seed` parameter; reference:
    lib/llm/src/protocols/common.rs:248 SamplingOptions.seed). Unseeded
    lanes draw from the engine's global stream, decorrelated per lane.
    """
    B = seed.shape[0]

    def one(lane, s, p):
        seeded = jax.random.fold_in(
            jax.random.PRNGKey(jnp.maximum(s, 0).astype(jnp.uint32)), p
        )
        unseeded = jax.random.fold_in(key, lane)
        return jnp.where(s >= 0, seeded, unseeded)

    return jax.vmap(one)(jnp.arange(B), seed, sample_pos)


def apply_penalties(
    logits: jnp.ndarray,        # [B, V]
    counts: jnp.ndarray,        # [B, V] int — output-token occurrence counts
    frequency_penalty: jnp.ndarray,  # [B] float32
    presence_penalty: jnp.ndarray,   # [B] float32
) -> jnp.ndarray:
    """OpenAI-style penalties over the generated-token counts:
    ``logit[t] -= freq * count[t] + pres * (count[t] > 0)``."""
    c = counts.astype(logits.dtype)
    return (
        logits
        - frequency_penalty[:, None] * c
        - presence_penalty[:, None] * (c > 0)
    )


def token_logprobs(
    logits: jnp.ndarray,        # [B, V]
    chosen: jnp.ndarray,        # [B] int32 — the sampled token ids
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(chosen_logprob [B], top_ids [B, MAX_LOGPROBS], top_logprobs
    [B, MAX_LOGPROBS]) — log-softmax of the distribution actually sampled
    from (post-penalty), at temperature-1 scale, like the reference's
    engines report."""
    lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    chosen_lp = jnp.take_along_axis(lp, chosen[:, None].astype(jnp.int32), axis=1)[:, 0]
    top_lps, top_ids = jax.lax.top_k(lp, MAX_LOGPROBS)
    return chosen_lp, top_ids.astype(jnp.int32), top_lps


def sample_tokens(
    logits: jnp.ndarray,        # [B, V] float32
    key: jax.Array,             # PRNG key
    temperature: jnp.ndarray,   # [B] float32; <=0 means greedy
    top_k: jnp.ndarray,         # [B] int32; 0 means disabled
    top_p: jnp.ndarray,         # [B] float32; >=1 means disabled
    seed: jnp.ndarray | None = None,        # [B]; < 0 means unseeded
    sample_pos: jnp.ndarray | None = None,  # [B] token index being sampled
) -> jnp.ndarray:
    """Returns sampled token ids [B] int32. With ``seed``/``sample_pos``,
    seeded lanes sample from a per-lane deterministic stream (lane_keys).
    All-greedy batches skip the top-k window at runtime (see below)."""
    B, V = logits.shape
    greedy_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if seed is not None and sample_pos is None:
        # Zero-filling would reuse ONE key for every step of a seeded
        # lane (degenerate repeated draws) — refuse instead.
        raise ValueError("sample_pos is required when seed is given")

    def sampled(_):
        cap = min(MAX_TOP_K, V)
        top_vals, top_idx = jax.lax.top_k(logits, cap)  # [B, cap] sorted desc

        temp = jnp.maximum(temperature, 1e-6)[:, None]
        scaled = top_vals / temp

        # top-k mask within the candidate window
        k_eff = jnp.where(top_k <= 0, cap, jnp.minimum(top_k, cap))[:, None]
        rank = jnp.arange(cap)[None, :]
        mask = rank < k_eff

        # top-p (nucleus) mask over the sorted candidates
        probs = jax.nn.softmax(jnp.where(mask, scaled, -1e30), axis=-1)
        cumulative = jnp.cumsum(probs, axis=-1)
        p_eff = jnp.where(top_p <= 0, 1.0, jnp.minimum(top_p, 1.0))[:, None]
        # keep tokens whose cumulative mass *before* them is < p (always
        # keep #1)
        before = cumulative - probs
        mask2 = mask & (before < p_eff)

        masked = jnp.where(mask2, scaled, -1e30)
        if seed is None:
            sampled_pos = jax.random.categorical(key, masked, axis=-1)  # [B]
        else:
            keys = lane_keys(key, seed, sample_pos)
            sampled_pos = jax.vmap(
                lambda k, row: jax.random.categorical(k, row)
            )(keys, masked)
        return jnp.take_along_axis(
            top_idx, sampled_pos[:, None], axis=-1
        )[:, 0].astype(jnp.int32)

    # All-greedy batches (the common serving case) skip the whole top-k
    # window at RUNTIME — a real XLA conditional, so no extra compiles.
    sampled_ids = jax.lax.cond(
        jnp.all(temperature <= 0.0), lambda _: greedy_ids, sampled, None
    )
    return jnp.where(temperature <= 0.0, greedy_ids, sampled_ids)

"""Token sampling inside the jitted step: greedy / temperature / top-k /
top-p, fully vectorized per batch slot.

Dynamic per-sequence k and p are handled against a static candidate cap
(``MAX_TOP_K``): we take the top-64 logits once (MXU/VPU friendly), then mask
per-sequence within that window — no data-dependent shapes under jit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

MAX_TOP_K = 64


def sample_tokens(
    logits: jnp.ndarray,        # [B, V] float32
    key: jax.Array,             # PRNG key
    temperature: jnp.ndarray,   # [B] float32; <=0 means greedy
    top_k: jnp.ndarray,         # [B] int32; 0 means disabled
    top_p: jnp.ndarray,         # [B] float32; >=1 means disabled
) -> jnp.ndarray:
    """Returns sampled token ids [B] int32."""
    B, V = logits.shape
    greedy_ids = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    cap = min(MAX_TOP_K, V)
    top_vals, top_idx = jax.lax.top_k(logits, cap)  # [B, cap] sorted desc

    temp = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = top_vals / temp

    # top-k mask within the candidate window
    k_eff = jnp.where(top_k <= 0, cap, jnp.minimum(top_k, cap))[:, None]
    rank = jnp.arange(cap)[None, :]
    mask = rank < k_eff

    # top-p (nucleus) mask over the sorted candidates
    probs = jax.nn.softmax(jnp.where(mask, scaled, -1e30), axis=-1)
    cumulative = jnp.cumsum(probs, axis=-1)
    p_eff = jnp.where(top_p <= 0, 1.0, jnp.minimum(top_p, 1.0))[:, None]
    # keep tokens whose cumulative mass *before* them is < p (always keep #1)
    before = cumulative - probs
    mask = mask & (before < p_eff)

    masked = jnp.where(mask, scaled, -1e30)
    sampled_pos = jax.random.categorical(key, masked, axis=-1)  # [B]
    sampled_ids = jnp.take_along_axis(
        top_idx, sampled_pos[:, None], axis=-1
    )[:, 0].astype(jnp.int32)

    return jnp.where(temperature <= 0.0, greedy_ids, sampled_ids)

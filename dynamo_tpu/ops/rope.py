"""Rotary position embeddings (RoPE), Llama-style half-rotation layout.

Computed on the fly from positions — no precomputed cos/sin tables to ship
around, and XLA folds the trig into the attention fusion. Llama-3.1+
long-context checkpoints apply frequency-dependent scaling
(`rope_type: llama3`): low-frequency components are stretched by
``factor`` while high-frequency ones stay put, with a smooth ramp between
the two wavelength bands — without it, a 3.1/3.2 checkpoint decodes
garbage past the original 8k positions.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax.numpy as jnp


@dataclass(frozen=True)
class RopeScaling:
    """HF `rope_scaling` block: `llama3` frequency bands or `yarn`
    (DeepSeek-V2/V3/R1 long-context: NTK-by-parts interpolation with a
    log-scaled attention-temperature correction, `mscale`)."""

    kind: str = "llama3"
    factor: float = 8.0
    original_max_position: int = 8192
    # llama3 band parameters
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    # yarn parameters
    beta_fast: float = 32.0
    beta_slow: float = 1.0
    mscale: float = 1.0
    mscale_all_dim: float = 0.0

    @staticmethod
    def from_hf(d: dict | None) -> "RopeScaling | None":
        if not d:
            return None
        kind = d.get("rope_type", d.get("type", "llama3"))
        if kind == "default":
            return None  # HF semantics: explicitly no scaling
        if kind == "llama3":
            return RopeScaling(
                kind="llama3",
                factor=float(d.get("factor", 8.0)),
                low_freq_factor=float(d.get("low_freq_factor", 1.0)),
                high_freq_factor=float(d.get("high_freq_factor", 4.0)),
                original_max_position=int(
                    d.get("original_max_position_embeddings", 8192)
                ),
            )
        if kind == "linear":
            return RopeScaling(kind="linear", factor=float(d.get("factor", 1.0)))
        if kind == "yarn":
            return RopeScaling(
                kind="yarn",
                factor=float(d.get("factor", 1.0)),
                original_max_position=int(
                    d.get("original_max_position_embeddings", 4096)
                ),
                beta_fast=float(d.get("beta_fast", 32.0)),
                beta_slow=float(d.get("beta_slow", 1.0)),
                mscale=float(d.get("mscale", 1.0)),
                mscale_all_dim=float(d.get("mscale_all_dim", 0.0)),
            )
        raise ValueError(f"unsupported rope_scaling {d!r}")

    def attn_mscale(self) -> float:
        """Score-scale multiplier DeepSeek folds into the softmax scale
        under yarn (applied as a q multiplier in models/llama.py
        _qkv_mla): yarn_get_mscale(factor, mscale_all_dim)."""
        if self.kind != "yarn":
            return 1.0
        return _yarn_mscale(self.factor, self.mscale_all_dim)

    def embed_mscale(self) -> float:
        """cos/sin magnitude correction baked into the rotary embedding
        (HF DeepseekV2YarnRotaryEmbedding: mscale / mscale_all_dim ratio —
        1.0 on shipped DeepSeek configs where the two are equal)."""
        if self.kind != "yarn":
            return 1.0
        return _yarn_mscale(self.factor, self.mscale) / _yarn_mscale(
            self.factor, self.mscale_all_dim
        )


def _yarn_mscale(scale: float, mscale: float) -> float:
    if scale <= 1.0 or mscale <= 0.0:
        return 1.0
    return 0.1 * mscale * math.log(scale) + 1.0


def _scaled_freqs(freqs: jnp.ndarray, s: RopeScaling) -> jnp.ndarray:
    if s.kind == "yarn":
        return _yarn_freqs(freqs, s)
    if s.kind == "linear":
        # Plain position interpolation (Gemma-3 global layers: factor 8).
        return freqs / s.factor
    # Frequency-dependent stretch (the Llama-3.1 formula): wavelengths
    # shorter than the high-freq band keep their frequency, longer than the
    # low-freq band divide by `factor`, and the band between ramps smoothly.
    wavelen = 2.0 * math.pi / freqs
    low_wl = s.original_max_position / s.low_freq_factor
    high_wl = s.original_max_position / s.high_freq_factor
    smooth = (s.original_max_position / wavelen - s.low_freq_factor) / (
        s.high_freq_factor - s.low_freq_factor
    )
    mid = (1.0 - smooth) * freqs / s.factor + smooth * freqs
    return jnp.where(
        wavelen < high_wl, freqs, jnp.where(wavelen > low_wl, freqs / s.factor, mid)
    )


def _yarn_freqs(freqs: jnp.ndarray, s: RopeScaling) -> jnp.ndarray:
    """YaRN NTK-by-parts: high-frequency dims (below the beta_fast
    correction point) keep the original frequency (extrapolation),
    low-frequency dims (above beta_slow) interpolate by 1/factor, with a
    linear ramp between (the HF DeepseekV2YarnRotaryEmbedding recipe)."""
    half = freqs.shape[0]
    dim = 2 * half
    # theta recovered from the frequency ladder: freqs[i] = theta^(-i/half)
    # => log(theta) = -log(freqs[1]) * half ... derive via the ladder ratio.
    log_theta = -jnp.log(freqs[1]) * half if half > 1 else jnp.float32(0.0)

    def correction_dim(num_rotations):
        return (
            dim
            * jnp.log(s.original_max_position / (num_rotations * 2 * math.pi))
        ) / (2 * log_theta)

    low = jnp.floor(correction_dim(s.beta_fast))
    high = jnp.ceil(correction_dim(s.beta_slow))
    # HF yarn_find_correction_range clamps low/high to [0, dim-1]; only
    # `low` additionally needs the half-1 bound (it indexes the ramp
    # start). Clamping `high` to half-1 would steepen the interpolation
    # ramp whenever beta_slow's correction dim exceeds half (large
    # original_max_position / small base) and diverge from checkpoints.
    low = jnp.clip(low, 0, half - 1)
    high = jnp.clip(high, 0, dim - 1)
    ramp = jnp.clip(
        (jnp.arange(half, dtype=jnp.float32) - low)
        / jnp.maximum(high - low, 1e-3),
        0.0,
        1.0,
    )
    extrapolation_mask = 1.0 - ramp
    return freqs / s.factor * (1.0 - extrapolation_mask) + (
        freqs * extrapolation_mask
    )


def _angles(
    positions: jnp.ndarray,
    head_dim: int,
    theta: float,
    scaling: RopeScaling | None = None,
) -> tuple:
    """positions [...]: returns cos/sin of shape [..., head_dim//2]."""
    half = head_dim // 2
    freqs = jnp.exp(
        -jnp.log(theta) * (jnp.arange(half, dtype=jnp.float32) / half)
    )
    if scaling is not None:
        freqs = _scaled_freqs(freqs, scaling)
    ang = positions.astype(jnp.float32)[..., None] * freqs
    m = scaling.embed_mscale() if scaling is not None else 1.0
    return jnp.cos(ang) * m, jnp.sin(ang) * m


def apply_rope(
    x: jnp.ndarray,
    positions: jnp.ndarray,
    theta: float = 10000.0,
    scaling: RopeScaling | None = None,
) -> jnp.ndarray:
    """Rotate q or k. x: [..., n_heads, head_dim]; positions broadcastable to
    x.shape[:-2]."""
    head_dim = x.shape[-1]
    cos, sin = _angles(positions, head_dim, theta, scaling)
    cos = cos[..., None, :]  # broadcast over heads
    sin = sin[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)

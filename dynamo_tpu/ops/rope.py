"""Rotary position embeddings (RoPE), Llama-style half-rotation layout.

Computed on the fly from positions — no precomputed cos/sin tables to ship
around, and XLA folds the trig into the attention fusion.
"""

from __future__ import annotations

import jax.numpy as jnp


def _angles(positions: jnp.ndarray, head_dim: int, theta: float) -> tuple:
    """positions [...]: returns cos/sin of shape [..., head_dim//2]."""
    half = head_dim // 2
    freqs = jnp.exp(
        -jnp.log(theta) * (jnp.arange(half, dtype=jnp.float32) / half)
    )
    ang = positions.astype(jnp.float32)[..., None] * freqs
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(
    x: jnp.ndarray, positions: jnp.ndarray, theta: float = 10000.0
) -> jnp.ndarray:
    """Rotate q or k. x: [..., n_heads, head_dim]; positions broadcastable to
    x.shape[:-2]."""
    head_dim = x.shape[-1]
    cos, sin = _angles(positions, head_dim, theta)
    cos = cos[..., None, :]  # broadcast over heads
    sin = sin[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)

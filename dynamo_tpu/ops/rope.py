"""Rotary position embeddings (RoPE), Llama-style half-rotation layout.

Computed on the fly from positions — no precomputed cos/sin tables to ship
around, and XLA folds the trig into the attention fusion. Llama-3.1+
long-context checkpoints apply frequency-dependent scaling
(`rope_type: llama3`): low-frequency components are stretched by
``factor`` while high-frequency ones stay put, with a smooth ramp between
the two wavelength bands — without it, a 3.1/3.2 checkpoint decodes
garbage past the original 8k positions.
"""

from __future__ import annotations

import math
from dataclasses import dataclass

import jax.numpy as jnp


@dataclass(frozen=True)
class RopeScaling:
    """Llama-3.1 `rope_scaling` block (HF config.json)."""

    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position: int = 8192

    @staticmethod
    def from_hf(d: dict | None) -> "RopeScaling | None":
        if not d:
            return None
        kind = d.get("rope_type", d.get("type", "llama3"))
        if kind == "default":
            return None  # HF semantics: explicitly no scaling
        if kind != "llama3":
            raise ValueError(f"unsupported rope_scaling {d!r}")
        return RopeScaling(
            factor=float(d.get("factor", 8.0)),
            low_freq_factor=float(d.get("low_freq_factor", 1.0)),
            high_freq_factor=float(d.get("high_freq_factor", 4.0)),
            original_max_position=int(
                d.get("original_max_position_embeddings", 8192)
            ),
        )


def _scaled_freqs(freqs: jnp.ndarray, s: RopeScaling) -> jnp.ndarray:
    """Frequency-dependent stretch (the Llama-3.1 formula): wavelengths
    shorter than the high-freq band keep their frequency, longer than the
    low-freq band divide by `factor`, and the band between ramps smoothly."""
    wavelen = 2.0 * math.pi / freqs
    low_wl = s.original_max_position / s.low_freq_factor
    high_wl = s.original_max_position / s.high_freq_factor
    smooth = (s.original_max_position / wavelen - s.low_freq_factor) / (
        s.high_freq_factor - s.low_freq_factor
    )
    mid = (1.0 - smooth) * freqs / s.factor + smooth * freqs
    return jnp.where(
        wavelen < high_wl, freqs, jnp.where(wavelen > low_wl, freqs / s.factor, mid)
    )


def _angles(
    positions: jnp.ndarray,
    head_dim: int,
    theta: float,
    scaling: RopeScaling | None = None,
) -> tuple:
    """positions [...]: returns cos/sin of shape [..., head_dim//2]."""
    half = head_dim // 2
    freqs = jnp.exp(
        -jnp.log(theta) * (jnp.arange(half, dtype=jnp.float32) / half)
    )
    if scaling is not None:
        freqs = _scaled_freqs(freqs, scaling)
    ang = positions.astype(jnp.float32)[..., None] * freqs
    return jnp.cos(ang), jnp.sin(ang)


def apply_rope(
    x: jnp.ndarray,
    positions: jnp.ndarray,
    theta: float = 10000.0,
    scaling: RopeScaling | None = None,
) -> jnp.ndarray:
    """Rotate q or k. x: [..., n_heads, head_dim]; positions broadcastable to
    x.shape[:-2]."""
    head_dim = x.shape[-1]
    cos, sin = _angles(positions, head_dim, theta, scaling)
    cos = cos[..., None, :]  # broadcast over heads
    sin = sin[..., None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)

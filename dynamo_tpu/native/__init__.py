"""Native (C++) components, loaded via ctypes.

The reference keeps its data plane native (NIXL C++, CUDA kernels, Rust
runtime); here the bulk-transfer agent is C++ (native/transfer_agent) and
Python stays on the control plane only. Libraries build on demand with the
baked-in g++ (no pybind11 in the image — C ABI + ctypes).
"""

from dynamo_tpu.native.build import load_library

__all__ = ["load_library"]

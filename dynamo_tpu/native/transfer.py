"""ctypes bindings for the C++ transfer agent (native/transfer_agent).

`TransferServer` owns registered numpy arenas; remote peers write into them
with zero Python in the data path (the C++ thread memcpys straight into the
arena). `TransferClient` is the sender side. Completion notifications carry
opaque bytes (msgpack at our call sites) drained via `poll()`.
"""

from __future__ import annotations

import ctypes
import logging

import numpy as np

from dynamo_tpu.native.build import load_library

logger = logging.getLogger(__name__)

_SOURCES = ["native/transfer_agent/agent.cpp"]


def _lib():
    lib = load_library("transfer_agent", _SOURCES)
    if lib is None:
        return None
    lib.ta_create.restype = ctypes.c_void_p
    lib.ta_create.argtypes = [ctypes.c_char_p, ctypes.c_uint16, ctypes.c_char_p]
    lib.ta_port.restype = ctypes.c_uint16
    lib.ta_port.argtypes = [ctypes.c_void_p]
    lib.ta_register.restype = ctypes.c_int
    lib.ta_register.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint64,
    ]
    lib.ta_unregister.restype = ctypes.c_int
    lib.ta_unregister.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.ta_poll.restype = ctypes.c_int64
    lib.ta_poll.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_char_p,
        ctypes.c_uint32,
    ]
    lib.ta_destroy.argtypes = [ctypes.c_void_p]
    lib.ta_connect.restype = ctypes.c_void_p
    lib.ta_connect.argtypes = [
        ctypes.c_char_p, ctypes.c_uint16, ctypes.c_char_p,
    ]
    lib.ta_write.restype = ctypes.c_int
    lib.ta_write.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.c_void_p, ctypes.c_uint64,
    ]
    lib.ta_notify.restype = ctypes.c_int
    lib.ta_notify.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_void_p, ctypes.c_uint32,
    ]
    lib.ta_read.restype = ctypes.c_int64
    lib.ta_read.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
        ctypes.c_void_p, ctypes.c_uint64,
    ]
    lib.ta_close.argtypes = [ctypes.c_void_p]
    return lib


def available() -> bool:
    return _lib() is not None


class TransferServer:
    def __init__(self, port: int = 0, bind_host: str = "127.0.0.1") -> None:
        """bind_host="0.0.0.0" accepts cross-host peers (the reference's
        NIXL plane is multi-node); the default stays loopback-only. Every
        server requires peers to AUTH with `self.token` (distribute it via
        the trusted control plane) — the wire protocol is otherwise
        unauthenticated raw memory writes."""
        self._lib = _lib()
        if self._lib is None:
            raise RuntimeError("native transfer agent unavailable")
        import secrets

        self.token: bytes = secrets.token_bytes(16)
        self._h = self._lib.ta_create(bind_host.encode(), port, self.token)
        if not self._h:
            raise RuntimeError("ta_create failed")
        self.port = self._lib.ta_port(self._h)
        self._meta_buf = ctypes.create_string_buffer(1 << 20)
        # Keep registered arrays alive — the C++ side holds raw pointers.
        self._pinned: dict[int, np.ndarray] = {}

    def register(self, region_id: int, arena: np.ndarray) -> None:
        arena = np.ascontiguousarray(arena)
        rc = self._lib.ta_register(
            self._h, region_id, arena.ctypes.data_as(ctypes.c_void_p),
            arena.nbytes,
        )
        if rc != 0:
            raise RuntimeError(f"ta_register({region_id}) failed")
        self._pinned[region_id] = arena

    def unregister(self, region_id: int) -> None:
        self._lib.ta_unregister(self._h, region_id)
        self._pinned.pop(region_id, None)

    def poll(self) -> tuple[int, bytes] | None:
        """Drain one completion: (tag, meta) or None."""
        tag = ctypes.c_uint64()
        n = self._lib.ta_poll(
            self._h, ctypes.byref(tag), self._meta_buf,
            len(self._meta_buf),
        )
        if n < 0:
            return None
        return tag.value, self._meta_buf.raw[:n]

    def close(self) -> None:
        if self._h:
            self._lib.ta_destroy(self._h)
            self._h = None


class TransferClient:
    def __init__(self, host: str, port: int, token: bytes | None = None) -> None:
        self._lib = _lib()
        if self._lib is None:
            raise RuntimeError("native transfer agent unavailable")
        if token is not None and len(token) != 16:
            raise ValueError("auth token must be 16 bytes")
        # ta_connect takes a dotted quad (inet_pton, no DNS) — resolve
        # hostnames here so advertise addresses like "decode-0.svc" work.
        import socket

        host = socket.gethostbyname(host)
        self._c = self._lib.ta_connect(host.encode(), port, token)
        if not self._c:
            raise ConnectionError(f"ta_connect {host}:{port} failed")

    def write(self, region_id: int, offset: int, data: np.ndarray) -> None:
        data = np.ascontiguousarray(data)
        rc = self._lib.ta_write(
            self._c, region_id, offset,
            data.ctypes.data_as(ctypes.c_void_p), data.nbytes,
        )
        if rc != 0:
            raise ConnectionError("ta_write failed")

    def notify(self, tag: int, meta: bytes = b"") -> None:
        rc = self._lib.ta_notify(self._c, tag, meta, len(meta))
        if rc != 0:
            raise ConnectionError("ta_notify failed")

    def read(self, region_id: int, offset: int, nbytes: int) -> bytes:
        buf = ctypes.create_string_buffer(nbytes)
        n = self._lib.ta_read(self._c, region_id, offset, buf, nbytes)
        if n < 0:
            raise ConnectionError(f"ta_read failed ({n})")
        return buf.raw[:n]

    def close(self) -> None:
        if self._c:
            self._lib.ta_close(self._c)
            self._c = None

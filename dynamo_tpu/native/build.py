"""On-demand native builds: g++ -shared, cached by source content hash.

Build artifacts live under dynamo_tpu/native/_build, which is gitignored —
a fresh clone always compiles from the audited sources (mtime-based
staleness would let a stale checked-in blob win, since git does not
preserve mtimes). The content hash of all inputs plus the compile command
is embedded in the artifact name, so any source edit forces a rebuild.
"""

from __future__ import annotations

import ctypes
import hashlib
import logging
import os
import subprocess
from pathlib import Path

logger = logging.getLogger(__name__)

REPO_ROOT = Path(__file__).resolve().parents[2]
BUILD_DIR = REPO_ROOT / "dynamo_tpu" / "native" / "_build"

_CXX_CMD = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread"]

_cache: dict[str, ctypes.CDLL] = {}


def load_library(name: str, sources: list[str]) -> ctypes.CDLL | None:
    """Compile (if needed) and dlopen a native library. None if the
    toolchain is unavailable — callers fall back to pure Python."""
    if name in _cache:
        return _cache[name]
    srcs = [REPO_ROOT / s for s in sources]
    h = hashlib.sha256(" ".join(_CXX_CMD).encode())
    try:
        for s in srcs:
            h.update(s.read_bytes())
    except OSError as exc:
        logger.warning("native sources for %s unreadable: %s", name, exc)
        return None
    digest = h.hexdigest()[:16]
    BUILD_DIR.mkdir(parents=True, exist_ok=True)
    out = BUILD_DIR / f"lib{name}-{digest}.so"
    if not out.exists():
        # Compile to a process-unique temp path then atomically rename, so
        # concurrent processes (prefill + decode workers on one host) never
        # dlopen a half-written artifact.
        tmp = out.with_suffix(f".tmp{os.getpid()}")
        cmd = [*_CXX_CMD, *[str(s) for s in srcs], "-o", str(tmp)]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
            # The rename only guards concurrent dlopen; a crash loses
            # nothing a rebuild can't recreate, so fsync is overkill.
            # dynalint: allow[DT013] rebuildable artifact cache
            os.replace(tmp, out)
        except (subprocess.CalledProcessError, FileNotFoundError, OSError) as exc:
            detail = getattr(exc, "stderr", "") or str(exc)
            logger.warning("native build of %s failed: %s", name, detail)
            tmp.unlink(missing_ok=True)
            return None
        # Drop .so artifacts from older source revisions. A concurrent
        # process's live .tmp<pid> must NOT be swept (it would break that
        # process's atomic rename); orphans from killed processes are
        # reclaimed once they are demonstrably old.
        import time

        for stale in BUILD_DIR.glob(f"lib{name}-*"):
            if stale == out:
                continue
            if ".tmp" in stale.name:
                try:
                    if time.time() - stale.stat().st_mtime < 600:
                        continue
                except OSError:
                    continue
            stale.unlink(missing_ok=True)
    try:
        lib = ctypes.CDLL(str(out))
    except OSError as exc:
        logger.warning("dlopen %s failed: %s", out, exc)
        return None
    _cache[name] = lib
    return lib

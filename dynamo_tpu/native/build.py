"""On-demand native builds: g++ -shared, cached by source mtime."""

from __future__ import annotations

import ctypes
import logging
import subprocess
from pathlib import Path

logger = logging.getLogger(__name__)

REPO_ROOT = Path(__file__).resolve().parents[2]
BUILD_DIR = REPO_ROOT / "dynamo_tpu" / "native" / "_build"

_cache: dict[str, ctypes.CDLL] = {}


def load_library(name: str, sources: list[str]) -> ctypes.CDLL | None:
    """Compile (if stale) and dlopen a native library. None if the
    toolchain is unavailable — callers fall back to pure Python."""
    if name in _cache:
        return _cache[name]
    BUILD_DIR.mkdir(parents=True, exist_ok=True)
    out = BUILD_DIR / f"lib{name}.so"
    srcs = [REPO_ROOT / s for s in sources]
    if not out.exists() or any(
        s.stat().st_mtime > out.stat().st_mtime for s in srcs
    ):
        cmd = [
            "g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
            *[str(s) for s in srcs], "-o", str(out),
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except (subprocess.CalledProcessError, FileNotFoundError) as exc:
            detail = getattr(exc, "stderr", "") or str(exc)
            logger.warning("native build of %s failed: %s", name, detail)
            return None
    try:
        lib = ctypes.CDLL(str(out))
    except OSError as exc:
        logger.warning("dlopen %s failed: %s", out, exc)
        return None
    _cache[name] = lib
    return lib

"""Mid-stream worker-death failover: the ingress-side survival plane.

At fleet scale worker death is a steady-state event, not an exception
(PAPER §5 failure detection/recovery; Mooncake-style disaggregated
fleets assume recompute-over-error arithmetic — PAPERS.md 2606.03910).
Before this module a worker crashing mid-decode errored every in-flight
stream on it; now request survival is an *ingress-side* property
(docs/architecture/failure_model.md "Mid-stream failover"):

- **Eligibility** is by error CLASS, never by guess: only
  transport/engine-death errors (``ConnectionError`` lineage — the
  receiver's ``WorkerDiedError``, the bus's ``NoSubscriberError``,
  injected ``FaultError``s — plus the engine-fault ``ERROR`` finish
  frame) fail over. ``ShedError`` / ``DeadlineError`` / ``RequestError``
  NEVER do — overload, expiry, and client faults are deliberate
  decisions this plane must not overrule (tests prove the negative).
- **Replay** re-routes through the PushRouter (which already evicted the
  dead instance via its mark-dead fast path) with the REMAINING
  deadline and the ORIGINAL trace id. The replay prompt is
  ``prompt + tokens-already-emitted``: the new worker recomputes the
  delivered prefix as prefill (its prefix cache may hit), so its first
  generated token is exactly token K+1 and the wrapper skips all K
  already-delivered tokens by construction — a greedy stream is
  byte-identical across a mid-stream kill. ``max_tokens``/``min_tokens``
  shrink by K so length accounting never doubles.
- **Bounded**: ``max_attempts`` failovers, then a clean typed 502
  (``FailoverExhausted``) — never a hang, never a generic 500.

``FAILOVER`` is the process-wide counter registry
(``failover_total`` / ``failover_success_total`` /
``workers_marked_dead_total``, split per reason), exported on all three
metric surfaces next to ``retries_total``.
"""

# dynarace: context[loop]

from __future__ import annotations

import asyncio
import logging
import threading
from typing import Any, AsyncIterator

from dynamo_tpu.utils.tracing import TraceContext, tracer

logger = logging.getLogger(__name__)

#: Bounded failover attempts per request (re-dispatches, not counting
#: the original). Past this the request gets the typed 502.
DEFAULT_MAX_ATTEMPTS = 3


class FailoverStats:
    """Process-wide failover accounting, split per reason — the same
    shape as utils/retry.RetryCounter so the three surfaces export the
    robustness counters uniformly."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.attempts_by_reason: dict[str, int] = {}
        self.success_by_reason: dict[str, int] = {}
        self.marked_dead_by_reason: dict[str, int] = {}

    def note_attempt(self, reason: str) -> None:
        with self._lock:
            self.attempts_by_reason[reason] = (
                self.attempts_by_reason.get(reason, 0) + 1
            )

    def note_success(self, reason: str) -> None:
        with self._lock:
            self.success_by_reason[reason] = (
                self.success_by_reason.get(reason, 0) + 1
            )

    def note_marked_dead(self, reason: str) -> None:
        with self._lock:
            self.marked_dead_by_reason[reason] = (
                self.marked_dead_by_reason.get(reason, 0) + 1
            )

    # dynarace: context[loop, engine]
    @property
    def total(self) -> int:
        with self._lock:
            return sum(self.attempts_by_reason.values())

    # dynarace: context[loop, engine]
    @property
    def success_total(self) -> int:
        with self._lock:
            return sum(self.success_by_reason.values())

    # dynarace: context[loop, engine]
    @property
    def marked_dead_total(self) -> int:
        with self._lock:
            return sum(self.marked_dead_by_reason.values())

    def snapshot(self) -> dict[str, dict[str, int]]:
        with self._lock:
            return {
                "failover_total": dict(self.attempts_by_reason),
                "failover_success_total": dict(self.success_by_reason),
                "workers_marked_dead_total": dict(self.marked_dead_by_reason),
            }

    def render_labeled(self, prefix: str = "dyntpu") -> str:
        """Per-reason Prometheus series for the failover counters — the
        flat totals ride the gauge surfaces (DT011 parity); this is the
        breakdown an incident actually needs. The per-seam
        ``retries_total`` split lives on the retry registry
        (utils/retry.RETRIES.render_labeled) — each surface appends
        both, so neither plane's observability depends on the other."""
        lines: list[str] = []
        split = self.snapshot()
        for family, label in (
            ("failover_total", "reason"),
            ("failover_success_total", "reason"),
            ("workers_marked_dead_total", "reason"),
        ):
            counts = split[family]
            if not counts:
                continue
            lines.append(f"# TYPE {prefix}_{family}_by_{label} counter")
            for key, n in sorted(counts.items()):
                lines.append(
                    f'{prefix}_{family}_by_{label}{{{label}="{key}"}} {n}'
                )
        return "\n".join(lines) + ("\n" if lines else "")


FAILOVER = FailoverStats()


def failover_eligible(exc: BaseException) -> bool:
    """Transport/engine-death classification. ConnectionError lineage
    covers WorkerDiedError, NoSubscriberError, injected FaultError, and
    reset/refused sockets; IncompleteReadError is a torn frame. Shed /
    Deadline / Request errors are RuntimeError/ValueError subclasses and
    can never match — the taxonomy is structural, not a blocklist."""
    return isinstance(
        exc, (ConnectionError, asyncio.IncompleteReadError)
    )


def _finish_reason(item: Any) -> str | None:
    if isinstance(item, dict):
        return item.get("finish_reason")
    fr = getattr(item, "finish_reason", None)
    return getattr(fr, "value", fr)


def _token_ids(item: Any) -> list[int]:
    if isinstance(item, dict):
        return list(item.get("token_ids") or [])
    return list(getattr(item, "token_ids", None) or [])


class FailoverEngine:
    """AsyncEngine wrapper around the PushRouter: replays a stream that
    died with an engine-death class error onto a surviving worker.

    Sits between the Detokenizer and the router in the serving pipeline
    (llm/discovery.build_serving_pipeline), so the detokenizer upstream
    sees one continuous token stream — its incremental-decode state,
    stop-string jail, and max_tokens count carry straight across the
    failover and the client bytes never skip or repeat."""

    def __init__(self, downstream, max_attempts: int = DEFAULT_MAX_ATTEMPTS):
        self._next = downstream
        self.max_attempts = max_attempts

    def __getattr__(self, name):
        # Router surface passthrough (client, mark_dead, mode...) so
        # everything that introspects the pipeline's terminal engine
        # still finds the PushRouter underneath.
        return getattr(self._next, name)

    async def generate(self, request) -> AsyncIterator[Any]:
        from dynamo_tpu.llm.protocols.common import (
            DeadlineError,
            FailoverExhausted,
            FinishReason,
            ShedError,
        )
        from dynamo_tpu.utils.deadline import OVERLOAD, Deadline

        wire = request.payload if isinstance(request.payload, dict) else None
        replayable = wire is not None and "token_ids" in wire
        deadline = (
            Deadline.from_wire(wire.get("deadline_ms"))
            if replayable and wire.get("deadline_ms") is not None
            else None
        )
        emitted: list[int] = []
        yielded_any = False
        attempt = 0
        last_reason = ""
        trace_id = tracer().trace_id(request.id)
        ctx = request
        resumed: AsyncIterator[Any] | None = None
        while True:
            death: BaseException | None = None
            stream = (
                resumed if resumed is not None else self._next.generate(ctx)
            )
            resumed = None
            death_from_error_frame = False
            try:
                async for item in stream:
                    fr = _finish_reason(item)
                    if fr == FinishReason.ERROR.value:
                        # Engine fault frames end the stream NORMALLY
                        # (engine/engine.py _engine_loop) — re-typify to
                        # the death class instead of delivering a corpse
                        # marker to the client.
                        from dynamo_tpu.llm.protocols.common import (
                            WorkerDiedError,
                        )

                        death = WorkerDiedError(
                            "engine fault: stream ended with an ERROR "
                            "finish frame"
                        )
                        death_from_error_frame = True
                        break
                    toks = _token_ids(item)
                    if toks:
                        emitted.extend(toks)
                    if attempt and isinstance(item, dict) and (
                        "cum_tokens" in item
                    ):
                        # The replay engine restarts its count at 1; the
                        # client-visible cumulative count must keep
                        # climbing across the seam — on EVERY frame,
                        # including the tokenless terminal one (whose
                        # replay-local count would otherwise regress it).
                        item = dict(item)
                        item["cum_tokens"] = len(emitted)
                    yielded_any = True
                    yield item
                    if fr is not None:
                        if attempt:
                            FAILOVER.note_success(last_reason)
                        return
            except (GeneratorExit, asyncio.CancelledError):
                raise
            except BaseException as exc:  # noqa: BLE001 — classified below
                if not failover_eligible(exc):
                    raise
                death = exc
            if death is None:
                # Clean end without a terminal frame (single-shot
                # payloads: embeddings, raw dicts).
                if attempt:
                    FAILOVER.note_success(last_reason)
                return
            # -- the stream died with an engine-death class error --------
            reason = type(death).__name__
            last_reason = reason
            old_worker = request.annotations.get("worker_id")
            if death_from_error_frame and old_worker is not None:
                # An ERROR finish frame arrives over a HEALTHY transport,
                # so egress's mid-stream detection never fired — mark the
                # faulted worker dead here or the replay (KV mode
                # especially: the corpse holds the longest cached prefix
                # for prompt+emitted) routes straight back to it.
                mark = getattr(self._next, "mark_dead", None)
                if mark is not None:
                    mark(old_worker, "engine_fault")
            if not replayable and yielded_any:
                # A non-token stream that already delivered output can't
                # be replayed without duplicating it.
                raise FailoverExhausted(
                    f"stream died ({reason}) after partial non-token "
                    f"output; not replayable",
                    attempts=attempt,
                ) from death
            if attempt >= self.max_attempts:
                raise FailoverExhausted(
                    f"failover attempts exhausted "
                    f"({self.max_attempts}) — last error: {death}",
                    attempts=attempt,
                ) from death
            # The worker can die BETWEEN its final token frame and the
            # tokenless terminal frame (engine/engine.py emits every
            # finish reason as a separate frame): everything owed was
            # already delivered — synthesize the finish instead of
            # replaying, or the client receives tokens past the true
            # end (a max_tokens+1st token / content after the stop id).
            stop = (wire.get("stop") or {}) if replayable else {}
            synth = None
            if (
                stop.get("max_tokens") is not None
                and len(emitted) >= stop["max_tokens"]
            ):
                synth = FinishReason.LENGTH.value
            elif (
                emitted
                and not stop.get("ignore_eos")
                and emitted[-1] in (stop.get("stop_token_ids") or ())
            ):
                synth = FinishReason.STOP.value
            if synth is not None:
                yield {
                    "token_ids": [], "text": None,
                    "finish_reason": synth,
                    "cum_tokens": len(emitted),
                    "kv_transfer_params": None,
                }
                if attempt:
                    FAILOVER.note_success(last_reason)
                return
            if deadline is not None and deadline.expired:
                OVERLOAD.note_deadline("failover")
                raise DeadlineError(
                    "request deadline expired during failover"
                ) from death
            attempt += 1
            FAILOVER.note_attempt(reason)
            # Keep the ORIGINAL trace id across the seam: a dead worker
            # sharing this process's tracer (mocker fleets) closed the
            # trace in its stream teardown — re-adopt under the same id
            # so the failover span, the replay's spans, and the final
            # finish all join ONE cross-process timeline
            # (trace_merge honors the chain instead of red-barring it).
            tracer().adopt(
                request.id, TraceContext(trace_id, sent_unix=None)
            )
            tracer().mark(request.id, "failover")
            tracer().span_begin(request.id, "failover")
            logger.warning(
                "request %s: worker %s died mid-stream (%s) — failover "
                "attempt %d/%d resuming at token %d",
                request.id, hex(old_worker) if old_worker else "?",
                reason, attempt, self.max_attempts, len(emitted),
            )
            if replayable:
                ctx = request.map(
                    self._replay_wire(wire, emitted, deadline)
                )
            # The PushRouter re-picks EXCLUDING everything its mark-dead
            # fast path evicted; it raises ShedError when the fleet has
            # no healthy capacity left — which, inside a failover, IS
            # exhaustion: the clean typed 502. The failover span closes
            # on the replay's first frame (new worker known by then), so
            # it covers exactly the client-visible resume gap. A replay
            # whose first frame ALSO dies loops back through the death
            # path above — every re-dispatch is bounded by max_attempts.
            replay = self._next.generate(ctx)
            try:
                first = await replay.__anext__()
            except StopAsyncIteration:
                tracer().span_end(request.id, "failover")
                FAILOVER.note_success(last_reason)
                return
            except ShedError as exc:
                tracer().span_end(request.id, "failover")
                raise FailoverExhausted(
                    f"no healthy capacity for failover: {exc}",
                    attempts=attempt,
                ) from exc
            except (GeneratorExit, asyncio.CancelledError):
                tracer().span_end(request.id, "failover")
                raise
            except BaseException as exc:  # noqa: BLE001 — classified below
                tracer().span_end(request.id, "failover")
                if not failover_eligible(exc):
                    raise
                # The replacement died too before producing a frame —
                # feed the error back through the bounded death path.
                resumed = _raising(exc)
                continue
            tracer().span_end(request.id, "failover")
            new_worker = request.annotations.get("worker_id")
            self._export_record(
                request.id, reason, attempt, old_worker, new_worker,
                len(emitted),
            )
            resumed = _resume(replay, first)

    @staticmethod
    def _replay_wire(
        wire: dict, emitted: list[int], deadline
    ) -> dict[str, Any]:
        """The replay request: prompt + already-emitted tokens (the new
        worker recomputes the delivered prefix — prefix cache may hit),
        stop budgets shrunk by K, and the REMAINING deadline re-stamped
        (re-shipping the original wire value would re-anchor the full
        budget on the new worker — a deadline reset)."""
        w = dict(wire)
        w["token_ids"] = list(wire["token_ids"]) + list(emitted)
        stop = dict(w.get("stop") or {})
        if stop.get("max_tokens") is not None:
            stop["max_tokens"] = max(1, stop["max_tokens"] - len(emitted))
        if stop.get("min_tokens"):
            stop["min_tokens"] = max(0, stop["min_tokens"] - len(emitted))
        w["stop"] = stop
        if deadline is not None:
            w["deadline_ms"] = deadline.to_wire()
        return w

    @staticmethod
    def _export_record(
        request_id: str, reason: str, attempt: int,
        old_worker, new_worker, resumed_at: int,
    ) -> None:
        """kind="failover" line into the DYNTPU_TRACE capture — joins
        the trace catalog next to route/kv_actual/planner records."""
        try:
            tracer().export({
                "kind": "failover",
                "id": request_id,
                "trace": tracer().trace_id_if_active(request_id) or "",
                "reason": reason,
                "attempt": attempt,
                "old_worker": old_worker,
                "new_worker": new_worker,
                "resumed_at_token": resumed_at,
            })
        except Exception:  # noqa: BLE001 — observability must not fail failover
            logger.exception("failover record export failed")


async def _resume(stream, first) -> AsyncIterator[Any]:
    """The replay stream with its first (already-awaited) frame stitched
    back on front, so the failover loop processes every frame — ERROR
    re-typing, cum_tokens rewrite, emitted tracking — uniformly."""
    yield first
    async for item in stream:
        yield item


async def _raising(exc: BaseException) -> AsyncIterator[Any]:
    """An immediately-dying stream: routes a replay's first-frame death
    back into the failover loop's ONE bounded death path."""
    raise exc
    yield  # pragma: no cover — makes this an async generator

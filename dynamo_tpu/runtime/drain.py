"""Control-plane drain verb.

A worker must be drainable two ways (reference: disagg_serving.md graceful
drain; k8s rolling restarts): SIGTERM (the kubelet path) and an explicit
control-plane verb (operators retiring one instance without touching the
pod). Both funnel into the same in-process drain flow (cli.py
``_graceful_drain``): stop admitting → finish in-flight → flip readiness →
deregister → exit.

The verb rides the message bus as a broadcast on a per-component subject;
each worker subscribes at startup and triggers its drain callback when a
message targets its lease (or all instances, ``lease_id: null``). The bus
broadcast is fire-and-forget by design — the authoritative signal that the
drain COMPLETED is the instance key vanishing from the discovery store
(routers evict on that DELETE), which the initiator can watch.

The fleet planner's scale-downs ride this same machinery
(docs/architecture/planner.md): a shrinking decode pool retires workers
through SIGTERM/this verb — both funnel into ``cli.py _graceful_drain``,
so in-flight streams always finish — and a shrinking prefill pool relies
on the worker's graceful stop (finish + ack the leased queue item) with
lease-expiry redelivery as the crash backstop.
"""

from __future__ import annotations

import asyncio
import logging

import msgpack

from dynamo_tpu.utils.task import spawn_tracked

logger = logging.getLogger(__name__)


def drain_subject(namespace: str, component: str) -> str:
    return f"{namespace}.{component}._drain"


async def request_drain(
    drt, namespace: str, component: str, lease_id: int | None = None
) -> None:
    """Ask instances of ``namespace.component`` to drain: one instance by
    lease id, or every instance with ``lease_id=None``."""
    await drt.bus.broadcast(
        drain_subject(namespace, component),
        msgpack.packb({"lease_id": lease_id}),
    )


async def watch_drain(
    drt, namespace: str, component: str, on_drain
) -> "DrainWatch":
    """Subscribe this process to the component's drain subject;
    ``on_drain()`` fires (once) when a drain message targets this
    process's primary lease or all instances."""
    sub = await drt.bus.subscribe(drain_subject(namespace, component))
    watch = DrainWatch(sub, drt.primary_lease_id, on_drain)
    watch.start()
    drt.runtime.token.on_cancel(sub.close)
    return watch


class DrainWatch:
    def __init__(self, sub, lease_id: int, on_drain) -> None:
        self._sub = sub
        self._lease_id = lease_id
        self._on_drain = on_drain
        self._task: asyncio.Task | None = None
        self.fired = False

    def start(self) -> None:
        self._task = spawn_tracked(self._pump(), name="drain-watch")

    async def _pump(self) -> None:
        try:
            async for raw in self._sub:
                try:
                    msg = msgpack.unpackb(raw)
                except Exception:  # noqa: BLE001 — malformed drain frame is ignored, not fatal
                    logger.warning("malformed drain message ignored")
                    continue
                target = msg.get("lease_id")
                if target is not None and target != self._lease_id:
                    continue
                if not self.fired:
                    self.fired = True
                    logger.info(
                        "drain requested via control plane (lease %#x)",
                        self._lease_id,
                    )
                    self._on_drain()
        except asyncio.CancelledError:
            pass

    def close(self) -> None:
        self._sub.close()
        if self._task is not None:
            self._task.cancel()

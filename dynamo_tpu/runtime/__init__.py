from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.pipeline import Operator, Pipeline
from dynamo_tpu.runtime.runtime import Runtime, Worker

__all__ = [
    "AsyncEngine",
    "Context",
    "Operator",
    "Pipeline",
    "Runtime",
    "Worker",
]

"""Namespace → Component → Endpoint component model.

Mirrors the reference hierarchy (reference: lib/runtime/src/component.rs:106,
docs/architecture/distributed_runtime.md:22-29): a deployment is organized as
namespaces containing components exposing endpoints. A live *instance* is an
endpoint served by one worker, registered in the discovery store under
``instances/{ns}/{comp}/{endpoint}:{lease_id_hex}`` (reference:
component.rs:62-64,318-325) with the key bound to the worker's lease, so
worker death auto-deregisters it.

Endpoints are addressed as ``dyn://namespace.component.endpoint``
(reference: lib/runtime/src/protocols.rs:35-171).
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import TYPE_CHECKING, Any

if TYPE_CHECKING:
    from dynamo_tpu.runtime.distributed import DistributedRuntime

INSTANCE_ROOT = "instances/"


@dataclass(frozen=True)
class EndpointId:
    namespace: str
    component: str
    name: str

    @staticmethod
    def parse(path: str) -> "EndpointId":
        """Parse ``dyn://ns.component.endpoint`` or ``ns.component.endpoint``."""
        if path.startswith("dyn://"):
            path = path[len("dyn://") :]
        parts = path.split(".")
        if len(parts) < 3:
            raise ValueError(
                f"endpoint path {path!r} must be namespace.component.endpoint"
            )
        return EndpointId(parts[0], ".".join(parts[1:-1]), parts[-1])

    def __str__(self) -> str:
        return f"dyn://{self.namespace}.{self.component}.{self.name}"

    @property
    def etcd_prefix(self) -> str:
        return f"{INSTANCE_ROOT}{self.namespace}/{self.component}/{self.name}:"


@dataclass(frozen=True)
class Instance:
    """A live served endpoint: identity + bus subject for requests."""

    endpoint: EndpointId
    lease_id: int
    subject: str

    @property
    def instance_id(self) -> int:
        # Workers are identified by their lease id (reference: worker_id ==
        # lease_id throughout the KV-router protocols).
        return self.lease_id

    @property
    def store_key(self) -> str:
        return f"{self.endpoint.etcd_prefix}{self.lease_id:x}"

    def to_json(self) -> bytes:
        return json.dumps(
            {
                "namespace": self.endpoint.namespace,
                "component": self.endpoint.component,
                "endpoint": self.endpoint.name,
                "lease_id": self.lease_id,
                "subject": self.subject,
            }
        ).encode()

    @staticmethod
    def from_json(raw: bytes) -> "Instance":
        d = json.loads(raw)
        return Instance(
            endpoint=EndpointId(d["namespace"], d["component"], d["endpoint"]),
            lease_id=d["lease_id"],
            subject=d["subject"],
        )


class Namespace:
    def __init__(self, drt: "DistributedRuntime", name: str) -> None:
        self._drt = drt
        self.name = name

    def component(self, name: str) -> "Component":
        return Component(self._drt, self, name)


class Component:
    def __init__(self, drt: "DistributedRuntime", ns: Namespace, name: str) -> None:
        self._drt = drt
        self.namespace = ns
        self.name = name

    @property
    def service_name(self) -> str:
        return f"{self.namespace.name}_{self.name}"

    def endpoint(self, name: str) -> "Endpoint":
        return Endpoint(self._drt, self, name)

    def event_subject(self, plane: str) -> str:
        """Component-scoped broadcast subject (kv_events, metrics...)."""
        return f"{self.service_name}.events.{plane}"


class Endpoint:
    def __init__(self, drt: "DistributedRuntime", comp: Component, name: str) -> None:
        self._drt = drt
        self.component = comp
        self.name = name

    @property
    def id(self) -> EndpointId:
        return EndpointId(
            self.component.namespace.name, self.component.name, self.name
        )

    def subject_for(self, lease_id: int) -> str:
        """Per-instance request subject (reference: component.rs:335-346)."""
        return f"{self.component.service_name}.{self.name}-{lease_id:x}"

    async def serve(self, engine: Any, metadata: dict | None = None):
        """Register this endpoint instance and start handling requests.
        Returns a `ServedInstance` handle (stop() deregisters)."""
        from dynamo_tpu.runtime.ingress import serve_endpoint

        return await serve_endpoint(self._drt, self, engine, metadata)

    async def client(self, **kwargs):
        from dynamo_tpu.runtime.egress import Client

        return await Client.create(self._drt, self.id, **kwargs)

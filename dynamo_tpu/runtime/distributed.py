"""DistributedRuntime: the per-process handle to the control/data planes.

Mirrors the reference (reference: lib/runtime/src/distributed.rs:34-77): a
Runtime plus a discovery store client with a *primary lease* kept alive by a
background task — if the lease dies the runtime shuts down, and if the
runtime shuts down the lease is revoked (reference:
lib/runtime/src/transports/etcd.rs:100-131) — plus the message bus and a lazy
TCP response-stream server.

Construction modes:
- ``DistributedRuntime.in_process()`` — MemoryStore + InProcBus, single
  process (reference analogue: from_settings_without_discovery,
  distributed.rs:85).
- ``DistributedRuntime.connect(addr)`` — client of the framework's own
  control-plane server (multi-process / multi-host).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Optional

from dynamo_tpu.runtime.component import Namespace
from dynamo_tpu.runtime.runtime import Runtime
from dynamo_tpu.runtime.transports.bus import InProcBus
from dynamo_tpu.runtime.transports.store import KeyValueStore, MemoryStore
from dynamo_tpu.runtime.transports.tcp import TcpStreamServer
from dynamo_tpu.utils.cancellation import CancellationToken
from dynamo_tpu.utils.task import CriticalTask, spawn_tracked

logger = logging.getLogger(__name__)

LEASE_TTL_S = 10.0


class DistributedRuntime:
    def __init__(
        self,
        runtime: Runtime,
        store: KeyValueStore,
        bus,
        lease_id: int,
        keepalive: Optional[CriticalTask] = None,
    ) -> None:
        self.runtime = runtime
        self.store = store
        self.bus = bus
        self.primary_lease_id = lease_id
        self.lease_ttl_s = LEASE_TTL_S
        self._keepalive = keepalive
        self._tcp_server: TcpStreamServer | None = None
        self._tcp_lock = asyncio.Lock()
        runtime.token.on_cancel(self._on_shutdown)

    # -- constructors -------------------------------------------------------
    @staticmethod
    async def in_process(
        runtime: Runtime | None = None,
        store: KeyValueStore | None = None,
        bus=None,
    ) -> "DistributedRuntime":
        """In-process runtime. Pass another runtime's `store`/`bus` to create
        a second logical worker sharing one control plane (the test pattern
        for multi-worker behavior without processes — reference analogue:
        lib/runtime/tests/common/mock.rs)."""
        runtime = runtime or Runtime()
        store = store if store is not None else MemoryStore()
        bus = bus if bus is not None else InProcBus()
        lease_id = await store.grant_lease(LEASE_TTL_S)
        drt = DistributedRuntime(runtime, store, bus, lease_id)
        drt._start_keepalive()
        return drt

    @staticmethod
    async def connect(
        addr: str,
        runtime: Runtime | None = None,
        token: str | None = None,
        lease_ttl_s: float = LEASE_TTL_S,
    ) -> "DistributedRuntime":
        """Join a deployment via its control-plane server
        (transports/control_plane.py). The client implements both the store
        and bus protocols over one multiplexed TCP connection. Connection
        establishment retries under the shared backoff policy — workers
        routinely start before the control plane finishes binding (k8s
        rollout ordering), and a refused first dial must not kill them."""
        from dynamo_tpu.runtime.transports.control_client import ControlPlaneClient
        from dynamo_tpu.utils.retry import CONTROL_CONNECT, retry_async

        runtime = runtime or Runtime()

        async def dial() -> tuple[ControlPlaneClient, int]:
            # Dial + first RPC as ONE retried unit: a server that accepts
            # the socket but dies before granting the lease re-dials too.
            c = await ControlPlaneClient.connect(addr, token=token)
            try:
                return c, await c.grant_lease(lease_ttl_s)
            except BaseException:
                await c.close()
                raise

        client, lease_id = await retry_async(
            dial, CONTROL_CONNECT, seam="control.connect"
        )
        drt = DistributedRuntime(runtime, client, client, lease_id)
        drt.lease_ttl_s = lease_ttl_s
        drt._start_keepalive()
        return drt

    # -- lease lifecycle ----------------------------------------------------
    def _start_keepalive(self) -> None:
        from dynamo_tpu.utils.retry import RetryPolicy, retry_async

        async def keepalive(token: CancellationToken) -> None:
            while not token.is_cancelled():
                await asyncio.sleep(self.lease_ttl_s / 3)
                if token.is_cancelled():
                    break  # shutting down — the revoked lease is expected
                # Flap hardening: a TRANSIENT control-plane blip must not
                # take a healthy worker down — the lease tolerates missed
                # renewals up to its TTL, so the renewal does too. Retries
                # are budgeted to ~ttl/2 of wall (sleep ttl/3 + retries
                # stays under the TTL); only a partition that outlives
                # that budget — i.e. one the lease itself cannot survive —
                # escalates to the lease-death ⇒ shutdown coupling.
                ttl = self.lease_ttl_s
                policy = RetryPolicy(
                    attempts=6,
                    base_delay_s=ttl / 30,
                    max_delay_s=ttl / 6,
                    deadline_s=ttl / 2,
                    jitter=0.25,
                )
                try:
                    ok = await retry_async(
                        lambda: self.store.keep_alive(self.primary_lease_id),
                        policy,
                        seam="control.keepalive",
                    )
                except asyncio.CancelledError:
                    raise
                except Exception as exc:  # noqa: BLE001 — budget spent, lease is gone
                    raise RuntimeError(
                        f"primary lease {self.primary_lease_id:#x} lost: "
                        f"keepalive failed past the TTL budget ({exc!r})"
                    ) from exc
                if not ok:
                    # The server answered and said NO — authoritative,
                    # no retry: the lease already expired server-side.
                    raise RuntimeError(
                        f"primary lease {self.primary_lease_id:#x} lost"
                    )

        self._keepalive = CriticalTask(
            keepalive, self.runtime.token, name="primary-lease-keepalive"
        )

    def _on_shutdown(self) -> None:
        # Best-effort lease revoke so instance keys vanish promptly.
        try:
            loop = asyncio.get_event_loop()
            if loop.is_running():
                spawn_tracked(
                    loop.create_task(
                        self.store.revoke_lease(self.primary_lease_id)
                    ),
                    name="lease-revoke",
                )
        except RuntimeError:
            pass

    async def shutdown(self) -> None:
        self.runtime.shutdown()
        await self.store.revoke_lease(self.primary_lease_id)
        if self._tcp_server is not None:
            await self._tcp_server.stop()
        # A remote control-plane client holds a live TCP connection; close
        # it so the server's handler (and wait_closed) can finish.
        closer = getattr(self.store, "close", None)
        if closer is not None:
            await closer()

    # -- accessors ----------------------------------------------------------
    def namespace(self, name: str) -> Namespace:
        return Namespace(self, name)

    async def tcp_server(self) -> TcpStreamServer:
        """Lazy caller-side response-stream server. Guarded: a concurrent
        caller must never see a constructed-but-unbound server (it would
        hand out ConnectionInfo with port 0)."""
        if self._tcp_server is None:
            async with self._tcp_lock:
                if self._tcp_server is None:
                    server = TcpStreamServer()
                    await server.start()
                    self._tcp_server = server
        return self._tcp_server

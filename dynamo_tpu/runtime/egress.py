"""Client-side request routing (egress).

`Client` maintains a live instance list for an endpoint (static list or a
discovery-store watch — reference: lib/runtime/src/component/client.rs:1-224).
`PushRouter` picks an instance per request — Random / RoundRobin / Direct /
KV-aware — publishes the request envelope to the instance's bus subject with
embedded TCP connection info, and yields the response stream (reference:
lib/runtime/src/pipeline/network/egress/push_router.rs:65-203,
addressed_router.rs:59-178).
"""

from __future__ import annotations

import asyncio
import enum
import logging
import random
import uuid
from typing import Any, AsyncIterator

import msgpack

from dynamo_tpu.runtime.component import EndpointId, Instance
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.failover import FAILOVER
from dynamo_tpu.runtime.transports.store import EventKind
from dynamo_tpu.utils.faults import FAULTS
from dynamo_tpu.utils.task import spawn_tracked
from dynamo_tpu.utils.tracing import tracer

logger = logging.getLogger(__name__)

#: How long a dispatched worker gets to open its response connection
#: before the dispatch counts as dead (the reverse-connection analogue
#: of connection-refused). The connect-back happens BEFORE any engine
#: work, so this bounds only the handshake, never prefill.
DEFAULT_CONNECT_TIMEOUT_S = 5.0

#: Distinct instances one generate() call will try before giving up on
#: dispatch (each failure marks that instance dead first).
MAX_DISPATCH_ATTEMPTS = 8


class RouterMode(enum.Enum):
    RANDOM = "random"
    ROUND_ROBIN = "round_robin"
    DIRECT = "direct"
    KV = "kv"


class Client:
    """Instance source for one endpoint, kept live via a store watch."""

    def __init__(self, drt, endpoint_id: EndpointId) -> None:
        self._drt = drt
        self.endpoint_id = endpoint_id
        self._instances: dict[int, Instance] = {}
        self._watch_task: asyncio.Task | None = None
        self._event = asyncio.Event()
        # Evictions since the last store re-resolve: a FALSELY
        # marked-dead worker (transient blip, missed connect-back) has
        # no watch event to bring it back — lease keepalive touches the
        # TTL, not the key — so the next pick after an eviction goes
        # back to the store once, instead of leaking that worker from
        # this process's view until it re-registers.
        self._evicted_since_refresh = False
        self._refreshing = False
        # Watch-DELETE tombstones (id -> monotonic stamp): a refresh's
        # store snapshot is read BEFORE the await completes, so a worker
        # that deregistered mid-refresh would be resurrected from the
        # stale bytes — and no further watch event would ever remove it.
        # Deletes stamped after the snapshot started win over it.
        self._deleted: dict[int, float] = {}

    @staticmethod
    async def create(drt, endpoint_id: EndpointId) -> "Client":
        client = Client(drt, endpoint_id)
        watch = await drt.store.watch_prefix(endpoint_id.etcd_prefix)
        for _, raw in watch.initial.items():
            inst = Instance.from_json(raw)
            client._instances[inst.instance_id] = inst
        client._event.set() if client._instances else None
        client._watch_task = asyncio.ensure_future(client._pump(watch))
        drt.runtime.token.on_cancel(watch.cancel)
        return client

    async def _pump(self, watch) -> None:
        async for ev in watch:
            if ev.kind is EventKind.PUT and ev.value:
                inst = Instance.from_json(ev.value)
                self._instances[inst.instance_id] = inst
                self._deleted.pop(inst.instance_id, None)
                self._event.set()
            elif ev.kind is EventKind.DELETE:
                lease_hex = ev.key.rsplit(":", 1)[-1]
                try:
                    wid = int(lease_hex, 16)
                except ValueError:
                    continue
                self._instances.pop(wid, None)
                self._deleted[wid] = asyncio.get_running_loop().time()

    def instances(self) -> list[Instance]:
        return list(self._instances.values())

    def instance_ids(self) -> list[int]:
        return list(self._instances.keys())

    def evict(self, instance_id: int) -> bool:
        """Immediate removal from the live view (the mark-dead fast
        path): a dispatch that hit a corpse must not wait out the lease
        TTL before the next request stops routing to it. The discovery
        store is untouched — lease expiry (or an explicit deregister)
        remains the authoritative cleanup."""
        self._evicted_since_refresh = True
        return self._instances.pop(instance_id, None) is not None

    async def refresh(self) -> list[Instance]:
        """Re-read the authoritative instance set from the discovery
        store. The recovery path for a FALSE mark-dead (a router-side
        network blip poisons the whole local view): watch events only
        fire on store changes, so an evicted-but-alive worker would
        otherwise never come back until it re-registered."""
        t0 = asyncio.get_running_loop().time()
        # Re-arm BEFORE the snapshot read: an eviction landing while the
        # store call is in flight must trigger the NEXT background
        # revalidate — clearing the flag after the await would discard
        # exactly that signal (and this refresh's stale snapshot is what
        # resurrects the concurrently-evicted corpse).
        self._evicted_since_refresh = False
        raw = await self._drt.store.get_prefix(self.endpoint_id.etcd_prefix)
        fresh: dict[int, Instance] = {}
        for value in raw.values():
            try:
                inst = Instance.from_json(value)
            except Exception:  # noqa: BLE001 — skip torn entries
                logger.warning("skipping malformed instance entry")
                continue
            # A DELETE that landed while the snapshot was in flight wins
            # over the snapshot's (necessarily older) bytes: a worker
            # that deregistered mid-refresh must not be resurrected into
            # the live view with no future event to remove it.
            if self._deleted.get(inst.instance_id, -1.0) >= t0:
                continue
            fresh[inst.instance_id] = inst
        self._instances = fresh
        # Tombstones only matter across one in-flight snapshot — prune
        # anything old so the map can't grow with fleet churn.
        for wid in [w for w, ts in self._deleted.items() if ts < t0]:
            del self._deleted[wid]
        if fresh:
            self._event.set()
        return list(fresh.values())

    async def _refresh_background(self) -> None:
        """Single-flight, non-blocking re-resolve after an eviction —
        the hot pick path never pays a store round trip; a falsely
        evicted worker reappears within one refresh instead of never."""
        if self._refreshing:
            return
        self._refreshing = True
        try:
            await self.refresh()
        except Exception:  # noqa: BLE001 — store blip: next eviction retries
            logger.debug("background instance refresh failed", exc_info=True)
        finally:
            self._refreshing = False

    async def wait_for_instances(self, timeout_s: float = 5.0) -> list[Instance]:
        if not self._instances:
            # The local view may be empty because mark-dead evicted
            # everything — re-resolve from the store before concluding
            # the endpoint has no capacity.
            try:
                await self.refresh()
            except Exception:  # noqa: BLE001 — store blip: fall through to wait
                logger.debug("instance refresh failed", exc_info=True)
        elif self._evicted_since_refresh:
            # Non-empty view with pending evictions: re-validate against
            # the store off the hot path (a TRUE corpse gets re-evicted
            # on its next failed dispatch; a false one comes back).
            spawn_tracked(
                self._refresh_background(), name="client-refresh"
            )
        if not self._instances:
            self._event.clear()
            await asyncio.wait_for(self._event.wait(), timeout_s)
        return self.instances()


class PushRouter:
    """Routes requests to instances; itself an AsyncEngine.

    KV-aware mode delegates instance choice to a `selector` callable
    (installed by the KV router layer) receiving the request payload and the
    live instance list.
    """

    def __init__(
        self,
        drt,
        client: Client,
        mode: RouterMode = RouterMode.ROUND_ROBIN,
        selector=None,
        connect_timeout_s: float = DEFAULT_CONNECT_TIMEOUT_S,
    ) -> None:
        self._drt = drt
        self.client = client
        self.mode = mode
        self.connect_timeout_s = connect_timeout_s
        self._selector = selector
        # Dead-worker hooks, fired with the instance id on every
        # mark_dead. A KV-aware selector's owning router is auto-wired:
        # the metrics aggregator drops the corpse's load snapshot and
        # the radix index prunes its blocks IN THE SAME STEP as the
        # routing eviction (satellite: ghosts used to linger until
        # endpoint_ttl_s).
        self.on_dead: list = []
        owner = getattr(selector, "__self__", None)
        hook = getattr(owner, "note_worker_dead", None)
        if hook is not None:
            self.on_dead.append(hook)
        # Whether the selector takes the request id (KvRouter.selector_fn
        # does — it binds the route-audit record to the request's trace);
        # legacy two-arg selectors keep working unchanged. Sniffed once,
        # not per request, and never via a TypeError probe (which would
        # mask a TypeError raised INSIDE the selector body).
        self._selector_takes_rid = False
        if selector is not None:
            import inspect

            try:
                params = inspect.signature(selector).parameters.values()
                self._selector_takes_rid = any(
                    p.name == "request_id" or p.kind is p.VAR_KEYWORD
                    for p in params
                )
            except (TypeError, ValueError):
                pass
        self._rr = 0

    @staticmethod
    async def create(
        drt, endpoint_id: EndpointId | str, mode: RouterMode = RouterMode.ROUND_ROBIN,
        selector=None,
        connect_timeout_s: float = DEFAULT_CONNECT_TIMEOUT_S,
    ) -> "PushRouter":
        if isinstance(endpoint_id, str):
            endpoint_id = EndpointId.parse(endpoint_id)
        client = await Client.create(drt, endpoint_id)
        return PushRouter(
            drt, client, mode, selector, connect_timeout_s=connect_timeout_s
        )

    async def _pick(
        self, payload: Any, instance_id: int | None,
        request_id: str | None = None,
        exclude: set[int] | None = None,
    ) -> Instance:
        try:
            instances = await self.client.wait_for_instances()
        except asyncio.TimeoutError:
            # Every instance evicted (rolling restart, drain, lease
            # expiry): a typed retryable rejection — the HTTP layer maps
            # it to 503 + Retry-After so clients back off and retry,
            # instead of a generic 500.
            from dynamo_tpu.llm.protocols.common import ShedError

            raise ShedError(
                f"no live instances for {self.client.endpoint_id}",
                retry_after_s=2.0,
            ) from None
        if exclude:
            # Failover re-dispatch: instances this request already found
            # dead stay out even if a store refresh re-added the corpse.
            instances = [
                i for i in instances if i.instance_id not in exclude
            ]
            if not instances:
                from dynamo_tpu.llm.protocols.common import ShedError

                raise ShedError(
                    f"every live instance of {self.client.endpoint_id} "
                    f"already failed this request",
                    retry_after_s=2.0,
                )
        if instance_id is not None:
            for inst in instances:
                if inst.instance_id == instance_id:
                    return inst
            raise LookupError(
                f"instance {instance_id:#x} not found for {self.client.endpoint_id}"
            )
        if self.mode is RouterMode.RANDOM:
            return random.choice(instances)
        if self.mode is RouterMode.ROUND_ROBIN:
            inst = instances[self._rr % len(instances)]
            self._rr += 1
            return inst
        if self.mode is RouterMode.KV:
            if self._selector is None:
                raise RuntimeError("KV mode requires a selector")
            chosen_id = await (
                self._selector(payload, instances, request_id=request_id)
                if self._selector_takes_rid
                else self._selector(payload, instances)
            )
            if exclude and chosen_id in exclude:
                # Stale selector metrics can still point at the corpse —
                # fall back to spreading over the surviving candidates.
                chosen_id = random.choice(instances).instance_id
            try:
                return await self._pick(payload, chosen_id, exclude=exclude)
            except LookupError:
                # The selector's choice raced a concurrent mark-dead
                # eviction (another request's failover removed it while
                # we awaited the selector). A healthy request must not
                # 500 on that race — spread over the survivors; if the
                # pick is ALSO a corpse, dispatch marks it dead and the
                # caller's retry loop moves on.
                survivors = [
                    i for i in instances if i.instance_id != chosen_id
                ]
                if not survivors:
                    from dynamo_tpu.llm.protocols.common import ShedError

                    raise ShedError(
                        f"no surviving instances for "
                        f"{self.client.endpoint_id}",
                        retry_after_s=2.0,
                    ) from None
                return random.choice(survivors)
        raise RuntimeError(f"direct mode requires instance_id")

    def mark_dead(self, instance_id: int, reason: str) -> None:
        """The mark-dead fast path: a typed transport failure against a
        worker immediately evicts it from the live routing view AND
        fires the on_dead hooks (metrics-aggregator poison + radix
        prune, plus the ``worker_dead`` broadcast that propagates the
        eviction to sibling router replicas — kv_router/router.py
        note_worker_dead) — in ONE step, instead of letting the ghost
        linger until the lease TTL / endpoint_ttl_s expire it. The same
        path covers dead ROUTER REPLICAS when the instances ARE
        replicas (a frontend spreading over N RouterServices —
        docs/architecture/ingress_scale.md): replica death and worker
        death are one taxonomy at this seam."""
        if self.client.evict(instance_id):
            FAILOVER.note_marked_dead(reason)
            logger.warning(
                "marked worker %#x dead (%s) — evicted from the live "
                "instance view", instance_id, reason,
            )
        for hook in self.on_dead:
            try:
                hook(instance_id)
            except Exception:  # noqa: BLE001 — a hook bug must not break routing
                logger.exception("on_dead hook failed for %#x", instance_id)

    async def generate(
        self, request: Context, instance_id: int | None = None
    ) -> AsyncIterator[Any]:
        from dynamo_tpu.llm.protocols.common import WorkerDiedError

        tried: set[int] = set()
        while True:
            with tracer().span(request.id, "route"):
                instance = await self._pick(
                    request.payload, instance_id, request_id=request.id,
                    exclude=tried or None,
                )
            try:
                receiver = await self._dispatch(instance, request)
            except (
                ConnectionError, OSError,
                asyncio.TimeoutError, TimeoutError,
            ) as exc:
                # Dispatch-time connection failure: the worker is dead at
                # the seam (connection-refused class). Mark it, and —
                # since NOTHING has streamed yet — re-pick transparently.
                self.mark_dead(
                    instance.instance_id, f"dispatch:{type(exc).__name__}"
                )
                tried.add(instance.instance_id)
                if instance_id is not None or len(tried) >= MAX_DISPATCH_ATTEMPTS:
                    raise WorkerDiedError(
                        f"dispatch to {instance.instance_id:#x} failed: "
                        f"{exc}"
                    ) from exc
                continue
            request.annotations["worker_id"] = instance.instance_id
            async for item in self._relay(instance, receiver, request):
                yield item
            return

    async def direct(self, request: Context, instance_id: int) -> AsyncIterator[Any]:
        instance = await self._pick(request.payload, instance_id)
        try:
            receiver = await self._dispatch(instance, request)
        except (
            ConnectionError, OSError, asyncio.TimeoutError, TimeoutError,
        ) as exc:
            self.mark_dead(
                instance.instance_id, f"dispatch:{type(exc).__name__}"
            )
            raise
        async for item in self._relay(instance, receiver, request):
            yield item

    async def _dispatch(self, instance: Instance, request: Context):
        """Publish the request envelope and wait for the worker's
        response connection (the dispatch ack). Raises the typed
        transport error on a dead subject (NoSubscriberError), an
        injected ``fleet.worker_kill`` fault, or a connect-back that
        never arrives — the three faces of 'the worker is a corpse'."""
        server = await self._drt.tcp_server()
        stream_id = uuid.uuid4().hex
        receiver = server.register(stream_id)
        envelope = {
            "id": request.id,
            "payload": request.payload,
            "resp": server.connection_info(stream_id).to_wire(),
            # Trace identity at the envelope level too: payloads that are
            # not a PreprocessedRequest wire (embeddings, raw dicts) still
            # join the request's cross-process timeline, and the worker's
            # error-plane frames stay attributable to this trace.
            "trace": tracer().context_wire(request.id, parent_span="route"),
        }
        try:
            if FAULTS.active:
                await FAULTS.maybe_fail_async("fleet.worker_kill")
            await self._drt.bus.publish(
                instance.subject, msgpack.packb(envelope),
                require_subscriber=True,
            )
            await asyncio.wait_for(
                receiver.connected.wait(), self.connect_timeout_s
            )
        except BaseException:
            server.unregister(stream_id)
            raise
        return receiver

    async def _relay(
        self, instance: Instance, receiver, request: Context
    ) -> AsyncIterator[Any]:
        from dynamo_tpu.llm.protocols.common import WorkerDiedError

        try:
            async for payload in receiver:
                if request.is_killed:
                    break
                # Each streamed frame proves the request is alive: refresh
                # the frontend capture's TTL so a stream outliving ttl_s is
                # not reaped (and falsely counted abandoned) mid-flight.
                tracer().touch(request.id)
                yield msgpack.unpackb(payload)
        except WorkerDiedError as exc:
            # Mid-stream death: evict + poison NOW so the failover
            # re-dispatch (and every other request) stops routing here.
            # ONLY on transport evidence — a WorkerDiedError that crossed
            # as an error FRAME was delivered by a live worker (a
            # worker-local transient, e.g. a disagg pull reset): it still
            # fails over, but evicting the reporter and pruning its radix
            # blocks would punish the fleet for nothing.
            if getattr(exc, "transport_dead", False):
                self.mark_dead(instance.instance_id, "stream")
            raise

"""Client-side request routing (egress).

`Client` maintains a live instance list for an endpoint (static list or a
discovery-store watch — reference: lib/runtime/src/component/client.rs:1-224).
`PushRouter` picks an instance per request — Random / RoundRobin / Direct /
KV-aware — publishes the request envelope to the instance's bus subject with
embedded TCP connection info, and yields the response stream (reference:
lib/runtime/src/pipeline/network/egress/push_router.rs:65-203,
addressed_router.rs:59-178).
"""

from __future__ import annotations

import asyncio
import enum
import logging
import random
import uuid
from typing import Any, AsyncIterator

import msgpack

from dynamo_tpu.runtime.component import EndpointId, Instance
from dynamo_tpu.runtime.engine import Context
from dynamo_tpu.runtime.transports.store import EventKind
from dynamo_tpu.utils.tracing import tracer

logger = logging.getLogger(__name__)


class RouterMode(enum.Enum):
    RANDOM = "random"
    ROUND_ROBIN = "round_robin"
    DIRECT = "direct"
    KV = "kv"


class Client:
    """Instance source for one endpoint, kept live via a store watch."""

    def __init__(self, drt, endpoint_id: EndpointId) -> None:
        self._drt = drt
        self.endpoint_id = endpoint_id
        self._instances: dict[int, Instance] = {}
        self._watch_task: asyncio.Task | None = None
        self._event = asyncio.Event()

    @staticmethod
    async def create(drt, endpoint_id: EndpointId) -> "Client":
        client = Client(drt, endpoint_id)
        watch = await drt.store.watch_prefix(endpoint_id.etcd_prefix)
        for _, raw in watch.initial.items():
            inst = Instance.from_json(raw)
            client._instances[inst.instance_id] = inst
        client._event.set() if client._instances else None
        client._watch_task = asyncio.ensure_future(client._pump(watch))
        drt.runtime.token.on_cancel(watch.cancel)
        return client

    async def _pump(self, watch) -> None:
        async for ev in watch:
            if ev.kind is EventKind.PUT and ev.value:
                inst = Instance.from_json(ev.value)
                self._instances[inst.instance_id] = inst
                self._event.set()
            elif ev.kind is EventKind.DELETE:
                lease_hex = ev.key.rsplit(":", 1)[-1]
                try:
                    self._instances.pop(int(lease_hex, 16), None)
                except ValueError:
                    pass

    def instances(self) -> list[Instance]:
        return list(self._instances.values())

    def instance_ids(self) -> list[int]:
        return list(self._instances.keys())

    async def wait_for_instances(self, timeout_s: float = 5.0) -> list[Instance]:
        if not self._instances:
            self._event.clear()
            await asyncio.wait_for(self._event.wait(), timeout_s)
        return self.instances()


class PushRouter:
    """Routes requests to instances; itself an AsyncEngine.

    KV-aware mode delegates instance choice to a `selector` callable
    (installed by the KV router layer) receiving the request payload and the
    live instance list.
    """

    def __init__(
        self,
        drt,
        client: Client,
        mode: RouterMode = RouterMode.ROUND_ROBIN,
        selector=None,
    ) -> None:
        self._drt = drt
        self.client = client
        self.mode = mode
        self._selector = selector
        # Whether the selector takes the request id (KvRouter.selector_fn
        # does — it binds the route-audit record to the request's trace);
        # legacy two-arg selectors keep working unchanged. Sniffed once,
        # not per request, and never via a TypeError probe (which would
        # mask a TypeError raised INSIDE the selector body).
        self._selector_takes_rid = False
        if selector is not None:
            import inspect

            try:
                params = inspect.signature(selector).parameters.values()
                self._selector_takes_rid = any(
                    p.name == "request_id" or p.kind is p.VAR_KEYWORD
                    for p in params
                )
            except (TypeError, ValueError):
                pass
        self._rr = 0

    @staticmethod
    async def create(
        drt, endpoint_id: EndpointId | str, mode: RouterMode = RouterMode.ROUND_ROBIN,
        selector=None,
    ) -> "PushRouter":
        if isinstance(endpoint_id, str):
            endpoint_id = EndpointId.parse(endpoint_id)
        client = await Client.create(drt, endpoint_id)
        return PushRouter(drt, client, mode, selector)

    async def _pick(
        self, payload: Any, instance_id: int | None,
        request_id: str | None = None,
    ) -> Instance:
        try:
            instances = await self.client.wait_for_instances()
        except asyncio.TimeoutError:
            # Every instance evicted (rolling restart, drain, lease
            # expiry): a typed retryable rejection — the HTTP layer maps
            # it to 503 + Retry-After so clients back off and retry,
            # instead of a generic 500.
            from dynamo_tpu.llm.protocols.common import ShedError

            raise ShedError(
                f"no live instances for {self.client.endpoint_id}",
                retry_after_s=2.0,
            ) from None
        if instance_id is not None:
            for inst in instances:
                if inst.instance_id == instance_id:
                    return inst
            raise LookupError(
                f"instance {instance_id:#x} not found for {self.client.endpoint_id}"
            )
        if self.mode is RouterMode.RANDOM:
            return random.choice(instances)
        if self.mode is RouterMode.ROUND_ROBIN:
            inst = instances[self._rr % len(instances)]
            self._rr += 1
            return inst
        if self.mode is RouterMode.KV:
            if self._selector is None:
                raise RuntimeError("KV mode requires a selector")
            chosen_id = await (
                self._selector(payload, instances, request_id=request_id)
                if self._selector_takes_rid
                else self._selector(payload, instances)
            )
            return await self._pick(payload, chosen_id)
        raise RuntimeError(f"direct mode requires instance_id")

    async def generate(
        self, request: Context, instance_id: int | None = None
    ) -> AsyncIterator[Any]:
        with tracer().span(request.id, "route"):
            instance = await self._pick(
                request.payload, instance_id, request_id=request.id
            )
        async for item in self._send(instance, request):
            yield item

    async def direct(self, request: Context, instance_id: int) -> AsyncIterator[Any]:
        instance = await self._pick(request.payload, instance_id)
        async for item in self._send(instance, request):
            yield item

    async def _send(self, instance: Instance, request: Context) -> AsyncIterator[Any]:
        server = await self._drt.tcp_server()
        stream_id = uuid.uuid4().hex
        receiver = server.register(stream_id)
        envelope = {
            "id": request.id,
            "payload": request.payload,
            "resp": server.connection_info(stream_id).to_wire(),
            # Trace identity at the envelope level too: payloads that are
            # not a PreprocessedRequest wire (embeddings, raw dicts) still
            # join the request's cross-process timeline, and the worker's
            # error-plane frames stay attributable to this trace.
            "trace": tracer().context_wire(request.id, parent_span="route"),
        }
        await self._drt.bus.publish(instance.subject, msgpack.packb(envelope))
        async for payload in receiver:
            if request.is_killed:
                break
            # Each streamed frame proves the request is alive: refresh
            # the frontend capture's TTL so a stream outliving ttl_s is
            # not reaped (and falsely counted abandoned) mid-flight.
            tracer().touch(request.id)
            yield msgpack.unpackb(payload)

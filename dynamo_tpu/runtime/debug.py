"""Control-plane debug verbs: on-demand profiling.

The drain verb's twin (runtime/drain.py): an operator must be able to
capture a TPU profile window on a running worker WITHOUT port-forwarding
to its debug HTTP endpoint — `dynamo-tpu` workers subscribe to a
per-component ``_profile`` subject at startup and run a
``utils/profiling.Profiler`` window when a message targets their lease
(or all instances, ``lease_id: null``). Fire-and-forget by design, like
drain: the capture lands in the worker's configured profile directory;
the worker's logs carry the output path.
"""

from __future__ import annotations

import asyncio
import logging

import msgpack

from dynamo_tpu.utils.task import spawn_tracked

logger = logging.getLogger(__name__)


def profile_subject(namespace: str, component: str) -> str:
    return f"{namespace}.{component}._profile"


async def request_profile(
    drt,
    namespace: str,
    component: str,
    seconds: float = 5.0,
    lease_id: int | None = None,
) -> None:
    """Ask instances of ``namespace.component`` to capture a profile
    window: one instance by lease id, or every instance with
    ``lease_id=None``."""
    await drt.bus.broadcast(
        profile_subject(namespace, component),
        msgpack.packb({"lease_id": lease_id, "seconds": float(seconds)}),
    )


async def watch_profile(
    drt, namespace: str, component: str, profiler
) -> "ProfileWatch":
    """Subscribe this process to the component's profile subject; each
    targeted message runs one ``profiler.capture(seconds)`` window (the
    profiler's own single-flight/cap rails apply)."""
    sub = await drt.bus.subscribe(profile_subject(namespace, component))
    watch = ProfileWatch(sub, drt.primary_lease_id, profiler)
    watch.start()
    drt.runtime.token.on_cancel(sub.close)
    return watch


class ProfileWatch:
    def __init__(self, sub, lease_id: int, profiler) -> None:
        self._sub = sub
        self._lease_id = lease_id
        self._profiler = profiler
        self._task: asyncio.Task | None = None
        self.fired = 0

    def start(self) -> None:
        self._task = spawn_tracked(self._pump(), name="profile-watch")

    async def _pump(self) -> None:
        try:
            async for raw in self._sub:
                try:
                    msg = msgpack.unpackb(raw)
                    target = msg.get("lease_id")
                    if target is not None and target != self._lease_id:
                        continue
                    seconds = float(msg.get("seconds") or 5.0)
                except Exception:  # noqa: BLE001 — malformed frame is ignored, not fatal
                    # Covers the unpack AND the body shape (non-dict
                    # payload, non-numeric seconds): a bad verb must not
                    # kill the pump and silently disable profiling for
                    # the rest of the worker's life.
                    logger.warning("malformed profile message ignored")
                    continue
                self.fired += 1
                try:
                    result = await self._profiler.capture(seconds)
                    logger.info(
                        "control-plane profile window done: %s",
                        result["path"],
                    )
                # noqa: a refused/failed window is logged; fire-and-forget
                except Exception:  # noqa: BLE001
                    logger.exception("control-plane profile window failed")
        except asyncio.CancelledError:
            pass

    def close(self) -> None:
        self._sub.close()
        if self._task is not None:
            self._task.cancel()

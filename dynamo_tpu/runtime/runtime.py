"""Runtime and Worker harness.

The reference `Runtime` owns the async executor and the root cancellation
token; `Worker` is the main() harness wiring SIGINT/SIGTERM to graceful
shutdown (reference: lib/runtime/src/lib.rs:66-73, worker.rs:16-66). Our
Runtime owns the asyncio loop's root token; everything long-lived hangs a
child token (or a CriticalTask) off it.
"""

from __future__ import annotations

import asyncio
import logging
import signal
from typing import Awaitable, Callable

from dynamo_tpu.utils.cancellation import CancellationToken
from dynamo_tpu.utils.logging import init_logging

logger = logging.getLogger(__name__)


class Runtime:
    """Process-wide runtime: root cancellation token + background tasks."""

    def __init__(self) -> None:
        self._token = CancellationToken()

    def child_token(self) -> CancellationToken:
        return self._token.child_token()

    @property
    def token(self) -> CancellationToken:
        return self._token

    def shutdown(self) -> None:
        logger.info("runtime shutdown requested")
        self._token.cancel()

    @property
    def is_shutdown(self) -> bool:
        return self._token.is_cancelled()


class Worker:
    """Main harness: run an async entrypoint under a Runtime with signal
    handling; the entrypoint receives the Runtime and should exit when its
    token cancels."""

    def __init__(self) -> None:
        init_logging()

    def execute(self, main: Callable[[Runtime], Awaitable[None]]) -> None:
        asyncio.run(self._run(main))

    async def _run(self, main: Callable[[Runtime], Awaitable[None]]) -> None:
        runtime = Runtime()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, runtime.shutdown)
            except NotImplementedError:  # non-unix / nested loops
                pass
        try:
            await main(runtime)
        finally:
            runtime.shutdown()

"""TCP response plane.

Requests ride the message bus to a worker; the response stream comes straight
back over a direct TCP connection from the worker to the caller, bypassing
the bus (reference: lib/runtime/src/pipeline/network/tcp/server.rs:74,125 —
`TcpStreamServer` + `ConnectionInfo` handshake; egress/addressed_router.rs
embeds the caller's address in the request envelope).

Protocol: the worker connects, sends a prologue frame whose header is
``{"stream_id": ...}``, then data frames with headers ``{"t": "data"}``,
``{"t": "err", "msg": ...}`` and finally ``{"t": "end"}``.
"""

from __future__ import annotations

import asyncio
import logging
from dataclasses import dataclass
from typing import Any, AsyncIterator

import msgpack

from dynamo_tpu.runtime.transports.codec import encode_frame, read_frame
from dynamo_tpu.utils.faults import FAULTS

logger = logging.getLogger(__name__)


@dataclass(frozen=True)
class ConnectionInfo:
    """Where the worker should connect to stream responses back."""

    host: str
    port: int
    stream_id: str

    def to_wire(self) -> dict[str, Any]:
        return {"host": self.host, "port": self.port, "stream_id": self.stream_id}

    @staticmethod
    def from_wire(d: dict[str, Any]) -> "ConnectionInfo":
        return ConnectionInfo(d["host"], d["port"], d["stream_id"])


class ResponseStreamReceiver:
    """Caller-side handle: an async iterator of response payload bytes.

    Distinguishes the two ways a stream can close: a terminal frame
    (``end``/``err`` — the worker finished or reported) versus the raw
    connection dying with no terminal frame — which is worker DEATH
    mid-stream, surfaced as a typed ``WorkerDiedError`` so the ingress
    failover plane can re-dispatch instead of treating a truncated
    stream as a clean completion (the pre-failover behavior silently
    dropped the request's tail)."""

    def __init__(self) -> None:
        self._queue: asyncio.Queue[tuple[str, bytes] | None] = asyncio.Queue()
        #: Set when the worker's connection presented this stream id —
        #: the dispatch-ack the router's connect-timeout watches: a
        #: worker that died between envelope delivery and connect-back
        #: would otherwise leave the caller waiting forever.
        self.connected = asyncio.Event()
        self._terminal = False

    def _push(self, kind: str, payload: bytes) -> None:
        if kind in ("end", "err"):
            self._terminal = True
        self._queue.put_nowait((kind, payload))

    def _close(self) -> None:
        self._queue.put_nowait(None)

    def __aiter__(self) -> AsyncIterator[bytes]:
        return self

    async def __anext__(self) -> bytes:
        item = await self._queue.get()
        if item is None:
            if not self._terminal:
                from dynamo_tpu.llm.protocols.common import WorkerDiedError

                err = WorkerDiedError(
                    "response stream closed without a terminal frame — "
                    "worker died mid-stream"
                )
                # Transport-level evidence: the SOCKET died, not a
                # worker-reported error — this is what licenses the
                # router's mark-dead fast path.
                err.transport_dead = True
                raise err
            raise StopAsyncIteration
        kind, payload = item
        if kind == "end":
            raise StopAsyncIteration
        if kind == "err":
            raise _typed_stream_error(payload.decode("utf-8", "replace"))
        return payload


def _typed_stream_error(message: str) -> Exception:
    """Re-typify worker-side errors that crossed the wire as
    ``"TypeName: message"`` frames (runtime/ingress.py ``_wire_error``).
    Shed/deadline/request errors must keep their HTTP mapping
    (429/503/504/400) on a REMOTE frontend — collapsing them to
    RuntimeError would turn every overload rejection into a 500 and
    defeat client backoff. ShedError frames carry their retry/draining
    hints as ``ShedError[<retry_after_s>,<0|1>]: msg``."""
    import re

    from dynamo_tpu.llm.protocols.common import (
        DeadlineError,
        RequestError,
        ShedError,
        WorkerDiedError,
    )

    m = re.match(r"^ShedError\[([0-9.eE+-]+),([01])\]: (.*)$", message, re.S)
    if m:
        return ShedError(
            m.group(3),
            retry_after_s=float(m.group(1)),
            draining=m.group(2) == "1",
        )
    name, sep, rest = message.partition(": ")
    if sep:
        if name == "ShedError":
            return ShedError(rest)
        if name == "DeadlineError":
            return DeadlineError(rest)
        if name == "RequestError":
            return RequestError(rest)
        if name == "WorkerDiedError":
            # Engine-death class must keep its transport typing across
            # the wire: a remote frontend's failover plane re-dispatches
            # on it (and ONLY on it) exactly like a local one.
            return WorkerDiedError(rest)
    return RuntimeError(message)


class TcpStreamServer:
    """Caller-side server accepting response streams from workers."""

    def __init__(self, host: str = "127.0.0.1") -> None:
        self._host = host
        self._server: asyncio.base_events.Server | None = None
        self._pending: dict[str, ResponseStreamReceiver] = {}
        self.port: int = 0

    async def start(self) -> None:
        self._server = await asyncio.start_server(self._on_conn, self._host, 0)
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    def register(self, stream_id: str) -> ResponseStreamReceiver:
        receiver = ResponseStreamReceiver()
        self._pending[stream_id] = receiver
        return receiver

    def unregister(self, stream_id: str) -> None:
        """Forget a stream whose worker never connected (dispatch failed
        or timed out) — a late connection then logs-and-drops instead of
        feeding a receiver nobody reads."""
        self._pending.pop(stream_id, None)

    def connection_info(self, stream_id: str) -> ConnectionInfo:
        return ConnectionInfo(self._host, self.port, stream_id)

    async def _on_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        receiver: ResponseStreamReceiver | None = None
        try:
            header, _ = await read_frame(reader)
            prologue = msgpack.unpackb(header)
            receiver = self._pending.pop(prologue["stream_id"], None)
            if receiver is None:
                logger.warning("unknown stream id %s", prologue.get("stream_id"))
                return
            receiver.connected.set()
            while True:
                header, payload = await read_frame(reader)
                ctl = msgpack.unpackb(header)
                kind = ctl["t"]
                receiver._push(kind, payload)
                if kind in ("end", "err"):
                    break
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            if receiver is not None:
                receiver._close()
            writer.close()


class TcpResponseSender:
    """Worker-side handle: connect back to the caller and stream frames."""

    def __init__(self, writer: asyncio.StreamWriter) -> None:
        self._writer = writer

    @staticmethod
    async def connect(info: ConnectionInfo) -> "TcpResponseSender":
        _, writer = await asyncio.open_connection(info.host, info.port)
        writer.write(
            encode_frame(msgpack.packb({"stream_id": info.stream_id}))
        )
        await writer.drain()
        return TcpResponseSender(writer)

    async def send(self, payload: bytes) -> None:
        # A raise here models the caller vanishing mid-stream; the worker's
        # serve loop already treats send failure as request cancellation.
        if FAULTS.active:
            await FAULTS.maybe_fail_async("tcp.respond")
        self._writer.write(encode_frame(msgpack.packb({"t": "data"}), payload))
        await self._writer.drain()

    async def error(self, message: str) -> None:
        self._writer.write(
            encode_frame(msgpack.packb({"t": "err"}), message.encode())
        )
        await self._writer.drain()

    async def end(self) -> None:
        try:
            self._writer.write(encode_frame(msgpack.packb({"t": "end"})))
            await self._writer.drain()
        finally:
            self._writer.close()

    def abort(self) -> None:
        """Abrupt close with NO terminal frame — the worker-death wire
        signature. The ingress kill path uses this so a cancelled
        handler's caller sees ``WorkerDiedError`` (failover-eligible),
        never a clean-looking truncated stream."""
        try:
            self._writer.transport.abort()
        except Exception:  # transport may already be gone
            self._writer.close()

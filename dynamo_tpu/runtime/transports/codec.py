"""Two-part frame codec for response streams.

Length-prefixed header+payload framing, the wire format of the TCP response
plane (reference: lib/runtime/src/pipeline/network/codec/two_part.rs:23-207).
Frame layout: ``[u32 header_len][u32 payload_len][header][payload]`` with
little-endian lengths. Headers are small msgpack control maps; payloads are
opaque serialized response items.
"""

from __future__ import annotations

import asyncio
import struct

_LEN = struct.Struct("<II")
MAX_FRAME = 64 * 1024 * 1024


def encode_frame(header: bytes, payload: bytes = b"") -> bytes:
    return _LEN.pack(len(header), len(payload)) + header + payload


async def read_frame(reader: asyncio.StreamReader) -> tuple[bytes, bytes]:
    raw = await reader.readexactly(_LEN.size)
    hlen, plen = _LEN.unpack(raw)
    if hlen > MAX_FRAME or plen > MAX_FRAME:
        raise ValueError(f"frame too large: header={hlen} payload={plen}")
    header = await reader.readexactly(hlen) if hlen else b""
    payload = await reader.readexactly(plen) if plen else b""
    return header, payload

"""Control-plane client: RemoteStore + RemoteBus over one TCP connection.

The worker-process side of transports/control_plane.py. One
`ControlPlaneClient` implements BOTH the KeyValueStore protocol
(transports/store.py) and the MessageBus / WorkQueue-factory / ObjectStore
surface (transports/bus.py), so `DistributedRuntime.connect(addr)` passes
it as the runtime's `store` and `bus` (reference: the etcd+NATS client
pair held by DistributedRuntime, lib/runtime/src/distributed.rs:34-77).

All traffic multiplexes over a single connection: request/response pairs
matched by "id", server-pushed stream frames (watch events, subscription
messages) routed by "sid". Connection loss fails every pending call and
ends every stream — the runtime's lease-keepalive CriticalTask then
escalates to process shutdown, which is exactly the reference's
lease-death ⇒ shutdown coupling.
"""

from __future__ import annotations

import asyncio
import itertools
import logging

import msgpack

from dynamo_tpu.runtime.transports.bus import Subscription
from dynamo_tpu.runtime.transports.codec import encode_frame, read_frame
from dynamo_tpu.runtime.transports.store import EventKind, Watch, WatchEvent
from dynamo_tpu.utils.faults import FAULTS
from dynamo_tpu.utils.task import spawn_tracked

logger = logging.getLogger(__name__)

RPC_TIMEOUT_S = 10.0


class ControlPlaneClient:
    def __init__(self, reader, writer) -> None:
        self._reader = reader
        self._writer = writer
        self._wlock = asyncio.Lock()
        self._ids = itertools.count(1)
        self._pending: dict[int, asyncio.Future] = {}
        self._watches: dict[int, Watch] = {}
        self._subs: dict[int, Subscription] = {}
        # Stream frames that raced ahead of their sid's registration: the
        # server starts pumping immediately after the watch/subscribe
        # response, and _read_loop can process buffered frames before the
        # _call() continuation installs the sid (ADVICE r02). Held here and
        # replayed by _register_stream.
        self._orphans: dict[int, list[tuple[dict, bytes]]] = {}
        # Sids cancelled locally: in-flight frames the server wrote before
        # processing the cancel are dropped, not buffered (they would sit in
        # _orphans forever — no future _register_stream for a dead sid).
        # Insertion-ordered + bounded: tail frames arrive promptly after the
        # cancel, so only recent sids matter.
        self._dead_sids: dict[int, None] = {}
        self._pump = asyncio.ensure_future(self._read_loop())
        self.closed = False

    _MAX_ORPHANS = 1024  # frames; a sid that never registers gets dropped

    @staticmethod
    async def connect(addr: str, token: str | None = None) -> "ControlPlaneClient":
        host, port = addr.rsplit(":", 1)
        reader, writer = await asyncio.open_connection(host, int(port))
        client = ControlPlaneClient(reader, writer)
        if token is not None:
            await client._call({"op": "auth", "token": token})
        return client

    # -- wire ---------------------------------------------------------------
    async def _call(
        self, header: dict, payload: bytes = b"", timeout_s: float | None = RPC_TIMEOUT_S
    ) -> tuple[dict, bytes]:
        # A dropped control RPC behaves like a lost connection: the caller
        # sees the injected ConnectionError, never a silent half-call.
        if FAULTS.active:
            await FAULTS.maybe_fail_async("control.call")
        if self.closed:
            raise ConnectionError("control plane connection closed")
        rid = next(self._ids)
        header["id"] = rid
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[rid] = fut
        try:
            async with self._wlock:
                self._writer.write(
                    encode_frame(msgpack.packb(header), payload)
                )
                await self._writer.drain()
            resp, data = await asyncio.wait_for(fut, timeout_s)
        finally:
            self._pending.pop(rid, None)
        if not resp.get("ok"):
            if resp.get("err_type") == "NoSubscriberError":
                # Re-typify: the server-side bus found the worker's
                # subject dead — the remote publisher must see the same
                # ConnectionError-class failure the in-proc bus raises.
                from dynamo_tpu.runtime.transports.bus import (
                    NoSubscriberError,
                )

                raise NoSubscriberError(str(resp.get("err")))
            raise RuntimeError(
                f"control plane {header.get('op')} failed: {resp.get('err')}"
            )
        return resp, data

    async def _read_loop(self) -> None:
        try:
            while True:
                raw_header, payload = await read_frame(self._reader)
                h = msgpack.unpackb(raw_header)
                if "sid" in h and "id" not in h:
                    self._on_stream(h, payload)
                    continue
                fut = self._pending.get(h.get("id"))
                if fut is not None and not fut.done():
                    fut.set_result((h, payload))
        except (
            asyncio.IncompleteReadError,
            ConnectionResetError,
            asyncio.CancelledError,
            OSError,
        ):
            pass
        finally:
            self._teardown()

    def _on_stream(self, h: dict, payload: bytes) -> None:
        sid = h["sid"]
        if sid in self._dead_sids:
            return  # cancelled stream's tail frames
        if sid not in self._subs and sid not in self._watches:
            # Raced ahead of registration — buffer for _register_stream.
            if sum(len(v) for v in self._orphans.values()) < self._MAX_ORPHANS:
                self._orphans.setdefault(sid, []).append((h, payload))
            else:
                logger.warning("dropping orphan stream frame for sid %s", sid)
            return
        self._dispatch_stream(h, payload)

    def _dispatch_stream(self, h: dict, payload: bytes) -> None:
        sid = h["sid"]
        if h["ev"] == "msg":
            sub = self._subs.get(sid)
            if sub is not None:
                sub._deliver(payload)
            return
        watch = self._watches.get(sid)
        if watch is not None:
            watch._emit(
                WatchEvent(EventKind(h["ev"]), h["key"], payload or None)
            )

    def _register_stream(self, sid: int) -> None:
        """Replay frames that arrived before the sid was installed."""
        for h, payload in self._orphans.pop(sid, []):
            self._dispatch_stream(h, payload)

    def _teardown(self) -> None:
        self.closed = True
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionError("control plane lost"))
        self._pending.clear()
        # cancel()/close() re-enter _cancel_stream, which pops from these
        # dicts — iterate over snapshots.
        for watch in list(self._watches.values()):
            watch.cancel()
        self._watches.clear()
        for sub in list(self._subs.values()):
            sub.close()
        self._subs.clear()

    async def close(self) -> None:
        self._pump.cancel()
        try:
            self._writer.close()
        except Exception:
            pass
        self._teardown()

    # -- KeyValueStore -------------------------------------------------------
    async def put(self, key: str, value: bytes, lease_id: int | None = None) -> None:
        await self._call({"op": "put", "key": key, "lease": lease_id}, value)

    async def create(self, key: str, value: bytes, lease_id: int | None = None) -> bool:
        resp, _ = await self._call(
            {"op": "create", "key": key, "lease": lease_id}, value
        )
        return bool(resp["created"])

    async def get(self, key: str) -> bytes | None:
        resp, data = await self._call({"op": "get", "key": key})
        return data if resp["found"] else None

    async def get_prefix(self, prefix: str) -> dict[str, bytes]:
        _, data = await self._call({"op": "get_prefix", "prefix": prefix})
        return msgpack.unpackb(data)

    async def delete(self, key: str) -> None:
        await self._call({"op": "delete", "key": key})

    async def delete_prefix(self, prefix: str) -> None:
        await self._call({"op": "delete_prefix", "prefix": prefix})

    async def grant_lease(self, ttl_s: float) -> int:
        resp, _ = await self._call({"op": "lease_grant", "ttl": ttl_s})
        return resp["lease"]

    async def keep_alive(self, lease_id: int) -> bool:
        # Keepalive gets its own fault point: lease death ⇒ deregister ⇒
        # drain is THE recovery path the reference encodes (disagg_serving
        # failure semantics) and the chaos suite must drive it alone.
        await FAULTS.maybe_fail_async("control.keepalive")
        resp, _ = await self._call({"op": "lease_keepalive", "lease": lease_id})
        return bool(resp["alive"])

    async def revoke_lease(self, lease_id: int) -> None:
        if self.closed:
            return  # connection gone ⇒ lease will TTL-expire server-side
        await self._call({"op": "lease_revoke", "lease": lease_id})

    async def watch_prefix(self, prefix: str) -> Watch:
        resp, data = await self._call({"op": "watch", "prefix": prefix})
        initial = msgpack.unpackb(data)
        watch = _RemoteWatch(initial, self, resp["sid"])
        self._watches[resp["sid"]] = watch
        self._register_stream(resp["sid"])
        return watch

    # -- MessageBus / queues / objects ---------------------------------------
    async def publish(
        self, subject: str, payload: bytes, require_subscriber: bool = False
    ) -> None:
        await self._call(
            {
                "op": "publish",
                "subject": subject,
                "require": require_subscriber,
            },
            payload,
        )

    async def broadcast(self, subject: str, payload: bytes) -> None:
        await self._call({"op": "broadcast", "subject": subject}, payload)

    async def subscribe(self, subject: str) -> Subscription:
        resp, _ = await self._call({"op": "subscribe", "subject": subject})
        sub = _RemoteSubscription(self, resp["sid"])
        self._subs[resp["sid"]] = sub
        self._register_stream(resp["sid"])
        return sub

    async def request(
        self, subject: str, payload: bytes, timeout_s: float = 5.0
    ) -> bytes:
        raise NotImplementedError("use PushRouter for request/stream")

    def work_queue(self, name: str) -> "RemoteQueue":
        return RemoteQueue(self, name)

    async def put_object(self, bucket: str, key: str, data: bytes) -> None:
        await self._call({"op": "obj_put", "bucket": bucket, "key": key}, data)

    async def get_object(self, bucket: str, key: str) -> bytes | None:
        resp, data = await self._call(
            {"op": "obj_get", "bucket": bucket, "key": key}
        )
        return data if resp["found"] else None

    async def list_objects(self, bucket: str, prefix: str = "") -> list[str]:
        resp, _ = await self._call(
            {"op": "obj_list", "bucket": bucket, "prefix": prefix}
        )
        return list(resp["keys"])

    async def delete_object(self, bucket: str, key: str) -> bool:
        resp, _ = await self._call(
            {"op": "obj_del", "bucket": bucket, "key": key}
        )
        return bool(resp["deleted"])

    def _cancel_stream(self, sid: int) -> None:
        self._watches.pop(sid, None)
        self._subs.pop(sid, None)
        self._orphans.pop(sid, None)
        self._dead_sids[sid] = None
        while len(self._dead_sids) > 4096:
            self._dead_sids.pop(next(iter(self._dead_sids)))
        if not self.closed:
            spawn_tracked(self._try_cancel(sid), name="control-cancel")

    async def _try_cancel(self, sid: int) -> None:
        try:
            await self._call({"op": "cancel", "sid": sid})
        except Exception:
            pass


class _RemoteWatch(Watch):
    def __init__(self, initial, client: ControlPlaneClient, sid: int) -> None:
        super().__init__(initial)
        self._client = client
        self._sid = sid

    def cancel(self) -> None:
        if not self.cancelled:
            super().cancel()
            self._client._cancel_stream(self._sid)


class _RemoteSubscription(Subscription):
    def __init__(self, client: ControlPlaneClient, sid: int) -> None:
        super().__init__()
        self._client = client
        self._sid = sid

    def close(self) -> None:
        if not self.closed:
            super().close()
            self._client._cancel_stream(self._sid)


class RemoteQueue:
    """WorkQueue over the control plane (the prefill-queue primitive)."""

    def __init__(self, client: ControlPlaneClient, name: str) -> None:
        self._client = client
        self.name = name

    async def enqueue(self, payload: bytes) -> None:
        await self._client._call(
            {"op": "q_enqueue", "name": self.name}, payload
        )

    async def dequeue(self, timeout_s: float | None = None) -> bytes | None:
        rpc_timeout = None if timeout_s is None else timeout_s + RPC_TIMEOUT_S
        resp, data = await self._client._call(
            {"op": "q_dequeue", "name": self.name, "timeout": timeout_s},
            timeout_s=rpc_timeout,
        )
        return data if resp["found"] else None

    async def dequeue_leased(
        self, timeout_s: float | None = None, lease_s: float = 30.0
    ) -> tuple[int, bytes] | None:
        """Visibility-timeout dequeue: the item redelivers unless ``ack``ed
        within ``lease_s`` (or immediately if this connection dies)."""
        rpc_timeout = None if timeout_s is None else timeout_s + RPC_TIMEOUT_S
        resp, data = await self._client._call(
            {
                "op": "q_dequeue", "name": self.name, "timeout": timeout_s,
                "lease": lease_s,
            },
            timeout_s=rpc_timeout,
        )
        return (resp["item"], data) if resp["found"] else None

    async def ack(self, item_id: int) -> bool:
        resp, _ = await self._client._call(
            {"op": "q_ack", "name": self.name, "item": item_id}
        )
        return bool(resp["acked"])

    async def nack(self, item_id: int) -> bool:
        resp, _ = await self._client._call(
            {"op": "q_nack", "name": self.name, "item": item_id}
        )
        return bool(resp["nacked"])

    async def depth(self) -> int:
        resp, _ = await self._client._call(
            {"op": "q_depth", "name": self.name}
        )
        return resp["depth"]

    async def oldest_age_s(self) -> float:
        return (await self.stats())[1]

    async def stats(self) -> tuple[int, float]:
        """(depth, oldest item age) in ONE round trip — the disagg hot
        path reads both per request."""
        resp, _ = await self._client._call(
            {"op": "q_depth", "name": self.name}
        )
        return resp["depth"], float(resp.get("oldest_age", 0.0))

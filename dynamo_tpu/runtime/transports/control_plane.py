"""Control-plane server: the framework's own etcd+NATS-role service.

One process runs a `ControlPlaneServer`; every worker process connects with
`transports/control_client.ControlPlaneClient` and gets the full discovery
plane (KV store with leases + prefix watches — reference:
lib/runtime/src/transports/etcd.rs:100-131,309), messaging plane (pub/sub
subjects with queue-group and broadcast delivery — reference:
transports/nats.rs:50-120), work queues (the prefill-queue primitive —
reference: transports/nats.rs:345-478 NatsQueue) and object store
(model-card/tokenizer blobs — reference: transports/nats.rs:123-196).

The authoritative state is simply a MemoryStore + InProcBus owned by the
server process; this module is the wire layer exposing them. Protocol: the
two-part codec (transports/codec.py) over TCP, header = msgpack control
map, payload = opaque value bytes.

Request ops (header fields; V marks ops whose value rides the payload):
  auth(token)                       — must be first when the server has a token
  put(key, lease)V create(key, lease)V get(key) get_prefix(prefix)
  delete(key) delete_prefix(prefix)
  lease_grant(ttl) lease_keepalive(lease) lease_revoke(lease)
  watch(prefix) -> {sid, initial}; events stream as {sid, ev, key}V
  publish(subject)V broadcast(subject)V
  subscribe(subject) -> {sid}; messages stream as {sid, ev:"msg"}V
  cancel(sid)                       — stop a watch/subscription stream
  q_enqueue(name)V q_dequeue(name, timeout[, lease]) q_depth(name)
  q_ack(name, item) q_nack(name, item)
  obj_put(bucket, key)V obj_get(bucket, key)

Queue durability (reference: JetStream ack/redelivery semantics,
lib/runtime/src/transports/nats.rs:345-478): a q_dequeue with "lease"
returns {item} and holds the item in-flight until q_ack; lease expiry or
consumer-connection death nacks it back to the FRONT of the queue. A
legacy no-lease dequeue is served under a short internal lease that is
acked only after the response frame is written, so a connection dying
between dequeue and send never loses the item.

Responses echo the request "id": {"id", "ok", ...} (+payload for values).
A blocking q_dequeue is served by a per-request task so one long poll
never stalls the connection's other traffic.
"""

from __future__ import annotations

import asyncio
import hmac
import logging
from typing import Optional

import msgpack

from dynamo_tpu.runtime.transports.bus import InProcBus
from dynamo_tpu.runtime.transports.codec import encode_frame, read_frame
from dynamo_tpu.runtime.transports.store import MemoryStore

logger = logging.getLogger(__name__)


class ControlPlaneServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        token: Optional[str] = None,
        store: MemoryStore | None = None,
        bus: InProcBus | None = None,
    ) -> None:
        self.store = store if store is not None else MemoryStore()
        self.bus = bus if bus is not None else InProcBus()
        self._host = host
        self._port = port
        self._token = token
        self._server: asyncio.AbstractServer | None = None
        self._conns: set["_Conn"] = set()
        self.port: int = 0

    async def start(self) -> "ControlPlaneServer":
        self._server = await asyncio.start_server(
            self._on_conn, self._host, self._port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        logger.info("control plane listening on %s:%d", self._host, self.port)
        return self

    @property
    def address(self) -> str:
        return f"{self._host}:{self.port}"

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            # Force-close live connections: wait_closed() (3.12+) waits for
            # their handlers, which otherwise block in read_frame forever.
            for conn in list(self._conns):
                await conn.close()
            await self._server.wait_closed()

    # -- per-connection ------------------------------------------------------
    async def _on_conn(self, reader, writer) -> None:
        conn = _Conn(self, reader, writer)
        self._conns.add(conn)
        try:
            await conn.run()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        except Exception:
            logger.exception("control plane connection failed")
        finally:
            self._conns.discard(conn)
            await conn.close()


class _Conn:
    """One client connection: request dispatch + stream pumps."""

    def __init__(self, server: ControlPlaneServer, reader, writer) -> None:
        self.server = server
        self.reader = reader
        self.writer = writer
        self._wlock = asyncio.Lock()
        self._streams: dict[int, object] = {}  # sid -> Watch | Subscription
        self._pumps: list[asyncio.Task] = []
        self._sid = 0
        self._authed = server._token is None
        # Items this connection holds under lease; nacked back to the
        # queue if the consumer dies without acking.
        self._leased: set[tuple[str, int]] = set()

    async def _send(self, header: dict, payload: bytes = b"") -> None:
        async with self._wlock:
            self.writer.write(encode_frame(msgpack.packb(header), payload))
            await self.writer.drain()

    async def run(self) -> None:
        while True:
            header, payload = await read_frame(self.reader)
            h = msgpack.unpackb(header)
            op = h.get("op")
            if not self._authed:
                if op != "auth" or not hmac.compare_digest(
                    str(h.get("token", "")), self.server._token
                ):
                    logger.warning("control plane: rejected unauthed peer")
                    return
                self._authed = True
                await self._send({"id": h.get("id"), "ok": True})
                continue
            if op == "q_dequeue":
                # Long poll: serve concurrently, don't stall the connection.
                # Self-pruning — a worker polls this op for its whole
                # lifetime, so completed tasks must not accumulate.
                task = asyncio.ensure_future(self._q_dequeue(h))
                self._pumps.append(task)
                task.add_done_callback(
                    lambda t: t in self._pumps and self._pumps.remove(t)
                )
                continue
            try:
                await self._dispatch(op, h, payload)
            except Exception as exc:  # noqa: BLE001 — report, keep serving
                await self._send(
                    {"id": h.get("id"), "ok": False, "err": f"{exc}"}
                )

    async def _dispatch(self, op: str, h: dict, payload: bytes) -> None:
        store, bus = self.server.store, self.server.bus
        rid = h.get("id")
        if op == "put":
            await store.put(h["key"], payload, lease_id=h.get("lease"))
            await self._send({"id": rid, "ok": True})
        elif op == "create":
            created = await store.create(h["key"], payload, lease_id=h.get("lease"))
            await self._send({"id": rid, "ok": True, "created": created})
        elif op == "get":
            value = await store.get(h["key"])
            await self._send(
                {"id": rid, "ok": True, "found": value is not None},
                value or b"",
            )
        elif op == "get_prefix":
            d = await store.get_prefix(h["prefix"])
            await self._send({"id": rid, "ok": True}, msgpack.packb(d))
        elif op == "delete":
            await store.delete(h["key"])
            await self._send({"id": rid, "ok": True})
        elif op == "delete_prefix":
            await store.delete_prefix(h["prefix"])
            await self._send({"id": rid, "ok": True})
        elif op == "lease_grant":
            lease = await store.grant_lease(h["ttl"])
            await self._send({"id": rid, "ok": True, "lease": lease})
        elif op == "lease_keepalive":
            alive = await store.keep_alive(h["lease"])
            await self._send({"id": rid, "ok": True, "alive": alive})
        elif op == "lease_revoke":
            await store.revoke_lease(h["lease"])
            await self._send({"id": rid, "ok": True})
        elif op == "watch":
            watch = await store.watch_prefix(h["prefix"])
            sid = self._new_sid()
            self._streams[sid] = watch
            await self._send(
                {"id": rid, "ok": True, "sid": sid},
                msgpack.packb(watch.initial),
            )
            self._pumps.append(
                asyncio.ensure_future(self._pump_watch(sid, watch))
            )
        elif op == "publish":
            from dynamo_tpu.runtime.transports.bus import NoSubscriberError

            try:
                await bus.publish(
                    h["subject"], payload,
                    require_subscriber=bool(h.get("require")),
                )
            except NoSubscriberError as exc:
                # Typed so the remote publisher's mark-dead fast path
                # fires exactly as it would on the in-proc bus.
                await self._send({
                    "id": rid, "ok": False, "err": str(exc),
                    "err_type": "NoSubscriberError",
                })
                return
            await self._send({"id": rid, "ok": True})
        elif op == "broadcast":
            await bus.broadcast(h["subject"], payload)
            await self._send({"id": rid, "ok": True})
        elif op == "subscribe":
            sub = await bus.subscribe(h["subject"])
            sid = self._new_sid()
            self._streams[sid] = sub
            await self._send({"id": rid, "ok": True, "sid": sid})
            self._pumps.append(asyncio.ensure_future(self._pump_sub(sid, sub)))
        elif op == "cancel":
            stream = self._streams.pop(h["sid"], None)
            if stream is not None:
                _close_stream(stream)
            await self._send({"id": rid, "ok": True})
        elif op == "q_enqueue":
            await bus.work_queue(h["name"]).enqueue(payload)
            await self._send({"id": rid, "ok": True})
        elif op == "q_ack":
            done = await bus.work_queue(h["name"]).ack(h["item"])
            self._leased.discard((h["name"], h["item"]))
            await self._send({"id": rid, "ok": True, "acked": done})
        elif op == "q_nack":
            done = await bus.work_queue(h["name"]).nack(h["item"])
            self._leased.discard((h["name"], h["item"]))
            await self._send({"id": rid, "ok": True, "nacked": done})
        elif op == "q_depth":
            queue = bus.work_queue(h["name"])
            depth = await queue.depth()
            age = await queue.oldest_age_s()
            await self._send(
                {"id": rid, "ok": True, "depth": depth, "oldest_age": age}
            )
        elif op == "obj_put":
            await bus.put_object(h["bucket"], h["key"], payload)
            await self._send({"id": rid, "ok": True})
        elif op == "obj_get":
            data = await bus.get_object(h["bucket"], h["key"])
            await self._send(
                {"id": rid, "ok": True, "found": data is not None}, data or b""
            )
        elif op == "obj_list":
            keys = await bus.list_objects(h["bucket"], h.get("prefix", ""))
            await self._send({"id": rid, "ok": True, "keys": keys})
        elif op == "obj_del":
            deleted = await bus.delete_object(h["bucket"], h["key"])
            await self._send({"id": rid, "ok": True, "deleted": deleted})
        else:
            await self._send({"id": rid, "ok": False, "err": f"bad op {op!r}"})

    # Internal lease covering a legacy (no-lease) dequeue between queue pop
    # and a successful send — so a dying connection can't lose the item
    # (ADVICE r02: dequeue-then-send loss window).
    SEND_GRACE_S = 30.0

    async def _q_dequeue(self, h: dict) -> None:
        name = h["name"]
        queue = self.server.bus.work_queue(name)
        lease = h.get("lease")
        got = None
        try:
            got = await queue.dequeue_leased(
                timeout_s=h.get("timeout"),
                lease_s=lease if lease is not None else self.SEND_GRACE_S,
            )
            if got is None:
                await self._send({"id": h.get("id"), "ok": True, "found": False})
                return
            item_id, payload = got
            if lease is not None:
                self._leased.add((name, item_id))
            await self._send(
                {"id": h.get("id"), "ok": True, "found": True, "item": item_id},
                payload,
            )
            if lease is None:
                await queue.ack(item_id)  # delivered — retire the grace lease
            got = None  # delivery complete; no rollback below
        except asyncio.CancelledError:
            pass
        except Exception as exc:  # noqa: BLE001
            try:
                await self._send(
                    {"id": h.get("id"), "ok": False, "err": f"{exc}"}
                )
            except Exception:
                pass
        finally:
            if got is not None:
                # Dequeued but never delivered (send failed / cancelled):
                # put it straight back at the front.
                item_id, _ = got
                self._leased.discard((name, item_id))
                await queue.nack(item_id)

    def _new_sid(self) -> int:
        self._sid += 1
        return self._sid

    async def _pump_watch(self, sid: int, watch) -> None:
        try:
            async for ev in watch:
                await self._send(
                    {"sid": sid, "ev": ev.kind.value, "key": ev.key},
                    ev.value or b"",
                )
        except (ConnectionResetError, asyncio.CancelledError):
            pass

    async def _pump_sub(self, sid: int, sub) -> None:
        try:
            async for payload in sub:
                await self._send({"sid": sid, "ev": "msg"}, payload)
        except (ConnectionResetError, asyncio.CancelledError):
            pass

    async def close(self) -> None:
        for stream in self._streams.values():
            _close_stream(stream)
        self._streams.clear()
        for task in self._pumps:
            task.cancel()
        # Consumer died holding leases — redeliver its items immediately
        # rather than waiting for the visibility timeout.
        for name, item_id in list(self._leased):
            try:
                await self.server.bus.work_queue(name).nack(item_id)
            except Exception:
                logger.exception("nack of %s/%s on close failed", name, item_id)
        self._leased.clear()
        try:
            self.writer.close()
        except Exception:
            pass


def _close_stream(stream) -> None:
    cancel = getattr(stream, "cancel", None) or getattr(stream, "close", None)
    if cancel is not None:
        cancel()

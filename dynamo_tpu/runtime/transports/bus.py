"""Message bus and work queue — the request/messaging plane.

Plays the role NATS plays in the reference: core pub/sub carrying requests to
worker-endpoint subjects (reference: lib/runtime/src/transports/nats.rs:50-120,
pipeline/network/egress/addressed_router.rs:59-178), JetStream-backed work
queues for the prefill queue (reference: transports/nats.rs:345-478
`NatsQueue`), and an object store for model-card/tokenizer blobs
(reference: transports/nats.rs:123-196).

`InProcBus` is the in-process implementation; the control-plane server
(transports/control_plane.py) provides the multi-process one over TCP.
"""

from __future__ import annotations

import asyncio
from collections import defaultdict, deque
from typing import AsyncIterator, Protocol


class Subscription:
    """A live subscription delivering message payloads."""

    def __init__(self) -> None:
        self._queue: asyncio.Queue[bytes | None] = asyncio.Queue()
        self.closed = False

    def _deliver(self, payload: bytes) -> None:
        if not self.closed:
            self._queue.put_nowait(payload)

    def close(self) -> None:
        self.closed = True
        self._queue.put_nowait(None)

    def __aiter__(self) -> AsyncIterator[bytes]:
        return self

    async def __anext__(self) -> bytes:
        payload = await self._queue.get()
        if payload is None:
            raise StopAsyncIteration
        return payload


class MessageBus(Protocol):
    async def publish(self, subject: str, payload: bytes) -> None: ...
    async def subscribe(self, subject: str) -> Subscription: ...
    async def request(self, subject: str, payload: bytes, timeout_s: float = 5.0) -> bytes: ...


class WorkQueue(Protocol):
    """At-least-once work queue (the prefill-queue primitive)."""

    async def enqueue(self, payload: bytes) -> None: ...
    async def dequeue(self, timeout_s: float | None = None) -> bytes | None: ...
    async def depth(self) -> int: ...


class ObjectStore(Protocol):
    async def put_object(self, bucket: str, key: str, data: bytes) -> None: ...
    async def get_object(self, bucket: str, key: str) -> bytes | None: ...


class InProcBus:
    """In-process MessageBus + WorkQueue factory + ObjectStore."""

    def __init__(self) -> None:
        self._subs: dict[str, list[Subscription]] = defaultdict(list)
        self._rr: dict[str, int] = defaultdict(int)
        self._queues: dict[str, "InProcQueue"] = {}
        self._objects: dict[tuple[str, str], bytes] = {}

    # -- MessageBus ---------------------------------------------------------
    async def publish(self, subject: str, payload: bytes) -> None:
        subs = [s for s in self._subs.get(subject, []) if not s.closed]
        self._subs[subject] = subs
        if not subs:
            return
        # Endpoint subjects have one subscriber (the worker); if several
        # share a subject they form a queue group — deliver to one.
        idx = self._rr[subject] % len(subs)
        self._rr[subject] += 1
        subs[idx]._deliver(payload)

    async def broadcast(self, subject: str, payload: bytes) -> None:
        """Fan-out delivery (events plane: KV events, metrics)."""
        for sub in list(self._subs.get(subject, [])):
            sub._deliver(payload)

    async def subscribe(self, subject: str) -> Subscription:
        sub = Subscription()
        self._subs[subject].append(sub)
        return sub

    async def request(
        self, subject: str, payload: bytes, timeout_s: float = 5.0
    ) -> bytes:
        raise NotImplementedError("use PushRouter for request/stream")

    # -- queues / objects ---------------------------------------------------
    def work_queue(self, name: str) -> "InProcQueue":
        if name not in self._queues:
            self._queues[name] = InProcQueue()
        return self._queues[name]

    async def put_object(self, bucket: str, key: str, data: bytes) -> None:
        self._objects[(bucket, key)] = data

    async def get_object(self, bucket: str, key: str) -> bytes | None:
        return self._objects.get((bucket, key))


class InProcQueue:
    """In-process WorkQueue."""

    def __init__(self) -> None:
        self._items: deque[bytes] = deque()
        self._waiters: deque[asyncio.Future] = deque()

    async def enqueue(self, payload: bytes) -> None:
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                fut.set_result(payload)
                return
        self._items.append(payload)

    async def dequeue(self, timeout_s: float | None = None) -> bytes | None:
        if self._items:
            return self._items.popleft()
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._waiters.append(fut)
        try:
            if timeout_s is None:
                return await fut
            return await asyncio.wait_for(fut, timeout_s)
        except asyncio.TimeoutError:
            return None

    async def depth(self) -> int:
        return len(self._items)

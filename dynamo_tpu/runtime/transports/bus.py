"""Message bus and work queue — the request/messaging plane.

Plays the role NATS plays in the reference: core pub/sub carrying requests to
worker-endpoint subjects (reference: lib/runtime/src/transports/nats.rs:50-120,
pipeline/network/egress/addressed_router.rs:59-178), JetStream-backed work
queues for the prefill queue (reference: transports/nats.rs:345-478
`NatsQueue`), and an object store for model-card/tokenizer blobs
(reference: transports/nats.rs:123-196).

`InProcBus` is the in-process implementation; the control-plane server
(transports/control_plane.py) provides the multi-process one over TCP.
"""

from __future__ import annotations

import asyncio
from collections import defaultdict, deque
from typing import AsyncIterator, Protocol

from dynamo_tpu.utils.faults import FAULTS


class NoSubscriberError(ConnectionError):
    """A request-plane publish found no live subscriber on the subject —
    the bus-architecture analogue of connection-refused: the worker that
    owned this subject is gone (its subscription closed) but its lease
    has not yet TTL-expired out of discovery. Subclasses ConnectionError
    so the router's mark-dead fast path and every transport-retry filter
    classify it as a dead peer, not a server bug. Only raised when the
    publisher asked for delivery confirmation (``require_subscriber``);
    fire-and-forget event kicks keep their silent-drop semantics."""


class Subscription:
    """A live subscription delivering message payloads."""

    def __init__(self) -> None:
        self._queue: asyncio.Queue[bytes | None] = asyncio.Queue()
        self.closed = False

    def _deliver(self, payload: bytes) -> None:
        if not self.closed:
            self._queue.put_nowait(payload)

    def close(self) -> None:
        self.closed = True
        self._queue.put_nowait(None)

    def poll(self) -> bytes | None:
        """Non-blocking: next queued payload, or None when nothing is
        pending (the stepcast watchdog drains backlogged heartbeats with
        this before judging liveness). Preserves the close sentinel."""
        try:
            payload = self._queue.get_nowait()
        except asyncio.QueueEmpty:
            return None
        if payload is None:
            self._queue.put_nowait(None)  # keep the closed marker
            return None
        return payload

    def __aiter__(self) -> AsyncIterator[bytes]:
        return self

    async def __anext__(self) -> bytes:
        payload = await self._queue.get()
        if payload is None:
            raise StopAsyncIteration
        return payload


class MessageBus(Protocol):
    async def publish(
        self, subject: str, payload: bytes, require_subscriber: bool = False
    ) -> None: ...
    async def subscribe(self, subject: str) -> Subscription: ...
    async def request(self, subject: str, payload: bytes, timeout_s: float = 5.0) -> bytes: ...


class WorkQueue(Protocol):
    """At-least-once work queue (the prefill-queue primitive).

    ``dequeue_leased`` hands an item out under a visibility timeout; the
    consumer must ``ack`` within the lease or the item is redelivered to
    the next consumer (reference: JetStream-backed `NatsQueue` ack/
    redelivery semantics, lib/runtime/src/transports/nats.rs:345-478).
    Plain ``dequeue`` is destructive (auto-ack) for fire-and-forget uses.
    """

    async def enqueue(self, payload: bytes) -> None: ...
    async def dequeue(self, timeout_s: float | None = None) -> bytes | None: ...
    async def dequeue_leased(
        self, timeout_s: float | None = None, lease_s: float = 30.0
    ) -> tuple[int, bytes] | None: ...
    async def ack(self, item_id: int) -> bool: ...
    async def nack(self, item_id: int) -> bool: ...
    async def depth(self) -> int: ...
    async def oldest_age_s(self) -> float: ...
    async def stats(self) -> tuple[int, float]: ...  # (depth, oldest age)


class ObjectStore(Protocol):
    async def put_object(self, bucket: str, key: str, data: bytes) -> None: ...
    async def get_object(self, bucket: str, key: str) -> bytes | None: ...
    async def list_objects(self, bucket: str, prefix: str = "") -> list[str]: ...
    async def delete_object(self, bucket: str, key: str) -> bool: ...


class InProcBus:
    """In-process MessageBus + WorkQueue factory + ObjectStore."""

    def __init__(self) -> None:
        self._subs: dict[str, list[Subscription]] = defaultdict(list)
        self._rr: dict[str, int] = defaultdict(int)
        self._queues: dict[str, "InProcQueue"] = {}
        self._objects: dict[tuple[str, str], bytes] = {}

    # -- MessageBus ---------------------------------------------------------
    async def publish(
        self, subject: str, payload: bytes, require_subscriber: bool = False
    ) -> None:
        if FAULTS.active and not await FAULTS.maybe_fail_async(
            "bus.publish", can_drop=True
        ):
            return  # injected message loss
        subs = [s for s in self._subs.get(subject, []) if not s.closed]
        self._subs[subject] = subs
        if not subs:
            if require_subscriber:
                # Request-plane contract (runtime/egress.py): the caller
                # needs to KNOW the worker is gone NOW — a silent drop
                # here turns worker death into a caller that hangs until
                # its own timeout, exactly the failure-detection gap the
                # mark-dead fast path closes.
                raise NoSubscriberError(
                    f"no live subscriber on subject {subject!r}"
                )
            return
        # Endpoint subjects have one subscriber (the worker); if several
        # share a subject they form a queue group — deliver to one.
        idx = self._rr[subject] % len(subs)
        self._rr[subject] += 1
        subs[idx]._deliver(payload)

    async def broadcast(self, subject: str, payload: bytes) -> None:
        """Fan-out delivery (events plane: KV events, metrics). Prunes
        closed subscriptions like publish() — a broadcast-only subject
        would otherwise accumulate dead Subscription objects forever."""
        if FAULTS.active and not await FAULTS.maybe_fail_async(
            "bus.broadcast", can_drop=True
        ):
            return  # injected message loss
        subs = [s for s in self._subs.get(subject, []) if not s.closed]
        self._subs[subject] = subs
        for sub in subs:
            sub._deliver(payload)

    async def subscribe(self, subject: str) -> Subscription:
        sub = Subscription()
        self._subs[subject].append(sub)
        return sub

    async def request(
        self, subject: str, payload: bytes, timeout_s: float = 5.0
    ) -> bytes:
        raise NotImplementedError("use PushRouter for request/stream")

    # -- queues / objects ---------------------------------------------------
    def work_queue(self, name: str) -> "InProcQueue":
        if name not in self._queues:
            self._queues[name] = InProcQueue()
        return self._queues[name]

    async def put_object(self, bucket: str, key: str, data: bytes) -> None:
        self._objects[(bucket, key)] = data

    async def get_object(self, bucket: str, key: str) -> bytes | None:
        return self._objects.get((bucket, key))

    async def list_objects(self, bucket: str, prefix: str = "") -> list[str]:
        return sorted(
            k for b, k in self._objects if b == bucket and k.startswith(prefix)
        )

    async def delete_object(self, bucket: str, key: str) -> bool:
        return self._objects.pop((bucket, key), None) is not None


class InProcQueue:
    """In-process WorkQueue with visibility-timeout redelivery.

    Items carry a queue-unique id. A leased dequeue moves the item to the
    in-flight table with a deadline; ``ack`` completes it, ``nack`` (or
    lease expiry, driven by an asyncio timer) requeues it at the FRONT so
    redelivered work doesn't lose its place behind newer arrivals.
    """

    def __init__(self) -> None:
        # (item_id, payload, enqueued_at) — enqueued_at survives redelivery
        # so age reflects how long the WORK has waited, not the last lease.
        self._items: deque[tuple[int, bytes, float]] = deque()
        # item_id -> (payload, deadline monotonic, enqueued_at)
        self._inflight: dict[int, tuple[bytes, float, float]] = {}
        # waiter futures resolve to an (item_id, payload) pair; each waiter
        # carries the lease it asked for (None = destructive dequeue).
        self._waiters: deque[tuple[asyncio.Future, float | None]] = deque()
        self._next_id = 0
        self._timer: asyncio.TimerHandle | None = None
        self.delivered = 0
        self.redelivered = 0

    # -- internals ------------------------------------------------------------
    def _lease_out(
        self, item_id: int, payload: bytes, lease_s: float | None, ts: float
    ):
        self.delivered += 1
        if lease_s is None:
            return
        deadline = asyncio.get_running_loop().time() + lease_s
        self._inflight[item_id] = (payload, deadline, ts)
        self._arm_timer()

    def _arm_timer(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        if not self._inflight:
            return
        loop = asyncio.get_running_loop()
        nxt = min(dl for _, dl, _ts in self._inflight.values())
        self._timer = loop.call_later(
            max(0.0, nxt - loop.time()), self._expire_sweep
        )

    def _expire_sweep(self) -> None:
        self._timer = None
        now = asyncio.get_running_loop().time()
        expired = [
            iid for iid, (_, dl, _ts) in self._inflight.items() if dl <= now
        ]
        # Oldest first at the front keeps redelivery order stable.
        for iid in sorted(expired, reverse=True):
            payload, _, ts = self._inflight.pop(iid)
            self.redelivered += 1
            self._push_front(payload, ts)
        self._arm_timer()

    def _push_front(self, payload: bytes, ts: float) -> None:
        """Redeliver under a FRESH id (each delivery gets its own id, so a
        stale ack/nack from the previous holder can't touch the new lease),
        to a parked waiter if any, else back at the front of the queue."""
        self._next_id += 1
        item_id = self._next_id
        while self._waiters:
            fut, lease_s = self._waiters.popleft()
            if not fut.done():
                self._lease_out(item_id, payload, lease_s, ts)
                fut.set_result((item_id, payload))
                return
        self._items.appendleft((item_id, payload, ts))

    # -- WorkQueue -------------------------------------------------------------
    async def enqueue(self, payload: bytes) -> None:
        self._next_id += 1
        item_id = self._next_id
        ts = asyncio.get_running_loop().time()
        while self._waiters:
            fut, lease_s = self._waiters.popleft()
            if not fut.done():
                self._lease_out(item_id, payload, lease_s, ts)
                fut.set_result((item_id, payload))
                return
        self._items.append((item_id, payload, ts))

    async def dequeue_leased(
        self, timeout_s: float | None = None, lease_s: float | None = 30.0
    ) -> tuple[int, bytes] | None:
        if self._items:
            item_id, payload, ts = self._items.popleft()
            self._lease_out(item_id, payload, lease_s, ts)
            return item_id, payload
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        entry = (fut, lease_s)
        self._waiters.append(entry)
        try:
            if timeout_s is None:
                return await fut
            return await asyncio.wait_for(fut, timeout_s)
        except asyncio.TimeoutError:
            return None
        finally:
            if not fut.done() or fut.cancelled():
                # Timed out / cancelled before delivery: a polling consumer
                # must not leave a dead waiter behind per poll.
                try:
                    self._waiters.remove(entry)
                except ValueError:
                    pass

    async def dequeue(self, timeout_s: float | None = None) -> bytes | None:
        got = await self.dequeue_leased(timeout_s, lease_s=None)
        return got[1] if got is not None else None

    async def ack(self, item_id: int) -> bool:
        done = self._inflight.pop(item_id, None) is not None
        if done:
            self._arm_timer()
        return done

    async def nack(self, item_id: int) -> bool:
        entry = self._inflight.pop(item_id, None)
        if entry is None:
            return False
        self.redelivered += 1
        self._push_front(entry[0], entry[2])
        self._arm_timer()
        return True

    async def depth(self) -> int:
        return len(self._items)

    async def oldest_age_s(self) -> float:
        """Seconds the oldest live item (queued OR leased in-flight) has
        waited — the per-item SLA signal depth alone can't give. In-flight
        items count because a stuck consumer holding the only item is
        exactly the stall this signal exists to expose."""
        ages = [ts for _, _, ts in self._items]
        ages.extend(ts for _, _, ts in self._inflight.values())
        if not ages:
            return 0.0
        return max(0.0, asyncio.get_running_loop().time() - min(ages))

    async def stats(self) -> tuple[int, float]:
        return len(self._items), await self.oldest_age_s()

    @property
    def inflight(self) -> int:
        return len(self._inflight)

"""Key-value store with leases and prefix watches — the discovery plane.

Plays the role etcd plays in the reference (reference:
lib/runtime/src/transports/etcd.rs:100-131 primary lease w/ TTL keep-alive,
:309 kv_get_and_watch_prefix, :471 KvCache): instance registration keys are
bound to a worker's lease; if the lease expires (worker death) the keys
vanish and every watcher sees the worker disappear.

Two implementations:
- `MemoryStore` — in-process, for single-process serving and tests.
- `RemoteStore` (transports/control_client.py) — client for the framework's
  own control-plane server, replacing the external etcd dependency with a
  native component.
"""

from __future__ import annotations

import asyncio
import enum
import itertools
import time
from dataclasses import dataclass, field
from typing import AsyncIterator, Protocol


class EventKind(enum.Enum):
    PUT = "put"
    DELETE = "delete"


@dataclass(frozen=True)
class WatchEvent:
    kind: EventKind
    key: str
    value: bytes | None = None


class Watch:
    """A live prefix watch: initial snapshot + async event stream."""

    def __init__(self, initial: dict[str, bytes]) -> None:
        self.initial = initial
        self._queue: asyncio.Queue[WatchEvent | None] = asyncio.Queue()
        self.cancelled = False

    def _emit(self, ev: WatchEvent) -> None:
        if not self.cancelled:
            self._queue.put_nowait(ev)

    def cancel(self) -> None:
        self.cancelled = True
        self._queue.put_nowait(None)

    def __aiter__(self) -> AsyncIterator[WatchEvent]:
        return self

    async def __anext__(self) -> WatchEvent:
        ev = await self._queue.get()
        if ev is None:
            raise StopAsyncIteration
        return ev


class KeyValueStore(Protocol):
    async def put(self, key: str, value: bytes, lease_id: int | None = None) -> None: ...
    async def create(self, key: str, value: bytes, lease_id: int | None = None) -> bool: ...
    async def get(self, key: str) -> bytes | None: ...
    async def get_prefix(self, prefix: str) -> dict[str, bytes]: ...
    async def delete(self, key: str) -> None: ...
    async def delete_prefix(self, prefix: str) -> None: ...
    async def grant_lease(self, ttl_s: float) -> int: ...
    async def keep_alive(self, lease_id: int) -> bool: ...
    async def revoke_lease(self, lease_id: int) -> None: ...
    async def watch_prefix(self, prefix: str) -> Watch: ...


@dataclass
class _Lease:
    id: int
    ttl_s: float
    expires_at: float
    keys: set[str] = field(default_factory=set)


class MemoryStore:
    """In-process KeyValueStore with real lease-expiry semantics."""

    def __init__(self) -> None:
        self._data: dict[str, bytes] = {}
        self._key_lease: dict[str, int] = {}
        self._leases: dict[int, _Lease] = {}
        self._watches: list[tuple[str, Watch]] = []
        self._lease_ids = itertools.count(0x1000)
        self._reaper: asyncio.Task | None = None

    # -- internals ----------------------------------------------------------
    def _notify(self, ev: WatchEvent) -> None:
        for prefix, watch in list(self._watches):
            if watch.cancelled:
                self._watches.remove((prefix, watch))
            elif ev.key.startswith(prefix):
                watch._emit(ev)

    def _delete_key(self, key: str) -> None:
        if key in self._data:
            del self._data[key]
            lease = self._key_lease.pop(key, None)
            if lease is not None and lease in self._leases:
                self._leases[lease].keys.discard(key)
            self._notify(WatchEvent(EventKind.DELETE, key))

    def _ensure_reaper(self) -> None:
        if self._reaper is None or self._reaper.done():
            self._reaper = asyncio.ensure_future(self._reap_loop())

    async def _reap_loop(self) -> None:
        while self._leases:
            now = time.monotonic()
            for lease in list(self._leases.values()):
                if lease.expires_at <= now:
                    await self.revoke_lease(lease.id)
            await asyncio.sleep(0.05)

    def _attach_lease(self, key: str, lease_id: int | None) -> None:
        if lease_id is None:
            return
        lease = self._leases.get(lease_id)
        if lease is None:
            raise KeyError(f"unknown lease {lease_id:#x}")
        lease.keys.add(key)
        self._key_lease[key] = lease_id

    # -- KeyValueStore ------------------------------------------------------
    async def put(self, key: str, value: bytes, lease_id: int | None = None) -> None:
        self._data[key] = value
        self._attach_lease(key, lease_id)
        self._notify(WatchEvent(EventKind.PUT, key, value))

    async def create(self, key: str, value: bytes, lease_id: int | None = None) -> bool:
        if key in self._data:
            return False
        await self.put(key, value, lease_id)
        return True

    async def get(self, key: str) -> bytes | None:
        return self._data.get(key)

    async def get_prefix(self, prefix: str) -> dict[str, bytes]:
        return {k: v for k, v in self._data.items() if k.startswith(prefix)}

    async def delete(self, key: str) -> None:
        self._delete_key(key)

    async def delete_prefix(self, prefix: str) -> None:
        for key in [k for k in self._data if k.startswith(prefix)]:
            self._delete_key(key)

    async def grant_lease(self, ttl_s: float) -> int:
        lease_id = next(self._lease_ids)
        self._leases[lease_id] = _Lease(
            id=lease_id, ttl_s=ttl_s, expires_at=time.monotonic() + ttl_s
        )
        self._ensure_reaper()
        return lease_id

    async def keep_alive(self, lease_id: int) -> bool:
        lease = self._leases.get(lease_id)
        if lease is None:
            return False
        lease.expires_at = time.monotonic() + lease.ttl_s
        return True

    async def revoke_lease(self, lease_id: int) -> None:
        lease = self._leases.pop(lease_id, None)
        if lease is None:
            return
        for key in list(lease.keys):
            self._delete_key(key)

    async def watch_prefix(self, prefix: str) -> Watch:
        watch = Watch(await self.get_prefix(prefix))
        self._watches.append((prefix, watch))
        return watch


class KvCache:
    """A watched, locally cached view of a prefix — live dynamic config.

    Mirrors the reference's EtcdKvCache used for runtime-updatable disagg
    thresholds (reference: lib/runtime/src/transports/etcd.rs:471-597).
    """

    def __init__(self, store: KeyValueStore, prefix: str) -> None:
        self._store = store
        self._prefix = prefix
        self._cache: dict[str, bytes] = {}
        self._task: asyncio.Task | None = None

    async def start(self) -> None:
        watch = await self._store.watch_prefix(self._prefix)
        self._cache = dict(watch.initial)
        self._task = asyncio.ensure_future(self._pump(watch))

    async def _pump(self, watch: Watch) -> None:
        async for ev in watch:
            if ev.kind is EventKind.PUT:
                self._cache[ev.key] = ev.value or b""
            else:
                self._cache.pop(ev.key, None)

    def get(self, key: str) -> bytes | None:
        return self._cache.get(self._prefix + key)

    def snapshot(self) -> dict[str, bytes]:
        return dict(self._cache)

    def stop(self) -> None:
        if self._task:
            self._task.cancel()

"""Server-side request handling (ingress).

Wraps an AsyncEngine as a served endpoint: subscribe the endpoint's bus
subject, and for each arriving request envelope spawn a handler that runs the
engine and streams responses back over the TCP response plane (reference:
lib/runtime/src/pipeline/network/ingress/push_endpoint.rs:26-111,
network.rs:279-323 `Ingress::for_engine`).

Request envelope (msgpack): ``{"id": str, "payload": <obj>, "resp":
{host, port, stream_id}}``. Response frames carry msgpack-serialized items;
the final frame is an end/err control frame (transports/tcp.py).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any

import msgpack

from dynamo_tpu.runtime.component import Endpoint, Instance
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.transports.tcp import ConnectionInfo, TcpResponseSender

logger = logging.getLogger(__name__)


async def serve_endpoint(
    drt,
    endpoint: Endpoint,
    engine: AsyncEngine,
    metadata: dict | None = None,
) -> Instance:
    """Register `engine` as a live instance of `endpoint` and start the
    request pump. Returns the registered Instance."""
    lease_id = drt.primary_lease_id
    subject = endpoint.subject_for(lease_id)
    instance = Instance(endpoint=endpoint.id, lease_id=lease_id, subject=subject)

    sub = await drt.bus.subscribe(subject)
    await drt.store.put(instance.store_key, instance.to_json(), lease_id=lease_id)

    async def pump() -> None:
        try:
            async for raw in sub:
                asyncio.ensure_future(_handle_request(engine, raw))
        except asyncio.CancelledError:
            pass

    task = asyncio.ensure_future(pump())
    drt.runtime.token.on_cancel(lambda: (sub.close(), task.cancel()))
    logger.info("serving %s on %s (lease %#x)", endpoint.id, subject, lease_id)
    return instance


async def _handle_request(engine: AsyncEngine, raw: bytes) -> None:
    envelope = msgpack.unpackb(raw)
    sender: TcpResponseSender | None = None
    try:
        info = ConnectionInfo.from_wire(envelope["resp"])
        sender = await TcpResponseSender.connect(info)
        ctx: Context[Any] = Context(envelope["payload"], id=envelope["id"])
        async for item in engine.generate(ctx):
            await sender.send(msgpack.packb(item, default=_default))
        await sender.end()
    except Exception as exc:  # noqa: BLE001 — report to caller, don't die
        logger.exception("request %s failed", envelope.get("id"))
        if sender is not None:
            try:
                await sender.error(f"{type(exc).__name__}: {exc}")
            except Exception:
                pass


def _default(obj):
    """msgpack fallback for dataclass-ish payloads."""
    if hasattr(obj, "to_wire"):
        return obj.to_wire()
    if hasattr(obj, "__dict__"):
        return obj.__dict__
    raise TypeError(f"cannot serialize {type(obj).__name__}")

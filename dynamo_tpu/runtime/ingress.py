"""Server-side request handling (ingress).

Wraps an AsyncEngine as a served endpoint: subscribe the endpoint's bus
subject, and for each arriving request envelope spawn a handler that runs the
engine and streams responses back over the TCP response plane (reference:
lib/runtime/src/pipeline/network/ingress/push_endpoint.rs:26-111,
network.rs:279-323 `Ingress::for_engine`).

Request envelope (msgpack): ``{"id": str, "payload": <obj>, "resp":
{host, port, stream_id}}``. Response frames carry msgpack-serialized items;
the final frame is an end/err control frame (transports/tcp.py).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any

import msgpack

from dynamo_tpu.runtime.component import Endpoint, Instance
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.transports.tcp import ConnectionInfo, TcpResponseSender
from dynamo_tpu.utils.logging import request_scope
from dynamo_tpu.utils.task import spawn_tracked
from dynamo_tpu.utils.tracing import TraceContext, tracer

logger = logging.getLogger(__name__)


class ServedInstance:
    """A live served endpoint plus its teardown. Proxies the registered
    `Instance`'s attributes; ``stop()`` deregisters from the store and
    halts the request pump without shutting down the whole runtime (for
    services that retire an endpoint mid-life, e.g. RouterService);
    ``drain()`` is the loss-free variant: stop accepting, FINISH the
    in-flight request handlers, then deregister."""

    def __init__(
        self, drt, instance: Instance, sub, task, inflight: set
    ) -> None:
        self.instance = instance
        self._drt = drt
        self._sub = sub
        self._task = task
        self._inflight = inflight

    def __getattr__(self, name):
        return getattr(self.instance, name)

    @property
    def inflight(self) -> int:
        """Requests currently being handled by this endpoint."""
        return len(self._inflight)

    async def _deregister(self) -> None:
        try:
            await self._drt.store.delete(self.instance.store_key)
        except Exception:  # store may already be gone at runtime teardown
            logger.debug("instance deregister failed", exc_info=True)

    async def drain(self, grace_s: float = 30.0) -> bool:
        """Graceful retirement (docs/architecture/overload_and_drain.md):
        deregister FIRST (routers stop picking this instance — eviction),
        stop the request pump (no new envelope is handled), then wait up
        to `grace_s` for in-flight handlers to finish streaming their
        responses (the response plane is direct TCP, independent of
        discovery, so they complete untouched). Returns True when nothing
        was abandoned."""
        await self._deregister()
        self._sub.close()
        self._task.cancel()
        try:
            await self._task
        except asyncio.CancelledError:
            pass
        pending = {t for t in self._inflight if not t.done()}
        if pending:
            done, still = await asyncio.wait(pending, timeout=grace_s)
            if still:
                logger.warning(
                    "drain grace expired with %d request(s) in flight",
                    len(still),
                )
                return False
        return True

    async def stop(self) -> None:
        self._sub.close()
        self._task.cancel()
        try:
            await self._task
        except asyncio.CancelledError:
            pass
        await self._deregister()

    async def kill(self) -> None:
        """Abrupt worker death (the chaos path — docs/architecture/
        failure_model.md "Mid-stream failover"): the subscription closes,
        the pump dies, and every in-flight handler is CANCELLED — its
        response socket aborts with no terminal frame, so each caller
        sees a typed ``WorkerDiedError`` and fails over. Deliberately
        does NOT deregister: a crashed process never gets to clean up
        discovery — the lease TTL (slow path) or the router's mark-dead
        fast path is what evicts the corpse, which is exactly the seam
        the failover plane exists to cover."""
        self._sub.close()
        self._task.cancel()
        doomed = [self._task, *self._inflight]
        for t in doomed[1:]:
            t.cancel()
        for t in doomed:
            try:
                await t
            except (asyncio.CancelledError, Exception):  # noqa: BLE001 — dying
                pass


async def serve_endpoint(
    drt,
    endpoint: Endpoint,
    engine: AsyncEngine,
    metadata: dict | None = None,
) -> ServedInstance:
    """Register `engine` as a live instance of `endpoint` and start the
    request pump. Returns the registered instance handle."""
    lease_id = drt.primary_lease_id
    subject = endpoint.subject_for(lease_id)
    instance = Instance(endpoint=endpoint.id, lease_id=lease_id, subject=subject)

    sub = await drt.bus.subscribe(subject)
    await drt.store.put(instance.store_key, instance.to_json(), lease_id=lease_id)
    # Live handler tasks, tracked so drain() can await their completion
    # (spawn_tracked's registry is process-global; this set is per
    # endpoint). Done tasks remove themselves.
    inflight: set[asyncio.Future] = set()

    async def pump() -> None:
        try:
            async for raw in sub:
                t = spawn_tracked(
                    _handle_request(engine, raw), name="ingress-request"
                )
                inflight.add(t)
                t.add_done_callback(inflight.discard)
        except asyncio.CancelledError:
            pass

    task = asyncio.ensure_future(pump())
    drt.runtime.token.on_cancel(lambda: (sub.close(), task.cancel()))
    logger.info("serving %s on %s (lease %#x)", endpoint.id, subject, lease_id)
    return ServedInstance(drt, instance, sub, task, inflight)


async def _handle_request(engine: AsyncEngine, raw: bytes) -> None:
    envelope = msgpack.unpackb(raw)
    sender: TcpResponseSender | None = None
    rid = envelope.get("id", "")
    # Adopt the caller's trace identity before any work: every span this
    # worker records — and any error frame it sends back — joins the
    # request's cross-process timeline under the same trace id.
    ctx_trace = TraceContext.from_wire(envelope.get("trace"))
    tracer().adopt(rid, ctx_trace)
    trace_id = ctx_trace.trace_id if ctx_trace is not None else None
    with request_scope(rid, trace_id):
        try:
            info = ConnectionInfo.from_wire(envelope["resp"])
            sender = await TcpResponseSender.connect(info)
            ctx: Context[Any] = Context(envelope["payload"], id=rid)
            async for item in engine.generate(ctx):
                await sender.send(msgpack.packb(item, default=_default))
            await sender.end()
            # Generate requests are finished by the engine at delivery;
            # payloads that bypass that path (embeddings, raw dicts) only
            # ever opened a capture via the adopt() above — close it here
            # or each one leaks until the TTL sweep. No-op when the
            # engine already finished.
            tracer().finish(rid)
        except asyncio.CancelledError:
            # Abrupt worker death (ServedInstance.kill / process
            # teardown): abort the response socket with NO terminal
            # frame — the caller must see WorkerDiedError and fail the
            # request over, not a clean-looking truncated stream.
            tracer().mark_if_active(rid, "error")
            tracer().finish(rid)
            if sender is not None:
                sender.abort()
            raise
        except Exception as exc:  # noqa: BLE001 — report to caller, don't die
            logger.exception("request %s failed", envelope.get("id"))
            # The worker-side capture must not leak (or orphan) when the
            # request dies on the error plane: mark + finish under the
            # SAME trace id the caller will finish its half with.
            tracer().mark_if_active(rid, "error")
            tracer().finish(rid)
            if sender is not None:
                try:
                    await sender.error(_wire_error(exc))
                except Exception:
                    pass


def _wire_error(exc: Exception) -> str:
    """Error-frame text for the response plane. ShedError additionally
    carries its retry/draining hints in a parseable prefix — a REMOTE
    frontend must map an overload rejection to the same 429/503 +
    Retry-After a local one gets (transports/tcp.py _typed_stream_error
    is the decoder). ConnectionError-class failures (engine death, lost
    transport under the handler) collapse to the one name the decoder
    re-typifies as failover-eligible — subclass names would cross as
    unknown types and land as non-retryable RuntimeError."""
    from dynamo_tpu.llm.protocols.common import ShedError

    if isinstance(exc, ShedError):
        return (
            f"ShedError[{exc.retry_after_s:g},{int(exc.draining)}]: {exc}"
        )
    if isinstance(exc, ConnectionError):
        return f"WorkerDiedError: {exc}"
    return f"{type(exc).__name__}: {exc}"


def _default(obj):
    """msgpack fallback for dataclass-ish payloads."""
    if hasattr(obj, "to_wire"):
        return obj.to_wire()
    if hasattr(obj, "__dict__"):
        return obj.__dict__
    raise TypeError(f"cannot serialize {type(obj).__name__}")

"""Server-side request handling (ingress).

Wraps an AsyncEngine as a served endpoint: subscribe the endpoint's bus
subject, and for each arriving request envelope spawn a handler that runs the
engine and streams responses back over the TCP response plane (reference:
lib/runtime/src/pipeline/network/ingress/push_endpoint.rs:26-111,
network.rs:279-323 `Ingress::for_engine`).

Request envelope (msgpack): ``{"id": str, "payload": <obj>, "resp":
{host, port, stream_id}}``. Response frames carry msgpack-serialized items;
the final frame is an end/err control frame (transports/tcp.py).
"""

from __future__ import annotations

import asyncio
import logging
from typing import Any

import msgpack

from dynamo_tpu.runtime.component import Endpoint, Instance
from dynamo_tpu.runtime.engine import AsyncEngine, Context
from dynamo_tpu.runtime.transports.tcp import ConnectionInfo, TcpResponseSender
from dynamo_tpu.utils.task import spawn_tracked

logger = logging.getLogger(__name__)


class ServedInstance:
    """A live served endpoint plus its teardown. Proxies the registered
    `Instance`'s attributes; ``stop()`` deregisters from the store and
    halts the request pump without shutting down the whole runtime (for
    services that retire an endpoint mid-life, e.g. RouterService)."""

    def __init__(self, drt, instance: Instance, sub, task) -> None:
        self.instance = instance
        self._drt = drt
        self._sub = sub
        self._task = task

    def __getattr__(self, name):
        return getattr(self.instance, name)

    async def stop(self) -> None:
        self._sub.close()
        self._task.cancel()
        try:
            await self._task
        except asyncio.CancelledError:
            pass
        try:
            await self._drt.store.delete(self.instance.store_key)
        except Exception:  # store may already be gone at runtime teardown
            logger.debug("instance deregister failed", exc_info=True)


async def serve_endpoint(
    drt,
    endpoint: Endpoint,
    engine: AsyncEngine,
    metadata: dict | None = None,
) -> ServedInstance:
    """Register `engine` as a live instance of `endpoint` and start the
    request pump. Returns the registered instance handle."""
    lease_id = drt.primary_lease_id
    subject = endpoint.subject_for(lease_id)
    instance = Instance(endpoint=endpoint.id, lease_id=lease_id, subject=subject)

    sub = await drt.bus.subscribe(subject)
    await drt.store.put(instance.store_key, instance.to_json(), lease_id=lease_id)

    async def pump() -> None:
        try:
            async for raw in sub:
                spawn_tracked(
                    _handle_request(engine, raw), name="ingress-request"
                )
        except asyncio.CancelledError:
            pass

    task = asyncio.ensure_future(pump())
    drt.runtime.token.on_cancel(lambda: (sub.close(), task.cancel()))
    logger.info("serving %s on %s (lease %#x)", endpoint.id, subject, lease_id)
    return ServedInstance(drt, instance, sub, task)


async def _handle_request(engine: AsyncEngine, raw: bytes) -> None:
    envelope = msgpack.unpackb(raw)
    sender: TcpResponseSender | None = None
    try:
        info = ConnectionInfo.from_wire(envelope["resp"])
        sender = await TcpResponseSender.connect(info)
        ctx: Context[Any] = Context(envelope["payload"], id=envelope["id"])
        async for item in engine.generate(ctx):
            await sender.send(msgpack.packb(item, default=_default))
        await sender.end()
    except Exception as exc:  # noqa: BLE001 — report to caller, don't die
        logger.exception("request %s failed", envelope.get("id"))
        if sender is not None:
            try:
                await sender.error(f"{type(exc).__name__}: {exc}")
            except Exception:
                pass


def _default(obj):
    """msgpack fallback for dataclass-ish payloads."""
    if hasattr(obj, "to_wire"):
        return obj.to_wire()
    if hasattr(obj, "__dict__"):
        return obj.__dict__
    raise TypeError(f"cannot serialize {type(obj).__name__}")

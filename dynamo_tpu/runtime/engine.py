"""The streaming engine contract.

Mirrors the reference's core abstraction (reference: lib/runtime/src/engine.rs:
`AsyncEngine<SingleIn<Req>, ManyOut<Resp>, Error>` :104, `AsyncEngineContext`
:47-85, `ResponseStream` :116): every stage — preprocessor, router, worker
engine — accepts ONE request and yields MANY streamed responses, with a
context carrying the request id and stop/kill signals the whole way through.

In Python the natural spelling is: `generate(request: Context) ->
AsyncIterator[resp]`, where `Context` wraps the payload and the cancellation
signals, and operators transform both the request on the way down and the
response stream on the way back up.
"""

from __future__ import annotations

import uuid
from typing import Any, AsyncIterator, Generic, Protocol, TypeVar, runtime_checkable

from dynamo_tpu.utils.cancellation import CancellationToken

T = TypeVar("T")
U = TypeVar("U")


class Context(Generic[T]):
    """Request envelope: payload + id + stop/kill signals + annotations.

    `stop` requests graceful end-of-generation (finish the current token);
    `kill` aborts immediately. Mirrors AsyncEngineContext stop_generating/kill
    (reference: lib/runtime/src/engine.rs:47-85).
    """

    __slots__ = ("payload", "id", "_stop", "_kill", "annotations")

    def __init__(
        self,
        payload: T,
        id: str | None = None,
        stop: CancellationToken | None = None,
        kill: CancellationToken | None = None,
        annotations: dict[str, Any] | None = None,
    ) -> None:
        self.payload = payload
        self.id = id or uuid.uuid4().hex
        self._stop = stop or CancellationToken()
        self._kill = kill or self._stop.child_token()
        self.annotations = annotations if annotations is not None else {}

    def map(self, payload: U) -> "Context[U]":
        """New payload, same identity/signals — the request-path transform."""
        return Context(
            payload,
            id=self.id,
            stop=self._stop,
            kill=self._kill,
            annotations=self.annotations,
        )

    def stop_generating(self) -> None:
        self._stop.cancel()

    def kill(self) -> None:
        self._stop.cancel()
        self._kill.cancel()

    @property
    def is_stopped(self) -> bool:
        return self._stop.is_cancelled()

    @property
    def is_killed(self) -> bool:
        return self._kill.is_cancelled()


@runtime_checkable
class AsyncEngine(Protocol):
    """Anything that turns one request into a stream of responses."""

    def generate(self, request: Context) -> AsyncIterator[Any]:
        ...


class EngineAdapter:
    """Wrap a plain async-generator function as an AsyncEngine."""

    def __init__(self, fn) -> None:
        self._fn = fn

    def generate(self, request: Context) -> AsyncIterator[Any]:
        return self._fn(request)

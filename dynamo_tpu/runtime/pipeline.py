"""Operator pipelines: the bidirectional node graph.

The reference models a service as a linked node graph — Frontend → Operators →
Backend — where an Operator transforms the request on the forward path AND the
response stream on the backward path, letting it carry per-request state from
one side to the other (reference: lib/runtime/src/pipeline/nodes.rs:16-120,
pipeline.rs:43-70; e.g. the OpenAI preprocessor tokenizes going down and maps
engine deltas back to OpenAI chunks coming up).

Here an Operator is an object with
`generate(request: Context, downstream: AsyncEngine) -> AsyncIterator`:
it may transform the request, call `downstream.generate(...)`, and transform
or annotate each yielded item — one Python object per reference node pair
(forward Source + backward Sink). Graph mechanics:

- `Pipeline.link(*ops, engine=...)` — the linear chain; the composed object
  is itself an AsyncEngine, so pipelines nest and can be registered as
  endpoints or models transparently.
- `Segment(*ops)` — a reusable, composable operator fragment: segments
  `link()` onto each other and terminate `into(engine)`; the same segment
  instance can be shared by many pipelines (reference: `link()` chaining of
  forward/backward edges, nodes.rs:105-120).
- `Switch(selector, branches)` — request-path branching: route each request
  to one of several named downstream engines (e.g. a multimodal encode
  branch ahead of the decode worker vs. the text-only fast path); the
  response stream rides back through the same operator stack.
- `Tap(on_request, on_response)` — observability node: sees the request on
  the way down and every item on the way up without transforming either.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, AsyncIterator, Callable, Mapping

from dynamo_tpu.runtime.engine import AsyncEngine, Context


class Operator(ABC):
    """A bidirectional pipeline stage."""

    @abstractmethod
    def generate(
        self, request: Context, downstream: AsyncEngine
    ) -> AsyncIterator[Any]:
        ...


class _Linked:
    """An Operator bound to its downstream engine; an AsyncEngine itself."""

    __slots__ = ("_op", "_next")

    def __init__(self, op: Operator, next_engine: AsyncEngine) -> None:
        self._op = op
        self._next = next_engine

    def generate(self, request: Context) -> AsyncIterator[Any]:
        return self._op.generate(request, self._next)


class Pipeline:
    """Compose `ops` in order onto `engine`: ops[0] sees the request first."""

    def __init__(self, ops: list[Operator], engine: AsyncEngine) -> None:
        composed: AsyncEngine = engine
        for op in reversed(ops):
            composed = _Linked(op, composed)
        self._engine = composed

    @staticmethod
    def link(*ops: Operator, engine: AsyncEngine) -> "Pipeline":
        return Pipeline(list(ops), engine)

    def generate(self, request: Context) -> AsyncIterator[Any]:
        return self._engine.generate(request)


class Segment:
    """A reusable operator fragment — the composable unit of the graph.

    Segments hold no engine: `a.link(b)` concatenates fragments, and
    `seg.into(engine)` produces a Pipeline. One segment instance may be
    linked into many pipelines (operators must therefore keep per-request
    state on the Context, not on themselves — same discipline the
    reference's Arc-shared nodes require)."""

    def __init__(self, *ops: Operator) -> None:
        self.ops: tuple[Operator, ...] = tuple(ops)

    def link(self, other: "Segment | Operator") -> "Segment":
        more = other.ops if isinstance(other, Segment) else (other,)
        return Segment(*self.ops, *more)

    def into(self, engine: AsyncEngine) -> Pipeline:
        return Pipeline(list(self.ops), engine)


class Switch:
    """Request-path branching node; an AsyncEngine over named branches.

    `selector(request)` names the branch the request takes; the branch's
    response stream is relayed unchanged, so upstream operators see one
    continuous backward path regardless of routing (reference analogue:
    the per-model/per-modality pipeline dispatch the watcher builds —
    here available INSIDE a pipeline)."""

    def __init__(
        self,
        selector: Callable[[Context], str],
        branches: Mapping[str, AsyncEngine],
        default: str | None = None,
    ) -> None:
        if not branches:
            raise ValueError("Switch needs at least one branch")
        self._selector = selector
        self._branches = dict(branches)
        self._default = default
        if default is not None and default not in self._branches:
            raise KeyError(f"default branch {default!r} not in branches")

    async def generate(self, request: Context) -> AsyncIterator[Any]:
        name = self._selector(request)
        engine = self._branches.get(name)
        if engine is None:
            if self._default is None:
                raise LookupError(
                    f"switch: no branch {name!r} (have "
                    f"{sorted(self._branches)})"
                )
            engine = self._branches[self._default]
        async for item in engine.generate(request):
            yield item


class Tap(Operator):
    """Observe both directions without transforming either — latency probes,
    audit logs, metrics hooks."""

    def __init__(
        self,
        on_request: Callable[[Context], None] | None = None,
        on_response: Callable[[Context, Any], None] | None = None,
    ) -> None:
        self._on_request = on_request
        self._on_response = on_response

    async def generate(
        self, request: Context, downstream: AsyncEngine
    ) -> AsyncIterator[Any]:
        if self._on_request is not None:
            self._on_request(request)
        async for item in downstream.generate(request):
            if self._on_response is not None:
                self._on_response(request, item)
            yield item

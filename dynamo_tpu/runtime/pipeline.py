"""Operator pipelines.

The reference models a service as a linked node graph — Frontend → Operators →
Backend — where an Operator transforms the request on the forward path AND the
response stream on the backward path, letting it carry per-request state from
one side to the other (reference: lib/runtime/src/pipeline/nodes.rs:16-120,
pipeline.rs:43-70; e.g. the OpenAI preprocessor tokenizes going down and maps
engine deltas back to OpenAI chunks coming up).

Here an Operator is an object with
`generate(request: Context, downstream: AsyncEngine) -> AsyncIterator`:
it may transform the request, call `downstream.generate(...)`, and transform
or annotate each yielded item. `Pipeline.link` composes operators onto a
terminal engine; the composed object is itself an AsyncEngine, so pipelines
nest and can be registered as endpoints or models transparently.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, AsyncIterator

from dynamo_tpu.runtime.engine import AsyncEngine, Context


class Operator(ABC):
    """A bidirectional pipeline stage."""

    @abstractmethod
    def generate(
        self, request: Context, downstream: AsyncEngine
    ) -> AsyncIterator[Any]:
        ...


class _Linked:
    """An Operator bound to its downstream engine; an AsyncEngine itself."""

    __slots__ = ("_op", "_next")

    def __init__(self, op: Operator, next_engine: AsyncEngine) -> None:
        self._op = op
        self._next = next_engine

    def generate(self, request: Context) -> AsyncIterator[Any]:
        return self._op.generate(request, self._next)


class Pipeline:
    """Compose `ops` in order onto `engine`: ops[0] sees the request first."""

    def __init__(self, ops: list[Operator], engine: AsyncEngine) -> None:
        composed: AsyncEngine = engine
        for op in reversed(ops):
            composed = _Linked(op, composed)
        self._engine = composed

    @staticmethod
    def link(*ops: Operator, engine: AsyncEngine) -> "Pipeline":
        return Pipeline(list(ops), engine)

    def generate(self, request: Context) -> AsyncIterator[Any]:
        return self._engine.generate(request)
